"""SameDiff control flow: if/while as compiler-friendly subgraph ops.

Reference: TF-style frames in ``org.nd4j.autodiff.samediff.internal.
AbstractSession`` + the Switch/Merge/Enter/Exit logic ops (SURVEY §2.2
J11/J12) — a host-side interpreter tracks frame/iteration bookkeeping per
node. TPU inversion: a conditional is ONE ``lax.cond`` and a loop is ONE
``lax.while_loop`` inside the same compiled graph — no per-iteration host
round trips, no frame bookkeeping; XLA compiles the whole loop body once.

Subgraphs are real nested :class:`SameDiff` graphs (built by user lambdas),
stored in the op node's kwargs and serialized recursively with the parent —
the FlatBuffers-scope story (§2.1 N11 logic ops) without a second format.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

IF_OP = "__if__"
WHILE_OP = "__while__"
CONTROL_OPS = (IF_OP, WHILE_OP)


def build_subgraph(fn: Callable, n_args: int) -> Dict[str, Any]:
    """Run a user body lambda ``fn(sub_sd, *arg_vars) -> var|tuple`` against
    a fresh nested SameDiff; returns the stored-subgraph dict."""
    from .samediff import SameDiff

    sub = SameDiff.create()
    args = [sub.placeholder(f"__arg{i}", None) for i in range(n_args)]
    outs = fn(sub, *args)
    outs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
    return {
        "graph": sub,
        "args": [a.name for a in args],
        "outputs": [o.name for o in outs],
    }


def subgraph_callable(subg: Dict[str, Any]) -> Callable:
    """(arg arrays) -> tuple(output arrays): replays the nested graph —
    traceable, so it nests inside lax.cond/while_loop."""
    sub = subg["graph"]
    traced = sub._trace_fn(tuple(subg["outputs"]))

    def call(*vals):
        ph = dict(zip(subg["args"], vals))
        out = traced(dict(sub.arrays), ph)
        return tuple(out[o] for o in subg["outputs"])

    return call


def apply_if(kwargs: Dict[str, Any], pred, *args):
    t = subgraph_callable(kwargs["true"])
    f = subgraph_callable(kwargs["false"])
    res = jax.lax.cond(jnp.asarray(pred).astype(bool).reshape(()),
                       lambda ops: t(*ops), lambda ops: f(*ops), tuple(args))
    return res


def apply_while(kwargs: Dict[str, Any], *loop_vars):
    cond = subgraph_callable(kwargs["cond"])
    body = subgraph_callable(kwargs["body"])
    res = jax.lax.while_loop(
        lambda vs: jnp.asarray(cond(*vs)[0]).astype(bool).reshape(()),
        lambda vs: tuple(body(*vs)),
        tuple(jnp.asarray(v) for v in loop_vars))
    return res


# ------------------------------------------------------------- serialization


def subgraph_to_json(subg: Dict[str, Any]) -> Dict[str, Any]:
    from .samediff import _json_safe

    sub = subg["graph"]
    return {
        "__subgraph__": True,
        "args": subg["args"],
        "outputs": subg["outputs"],
        "vars": [{"name": v.name, "type": v.var_type,
                  "shape": list(v.shape) if v.shape else None}
                 for v in sub.vars.values()],
        "ops": [{"op": n.op_name, "inputs": n.inputs, "outputs": n.outputs,
                 "kwargs": _json_safe(n.kwargs), "n_outputs": n.n_outputs}
                for n in sub.ops],
        "arrays": {k: _small_array_json(k, v) for k, v in sub.arrays.items()},
    }


_SUBGRAPH_CONST_MAX = 65536


def _small_array_json(name: str, v):
    a = np.asarray(v)
    if a.size > _SUBGRAPH_CONST_MAX:
        raise ValueError(
            f"subgraph constant '{name}' has {a.size} elements; control-flow "
            "subgraph constants serialize into graph.json (text) — keep big "
            "tensors in the parent graph and pass them in as loop vars / "
            "if_cond inputs instead")
    return {"data": a.tolist(), "dtype": str(a.dtype)}


def subgraph_from_json(d: Dict[str, Any]) -> Dict[str, Any]:
    from .samediff import OpNode, SameDiff, SDVariable, _json_decode

    sub = SameDiff.create()
    for vd in d["vars"]:
        v = SDVariable(sub, vd["name"], vd["type"],
                       tuple(vd["shape"]) if vd["shape"] else None)
        sub.vars[vd["name"]] = v
    for n in d["ops"]:
        sub.ops.append(OpNode(n["op"], n["inputs"], n["outputs"],
                              _json_decode(n["kwargs"]), n["n_outputs"]))
    sub.arrays = {k: jnp.asarray(np.asarray(e["data"], e["dtype"]))
                  for k, e in d["arrays"].items()}
    return {"graph": sub, "args": d["args"], "outputs": d["outputs"]}
