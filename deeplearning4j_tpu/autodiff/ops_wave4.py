"""Op corpus wave 4 — closes the N6 tail named by VERDICT r4 missing #1.

Reference analog: ``libnd4j/include/ops/declarable/generic/**`` (SURVEY §2.1
N6). This wave lands the remaining named families:

- convolution/pooling tail (deconv3d, sconv2d, 1-D pools/upsampling,
  pointwise/pnorm pools, ismax) — generic/nn/convo/**
- the RNN compat family (lstm_block_cell, static/dynamic[/bidirectional]
  RNN, sru_bi) — generic/nn/recurrent/**
- the updater op family (sgd_updater … adabelief_updater, apply_sgd) —
  generic/updaters/**, generic/nn/apply_sgd.cpp
- NDArrayList / TensorArray ops (create_list … delete_list) — generic/list/**
- Barnes-Hut tSNE helpers (barnes_gains, barnes_edge_forces,
  barnes_symmetrized, cell_contains, knn_mindistance) — generic/datatypes
  + helpers/BarnesHutTsne (SURVEY §2.5 P5)
- gradient-compression codec ops (encode/decode_threshold, encode/
  decode_bitmap) — generic/compression/** (same wire semantics as
  ``native/tnd.cpp``; SURVEY §2.1 N15)
- image tail (image_resize, draw_bounding_boxes, yiq/yuv conversions,
  NMS-with-overlaps, adjust_contrast_v2) — generic/images/**
- bit ops (toggle_bits, bits_hamming_distance, shift_bits, hashcode) —
  generic/bitwise/** (declarable "helpers/hashcode")
- TF-compat tail (compat_sparse_to_dense, compat_string_split, select,
  where_np, choose, identity_n, multinomial) — generic/compat/**,
  generic/parity_ops/**
- linalg tail (eig, logdet, solve_ls) — generic/linalg/**
- the reference-canonical registry spellings (avgpool2d, maxpool3dnew,
  conv3dnew, batchnorm, *_loss names) that differ from the TF-flavoured
  aliases registered in earlier waves — both names resolve, as both are
  probe-able registry vocabulary upstream.

Every op is a jax-traceable callable except the explicitly host-side ones
(eig, compat_string_split, barnes_symmetrized, the list container family),
mirroring the reference's CPU-helper pattern. The build-failing coverage
gate in tests/test_op_validation.py applies to every name added here.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .ops_registry import OPS, op

# --------------------------------------------------------- conv / pool tail


@op("deconv3d")
def _deconv3d(x, w, stride=(2, 2, 2), padding="SAME"):
    """3-D transposed convolution, NCDHW / IODHW kernel (ref: generic/nn/
    convo/deconv3d.cpp; same kernel convention as the 2-D deconv2d op)."""
    return lax.conv_transpose(x, w, strides=tuple(stride), padding=padding,
                              dimension_numbers=("NCDHW", "IODHW", "NCDHW"))


@op("sconv2d")
def _sconv2d(x, depth_w, point_w=None, b=None, stride=(1, 1), padding="SAME"):
    """Separable conv2d, nd4j spelling (ref: generic/nn/convo/sconv2d.cpp):
    depthwise [C*M, 1, kH, kW] then optional 1x1 pointwise [O, C*M, 1, 1]."""
    C = x.shape[1]
    z = lax.conv_general_dilated(
        x, depth_w, window_strides=tuple(stride), padding=padding,
        feature_group_count=C, dimension_numbers=("NCHW", "OIHW", "NCHW"))
    if point_w is not None:
        z = lax.conv_general_dilated(z, point_w, window_strides=(1, 1),
                                     padding="VALID",
                                     dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return z if b is None else z + b[None, :, None, None]


@op("pointwise_conv2d")
def _pointwise_conv2d(x, w, b=None):
    """1x1 conv (ref: generic/nn/convo/pointwise_conv2d.cpp), NCHW/OIHW."""
    z = lax.conv_general_dilated(x, w, window_strides=(1, 1), padding="VALID",
                                 dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return z if b is None else z + b[None, :, None, None]


@op("deconv2d_tf")
def _deconv2d_tf(output_shape, w, x, stride=(2, 2), padding="SAME"):
    """TF Conv2DBackpropInput flavour (ref: generic/nn/convo/deconv2d_tf.cpp):
    first arg is the target output shape [N,C,H,W]; kernel IOHW like deconv2d."""
    z = lax.conv_transpose(x, w, strides=tuple(stride), padding=padding,
                           dimension_numbers=("NCHW", "IOHW", "NCHW"))
    tgt = tuple(int(d) for d in np.asarray(output_shape).reshape(-1))
    if tuple(z.shape) != tgt:
        raise ValueError(f"deconv2d_tf produced {z.shape}, expected {tgt}")
    return z


@op("max_pool1d")
@op("maxpool1d")
def _max_pool1d(x, kernel=2, stride=2, padding="VALID"):
    """[N, C, W] max pool (ref: generic/nn/convo/pooling/maxpool1d? — the
    1-D pools lower to 2-D with a unit height upstream; same here)."""
    k = kernel if isinstance(kernel, int) else kernel[0]
    s = stride if isinstance(stride, int) else stride[0]
    return lax.reduce_window(x, -jnp.inf, lax.max, (1, 1, k), (1, 1, s), padding)


@op("avg_pool1d")
@op("avgpool1d")
def _avg_pool1d(x, kernel=2, stride=2, padding="VALID"):
    k = kernel if isinstance(kernel, int) else kernel[0]
    s = stride if isinstance(stride, int) else stride[0]
    sm = lax.reduce_window(x, 0.0, lax.add, (1, 1, k), (1, 1, s), padding)
    c = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, (1, 1, k), (1, 1, s), padding)
    return sm / c


@op("upsampling1d")
def _upsampling1d(x, scale=2):
    """[N, C, W] nearest-neighbour repeat (ref: generic/nn/convo/upsampling1d.cpp)."""
    return jnp.repeat(x, scale, axis=2)


@op("pnormpool2d")
def _pnormpool2d(x, kernel=(2, 2), stride=(2, 2), padding="VALID", p=2.0):
    """p-norm pooling (ref: generic/nn/convo/pooling/pnormpool2d.cpp)."""
    s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, (1, 1) + tuple(kernel),
                         (1, 1) + tuple(stride), padding)
    return s ** (1.0 / p)


@op("ismax")
def _ismax(x, axis=None):
    """One-hot of the (global or per-axis) argmax (legacy transform IsMax)."""
    if axis is None:
        flat = x.reshape(-1)
        hot = jnp.zeros_like(flat).at[jnp.argmax(flat)].set(1)
        return hot.reshape(x.shape)
    idx = jnp.argmax(x, axis=axis, keepdims=True)
    return (jnp.arange(x.shape[axis]).reshape(
        tuple(-1 if i == (axis % x.ndim) else 1 for i in range(x.ndim))) == idx
    ).astype(x.dtype)


# ----------------------------------------------------------------- rnn tail


def _rnn_scan(x_tbi, h0, wx, wh, b, seq_len=None):
    """Elman RNN (tanh) over time-major input — the static/dynamic RNN core
    (ref: generic/nn/recurrent/staticRNN.cpp / dynamicRNN.cpp). With
    seq_len, the carried state freezes at each row's last real step and the
    OUTPUT is zero past it — the TF dynamic_rnn contract (r5 review)."""
    T = x_tbi.shape[0]

    def cell(h, inp):
        x_t, t = inp
        hn = jnp.tanh(x_t @ wx + h @ wh + b)
        if seq_len is None:
            return hn, hn
        alive = (t < seq_len)[:, None]
        hn = jnp.where(alive, hn, h)
        return hn, jnp.where(alive, hn, 0.0)

    hT, ys = lax.scan(cell, h0, (x_tbi, jnp.arange(T)))
    return ys, hT


@op("static_rnn")
def _static_rnn(x, h0, wx, wh, b, seq_len=None):
    """x [T,B,I] → (ys [T,B,H], h_T). seq_len [B] freezes finished rows."""
    return _rnn_scan(x, h0, wx, wh, b, seq_len)


@op("dynamic_rnn")
def _dynamic_rnn(x, h0, wx, wh, b, seq_len=None, time_major=False):
    """TF dynamicRNN flavour: batch-major [B,T,I] unless time_major."""
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)
    ys, hT = _rnn_scan(x, h0, wx, wh, b, seq_len)
    if not time_major:
        ys = jnp.swapaxes(ys, 0, 1)
    return ys, hT


def _reverse_by_len(x_tbi, seq_len):
    """reverse_sequence on a time-major [T,B,...] batch: row b reverses its
    first seq_len[b] steps, padding stays in place (TF/DL4J bidirectional
    semantics — a plain x[::-1] would feed the backward cell padding first
    and never reach short rows' real data)."""
    if seq_len is None:
        return x_tbi[::-1]
    T = x_tbi.shape[0]
    t = jnp.arange(T)[:, None]                       # [T,1]
    src = jnp.where(t < seq_len[None, :], seq_len[None, :] - 1 - t, t)  # [T,B]
    return jnp.take_along_axis(
        x_tbi, src.reshape(src.shape + (1,) * (x_tbi.ndim - 2)), axis=0)


@op("static_bidirectional_rnn")
def _static_bidirectional_rnn(x, h0f, h0b, wxf, whf, bf, wxb, whb, bb, seq_len=None):
    """Forward + per-row-reversed backward pass, outputs concatenated on H
    (ref: generic/nn/recurrent/staticBidirectionalRNN.cpp)."""
    yf, hf = _rnn_scan(x, h0f, wxf, whf, bf, seq_len)
    yb, hb = _rnn_scan(_reverse_by_len(x, seq_len), h0b, wxb, whb, bb, seq_len)
    return jnp.concatenate([yf, _reverse_by_len(yb, seq_len)], axis=-1), hf, hb


@op("dynamic_bidirectional_rnn")
def _dynamic_bidirectional_rnn(x, h0f, h0b, wxf, whf, bf, wxb, whb, bb,
                               seq_len=None, time_major=False):
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)
    ys, hf, hb = _static_bidirectional_rnn(x, h0f, h0b, wxf, whf, bf, wxb, whb,
                                           bb, seq_len)
    if not time_major:
        ys = jnp.swapaxes(ys, 0, 1)
    return ys, hf, hb


@op("lstm_block_cell")
def _lstm_block_cell(x, h_prev, c_prev, wx, wh, b, wci=None, wcf=None, wco=None,
                     forget_bias=0.0):
    """One lstmBlock step with optional peepholes + forget bias (ref:
    generic/nn/recurrent/lstmBlockCell.cpp). Returns (h, c) — the
    reference's seven intermediate outputs are recomputable from these and
    exist upstream only to feed its op-by-op backward, which jax replaces."""
    H = h_prev.shape[-1]
    z = x @ wx + h_prev @ wh + b
    i, f, g, o = z[..., :H], z[..., H:2 * H], z[..., 2 * H:3 * H], z[..., 3 * H:]
    if wci is not None:
        i = i + c_prev * wci
        f = f + c_prev * wcf
    c = jax.nn.sigmoid(f + forget_bias) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(g)
    if wco is not None:
        o = o + c * wco
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return h, c


@op("sru_bi")
def _sru_bi(x, c0f, c0b, w, wf, wr, bf, br, wb, wfb, wrb, bfb, brb):
    """Bidirectional SRU (ref: generic/nn/recurrent/sru.cpp sru_bi):
    forward + reversed backward cell, H-concatenated. x [T,B,I]."""
    fwd = OPS["sru"]
    hf, cf = fwd(x, c0f, w, wf, wr, bf, br)
    hb, cb = fwd(x[::-1], c0b, wb, wfb, wrb, bfb, brb)
    return jnp.concatenate([hf, hb[::-1]], axis=-1), cf, cb


# -------------------------------------------------------------- random tail


@op("multinomial")
def _multinomial(key, logits, num_samples):
    """TF Multinomial compat spelling (ref: generic/random/multinomial.cpp);
    same sampler as random_multinomial."""
    return OPS["random_multinomial"](key, logits, num_samples)


@op("alpha_dropout")
def _alpha_dropout(key, x, rate=0.1):
    """SELU-preserving alpha dropout (legacy random op AlphaDropOut; the
    DL4J AlphaDropout scheme): dropped units go to alpha', output is
    affine-corrected to keep mean/variance."""
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    a = (keep + alpha_p ** 2 * keep * rate) ** -0.5
    bcoef = -a * alpha_p * rate
    return a * jnp.where(mask, x, alpha_p) + bcoef


@op("dropout_inverted")
def _dropout_inverted(key, x, rate=0.5):
    """Inverted dropout (legacy random op DropOutInverted): survivors scaled
    by 1/keep at train time so inference is identity."""
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


@op("get_seed")
def _get_seed():
    """Current stateful-RNG seed (ref: generic/random/get_seed.cpp via the
    NativeOps RNG facade — here rng/random.py)."""
    from ..rng.random import get_random

    return np.int64(get_random().seed)


@op("set_seed")
def _set_seed(seed):
    from ..rng.random import set_seed as _ss

    _ss(int(seed))
    return np.int64(seed)


# --------------------------------------------------------------- image tail


@op("image_resize")
def _image_resize(images, size, method="bilinear", antialias=True):
    """Umbrella resize op (ref: generic/images/image_resize.cpp), NHWC.

    Supported kernels: bilinear/nearest/bicubic/lanczos3/lanczos5 (XLA
    resize), plus exact 'area' (box mean) for integral downscales. The
    reference's gaussian/mitchellcubic kernels have no XLA equivalent and
    raise rather than silently substituting a different filter."""
    methods = {"bilinear": "linear", "nearest": "nearest", "bicubic": "cubic",
               "lanczos3": "lanczos3", "lanczos5": "lanczos5"}
    B, H, W, C = images.shape
    h, w = (int(s) for s in np.asarray(size).reshape(-1))
    if method == "area":
        if H % h or W % w:
            raise ValueError(
                f"area resize supports integral downscale only, got {(H, W)}→{(h, w)}")
        return jnp.asarray(images).reshape(B, h, H // h, w, W // w, C).mean((2, 4))
    if method not in methods:
        raise ValueError(f"unsupported resize method '{method}' "
                         f"(supported: {sorted(methods)} + 'area')")
    if method == "nearest":
        antialias = False
    return jax.image.resize(images, (B, h, w, C), methods[method],
                            antialias=antialias)


@op("draw_bounding_boxes")
def _draw_bounding_boxes(images, boxes, colors=None):
    """Paint 1-px box borders (ref: generic/images/draw_bounding_boxes.cpp).
    images [B,H,W,C]; boxes [B,K,4] normalized (ymin,xmin,ymax,xmax);
    colors [K,C] (cycled), default red-ish first channel."""
    images = jnp.asarray(images)
    B, H, W, C = images.shape
    boxes = jnp.asarray(boxes)
    K = boxes.shape[1]
    if colors is None:
        colors = jnp.zeros((K, C)).at[:, 0].set(1.0)
    colors = jnp.asarray(colors)
    yy = jnp.arange(H)[:, None]
    xx = jnp.arange(W)[None, :]
    out = images
    for kbox in range(K):
        y0 = jnp.round(boxes[:, kbox, 0] * (H - 1)).astype(jnp.int32)
        x0 = jnp.round(boxes[:, kbox, 1] * (W - 1)).astype(jnp.int32)
        y1 = jnp.round(boxes[:, kbox, 2] * (H - 1)).astype(jnp.int32)
        x1 = jnp.round(boxes[:, kbox, 3] * (W - 1)).astype(jnp.int32)
        inside = ((yy[None] >= y0[:, None, None]) & (yy[None] <= y1[:, None, None])
                  & (xx[None] >= x0[:, None, None]) & (xx[None] <= x1[:, None, None]))
        border = inside & ((yy[None] == y0[:, None, None]) | (yy[None] == y1[:, None, None])
                           | (xx[None] == x0[:, None, None]) | (xx[None] == x1[:, None, None]))
        color = colors[kbox % colors.shape[0]]
        out = jnp.where(border[..., None], color, out)
    return out


_YIQ = np.array([[0.299, 0.587, 0.114],
                 [0.5959, -0.2746, -0.3213],
                 [0.2115, -0.5227, 0.3112]], np.float32)
_YUV = np.array([[0.299, 0.587, 0.114],
                 [-0.14714119, -0.28886916, 0.43601035],
                 [0.61497538, -0.51496512, -0.10001026]], np.float32)


@op("rgb_to_yiq")
def _rgb_to_yiq(x):
    """(ref: generic/images/rgb_to_yiq.cpp) — last axis is the channel."""
    return x @ jnp.asarray(_YIQ).T


@op("yiq_to_rgb")
def _yiq_to_rgb(x):
    return x @ jnp.asarray(np.linalg.inv(_YIQ)).T


@op("rgb_to_yuv")
def _rgb_to_yuv(x):
    return x @ jnp.asarray(_YUV).T


@op("yuv_to_rgb")
def _yuv_to_rgb(x):
    return x @ jnp.asarray(np.linalg.inv(_YUV)).T


@op("adjust_contrast_v2")
def _adjust_contrast_v2(x, factor):
    """Per-channel-mean contrast scaling (ref: generic/images/
    adjust_contrast.cpp, the _v2 TF-parity variant)."""
    mean = jnp.mean(x, axis=(-3, -2), keepdims=True)
    return (x - mean) * factor + mean


@op("non_max_suppression_overlaps")
def _nms_overlaps(overlaps, scores, max_out, overlap_threshold=0.5,
                  score_threshold=-jnp.inf):
    """NMS on a precomputed [N,N] overlaps matrix (ref: generic/images/
    non_max_suppression_overlaps.cpp). Returns (indices [max_out] padded
    with -1, count)."""
    overlaps = jnp.asarray(overlaps)
    scores = jnp.asarray(scores)
    N = scores.shape[0]
    alive = scores > score_threshold

    def body(carry, _):
        alive, out, cnt = carry
        masked = jnp.where(alive, scores, -jnp.inf)
        best = jnp.argmax(masked)
        ok = masked[best] > -jnp.inf
        out = out.at[cnt].set(jnp.where(ok, best, -1))
        cnt = cnt + ok.astype(jnp.int32)
        suppress = overlaps[best] > overlap_threshold
        alive = alive & ~suppress & ok
        return (alive, out, cnt), None

    out0 = jnp.full((max_out,), -1, jnp.int32)
    (alive, out, cnt), _ = lax.scan(body, (alive, out0, jnp.int32(0)),
                                    None, length=max_out)
    return out, cnt


# ----------------------------------------------------------------- bit ops


@op("toggle_bits")
def _toggle_bits(x):
    """Bitwise NOT on integer buffers (ref: generic/bitwise/toggle_bits.cpp)."""
    return jnp.invert(jnp.asarray(x))


@op("shift_bits")
def _shift_bits(x, shift):
    """nd4j spelling of left shift (generic/bitwise/shift_bits.cpp)."""
    return jnp.left_shift(jnp.asarray(x), shift)


@op("rshift_bits")
def _rshift_bits(x, shift):
    return jnp.right_shift(jnp.asarray(x), shift)


def _popcount32(v):
    v = v - ((v >> 1) & 0x55555555)
    v = (v & 0x33333333) + ((v >> 2) & 0x33333333)
    v = (v + (v >> 4)) & 0x0F0F0F0F
    return (v * 0x01010101) >> 24


@op("bits_hamming_distance")
def _bits_hamming_distance(a, b):
    """Total differing BITS (ref: generic/bitwise/bits_hamming_distance.cpp)
    — distinct from the elementwise 'hamming_distance' reduction."""
    x = jnp.bitwise_xor(jnp.asarray(a, jnp.int32), jnp.asarray(b, jnp.int32))
    return jnp.sum(_popcount32(x.astype(jnp.uint32)).astype(jnp.int64))


@op("hashcode")
def _hashcode(x):
    """Deterministic buffer hash (ref: libnd4j helpers/hashcode.h — the
    java-style 31·h + v fold over the raw int32 view). Computed in closed
    form, h = 17·31^N + Σ v_i·31^(N−1−i) under wraparound arithmetic, so
    the whole hash is one parallel cumprod + dot instead of an O(N)
    sequential scan (r5 review)."""
    v = jnp.asarray(x)
    if v.dtype in (jnp.float32, jnp.float64, jnp.bfloat16, jnp.float16):
        v = lax.bitcast_convert_type(v.astype(jnp.float32), jnp.int32)
    v = v.astype(jnp.int64).reshape(-1)
    n = v.shape[0]
    if n == 0:
        return jnp.int64(17)
    base = jnp.full((n,), 31, v.dtype).at[0].set(1)
    powers = jnp.flip(jnp.cumprod(base))          # 31^(N-1) … 31^0, wrapping
    return jnp.int64(17) * powers[0] * jnp.asarray(31, v.dtype) + jnp.sum(v * powers)


# -------------------------------------------------------------- compat tail


@op("compat_sparse_to_dense")
def _compat_sparse_to_dense(indices, shape, values, default=0):
    """(ref: generic/compat/compat_sparse_to_dense.cpp) indices [N,R]."""
    shape = tuple(int(s) for s in np.asarray(shape).reshape(-1))
    out = jnp.full(shape, default, dtype=jnp.asarray(values).dtype)
    return out.at[tuple(jnp.asarray(indices, jnp.int32).T)].set(values)


@op("compat_string_split")
def _compat_string_split(strings, delimiter=" "):
    """Host-side (string tensors never reach the device — the reference
    runs this on CPU too; ref: generic/compat/compat_string_split.cpp).
    Returns (indices [N,2], values list, dense_shape)."""
    strings = np.asarray(strings).reshape(-1)
    indices, values = [], []
    max_c = 0
    for i, s in enumerate(strings):
        parts = str(s).split(delimiter) if delimiter else list(str(s))
        parts = [p for p in parts if p != ""]
        max_c = max(max_c, len(parts))
        for j, p in enumerate(parts):
            indices.append((i, j))
            values.append(p)
    return (np.asarray(indices, np.int64).reshape(-1, 2), values,
            np.asarray([len(strings), max_c], np.int64))


@op("select")
def _select(cond, a, b):
    """TF Select (ref: generic/parity_ops/select.cpp)."""
    return jnp.where(jnp.asarray(cond, bool), a, b)


@op("where_np")
def _where_np(cond, a=None, b=None):
    """numpy-flavoured where (ref: generic/parity_ops/where_np.cpp):
    1-arg form returns the [N, rank] index matrix of true positions,
    padded with -1 rows to the input size (static shapes under jit)."""
    cond = jnp.asarray(cond)
    if a is not None:
        return jnp.where(cond.astype(bool), a, b)
    flat = cond.reshape(-1).astype(bool)
    n = flat.shape[0]
    order = jnp.argsort(~flat)  # true positions first, stable
    rows = jnp.stack(jnp.unravel_index(order, cond.shape), axis=1)
    valid = flat[order][:, None]
    return jnp.where(valid, rows, -1), jnp.sum(flat.astype(jnp.int32))


@op("choose")
def _choose(x, comp, mode=0):
    """nd4j 'choose' (generic/parity_ops/choose.cpp): filter by comparison
    mode (0:<, 1:<=, 2:>, 3:>=, 4:==, 5:!=) against scalar/array ``comp``.
    Returns (matching values front-packed, count) with static shapes."""
    x = jnp.asarray(x).reshape(-1)
    cmp = [jnp.less, jnp.less_equal, jnp.greater, jnp.greater_equal,
           jnp.equal, jnp.not_equal][mode]
    keep = cmp(x, comp)
    order = jnp.argsort(~keep)
    vals = jnp.where(keep[order], x[order], 0)
    return vals, jnp.sum(keep.astype(jnp.int32))


@op("identity_n")
def _identity_n(*xs):
    """(ref: generic/parity_ops/identity_n.cpp)"""
    return tuple(jnp.asarray(x) for x in xs)


@op("crelu")
def _crelu(x, axis=-1):
    """Concatenated ReLU (ref: generic/parity_ops/crelu.cpp)."""
    return jnp.concatenate([jax.nn.relu(x), jax.nn.relu(-x)], axis=axis)


@op("precise_gelu")
def _precise_gelu(x):
    """erf-form gelu (ref: generic/nn/activations — precise_gelu)."""
    return jax.nn.gelu(x, approximate=False)


@op("argamax")
def _argamax(x, axis=None):
    """Index of max |x| (legacy IAMax / declarable argamax)."""
    return jnp.argmax(jnp.abs(x), axis=axis)


@op("argamin")
def _argamin(x, axis=None):
    return jnp.argmin(jnp.abs(x), axis=axis)


@op("ones_as")
def _ones_as(x):
    return jnp.ones_like(x)


@op("zeros_as")
def _zeros_as(x):
    return jnp.zeros_like(x)


@op("assert")
def _assert(cond, message="assertion failed"):
    """Host assertion on concrete values; under jit it degrades to a
    checkable passthrough (the reference's Assert is likewise a no-op in
    release graphs)."""
    c = jnp.asarray(cond)
    if not isinstance(c, jax.core.Tracer) and not bool(jnp.all(c)):
        raise AssertionError(message)
    return c


@op("fake_quant_with_min_max_vars_per_channel")
def _fake_quant_per_channel(x, mins, maxs, num_bits=8, narrow_range=False):
    """Per-channel variant (last axis) of fake_quant_with_min_max_vars."""
    per = OPS["fake_quant_with_min_max_vars"]
    return jax.vmap(lambda col, lo, hi: per(col, lo, hi, num_bits, narrow_range),
                    in_axes=(-1, 0, 0), out_axes=-1)(x, mins, maxs)


@op("match_condition")
def _match_condition(x, value, mode=4, eps=1e-5):
    """Count of elements matching a condition (legacy MatchCondition
    reduction; mode as in 'choose', 4 = eps-equals)."""
    x = jnp.asarray(x)
    if mode == 4:
        keep = jnp.abs(x - value) <= eps
    else:
        keep = [jnp.less, jnp.less_equal, jnp.greater, jnp.greater_equal,
                None, jnp.not_equal][mode](x, value)
    return jnp.sum(keep.astype(jnp.int64))


@op("evaluate_reduction_shape")
def _evaluate_reduction_shape(shape, axes, keepdims=False):
    """(ref: generic/shape/evaluate_reduction_shape.cpp)"""
    shape = [int(s) for s in np.asarray(shape).reshape(-1)]
    axes = {a % len(shape) for a in np.asarray(axes).reshape(-1).tolist()}
    if keepdims:
        out = [1 if i in axes else d for i, d in enumerate(shape)]
    else:
        out = [d for i, d in enumerate(shape) if i not in axes]
    return np.asarray(out, np.int64)


@op("create")
def _create(shape, dtype="float32", order="c"):
    """Allocate a zeroed array (ref: generic/parity_ops/create.cpp); order
    is metadata here — XLA owns physical layout (SURVEY §2.9)."""
    return jnp.zeros(tuple(int(s) for s in np.asarray(shape).reshape(-1)),
                     jnp.dtype(dtype))


@op("broadcastgradientargs")
def _broadcastgradientargs(shape_a, shape_b):
    """Axes each operand must sum-reduce over after a broadcast op — the
    TF BroadcastGradientArgs contract (ref: generic/shape/
    broadcastgradientargs? — used by the import path's grad splitting)."""
    sa = [int(s) for s in np.asarray(shape_a).reshape(-1)]
    sb = [int(s) for s in np.asarray(shape_b).reshape(-1)]
    r = max(len(sa), len(sb))
    pa = [1] * (r - len(sa)) + sa
    pb = [1] * (r - len(sb)) + sb
    ra = [i for i in range(r) if pa[i] == 1 and pb[i] != 1]
    rb = [i for i in range(r) if pb[i] == 1 and pa[i] != 1]
    return np.asarray(ra, np.int64), np.asarray(rb, np.int64)


@op("tear")
def _tear(x, axis=0):
    """Split into unit slices along axis (ref: generic/transforms/tear.cpp);
    returns a tuple, the inverse of stack."""
    return tuple(jnp.squeeze(s, axis=axis)
                 for s in jnp.split(jnp.asarray(x), x.shape[axis], axis=axis))


@op("truncatemod")
def _truncatemod(a, b):
    """C-style remainder, truncation toward zero (generic/broadcastable)."""
    return jnp.fmod(a, b)


@op("axpy")
def _axpy(x, y, alpha=1.0):
    """BLAS axpy as a declarable op (legacy blas/axpy)."""
    return alpha * x + y


@op("stabilize")
def _stabilize(x, cutoff=1e-5):
    """Legacy Stabilize transform: clamp tiny magnitudes away from zero
    (negatives to −cutoff, zero and small positives to +cutoff)."""
    return jnp.where(jnp.abs(x) < cutoff,
                     jnp.where(x < 0, -cutoff, cutoff), x)


@op("log_x")
def _log_x(x, base=np.e):
    """Legacy LogX transform: log base-n."""
    return jnp.log(x) / np.log(base)


@op("pow_derivative")
def _pow_derivative(x, p=2.0):
    """Legacy PowDerivative transform: p * x^(p-1)."""
    return p * x ** (p - 1.0)


# -------------------------------------------------------------- linalg tail


@op("eig")
def _eig(x):
    """General (non-symmetric) eigendecomposition. Host-side numpy: XLA has
    no TPU lowering for general eig (the reference's is a CPU helper too;
    ref: generic/linalg — eig). Returns (eigenvalues, eigenvectors),
    complex64."""
    w, v = np.linalg.eig(np.asarray(x, np.float64))
    return np.asarray(w, np.complex64), np.asarray(v, np.complex64)


@op("logdet")
def _logdet(x):
    """log|det| for SPD batches via Cholesky (ref: generic/linalg/logdet.cpp)."""
    L = jnp.linalg.cholesky(x)
    return 2.0 * jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), axis=-1)


@op("solve_ls")
def _solve_ls(a, b, fast=True):
    """Least-squares solve, nd4j spelling (generic/linalg/lstsq.cpp twin)."""
    return OPS["lstsq"](a, b)


# ------------------------------------------------------------ updater family
# (ref: libnd4j/include/ops/declarable/generic/updaters/*.cpp — the raw
# updater math as declarable ops, distinct from the nn/updaters.py classes
# the trainers use; both exist upstream.)


@op("apply_sgd")
def _apply_sgd(params, grad, lr=0.01):
    """(ref: generic/nn/apply_sgd.cpp)"""
    return params - lr * grad


@op("sgd_updater")
def _sgd_updater(grad, lr=0.01):
    return grad * lr


@op("nesterovs_updater")
def _nesterovs_updater(grad, state_v, lr=0.1, momentum=0.9):
    """DL4J Nesterov momentum (ref: generic/updaters/nesterovsUpdater.cpp):
    v ← μv − λg; update = μ·v_prev − (1+μ)·v (applied as params − update)."""
    v = momentum * state_v - lr * grad
    return momentum * state_v - (1.0 + momentum) * v, v


@op("adam_updater")
def _adam_updater(grad, state_u, state_m, lr=1e-3, beta1=0.9, beta2=0.999,
                  eps=1e-8, iteration=0):
    m = beta1 * state_m + (1 - beta1) * grad
    u = beta2 * state_u + (1 - beta2) * grad * grad
    t = iteration + 1
    a = lr * jnp.sqrt(1 - beta2 ** t) / (1 - beta1 ** t)
    return a * m / (jnp.sqrt(u) + eps), u, m


@op("ada_grad_updater")
def _ada_grad_updater(grad, state_h, lr=0.01, eps=1e-6):
    h = state_h + grad * grad
    return lr * grad / (jnp.sqrt(h) + eps), h


@op("ada_delta_updater")
def _ada_delta_updater(grad, state_msg, state_msdx, rho=0.95, eps=1e-6):
    msg = rho * state_msg + (1 - rho) * grad * grad
    dx = jnp.sqrt(state_msdx + eps) / jnp.sqrt(msg + eps) * grad
    msdx = rho * state_msdx + (1 - rho) * dx * dx
    return dx, msg, msdx


@op("rms_prop_updater")
def _rms_prop_updater(grad, state_g, lr=0.01, decay=0.95, eps=1e-8):
    g = decay * state_g + (1 - decay) * grad * grad
    return lr * grad / (jnp.sqrt(g) + eps), g


@op("ada_max_updater")
def _ada_max_updater(grad, state_u, state_m, lr=2e-3, beta1=0.9, beta2=0.999,
                     eps=1e-8, iteration=0):
    m = beta1 * state_m + (1 - beta1) * grad
    u = jnp.maximum(beta2 * state_u, jnp.abs(grad))
    t = iteration + 1
    return lr / (1 - beta1 ** t) * m / (u + eps), u, m


@op("nadam_updater")
def _nadam_updater(grad, state_u, state_m, lr=1e-3, beta1=0.9, beta2=0.999,
                   eps=1e-8, iteration=0):
    m = beta1 * state_m + (1 - beta1) * grad
    u = beta2 * state_u + (1 - beta2) * grad * grad
    t = iteration + 1
    mhat = m / (1 - beta1 ** t)
    uhat = u / (1 - beta2 ** t)
    return lr * (beta1 * mhat + (1 - beta1) * grad / (1 - beta1 ** t)) / (
        jnp.sqrt(uhat) + eps), u, m


@op("ams_grad_updater")
def _ams_grad_updater(grad, state_u, state_m, state_h, lr=1e-3, beta1=0.9,
                      beta2=0.999, eps=1e-8, iteration=0):
    m = beta1 * state_m + (1 - beta1) * grad
    u = beta2 * state_u + (1 - beta2) * grad * grad
    h = jnp.maximum(state_h, u)
    t = iteration + 1
    a = lr * jnp.sqrt(1 - beta2 ** t) / (1 - beta1 ** t)
    return a * m / (jnp.sqrt(h) + eps), u, m, h


@op("adabelief_updater")
def _adabelief_updater(grad, state_u, state_m, lr=1e-3, beta1=0.9, beta2=0.999,
                       eps=1e-8, iteration=0):
    m = beta1 * state_m + (1 - beta1) * grad
    u = beta2 * state_u + (1 - beta2) * (grad - m) ** 2 + eps
    t = iteration + 1
    a = lr * jnp.sqrt(1 - beta2 ** t) / (1 - beta1 ** t)
    return a * m / (jnp.sqrt(u) + eps), u, m


# -------------------------------------------------------- NDArrayList family
# (ref: generic/list/*.cpp — the graph-side TensorArray/NDArrayList ops.
# The container is host-side by design, like the reference's CPU list
# holder; the arrays inside stay on device.)


class NDArrayList:
    """Append/scatter list of same-rank arrays (ref: nd4j NDArrayList)."""

    def __init__(self, arrays=None):
        self.arrays = dict(arrays or {})

    def max_index(self):
        return max(self.arrays, default=-1)


@op("create_list")
def _create_list(*_unused):
    return NDArrayList()


@op("write_list")
def _write_list(lst, idx, arr):
    lst.arrays[int(idx)] = jnp.asarray(arr)
    return lst


@op("read_list")
def _read_list(lst, idx):
    return lst.arrays[int(idx)]


@op("size_list")
def _size_list(lst):
    return np.int64(lst.max_index() + 1)


@op("stack_list")
def _stack_list(lst):
    return jnp.stack([lst.arrays[i] for i in range(lst.max_index() + 1)])


@op("unstack_list")
def _unstack_list(arr):
    arr = jnp.asarray(arr)
    return NDArrayList({i: arr[i] for i in range(arr.shape[0])})


@op("scatter_list")
def _scatter_list(lst, indices, arr):
    arr = jnp.asarray(arr)
    for j, i in enumerate(np.asarray(indices).reshape(-1)):
        lst.arrays[int(i)] = arr[j]
    return lst


@op("gather_list")
def _gather_list(lst, indices):
    return jnp.stack([lst.arrays[int(i)] for i in np.asarray(indices).reshape(-1)])


@op("split_list")
def _split_list(lst, arr, sizes):
    arr = jnp.asarray(arr)
    off = 0
    for i, s in enumerate(np.asarray(sizes).reshape(-1)):
        lst.arrays[i] = arr[off:off + int(s)]
        off += int(s)
    return lst


@op("pick_list")
def _pick_list(lst, indices):
    return jnp.concatenate([jnp.atleast_1d(lst.arrays[int(i)])
                            for i in np.asarray(indices).reshape(-1)])


@op("clone_list")
def _clone_list(lst):
    return NDArrayList(lst.arrays)


@op("delete_list")
def _delete_list(lst, idx=None):
    if idx is None:
        lst.arrays.clear()
    else:
        lst.arrays.pop(int(idx), None)
    return lst


# -------------------------------------------------- Barnes-Hut tSNE helpers


@op("barnes_gains")
def _barnes_gains(gains, gradx, epsilon):
    """tSNE adaptive gains (ref: generic — barnes_gains; helpers/
    BarnesHutTsne): +0.2 where grad and step disagree in sign, ×0.8 where
    they agree, floored at 0.01."""
    same = jnp.sign(gradx) == jnp.sign(epsilon)
    return jnp.maximum(jnp.where(same, gains * 0.8, gains + 0.2), 0.01)


@op("barnes_edge_forces")
def _barnes_edge_forces(row_p, col_p, val_p, n, data):
    """Attractive edge forces over the sparse P (CSR rows row_p, cols
    col_p, values val_p): F_i = Σ_j p_ij (y_i - y_j) / (1 + |y_i - y_j|²).
    Edge loop is a segment-sum — TPU-friendly, no scatter races."""
    row_p = np.asarray(row_p, np.int64).reshape(-1)
    col = jnp.asarray(col_p, jnp.int32).reshape(-1)
    val = jnp.asarray(val_p)
    data = jnp.asarray(data)
    src = np.repeat(np.arange(n), np.diff(row_p)).astype(np.int32)
    d = data[src] - data[col]
    w = val / (1.0 + jnp.sum(d * d, axis=-1))
    return jax.ops.segment_sum(w[:, None] * d, jnp.asarray(src), num_segments=int(n))


@op("barnes_symmetrized")
def _barnes_symmetrized(row_p, col_p, val_p, n):
    """Symmetrize sparse P: P = (P + Pᵀ)/2 on CSR triplets. Host-side —
    output sparsity is data-dependent (the reference's is a CPU helper)."""
    row_p = np.asarray(row_p, np.int64).reshape(-1)
    col_p = np.asarray(col_p, np.int64).reshape(-1)
    val_p = np.asarray(val_p, np.float64).reshape(-1)
    acc = {}
    for i in range(int(n)):
        for k in range(row_p[i], row_p[i + 1]):
            j = int(col_p[k])
            acc[(i, j)] = acc.get((i, j), 0.0) + val_p[k] / 2.0
            acc[(j, i)] = acc.get((j, i), 0.0) + val_p[k] / 2.0
    keys = sorted(acc)
    rows = np.zeros(int(n) + 1, np.int64)
    for (i, _j) in keys:
        rows[i + 1] += 1
    rows = np.cumsum(rows)
    cols = np.asarray([j for (_i, j) in keys], np.int64)
    vals = np.asarray([acc[k] for k in keys], np.float32)
    return rows, cols, vals


@op("cell_contains")
def _cell_contains(corner, width, point):
    """Barnes-Hut space-partitioning predicate: point inside the cell
    [corner - width/2, corner + width/2] on every axis."""
    corner = jnp.asarray(corner)
    width = jnp.asarray(width)
    point = jnp.asarray(point)
    return jnp.all((point >= corner - width / 2) & (point <= corner + width / 2))


@op("knn_mindistance")
def _knn_mindistance(point, lowest, highest):
    """Min distance from a point to an axis-aligned box (ref: generic/
    parity_ops/knn_mindistance.cpp — the KNN tree-pruning bound)."""
    clamped = jnp.clip(jnp.asarray(point), lowest, highest)
    return jnp.sqrt(jnp.sum((point - clamped) ** 2))


# ------------------------------------------------- compression codec ops
# (ref: generic/compression/threshold.cpp + bitmap.cpp; same semantics as
# the C++ codecs in native/tnd.cpp — these are the graph-op spellings.)


@op("encode_threshold")
def _encode_threshold(grad, threshold=1e-3):
    """Sign-threshold encode: returns (flat indices int32, signs ±1 float32,
    residual). Elements |g| >= threshold are quantized to ±threshold and
    subtracted; the rest accumulate in the residual."""
    g = jnp.asarray(grad)
    flat = g.reshape(-1)
    fire = jnp.abs(flat) >= threshold
    order = jnp.argsort(~fire)
    idx = jnp.where(fire[order], order, -1).astype(jnp.int32)
    signs = jnp.where(fire[order], jnp.sign(flat[order]), 0.0)
    residual = jnp.where(fire, flat - jnp.sign(flat) * threshold, flat).reshape(g.shape)
    return idx, signs, residual


@op("decode_threshold")
def _decode_threshold(idx, signs, shape, threshold=1e-3):
    flat = jnp.zeros(int(np.prod(shape)), jnp.float32)
    safe = jnp.where(idx >= 0, idx, 0)
    flat = flat.at[safe].add(jnp.where(idx >= 0, signs * threshold, 0.0))
    return flat.reshape(tuple(int(s) for s in np.asarray(shape).reshape(-1)))


@op("encode_bitmap")
def _encode_bitmap(grad, threshold=1e-3):
    """2-bit bitmap encode (ref: bitmap.cpp): 0 = skip, 1 = +threshold,
    2 = -threshold, packed 16 codes per int32. Returns (codes, residual)."""
    g = jnp.asarray(grad).reshape(-1)
    code = jnp.where(g >= threshold, 1, jnp.where(g <= -threshold, 2, 0)).astype(jnp.uint32)
    pad = (-code.shape[0]) % 16
    code = jnp.pad(code, (0, pad))
    packed = code.reshape(-1, 16) << (2 * jnp.arange(16, dtype=jnp.uint32))
    codes = lax.reduce(packed, jnp.uint32(0), lax.bitwise_or, (1,))
    applied = jnp.where(code[:g.shape[0]] == 1, threshold,
                        jnp.where(code[:g.shape[0]] == 2, -threshold, 0.0))
    return codes.astype(jnp.int32), (g - applied).reshape(jnp.asarray(grad).shape)


@op("decode_bitmap")
def _decode_bitmap(codes, length, threshold=1e-3):
    c = jnp.asarray(codes).astype(jnp.uint32)
    expanded = (c[:, None] >> (2 * jnp.arange(16, dtype=jnp.uint32))) & 0x3
    flat = expanded.reshape(-1)[:int(length)]
    return jnp.where(flat == 1, threshold, jnp.where(flat == 2, -threshold, 0.0))


# ------------------------------------------------------------- reduce tail
# (the declarable reduce_* spellings — distinct registry entries from the
# legacy norm1/norm2/normmax/sqnorm reductions upstream, same math)


@op("reduce_norm1")
def _reduce_norm1(x, dims=None, keepdims=False):
    return jnp.sum(jnp.abs(x), axis=dims, keepdims=keepdims)


@op("reduce_norm2")
def _reduce_norm2(x, dims=None, keepdims=False):
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=dims, keepdims=keepdims))


@op("reduce_norm_max")
def _reduce_norm_max(x, dims=None, keepdims=False):
    return jnp.max(jnp.abs(x), axis=dims, keepdims=keepdims)


@op("reduce_sqnorm")
def _reduce_sqnorm(x, dims=None, keepdims=False):
    return jnp.sum(jnp.square(x), axis=dims, keepdims=keepdims)


@op("reduce_variance")
def _reduce_variance(x, dims=None, keepdims=False, bias_corrected=False):
    return jnp.var(x, axis=dims, keepdims=keepdims,
                   ddof=1 if bias_corrected else 0)


@op("reduce_stdev")
def _reduce_stdev(x, dims=None, keepdims=False, bias_corrected=False):
    return jnp.std(x, axis=dims, keepdims=keepdims,
                   ddof=1 if bias_corrected else 0)


# -------------------------------------------------------------- shape tail


@op("order")
def _order(x, order="c"):
    """Layout-order copy (ref: generic/shape/order.cpp). Physical layout is
    XLA's (SURVEY §2.9) — semantically a copy; the NDArray facade carries
    the order flag."""
    return jnp.asarray(x) + 0


@op("tile_to_shape")
def _tile_to_shape(x, shape):
    """(ref: generic/shape/tile_to_shape.cpp)"""
    shape = tuple(int(s) for s in np.asarray(shape).reshape(-1))
    reps = tuple(t // s for t, s in zip(shape, x.shape))
    return jnp.tile(x, reps)


@op("reshape_as")
def _reshape_as(x, y):
    return jnp.reshape(x, jnp.asarray(y).shape)


@op("flatten")
def _flatten(*xs, order="c"):
    """Concat of raveled inputs (ref: generic/flatten.cpp)."""
    return jnp.concatenate([jnp.asarray(x).reshape(-1) for x in xs])


@op("shapes_of")
def _shapes_of(*xs):
    return tuple(np.asarray(jnp.asarray(x).shape, np.int64) for x in xs)


# ---------------------------------------------------------------- nlp tail


@op("skipgram_inference")
def _skipgram_inference(syn0, syn1neg, center, targets):
    """Inference-mode skip-gram scores (newer sg_cb.cpp *_inference ops):
    sigmoid(h · w_t) for one center row against target rows — no update."""
    h = jnp.asarray(syn0)[jnp.asarray(center, jnp.int32)]
    w = jnp.asarray(syn1neg)[jnp.asarray(targets, jnp.int32)]
    return jax.nn.sigmoid(w @ h)


@op("cbow_inference")
def _cbow_inference(syn0, syn1neg, context, targets):
    """Inference-mode CBOW scores: h = mean of context rows."""
    h = jnp.asarray(syn0)[jnp.asarray(context, jnp.int32)].mean(axis=0)
    w = jnp.asarray(syn1neg)[jnp.asarray(targets, jnp.int32)]
    return jax.nn.sigmoid(w @ h)


# ----------------------------------------------------------- attention tail


@op("dot_product_attention_v2")
def _dot_product_attention_v2(q, k, v, mask=None, scale=None, causal=False):
    """The newer libnd4j attention op (generic/nn/dot_product_attention_v2
    .cpp) — routed through the framework front door, so on TPU it hits the
    Pallas flash path incl. the masked variant ([B,H,T,D] layout)."""
    from ..kernels.attention import dot_product_attention

    return dot_product_attention(q, k, v, mask, causal=causal, scale=scale)


# ----------------------------------------------------------------- util ops


@op("print_variable")
def _print_variable(x, message=""):
    jax.debug.print("{m}{x}", m=message, x=x)
    return x


@op("print_affinity")
def _print_affinity(x):
    x = jnp.asarray(x)
    dev = getattr(x, "devices", lambda: {"<traced>"})()
    jax.debug.print("affinity: {d}", d=str(dev))
    return x


# --------------------------------------- reference-canonical name aliases
# The libnd4j registry spells several ops differently from the TF-flavoured
# names earlier waves registered; both spellings are real probe-able
# vocabulary upstream, so both resolve here (same impl object). The test
# gate imports this map so alias and validation case stay in lockstep.

CANONICAL_ALIASES = {
    # broadcastable / comparison canonical spellings (libnd4j registers the
    # long names; the short TF-flavoured twins were registered in wave 1)
    "subtract": "sub",
    "multiply": "mul",
    "divide": "div",
    "reversesubtract": "rsub",
    "reversedivide": "rdiv",
    "squaredsubtract": "squared_difference",
    "greater": "gt",
    "greater_equal": "gte",
    "less": "lt",
    "less_equal": "lte",
    "equals": "eq",
    "not_equals": "neq",
    "onehot": "one_hot",
    "avgpool2d": "avg_pool2d",
    "maxpool2d": "max_pool2d",
    "avgpool3dnew": "avg_pool3d",
    "maxpool3dnew": "max_pool3d",
    "conv3dnew": "conv3d",
    "batchnorm": "batch_norm",
    "softmax_cross_entropy_loss": "softmax_cross_entropy",
    "sigm_cross_entropy_loss": "sigmoid_cross_entropy",
    "absolute_difference_loss": "absolute_difference",
    "cosine_distance_loss": "cosine_distance",
    "mean_sqerr_loss": "mean_squared_error",
}
for _canon, _alias in CANONICAL_ALIASES.items():
    OPS[_canon] = OPS[_alias]
