"""Per-op config beans (VERDICT r4 partial J3 tail).

Reference: ``org.nd4j.linalg.api.ops.impl.layers.convolution.config.*``
(Conv1DConfig/Conv2DConfig/Conv3DConfig/DeConv2DConfig/DeConv3DConfig/
Pooling2DConfig/Pooling3DConfig/LocalResponseNormalizationConfig) and the
recurrent ``LSTMConfiguration`` — validated parameter beans the SameDiff
op builders consume instead of loose int lists.

Here each bean is a dataclass with the reference's field names
(kH/kW/sH/sW/pH/pW/dH/dW, isSameMode …), a ``validate()`` that enforces
the same constraints the reference's builders do, and an ``execute(…)``
that lowers onto the op registry — so graph-building code ported from
nd4j keeps its shape while execution stays whole-graph XLA.
"""

from __future__ import annotations

from dataclasses import dataclass, fields

from .ops_registry import OPS

__all__ = [
    "Conv1DConfig", "Conv2DConfig", "Conv3DConfig",
    "DeConv2DConfig", "DeConv3DConfig",
    "Pooling2DConfig", "Pooling3DConfig",
    "LocalResponseNormalizationConfig", "LSTMConfiguration",
]


class OpConfigError(ValueError):
    """Invalid bean field combination (the reference's IllegalState)."""


def _positive(cfg, *names):
    for n in names:
        if getattr(cfg, n) <= 0:
            raise OpConfigError(
                f"{type(cfg).__name__}.{n} must be > 0, got {getattr(cfg, n)}")


def _non_negative(cfg, *names):
    for n in names:
        if getattr(cfg, n) < 0:
            raise OpConfigError(
                f"{type(cfg).__name__}.{n} must be >= 0, got {getattr(cfg, n)}")


class _Bean:
    def validate(self):
        raise NotImplementedError

    def to_dict(self) -> dict:
        return {f.name: getattr(self, f.name) for f in fields(self)}

    def _padding(self, pads):
        # isSameMode wins over explicit pads, like the reference builders
        return "SAME" if self.isSameMode else [(p, p) for p in pads]


@dataclass
class Conv2DConfig(_Bean):
    """ref: …convolution.config.Conv2DConfig (kH,kW,sH,sW,pH,pW,dH,dW,
    isSameMode, dataFormat). Execution layout is NCHW (the nd4j default)."""

    kH: int = 1
    kW: int = 1
    sH: int = 1
    sW: int = 1
    pH: int = 0
    pW: int = 0
    dH: int = 1
    dW: int = 1
    isSameMode: bool = False
    dataFormat: str = "NCHW"

    def validate(self):
        _positive(self, "kH", "kW", "sH", "sW", "dH", "dW")
        _non_negative(self, "pH", "pW")
        if self.dataFormat != "NCHW":
            raise OpConfigError("dataFormat NCHW only (public layout; XLA "
                                "owns physical layout per SURVEY §2.9)")
        return self

    def execute(self, x, w, b=None):
        self.validate()
        return OPS["conv2d"](x, w, b, stride=(self.sH, self.sW),
                             padding=self._padding((self.pH, self.pW)),
                             dilation=(self.dH, self.dW))


@dataclass
class Conv1DConfig(_Bean):
    """ref: Conv1DConfig (k, s, p, isSameMode); NCW layout."""

    k: int = 1
    s: int = 1
    p: int = 0
    isSameMode: bool = False

    def validate(self):
        _positive(self, "k", "s")
        _non_negative(self, "p")
        return self

    def execute(self, x, w, b=None):
        self.validate()
        return OPS["conv1d"](x, w, b, stride=self.s,
                             padding="SAME" if self.isSameMode else [(self.p, self.p)])


@dataclass
class Conv3DConfig(_Bean):
    """ref: Conv3DConfig (kD,kH,kW,sD,sH,sW,pD,pH,pW, biasUsed, isSameMode);
    NCDHW layout."""

    kD: int = 1
    kH: int = 1
    kW: int = 1
    sD: int = 1
    sH: int = 1
    sW: int = 1
    pD: int = 0
    pH: int = 0
    pW: int = 0
    biasUsed: bool = False
    isSameMode: bool = False

    def validate(self):
        _positive(self, "kD", "kH", "kW", "sD", "sH", "sW")
        _non_negative(self, "pD", "pH", "pW")
        return self

    def execute(self, x, w, b=None):
        self.validate()
        if self.biasUsed and b is None:
            raise OpConfigError("biasUsed=True but no bias given")
        return OPS["conv3d"](x, w, b if self.biasUsed else None,
                             stride=(self.sD, self.sH, self.sW),
                             padding=self._padding((self.pD, self.pH, self.pW)))


@dataclass
class DeConv2DConfig(_Bean):
    """ref: DeConv2DConfig — transpose conv, IOHW kernel."""

    kH: int = 1
    kW: int = 1
    sH: int = 1
    sW: int = 1
    isSameMode: bool = True

    def validate(self):
        _positive(self, "kH", "kW", "sH", "sW")
        return self

    def execute(self, x, w):
        self.validate()
        return OPS["deconv2d"](x, w, stride=(self.sH, self.sW),
                               padding="SAME" if self.isSameMode else "VALID")


@dataclass
class DeConv3DConfig(_Bean):
    """ref: DeConv3DConfig — transpose conv, IODHW kernel."""

    kD: int = 1
    kH: int = 1
    kW: int = 1
    sD: int = 1
    sH: int = 1
    sW: int = 1
    isSameMode: bool = True

    def validate(self):
        _positive(self, "kD", "kH", "kW", "sD", "sH", "sW")
        return self

    def execute(self, x, w):
        self.validate()
        return OPS["deconv3d"](x, w, stride=(self.sD, self.sH, self.sW),
                               padding="SAME" if self.isSameMode else "VALID")


@dataclass
class Pooling2DConfig(_Bean):
    """ref: Pooling2DConfig (kH,kW,sH,sW,pH,pW, type MAX|AVG|PNORM,
    isSameMode, extra=pnorm p)."""

    kH: int = 2
    kW: int = 2
    sH: int = 2
    sW: int = 2
    pH: int = 0
    pW: int = 0
    type: str = "MAX"
    isSameMode: bool = False
    extra: float = 2.0

    _OPS = {"MAX": "max_pool2d", "AVG": "avg_pool2d", "PNORM": "pnormpool2d"}

    def validate(self):
        _positive(self, "kH", "kW", "sH", "sW")
        _non_negative(self, "pH", "pW")
        if self.type.upper() not in self._OPS:
            raise OpConfigError(f"pooling type {self.type!r} not in MAX|AVG|PNORM")
        return self

    def execute(self, x):
        self.validate()
        pad = ("SAME" if self.isSameMode
               else [(0, 0), (0, 0), (self.pH, self.pH), (self.pW, self.pW)])
        kw = dict(kernel=(self.kH, self.kW), stride=(self.sH, self.sW),
                  padding=pad)
        if self.type.upper() == "PNORM":
            kw["p"] = self.extra
        return OPS[self._OPS[self.type.upper()]](x, **kw)


@dataclass
class Pooling3DConfig(_Bean):
    """ref: Pooling3DConfig over NCDHW."""

    kD: int = 2
    kH: int = 2
    kW: int = 2
    sD: int = 2
    sH: int = 2
    sW: int = 2
    type: str = "MAX"
    isSameMode: bool = False

    def validate(self):
        _positive(self, "kD", "kH", "kW", "sD", "sH", "sW")
        if self.type.upper() not in ("MAX", "AVG"):
            raise OpConfigError(f"pooling type {self.type!r} not in MAX|AVG")
        return self

    def execute(self, x):
        self.validate()
        op = "max_pool3d" if self.type.upper() == "MAX" else "avg_pool3d"
        return OPS[op](x, kernel=(self.kD, self.kH, self.kW),
                       stride=(self.sD, self.sH, self.sW),
                       padding="SAME" if self.isSameMode else "VALID")


@dataclass
class LocalResponseNormalizationConfig(_Bean):
    """ref: LocalResponseNormalizationConfig (alpha, beta, bias, depth)."""

    alpha: float = 1e-4
    beta: float = 0.75
    bias: float = 1.0
    depth: int = 5

    def validate(self):
        _positive(self, "depth")
        return self

    def execute(self, x):
        self.validate()
        return OPS["lrn"](x, depth_radius=self.depth // 2, alpha=self.alpha,
                          beta=self.beta, bias=self.bias)


@dataclass
class LSTMConfiguration(_Bean):
    """ref: …impl.layers.recurrent.config.LSTMConfiguration (peepHole,
    forgetBias, clippingCellValue — the lstmBlockCell knobs)."""

    peepHole: bool = False
    forgetBias: float = 0.0
    clippingCellValue: float = 0.0  # 0 = no clipping, like the reference

    def validate(self):
        if self.clippingCellValue < 0:
            raise OpConfigError("clippingCellValue must be >= 0")
        return self

    def execute_cell(self, x, h_prev, c_prev, wx, wh, b,
                     wci=None, wcf=None, wco=None):
        self.validate()
        if self.peepHole and wci is None:
            raise OpConfigError("peepHole=True requires wci/wcf/wco")
        h, c = OPS["lstm_block_cell"](
            x, h_prev, c_prev, wx, wh, b,
            wci if self.peepHole else None,
            wcf if self.peepHole else None,
            wco if self.peepHole else None,
            forget_bias=self.forgetBias)
        if self.clippingCellValue > 0:
            c = OPS["clip_by_value"](c, -self.clippingCellValue,
                                     self.clippingCellValue)
        return h, c
