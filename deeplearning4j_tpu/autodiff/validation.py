"""Gradient-check + op-validation harness.

Reference: ``org.nd4j.autodiff.validation.OpValidation`` + ``TestCase`` +
``GradCheckUtil`` (SURVEY §4.2): per-op forward check vs reference, central-
difference numerical gradient check, serialization round-trip, and per-op
coverage tracking that FAILS when an op has no validation (§4.6 #2).
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence, Set

import jax.numpy as jnp
import numpy as np

from .ops_registry import OPS
from .samediff import SameDiff


def check_gradients(sd: SameDiff, placeholders: Dict[str, np.ndarray],
                    wrt: Sequence[str], eps: float = 1e-4,
                    max_rel_error: float = 1e-3, abs_error: float = 1e-5) -> bool:
    """Central-difference gradient check (GradCheckUtil.checkGradients):
    perturb every element of every wrt variable, compare numeric vs analytic.
    Run in float64-sized eps on small graphs only."""
    analytic = sd.calculate_gradients(placeholders, wrt)
    for name in wrt:
        base = np.asarray(sd.arrays[name], np.float64)
        an = np.asarray(analytic[name], np.float64)
        num = np.zeros_like(base)
        flat = base.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            for sign in (+1, -1):
                flat[i] = orig + sign * eps
                sd.arrays[name] = jnp.asarray(base.reshape(base.shape), jnp.float32)
                outs = sd.output(placeholders, sd.loss_names)
                val = sum(float(np.sum(np.asarray(v))) for v in outs.values())
                if sign > 0:
                    plus = val
                else:
                    minus = val
            num.reshape(-1)[i] = (plus - minus) / (2 * eps)
            flat[i] = orig
        sd.arrays[name] = jnp.asarray(base, jnp.float32)
        denom = np.maximum(np.abs(an) + np.abs(num), 1e-8)
        rel = np.abs(an - num) / denom
        bad = (rel > max_rel_error) & (np.abs(an - num) > abs_error)
        if np.any(bad):
            idx = np.argwhere(bad)[0]
            raise AssertionError(
                f"gradient check failed for '{name}' at {tuple(idx)}: "
                f"analytic={an[tuple(idx)]:.6g} numeric={num[tuple(idx)]:.6g}")
    return True


class OpValidation:
    """Coverage tracker: ops exercised through validated TestCases vs the
    full registry. ``assert_coverage`` fails if a listed op has no test —
    the reference's build-failing coverage gate."""

    _validated: Set[str] = set()

    @classmethod
    def record(cls, op_name: str):
        cls._validated.add(op_name)

    @classmethod
    def validated(cls) -> Set[str]:
        return set(cls._validated)

    @classmethod
    def coverage(cls) -> float:
        return len(cls._validated & set(OPS)) / max(len(OPS), 1)

    @classmethod
    def assert_coverage(cls, required: Iterable[str]):
        missing = set(required) - cls._validated
        if missing:
            raise AssertionError(f"ops without validation: {sorted(missing)}")


def validate_op(op_name: str, args, kwargs=None, expected=None, rtol=1e-5, atol=1e-6):
    """Forward-check one op against an expected numpy result and record
    coverage (TestCase.expectedOutput equivalent)."""
    fn = OPS[op_name]
    out = fn(*[jnp.asarray(a) for a in args], **(kwargs or {}))
    if expected is not None:
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=rtol, atol=atol)
    OpValidation.record(op_name)
    return out
