"""Gradient-check + op-validation harness.

Reference: ``org.nd4j.autodiff.validation.OpValidation`` + ``TestCase`` +
``GradCheckUtil`` (SURVEY §4.2): per-op forward check vs reference, central-
difference numerical gradient check, serialization round-trip, and per-op
coverage tracking that FAILS when an op has no validation (§4.6 #2).
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence, Set

import jax.numpy as jnp
import numpy as np

from .ops_registry import OPS
from .samediff import SameDiff


def check_gradients(sd: SameDiff, placeholders: Dict[str, np.ndarray],
                    wrt: Sequence[str], eps: float = 1e-4,
                    max_rel_error: float = 1e-3, abs_error: float = 1e-5) -> bool:
    """Central-difference gradient check (GradCheckUtil.checkGradients):
    perturb every element of every wrt variable, compare numeric vs analytic.
    Run in float64-sized eps on small graphs only."""
    analytic = sd.calculate_gradients(placeholders, wrt)
    for name in wrt:
        base = np.asarray(sd.arrays[name], np.float64)
        an = np.asarray(analytic[name], np.float64)
        num = np.zeros_like(base)
        flat = base.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            for sign in (+1, -1):
                flat[i] = orig + sign * eps
                sd.arrays[name] = jnp.asarray(base.reshape(base.shape), jnp.float32)
                outs = sd.output(placeholders, sd.loss_names)
                val = sum(float(np.sum(np.asarray(v))) for v in outs.values())
                if sign > 0:
                    plus = val
                else:
                    minus = val
            num.reshape(-1)[i] = (plus - minus) / (2 * eps)
            flat[i] = orig
        sd.arrays[name] = jnp.asarray(base, jnp.float32)
        denom = np.maximum(np.abs(an) + np.abs(num), 1e-8)
        rel = np.abs(an - num) / denom
        bad = (rel > max_rel_error) & (np.abs(an - num) > abs_error)
        if np.any(bad):
            idx = np.argwhere(bad)[0]
            raise AssertionError(
                f"gradient check failed for '{name}' at {tuple(idx)}: "
                f"analytic={an[tuple(idx)]:.6g} numeric={num[tuple(idx)]:.6g}")
    return True


class OpValidation:
    """Coverage tracker: ops exercised through validated TestCases vs the
    full registry. ``assert_coverage`` fails if a listed op has no test —
    the reference's build-failing coverage gate."""

    _validated: Set[str] = set()

    @classmethod
    def record(cls, op_name: str):
        cls._validated.add(op_name)

    @classmethod
    def validated(cls) -> Set[str]:
        return set(cls._validated)

    @classmethod
    def coverage(cls) -> float:
        return len(cls._validated & set(OPS)) / max(len(OPS), 1)

    @classmethod
    def assert_coverage(cls, required: Iterable[str]):
        missing = set(required) - cls._validated
        if missing:
            raise AssertionError(f"ops without validation: {sorted(missing)}")


def validate_op(op_name: str, args, kwargs=None, expected=None, rtol=1e-5, atol=1e-6):
    """Forward-check one op against an expected numpy result and record
    coverage (TestCase.expectedOutput equivalent)."""
    fn = OPS[op_name]
    out = fn(*[jnp.asarray(a) for a in args], **(kwargs or {}))
    if expected is not None:
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=rtol, atol=atol)
    OpValidation.record(op_name)
    return out


def _float_sum(out) -> float:
    import jax

    total = 0.0
    for leaf in jax.tree.leaves(out):
        a = np.asarray(leaf)
        if np.issubdtype(a.dtype, np.floating):
            total += float(np.sum(a))
    return total


def check_op_gradients(op_name: str, args, kwargs=None, diff_args: Sequence[int] = (0,),
                       eps: float = 1e-3, rtol: float = 3e-2, atol: float = 3e-3):
    """GradCheckUtil analog applied directly to a registry op: analytic
    jax.grad of sum(float outputs) vs central differences, per diff arg.
    float32 + eps=1e-3 → tolerances are correspondingly loose; callers pick
    well-conditioned inputs (away from kinks/branch points)."""
    import jax

    fn = OPS[op_name]
    kwargs = kwargs or {}
    # only real arrays become traced values; python ints/floats stay static
    # (axis numbers, scale factors — jnp.swapaxes etc. require hashables)
    jargs = [jnp.asarray(a) if isinstance(a, np.ndarray) else a for a in args]

    def loss(*diff_vals):
        full = list(jargs)
        for di, v in zip(diff_args, diff_vals):
            full[di] = v
        out = fn(*full, **kwargs)
        leaves = [l for l in jax.tree.leaves(out)
                  if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.floating)]
        return sum(jnp.sum(l) for l in leaves)

    def loss_with(ai, arr) -> float:
        full = list(jargs)
        full[ai] = jnp.asarray(arr, jnp.float32)
        return _float_sum(fn(*full, **kwargs))

    analytic = jax.grad(loss, argnums=tuple(range(len(diff_args))))(
        *[jargs[i] for i in diff_args])
    for k, ai in enumerate(diff_args):
        # order='C' matters: np.array(..., order='K') keeps a non-contiguous
        # source layout (e.g. stack of transposes) and reshape(-1) would then
        # COPY, silently disconnecting the perturbation from the array
        base = np.array(args[ai], np.float64, order="C")
        an = np.asarray(analytic[k], np.float64)
        num = np.zeros_like(base)
        for i in range(base.size):
            idx = np.unravel_index(i, base.shape) if base.shape else ()
            orig = base[idx]
            base[idx] = orig + eps
            plus = loss_with(ai, base)
            base[idx] = orig - eps
            minus = loss_with(ai, base)
            base[idx] = orig
            num[idx] = (plus - minus) / (2 * eps)
        denom = np.maximum(np.abs(an) + np.abs(num), 1e-6)
        bad = (np.abs(an - num) / denom > rtol) & (np.abs(an - num) > atol)
        if np.any(bad):
            idx = tuple(np.argwhere(bad)[0])
            raise AssertionError(
                f"grad check failed for op '{op_name}' arg {ai} at {idx}: "
                f"analytic={an[idx]:.6g} numeric={num[idx]:.6g}")
    return True
