"""Op namespaces: sd.math()/nn()/cnn()/rnn()/loss()/linalg().

Reference: generated ``org.nd4j.autodiff.samediff.ops.{SDMath, SDNN, SDCNN,
SDRNN, SDLoss, SDLinalg}`` (SURVEY §2.2 J11; §2.8 codegen-tools note — the
reference generates these from an op DSL, which is why they look mechanical;
here they are thin typed veneers over the ops registry).
"""

from __future__ import annotations

from typing import Optional

from .samediff import SameDiff, SDVariable


class _NS:
    def __init__(self, sd: SameDiff):
        self.sd = sd

    def _o(self, op, *xs, name=None, n_outputs=1, **kw):
        return self.sd.op(op, *xs, name=name, n_outputs=n_outputs, **kw)


class SDMath(_NS):
    def abs(self, x, name=None):
        return self._o("abs", x, name=name)

    def exp(self, x, name=None):
        return self._o("exp", x, name=name)

    def log(self, x, name=None):
        return self._o("log", x, name=name)

    def sqrt(self, x, name=None):
        return self._o("sqrt", x, name=name)

    def square(self, x, name=None):
        return self._o("square", x, name=name)

    def pow(self, x, p, name=None):
        return self._o("pow", x, p, name=name)

    def tanh(self, x, name=None):
        return self._o("tanh", x, name=name)

    def sin(self, x, name=None):
        return self._o("sin", x, name=name)

    def cos(self, x, name=None):
        return self._o("cos", x, name=name)

    def erf(self, x, name=None):
        return self._o("erf", x, name=name)

    def max(self, a, b, name=None):
        return self._o("maximum", a, b, name=name)

    def min(self, a, b, name=None):
        return self._o("minimum", a, b, name=name)

    def neg(self, x, name=None):
        return self._o("neg", x, name=name)

    def clip_by_value(self, x, lo, hi, name=None):
        return self._o("clip_by_value", x, name=name, clip_min=lo, clip_max=hi)

    def cumsum(self, x, axis=0, name=None):
        return self._o("cumsum", x, name=name, axis=axis)

    def is_nan(self, x, name=None):
        return self._o("isnan", x, name=name)

    def argmax(self, x, dim=None, name=None):
        return self._o("argmax", x, name=name, dims=dim)

    def mean(self, x, *dims, name=None):
        return self._o("reduce_mean", x, name=name, dims=list(dims) or None)

    def sum(self, x, *dims, name=None):
        return self._o("reduce_sum", x, name=name, dims=list(dims) or None)


class SDNN(_NS):
    def relu(self, x, name=None):
        return self._o("relu", x, name=name)

    def relu6(self, x, name=None):
        return self._o("relu6", x, name=name)

    def gelu(self, x, name=None):
        return self._o("gelu", x, name=name)

    def elu(self, x, name=None):
        return self._o("elu", x, name=name)

    def selu(self, x, name=None):
        return self._o("selu", x, name=name)

    def swish(self, x, name=None):
        return self._o("swish", x, name=name)

    def sigmoid(self, x, name=None):
        return self._o("sigmoid", x, name=name)

    def softplus(self, x, name=None):
        return self._o("softplus", x, name=name)

    def softmax(self, x, axis=-1, name=None):
        return self._o("softmax", x, name=name, axis=axis)

    def log_softmax(self, x, axis=-1, name=None):
        return self._o("log_softmax", x, name=name, axis=axis)

    def leaky_relu(self, x, alpha=0.01, name=None):
        return self._o("leaky_relu", x, name=name, alpha=alpha)

    def linear(self, x, w, b=None, name=None):
        args = (x, w) if b is None else (x, w, b)
        return self._o("linear", *args, name=name)

    def layer_norm(self, x, gain, bias=None, name=None):
        args = (x, gain) if bias is None else (x, gain, bias)
        return self._o("layer_norm", *args, name=name)

    def batch_norm(self, x, mean, var, gamma, beta, eps=1e-5, axis=1, name=None):
        return self._o("batch_norm", x, mean, var, gamma, beta, name=name, eps=eps, axis=axis)

    def dropout(self, x, rng, keep_prob=0.5, name=None):
        return self._o("dropout", x, rng, name=name, keep_prob=keep_prob)

    def embedding_lookup(self, table, ids, name=None):
        return self._o("embedding_lookup", table, ids, name=name)

    def dot_product_attention(self, q, k, v, mask=None, name=None):
        args = (q, k, v) if mask is None else (q, k, v, mask)
        return self._o("dot_product_attention", *args, name=name)

    def multi_head_dot_product_attention(self, q, k, v, wq, wk, wv, wo, n_heads, name=None):
        return self._o("multi_head_dot_product_attention", q, k, v, wq, wk, wv, wo,
                       name=name, n_heads=n_heads)


class SDCNN(_NS):
    def conv2d(self, x, w, b=None, stride=(1, 1), padding="SAME", dilation=(1, 1), name=None):
        args = (x, w) if b is None else (x, w, b)
        return self._o("conv2d", *args, name=name, stride=tuple(stride),
                       padding=padding, dilation=tuple(dilation))

    def max_pooling2d(self, x, kernel=(2, 2), stride=(2, 2), padding="VALID", name=None):
        return self._o("max_pool2d", x, name=name, kernel=tuple(kernel),
                       stride=tuple(stride), padding=padding)

    def avg_pooling2d(self, x, kernel=(2, 2), stride=(2, 2), padding="VALID", name=None):
        return self._o("avg_pool2d", x, name=name, kernel=tuple(kernel),
                       stride=tuple(stride), padding=padding)


class SDRNN(_NS):
    def lstm_layer(self, x_tnd, h0, c0, wx, wh, b, name=None):
        return self._o("lstm_layer", x_tnd, h0, c0, wx, wh, b, name=name, n_outputs=3)

    def gru(self, x_tnd, h0, wx, wh, b, name=None):
        return self._o("gru", x_tnd, h0, wx, wh, b, name=name, n_outputs=2)


class SDLoss(_NS):
    def softmax_cross_entropy(self, labels, logits, weights=None, name=None):
        args = (labels, logits) if weights is None else (labels, logits, weights)
        return self._o("softmax_cross_entropy", *args, name=name)

    def sparse_softmax_cross_entropy(self, labels, logits, name=None):
        return self._o("sparse_softmax_cross_entropy", labels, logits, name=name)

    def sigmoid_cross_entropy(self, labels, logits, name=None):
        return self._o("sigmoid_cross_entropy", labels, logits, name=name)

    def mean_squared_error(self, labels, preds, name=None):
        return self._o("mean_squared_error", labels, preds, name=name)

    def absolute_difference(self, labels, preds, name=None):
        return self._o("mean_absolute_error", labels, preds, name=name)

    def huber_loss(self, labels, preds, delta=1.0, name=None):
        return self._o("huber_loss", labels, preds, name=name, delta=delta)

    def log_loss(self, labels, preds, name=None):
        return self._o("log_loss", labels, preds, name=name)


class SDLinalg(_NS):
    def mmul(self, a, b, transpose_a=False, transpose_b=False, name=None):
        return self._o("matmul", a, b, name=name, transpose_a=transpose_a,
                       transpose_b=transpose_b)

    def tensormmul(self, a, b, axes_a, axes_b, name=None):
        return self._o("tensormmul", a, b, name=name, axes_a=list(axes_a), axes_b=list(axes_b))

    def cholesky(self, x, name=None):
        return self._o("cholesky", x, name=name)

    def inverse(self, x, name=None):
        return self._o("matrix_inverse", x, name=name)

    def solve(self, a, b, name=None):
        return self._o("solve", a, b, name=name)
