"""Op corpus wave 3 — closes the named N6 gaps (VERDICT r3 missing #2).

Reference analog: ``libnd4j/include/ops/declarable/generic/**`` (SURVEY §2.1
N6): the CTC family, fused/peephole recurrent units, the unsorted_segment_*
family, TF-compat image/space-batch ops, LU/expm linalg tail, and the
skipgram/cbow training ops that the reference exposes as declarable ops
(``generic/nlp/sg_cb.cpp``). Everything is a jax-traceable callable except
the beam-search decoder (host-side by design, like the reference's CPU
helper). Registered into the same ``OPS`` table; the build-failing coverage
gate in tests/test_op_validation.py applies to every name added here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .ops_registry import OPS, op

_NEG = -1e30


# ------------------------------------------------------------------ ctc family


@op("ctc_loss")
def _ctc_loss(labels, logits, label_lengths, logit_lengths, blank=0,
              reduction="mean"):
    """CTC negative log-likelihood.

    labels [B,S] int32, logits [B,T,C] raw scores, lengths [B].
    Log-space alpha recursion as one ``lax.scan`` over time (ref:
    generic/loss/ctcLoss.cpp); fully differentiable w.r.t. logits.
    ``reduction``: 'mean' (batch mean, the DL4J loss-layer contract) or
    'none' for the per-example [B] vector TF ctc_loss returns (ADVICE r4:
    per-example weighting callers need the vector).
    """
    labels = jnp.asarray(labels, jnp.int32)
    logits = jnp.asarray(logits)
    label_lengths = jnp.asarray(label_lengths, jnp.int32)
    logit_lengths = jnp.asarray(logit_lengths, jnp.int32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    B, T, C = logp.shape
    S = labels.shape[1]
    L = 2 * S + 1
    ext = jnp.full((B, L), blank, jnp.int32).at[:, 1::2].set(labels)
    pos = jnp.arange(L)
    # skip transition s-2 -> s allowed where ext[s] != blank and != ext[s-2]
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :L]
    can_skip = (ext != blank) & (ext != ext_m2) & (pos >= 2)

    emit = jnp.take_along_axis(logp[:, :, :], ext[:, None, :], axis=2)  # [B,T,L]

    a0 = jnp.full((B, L), _NEG)
    a0 = a0.at[:, 0].set(emit[:, 0, 0])
    a0 = a0.at[:, 1].set(emit[:, 0, 1])

    def shift(x, k):
        return jnp.pad(x, ((0, 0), (k, 0)), constant_values=_NEG)[:, :L]

    def body(alpha, t):
        stay = alpha
        step1 = shift(alpha, 1)
        step2 = jnp.where(can_skip, shift(alpha, 2), _NEG)
        na = jnp.logaddexp(jnp.logaddexp(stay, step1), step2) + emit[:, t, :]
        na = jnp.where((t < logit_lengths)[:, None], na, alpha)
        return na, None

    alpha, _ = lax.scan(body, a0, jnp.arange(1, T))
    end = 2 * label_lengths  # final blank position
    a_end = jnp.take_along_axis(alpha, end[:, None], axis=1)[:, 0]
    a_last = jnp.take_along_axis(alpha, jnp.maximum(end - 1, 0)[:, None], axis=1)[:, 0]
    ll = jnp.logaddexp(a_end, jnp.where(label_lengths > 0, a_last, _NEG))
    if reduction == "none":
        return -ll
    return -jnp.mean(ll)


@op("ctc_greedy_decoder")
def _ctc_greedy_decoder(logits, logit_lengths=None, blank=0):
    """Best-path decode: frame argmax, collapse repeats, drop blanks.

    Returns (decoded [B,T] padded with -1, lengths [B]). Static shapes —
    decoded is right-padded so the op stays jittable.
    """
    logits = jnp.asarray(logits)
    B, T, C = logits.shape
    path = jnp.argmax(logits, axis=-1)  # [B,T]
    if logit_lengths is not None:
        t_idx = jnp.arange(T)[None, :]
        path = jnp.where(t_idx < jnp.asarray(logit_lengths, jnp.int32)[:, None], path, blank)
    prev = jnp.pad(path, ((0, 0), (1, 0)), constant_values=-1)[:, :T]
    keep = (path != blank) & (path != prev)

    def compact(row_path, row_keep):
        idx = jnp.cumsum(row_keep) - 1
        out = jnp.full((T,), -1, path.dtype)
        out = out.at[jnp.where(row_keep, idx, T)].set(row_path, mode="drop")
        return out

    decoded = jax.vmap(compact)(path, keep)
    return decoded, jnp.sum(keep, axis=1)


@op("ctc_beam_search_decoder")
def _ctc_beam_search_decoder(logits, beam_width=8, blank=0, top_paths=1):
    """Prefix beam search (no LM). Host-side numpy by design — dynamic
    prefix sets don't map to static shapes (the reference's decoder is a
    CPU helper too). Returns list of (sequence tuple, log_prob) per batch."""
    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), axis=-1))
    B, T, C = logp.shape
    results = []
    for b in range(B):
        # beams: prefix -> (log p ending in blank, log p ending in non-blank)
        beams = {(): (0.0, _NEG)}
        for t in range(T):
            new = {}

            def add(pref, pb, pnb):
                opb, opnb = new.get(pref, (_NEG, _NEG))
                new[pref] = (np.logaddexp(opb, pb), np.logaddexp(opnb, pnb))

            for pref, (pb, pnb) in beams.items():
                total = np.logaddexp(pb, pnb)
                add(pref, total + logp[b, t, blank], _NEG)  # extend with blank
                for c in range(C):
                    if c == blank:
                        continue
                    p_c = logp[b, t, c]
                    if pref and pref[-1] == c:
                        add(pref, _NEG, pnb + p_c)         # repeat emission merges
                        add(pref + (c,), _NEG, pb + p_c)   # new symbol needs blank gap
                    else:
                        add(pref + (c,), _NEG, total + p_c)
            beams = dict(sorted(new.items(), key=lambda kv: -np.logaddexp(*kv[1]))[:beam_width])
        ranked = sorted(((pref, float(np.logaddexp(pb, pnb)))
                         for pref, (pb, pnb) in beams.items()), key=lambda kv: -kv[1])
        results.append(ranked[:top_paths])
    return results


# ------------------------------------------------------- fused recurrent units


def _sigmoid(x):
    return jax.nn.sigmoid(x)


@op("lstm_cell")
def _lstm_cell(x, h_prev, c_prev, wx, wh, b):
    """One LSTM step, gates fused in one [.,4H] GEMM (i,f,g,o order)."""
    H = h_prev.shape[-1]
    z = x @ wx + h_prev @ wh + b
    i, f, g, o = z[..., :H], z[..., H:2 * H], z[..., 2 * H:3 * H], z[..., 3 * H:]
    c = _sigmoid(f) * c_prev + _sigmoid(i) * jnp.tanh(g)
    h = _sigmoid(o) * jnp.tanh(c)
    return h, c


@op("lstm_block")
def _lstm_block(x, h0, c0, wx, wh, b, wci=None, wcf=None, wco=None):
    """Full-sequence LSTM with optional peepholes (ref: lstmBlock /
    lstmBlockCell, generic/nn/recurrent/lstmBlock.cpp). x [T,B,I]; one
    ``lax.scan`` over time — per-step gates are a single fused GEMM on the
    MXU. Returns (ys [T,B,H], h_T, c_T)."""
    H = h0.shape[-1]
    use_peep = wci is not None

    def step(carry, xt):
        h, c = carry
        z = xt @ wx + h @ wh + b
        i, f, g, o = z[..., :H], z[..., H:2 * H], z[..., 2 * H:3 * H], z[..., 3 * H:]
        if use_peep:
            i = i + c * wci
            f = f + c * wcf
        cn = _sigmoid(f) * c + _sigmoid(i) * jnp.tanh(g)
        if use_peep:
            o = o + cn * wco
        hn = _sigmoid(o) * jnp.tanh(cn)
        return (hn, cn), hn

    (hT, cT), ys = lax.scan(step, (h0, c0), x)
    return ys, hT, cT


@op("sru")
def _sru(x, c0, w, wf, wr, bf, br):
    """Simple Recurrent Unit (ref: generic/nn/recurrent/sru.cpp; Lei et al.
    2017). The heavy lifting (all three projections) is time-parallel — the
    scan carries only the cheap elementwise recurrence, the TPU-native way
    to run this cell. x [T,B,I] -> (h [T,B,H], c_T)."""
    xt = x @ w        # [T,B,H]
    f = _sigmoid(x @ wf + bf)
    r = _sigmoid(x @ wr + br)

    def step(c, tfr):
        xt_t, f_t, r_t = tfr
        cn = f_t * c + (1.0 - f_t) * xt_t
        h = r_t * jnp.tanh(cn) + (1.0 - r_t) * xt_t
        return cn, h

    cT, h = lax.scan(step, c0, (xt, f, r))
    return h, cT


@op("sru_cell")
def _sru_cell(x, c_prev, w, wf, wr, bf, br):
    xt = x @ w
    f = _sigmoid(x @ wf + bf)
    r = _sigmoid(x @ wr + br)
    c = f * c_prev + (1.0 - f) * xt
    h = r * jnp.tanh(c) + (1.0 - r) * xt
    return h, c


@op("gru_cell")
def _gru_cell(x, h_prev, wx, wh, b):
    """One GRU step (r,u,n gate order, matching the sequence 'gru' op)."""
    H = h_prev.shape[-1]
    xz = x @ wx + b
    hz = h_prev @ wh
    r = _sigmoid(xz[..., :H] + hz[..., :H])
    u = _sigmoid(xz[..., H:2 * H] + hz[..., H:2 * H])
    n = jnp.tanh(xz[..., 2 * H:] + r * hz[..., 2 * H:])
    return (1.0 - u) * n + u * h_prev


# ------------------------------------------------------ unsorted segment family


def _useg(reducer, init, x, ids, num_segments):
    ids = jnp.asarray(ids, jnp.int32)
    out = jnp.full((num_segments,) + x.shape[1:], init, x.dtype)
    return reducer(out, ids, x)


def _dtype_extreme(dtype, lowest):
    """TF parity: empty segments get the dtype's lowest/highest value —
    works for int dtypes too (ADVICE r4: jnp.full(±inf) raises on ints)."""
    dtype = jnp.dtype(dtype)
    if jnp.issubdtype(dtype, jnp.integer):
        info = jnp.iinfo(dtype)
        return info.min if lowest else info.max
    return -jnp.inf if lowest else jnp.inf


@op("unsorted_segment_max")
def _unsorted_segment_max(x, ids, num_segments):
    x = jnp.asarray(x)
    return _useg(lambda o, i, v: o.at[i].max(v, mode="drop"),
                 _dtype_extreme(x.dtype, lowest=True), x, ids, num_segments)


@op("unsorted_segment_min")
def _unsorted_segment_min(x, ids, num_segments):
    x = jnp.asarray(x)
    return _useg(lambda o, i, v: o.at[i].min(v, mode="drop"),
                 _dtype_extreme(x.dtype, lowest=False), x, ids, num_segments)


@op("unsorted_segment_prod")
def _unsorted_segment_prod(x, ids, num_segments):
    return _useg(lambda o, i, v: o.at[i].multiply(v, mode="drop"), 1, x, ids, num_segments)


@op("unsorted_segment_mean")
def _unsorted_segment_mean(x, ids, num_segments):
    ids = jnp.asarray(ids, jnp.int32)
    s = jnp.zeros((num_segments,) + x.shape[1:], x.dtype).at[ids].add(x, mode="drop")
    n = jnp.zeros((num_segments,), x.dtype).at[ids].add(1.0, mode="drop")
    return s / jnp.maximum(n, 1).reshape((-1,) + (1,) * (x.ndim - 1))


@op("unsorted_segment_sqrt_n")
def _unsorted_segment_sqrt_n(x, ids, num_segments):
    ids = jnp.asarray(ids, jnp.int32)
    s = jnp.zeros((num_segments,) + x.shape[1:], x.dtype).at[ids].add(x, mode="drop")
    n = jnp.zeros((num_segments,), x.dtype).at[ids].add(1.0, mode="drop")
    return s / jnp.sqrt(jnp.maximum(n, 1)).reshape((-1,) + (1,) * (x.ndim - 1))


# ------------------------------------------------------------- image/space ops


@op("extract_image_patches")
def _extract_image_patches(x, ksizes, strides=(1, 1), rates=(1, 1), padding="VALID"):
    """TF-compat patch extraction. x [B,H,W,C] -> [B,OH,OW,KH*KW*C]."""
    kh, kw = ksizes
    x_nchw = jnp.transpose(x, (0, 3, 1, 2))
    patches = lax.conv_general_dilated_patches(
        x_nchw, (kh, kw), strides, padding, rhs_dilation=rates)
    # [B, C*KH*KW, OH, OW] with C slowest — reorder to TF's KH,KW,C fastest-C
    B, _, OH, OW = patches.shape
    C = x.shape[3]
    p = patches.reshape(B, C, kh * kw, OH, OW)
    p = jnp.transpose(p, (0, 3, 4, 2, 1))  # [B,OH,OW,KH*KW,C]
    return p.reshape(B, OH, OW, kh * kw * C)


@op("im2col")
def _im2col(x, kernel=(3, 3), strides=(1, 1), padding="SAME", dilation=(1, 1)):
    """NCHW im2col: [B,C,H,W] -> [B, C*KH*KW, OH, OW] (ref: helpers/im2col)."""
    return lax.conv_general_dilated_patches(x, tuple(kernel), tuple(strides),
                                            padding, rhs_dilation=tuple(dilation))


@op("col2im")
def _col2im(cols, input_shape, kernel=(3, 3), strides=(1, 1), padding="SAME", dilation=(1, 1)):
    """Adjoint of im2col (scatter-add of patches) — derived as the exact VJP
    of the im2col lowering rather than re-implementing the index arithmetic."""
    primal = jnp.zeros(input_shape, cols.dtype)
    _, vjp = jax.vjp(lambda x: _im2col(x, kernel, strides, padding, dilation), primal)
    return vjp(cols)[0]


@op("space_to_batch_nd")
def _space_to_batch_nd(x, block_shape, paddings):
    """TF SpaceToBatchND: x [B, S1..Sn, ...] with n spatial dims."""
    block_shape = list(block_shape)
    n = len(block_shape)
    pads = [(0, 0)] + [tuple(p) for p in paddings] + [(0, 0)] * (x.ndim - 1 - n)
    x = jnp.pad(x, pads)
    B = x.shape[0]
    rest = list(x.shape[1 + n:])
    outer = [x.shape[1 + i] // block_shape[i] for i in range(n)]
    shape = [B]
    for i in range(n):
        shape += [outer[i], block_shape[i]]
    x = x.reshape(shape + rest)
    # blocks in front of batch: [b1..bn, B, S1/b1..Sn/bn, rest]
    perm = [2 + 2 * i for i in range(n)] + [0] + [1 + 2 * i for i in range(n)]
    perm += list(range(1 + 2 * n, x.ndim))
    x = jnp.transpose(x, perm)
    return x.reshape([B * int(np.prod(block_shape))] + outer + rest)


@op("batch_to_space_nd")
def _batch_to_space_nd(x, block_shape, crops):
    block_shape = list(block_shape)
    n = len(block_shape)
    prod = int(np.prod(block_shape))
    B = x.shape[0] // prod
    spatial = list(x.shape[1 : 1 + n])
    rest = list(x.shape[1 + n:])
    x = x.reshape(block_shape + [B] + spatial + rest)
    # interleave: [B, S1, b1, S2, b2, ...]
    perm = [n]
    for i in range(n):
        perm += [n + 1 + i, i]
    perm += list(range(1 + 2 * n, x.ndim))
    x = jnp.transpose(x, perm)
    x = x.reshape([B] + [s * b for s, b in zip(spatial, block_shape)] + rest)
    for i in range(n):
        lo, hi = crops[i]
        size = x.shape[1 + i] - lo - hi
        x = lax.slice_in_dim(x, lo, lo + size, axis=1 + i)
    return x


@op("space_to_batch")
def _space_to_batch(x, block_size, paddings=((0, 0), (0, 0))):
    """2D special case, NHWC (ref: generic/parity_ops/space_to_batch.cpp)."""
    return _space_to_batch_nd(x, (block_size, block_size), paddings)


@op("batch_to_space")
def _batch_to_space(x, block_size, crops=((0, 0), (0, 0))):
    return _batch_to_space_nd(x, (block_size, block_size), crops)


@op("resize_bicubic")
def _resize_bicubic(x, size):
    """NCHW bicubic resize via jax.image (keys-cubic kernel)."""
    B, C, H, W = x.shape
    return jax.image.resize(x, (B, C, size[0], size[1]), method="cubic")


@op("resize_area")
def _resize_area(x, size):
    """Area (box-average) resize. Integer downscale = exact mean pooling;
    otherwise antialiased linear (documented approximation)."""
    B, C, H, W = x.shape
    oh, ow = size
    if H % oh == 0 and W % ow == 0:
        fh, fw = H // oh, W // ow
        return x.reshape(B, C, oh, fh, ow, fw).mean(axis=(3, 5))
    return jax.image.resize(x, (B, C, oh, ow), method="linear", antialias=True)


@op("crop_and_resize")
def _crop_and_resize(image, boxes, box_indices, crop_size):
    """TF CropAndResize, bilinear. image [B,H,W,C], boxes [N,4] normalized
    (y1,x1,y2,x2), box_indices [N] -> [N,ch,cw,C]."""
    image = jnp.asarray(image)
    boxes = jnp.asarray(boxes)
    box_indices = jnp.asarray(box_indices, jnp.int32)
    H, W = image.shape[1], image.shape[2]
    ch, cw = crop_size

    def one(box, bi):
        y1, x1, y2, x2 = box
        ys = y1 * (H - 1) + (jnp.arange(ch) / jnp.maximum(ch - 1, 1)) * (y2 - y1) * (H - 1)
        xs = x1 * (W - 1) + (jnp.arange(cw) / jnp.maximum(cw - 1, 1)) * (x2 - x1) * (W - 1)
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, H - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, W - 1)
        y1i = jnp.clip(y0 + 1, 0, H - 1)
        x1i = jnp.clip(x0 + 1, 0, W - 1)
        wy = (ys - y0).clip(0, 1)[:, None, None]
        wx = (xs - x0).clip(0, 1)[None, :, None]
        img = image[bi]
        a = img[y0][:, x0] * (1 - wy) * (1 - wx)
        b = img[y0][:, x1i] * (1 - wy) * wx
        c = img[y1i][:, x0] * wy * (1 - wx)
        d = img[y1i][:, x1i] * wy * wx
        return a + b + c + d

    return jax.vmap(one)(boxes, box_indices)


def _rgb_hsv_fwd(r, g, b):
    mx = jnp.maximum(jnp.maximum(r, g), b)
    mn = jnp.minimum(jnp.minimum(r, g), b)
    d = mx - mn
    safe = jnp.where(d == 0, 1.0, d)
    h = jnp.where(
        mx == r, (g - b) / safe % 6.0,
        jnp.where(mx == g, (b - r) / safe + 2.0, (r - g) / safe + 4.0)) / 6.0
    h = jnp.where(d == 0, 0.0, h)
    s = jnp.where(mx == 0, 0.0, d / jnp.where(mx == 0, 1.0, mx))
    return h, s, mx


@op("rgb_to_hsv")
def _rgb_to_hsv(x):
    """Channels-last [...,3] in [0,1] (ref: generic/images/rgb_to_hsv)."""
    h, s, v = _rgb_hsv_fwd(x[..., 0], x[..., 1], x[..., 2])
    return jnp.stack([h, s, v], axis=-1)


@op("hsv_to_rgb")
def _hsv_to_rgb(x):
    h, s, v = x[..., 0] * 6.0, x[..., 1], x[..., 2]
    i = jnp.floor(h)
    f = h - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(jnp.int32) % 6
    r = jnp.choose(i, [v, q, p, p, t, v], mode="clip")
    g = jnp.choose(i, [t, v, v, q, p, p], mode="clip")
    b = jnp.choose(i, [p, p, t, v, v, q], mode="clip")
    return jnp.stack([r, g, b], axis=-1)


@op("rgb_to_grs")
def _rgb_to_grs(x):
    """ITU-R BT.601 luma, channels-last [...,3] -> [...,1]."""
    w = jnp.asarray([0.299, 0.587, 0.114], x.dtype)
    return jnp.sum(x * w, axis=-1, keepdims=True)


@op("adjust_hue")
def _adjust_hue(x, delta):
    hsv = _rgb_to_hsv(x)
    h = (hsv[..., 0] + delta) % 1.0
    return _hsv_to_rgb(jnp.stack([h, hsv[..., 1], hsv[..., 2]], axis=-1))


@op("adjust_saturation")
def _adjust_saturation(x, factor):
    hsv = _rgb_to_hsv(x)
    s = jnp.clip(hsv[..., 1] * factor, 0.0, 1.0)
    return _hsv_to_rgb(jnp.stack([hsv[..., 0], s, hsv[..., 2]], axis=-1))


@op("non_max_suppression")
def _non_max_suppression(boxes, scores, max_output_size, iou_threshold=0.5):
    """Greedy NMS. boxes [N,4] (y1,x1,y2,x2) -> (indices [max_output_size]
    padded with -1, valid count). Static shapes (lax.fori_loop selection)."""
    boxes = jnp.asarray(boxes)
    scores = jnp.asarray(scores)
    N = boxes.shape[0]
    area = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0) * jnp.maximum(boxes[:, 3] - boxes[:, 1], 0)

    def iou(i, mask):
        b = boxes[i]
        yy1 = jnp.maximum(b[0], boxes[:, 0])
        xx1 = jnp.maximum(b[1], boxes[:, 1])
        yy2 = jnp.minimum(b[2], boxes[:, 2])
        xx2 = jnp.minimum(b[3], boxes[:, 3])
        inter = jnp.maximum(yy2 - yy1, 0) * jnp.maximum(xx2 - xx1, 0)
        return inter / jnp.maximum(area[i] + area - inter, 1e-9)

    def body(k, state):
        sel, alive, live_scores = state
        i = jnp.argmax(live_scores)
        ok = live_scores[i] > _NEG / 2
        sel = sel.at[k].set(jnp.where(ok, i, -1))
        kill = (iou(i, alive) > iou_threshold) | (jnp.arange(N) == i)
        alive = alive & ~kill & ok
        live_scores = jnp.where(alive, scores, _NEG)
        return sel, alive, live_scores

    sel0 = jnp.full((max_output_size,), -1, jnp.int32)
    alive0 = jnp.ones((N,), bool)
    sel, _, _ = lax.fori_loop(0, max_output_size, body,
                              (sel0, alive0, jnp.where(alive0, scores, _NEG)))
    return sel, jnp.sum(sel >= 0)


@op("max_pool_with_argmax")
def _max_pool_with_argmax(x, kernel=(2, 2), strides=(2, 2), padding="VALID"):
    """NCHW max pool returning (values, flat HW argmax indices) — TF
    semantics where the index is into the flattened H*W plane. VALID only:
    under SAME the patch extraction zero-pads, so an argmax could name a pad
    cell with no in-plane index."""
    if padding != "VALID":
        raise NotImplementedError("max_pool_with_argmax supports VALID padding only")
    B, C, H, W = x.shape
    patches = lax.conv_general_dilated_patches(x, kernel, strides, padding)
    _, CKK, OH, OW = patches.shape
    kk = kernel[0] * kernel[1]
    p = patches.reshape(B, C, kk, OH, OW)
    vals = p.max(axis=2)
    local = p.argmax(axis=2)  # 0..kk-1
    oh = jnp.arange(OH)[:, None]
    ow = jnp.arange(OW)[None, :]
    kh_off = local // kernel[1]
    kw_off = local % kernel[1]
    flat = (oh * strides[0] + kh_off) * W + (ow * strides[1] + kw_off)
    return vals, flat.astype(jnp.int32)


@op("fused_batch_norm")
def _fused_batch_norm(x, scale, offset, eps=1e-3):
    """Training-mode fused BN over NHWC [B,H,W,C] -> (y, mean, var).

    One-pass statistics (sum + sum-of-squares in a single fused reduction)
    — the r4 bandwidth optimization, see nn/conf.py BatchNormalization."""
    n = x.shape[0] * x.shape[1] * x.shape[2]
    s1 = jnp.sum(x, axis=(0, 1, 2))
    s2 = jnp.sum(x * x, axis=(0, 1, 2))
    mean = s1 / n
    var = jnp.maximum(s2 / n - mean * mean, 0.0)
    y = (x - mean) * lax.rsqrt(var + eps) * scale + offset
    return y, mean, var


@op("mirror_pad")
def _mirror_pad(x, paddings, mode="REFLECT"):
    np_mode = "reflect" if mode.upper() == "REFLECT" else "symmetric"
    return jnp.pad(x, [tuple(p) for p in paddings], mode=np_mode)


@op("upsampling3d")
def _upsampling3d(x, factor):
    """NCDHW nearest upsample by integer factor."""
    f = (factor, factor, factor) if isinstance(factor, int) else tuple(factor)
    x = jnp.repeat(x, f[0], axis=2)
    x = jnp.repeat(x, f[1], axis=3)
    return jnp.repeat(x, f[2], axis=4)


# ------------------------------------------------------------------- linalg


@op("lu")
def _lu(a):
    """LU with partial pivoting -> (P, L, U) with P @ A = L @ U... returned
    as TF-style (lu_matrix, permutation_vector)? We follow scipy: (p, l, u)
    permutation MATRIX such that a = p @ l @ u."""
    import jax.scipy.linalg as jsl

    return jsl.lu(a)


@op("matrix_exp")
def _matrix_exp(a):
    import jax.scipy.linalg as jsl

    return jsl.expm(a)


@op("sqrtm")
def _sqrtm(a):
    import jax.scipy.linalg as jsl

    return jsl.sqrtm(a)


@op("pinv")
def _pinv(a):
    return jnp.linalg.pinv(a)


@op("kron")
def _kron(a, b):
    return jnp.kron(a, b)


@op("matrix_power")
def _matrix_power(a, n):
    return jnp.linalg.matrix_power(a, n)


@op("tri")
def _tri(n, m=None, k=0):
    return jnp.tri(n, m, k)


@op("diag_part")
def _diag_part(x):
    return jnp.diagonal(x, axis1=-2, axis2=-1)


# ----------------------------------------------------------- sg/cb train ops


@op("skipgram")
def _skipgram(syn0, syn1neg, center, context, negatives, lr=0.025):
    """One skip-gram negative-sampling update as a pure function (ref:
    generic/nlp/sg_cb.cpp skipgram op — there mutating, here functional:
    returns (new_syn0, new_syn1neg)). center/context [B], negatives [B,K]."""
    syn0 = jnp.asarray(syn0)
    syn1neg = jnp.asarray(syn1neg)
    center = jnp.asarray(center, jnp.int32)
    context = jnp.asarray(context, jnp.int32)
    negatives = jnp.asarray(negatives, jnp.int32)
    h = syn0[center]                                     # [B,D]
    targets = jnp.concatenate([context[:, None], negatives], axis=1)  # [B,1+K]
    labels = jnp.zeros(targets.shape, syn0.dtype).at[:, 0].set(1.0)
    w = syn1neg[targets]                                 # [B,1+K,D]
    logits = jnp.einsum("bd,bkd->bk", h, w)
    g = (jax.nn.sigmoid(logits) - labels) * lr           # [B,1+K]
    dh = jnp.einsum("bk,bkd->bd", g, w)
    dw = g[..., None] * h[:, None, :]
    new_syn0 = syn0.at[center].add(-dh)
    new_syn1 = syn1neg.at[targets.reshape(-1)].add(-dw.reshape(-1, dw.shape[-1]))
    return new_syn0, new_syn1


@op("cbow")
def _cbow(syn0, syn1neg, context_window, target, negatives, lr=0.025):
    """CBOW-NS update: h = mean of context rows. context_window [B,W],
    target [B], negatives [B,K] -> (new_syn0, new_syn1neg)."""
    syn0 = jnp.asarray(syn0)
    syn1neg = jnp.asarray(syn1neg)
    ctx = jnp.asarray(context_window, jnp.int32)
    target = jnp.asarray(target, jnp.int32)
    negatives = jnp.asarray(negatives, jnp.int32)
    W = ctx.shape[1]
    h = syn0[ctx].mean(axis=1)                            # [B,D]
    targets = jnp.concatenate([target[:, None], negatives], axis=1)
    labels = jnp.zeros(targets.shape, syn0.dtype).at[:, 0].set(1.0)
    w = syn1neg[targets]
    logits = jnp.einsum("bd,bkd->bk", h, w)
    g = (jax.nn.sigmoid(logits) - labels) * lr
    # word2vec.c / sg_cb.cpp apply the accumulated neu1e to EVERY context row
    # undivided (no 1/W), even though h averaged over the window (ADVICE r4)
    dh = jnp.einsum("bk,bkd->bd", g, w)
    dw = g[..., None] * h[:, None, :]
    new_syn0 = syn0.at[ctx.reshape(-1)].add(-jnp.repeat(dh, W, axis=0))
    new_syn1 = syn1neg.at[targets.reshape(-1)].add(-dw.reshape(-1, dw.shape[-1]))
    return new_syn0, new_syn1


# ------------------------------------------------------------ reductions tail


@op("reduce_logsumexp")
def _reduce_logsumexp(x, dims=None, keepdims=False):
    return jax.scipy.special.logsumexp(x, axis=dims, keepdims=keepdims)


@op("count_nonzero")
def _count_nonzero(x, dims=None):
    return jnp.sum((x != 0).astype(jnp.int32), axis=dims)


@op("count_zero")
def _count_zero(x, dims=None):
    return jnp.sum((x == 0).astype(jnp.int32), axis=dims)


@op("zero_fraction")
def _zero_fraction(x):
    return jnp.mean((x == 0).astype(jnp.float32))


@op("amax")
def _amax(x, dims=None, keepdims=False):
    return jnp.max(jnp.abs(x), axis=dims, keepdims=keepdims)


@op("amin")
def _amin(x, dims=None, keepdims=False):
    return jnp.min(jnp.abs(x), axis=dims, keepdims=keepdims)


@op("amean")
def _amean(x, dims=None, keepdims=False):
    return jnp.mean(jnp.abs(x), axis=dims, keepdims=keepdims)


@op("asum")
def _asum(x, dims=None, keepdims=False):
    return jnp.sum(jnp.abs(x), axis=dims, keepdims=keepdims)


@op("reduce_dot")
def _reduce_dot(a, b, dims=None):
    return jnp.sum(a * b, axis=dims)


@op("sqnorm")
def _sqnorm(x, dims=None, keepdims=False):
    return jnp.sum(jnp.square(x), axis=dims, keepdims=keepdims)


@op("percentile")
def _percentile(x, q, dims=None):
    return jnp.percentile(x, q, axis=dims)


@op("median")
def _median(x, dims=None):
    return jnp.median(x, axis=dims)


# --------------------------------------------------------- broadcastable tail


@op("truncatediv")
def _truncatediv(a, b):
    return jnp.trunc(a / b)


@op("divide_no_nan")
def _divide_no_nan(a, b):
    return jnp.where(b == 0, 0.0, a / jnp.where(b == 0, 1.0, b))


@op("realdiv")
def _realdiv(a, b):
    return a / b


@op("floormod")
def _floormod(a, b):
    return a - jnp.floor(a / b) * b


@op("logaddexp")
def _logaddexp(a, b):
    return jnp.logaddexp(a, b)


@op("zeta")
def _zeta(x, q):
    return jax.scipy.special.zeta(x, q)


# ----------------------------------------------------------------- merge ops


@op("mergeadd")
def _mergeadd(*xs):
    return sum(xs[1:], xs[0])


@op("mergeavg")
def _mergeavg(*xs):
    return sum(xs[1:], xs[0]) / len(xs)


@op("mergemax")
def _mergemax(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = jnp.maximum(out, x)
    return out


@op("accumulate_n")
def _accumulate_n(xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


# ------------------------------------------------------------- shape/misc tail


@op("invert_permutation")
def _invert_permutation(p):
    p = jnp.asarray(p, jnp.int32)
    return jnp.zeros_like(p).at[p].set(jnp.arange(p.shape[0], dtype=p.dtype))


@op("unique")
def _unique(x, size=None):
    """Sorted unique values. ``size`` required under jit (static shapes);
    eager calls may omit it (host round trip, like the reference's CPU op)."""
    if size is None:
        return jnp.unique(np.asarray(x))
    return jnp.unique(x, size=size)


@op("unique_with_counts")
def _unique_with_counts(x, size=None):
    if size is None:
        return jnp.unique(np.asarray(x), return_counts=True)
    return jnp.unique(x, size=size, return_counts=True)


@op("listdiff")
def _listdiff(x, y):
    """Values (and indices) in x not present in y. Host-side (dynamic)."""
    x = np.asarray(x)
    mask = ~np.isin(x, np.asarray(y))
    return x[mask], np.nonzero(mask)[0].astype(np.int32)


@op("nth_element")
def _nth_element(x, n, reverse=False):
    s = jnp.sort(x, axis=-1)
    if reverse:
        s = jnp.flip(s, axis=-1)
    return s[..., n]


@op("histogram")
def _histogram(x, bins=10, range=None):
    return jnp.histogram(x, bins=bins, range=range)[0]


@op("histogram_fixed_width")
def _histogram_fixed_width(x, value_range, nbins=100):
    lo, hi = value_range
    idx = jnp.clip(((x - lo) / (hi - lo) * nbins).astype(jnp.int32), 0, nbins - 1)
    return jnp.zeros((nbins,), jnp.int32).at[idx.reshape(-1)].add(1)


@op("nonzero")
def _nonzero(x, size=None):
    if size is None:
        return jnp.stack(jnp.nonzero(np.asarray(x)), axis=1)
    return jnp.stack(jnp.nonzero(x, size=size), axis=1)


@op("searchsorted")
def _searchsorted(sorted_seq, values, side="left"):
    return jnp.searchsorted(sorted_seq, values, side=side)


@op("bucketize")
def _bucketize(x, boundaries):
    return jnp.searchsorted(jnp.asarray(boundaries), x, side="right")


@op("clip_by_avg_norm")
def _clip_by_avg_norm(x, clip):
    avg = jnp.sqrt(jnp.mean(jnp.square(x)))
    return x * jnp.minimum(1.0, clip / jnp.maximum(avg, 1e-12))


@op("clip_by_global_norm")
def _clip_by_global_norm(xs, clip):
    g = jnp.sqrt(sum(jnp.sum(jnp.square(x)) for x in xs))
    scale = jnp.minimum(1.0, clip / jnp.maximum(g, 1e-12))
    return [x * scale for x in xs]


@op("check_numerics")
def _check_numerics(x, message="check_numerics"):
    return lax.cond(jnp.all(jnp.isfinite(x)), lambda: x,
                    lambda: x * jnp.nan)  # poison output, parity with panic mode


@op("assign")
def _assign(ref, value):
    return jnp.broadcast_to(value, jnp.shape(ref)).astype(jnp.asarray(ref).dtype)


@op("identity")
def _identity(x):
    return jnp.asarray(x)


@op("stop_gradient")
def _stop_gradient(x):
    return lax.stop_gradient(x)


@op("nan_to_num")
def _nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@op("dynamic_partition")
def _dynamic_partition(x, partitions, num_partitions):
    """Host-side (output shapes are data-dependent, as in the reference)."""
    x = np.asarray(x)
    partitions = np.asarray(partitions)
    return [x[partitions == i] for i in range(num_partitions)]


@op("split_v")
def _split_v(x, sizes, axis=0):
    out = []
    off = 0
    for s in sizes:
        out.append(lax.slice_in_dim(x, off, off + s, axis=axis))
        off += s
    return out


@op("batch_gather")
def _batch_gather(x, indices):
    """Gather along axis 1 with a leading shared batch dim."""
    x = jnp.asarray(x)
    idx = jnp.asarray(indices, jnp.int32)  # before .shape: plain lists work too
    return jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - idx.ndim)), axis=1)


@op("logspace")
def _logspace(start, stop, num, base=10.0):
    return jnp.logspace(start, stop, num, base=base)


@op("step_fn")
def _step_fn(x):
    """Unit step (nd4j legacy 'step' transform)."""
    return (x > 0).astype(jnp.asarray(x).dtype if jnp.asarray(x).dtype.kind == "f" else jnp.float32)


@op("rationaltanh")
def _rationaltanh(x):
    """nd4j legacy rational tanh approximation (softsign-family curve)."""
    a = 1.7159 * x * (2.0 / 3.0)
    return a / (1 + jnp.abs(a))


@op("cyclic_rshift_bits")
def _cyclic_rshift_bits(x, n):
    bits = jnp.asarray(x).dtype.itemsize * 8
    n = jnp.asarray(n, x.dtype)
    return (x >> n) | (x << (bits - n))


# ----------------------------------------------------------------- nn tail


@op("bias_add")
def _bias_add(x, b):
    return x + b


@op("xw_plus_b")
def _xw_plus_b(x, w, b):
    return x @ w + b


@op("relu_layer")
def _relu_layer(x, w, b):
    return jax.nn.relu(x @ w + b)


@op("l2_loss")
def _l2_loss(x):
    return 0.5 * jnp.sum(jnp.square(x))


@op("log_poisson_loss")
def _log_poisson_loss(targets, log_input, full=False):
    loss = jnp.exp(log_input) - targets * log_input
    if full:
        loss = loss + targets * jnp.log(jnp.maximum(targets, 1e-12)) - targets \
            + 0.5 * jnp.log(2 * jnp.pi * jnp.maximum(targets, 1e-12))
    return jnp.mean(loss)


@op("separable_conv2d")
def _separable_conv2d(x, depth_w, point_w, strides=(1, 1), padding="SAME"):
    """NCHW separable conv: depth_w [C*M,1,KH,KW], point_w [O,C*M,1,1]."""
    c_in = x.shape[1]
    z = lax.conv_general_dilated(
        x, depth_w, strides, padding,
        dimension_numbers=("NCHW", "OIHW", "NCHW"), feature_group_count=c_in)
    return lax.conv_general_dilated(
        z, point_w, (1, 1), "VALID", dimension_numbers=("NCHW", "OIHW", "NCHW"))


# -------------------------------------------------------------- random tail


@op("random_multinomial")
def _random_multinomial(key, logits, num_samples):
    logits = jnp.asarray(logits)
    return jax.random.categorical(key, logits[:, None, :],
                                  shape=(logits.shape[0], num_samples))


@op("random_binomial")
def _random_binomial(key, shape, n=1, p=0.5):
    return jnp.sum(jax.random.bernoulli(key, p, (n,) + tuple(shape)).astype(jnp.int32), axis=0)


@op("random_truncated_normal")
def _random_truncated_normal(key, shape):
    return jax.random.truncated_normal(key, -2.0, 2.0, shape)


@op("isclose")
def _isclose(a, b, rtol=1e-5, atol=1e-8):
    return jnp.isclose(a, b, rtol=rtol, atol=atol)


@op("approx_equal")
def _approx_equal(a, b, tolerance=1e-5):
    return jnp.abs(a - b) < tolerance


# ---------------------------------------------------------- wave 3b (r4 tail)
# More of the generic corpus: morphology, scatter_nd family, quantization,
# shape/meta utilities (ref: ops/declarable/generic/** rows not yet covered).


def _morph_patches(x, kernel, strides, rates, padding):
    """Shared window extraction for the morphology pair: returns
    (patches [B,C,KH*KW,OH,OW], kernel_flat [C,KH*KW])."""
    x = jnp.asarray(x)
    kernel = jnp.asarray(kernel)
    kh, kw, C = kernel.shape
    patches = lax.conv_general_dilated_patches(
        jnp.transpose(x, (0, 3, 1, 2)), (kh, kw), tuple(strides), padding,
        rhs_dilation=tuple(rates))
    B, _, OH, OW = patches.shape
    p = patches.reshape(B, C, kh * kw, OH, OW)
    kflat = jnp.transpose(kernel, (2, 0, 1)).reshape(C, kh * kw)
    return p, kflat


@op("dilation2d")
def _dilation2d(x, kernel, strides=(1, 1), rates=(1, 1), padding="VALID"):
    """Grayscale morphological dilation (TF semantics): x [B,H,W,C],
    kernel [KH,KW,C]; out[p] = max over window (x + kernel)."""
    p, kflat = _morph_patches(x, kernel, strides, rates, padding)
    out = jnp.max(p + kflat[None, :, :, None, None], axis=2)
    return jnp.transpose(out, (0, 2, 3, 1))


@op("erosion2d")
def _erosion2d(x, kernel, strides=(1, 1), rates=(1, 1), padding="VALID"):
    """Morphological erosion, TF semantics: min over window of
    (x - SPATIALLY-FLIPPED kernel) — erosion2d(x,k) is the dual
    -dilation2d(-x, flip(k))."""
    kernel = jnp.asarray(kernel)[::-1, ::-1, :]
    p, kflat = _morph_patches(x, kernel, strides, rates, padding)
    out = jnp.min(p - kflat[None, :, :, None, None], axis=2)
    return jnp.transpose(out, (0, 2, 3, 1))


@op("fake_quant_with_min_max_vars")
def _fake_quant(x, min_val, max_val, num_bits=8, narrow_range=False):
    """Simulated quantization (quantization-aware training forward)."""
    qmin = 1.0 if narrow_range else 0.0
    qmax = float(2 ** num_bits - 1)
    scale = (max_val - min_val) / (qmax - qmin)
    degenerate = scale == 0
    scale = jnp.where(degenerate, 1.0, scale)  # avoid 0-div; masked below
    zero = qmin - min_val / scale
    zero = jnp.clip(jnp.round(zero), qmin, qmax)
    q = jnp.clip(jnp.round(x / scale + zero), qmin, qmax)
    return jnp.where(degenerate, 0.0, (q - zero) * scale)


@op("is_numeric_tensor")
def _is_numeric_tensor(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.number)


@op("log_matrix_determinant")
def _log_matrix_determinant(a):
    sign, logdet = jnp.linalg.slogdet(a)
    return sign, logdet


@op("matrix_set_diag")
def _matrix_set_diag(x, diag):
    x = jnp.asarray(x)
    n = min(x.shape[-2], x.shape[-1])
    idx = jnp.arange(n)
    return x.at[..., idx, idx].set(jnp.asarray(diag)[..., :n])


@op("mergemax_index")
def _mergemax_index(*xs):
    """Index of the input holding the elementwise max (ref mergemaxindex)."""
    stacked = jnp.stack(xs)
    return jnp.argmax(stacked, axis=0)


@op("norm")
def _norm(x, ord=2, dims=None, keepdims=False):
    x = jnp.asarray(x)
    if ord == 1:
        return jnp.sum(jnp.abs(x), axis=dims, keepdims=keepdims)
    if ord == 2:
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=dims, keepdims=keepdims))
    if ord in ("inf", np.inf):
        return jnp.max(jnp.abs(x), axis=dims, keepdims=keepdims)
    return jnp.sum(jnp.abs(x) ** ord, axis=dims, keepdims=keepdims) ** (1.0 / ord)


@op("normalize_moments")
def _normalize_moments(counts, mean_ss, variance_ss, shift=0.0):
    """TF normalize_moments: sufficient statistics → (mean, variance)."""
    divisor = 1.0 / jnp.maximum(counts, 1e-12)
    shifted_mean = mean_ss * divisor
    mean = shifted_mean + shift
    variance = variance_ss * divisor - shifted_mean * shifted_mean
    return mean, variance


@op("sufficient_statistics")
def _sufficient_statistics(x, dims, shift=0.0):
    """TF sufficient_statistics: (count, mean_ss, var_ss, shift)."""
    x = jnp.asarray(x)
    dims = tuple(np.atleast_1d(dims).tolist())
    count = float(np.prod([x.shape[d] for d in dims]))
    m_ss = jnp.sum(x - shift, axis=dims)
    v_ss = jnp.sum(jnp.square(x - shift), axis=dims)
    return count, m_ss, v_ss, shift


@op("random_crop")
def _random_crop(key, x, size):
    """Uniform-corner crop to ``size`` (ref random_crop)."""
    x = jnp.asarray(x)
    size = tuple(size)
    starts = []
    for d, (full, want) in enumerate(zip(x.shape, size)):
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, full - want + 1))
    return lax.dynamic_slice(x, starts, size)


@op("scatter_nd")
def _scatter_nd(indices, updates, shape):
    indices = jnp.asarray(indices, jnp.int32)
    out = jnp.zeros(tuple(shape), jnp.asarray(updates).dtype)
    return out.at[tuple(jnp.moveaxis(indices, -1, 0))].add(updates)


@op("scatter_nd_add")
def _scatter_nd_add(ref, indices, updates):
    indices = jnp.asarray(indices, jnp.int32)
    return jnp.asarray(ref).at[tuple(jnp.moveaxis(indices, -1, 0))].add(updates)


@op("scatter_nd_update")
def _scatter_nd_update(ref, indices, updates):
    indices = jnp.asarray(indices, jnp.int32)
    return jnp.asarray(ref).at[tuple(jnp.moveaxis(indices, -1, 0))].set(updates)


@op("size_at")
def _size_at(x, dim):
    return jnp.shape(x)[dim]


@op("compare_and_bitpack")
def _compare_and_bitpack(x, threshold):
    """TF compare_and_bitpack: last dim (divisible by 8) packed into uint8."""
    bits = (jnp.asarray(x) > threshold).astype(jnp.uint8)
    b = bits.reshape(bits.shape[:-1] + (bits.shape[-1] // 8, 8))
    weights = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], jnp.uint8)
    return jnp.sum(b * weights, axis=-1).astype(jnp.uint8)


@op("bitcast")
def _bitcast(x, dtype):
    return lax.bitcast_convert_type(jnp.asarray(x), dtype)


@op("broadcast_dynamic_shape")
def _broadcast_dynamic_shape(a, b):
    """Numpy broadcast of two shape VECTORS — jnp ops only, so it traces
    (shape vectors can be computed tensors under jit)."""
    a = jnp.asarray(a, jnp.int64)
    b = jnp.asarray(b, jnp.int64)
    n = max(a.shape[0], b.shape[0])
    ap = jnp.concatenate([jnp.ones((n - a.shape[0],), jnp.int64), a])
    bp = jnp.concatenate([jnp.ones((n - b.shape[0],), jnp.int64), b])
    return jnp.where(ap == 1, bp, jnp.where(bp == 1, ap, jnp.maximum(ap, bp)))


@op("mean_pairwssqerr_loss")
def _mean_pairwssqerr(labels, preds):
    """nd4j mean_pairwssqerr: mean squared difference of all PAIRWISE
    differences per sample (pairwise-ranking-flavored regression loss)."""
    d = (jnp.asarray(preds) - jnp.asarray(labels))
    pair = d[:, :, None] - d[:, None, :]
    return jnp.mean(jnp.square(pair))
