"""Self-driving model lifecycle (ISSUE 18): the unattended
train → validate → canary → promote controller."""

from .controller import FleetController, GATE_CHAIN

__all__ = ["FleetController", "GATE_CHAIN"]
