"""Self-driving model lifecycle (ISSUE 18): watch a checkpoint lineage,
drive every newly COMMITTED generation through an ordered gate chain, and
promote or roll back — unattended.

The repo already owns every primitive of continuous deployment: gangs
survive chaos and commit verified generations (PR 2/14/15), ``swap_model``
rolls a pool with zero downtime (PR 13/14), the SLO tracker + AlertEngine
judge replayed traffic (PR 10/11), and the autoscaler proves alerts can
drive actions (PR 12). What was missing is the composition: without it, a
poisoned candidate reaches the fleet unless a human is watching. The
:class:`FleetController` is that composition — one gate contract, many gate
implementations (the 2207.00257 lesson applied to deployment):

1. **integrity** — ``verify_checkpoint`` deep verify of the candidate
   generation; quarantine evidence (``*.corrupt`` renames) honored. Catches
   torn/bit-flipped artifacts for the price of a read, never a replica.
2. **eval** — offline metrics on a held-out iterator (any callable
   ``gen_dir -> metrics`` — return an :class:`eval.Evaluation` and its
   ``to_metrics`` hook puts the judged numbers on ``/metrics``), checked
   against absolute thresholds AND a regression band vs the
   currently-promoted generation. Catches valid-but-ruined weights (the
   ``loss_spike`` poison) no structural check can see.
3. **canary** — surge ONE replica on the candidate
   (``ServingPool.start_canary``: router-invisible, old fleet untouched)
   and replay the same seeded :class:`TraceSpec` against the canary and a
   baseline replica CONCURRENTLY; judge the paired per-window SLO stats
   (availability, burn, p99 ratio — ``monitoring/deploy.py``) with real
   :class:`AlertRule` ``for_duration``/hysteresis semantics. Catches what
   only live traffic can: latency/availability regressions that ship WITH
   the candidate.
4. **promote** — complete the rolling swap (``swap_model``: updates the
   pool's default overrides so scale-ups spawn the new version) on
   sustained-clear; ANY gate failure rolls back by killing only the surge.

Robustness is the headline:

- **durable resume** — controller state (per-candidate gate progress,
  verdicts, the promoted baseline) is written with
  ``common.durability.durable_write_json`` BEFORE and AFTER every gate; a
  SIGKILLed controller restarted on the same workdir re-enters the exact
  gate it died in and reaches the same terminal verdict.
- **bounded gates** — every gate runs under ``gate_timeout_s`` in its own
  thread; a wedged canary additionally hits ``start_canary``'s ready
  timeout. Timeout = rollback, never a hang.
- **retry before verdict** — exceptions escaping a gate (transient FS/eval
  errors) retry with exponential backoff; only after ``retries`` attempts
  do they count as a failing verdict.

Every decision is a flight event (``deploy_candidate`` / ``deploy_gate`` /
``deploy_promote`` / ``deploy_rollback`` — the AST lint in
tests/test_controller.py proves no decision path forgets its breadcrumb)
and a ``tdl_deploy_*`` metric; every run rewrites a postmortem-style
``audit.json`` (gate verdicts, evidence pointers, fleet-timeline artifact
via ``monitoring/timeline.build_timeline``).

Subprocess mode (the unattended story end-to-end)::

    python -m deeplearning4j_tpu.deploy.controller config.json --once

with a JSON config naming the lineage, the pool target, the trace and the
gate thresholds — see :func:`from_config`.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..common.durability import durable_write_json
from ..monitoring import flight
from ..monitoring.deploy import (canary_rules as default_canary_rules,
                                 deploy_metrics, judge_canary_windows,
                                 paired_canary_windows)
from ..monitoring.registry import MetricsRegistry, get_registry
from ..serde.checkpoint import lineage_state, verify_checkpoint

log = logging.getLogger(__name__)

#: the full gate chain, in order; configurable subsets keep the one-gate
#: contract (e.g. ``("integrity", "eval")`` for a controller without a pool)
GATE_CHAIN = ("integrity", "eval", "canary")

STATE_FILE = "controller_state.json"
AUDIT_FILE = "audit.json"


def _load_callable(spec: str) -> Callable:
    """``module:function`` or ``/path/to/file.py:function`` — the same two
    target forms pool replicas and launcher workers accept."""
    mod_name, _, fn_name = spec.rpartition(":")
    if mod_name.endswith(".py"):
        import importlib.util

        loader_spec = importlib.util.spec_from_file_location(
            "_tdl_eval_target", mod_name)
        mod = importlib.util.module_from_spec(loader_spec)
        loader_spec.loader.exec_module(mod)
    else:
        import importlib

        mod = importlib.import_module(mod_name)
    return getattr(mod, fn_name)


class FleetController:
    """Unattended lineage-to-fleet promotion with staged fault gates.

    ``ckpt_dir``/``tag`` name the ``TrainingCheckpointer`` lineage to watch;
    ``pool`` is the :class:`serving.ServingPool` to canary against and
    promote into (None = gate chain without canary/swap — promotion then
    just moves the durable baseline). ``eval_fn(gen_dir)`` returns either a
    plain ``{metric: value}`` dict or an object with a
    ``to_metrics(registry, model=)`` hook (``eval.Evaluation`` /
    ``RegressionEvaluation``). ``eval_thresholds`` are absolute floors
    (``{"accuracy": 0.8}``); ``regression_band`` is how far below the
    promoted baseline's metric a candidate may fall before the eval gate
    fails it."""

    def __init__(self, ckpt_dir: str,
                 pool=None, *,
                 tag: str = "latest",
                 workdir: str,
                 gates: Optional[Sequence[str]] = None,
                 eval_fn: Optional[Callable[[str], Any]] = None,
                 eval_thresholds: Optional[Dict[str, float]] = None,
                 regression_band: float = 0.05,
                 trace=None,
                 rules=None,
                 payload: Any = None,
                 n_clients: int = 4,
                 slo_threshold_ms: float = 250.0,
                 slo_target: float = 0.99,
                 burn_window_s: float = 0.5,
                 canary_ready_timeout: float = 60.0,
                 gate_timeout_s: float = 300.0,
                 retries: int = 2,
                 retry_backoff_s: float = 0.2,
                 registry: Optional[MetricsRegistry] = None):
        self.ckpt_dir = str(ckpt_dir)
        self.tag = tag
        self.pool = pool
        self.workdir = str(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        if gates is None:
            gates = GATE_CHAIN if pool is not None else ("integrity", "eval")
        unknown = [g for g in gates if g not in GATE_CHAIN]
        if unknown:
            raise ValueError(f"unknown gates {unknown}; choose from "
                             f"{GATE_CHAIN}")
        self.gates = tuple(gates)
        self.eval_fn = eval_fn
        self.eval_thresholds = dict(eval_thresholds or {})
        self.regression_band = float(regression_band)
        if trace is None and "canary" in self.gates:
            from ..serving.loadgen import TraceSpec

            trace = TraceSpec(duration_s=4.0, base_rate=40.0, seed=18)
        self.trace = trace
        self.rules = tuple(rules) if rules is not None \
            else default_canary_rules()
        self.payload = payload if payload is not None else [[0.0, 0.0, 0.0,
                                                             0.0]]
        self.n_clients = int(n_clients)
        self.slo_threshold_ms = float(slo_threshold_ms)
        self.slo_target = float(slo_target)
        self.burn_window_s = float(burn_window_s)
        self.canary_ready_timeout = float(canary_ready_timeout)
        self.gate_timeout_s = float(gate_timeout_s)
        self.retries = int(retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.registry = registry if registry is not None else get_registry()
        self._m = deploy_metrics(self.registry)
        self.state_path = os.path.join(self.workdir, STATE_FILE)
        self.audit_path = os.path.join(self.workdir, AUDIT_FILE)
        self.flight_dir = os.path.join(self.workdir, "flight")
        self._own_recorder: Optional[flight.FlightRecorder] = None
        if not flight.active():
            # unattended means self-recording: without a supervisor's
            # TDL_FLIGHT_DIR the controller installs its own spool so every
            # deploy decision still reaches the audit's timeline
            self._own_recorder = flight.FlightRecorder(
                proc="deploy-controller", directory=self.flight_dir,
                interval=0.0)
            flight.set_flight_recorder(self._own_recorder)
        self.state = self._load_state()
        self._stop_evt = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        self._active_canary = None
        g = self.state.get("promoted") or {}
        self._m.promoted_generation.set(float(g.get("iteration", -1)))

    # -- durable state -----------------------------------------------------

    def _load_state(self) -> dict:
        try:
            with open(self.state_path) as f:
                st = json.load(f)
            # a candidate that was mid-gate when the previous incarnation
            # died resumes AT that gate — flag it so the audit says so
            for entry in st.get("candidates", {}).values():
                if entry.get("status") == "in_gate":
                    entry["resumed"] = True
            log.info("controller resumed from %s (%d candidates known)",
                     self.state_path, len(st.get("candidates", {})))
            return st
        except (OSError, ValueError):
            return {"version": 1, "tag": self.tag, "promoted": None,
                    "candidates": {}}

    def _save_state(self) -> None:
        durable_write_json(self.state_path, self.state)

    def close(self) -> None:
        self._stop_evt.set()
        t, self._watch_thread = self._watch_thread, None
        if t is not None:
            t.join(timeout=10.0)
        if self._active_canary is not None and self.pool is not None:
            try:
                self.pool.stop_canary(self._active_canary)
            except Exception:
                log.exception("canary cleanup failed on close")
            self._active_canary = None
        if self._own_recorder is not None:
            self._own_recorder.flush()
            flight.set_flight_recorder(None)
            self._own_recorder = None

    # -- watch loop --------------------------------------------------------

    def start(self, interval: float = 1.0) -> "FleetController":
        """Background watch: poll the lineage, process new committed
        generations as they appear. Idempotent."""
        if self._watch_thread is not None:
            return self
        self._stop_evt.clear()

        def loop():
            while not self._stop_evt.wait(interval):
                try:
                    self.run_once()
                except Exception:
                    log.exception("controller watch iteration failed")

        self._watch_thread = threading.Thread(
            target=loop, name="tdl-deploy-watch", daemon=True)
        self._watch_thread.start()
        return self

    def stop(self) -> None:
        self.close()

    def run_once(self) -> List[dict]:
        """One pass: pick up every committed generation not yet decided,
        run it through the gate chain, return the per-candidate audit rows
        (may be empty). Candidates no newer than the promoted baseline are
        skipped — the controller only ever moves the fleet forward."""
        st = lineage_state(self.ckpt_dir, self.tag)
        out = []
        promoted = self.state.get("promoted") or {}
        floor = promoted.get("iteration", -1)
        for cand in st["committed"]:
            entry = self.state["candidates"].get(cand["generation"])
            if entry and entry.get("status") in ("promoted", "rejected"):
                continue
            if entry is None and cand["iteration"] <= floor:
                continue  # older than what already serves
            out.append(self._process_candidate(cand, st))
            # a promotion raises the floor for the rest of this pass
            promoted = self.state.get("promoted") or {}
            floor = promoted.get("iteration", -1)
        if out:
            self._write_audit()
        return out

    # -- candidate pipeline ------------------------------------------------

    def _process_candidate(self, cand: dict, lineage: dict) -> dict:
        name = cand["generation"]
        gendir = os.path.join(lineage["dir"], name)
        entry = self.state["candidates"].setdefault(name, {
            "generation": name, "iteration": cand["iteration"],
            "dir": gendir, "status": "pending", "gate": None,
            "verdicts": [], "resumed": False})
        entry["dir"] = gendir
        if not entry.get("announced"):
            self._announce_candidate(entry)
        passed = {v["gate"] for v in entry["verdicts"] if v["ok"]}
        for gate in self.gates:
            if gate in passed:
                continue  # resume: this gate's pass verdict is durable
            entry["status"], entry["gate"] = "in_gate", gate
            self._save_state()  # crash here -> restart re-enters THIS gate
            verdict = self._run_gate(gate, entry, lineage)
            self._record_verdict(entry, verdict)
            if not verdict["ok"]:
                return self._rollback(entry, verdict)
        return self._promote(entry)

    # -- decision points (every one records its flight event; the AST lint
    # in tests/test_controller.py keeps it that way) -----------------------

    def _announce_candidate(self, entry: dict) -> None:
        flight.record("deploy_candidate", generation=entry["generation"],
                      iteration=entry["iteration"], dir=entry["dir"],
                      resumed=bool(entry.get("resumed")))
        self._m.candidates.inc()
        entry["announced"] = True
        self._save_state()

    def _record_verdict(self, entry: dict, verdict: dict) -> dict:
        flight.record("deploy_gate", gate=verdict["gate"],
                      verdict="pass" if verdict["ok"] else "fail",
                      generation=entry["generation"],
                      iteration=entry["iteration"],
                      reason=verdict.get("reason"),
                      seconds=verdict.get("seconds"))
        self._m.gate_verdicts.labels(
            verdict["gate"], "pass" if verdict["ok"] else "fail").inc()
        if verdict.get("seconds") is not None:
            self._m.gate_seconds.labels(verdict["gate"]).observe(
                verdict["seconds"])
        entry["verdicts"].append(verdict)
        self._save_state()
        return verdict

    def _promote(self, entry: dict) -> dict:
        if self.pool is not None:
            swap = self._swap_into_pool(entry)
            if not swap["ok"]:
                self._record_verdict(entry, swap)
                return self._rollback(entry, swap)
            self._record_verdict(entry, swap)
        flight.record("deploy_promote", generation=entry["generation"],
                      iteration=entry["iteration"], dir=entry["dir"])
        self._m.promotions.inc()
        self._m.promoted_generation.set(float(entry["iteration"]))
        entry["status"], entry["gate"] = "promoted", None
        self.state["promoted"] = {
            "generation": entry["generation"],
            "iteration": entry["iteration"], "dir": entry["dir"],
            "metrics": self._eval_metrics_of(entry)}
        self._save_state()
        log.info("promoted %s (iteration %d)", entry["generation"],
                 entry["iteration"])
        return entry

    def _rollback(self, entry: dict, verdict: dict) -> dict:
        flight.record("deploy_rollback", generation=entry["generation"],
                      iteration=entry["iteration"], gate=verdict["gate"],
                      reason=verdict.get("reason"), audit=self.audit_path)
        self._m.rollbacks.labels(verdict["gate"]).inc()
        entry["status"], entry["gate"] = "rejected", None
        entry["rejected_by"] = {"gate": verdict["gate"],
                                "reason": verdict.get("reason")}
        self._save_state()
        log.warning("rejected %s at the %s gate (%s) — fleet untouched",
                    entry["generation"], verdict["gate"],
                    verdict.get("reason"))
        return entry

    # -- gate driver -------------------------------------------------------

    def _run_gate(self, gate: str, entry: dict, lineage: dict) -> dict:
        """One gate, bounded and retried: the gate fn runs in its own
        thread under ``gate_timeout_s`` (a wedged gate is a failing verdict,
        never a hang); exceptions escaping it are treated as transient and
        retried with exponential backoff before counting as a verdict."""
        fn = getattr(self, f"_gate_{gate}")
        t0 = time.perf_counter()
        last_err: Optional[BaseException] = None
        for attempt in range(self.retries + 1):
            box: Dict[str, Any] = {}

            def runner():
                try:
                    box["v"] = fn(entry, lineage)
                except BaseException as e:  # noqa: BLE001 — verdict, below
                    box["e"] = e

            th = threading.Thread(target=runner, daemon=True,
                                  name=f"tdl-deploy-gate-{gate}")
            th.start()
            th.join(self.gate_timeout_s)
            if th.is_alive():
                self._cleanup_wedged_gate()
                return {"gate": gate, "ok": False, "reason": "timeout",
                        "seconds": round(time.perf_counter() - t0, 3),
                        "evidence": {"timeout_s": self.gate_timeout_s,
                                     "attempt": attempt}}
            if "v" in box:
                v = box["v"]
                v.setdefault("seconds",
                             round(time.perf_counter() - t0, 3))
                if attempt:
                    v.setdefault("evidence", {})["retries"] = attempt
                return v
            last_err = box.get("e")
            if isinstance(last_err, (KeyboardInterrupt, SystemExit)):
                raise last_err
            log.warning("gate %s attempt %d errored (%s) — backing off",
                        gate, attempt, last_err)
            time.sleep(self.retry_backoff_s * (2 ** attempt))
        return {"gate": gate, "ok": False,
                "reason": f"error:{type(last_err).__name__}",
                "seconds": round(time.perf_counter() - t0, 3),
                "evidence": {"error": str(last_err),
                             "attempts": self.retries + 1}}

    def _cleanup_wedged_gate(self) -> None:
        canary, self._active_canary = self._active_canary, None
        if canary is not None and self.pool is not None:
            try:
                self.pool.stop_canary(canary)
            except Exception:
                log.exception("wedged-gate canary cleanup failed")

    # -- the gates ---------------------------------------------------------

    def _gate_integrity(self, entry: dict, lineage: dict) -> dict:
        quarantined = [q for q in lineage.get("quarantined", ())
                       if q.startswith(entry["generation"])]
        if quarantined or not os.path.isdir(entry["dir"]):
            # the restore side already condemned (and renamed) it — honor
            # the evidence instead of re-verifying a dir that moved away
            return {"gate": "integrity", "ok": False, "reason": "quarantined",
                    "evidence": {"quarantined": quarantined or
                                 [entry["generation"]]}}
        report = verify_checkpoint(entry["dir"], deep=True,
                                   registry=self.registry)
        return {"gate": "integrity", "ok": bool(report["ok"]),
                "reason": None if report["ok"] else report["reason"],
                "evidence": {"verify": {k: report.get(k) for k in
                                        ("reason", "generation", "iteration",
                                         "format", "bytes", "seconds")},
                             "dir": entry["dir"]}}

    def _gate_eval(self, entry: dict, lineage: dict) -> dict:
        if self.eval_fn is None:
            return {"gate": "eval", "ok": True, "reason": "skipped:no_eval",
                    "evidence": {}}
        res = self.eval_fn(entry["dir"])
        if hasattr(res, "to_metrics"):
            metrics = res.to_metrics(self.registry,
                                     model=entry["generation"])
        else:
            metrics = {k: float(v) for k, v in dict(res).items()}
            # plain dicts still land on /metrics: the gate and the scrape
            # must agree on the judged numbers (ISSUE 18 satellite)
            from ..eval.evaluation import eval_metrics as _em

            acc_g, f1_g, score_g = _em(self.registry)
            by_name = {"accuracy": acc_g, "f1": f1_g, "score": score_g}
            for k, g in by_name.items():
                if k in metrics:
                    g.labels(entry["generation"]).set(metrics[k])
        failures = []
        for metric, floor in self.eval_thresholds.items():
            v = metrics.get(metric)
            if v is None or v < floor:
                failures.append(f"{metric}={v} < {floor}")
        baseline = (self.state.get("promoted") or {}).get("metrics") or {}
        for metric, base in baseline.items():
            v = metrics.get(metric)
            if v is not None and v < base - self.regression_band:
                failures.append(
                    f"{metric}={v:.4f} regressed below promoted "
                    f"{base:.4f} - band {self.regression_band}")
        return {"gate": "eval", "ok": not failures,
                "reason": "; ".join(failures) or None,
                "evidence": {"metrics": metrics,
                             "thresholds": self.eval_thresholds,
                             "baseline": baseline,
                             "regression_band": self.regression_band}}

    def _gate_canary(self, entry: dict, lineage: dict) -> dict:
        if self.pool is None:
            return {"gate": "canary", "ok": False, "reason": "no_pool",
                    "evidence": {}}
        baseline_port = self._baseline_port()
        if baseline_port is None:
            return {"gate": "canary", "ok": False, "reason": "no_baseline",
                    "evidence": {"pool": self.pool.describe()}}
        try:
            canary = self.pool.start_canary(
                entry["dir"], ready_timeout=self.canary_ready_timeout)
        except TimeoutError as e:
            return {"gate": "canary", "ok": False,
                    "reason": "canary_not_ready",
                    "evidence": {"error": str(e),
                                 "ready_timeout_s":
                                     self.canary_ready_timeout}}
        self._active_canary = canary
        try:
            reports = self._paired_replay(baseline_port, canary.port)
        finally:
            self._active_canary = None
            self.pool.stop_canary(canary)
        windows = paired_canary_windows(
            reports["baseline"].pop("requests"),
            reports["candidate"].pop("requests"),
            duration_s=self.trace.duration_s, window_s=self.burn_window_s,
            threshold_ms=self.slo_threshold_ms, target=self.slo_target)
        verdict = judge_canary_windows(windows, self.rules,
                                       registry=self.registry)
        reason = None
        if not verdict["ok"]:
            rules = sorted({f["rule"] for f in verdict["fired"]})
            reason = "slo:" + ",".join(rules)
        return {"gate": "canary", "ok": verdict["ok"], "reason": reason,
                "evidence": {"fired": verdict["fired"],
                             "windows_judged": verdict["judged"],
                             "windows": windows,
                             "baseline": reports["baseline"],
                             "candidate": reports["candidate"],
                             "canary_replica": canary.id,
                             "baseline_port": baseline_port}}

    def _baseline_port(self) -> Optional[int]:
        for r in self.pool.describe()["replicas"]:
            if (r["state"] == "ready" and not r["canary"]
                    and not r["retiring"] and r["port"]):
                return r["port"]
        return None

    def _paired_replay(self, baseline_port: int,
                       canary_port: int) -> Dict[str, dict]:
        """The mirrored replay: the SAME seeded arrival schedule against
        both arms, concurrently, so every sub-window pairs like with like.
        Summaries keep outcome counts and latency percentiles; the raw rows
        feed the paired-window judgement."""
        from ..serving.loadgen import LoadGenerator

        out: Dict[str, dict] = {}

        def arm(name: str, port: int):
            gen = LoadGenerator(
                self.trace, port, n_clients=self.n_clients,
                payload=self.payload,
                request_id_prefix=f"canary-{name}",
                slo_threshold_ms=self.slo_threshold_ms,
                slo_target=self.slo_target,
                burn_window_s=self.burn_window_s,
                record_requests=True, registry=self.registry)
            out[name] = gen.run()

        threads = [threading.Thread(target=arm, args=("baseline",
                                                      baseline_port)),
                   threading.Thread(target=arm, args=("candidate",
                                                      canary_port))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return out

    # -- promote helpers ---------------------------------------------------

    def _swap_into_pool(self, entry: dict) -> dict:
        """Complete the rolling swap — the promote 'gate'. The candidate
        already passed preflight-equivalent verification (integrity gate),
        but swap_model re-verifies; a surge that never probes ready rolls
        the swap back and the verdict fails."""
        try:
            result = self.pool.swap_model(entry["dir"])
        except (ValueError, RuntimeError) as e:
            return {"gate": "promote", "ok": False,
                    "reason": f"swap_rejected:{e}",
                    "evidence": {"error": str(e)}}
        if result.get("rolled_back") or not result.get("ok"):
            return {"gate": "promote", "ok": False,
                    "reason": "swap_rolled_back", "evidence": result}
        return {"gate": "promote", "ok": True, "reason": None,
                "evidence": result}

    def _eval_metrics_of(self, entry: dict) -> dict:
        for v in entry["verdicts"]:
            if v["gate"] == "eval" and v["ok"]:
                return dict(v.get("evidence", {}).get("metrics") or {})
        return {}

    # -- audit -------------------------------------------------------------

    def _write_audit(self) -> str:
        """Rewrite the postmortem-style audit: every candidate's gate
        verdicts with evidence pointers, the promoted baseline, and the
        merged fleet-timeline artifact."""
        timeline_path = None
        try:
            timeline_path = self._write_timeline()
        except Exception:
            log.exception("audit timeline merge failed (audit continues)")
        audit = {
            "wall": time.time(),  # wallclock-ok: human timestamp on the audit, never a duration
            "lineage": os.path.join(self.ckpt_dir, self.tag),
            "gates": list(self.gates),
            "promoted": self.state.get("promoted"),
            "candidates": [self.state["candidates"][k] for k in
                           sorted(self.state["candidates"])],
            "state": self.state_path,
            "timeline": timeline_path,
        }
        durable_write_json(self.audit_path, audit)
        return self.audit_path

    def _write_timeline(self) -> Optional[str]:
        path = os.path.join(self.workdir, "timeline.json")
        if self.pool is not None:
            return self.pool.write_timeline(path)
        from ..monitoring import timeline as _timeline

        dirs, extra = [], []
        rec = flight.get_flight_recorder() if flight.active() else None
        if rec is not None:
            if rec.directory is None:
                extra = rec.events()
            else:
                rec.flush()
                dirs.append(rec.directory)
        if not dirs and not extra:
            return None
        return _timeline.write_timeline(path, flight_dirs=dirs,
                                        extra_events=extra,
                                        registry=self.registry)


# -------------------------------------------------------- subprocess mode


def from_config(cfg: dict, registry: Optional[MetricsRegistry] = None):
    """Build ``(controller, pool)`` from a JSON-able config — the
    subprocess/unattended entry. Keys::

        ckpt_dir, tag, workdir                 lineage + durable state
        gates: ["integrity", "eval", "canary"]
        eval_target: "file.py:fn"              fn(gen_dir) -> metrics
        eval_thresholds: {"accuracy": 0.8}
        regression_band: 0.05
        trace: TraceSpec.to_dict()             canary replay recipe
        payload: [[...]]                       replay request payload
        slo: {threshold_ms, target, burn_window_s}
        canary: {ready_timeout_s, latency_ratio, min_availability,
                 burn_excess, for_duration}
        gate_timeout_s, retries, retry_backoff_s
        pool: {target, replicas, extra_env, ...}  ServingPool kwargs
    """
    pool = None
    if cfg.get("pool"):
        from ..serving.pool import ServingPool

        pkw = dict(cfg["pool"])
        target = pkw.pop("target")
        pkw.setdefault("workdir", os.path.join(cfg["workdir"], "pool"))
        pool = ServingPool(target, registry=registry, **pkw).start()
    trace = None
    if cfg.get("trace"):
        from ..serving.loadgen import TraceSpec

        trace = TraceSpec.from_dict(cfg["trace"])
    eval_fn = (_load_callable(cfg["eval_target"])
               if cfg.get("eval_target") else None)
    slo = cfg.get("slo") or {}
    canary = cfg.get("canary") or {}
    rules = None
    if canary:
        rules = default_canary_rules(
            latency_ratio=canary.get("latency_ratio", 2.0),
            min_availability=canary.get("min_availability", 0.95),
            burn_excess=canary.get("burn_excess", 2.0),
            for_duration=canary.get("for_duration", 2))
    ctl = FleetController(
        cfg["ckpt_dir"], pool,
        tag=cfg.get("tag", "latest"),
        workdir=cfg["workdir"],
        gates=cfg.get("gates"),
        eval_fn=eval_fn,
        eval_thresholds=cfg.get("eval_thresholds"),
        regression_band=cfg.get("regression_band", 0.05),
        trace=trace,
        rules=rules,
        payload=cfg.get("payload"),
        n_clients=cfg.get("n_clients", 4),
        slo_threshold_ms=slo.get("threshold_ms", 250.0),
        slo_target=slo.get("target", 0.99),
        burn_window_s=slo.get("burn_window_s", 0.5),
        canary_ready_timeout=canary.get("ready_timeout_s", 60.0),
        gate_timeout_s=cfg.get("gate_timeout_s", 300.0),
        retries=cfg.get("retries", 2),
        retry_backoff_s=cfg.get("retry_backoff_s", 0.2),
        registry=registry)
    return ctl, pool


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="unattended lineage->fleet deployment controller")
    ap.add_argument("config", help="JSON config (see from_config)")
    ap.add_argument("--once", action="store_true",
                    help="process the current committed set and exit")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="watch-mode poll seconds")
    ap.add_argument("--duration", type=float, default=0.0,
                    help="watch-mode wall bound (0 = forever)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    with open(args.config) as f:
        cfg = json.load(f)
    ctl, pool = from_config(cfg)
    try:
        if args.once:
            ctl.run_once()
        else:
            deadline = (time.monotonic() + args.duration
                        if args.duration else None)
            while deadline is None or time.monotonic() < deadline:
                ctl.run_once()
                time.sleep(args.interval)
        ctl._write_audit()
        # the CLI's machine-readable output contract (not a debug print):
        # one JSON summary on stdout, diagnostics stay on logging/stderr
        sys.stdout.write(json.dumps({
            "audit": ctl.audit_path,
            "promoted": ctl.state.get("promoted"),
            "candidates": {k: v["status"] for k, v in
                           ctl.state["candidates"].items()}}) + "\n")
        return 0
    finally:
        ctl.close()
        if pool is not None:
            pool.stop()


if __name__ == "__main__":
    raise SystemExit(main())
