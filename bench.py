"""Benchmark runner — prints ONE JSON line covering all 5 BASELINE configs.

Headline metric (BASELINE.json): ResNet-50 images/sec/chip. The other four
configs (LeNet MNIST TTA, GravesLSTM chars/sec, Word2Vec words/sec, BERT
tokens/sec) ride in the ``configs`` key of the same line.

Every train step is ONE compiled XLA executable; the loops below keep
dispatch async and sync once at the end. The mixed-precision policy
(TDL_MATMUL_PRECISION; see deeplearning4j_tpu/common/precision.py) is
recorded alongside each number per BASELINE.md's measurement protocol.

No reference numbers exist to compare against (BASELINE.json "published" is
empty), so vs_baseline is the ratio against this repo's own previous round,
read from the per-backend BENCH_BASELINE.<backend>.json. A stored baseline is
only comparable when its measurement config (batch / image size / effective
matmul precision) matches the current run (ADVICE r2); an off-config run
reports vs_baseline=1.0 without touching the stored baseline.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import time

import numpy as np

from deeplearning4j_tpu.common.environment import host_cpu_count

_HERE = pathlib.Path(__file__).parent


# ---------------------------------------------------------------- calibration


def calibration_probe():
    """Pinned probe timed alongside every config (VERDICT r3 weak #3): the
    axon tunnel's dispatch/bandwidth swings 3-10x between process windows,
    which made cross-round deltas on latency-sensitive configs
    unfalsifiable. Two fixed reference measurements taken in the SAME window
    as each config let the next round separate code changes from window
    changes:

    - ``probe_ms``: 8-deep 2048^2 bf16 matmul chain (~0.55 TFLOP), compute-
      shaped — scales with the window's achievable device throughput.
    - ``sync_ms``: scalar device fetch — the per-sync round-trip latency.
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def chain(x):
        for _ in range(8):
            x = (x @ x) * 1e-3 + x
        return x

    a = jnp.full((2048, 2048), 0.001, jnp.bfloat16)
    out = chain(a)          # compile
    float(jnp.sum(out[:1, :1]))
    t0 = time.perf_counter()
    n = 5
    for _ in range(n):
        out = chain(out)
    float(jnp.sum(out[:1, :1]))
    probe_ms = (time.perf_counter() - t0) / n * 1e3

    t0 = time.perf_counter()
    float(jnp.asarray(0.0) + 1.0)
    sync_ms = (time.perf_counter() - t0) * 1e3
    return {"probe_ms": round(probe_ms, 2), "sync_ms": round(sync_ms, 2),
            "probe_shape": "8x(2048^2 bf16 matmul)"}


# ------------------------------------------------------- cost observatory


def _roofline_probe():
    """Measured achievable matmul flops/sec in THIS window (ISSUE 10): a
    pinned matmul chain at the effective compute dtype. The utilization a
    config reports is achieved-model-flops over THIS number — a measured
    roofline, so the ratio stays honest across backends and tunnel windows
    (a vendor peak-TFLOPs constant would be fiction on the CPU smoke)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.common.precision import compute_dtype

    n = 1024
    reps = 4

    @jax.jit
    def chain(x):
        for _ in range(reps):
            x = (x @ x) * 1e-3 + x
        return x

    a = jnp.full((n, n), 0.5, compute_dtype())
    chain(a).block_until_ready()  # compile outside the window
    k = 3
    t0 = time.perf_counter()
    for _ in range(k):
        a = chain(a)
    a.block_until_ready()
    dt = time.perf_counter() - t0
    return 2.0 * n ** 3 * reps * k / dt


def _utilization(flops_per_step, steps, window_s, roofline):
    achieved = flops_per_step * steps / window_s if window_s > 0 else 0.0
    return {"achieved_gflops_per_sec": round(achieved / 1e9, 2),
            "roofline_gflops_per_sec": round(roofline / 1e9, 2),
            "utilization": round(achieved / roofline, 4) if roofline else None}


def _trim_table(table, top=12):
    """Bench-JSON-sized view of a cost table: the top-N layers by flops plus
    one 'others' roll-up row (ResNet-50 has ~120 rows; the gauges carry the
    full set, the JSON line stays readable)."""
    layers = sorted(table["layers"], key=lambda r: -r["flops"])
    if len(layers) > top:
        rest = layers[top:]
        layers = layers[:top] + [{
            "layer": f"(+{len(rest)} more)", "kind": "others",
            "flops": sum(r["flops"] for r in rest),
            "param_bytes": sum(r["param_bytes"] for r in rest),
            "activation_bytes": sum(r["activation_bytes"] for r in rest),
            "pct": round(sum(r["pct"] for r in rest), 2)}]
    return {**table, "layers": layers}


# ----------------------------------------------------------- step attribution


def _phase_recorder():
    """Per-step phase breakdown (ISSUE 7 layer 3) on the PROCESS registry, so
    the `tdl_step_phase_seconds` histograms ride the telemetry block and the
    per-variant percentage tables come from the same observations."""
    from deeplearning4j_tpu.monitoring import StepPhaseRecorder

    return StepPhaseRecorder()


# --------------------------------------------------------------------- config


def _scale(on_tpu):
    """(resnet, lenet, lstm, w2v, bert) shape params; small on CPU smoke."""
    if on_tpu:
        return {
            # steps=40: one ~200ms tunnel sync amortizes to ~5ms/step noise
            "resnet50": dict(batch=256, hw=224, classes=1000, steps=40, warmup=3, pipeline_steps=3),
            "lenet": dict(batch=128, examples=12800, target_acc=0.95, max_epochs=12),
            "lstm": dict(batch=64, vocab=77, seqlen=200, tbptt=50, steps=30, warmup=3),
            "w2v": dict(sent=20000, layer=100, batch=16384),
            # steps=40: the ~0.6s tunnel sync amortizes to ~15ms/step noise at
            # steps=10 — measured r5, same amortization rationale as resnet
            "bert": dict(batch=16, seq=128, steps=40, warmup=3, tiny=False),
            "serving": dict(clients=16, requests=320, batch_limit=16,
                            features=64, classes=8, queue=256),
            "serving_slo": dict(duration_s=20.0, base_rate=120.0, clients=32,
                                burst_mult=10.0, batch_limit=16, features=64,
                                classes=8, queue=256, slo_threshold_ms=250.0,
                                slo_target=0.99),
            "bert_large_fsdp": dict(batch=8, seq=128, steps=8, warmup=2,
                                    large=True, tp=1),
            "pipeline_parallel": dict(stages=4, layers=12, seq=128,
                                      microbatch=4, m1=4, m2=8, steps=8,
                                      warmup=2, fwd_repeats=5,
                                      force_devices=4),
            "serving_pool": dict(slots=8, duration_s=12.0, base_rate=60.0,
                                 burst_mult=10.0, max_new=16, clients=48,
                                 max_new_mix=(4, 8, 16, 48),
                                 d_model=256, n_layers=4, n_heads=8,
                                 d_ff=1024, vocab=8192, max_len=256,
                                 queue=256, replicas=2,
                                 pool_duration_s=8.0, pool_rate=30.0,
                                 slo_threshold_ms=1000.0, slo_target=0.99),
            "reshard": dict(features=64, hidden=512, classes=8, steps=4,
                            replicas=2),
            "ckpt_lineage": dict(features=256, hidden=2048, classes=32,
                                 steps=3, saves=4),
            # gangs run platform="cpu" regardless of backend: the sweep
            # prices fleet orchestration, not device math
            "hpo": dict(trials=8, rungs=(4, 8), concurrent=4, seed=7,
                        resume_trials=3, etl_images=48, etl_iters=3),
            "deploy": dict(features=256, hidden=2048, classes=32, steps=3,
                           canary_requests=2000),
            "compile_cache": dict(features=64, classes=8, batch_limit=16,
                                  max_rows=128, fit_batch=128, fit_steps=4,
                                  flash=dict(B=1, H=12, T=8192, D=64,
                                             trials=3)),
            "trace_overhead": dict(clients=8, requests_per_round=320,
                                   rounds=3, batch_limit=16, features=64,
                                   classes=8, queue=256, train_steps=30,
                                   train_batch=256, train_features=256,
                                   train_hidden=512),
            # few requests x long generations packed into a burst: the
            # replay measures decode DRAIN speed, not the arrival schedule
            "paged_decode": dict(d_model=256, n_layers=6, n_heads=8,
                                 d_ff=1024, vocab=8192, max_len=512,
                                 block_T=32, slots_dense=4, paged_slots=32,
                                 short_len=40, cap_prefix_len=224,
                                 cap_suffix_len=16, cap_max_new=16,
                                 max_new=384, draft_layers=1, spec_tokens=5,
                                 duration_s=0.3, base_rate=110.0, clients=32,
                                 prefix_tenants=4, prefix_len=96,
                                 suffix_len=16, queue=512),
        }
    return {
        "resnet50": dict(batch=8, hw=64, classes=10, steps=5, warmup=2, pipeline_steps=3),
        "lenet": dict(batch=64, examples=1280, target_acc=0.90, max_epochs=6),
        "lstm": dict(batch=8, vocab=32, seqlen=100, tbptt=50, steps=3, warmup=1),
        "w2v": dict(sent=400, layer=32, batch=2048),
        "bert": dict(batch=2, seq=64, steps=3, warmup=1, tiny=True),
        "serving": dict(clients=4, requests=80, batch_limit=8,
                        features=16, classes=4, queue=64),
        "serving_slo": dict(duration_s=6.0, base_rate=40.0, clients=8,
                            burst_mult=6.0, batch_limit=8, features=16,
                            classes=4, queue=64, slo_threshold_ms=250.0,
                            slo_target=0.99),
        "bert_large_fsdp": dict(batch=2, seq=64, steps=2, warmup=1,
                                large=False, tp=1),
        "pipeline_parallel": dict(stages=2, layers=6, seq=32, microbatch=2,
                                  m1=4, m2=8, steps=2, warmup=1,
                                  fwd_repeats=3, force_devices=4),
        "serving_pool": dict(slots=4, duration_s=5.0, base_rate=24.0,
                             burst_mult=6.0, max_new=8, clients=24,
                             max_new_mix=(2, 4, 8, 24),
                             d_model=64, n_layers=2, n_heads=4, d_ff=128,
                             vocab=256, max_len=64, queue=128, replicas=2,
                             pool_duration_s=4.0, pool_rate=12.0,
                             slo_threshold_ms=2000.0, slo_target=0.95),
        "reshard": dict(features=16, hidden=32, classes=4, steps=2,
                        replicas=2),
        "ckpt_lineage": dict(features=32, hidden=256, classes=8, steps=2,
                             saves=3),
        "hpo": dict(trials=4, rungs=(2, 4), concurrent=4, seed=7,
                    resume_trials=3, etl_images=32, etl_iters=2),
        "deploy": dict(features=32, hidden=256, classes=8, steps=2,
                       canary_requests=400),
        "compile_cache": dict(features=16, classes=4, batch_limit=8,
                              max_rows=32, fit_batch=32, fit_steps=2,
                              flash=dict(B=1, H=2, T=128, D=16, trials=1)),
        "trace_overhead": dict(clients=4, requests_per_round=80, rounds=2,
                               batch_limit=8, features=16, classes=4,
                               queue=64, train_steps=6, train_batch=32,
                               train_features=32, train_hidden=64),
        # few requests x long generations packed into a burst: the replay
        # measures decode DRAIN speed, not the arrival schedule or prefill
        "paged_decode": dict(d_model=64, n_layers=6, n_heads=4, d_ff=128,
                             vocab=256, max_len=256, block_T=16,
                             slots_dense=2, paged_slots=16,
                             short_len=24, cap_prefix_len=112,
                             cap_suffix_len=8, cap_max_new=8, max_new=192,
                             draft_layers=1, spec_tokens=7,
                             duration_s=0.2, base_rate=60.0, clients=16,
                             prefix_tenants=2, prefix_len=48, suffix_len=8,
                             queue=256),
    }


# ------------------------------------------------------------------ resnet-50


def bench_resnet50(p):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models import ResNet50

    batch, hw, classes = p["batch"], p["hw"], p["classes"]
    net = ResNet50(num_classes=classes, input_shape=(3, hw, hw)).init()
    step = net._train_step_fn()

    rs = np.random.RandomState(0)
    x = {"input": jnp.asarray(rs.rand(batch, 3, hw, hw).astype(np.float32))}
    y = {"output": jnp.asarray(np.eye(classes, dtype=np.float32)[rs.randint(0, classes, batch)])}
    rng = jax.random.key(0)
    it = jnp.asarray(0, jnp.int32)
    ep = jnp.asarray(0, jnp.int32)

    params, opt, bn = net.params_, net.updater_state, net.bn_state
    for _ in range(p["warmup"]):
        params, opt, bn, loss = step(params, opt, bn, it, ep, x, y, None, rng)
    float(loss)  # device fetch = true sync (drains the axon tunnel queue)

    phases = _phase_recorder()
    t0 = time.perf_counter()
    for _ in range(p["steps"]):
        with phases.phase("compute"):
            params, opt, bn, loss = step(params, opt, bn, it, ep, x, y, None, rng)
        phases.step_done()
    float(loss)
    dt = time.perf_counter() - t0
    out = {"metric": "resnet50_train_images_per_sec",
           "value": round(batch * p["steps"] / dt, 2),
           "unit": "images/sec/chip", "batch": batch, "image_size": hw}

    # ISSUE 10: per-layer cost attribution + achieved-vs-roofline. Estimator
    # only — re-lowering ResNet-50 for cost_analysis would double the
    # config's compile bill; LeNet/BERT carry the XLA-validated tables
    from deeplearning4j_tpu.monitoring import costmodel

    table = costmodel.publish("resnet50", costmodel.layer_costs(net, batch))
    out["cost"] = {**_trim_table(table),
                   **_utilization(table["total_flops"], p["steps"], dt,
                                  _roofline_probe())}

    # real-input-pipeline variant (SURVEY §2.3 D3 / VERDICT r2 missing #3):
    # JPEGs on disk → ImageRecordReader decode+augment → async prefetch;
    # proves ETL doesn't bottleneck the step (target ≥90% of synthetic)
    pipe_steps = p.get("pipeline_steps", 0)
    if pipe_steps:
        out["pipeline"] = _resnet_pipeline_variant(
            p, step, params, opt, bn, rng, out["value"], pipe_steps)
    return out


def _pad_labels_iter(base, classes, n_cls):
    """Pad dir-derived one-hot labels out to the model's class count ON THE
    HOST, before device staging — doing it consumer-side would read a device-
    resident label array back to host every step (the d2h→h2d round trip the
    device pipeline exists to remove)."""
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import DataSetIterator

    class _Pad(DataSetIterator):
        def has_next(self):
            return base.has_next()

        def reset(self):
            base.reset()

        def batch(self):
            return base.batch()

        def next(self):
            ds = base.next()
            y = np.zeros((ds.features.shape[0], classes), np.float32)
            y[:, :n_cls] = ds.labels[:, :min(n_cls, classes)]
            return DataSet(ds.features, y)

    return _Pad()


def _make_u8_step(step, ingest):
    """Fuse the on-device ingest (uint8 NHWC wire → f32 NCHW normalized) in
    front of the synthetic train step — ONE executable, normalization runs
    next to the matmuls."""
    import jax

    def step_u8(params, opt, bn, it, ep, xu8, y, rng):
        return step(params, opt, bn, it, ep, {"input": ingest(xu8)},
                    {"output": y}, None, rng)

    return jax.jit(step_u8, donate_argnums=(0, 1, 2))


def _resnet_pipeline_variant(p, step, params, opt, bn, rng, synthetic_ips, steps):
    """Device-resident JPEG path (ISSUE 4): decode+augment host-side on the
    persistent thread pool, uint8 NHWC over the wire (4x fewer h2d bytes),
    DevicePrefetchIterator staging the next batches to HBM while the current
    step runs, cast/scale/NCHW fused into the compiled step."""
    import shutil
    import tempfile

    import jax.numpy as jnp
    from PIL import Image

    from deeplearning4j_tpu.data import (
        DevicePrefetchIterator,
        FlipImageTransform,
        ImagePreProcessingScaler,
        ImageRecordReader,
        ImageRecordReaderDataSetIterator,
        ParentPathLabelGenerator,
        PipelineImageTransform,
        RandomCropTransform,
        make_device_ingest,
    )
    from deeplearning4j_tpu.data.records import FileSplit
    from deeplearning4j_tpu.monitoring import MetricsRegistry

    batch, hw, classes = p["batch"], p["hw"], p["classes"]
    n_images = batch * (steps + 1)
    tmp = tempfile.mkdtemp(prefix="bench_imgs_")
    try:
        rs = np.random.RandomState(0)
        src = hw + 32
        for i in range(n_images):
            cls = i % min(classes, 16)
            d = os.path.join(tmp, f"c{cls:03d}")
            os.makedirs(d, exist_ok=True)
            Image.fromarray(rs.randint(0, 255, (src, src, 3), dtype=np.uint8)).save(
                os.path.join(d, f"i{i}.jpg"), quality=85)
        chain = PipelineImageTransform([
            RandomCropTransform(hw, hw), FlipImageTransform(1)])
        rr = ImageRecordReader(hw, hw, 3, ParentPathLabelGenerator(),
                               transform=chain, uint8_wire=True)
        rr.initialize(FileSplit(tmp))
        n_cls = rr.num_labels()
        it_j = jnp.asarray(0, jnp.int32)
        ep_j = jnp.asarray(0, jnp.int32)
        # fresh registry: per-variant h2d/input-wait numbers (the process
        # registry would mix this variant's counters with the cached one's)
        data = DevicePrefetchIterator(
            _pad_labels_iter(ImageRecordReaderDataSetIterator(
                rr, batch, num_workers=min(16, host_cpu_count())),
                classes, n_cls),
            buffer_size=3, registry=MetricsRegistry())
        jstep = _make_u8_step(step, make_device_ingest(
            ImagePreProcessingScaler(), source_layout="NHWC"))
        done = 0
        t0 = None
        phases = _phase_recorder()
        while data.has_next() and done <= steps:
            with phases.phase("input"):
                ds = data.next()  # already device-resident uint8 NHWC
            if ds.features.shape[0] < batch:
                break
            with phases.phase("compute"):
                params, opt, bn, loss = jstep(params, opt, bn, it_j, ep_j,
                                              ds.features, ds.labels, rng)
            done += 1
            if t0 is None:  # first batch is warmup (compile + queue fill):
                # discard its phases entirely — observing the compile outlier
                # would skew the exported tdl_step_phase_seconds histogram
                phases.discard()
                float(loss)
                t0 = time.perf_counter()
            else:
                phases.step_done()
        float(loss)
        dt = time.perf_counter() - t0
        ips = batch * (done - 1) / dt
        pipe_stats = data.stats()
        data.reset()  # stop the worker + release the staged HBM batches
        jpeg = {"images_per_sec": round(ips, 2),
                # ISSUE 7 layer 3: where does a step's wall actually go —
                # input (blocked on the prefetcher), compute (step dispatch),
                # h2d/collective (≈0 here: staging overlaps worker-side,
                # single chip). Percentages of measured step wall, ~100 total
                "phases": phases.summary(),
                "vs_synthetic": round(ips / synthetic_ips, 3), "steps": done - 1,
                # JPEG decode is host-CPU-bound (~3ms/core/image at 224²):
                # the AFFINITY core count (not os.cpu_count — a cgroup-
                # limited host has fewer) is the ceiling for THIS path; the
                # cached + multi-process etl paths below are the answer on
                # small hosts
                "host_cpus": host_cpu_count(),
                # h2d MB/s measured on the real staged batches + consumer
                # input-wait per step (≈0 when prefetch keeps the chip fed)
                **pipe_stats}
        # each variant's steps DONATE the state buffers — thread the live
        # (params, opt, bn) from one variant into the next
        cached, params, opt, bn = _resnet_pipeline_cached(
            p, jstep, params, opt, bn, rng, synthetic_ips, steps, tmp)
        etl = _resnet_pipeline_etl(
            p, jstep, params, opt, bn, rng, synthetic_ips, steps, tmp)
        return {**jpeg, "cached": cached, "etl": etl}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _resnet_pipeline_cached(p, jstep, params, opt, bn, rng, synthetic_ips,
                            steps, img_dir):
    """Pre-decoded uint8 cache path (VERDICT r3 #3): decode once → memmap →
    vectorized crop/flip on the fly → uint8 NHWC staged to device by the
    prefetcher, cast/scale/NCHW on-chip. Proves the ETL overlap machinery on
    a 1-core host. ``jstep`` is the jpeg variant's already-compiled
    uint8-ingest step — a fresh `_make_u8_step` closure here would miss
    jax's jit cache and retrace ResNet-50 a second time."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.data import (
        CachedImageDataSetIterator,
        DevicePrefetchIterator,
        PreDecodedImageCache,
    )
    from deeplearning4j_tpu.data.records import FileSplit
    from deeplearning4j_tpu.monitoring import MetricsRegistry

    batch, hw, classes = p["batch"], p["hw"], p["classes"]
    t0 = time.perf_counter()
    cache = PreDecodedImageCache(os.path.join(img_dir, "_u8cache"),
                                 (hw + 32, hw + 32)).build(
        FileSplit(img_dir), num_workers=min(16, host_cpu_count()))
    build_s = time.perf_counter() - t0
    n_cls = cache.num_labels()

    data = DevicePrefetchIterator(
        _pad_labels_iter(CachedImageDataSetIterator(
            cache, batch, crop=(hw, hw), dtype=np.uint8), classes, n_cls),
        buffer_size=3, registry=MetricsRegistry())
    it_j = jnp.asarray(0, jnp.int32)
    ep_j = jnp.asarray(0, jnp.int32)
    done = 0
    t0 = None
    loss = None
    phases = _phase_recorder()
    while done <= steps:
        if not data.has_next():
            data.reset()
        with phases.phase("input"):
            ds = data.next()
        if ds.features.shape[0] < batch:
            continue
        with phases.phase("compute"):
            params, opt, bn, loss = jstep(params, opt, bn, it_j, ep_j,
                                          ds.features, ds.labels, rng)
        done += 1
        if t0 is None:  # first batch warms compile + queue: discard its
            # phases (the compile outlier must not skew the histogram)
            phases.discard()
            float(loss)
            t0 = time.perf_counter()
        else:
            phases.step_done()
    float(loss)
    dt = time.perf_counter() - t0
    ips = batch * (done - 1) / dt
    pipe_stats = data.stats()
    data.reset()  # stop the worker + release the staged HBM batches

    # host-only ETL rate (no device): proves whether the input machinery or
    # the host→device link is the binding constraint
    host_it = CachedImageDataSetIterator(cache, batch, crop=(hw, hw), dtype=np.uint8)
    list(host_it)  # warm page cache
    t0 = time.perf_counter()
    cnt = 0
    for _ in range(2):
        host_it.reset()
        for ds in host_it:
            cnt += ds.features.shape[0]
    host_ips = cnt / (time.perf_counter() - t0)

    # raw H2D bandwidth of one uint8 batch through whatever link exists
    # (PCIe on a real host; the axon tunnel here). Warm both the transfer
    # and block_until_ready so the timed window holds only the copy — a
    # compile or sync round trip in-window would bias the number low.
    blob = np.zeros((batch, hw, hw, 3), np.uint8)
    blob2 = np.ones_like(blob)  # distinct buffer: defeats transfer caching
    jnp.asarray(blob).block_until_ready()
    t0 = time.perf_counter()
    jnp.asarray(blob2).block_until_ready()
    h2d_s = time.perf_counter() - t0
    h2d_mbps = blob.nbytes / 1e6 / h2d_s

    return ({"images_per_sec": round(ips, 2),
             "vs_synthetic": round(ips / synthetic_ips, 3),
             "phases": phases.summary(),
             "steps": done - 1, "cache_build_s": round(build_s, 2),
             "host_etl_images_per_sec": round(host_ips, 1),
             "host_etl_vs_synthetic": round(host_ips / synthetic_ips, 3),
             # measured on the real staged batches (stats) + the isolated
             # single-blob probe, to tell pipeline overhead from raw link b/w
             **pipe_stats,
             "h2d_probe_MBps": round(h2d_mbps, 1)},
            params, opt, bn)  # live post-donation state for the next variant


def _resnet_pipeline_etl(p, jstep, params, opt, bn, rng, synthetic_ips,
                         steps, img_dir):
    """Multi-process sharded ETL path (ISSUE 6): N worker PROCESSES decode/
    augment into a shared-memory ring (true host parallelism past the GIL),
    zero-copy views staged to device by the prefetcher, decoded-batch cache
    making epoch ≥2 decode-free. Reports the worker-count SCALING CURVE
    (host-only consumption rate per worker count, steady-state = cache-warm)
    plus the full train-loop throughput at the largest worker count."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.data import (
        DevicePrefetchIterator,
        EtlDataSetIterator,
        ImageEtlSpec,
    )
    from deeplearning4j_tpu.monitoring import MetricsRegistry

    batch, hw, classes = p["batch"], p["hw"], p["classes"]
    spec = ImageEtlSpec.from_directory(
        img_dir, hw, hw, batch_size=batch, num_classes=classes,
        store_pad=32, cache_dir=os.path.join(img_dir, "_etlcache"))

    from deeplearning4j_tpu.monitoring import get_registry

    def host_rate(workers, epochs=2):
        it = EtlDataSetIterator(spec, num_workers=workers,
                                registry=MetricsRegistry())
        try:
            for _ in it:  # warmup epoch: spawn amortized, cache populated
                continue
            t0 = time.perf_counter()
            n = 0
            for _ in range(epochs):
                it.reset()
                while it.has_next():
                    n += it.next().features.shape[0]
            return n / (time.perf_counter() - t0)
        finally:
            it.close()

    host = host_cpu_count()
    curve = [{"workers": w, "host_images_per_sec": round(host_rate(w), 1)}
             for w in sorted({1, 2, 4, host})]

    # full stack at the largest worker count: decode → ring → device_put →
    # fused uint8 ingest train step. PROCESS registry on purpose (unlike the
    # per-variant fresh registries above): this variant is what makes the
    # tdl_h2d_*/tdl_etl_*/prefetch families show up in the telemetry block,
    # so --check-telemetry can prove they're alive end to end
    w_max = curve[-1]["workers"]
    data = DevicePrefetchIterator(
        EtlDataSetIterator(spec, num_workers=w_max, registry=get_registry()),
        buffer_size=3, registry=get_registry())
    it_j = jnp.asarray(0, jnp.int32)
    ep_j = jnp.asarray(0, jnp.int32)
    done = 0
    t0 = None
    loss = None
    phases = _phase_recorder()
    try:
        while done <= steps:
            if not data.has_next():
                data.reset()
            with phases.phase("input"):
                ds = data.next()
            with phases.phase("compute"):
                params, opt, bn, loss = jstep(params, opt, bn, it_j, ep_j,
                                              ds.features, ds.labels, rng)
            done += 1
            if t0 is None:  # first batch warms compile + ring fill: discard
                # its phases (the compile outlier must not skew the histogram)
                phases.discard()
                float(loss)
                t0 = time.perf_counter()
            else:
                phases.step_done()
        float(loss)
        dt = time.perf_counter() - t0
        pipe_stats = data.stats()  # includes the merged etl_* counters
    finally:
        data.close()
    ips = batch * (done - 1) / dt
    return {"workers_curve": curve, "workers": w_max,
            "images_per_sec": round(ips, 2),
            "vs_synthetic": round(ips / synthetic_ips, 3),
            "phases": phases.summary(),
            "steps": done - 1, **pipe_stats}


# --------------------------------------------------------------- lenet (TTA)


def _lenet_cost(net, batch):
    """ISSUE 10: per-layer cost table for LeNet joined against XLA
    cost_analysis of the compiled train step, plus the live-HBM breakdown —
    publishes tdl_model_flops_per_step / tdl_hbm_peak_bytes /
    tdl_layer_cost_info / tdl_hbm_bytes on the process registry."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.monitoring import costmodel

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(batch, 1, 28, 28).astype(np.float32))
    y = jnp.asarray(np.eye(10, dtype=np.float32)[rs.randint(0, 10, batch)])
    xla = costmodel.xla_step_cost(
        net._train_step_fn(), net.params_, net.updater_state, net.bn_state,
        jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32), x, y,
        None, None, jax.random.key(0))
    table = costmodel.publish("lenet", costmodel.layer_costs(net, batch), xla)
    table["hbm"] = costmodel.net_hbm_breakdown(net, model="lenet")
    return table


def bench_lenet(p):
    from deeplearning4j_tpu.data.datasets import MnistDataSetIterator
    from deeplearning4j_tpu.models import LeNet

    net = LeNet(num_classes=10).init()
    train_it = MnistDataSetIterator(p["batch"], train=True, num_examples=p["examples"])
    test_it = MnistDataSetIterator(256, train=False, num_examples=min(2560, p["examples"]))

    t0 = time.perf_counter()
    tta = None
    images = 0
    train_time = 0.0  # ADVICE r2: exclude evaluate() from the throughput denominator
    for epoch in range(p["max_epochs"]):
        train_it.reset()
        te = time.perf_counter()
        for ds in train_it:
            net.fit(ds)
            images += ds.features.shape[0]
        train_time += time.perf_counter() - te
        test_it.reset()
        acc = net.evaluate(test_it).accuracy()
        if acc >= p["target_acc"]:
            tta = time.perf_counter() - t0
            break
    return {"metric": "lenet_mnist_time_to_accuracy",
            "value": round(tta, 2) if tta is not None else None,  # null = not reached (valid JSON)
            "unit": f"sec_to_{p['target_acc']:.0%}_acc",
            "reached": tta is not None, "final_acc": round(float(acc), 4),
            "synthetic": bool(getattr(train_it, "synthetic", False)),
            "images_per_sec": round(images / train_time, 1),
            # ISSUE 10: where the step's flops/bytes go, validated against
            # XLA's own count of the compiled executable ("coverage")
            "cost": _lenet_cost(net, p["batch"])}


# -------------------------------------------------------- graveslstm char-rnn


def bench_lstm(p):
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.models import TextGenerationLSTM
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    B, V, T = p["batch"], p["vocab"], p["seqlen"]
    net = MultiLayerNetwork(TextGenerationLSTM(vocab_size=V, tbptt_length=p["tbptt"]).conf()).init()
    rs = np.random.RandomState(0)
    idx = rs.randint(0, V, (B, T))
    x = np.eye(V, dtype=np.float32)[idx].transpose(0, 2, 1)  # [B,V,T]
    y = np.eye(V, dtype=np.float32)[np.roll(idx, -1, 1)].transpose(0, 2, 1)

    import jax
    import jax.numpy as jnp

    # device-resident batch: re-uploading ~8MB per fit through the tunnel
    # costs ~0.5s (12-25 MB/s H2D) and was 3x the step itself — the r2-r4
    # "stagnant LSTM" was a bench artifact, not the model (r5 finding)
    xd, yd = jnp.asarray(x), jnp.asarray(y)
    jax.block_until_ready((xd, yd))
    ds = DataSet(xd, yd)

    def _sync():
        # block_until_ready does NOT drain the axon tunnel; a scalar fetch does
        return float(jax.tree.leaves(net.params_)[0].ravel()[0])

    for _ in range(p["warmup"]):
        net.fit(ds)
    _sync()
    t0 = time.perf_counter()
    for _ in range(p["steps"]):
        net.fit(ds)
    _sync()
    dt = time.perf_counter() - t0
    return {"metric": "graveslstm_chars_per_sec",
            "value": round(B * T * p["steps"] / dt, 1),
            "unit": "chars/sec", "batch": B, "seqlen": T, "tbptt": p["tbptt"]}


# ------------------------------------------------------------------- word2vec


def bench_w2v(p):
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    rs = np.random.RandomState(0)
    vocab = [f"w{i}" for i in range(2000)]
    zipf = 1.0 / np.arange(1, len(vocab) + 1)
    zipf /= zipf.sum()
    sentences = [" ".join(rs.choice(vocab, size=rs.randint(8, 20), p=zipf))
                 for _ in range(p["sent"])]
    total_words = sum(len(s.split()) for s in sentences)

    w2v = Word2Vec(layer_size=p["layer"], window=5, negative=5, epochs=1,
                   batch_size=p.get("batch", 1024))
    # warmup fit compiles the step executables (same vocab + static batch →
    # cache hit on the timed fit); steady-state throughput is the metric
    w2v.fit(sentences)
    t0 = time.perf_counter()
    w2v.fit(sentences)
    dt = time.perf_counter() - t0
    return {"metric": "word2vec_words_per_sec",
            "value": round(total_words / dt, 1), "unit": "words/sec",
            "corpus_words": total_words, "layer_size": p["layer"],
            "batch_size": p.get("batch", 1024)}


# ----------------------------------------------------------------- bert mlm


def bench_bert(p):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.transformer import TransformerConfig, init_params, make_train_step
    from deeplearning4j_tpu.nn.updaters import Adam

    B, T = p["batch"], p["seq"]
    cfg = (TransformerConfig.tiny(dropout=0.0) if p["tiny"]
           else TransformerConfig.bert_base(max_len=T, dropout=0.0))
    params = init_params(jax.random.key(0), cfg)
    updater = Adam(1e-4)
    opt = updater.init(params)
    step = jax.jit(make_train_step(cfg, updater), donate_argnums=(0, 1))

    rs = np.random.RandomState(0)
    # TF-BERT pretraining layout: the MLM head runs only at masked_lm_positions
    # (~15% of T) — the D×V tied decoder is the step's biggest matmul, so the
    # gather cuts it ~T/P× (VERDICT r4 weak #3 attack, with the bf16+fp32-acc
    # projection in models/transformer.mlm_head).
    P = max(1, int(T * 0.15))
    positions = np.stack([np.sort(rs.choice(T, P, replace=False)) for _ in range(B)])
    batch = {
        "tokens": jnp.asarray(rs.randint(0, cfg.vocab_size, (B, T)), jnp.int32),
        "mlm_positions": jnp.asarray(positions, jnp.int32),
        "labels": jnp.asarray(rs.randint(0, cfg.vocab_size, (B, P)), jnp.int32),
        "weights": jnp.ones((B, P), jnp.float32),
    }
    rng = jax.random.key(1)
    it = jnp.asarray(0, jnp.int32)

    def timed(run, b):
        """ONE measurement protocol for all three variants: warmup runs,
        true-sync, timed window, true-sync. ``run(b)`` advances its own
        captured state and returns the step loss."""
        for _ in range(p["warmup"]):
            loss = run(b)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(p["steps"]):
            loss = run(b)
        float(loss)
        return time.perf_counter() - t0

    state = {"params": params, "opt": opt}
    del params, opt  # donated into the step from here on — no other refs

    def run_mlm(b):
        state["params"], state["opt"], loss = step(state["params"],
                                                   state["opt"], b, it, rng)
        return loss

    dt = timed(run_mlm, batch)

    # ISSUE 10: the functional transformer's cost table, validated against
    # XLA cost_analysis of the compiled MLM step, + roofline utilization of
    # the timed window above
    from deeplearning4j_tpu.models.transformer import layer_costs
    from deeplearning4j_tpu.monitoring import costmodel

    xla_cost = costmodel.xla_step_cost(step, state["params"], state["opt"],
                                       batch, it, rng)
    cost = costmodel.publish("transformer",
                             layer_costs(cfg, B, T, mlm_positions=P), xla_cost)
    cost.update(_utilization(xla_cost["flops"] or cost["total_flops"],
                             p["steps"], dt, _roofline_probe()))

    # masked variant: padding mask present → the Pallas masked-flash path
    # (r4 silently fell back to the O(T^2) dense path under any mask)
    pad = np.ones((B, T), np.float32)
    pad[:, int(T * 0.9):] = 0.0
    dt_masked = timed(run_mlm, {**batch, "pad_mask": jnp.asarray(pad)})

    # SQuAD fine-tune variant — BASELINE configs[4] names the fine-tune
    # workload specifically ("BERT-base fine-tune via SameDiff TF-import
    # (SQuAD)"): span head over the full encoder, masked batch
    from deeplearning4j_tpu.models.transformer import (
        init_qa_head, make_qa_train_step)

    qa_step = jax.jit(make_qa_train_step(cfg, updater),
                      donate_argnums=(0, 1, 2, 3))
    qa_batch = {
        "tokens": batch["tokens"],
        "segments": jnp.asarray((np.arange(T)[None] >= T // 4)
                                .repeat(B, 0).astype(np.int32)),
        "pad_mask": jnp.asarray(pad),
        "start_positions": jnp.asarray(rs.randint(0, T, B), jnp.int32),
        "end_positions": jnp.asarray(rs.randint(0, T, B), jnp.int32),
    }
    # the MLM-trained encoder + its opt state move into the QA step (their
    # buffers get donated there; `state` is emptied to make that explicit)
    qa_params = init_qa_head(jax.random.key(2), cfg)
    qs = {"p": state.pop("params"), "qa": qa_params,
          "o": state.pop("opt"), "qo": updater.init(qa_params)}

    def run_qa(b):
        qs["p"], qs["qa"], qs["o"], qs["qo"], loss = qa_step(
            qs["p"], qs["qa"], qs["o"], qs["qo"], b, it, rng)
        return loss

    dt_squad = timed(run_qa, qa_batch)
    return {"metric": "bert_mlm_tokens_per_sec",
            "value": round(B * T * p["steps"] / dt, 1), "unit": "tokens/sec/chip",
            "batch": B, "seq": T, "mlm_positions": P,
            "masked_tokens_per_sec": round(B * T * p["steps"] / dt_masked, 1),
            "squad_finetune_tokens_per_sec": round(B * T * p["steps"] / dt_squad, 1),
            "model": "tiny" if p["tiny"] else "bert-base",
            "cost": _trim_table(cost)}


# ------------------------------------------------- multichip: fsdp x tp bert


def bench_fsdp(p):
    """ISSUE 9 multichip section: BERT trained with SHARDED parameters — a
    data=1 × fsdp×tp SpecLayout over every visible device, optimizer state
    sharded with the params, (params, opt) donated through the fused step.
    Reports per-rank param/opt shard bytes next to throughput, and records
    whether the replicated equivalent would fit one chip's HBM (on hardware
    it OOMs for bert-large; the skip reason is part of the result — honest
    models-bigger-than-one-HBM evidence, not a silent omission)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.common import jax_compat
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       init_params,
                                                       make_train_step)
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.parallel.partition import Partitioner, SpecLayout
    from deeplearning4j_tpu.parallel.sharding import batch_sharding

    B, T = p["batch"], p["seq"]
    cfg = (TransformerConfig.bert_large(max_len=T, dropout=0.0) if p["large"]
           else TransformerConfig.tiny(max_len=T, dropout=0.0))
    n_dev = len(jax.devices())
    tp = p["tp"] if n_dev % max(p["tp"], 1) == 0 else 1
    layout = SpecLayout(data=1, fsdp=-1, tp=tp)
    partitioner = Partitioner(layout)
    mesh = partitioner.mesh

    updater = Adam(1e-4)
    params = init_params(jax.random.key(0), cfg)
    opt = updater.init(params)
    specs = partitioner.spec_tree(params)
    params = partitioner.place(params, specs)
    opt = partitioner.shard_state_like(opt, specs)
    # publishes tdl_param_bytes_per_rank{kind} + tdl_mesh_layout_info
    report = partitioner.report(params, opt, specs)

    step = jax.jit(make_train_step(cfg, updater), donate_argnums=(0, 1))
    rs = np.random.RandomState(0)
    npos = max(1, int(T * 0.15))
    positions = np.stack([np.sort(rs.choice(T, npos, replace=False))
                          for _ in range(B)])
    bshard = batch_sharding(mesh)  # data axis (size 1 here) — replicated
    batch = {
        "tokens": jax.device_put(
            rs.randint(0, cfg.vocab_size, (B, T)).astype(np.int32), bshard),
        "mlm_positions": jax.device_put(positions.astype(np.int32), bshard),
        "labels": jax.device_put(
            rs.randint(0, cfg.vocab_size, (B, npos)).astype(np.int32), bshard),
        "weights": jax.device_put(np.ones((B, npos), np.float32), bshard),
    }
    rng = jax.random.key(1)
    it = jnp.asarray(0, jnp.int32)

    state = {"p": params, "o": opt}
    del params, opt  # donated into the step from here on

    with jax_compat.set_mesh(mesh):
        for _ in range(p["warmup"]):
            state["p"], state["o"], loss = step(state["p"], state["o"],
                                                batch, it, rng)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(p["steps"]):
            state["p"], state["o"], loss = step(state["p"], state["o"],
                                                batch, it, rng)
        float(loss)
        dt = time.perf_counter() - t0

    # would the replicated config even fit? params + Adam m/v = 3x param
    # bytes per chip BEFORE activations/grads — compare against the
    # device-reported HBM limit when there is one
    need = 3 * report.params_bytes_total
    stats = getattr(jax.devices()[0], "memory_stats", lambda: None)()
    limit = (stats or {}).get("bytes_limit")
    if limit is None:
        replicated = {"skipped": "no device memory limit reported (cpu "
                                 "smoke) — nothing to OOM against"}
    elif need > 0.5 * limit:
        replicated = {"skipped": f"replicated params+opt need ~{need/2**30:.2f}"
                                 f" GiB/chip vs {limit/2**30:.2f} GiB HBM "
                                 "limit — OOMs where the sharded layout trains"}
    else:
        replicated = {"skipped": f"fits replicated at this scale "
                                 f"(~{need/2**30:.2f} GiB/chip of "
                                 f"{limit/2**30:.2f} GiB) — sharded run is "
                                 "the measurement of record"}
    return {"metric": "bert_fsdp_tokens_per_sec",
            "value": round(B * T * p["steps"] / dt, 1), "unit": "tokens/sec",
            "section": "multichip", "batch": B, "seq": T,
            "model": "bert-large" if p["large"] else "tiny",
            "mesh": {"data": 1, "fsdp": int(mesh.shape[layout.fsdp_axis]),
                     "tp": int(mesh.shape[layout.tp_axis])},
            "param_bytes_total": report.params_bytes_total,
            "param_shard_bytes_per_rank": report.params_bytes_per_rank,
            "opt_state_bytes_per_rank": report.opt_bytes_per_rank,
            "per_device_param_bytes": report.per_device_params_bytes,
            "replicated": replicated}


# ------------------------------------------- multichip: pipeline parallelism


def _pipeline_parallel_measure(p):
    """Measurement core for :func:`bench_pipeline_parallel` — needs >= 2
    devices, so ``bench_pipeline_parallel`` either calls it in-process
    (multi-device hosts) or forks it into a forced-multi-device CPU child.

    Everything here runs the REAL ISSUE 19 code paths, which publish the
    four ``tdl_pipe_*`` families into whichever process executes this:
    the trainer ctor (``tdl_pipe_stages``), ``profile_stages``
    (``tdl_pipe_stage_seconds``), a forced ``maybe_rebalance``
    (``tdl_pipe_rebalances_total`` + the ``pipe_rebalance`` flight event),
    and the forward-schedule bubble fit below (``tdl_pipe_bubble_fraction``).
    """
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.common import jax_compat
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       init_params)
    from deeplearning4j_tpu.monitoring.partition import pipe_metrics
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.parallel.pipeline import (PipelineParallelTrainer,
                                                      transformer_pp_loss_fn)
    from deeplearning4j_tpu.parallel.partition import SpecLayout
    from deeplearning4j_tpu.parallel.sharding import batch_sharding

    n_dev = len(jax.devices())
    S = max(s for s in range(2, p["stages"] + 1) if n_dev % s == 0)
    L, T = p["layers"], p["seq"]
    mb, M1, M2 = p["microbatch"], p["m1"], p["m2"]
    cfg = TransformerConfig.tiny(max_len=T, dropout=0.0, n_layers=L)
    layout = SpecLayout(data=n_dev // S, pipe=S)
    trainer = PipelineParallelTrainer(
        init_params(jax.random.key(0), cfg), cfg, Adam(1e-4), layout,
        n_microbatches=M1, schedule="1f1b")
    mesh = trainer.mesh
    pipe_axis = trainer.partitioner.layout.pipe_axis
    rs = np.random.RandomState(0)

    def make_batch(B):
        bshard = batch_sharding(mesh)
        return {"tokens": jax.device_put(
                    rs.randint(0, cfg.vocab_size, (B, T)).astype(np.int32),
                    bshard),
                "labels": jax.device_put(
                    rs.randint(0, cfg.vocab_size, (B, T)).astype(np.int32),
                    bshard)}

    # --- full 1F1B train-step throughput (the headline rate) ---------------
    B1 = mb * M1
    batch = make_batch(B1)
    with jax_compat.set_mesh(mesh):
        for _ in range(p["warmup"]):
            trainer._fit_batch(batch)
        float(trainer.net.score_)
        t0 = time.perf_counter()
        for _ in range(p["steps"]):
            trainer._fit_batch(batch)
        float(trainer.net.score_)
        step_dt = (time.perf_counter() - t0) / p["steps"]

    # --- measured forward bubble vs the analytic fill-drain bound ----------
    # Fix the microbatch SIZE and vary the microbatch COUNT: a fill-drain
    # schedule costs t(M) ~= c*M + c*(S-1) + const, so the per-microbatch
    # tick cost c falls out of the slope between two M values and whatever
    # fraction of t(M1) is NOT M1*c is idle — fill/drain bubble (plus
    # dispatch constants; repeats amortize those). Analytic: (S-1)/(M+S-1).
    def time_fwd(M, boundaries):
        fn = jax.jit(transformer_pp_loss_fn(
            cfg, M, mesh, pipe_axis=pipe_axis, schedule="1f1b",
            boundaries=boundaries))
        b = make_batch(mb * M)
        with jax_compat.set_mesh(mesh):
            float(fn(trainer.net.params_, b))  # compile outside the clock
            t0 = time.perf_counter()
            for _ in range(p["fwd_repeats"]):
                out = fn(trainer.net.params_, b)
            jax.block_until_ready(out)
        return (time.perf_counter() - t0) / p["fwd_repeats"]

    t1 = time_fwd(M1, trainer.boundaries)
    t2 = time_fwd(M2, trainer.boundaries)
    c = max(0.0, (t2 - t1) / (M2 - M1))
    bubble = min(1.0, max(0.0, (t1 - M1 * c) / t1)) if t1 > 0 else 0.0
    analytic = (S - 1) / (M1 + S - 1)
    pipe_metrics().bubble.labels("1f1b").set(bubble)

    # --- cost-balanced vs deliberately skewed split ------------------------
    # Transformer blocks are homogeneous, so stage skew is induced the only
    # honest way available: a bad SPLIT (first S-1 stages get one layer
    # each, the last hoards the rest). The pipeline clock runs at the
    # slowest stage, so the balanced split's win should approach
    # max_stage_layers(imbalanced) / max_stage_layers(balanced).
    imbalanced = [(i, i + 1) for i in range(S - 1)] + [(S - 1, L)]
    t_bal = time_fwd(M1, trainer.boundaries)
    t_imb = time_fwd(M1, imbalanced)

    # --- measured stage seconds + a forced skew rebalance ------------------
    stage_seconds = trainer.profile_stages(repeats=max(2, p["fwd_repeats"]))
    predicted = trainer.predicted_stage_costs()
    old_b = list(trainer.boundaries)
    forced = [2.0] + [1.0] * (S - 1)  # stage 0 "measured" 2x slower
    new_b = trainer.maybe_rebalance(forced)
    if new_b is not None:
        with jax_compat.set_mesh(mesh):
            trainer._fit_batch(batch)  # recompiled step trains on the new split
        float(trainer.net.score_)

    return {"schedule": "1f1b", "stages": S, "layers": L, "seq": T,
            "mesh": {"data": n_dev // S, "pipe": S},
            "tokens_per_sec": round(B1 * T / step_dt, 1),
            "step_ms": round(step_dt * 1e3, 3),
            "microbatches": M1,
            "bubble": {"measured": round(bubble, 4),
                       "analytic_bound": round(analytic, 4),
                       "fwd_ms_m1": round(t1 * 1e3, 3),
                       "fwd_ms_m2": round(t2 * 1e3, 3),
                       "per_microbatch_ms": round(c * 1e3, 3)},
            "balance": {"balanced": [list(x) for x in old_b],
                        "imbalanced": [list(x) for x in imbalanced],
                        "fwd_ms_balanced": round(t_bal * 1e3, 3),
                        "fwd_ms_imbalanced": round(t_imb * 1e3, 3),
                        "speedup": round(t_imb / t_bal, 3) if t_bal > 0
                        else None},
            "stage_seconds": [round(t, 6) for t in stage_seconds],
            "predicted_stage_costs": predicted,
            "rebalance": {"forced_measured": forced, "old": [list(x) for x in old_b],
                          "new": [list(x) for x in new_b] if new_b else None},
            "rebalances_total": 1 if new_b else 0}


def bench_pipeline_parallel(p):
    """ISSUE 19 multichip section: cost-model-balanced pipeline parallelism.

    Reports full 1F1B train-step throughput over a ``data x pipe`` mesh,
    the MEASURED forward-schedule bubble next to the ``(S-1)/(M+S-1)``
    analytic fill-drain bound, the step-time win of the cost-balanced split
    over a deliberately skewed one, and one forced measured-skew rebalance
    (counter + ``pipe_rebalance`` flight event). Single-device hosts (CPU
    smoke without forced devices) fork the measurement into a child with
    ``--xla_force_host_platform_device_count`` and mirror the child-measured
    values into this process's registry so ``--check-telemetry`` still
    proves the four ``tdl_pipe_*`` families alive."""
    import jax

    n_dev = len(jax.devices())
    if any(n_dev % s == 0 for s in range(2, p["stages"] + 1)):
        res = _pipeline_parallel_measure(p)
        res["ran"] = "in-process"
    else:
        import subprocess

        forced = int(p.get("force_devices", 4))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        env["XLA_FLAGS"] = " ".join(
            flags + [f"--xla_force_host_platform_device_count={forced}"])
        code = ("import json, bench; print(json.dumps("
                f"bench._pipeline_parallel_measure({dict(p)!r})))")
        proc = subprocess.run(
            [sys.executable, "-c", code], cwd=str(_HERE), env=env,
            capture_output=True, text=True, timeout=900)
        if proc.returncode != 0:
            raise RuntimeError("pipeline_parallel child failed:\n"
                               + proc.stderr[-4000:])
        res = json.loads(proc.stdout.strip().splitlines()[-1])
        # mirror the child-MEASURED values into this process's registry —
        # same numbers, parent-side series, so the families ride the
        # telemetry block the parent snapshots for --check-telemetry
        from deeplearning4j_tpu.monitoring.partition import pipe_metrics
        pm = pipe_metrics()
        pm.stages.set(res["stages"])
        pm.bubble.labels(res["schedule"]).set(res["bubble"]["measured"])
        for i, t in enumerate(res["stage_seconds"]):
            pm.stage_seconds.labels(str(i)).set(t)
        if res["rebalances_total"]:
            pm.rebalances.inc(res["rebalances_total"])
        res["ran"] = f"subprocess ({forced} forced cpu devices)"
    return {"metric": "pipeline_parallel_tokens_per_sec",
            "value": res.pop("tokens_per_sec"), "unit": "tokens/sec",
            "section": "multichip", **res}


# ------------------------------------------------------------------- serving


def _latency_ms(latencies):
    """Shared nearest-rank p50/p99 over a SORTED seconds list — the serving
    and serving_pool replays must report identically-computed percentiles."""
    n = len(latencies)
    return {
        "p50_ms": round(latencies[n // 2] * 1e3, 2) if n else None,
        "p99_ms": round(latencies[min(n - 1, int(0.99 * n))] * 1e3, 2)
        if n else None,
    }


def bench_serving(p):
    """ISSUE 5: serving throughput + tail latency through the full stack —
    JsonModelClient → HTTP → bounded admission queue → micro-batching
    executor → ParallelInference bucketed forward. Mean coalesced batch rows
    come from the tdl_inference_batch_size histogram, so the number reported
    here is the same thing /metrics exposes in production."""
    import threading

    from deeplearning4j_tpu.monitoring import get_registry
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.serving import JsonModelClient, JsonModelServer

    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-3)).list()
            .layer(DenseLayer(n_in=p["features"], n_out=128, activation="relu"))
            .layer(OutputLayer(n_out=p["classes"], activation="softmax",
                               loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    warm = np.zeros((1, p["features"]), np.float32)
    bs = get_registry().get("tdl_inference_batch_size")
    base = bs.snapshot()["series"][0] if bs and bs.snapshot()["series"] else None
    server = (JsonModelServer.Builder(net).port(0)
              .batch_limit(p["batch_limit"]).queue_size(p["queue"])
              .warmup_input(warm).build().start())
    ready = server.wait_ready(60.0)
    if not ready:
        server.stop()
        return {"metric": "serving_requests_per_sec", "value": 0.0,
                "unit": "req/s", "error": "server never became ready"}
    x = np.random.RandomState(0).randn(1, p["features"]).astype(np.float32).tolist()
    per_client = p["requests"] // p["clients"]
    latencies, errors, lock = [], [0], threading.Lock()

    def worker():
        client = JsonModelClient(port=server.port, retries=3,
                                 backoff_base=0.02, backoff_max=0.25)
        mine = []
        for _ in range(per_client):
            t0 = time.perf_counter()
            try:
                client.predict(x)
                mine.append(time.perf_counter() - t0)
            except RuntimeError:
                with lock:
                    errors[0] += 1
        with lock:
            latencies.extend(mine)

    threads = [threading.Thread(target=worker) for _ in range(p["clients"])]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    server.stop(drain=True)

    latencies.sort()
    n = len(latencies)
    series = get_registry().get("tdl_inference_batch_size").snapshot()["series"]
    snap = series[0] if series else None  # no child if every request failed
    count = (snap["count"] - (base["count"] if base else 0)) if snap else 0
    total = (snap["sum"] - (base["sum"] if base else 0)) if snap else 0.0
    return {
        "metric": "serving_requests_per_sec",
        "value": round(n / elapsed, 1) if elapsed else 0.0,
        "unit": "req/s",
        "clients": p["clients"], "completed": n, "errors": errors[0],
        **_latency_ms(latencies),
        "mean_batch_rows": round(total / count, 2) if count else None,
        "batch_limit": p["batch_limit"],
    }


def bench_serving_slo(p):
    """ISSUE 11: SLO attainment under REPLAYED realistic traffic — a seeded
    diurnal+burst trace through the full client→HTTP→queue→executor stack,
    latency measured client-side, with a history ring + SLO tracker + alert
    engine evaluating live during the replay. The report is what ROADMAP 1's
    autoscaler bench consumes: attainment, error-budget remaining, burn
    rate, and which alert rules fired under the burst."""
    import threading

    from deeplearning4j_tpu.monitoring import (AlertEngine, HistoryRing,
                                               SloTracker, default_objectives,
                                               default_rules, get_registry)
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.serving import (Burst, JsonModelServer,
                                            LoadGenerator, TraceSpec)

    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-3)).list()
            .layer(DenseLayer(n_in=p["features"], n_out=128, activation="relu"))
            .layer(OutputLayer(n_out=p["classes"], activation="softmax",
                               loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    warm = np.zeros((1, p["features"]), np.float32)
    server = (JsonModelServer.Builder(net).port(0)
              .batch_limit(p["batch_limit"]).queue_size(p["queue"])
              .warmup_input(warm).build().start())
    if not server.wait_ready(60.0):
        server.stop()
        return {"metric": "slo_attainment", "value": 0.0, "unit": "ratio",
                "error": "server never became ready"}
    dur = p["duration_s"]
    spec = TraceSpec(
        duration_s=dur, base_rate=p["base_rate"], seed=0,
        diurnal_amplitude=0.4,  # one compressed "day" over the replay
        bursts=(Burst(0.5 * dur, 0.15 * dur, p["burst_mult"]),),
        deadline_mix=((0.9, None), (0.1, 2_000.0)))
    threshold_s = p["slo_threshold_ms"] / 1e3
    window_s = max(2.0, dur / 4)
    ring = HistoryRing(registry=get_registry(), interval=0.0)
    tracker = SloTracker(
        default_objectives(latency_threshold_s=threshold_s,
                           target=p["slo_target"], window_s=window_s),
        history_view=ring, registry=get_registry(),
        burn_windows=(("fast", window_s / 2), ("slow", window_s * 2)))
    engine = AlertEngine(
        default_rules(p99_latency_s=threshold_s,
                      latency_window_s=window_s,
                      shed_window_s=window_s),
        registry=get_registry(), history_view=ring)
    fired, stop_eval = set(), threading.Event()

    def evaluate_loop():  # live evaluation at scrape cadence during replay
        while not stop_eval.is_set():
            ring.sample(force=True)
            tracker.evaluate()
            fired.update(a["rule"] for a in engine.evaluate() if a["firing"])
            stop_eval.wait(0.2)

    evaluator = threading.Thread(target=evaluate_loop, daemon=True)
    evaluator.start()
    try:
        report = LoadGenerator(
            spec, server.port, n_clients=p["clients"],
            payload=np.random.RandomState(0)
            .randn(1, p["features"]).astype(np.float32).tolist(),
            slo_threshold_ms=p["slo_threshold_ms"],
            slo_target=p["slo_target"]).run()
    finally:
        stop_eval.set()
        evaluator.join(10.0)
        server.stop(drain=True)
    slo_rows = {r["slo"]: r for r in tracker.evaluate()}
    serving_lat = slo_rows.get("serving_latency", {})
    return {
        "metric": "slo_attainment",
        "value": report["slo"]["attainment"],
        "unit": "ratio",
        "offered": report["offered"],
        "offered_rate_per_s": report["offered_rate_per_s"],
        "outcomes": report["outcomes"],
        "p99_ms": report["latency_ms"]["p99"],
        "slo": report["slo"],
        "tracker": {
            "attainment": serving_lat.get("attainment"),
            "error_budget_remaining":
                serving_lat.get("error_budget_remaining"),
            "burn_rate": serving_lat.get("burn_rate"),
        },
        "alerts_fired_during_replay": sorted(fired),
        "trace": spec.to_dict(),
    }


# -------------------------------------------------------------- serving pool


def _pool_transformer_cfg(p):
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.transformer import TransformerConfig

    return TransformerConfig(
        causal=True, dropout=0.0, attn_impl="xla",
        vocab_size=p["vocab"], max_len=p["max_len"], d_model=p["d_model"],
        n_heads=p["n_heads"], n_layers=p["n_layers"], d_ff=p["d_ff"],
        param_dtype=jnp.float32, compute_dtype=jnp.float32)


def _serving_pool_replica():
    """Replica target (``bench:_serving_pool_replica``) for the serving_pool
    bench: a real KV-cache transformer slot pool behind a generative
    JsonModelServer, shaped by the TDL_BENCH_POOL_CFG env json. Warmup
    restores from the pool's shared compile cache — which is exactly what
    makes the pool's scale-up cheap enough to be alert-driven."""
    import jax
    import numpy as _np

    from deeplearning4j_tpu.models import transformer as tfm
    from deeplearning4j_tpu.serving import JsonModelServer

    p = json.loads(os.environ["TDL_BENCH_POOL_CFG"])
    cfg = _pool_transformer_cfg(p)
    params = tfm.init_params(jax.random.key(0), cfg)
    pool = tfm.DecodeSlotPool(params, cfg, slots=p["slots"])
    return JsonModelServer(
        None, port=0, generative_session=pool,
        default_max_new_tokens=p["max_new"], max_queue=p["queue"],
        warmup_input=_np.asarray([1, 2, 3], _np.int32))


def _replay_generative_executor(ex, spec, prompt_fn, max_new_fn, clients):
    """Open-loop replay of a TraceSpec's arrival schedule straight into a
    generative executor (no HTTP): per-request client-side latency, ok
    count, and wall — the measurement both batching policies share.
    ``max_new_fn(i)`` draws each request's generation budget: HETEROGENEOUS
    lengths are the realistic workload, and exactly what static padded
    batching pays for (a short ride queued behind a long batch member)."""
    import threading

    arrivals = spec.arrivals()
    results = [None] * len(arrivals)
    next_idx = [0]
    lock = threading.Lock()
    t0 = time.perf_counter()

    def worker():
        while True:
            with lock:
                i = next_idx[0]
                if i >= len(arrivals):
                    return
                next_idx[0] = i + 1
            delay = arrivals[i][0] - (time.perf_counter() - t0)
            if delay > 0:
                time.sleep(delay)
            sent = time.perf_counter()
            try:
                fut = ex.submit(prompt_fn(i), max_new_tokens=max_new_fn(i),
                                request_id=f"bench-pool-{i}")
                ok = fut.wait(120.0) and fut.error is None
            except Exception:
                ok = False
            results[i] = {"ok": bool(ok),
                          "latency": time.perf_counter() - sent}

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    lat = sorted(r["latency"] for r in results if r and r["ok"])
    return {
        "offered": len(arrivals),
        "ok": len(lat),
        "elapsed_s": round(elapsed, 3),
        **_latency_ms(lat),
    }


def bench_serving_pool(p):
    """ISSUE 13: the elastic-generative-serving evidence, in two phases.

    Phase 1 — continuous vs STATIC batching at equal load: the same seeded
    diurnal+burst generative trace replayed into a KV-cache slot pool twice,
    once with iteration-level admission (continuous) and once admitting only
    into an empty pool (static padded batching, the DL4J-era policy). The
    acceptance claim is measured, not assumed: p99 strictly lower AND
    tokens/s no worse, with mean decode-slot occupancy reported.

    Phase 2 — the replica pool: N real transformer replicas (subprocesses,
    shared persistent compile cache) behind the least-loaded router replay a
    trace through HTTP, then a manual scale-up measures time-to-ready for a
    NEW replica warming from the cache — the number that prices
    alert-driven autoscaling."""
    import jax

    from deeplearning4j_tpu.models import transformer as tfm
    from deeplearning4j_tpu.serving import (GenerativeInferenceExecutor,
                                            LoadGenerator, ServingPool,
                                            TraceSpec)

    cfg = _pool_transformer_cfg(p)
    params = tfm.init_params(jax.random.key(0), cfg)
    rs = np.random.RandomState(7)
    prompt_lens = (3, 5, 9, 14)
    prompts = [rs.randint(1, p["vocab"], n).tolist() for n in prompt_lens]
    mix = tuple(p.get("max_new_mix") or (p["max_new"],))

    def prompt_fn(i):
        return prompts[i % len(prompts)]

    def max_new_fn(i):
        return mix[i % len(mix)]

    dur = p["duration_s"]
    spec = TraceSpec(duration_s=dur, base_rate=p["base_rate"], seed=0,
                     diurnal_amplitude=0.4,
                     bursts=((0.5 * dur, 0.15 * dur, p["burst_mult"]),))
    phase1 = {}
    for mode, continuous in (("continuous", True), ("static", False)):
        pool = tfm.DecodeSlotPool(params, cfg, slots=p["slots"])
        ex = GenerativeInferenceExecutor(
            pool, continuous=continuous, max_queue=p["queue"],
            default_max_new_tokens=max(mix),
            warmup_prompt=np.asarray([1, 2, 3], np.int32)).start()
        ex.wait_warm(120.0)
        try:
            report = _replay_generative_executor(
                ex, spec, prompt_fn, max_new_fn, p["clients"])
        finally:
            ex.stop(drain=True)
        stats = ex.stats()
        report["tokens_per_s"] = (round(stats["tokens"] / report["elapsed_s"], 1)
                                  if report["elapsed_s"] else 0.0)
        report["mean_slot_occupancy"] = stats["mean_slot_occupancy"]
        report["decode_steps"] = stats["steps"]
        phase1[mode] = report

    cont, stat = phase1["continuous"], phase1["static"]
    p99_ratio = (round(stat["p99_ms"] / cont["p99_ms"], 2)
                 if cont.get("p99_ms") and stat.get("p99_ms") else None)

    # ---- phase 2: the replica pool over HTTP -----------------------------
    import tempfile

    workdir = tempfile.mkdtemp(prefix="tdl_bench_pool_")
    pool = ServingPool(
        "bench:_serving_pool_replica", replicas=p["replicas"],
        min_replicas=1, max_replicas=p["replicas"] + 1, workdir=workdir,
        extra_env={"TDL_BENCH_POOL_CFG": json.dumps(p)})
    pool_report = {"replicas": p["replicas"]}
    try:
        pool.start()
        if not pool.wait_ready(300.0):
            pool_report["error"] = "pool never became ready"
        else:
            pdur = p["pool_duration_s"]
            pool_spec = TraceSpec(
                duration_s=pdur, base_rate=p["pool_rate"], seed=1,
                diurnal_amplitude=0.3,
                bursts=((0.5 * pdur, 0.2 * pdur, p["burst_mult"]),))
            replay = LoadGenerator(
                pool_spec, pool.port, n_clients=min(16, p["clients"]),
                payload=prompts[0], slo_threshold_ms=p["slo_threshold_ms"],
                slo_target=p["slo_target"]).run()
            pool_report.update({
                "offered": replay["offered"],
                "outcomes": replay["outcomes"],
                "p99_ms": replay["latency_ms"]["p99"],
                "slo_attainment": replay["slo"]["attainment"],
                "burn_rate_worst_window": replay["slo"]["burn_rate_worst_window"],
            })
            # manual scale-up: time to a READY extra replica, warmed from
            # the shared persistent compile cache (why respawn is cheap)
            t0 = time.perf_counter()
            pool.scale_to(p["replicas"] + 1, reason="bench scale probe")
            deadline = time.monotonic() + 300.0
            while (pool.ready_count < p["replicas"] + 1
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            pool_report["scale_up_ready_s"] = round(
                time.perf_counter() - t0, 2)
            pool_report["scaled_ready"] = pool.ready_count
            pool.scale_to(p["replicas"], reason="bench scale probe done")
            pool_report["replica_states"] = {
                str(k): v for k, v in pool.replica_states().items()}
    finally:
        pool.stop()

    return {
        "metric": "serving_pool_continuous_tokens_per_sec",
        "value": cont["tokens_per_s"],
        "unit": "tokens/s",
        "slots": p["slots"], "max_new_tokens": p["max_new"],
        "continuous": cont,
        "static": stat,
        # the acceptance pair: >1.0 means continuous strictly beat static
        # on p99; tokens/s comparison is read off the two rows directly
        "static_over_continuous_p99": p99_ratio,
        "pool": pool_report,
        "trace": spec.to_dict(),
    }


# ------------------------------------------------------------- paged decoding


def _count_admissions(pool, prompts, max_new):
    """Concurrent sequences a pool holds at once: admit until the first
    refusal (no slot / no blocks), then release everything. Residency is
    priced at admission (a paged pool reserves the FULL span in blocks up
    front), so no decode steps are needed to measure capacity."""
    admitted = []
    for toks in prompts:
        try:
            slot, _ = pool.admit(np.asarray(toks, np.int32), max_new)
        except Exception:
            break
        admitted.append(slot)
    for s in admitted:
        pool.release(s)
    return len(admitted)


def bench_paged_decode(p):
    """ISSUE 17: the paged-KV + speculative-decoding evidence, in two phases.

    Phase 1 — capacity at equal HBM: a dense per-slot pool and a block-paged
    pool get the SAME arena budget (``slots_dense * max_len`` positions;
    the paged pool spends it as ``block_T``-sized blocks plus one trash
    block). Concurrent residency is counted twice: short unique prompts
    (paging wins by not padding every sequence to max_len) and long
    shared-prefix prompts (copy-on-write prefix sharing stacks tenants onto
    one physical prefix). The acceptance claim is >=3x concurrent
    long-context sequences.

    Phase 2 — speculative vs plain decode through the generative executor:
    the same seeded shared-prefix trace (the TraceSpec tenant mix) replayed
    into a paged pool twice, plain and with a draft model proposing
    ``spec_tokens`` per target step. The draft here is the target's first
    ``draft_layers`` layers and the target's tail layers are zeroed into
    identity (pre-LN residual: ``out_w``/``ffn_w2`` = 0 makes a block a
    no-op), so draft and target argmax agree by construction — acceptance
    ~1.0, the best case that bounds the machinery's speedup. Acceptance
    rate is reported alongside; the claim is >=1.5x tokens/s at a p99 no
    worse."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models import transformer as tfm
    from deeplearning4j_tpu.serving import (GenerativeInferenceExecutor,
                                            TraceSpec)

    cfg = _pool_transformer_cfg(p)
    params = tfm.init_params(jax.random.key(0), cfg)
    bT, max_new = p["block_T"], p["max_new"]
    n_blocks = 1 + p["slots_dense"] * (p["max_len"] // bT)  # equal HBM

    # ---- phase 1: dense vs paged capacity at equal HBM -------------------
    rng = np.random.default_rng(11)
    n_try = n_blocks + 4
    short = [rng.integers(1, p["vocab"], size=p["short_len"]).tolist()
             for _ in range(n_try)]
    prefixes = [rng.integers(1, p["vocab"], size=p["cap_prefix_len"]).tolist()
                for _ in range(p["prefix_tenants"])]
    shared = [prefixes[i % p["prefix_tenants"]]
              + rng.integers(1, p["vocab"], size=p["cap_suffix_len"]).tolist()
              for i in range(n_try)]

    cap_new = p["cap_max_new"]
    dense_pool = tfm.DecodeSlotPool(params, cfg, slots=p["slots_dense"])
    dense_short = _count_admissions(dense_pool, short, cap_new)
    dense_long = _count_admissions(dense_pool, shared, cap_new)

    # slots = usable blocks so BLOCKS (HBM), not slot-table rows, bind
    paged_pool = tfm.PagedDecodeSlotPool(
        params, cfg, slots=n_blocks - 1, block_T=bT, n_blocks=n_blocks)
    paged_short = _count_admissions(paged_pool, short, cap_new)
    paged_long = _count_admissions(paged_pool, shared, cap_new)
    capacity = {
        "hbm_positions": p["slots_dense"] * p["max_len"],
        "blocks_usable": n_blocks - 1, "block_T": bT,
        "dense_short": dense_short, "paged_short": paged_short,
        "dense_shared_prefix": dense_long, "paged_shared_prefix": paged_long,
        "gain_short": (round(paged_short / dense_short, 2)
                       if dense_short else None),
        "gain_shared_prefix": (round(paged_long / dense_long, 2)
                               if dense_long else None),
    }

    # ---- phase 2: plain vs speculative through the executor --------------
    # identity-tail target: layers >= draft_layers become exact no-ops, so
    # the first-draft_layers draft predicts the target's argmax exactly
    Ld = p["draft_layers"]
    for blk in params["blocks"][Ld:]:
        blk["out_w"] = jnp.zeros_like(blk["out_w"])
        blk["ffn_w2"] = jnp.zeros_like(blk["ffn_w2"])
    draft_cfg = dataclasses.replace(cfg, n_layers=Ld)
    draft_params = {"embed": params["embed"], "mlm": params["mlm"],
                    "blocks": params["blocks"][:Ld]}

    dur = p["duration_s"]
    spec = TraceSpec(duration_s=dur, base_rate=p["base_rate"], seed=3,
                     diurnal_amplitude=0.3,
                     bursts=((0.5 * dur, 0.2 * dur, 4.0),),
                     prefix_tenants=p["prefix_tenants"],
                     prefix_len=p["prefix_len"], suffix_len=p["suffix_len"],
                     prompt_vocab=p["vocab"])
    prompt_fn = spec.prompt_fn()

    phase2 = {}
    for mode in ("plain", "speculative"):
        kw = {}
        if mode == "speculative":
            kw = dict(draft_params=draft_params, draft_cfg=draft_cfg,
                      spec_tokens=p["spec_tokens"])
        pool = tfm.PagedDecodeSlotPool(
            params, cfg, slots=p["paged_slots"], block_T=bT, **kw)
        ex = GenerativeInferenceExecutor(
            pool, continuous=True, max_queue=p["queue"],
            default_max_new_tokens=max_new,
            warmup_prompt=np.asarray([1, 2, 3], np.int32)).start()
        ex.wait_warm(300.0)
        try:
            report = _replay_generative_executor(
                ex, spec, prompt_fn, lambda i: max_new, p["clients"])
        finally:
            ex.stop(drain=True)
        stats = ex.stats()
        report["tokens_per_s"] = (
            round(stats["tokens"] / report["elapsed_s"], 1)
            if report["elapsed_s"] else 0.0)
        report["decode_steps"] = stats["steps"]
        report["block_occupancy"] = stats.get("block_occupancy")
        report["spec_acceptance"] = stats.get("spec_acceptance")
        report["cow_shared_blocks"] = (
            (stats.get("blocks") or {}).get("cow_shared_blocks"))
        phase2[mode] = report

    plain, spv = phase2["plain"], phase2["speculative"]
    speedup = (round(spv["tokens_per_s"] / plain["tokens_per_s"], 2)
               if plain["tokens_per_s"] else None)
    p99_ratio = (round(plain["p99_ms"] / spv["p99_ms"], 2)
                 if spv.get("p99_ms") and plain.get("p99_ms") else None)

    return {
        "metric": "paged_decode_spec_tokens_per_sec",
        "value": spv["tokens_per_s"],
        "unit": "tokens/s",
        "capacity": capacity,
        "plain": plain,
        "speculative": spv,
        # acceptance pair: speedup >= 1.5 at plain_over_spec_p99 >= 1.0
        "spec_speedup": speedup,
        "plain_over_spec_p99": p99_ratio,
        "spec_tokens": p["spec_tokens"], "draft_layers": Ld,
        "trace": spec.to_dict(),
    }


# --------------------------------------------------------------------- driver


def _baseline_ratio(backend, value, config):
    """Per-backend self-relative trend (ADVICE r1: never cross-compare or
    clobber another backend's baseline; ADVICE r2: only compare runs whose
    measurement config — batch/image size/precision — matches). An off-config
    run reports 1.0 and leaves the stored baseline untouched; only a missing
    or corrupt baseline file is (re-)seeded."""
    per = _HERE / f"BENCH_BASELINE.{backend}.json"
    if per.exists():
        try:
            d = json.loads(per.read_text())
        except Exception:
            d = None  # corrupt file: fall through and re-seed below
        if d is not None:
            if d.get("backend") == backend and d.get("config") == config:
                return value / d["value"]
            # valid baseline with a different config: incomparable — leave
            # the stored trend intact so one off-config run can't reset it
            return 1.0
    per.write_text(json.dumps({"metric": "resnet50_train_images_per_sec",
                               "value": value, "backend": backend,
                               "config": config}))
    return 1.0


# ------------------------------------------------------------------- reshard


def _chunked_ckpt_write(lineage_dir, state, fsdp, n_files, iteration=1):
    """Write a COMMITTED lineage generation in TrainingCheckpointer's
    on-disk format AS IF an ``fsdp=<fsdp>`` gang of ``n_files`` processes
    had saved it: each leaf is tiled into fsdp contiguous dim-0 chunks
    (where divisible), the chunks are distributed round-robin over the
    shard files, and the full ISSUE 15 commit record lands — per-rank
    checksummed manifests, self-checksummed meta, COMMIT marker, pointer.
    Lets the bench measure a 4-rank-source restore (which now VERIFIES the
    generation first) on whatever devices this process actually has."""
    # the REAL path-syntax walker + checksum helpers: local copies would
    # silently drift from the on-disk format the restore actually reads
    from deeplearning4j_tpu.serde.checkpoint import (_array_crc, _gen_name,
                                                     _leaf_paths,
                                                     _self_checksummed)

    gen = _gen_name(iteration)
    ckdir = os.path.join(lineage_dir, gen)
    os.makedirs(ckdir, exist_ok=True)
    blobs = [{"__save_id__": np.asarray(iteration, np.int64)}
             for _ in range(n_files)]
    rr = 0
    for path, leaf in _leaf_paths(state):
        if not hasattr(leaf, "dtype"):
            continue
        a = np.asarray(leaf)
        parts = fsdp if a.ndim and a.shape[0] % fsdp == 0 else 1
        step = (a.shape[0] // parts) if a.ndim else 0
        for si in range(parts):
            idx = [[0, n] for n in a.shape]
            chunk = a
            if parts > 1:
                idx[0] = [si * step, (si + 1) * step]
                chunk = a[si * step:(si + 1) * step]
            blob = blobs[rr % n_files]
            rr += 1
            key = f"{path}|{si}"
            blob[key] = chunk
            blob[f"{key}|idx"] = np.asarray(idx, np.int64)
            blob[f"{key}|shape"] = np.asarray(list(a.shape), np.int64)
    layout = {"axes": {"data": 1, "fsdp": fsdp, "tp": 1},
              "axis_names": ["data", "fsdp", "tp"]}
    for proc, blob in enumerate(blobs):
        shard = f"shard_{proc}.npz"
        with open(os.path.join(ckdir, shard), "wb") as f:
            np.savez(f, **blob)
        manifest = _self_checksummed({
            "save_id": iteration, "proc": proc, "shard": shard,
            "process_count": n_files, "layout": layout,
            "entries": {k: _array_crc(v) for k, v in blob.items()},
            "nbytes": int(sum(int(v.nbytes) for v in blob.values()))})
        with open(os.path.join(ckdir, f"manifest_{proc}.json"), "w") as f:
            json.dump(manifest, f)
    meta = {"iteration": iteration, "epoch": 0, "score": None,
            "process_count": n_files, "generation": gen,
            "mesh_layout": layout}
    with open(os.path.join(ckdir, "train_state.json"), "w") as f:
        json.dump(_self_checksummed(meta), f)
    with open(os.path.join(ckdir, "COMMIT"), "w") as f:
        json.dump({"generation": gen, "iteration": iteration,
                   "process_count": n_files}, f)
    with open(os.path.join(lineage_dir, "LATEST"), "w") as f:
        f.write(gen + "\n")
    return ckdir


def _swap_replica():
    """Replica target (``bench:_swap_replica``) for the reshard bench's
    swap-window phase: a small real MLN restored from the TDL_MODEL_CKPT
    checkpoint dir, warmed from the pool's shared persistent compile cache —
    the configuration swap_model prices in production."""
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.serde.checkpoint import TrainingCheckpointer
    from deeplearning4j_tpu.serving import JsonModelServer

    p = json.loads(os.environ["TDL_BENCH_SWAP_CFG"])
    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-3)).list()
            .layer(DenseLayer(n_in=p["features"], n_out=p["hidden"],
                              activation="relu"))
            .layer(OutputLayer(n_out=p["classes"], activation="softmax",
                               loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    ckpt = os.environ.get("TDL_MODEL_CKPT")
    if ckpt:
        TrainingCheckpointer(ckpt, async_write=False).restore(net)
    return JsonModelServer(
        net, port=0, max_queue=64,
        warmup_input=np.zeros((1, p["features"]), np.float32))


def bench_reshard(p):
    """ISSUE 14: the cost of elasticity as tracked numbers.

    Phase 1 — the restore matrix: a 4-rank fsdp=4 checkpoint (written in the
    real on-disk format by :func:`_chunked_ckpt_write`) restored onto target
    layouts emulating 4, 2, and 8 ranks (clamped to the devices this process
    has; each row reports what actually ran and whether the saved and target
    layouts matched — a mismatch is a true cross-topology reshard through
    the chunk-intersection path, feeding ``tdl_reshard_*``).

    Phase 2 — the swap window: a 2-replica ServingPool of real MLN replicas
    rolls to a new checkpoint via ``swap_model`` with the persistent compile
    cache warm (the initial spawns populated it), so the reported window is
    restore + deserialization, not XLA compilation."""
    import tempfile

    import jax

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.parallel.mesh import mesh_from_shape
    from deeplearning4j_tpu.parallel.partition import (Partitioner,
                                                       largest_layout)
    from deeplearning4j_tpu.serde.checkpoint import TrainingCheckpointer
    from deeplearning4j_tpu.serving import ServingPool

    def build_net():
        conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-3))
                .list()
                .layer(DenseLayer(n_in=p["features"], n_out=p["hidden"],
                                  activation="relu"))
                .layer(OutputLayer(n_out=p["classes"], activation="softmax",
                                   loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    rs = np.random.RandomState(0)
    X = rs.randn(32, p["features"]).astype(np.float32)
    Y = np.eye(p["classes"], dtype=np.float32)[
        rs.randint(0, p["classes"], 32)]
    src = build_net()
    for _ in range(p["steps"]):
        src._fit_batch(DataSet(X, Y))
    state = {"params": src.params_, "updater": src.updater_state,
             "bn": src.bn_state}
    host = {k: jax.tree.map(lambda a: np.asarray(a), v)
            for k, v in state.items()}
    state_bytes = sum(a.nbytes for a in jax.tree.leaves(host))

    n_dev = len(jax.devices())
    out = {"metric": "reshard_restore_ms", "unit": "ms",
           "source": {"ranks": 4, "layout_fsdp": 4,
                      "state_bytes": state_bytes},
           "devices": n_dev, "restore": {}}
    with tempfile.TemporaryDirectory() as d:
        ckdir = os.path.join(d, "ck", "latest")
        _chunked_ckpt_write(ckdir, host, fsdp=4, n_files=4,
                            iteration=int(src.iteration))
        for name, want in (("4_to_4", 4), ("4_to_2", 2), ("4_to_8", 8)):
            tdev = min(want, n_dev)
            layout = largest_layout(tdev)
            part = Partitioner(layout, mesh=mesh_from_shape(
                layout.shape(), devices=jax.devices()[:tdev]))
            fresh = build_net()
            ck = TrainingCheckpointer(os.path.join(d, "ck"),
                                      partitioner=part, reshard=True)
            t0 = time.perf_counter()
            assert ck.restore(fresh)
            wall_ms = (time.perf_counter() - t0) * 1e3
            exact = all(
                np.array_equal(np.asarray(a), np.asarray(b))
                for a, b in zip(jax.tree.leaves(host["params"]),
                                jax.tree.leaves(fresh.params_)))
            out["restore"][name] = {
                "target_devices": tdev,
                "target_layout": part.describe()["axes"],
                "same_layout": part.describe() == {
                    "axes": {"data": 1, "fsdp": 4, "tp": 1},
                    "axis_names": ["data", "fsdp", "tp"]},
                "restore_ms": round(wall_ms, 2),
                "exact": bool(exact),
            }
        out["value"] = out["restore"]["4_to_2"]["restore_ms"]

        # ---- phase 2: the swap window over a live pool ------------------
        v1, v2 = os.path.join(d, "m1"), os.path.join(d, "m2")
        TrainingCheckpointer(v1, async_write=False).save(src)
        src._fit_batch(DataSet(X, Y))  # v2 is a genuinely different model
        TrainingCheckpointer(v2, async_write=False).save(src)
        pool = ServingPool(
            "bench:_swap_replica", replicas=p["replicas"], min_replicas=1,
            max_replicas=p["replicas"] + 1,
            workdir=os.path.join(d, "pool"),
            extra_env={"TDL_BENCH_SWAP_CFG": json.dumps(p),
                       "TDL_MODEL_CKPT": v1})
        swap = {"replicas": p["replicas"]}
        try:
            pool.start()
            if not pool.wait_ready(300.0):
                swap["error"] = "pool never became ready"
            else:
                res = pool.swap_model(v2)
                swap.update({
                    # the headline: full rolling swap, compile cache warm
                    "swap_window_s": res["window_s"],
                    "swapped": res["swapped"],
                    "rolled_back": res["rolled_back"],
                    "per_replica_s": round(
                        res["window_s"] / max(1, res["swapped"]), 3),
                })
        finally:
            pool.stop()
        out["swap"] = swap
    return out


# ------------------------------------------------------- checkpoint lineage


def bench_ckpt_lineage(p):
    """ISSUE 15: the price of durability, itemized.

    - ``commit_ms`` vs ``inplace_ms``: a full generational save (shard +
      checksummed manifest + meta + fsync discipline + COMMIT + pointer
      swap) against the pre-lineage strawman (one npz + one rename, no
      verify record, no fsync) — the two-phase-commit overhead in absolute
      terms;
    - ``nofsync_ms``: the same generational save with ``durable=False`` —
      isolates the fsync share of the overhead from the manifest share;
    - ``checksum_mb_per_s``: save-side CRC32 throughput over the real state
      bytes (the per-array manifest entries);
    - ``restore_verify_ms`` vs ``restore_noverify_ms`` and
      ``verify_mb_per_s``: what the pre-restore verification pass costs
      (price it against the PR 13 ``reshard`` block's restore_ms rows —
      same state-size ballpark, different axis of work);
    - ``fallback_restore_ms``: restore latency with the NEWEST generation
      bit-flipped — verify fail + quarantine + walk back to the previous
      commit, the unattended self-heal path.

    Runs the real ``tdl_ckpt_*`` counters hot for ``--check-telemetry``
    (commits, verify failures, quarantines, fallbacks, GC retirements)."""
    import tempfile
    import zlib

    import jax

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.serde.checkpoint import (TrainingCheckpointer,
                                                     verify_checkpoint)

    def build_net(seed=0):
        conf = (NeuralNetConfiguration.Builder().seed(seed)
                .updater(Adam(1e-3)).list()
                .layer(DenseLayer(n_in=p["features"], n_out=p["hidden"],
                                  activation="relu"))
                .layer(OutputLayer(n_out=p["classes"], activation="softmax",
                                   loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    rs = np.random.RandomState(0)
    X = rs.randn(32, p["features"]).astype(np.float32)
    Y = np.eye(p["classes"], dtype=np.float32)[
        rs.randint(0, p["classes"], 32)]
    net = build_net()
    for _ in range(p["steps"]):
        net._fit_batch(DataSet(X, Y))
    state = {"params": net.params_, "updater": net.updater_state,
             "bn": net.bn_state}
    host_leaves = [np.asarray(a) for a in jax.tree.leaves(state)
                   if hasattr(a, "dtype")]
    state_bytes = sum(a.nbytes for a in host_leaves)
    state_mb = state_bytes / (1 << 20)

    out = {"metric": "ckpt_lineage_commit_ms", "unit": "ms",
           "state_bytes": state_bytes}

    with tempfile.TemporaryDirectory() as d:
        # (0) save-side checksum throughput, measured directly on the bytes
        t0 = time.perf_counter()
        for a in host_leaves:
            zlib.crc32(np.ascontiguousarray(a).tobytes())
        crc_s = time.perf_counter() - t0
        out["checksum_mb_per_s"] = round(state_mb / max(crc_s, 1e-9), 1)

        # (1) full durable generational save — the commit wall
        ck = TrainingCheckpointer(os.path.join(d, "durable"),
                                  async_write=False, keep_last=2)
        walls = []
        for i in range(p["saves"]):
            net._fit_batch(DataSet(X, Y))
            t0 = time.perf_counter()
            ck.save(net)
            walls.append((time.perf_counter() - t0) * 1e3)
        out["commit_ms"] = round(min(walls), 2)  # best-of: page cache warm
        out["value"] = out["commit_ms"]
        out["saves"] = p["saves"]

        # (2) same save, fsync off — isolates the durability tax
        ck_nf = TrainingCheckpointer(os.path.join(d, "nofsync"),
                                     async_write=False, durable=False)
        t0 = time.perf_counter()
        ck_nf.save(net)
        out["nofsync_ms"] = round((time.perf_counter() - t0) * 1e3, 2)

        # (3) the old in-place save strawman: one npz + one rename, no
        # manifests, no fsync, no commit record — what PR 15 replaced
        from deeplearning4j_tpu.serde.checkpoint import _leaf_paths

        blob = {}
        for path, leaf in _leaf_paths(state):
            if hasattr(leaf, "dtype"):
                blob[path] = np.asarray(leaf)
        ip_dir = os.path.join(d, "inplace")
        os.makedirs(ip_dir)
        t0 = time.perf_counter()
        tmp = os.path.join(ip_dir, "shard_0.npz.tmp")
        with open(tmp, "wb") as f:
            np.savez(f, **blob)
        os.replace(tmp, os.path.join(ip_dir, "shard_0.npz"))
        with open(os.path.join(ip_dir, "train_state.json"), "w") as f:
            json.dump({"iteration": int(net.iteration)}, f)
        out["inplace_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
        out["commit_overhead_vs_inplace"] = round(
            out["commit_ms"] / max(out["inplace_ms"], 1e-6), 2)

        # (4) restore: verified vs structural-only
        fresh = build_net(seed=9)
        t0 = time.perf_counter()
        assert ck.restore(fresh)
        out["restore_verify_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
        ck_nv = TrainingCheckpointer(os.path.join(d, "durable"),
                                     async_write=False,
                                     verify_on_restore=False)
        fresh = build_net(seed=10)
        t0 = time.perf_counter()
        assert ck_nv.restore(fresh)
        out["restore_noverify_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 2)
        rep = verify_checkpoint(os.path.join(d, "durable"))
        assert rep["ok"], rep
        out["verify_ms"] = round(rep["seconds"] * 1e3, 2)
        out["verify_mb_per_s"] = round(
            (rep["bytes"] / (1 << 20)) / max(rep["seconds"], 1e-9), 1)

        # (5) fallback latency: bit-flip the newest committed shard (the
        # SAME corruption primitive the corrupt_ckpt chaos fault injects),
        # restore walks back one generation (quarantine + older verify)
        from deeplearning4j_tpu.common.faults import _flip_bit_in_shard

        gendir = ck.committed_generation()
        assert _flip_bit_in_shard(gendir) is not None
        fresh = build_net(seed=11)
        t0 = time.perf_counter()
        assert ck.restore(fresh)
        out["fallback_restore_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 2)
        out["fallback_quarantined"] = os.path.basename(gendir)
    return out


# ------------------------------------------------- deployment controller


def bench_deploy(p):
    """ISSUE 18: the price of an unattended promotion decision.

    Walks a real :class:`FleetController` gate chain (no pool — the canary
    leg is priced separately below) over a live lineage:

    - ``promote_ms`` (the headline): integrity deep-verify + offline eval +
      promote bookkeeping for one HEALTHY generation — what the controller
      adds on top of training before a candidate reaches the fleet;
    - ``integrity_reject_ms``: a bit-flipped generation caught at the first
      gate — the cheapest rejection (one verified read, no replica risk);
    - ``eval_reject_ms``: a loss-spiked generation (structurally perfect,
      numbers ruined) caught by the eval gate's threshold + regression band;
    - ``canary_judge_windows_per_s``: throughput of the paired old-vs-
      candidate SLO judgement (window pairing + AlertRule evaluation per
      sub-window) over synthetic replay rows — the gate's analysis cost,
      isolated from the replay's wall time.

    Runs every ``tdl_deploy_*`` and ``tdl_eval_*`` family hot for
    ``--check-telemetry``."""
    import tempfile

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.deploy import FleetController
    from deeplearning4j_tpu.monitoring import get_registry
    from deeplearning4j_tpu.monitoring.deploy import (canary_rules,
                                                      judge_canary_windows,
                                                      paired_canary_windows)
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.serde.checkpoint import TrainingCheckpointer

    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-3))
            .list()
            .layer(DenseLayer(n_in=p["features"], n_out=p["hidden"],
                              activation="relu"))
            .layer(OutputLayer(n_out=p["classes"], activation="softmax",
                               loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rs = np.random.RandomState(0)
    X = rs.randn(32, p["features"]).astype(np.float32)
    Y = np.eye(p["classes"], dtype=np.float32)[
        rs.randint(0, p["classes"], 32)]

    def weight_eval(gendir):
        # spiked generations carry blown-up parameters: a cheap stand-in
        # for a held-out eval with the same verdict structure
        shard = sorted(f for f in os.listdir(gendir)
                       if f.startswith("shard_"))[0]
        with np.load(os.path.join(gendir, shard)) as z:
            mags = [float(np.abs(z[k]).mean()) for k in z.files
                    if k.startswith("params/")and not k.endswith(
                        ("|idx", "|shape"))]
        return {"accuracy": 0.9 if max(mags) < 0.5 else 0.1}

    out = {"metric": "deploy_promote_ms", "unit": "ms"}
    with tempfile.TemporaryDirectory() as d:
        ck = TrainingCheckpointer(os.path.join(d, "ck"), async_write=False,
                                  keep_last=8)
        import jax as _jax

        for _ in range(p["steps"]):
            net._fit_batch(DataSet(X, Y))
        ck.save(net)  # healthy candidate
        ctl = FleetController(os.path.join(d, "ck"),
                              workdir=os.path.join(d, "deploy"),
                              eval_fn=weight_eval,
                              eval_thresholds={"accuracy": 0.8},
                              regression_band=0.1, retries=0,
                              registry=get_registry())
        try:
            t0 = time.perf_counter()
            ctl.run_once()
            out["promote_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
            out["value"] = out["promote_ms"]
            assert ctl.state["promoted"] is not None

            # loss-spiked candidate → eval-gate rejection
            net.params_ = _jax.tree.map(lambda a: a * 40.0, net.params_)
            net._fit_batch(DataSet(X, Y))
            ck.save(net)
            t0 = time.perf_counter()
            rows = ctl.run_once()
            out["eval_reject_ms"] = round(
                (time.perf_counter() - t0) * 1e3, 2)
            assert rows[-1]["rejected_by"]["gate"] == "eval"

            # bit-flipped candidate → integrity-gate rejection
            net._fit_batch(DataSet(X, Y))
            ck.save(net)
            from deeplearning4j_tpu.common.faults import _flip_bit_in_shard

            assert _flip_bit_in_shard(ck.committed_generation()) is not None
            t0 = time.perf_counter()
            rows = ctl.run_once()
            out["integrity_reject_ms"] = round(
                (time.perf_counter() - t0) * 1e3, 2)
            assert rows[-1]["rejected_by"]["gate"] == "integrity"
        finally:
            ctl.close()

    # the to_metrics hook (classification + regression): eval verdicts land
    # on /metrics under the model label
    from deeplearning4j_tpu.eval import Evaluation, RegressionEvaluation

    ev = Evaluation()
    y = np.eye(p["classes"], dtype=np.float32)[
        rs.randint(0, p["classes"], 64)]
    ev.eval(y, y)
    ev.to_metrics(get_registry(), model="bench-clf")
    rev = RegressionEvaluation()
    t = rs.randn(64, 1).astype(np.float32)
    rev.eval(t, t + 0.1 * rs.randn(64, 1).astype(np.float32))
    rev.to_metrics(get_registry(), model="bench-reg")

    # paired canary judgement throughput over synthetic replay rows
    rs = np.random.RandomState(1)
    n = p["canary_requests"]
    dur = 4.0

    def arm_rows(lat_ms):
        return [{"t": float(t), "outcome": "200",
                 "latency_ms": float(max(0.1, rs.normal(lat_ms, 2.0)))}
                for t in np.linspace(0, dur, n, endpoint=False)]

    base, cand = arm_rows(5.0), arm_rows(30.0)
    t0 = time.perf_counter()
    windows = paired_canary_windows(base, cand, duration_s=dur,
                                    window_s=0.25, threshold_ms=10.0,
                                    target=0.99)
    verdict = judge_canary_windows(windows, canary_rules(),
                                   registry=get_registry())
    judge_s = time.perf_counter() - t0
    assert not verdict["ok"]  # the slow arm must trip the paired rules
    out["canary_judge_windows_per_s"] = round(
        verdict["judged"] / max(judge_s, 1e-9), 1)
    out["canary_requests"] = 2 * n
    return out


# ------------------------------------------------------- compile cache


def bench_compile_cache(p):
    """ISSUE 12: cold-vs-warm executable restore through the persistent
    compile cache, for the two restart paths that used to re-pay full XLA
    compilation — serving warmup (a respawned replica warming its whole
    ParallelInference bucket ladder) and a gang respawn's fit loop — plus
    the Pallas autotune table (deterministic interpret fallback on the CPU
    smoke; measured search + measured-roofline utilization on TPU).

    "Warm" here = jax's in-memory caches dropped (``jax.clear_caches``) but
    the on-disk executable cache intact — the same state a fresh process
    sharing TDL_COMPILE_CACHE_DIR starts in (the cross-process form is
    pinned by tests/test_compile_cache.py). Runs LAST in the bench so the
    cache config never perturbs the other configs' windows."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.common import compile_cache
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.kernels import autotune, flash_attention
    from deeplearning4j_tpu.monitoring import compilecache
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    from deeplearning4j_tpu.serving.executor import BatchingInferenceExecutor

    def build_net():
        conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-3))
                .list()
                .layer(DenseLayer(n_in=p["features"], n_out=128,
                                  activation="relu"))
                .layer(OutputLayer(n_out=p["classes"], activation="softmax",
                                   loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    warmed = {}

    def warmup_wall():
        pi = ParallelInference(build_net(), batch_limit=p["batch_limit"])
        ex = BatchingInferenceExecutor(
            parallel_inference=pi,
            max_batch_rows=p["max_rows"],
            warmup_input=np.zeros((1, p["features"]), np.float32),
            warmup_all_buckets=True)
        t0 = time.perf_counter()
        ex.start()
        ex.wait_warm(600)
        wall = time.perf_counter() - t0
        ex.stop()
        warmed["buckets"] = len(pi.bucket_sizes(p["max_rows"]))
        return wall

    def fit_wall():
        rs = np.random.RandomState(0)
        X = rs.randn(p["fit_batch"], p["features"]).astype(np.float32)
        Y = np.eye(p["classes"], dtype=np.float32)[
            rs.randint(0, p["classes"], p["fit_batch"])]
        net = build_net()
        t0 = time.perf_counter()
        for _ in range(p["fit_steps"]):
            net._fit_batch(DataSet(X, Y))
        float(net.score_)  # drain the dispatch
        return time.perf_counter() - t0

    out = {"metric": "compile_cache_warm_speedup", "unit": "x"}
    with tempfile.TemporaryDirectory() as d:
        compile_cache.enable(os.path.join(d, "cc"))
        autotune.reset_table()
        try:
            serving_cold = warmup_wall()
            fit_cold = fit_wall()
            jax.clear_caches()  # the respawned-process state (disk intact)
            serving_warm = warmup_wall()
            fit_warm = fit_wall()
            stats = compilecache.stats()
            out["serving_warmup"] = {
                "cold_start_s": round(serving_cold, 3),
                "warm_start_s": round(serving_warm, 3),
                "speedup": round(serving_cold / serving_warm, 2)
                if serving_warm else None,
                "buckets_warmed": warmed["buckets"],
            }
            out["gang_respawn_fit"] = {
                "cold_start_s": round(fit_cold, 3),
                "warm_start_s": round(fit_warm, 3),
                "speedup": round(fit_cold / fit_warm, 2) if fit_warm else None,
            }
            out["cache"] = {"hits": round(sum(stats["hits"].values())),
                            "misses": round(sum(stats["misses"].values())),
                            "bytes": stats["bytes"]}
            out["value"] = (round(fit_cold / fit_warm, 2)
                            if fit_warm else 0.0)

            # ---- autotune: the table persists NEXT TO the executable cache
            fa = p["flash"]
            on_tpu = jax.default_backend() == "tpu"
            table = autotune.get_table(refresh=True)
            entry = autotune.autotune_flash_attention(
                fa["B"], fa["H"], fa["T"], fa["D"],
                jnp.bfloat16 if on_tpu else jnp.float32,
                trials=fa["trials"], table=table)
            at_block = {"grid_point": {k: fa[k] for k in ("B", "H", "T", "D")},
                        "entry": entry, "table_path": table.path,
                        # the consult path flash_attention takes (proves the
                        # persisted entry answers; feeds the lookup counter)
                        "resolved": autotune.resolve_blocks(
                            "flash_attention", B=fa["B"], H=fa["H"],
                            Tq=fa["T"], Tk=fa["T"], D=fa["D"],
                            dtype="bfloat16" if on_tpu else "float32",
                            table=table)}
            if on_tpu and entry.get("measured"):
                # validate the winner against THIS window's measured
                # roofline (ISSUE 10 discipline): attention flops over the
                # tuned fwd+bwd wall, honest in utilization terms
                roofline = _roofline_probe()
                q = jnp.zeros((fa["B"], fa["H"], fa["T"], fa["D"]),
                              jnp.bfloat16)

                def run():
                    return flash_attention(
                        q, q, q, block_q=entry["block_q"],
                        block_k=entry["block_k"])

                run().block_until_ready()
                t0 = time.perf_counter()
                run().block_until_ready()
                dt = time.perf_counter() - t0
                fwd_flops = 4.0 * fa["B"] * fa["H"] * fa["T"] ** 2 * fa["D"]
                at_block["forward"] = _utilization(fwd_flops, 1, dt, roofline)
                static_us = entry.get("static_us")
                if static_us and entry.get("best_us"):
                    at_block["vs_static"] = round(
                        static_us / entry["best_us"], 2)
            out["autotune"] = at_block
        finally:
            compile_cache.disable()
            autotune.reset_table()
    return out


# ------------------------------------------------------------ trace overhead


def bench_trace_overhead(p):
    """ISSUE 16: what the fleet-timeline instrumentation costs when it is
    ON at default sampling (flight ring + request spans + trace-id
    propagation, span_sample_n=1) vs fully OFF (no TDL_FLIGHT_DIR, no
    recorder). Two steady-state loops — serving req/s through the full
    client→HTTP→executor stack, and the ParallelTrainer step path that
    records step_begin/step_end — measured in alternating rounds so
    machine drift hits both modes equally. Acceptance: ≤2%% at default
    sampling."""
    import tempfile
    import threading

    from deeplearning4j_tpu.monitoring import flight
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.serving import JsonModelClient, JsonModelServer

    flight_dir = tempfile.mkdtemp(prefix="tdl_trace_bench_")
    saved_env = os.environ.get(flight.ENV_DIR)

    def set_mode(on: bool) -> None:
        if on:
            os.environ[flight.ENV_DIR] = flight_dir
        else:
            os.environ.pop(flight.ENV_DIR, None)

    def median(vals):
        vals = sorted(vals)
        return vals[len(vals) // 2] if vals else 0.0

    def overhead_pct(off, on, higher_is_better):
        if not off or not on:
            return None
        pct = ((off - on) / off if higher_is_better else (on - off) / off)
        return round(pct * 100.0, 2)

    out = {"metric": "trace_overhead_serving_pct", "unit": "%",
           "rounds": p["rounds"]}
    try:
        # -- serving: req/s with spans+trace propagation on vs off --------
        conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-3))
                .list()
                .layer(DenseLayer(n_in=p["features"], n_out=64,
                                  activation="relu"))
                .layer(OutputLayer(n_out=p["classes"], activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        warm = np.zeros((1, p["features"]), np.float32)
        set_mode(False)
        server = (JsonModelServer.Builder(net).port(0)
                  .batch_limit(p["batch_limit"]).queue_size(p["queue"])
                  .warmup_input(warm).build().start())
        if not server.wait_ready(60.0):
            server.stop()
            return {**out, "value": None, "error": "server never became ready"}
        x = np.random.RandomState(0).randn(
            1, p["features"]).astype(np.float32).tolist()
        per_client = p["requests_per_round"] // p["clients"]

        def one_round(tag):
            done = [0]
            lock = threading.Lock()

            def worker(ci):
                client = JsonModelClient(port=server.port, retries=2,
                                         backoff_base=0.02, backoff_max=0.25)
                n = 0
                for i in range(per_client):
                    try:  # trace id in BOTH modes: only recording differs
                        client.predict(x, trace_id=f"{tag}-{ci}-{i}")
                        n += 1
                    except RuntimeError:
                        pass
                with lock:
                    done[0] += n

            threads = [threading.Thread(target=worker, args=(ci,))
                       for ci in range(p["clients"])]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            return done[0] / dt if dt else 0.0

        one_round("warm")  # executor warmup outside the measured rounds
        rps_off, rps_on = [], []
        for r in range(p["rounds"]):
            set_mode(False)
            rps_off.append(one_round(f"off{r}"))
            set_mode(True)
            rps_on.append(one_round(f"on{r}"))
        server.stop(drain=True)

        # -- training: ParallelTrainer step path (step_begin/step_end) ----
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.parallel import ParallelTrainer

        tconf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-3))
                 .list()
                 .layer(DenseLayer(n_in=p["train_features"],
                                   n_out=p["train_hidden"],
                                   activation="relu"))
                 .layer(OutputLayer(n_out=p["classes"], activation="softmax",
                                    loss="mcxent"))
                 .build())
        tnet = MultiLayerNetwork(tconf).init()
        trainer = ParallelTrainer(tnet)
        rs = np.random.RandomState(0)
        ds = DataSet(
            rs.randn(p["train_batch"], p["train_features"]).astype(np.float32),
            np.eye(p["classes"], dtype=np.float32)[
                rs.randint(0, p["classes"], p["train_batch"])])
        set_mode(False)
        for _ in range(2):
            trainer._fit_batch(ds)  # compile outside the measured rounds

        def train_round():
            t0 = time.perf_counter()
            for _ in range(p["train_steps"]):
                trainer._fit_batch(ds)
            return (time.perf_counter() - t0) / p["train_steps"]

        step_off, step_on = [], []
        for _ in range(p["rounds"]):
            set_mode(False)
            step_off.append(train_round())
            set_mode(True)
            step_on.append(train_round())
    finally:
        if saved_env is None:
            os.environ.pop(flight.ENV_DIR, None)
        else:
            os.environ[flight.ENV_DIR] = saved_env

    r_off, r_on = median(rps_off), median(rps_on)
    s_off, s_on = median(step_off), median(step_on)
    serving_pct = overhead_pct(r_off, r_on, higher_is_better=True)
    train_pct = overhead_pct(s_off, s_on, higher_is_better=False)
    return {**out,
            # headline value = serving overhead (the hot request path; the
            # negative-is-noise convention matches compare_benchmarks)
            "value": serving_pct,
            "serving": {"rps_off": round(r_off, 1), "rps_on": round(r_on, 1),
                        "overhead_pct": serving_pct},
            "train": {"step_ms_off": round(s_off * 1e3, 3),
                      "step_ms_on": round(s_on * 1e3, 3),
                      "overhead_pct": train_pct},
            "span_sample_n": 1, "target_pct": 2.0}


# ------------------------------------------------------------------ hpo fleet


def bench_hpo(p):
    """ISSUE 20: the price of a fault-isolated PBT/ASHA sweep, itemized.

    - ``sweep_s`` vs ``sequential_s`` / ``speedup``: the same N-trial gang
      sweep (real ``GangSupervisor`` gangs over the synth task, one shared
      spool/flight/compile-cache plane) run at ``max_concurrent=K`` against
      one-gang-at-a-time — what the fleet's concurrency is worth at the
      wall clock, per-gang spawn cost included;
    - ``clone_verify_ms`` / ``clone_fallback_ms``: one PBT exploit through
      the REAL fleet path (suffixed-sibling re-save of the winner's newest
      committed generation: deep verify + commit + journal + loser-lineage
      retire), then the same exploit with that generation bit-flipped —
      quarantine the corrupt commit, fall back one generation;
    - ``resume``: SIGKILL the unattended fleet CLI mid-rung, rerun the same
      config, time to a winner — journaled scores are adopted, not re-run;
    - ``etl_cache``: two ``lenet_images`` trials sharing one
      ``DecodedBatchCache`` — the sweep pays the PNG decode once (first
      trial's misses), every later trial memmaps it (hits), read per trial
      from the merged worker spool.

    Phase 0 drives an in-process micro-fleet through every trial-terminal
    decision path (promote / demote / clone / quarantine) on the PROCESS
    registry, so the ``tdl_trial_*`` / ``tdl_fleet_*`` families are hot for
    ``--check-telemetry`` without waiting on real gangs."""
    import shutil
    import signal
    import subprocess
    import tempfile

    from PIL import Image

    from deeplearning4j_tpu.arbiter import (ContinuousParameterSpace,
                                            IntegerParameterSpace,
                                            RandomSearchGenerator)
    from deeplearning4j_tpu.arbiter.fleet import GangTrialRunner, TrialFleet
    from deeplearning4j_tpu.common.faults import _flip_bit_in_shard
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.monitoring import MetricsRegistry, aggregate
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.serde.checkpoint import (TrainingCheckpointer,
                                                     lineage_state)

    spaces = {
        "learning_rate": ContinuousParameterSpace(1e-3, 1e-1, log_scale=True),
        "hidden": IntegerParameterSpace(4, 32),
    }
    spaces_cfg = {
        "learning_rate": {"kind": "continuous", "lo": 1e-3, "hi": 1e-1,
                          "log_scale": True},
        "hidden": {"kind": "integer", "lo": 4, "hi": 32},
    }
    task = {"kind": "synth_classify", "seed": 11}

    def build_small_net(seed=5):
        conf = (NeuralNetConfiguration.Builder().seed(seed)
                .updater(Adam(1e-2)).list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        return MultiLayerNetwork(conf).init()

    def seed_lineage(directory, steps=2, seed=5):
        # a real committed lineage for PBT to clone from (the in-process
        # phases skip gang training but never fake checkpoint bytes)
        net = build_small_net(seed)
        rs = np.random.RandomState(0)
        x = rs.randn(16, 4).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 16)]
        ck = TrainingCheckpointer(directory, async_write=False, keep_last=8)
        for _ in range(steps):
            net._fit_batch(DataSet(x, y))
            ck.save(net)

    def micro_runner(slot, target_iter, timeout_s):
        if slot.trial_id == "t05":
            raise RuntimeError("chaos: injected trial crash")
        lr = float(slot.hparams["learning_rate"])
        return 1.0 / (1.0 + abs(np.log10(lr) + 2.0)) + 1e-3 * target_iter

    out = {"metric": "hpo_sweep_speedup", "unit": "x",
           "trials": p["trials"], "rungs": list(p["rungs"]),
           "concurrent": p["concurrent"]}
    tmp = tempfile.mkdtemp(prefix="bench_hpo_")
    try:
        # (0) decision-path micro-fleet on the process registry: one trial
        # crashes past its restart budget (quarantine), the ASHA cut
        # demotes, PBT clones the seeded winner lineage (ok outcome)
        fleet = TrialFleet(
            RandomSearchGenerator(spaces, seed=3), micro_runner,
            workdir=os.path.join(tmp, "micro"), n_trials=6, rungs=(1, 2),
            reduction=2, pbt=True, pbt_quantile=0.34, seed=3,
            trial_max_restarts=1, backoff_base_s=0.01, backoff_max_s=0.02,
            max_concurrent=4, rung_timeout_s=120.0, spaces=spaces)
        for tid, slot in fleet.trials.items():
            if tid != "t05":
                seed_lineage(slot.ckpt_dir)
        try:
            micro_winner = fleet.run()
        finally:
            fleet.close()
        out["micro"] = {
            "winner": micro_winner["trial"],
            "quarantined": sorted(t.trial_id for t in fleet.trials.values()
                                  if t.status == "quarantined"),
            "clones": [r["outcome"] for r in fleet.state["journal"]
                       if r["kind"] == "clone"]}

        # (1) clone + deep-verify latency through the real fleet path, then
        # the same exploit against a bit-flipped newest generation — the
        # quarantine-and-fall-back-one-commit price
        cfleet = TrialFleet(
            RandomSearchGenerator(spaces, seed=9), micro_runner,
            workdir=os.path.join(tmp, "clone"), n_trials=2, rungs=(1,),
            pbt=False, seed=9, spaces=spaces)
        winner, loser = cfleet.trials["t00"], cfleet.trials["t01"]
        seed_lineage(winner.ckpt_dir, steps=2, seed=5)
        seed_lineage(loser.ckpt_dir, steps=1, seed=7)
        t0 = time.perf_counter()
        got = cfleet._clone_into_slot(loser, winner, rung=0)
        out["clone_verify_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
        assert got == "ok", got
        newest = lineage_state(winner.ckpt_dir)["newest_committed"]
        assert _flip_bit_in_shard(
            os.path.join(winner.ckpt_dir, "latest", newest)) is not None
        t0 = time.perf_counter()
        got = cfleet._clone_into_slot(loser, winner, rung=0)
        out["clone_fallback_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
        assert got == "fallback", got
        cfleet.close()

        # (2) the sweep itself: real gangs, concurrent vs one-at-a-time.
        # Same generator seed → identical candidate sets; the sequential
        # baseline keeps its metrics off the process registry so the
        # telemetry block reflects the concurrent sweep
        def gang_sweep(wd, max_concurrent, registry=None):
            gen = RandomSearchGenerator(spaces, seed=p["seed"])
            runner = GangTrialRunner(wd, task, hang_timeout=60.0)
            fl = TrialFleet(
                gen, runner, workdir=wd, n_trials=p["trials"],
                rungs=tuple(p["rungs"]), reduction=2, pbt=True,
                seed=p["seed"], registry=registry, rung_timeout_s=900.0,
                trial_max_restarts=1, backoff_base_s=0.1,
                max_concurrent=max_concurrent)
            t0 = time.perf_counter()
            try:
                win = fl.run()
            finally:
                fl.close()
            return time.perf_counter() - t0, win

        sweep_s, win = gang_sweep(os.path.join(tmp, "sweep"),
                                  p["concurrent"])
        seq_s, _ = gang_sweep(os.path.join(tmp, "seq"), 1,
                              registry=MetricsRegistry())
        out["sweep_s"] = round(sweep_s, 2)
        out["sequential_s"] = round(seq_s, 2)
        out["speedup"] = round(seq_s / max(sweep_s, 1e-9), 2)
        out["value"] = out["speedup"]
        out["winner"] = {"trial": win["trial"],
                         "score": round(win["score"], 4)}

        # (3) SIGKILL the unattended CLI mid-rung, rerun the same config:
        # resume adopts the journaled scores instead of re-running them
        resume_wd = os.path.join(tmp, "resume")
        cfg_path = os.path.join(tmp, "resume_cfg.json")
        with open(cfg_path, "w") as f:
            json.dump({"workdir": resume_wd, "generator": "random",
                       "seed": 13, "n_trials": p["resume_trials"],
                       "rungs": [p["rungs"][0]], "max_concurrent": 1,
                       "pbt": False, "rung_timeout_s": 600.0,
                       "trial_max_restarts": 1, "backoff_base_s": 0.1,
                       "hang_timeout": 60.0, "task": task,
                       "spaces": spaces_cfg}, f)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        cli = [sys.executable, "-m", "deeplearning4j_tpu.arbiter.fleet",
               cfg_path]
        proc = subprocess.Popen(cli, env=env, cwd=str(_HERE),
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        state_path = os.path.join(resume_wd, "fleet_state.json")
        deadline = time.monotonic() + 300.0
        killed, pre_scores = False, 0
        while time.monotonic() < deadline and proc.poll() is None:
            try:
                rows = json.load(open(state_path))["journal"]
                pre_scores = sum(r["kind"] == "score" for r in rows)
            except (OSError, ValueError, KeyError):
                pre_scores = 0
            if pre_scores >= 1:  # mid-rung: a score is down, no winner yet
                proc.send_signal(signal.SIGKILL)
                proc.wait(timeout=30)
                killed = True
                break
            time.sleep(0.25)
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        t0 = time.perf_counter()
        res = subprocess.run(cli, env=env, cwd=str(_HERE),
                             capture_output=True, text=True, timeout=600)
        resume_s = time.perf_counter() - t0
        assert res.returncode == 0, res.stdout + res.stderr
        out["resume"] = {"resume_s": round(resume_s, 2),
                         "killed_mid_run": killed,
                         "scores_adopted": pre_scores}

        # (4) shared-ETL-cache evidence: two lenet_images trials, one
        # cache_dir, run one-at-a-time — the second trial's decode traffic
        # should be all hits, read per trial from the merged worker spool
        data_dir = os.path.join(tmp, "imgs")
        rs = np.random.RandomState(0)
        for i in range(int(p["etl_images"])):
            d = os.path.join(data_dir, f"c{i % 4}")
            os.makedirs(d, exist_ok=True)
            Image.fromarray(rs.randint(0, 255, (16, 16), dtype=np.uint8),
                            mode="L").save(os.path.join(d, f"i{i:03d}.png"))
        etl_wd = os.path.join(tmp, "etl")
        etl_task = {"kind": "lenet_images", "data_dir": data_dir,
                    "cache_dir": os.path.join(tmp, "etl_cache"),
                    "height": 12, "width": 12, "channels": 1, "batch": 8,
                    "store_pad": 2, "seed": 5}
        runner = GangTrialRunner(etl_wd, etl_task, hang_timeout=120.0)
        fl = TrialFleet(
            RandomSearchGenerator(
                {"learning_rate": ContinuousParameterSpace(
                    1e-3, 1e-2, log_scale=True)}, seed=5),
            runner, workdir=etl_wd, n_trials=2,
            rungs=(int(p["etl_iters"]),), pbt=False, seed=5,
            max_concurrent=1, rung_timeout_s=900.0, trial_max_restarts=1,
            registry=MetricsRegistry())
        try:
            fl.run()
        finally:
            fl.close()
        by_trial = {}
        for payload in aggregate.read_spools(runner.spool_dir,
                                             registry=MetricsRegistry()):
            trial = str(payload.get("proc") or "").split("-")[0]
            row = by_trial.setdefault(trial, {"hits": 0.0, "misses": 0.0})
            snap = payload.get("snapshot") or {}
            for fam, key in (("tdl_etl_cache_hits_total", "hits"),
                             ("tdl_etl_cache_misses_total", "misses")):
                for s in (snap.get(fam) or {}).get("series", []):
                    row[key] += float(s.get("value", 0))
        out["etl_cache"] = by_trial
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


BENCHES = {"resnet50": bench_resnet50, "lenet": bench_lenet, "lstm": bench_lstm,
           "w2v": bench_w2v, "bert": bench_bert, "serving": bench_serving,
           "serving_slo": bench_serving_slo, "bert_large_fsdp": bench_fsdp,
           "serving_pool": bench_serving_pool, "hpo": bench_hpo,
           "pipeline_parallel": bench_pipeline_parallel,
           "reshard": bench_reshard,
           "ckpt_lineage": bench_ckpt_lineage,
           "deploy": bench_deploy,
           "compile_cache": bench_compile_cache,
           "trace_overhead": bench_trace_overhead,
           "paged_decode": bench_paged_decode}


# -------------------------------------------------------- regression compare


def compare_benchmarks(current: dict, old: dict, threshold: float = 0.10):
    """Per-config throughput regressions of ``current`` vs a prior bench
    JSON (ISSUE 10 satellite: the BENCH trajectory becomes machine-checkable).
    Only rate metrics gate (unit contains "/s"); lower-is-better metrics like
    time-to-accuracy are skipped. Raises ValueError on a cross-backend
    compare — a CPU-smoke run regressing against a TPU baseline is noise,
    not signal."""
    if old.get("backend") != current.get("backend"):
        raise ValueError(
            f"cannot compare backends: current={current.get('backend')!r} "
            f"vs old={old.get('backend')!r}")
    regressions = []
    old_cfgs = old.get("configs") or {}
    for name, cur in (current.get("configs") or {}).items():
        prev = old_cfgs.get(name)
        if not isinstance(cur, dict) or not isinstance(prev, dict):
            continue
        unit = str(cur.get("unit") or "")
        if "/s" not in unit:
            continue
        if str(prev.get("unit") or "") != unit:
            # a config whose unit changed between runs is incomparable —
            # ratioing images/sec against batches/sec fabricates a
            # regression (or hides one behind a unit inflation)
            continue
        cv, pv = cur.get("value"), prev.get("value")
        # a prior value of None/0 gives no baseline; a CURRENT value of 0
        # against a real baseline is the worst regression there is — it must
        # gate, not fall through a falsy check
        if cv is None or pv is None or pv <= 0:
            continue
        ratio = cv / pv
        if ratio < 1.0 - threshold:
            regressions.append({"config": name, "old": pv, "new": cv,
                                "ratio": round(ratio, 3), "unit": unit})
    return regressions


# -------------------------------------------------------- telemetry checking


def documented_bench_families(doc_path=None):
    """Metric families docs/OBSERVABILITY.md marks as exercised by a full
    bench run (a ``bench`` cell containing ``yes``). The doc's catalog table
    is the single source of truth, so a family added to the code without a
    catalog row — or documented but silently dead (the PR 1
    ``last_batch_size`` bug class) — fails ``--check-telemetry``."""
    import re

    path = pathlib.Path(doc_path) if doc_path else (
        _HERE / "docs" / "OBSERVABILITY.md")
    families = []
    for line in path.read_text().splitlines():
        m = re.match(r"\|\s*`(tdl_[a-z0-9_]+)`\s*\|", line)
        if not m:
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if cells and cells[-1].lower().startswith("yes"):
            families.append(m.group(1))
    if not families:
        raise RuntimeError(f"no bench-marked metric families parsed from {path}")
    return families


def check_telemetry(out, families):
    """Families documented as bench-exercised but absent (or observation-free)
    in the telemetry block. Histograms with zero observations and counters
    never incremented count as missing — a dead metric that still registers
    itself is exactly the failure mode this catches."""
    metrics = (out.get("telemetry") or {}).get("metrics") or {}
    missing = []
    for fam in families:
        snap = metrics.get(fam)
        series = (snap or {}).get("series") or []
        if snap and snap.get("type") == "histogram":
            # a registered-but-never-observed histogram is dead
            alive = any(s.get("count", 0) > 0 for s in series)
        else:
            # counters/gauges create a series on first touch; a series whose
            # value drained back to 0 (queue depth) is still alive
            alive = bool(series)
        if not alive:
            missing.append(fam)
    return missing


def main():
    import jax

    from deeplearning4j_tpu.monitoring import (DeviceMemoryWatchdog,
                                               RecompileWatchdog, get_registry)

    # telemetry riding along with every bench run: XLA compile count/seconds
    # (recompile storms show up as a compile counter out of proportion to the
    # config count) + device-memory high-water per window
    recompile_wd = RecompileWatchdog().install()
    memory_wd = DeviceMemoryWatchdog()

    backend = jax.default_backend()
    params = _scale(backend == "tpu")
    argv = [a for a in sys.argv[1:] if a != "--check-telemetry"]
    check = "--check-telemetry" in sys.argv[1:]
    compare_path, compare_old = None, None
    if "--compare" in argv:
        i = argv.index("--compare")
        if i + 1 >= len(argv):
            sys.exit("--compare needs a prior bench JSON path")
        compare_path = argv[i + 1]
        del argv[i:i + 2]
        # load + validate NOW: a typo'd path must fail in under a second,
        # not after the whole bench run completes
        try:
            with open(compare_path) as f:
                compare_old = json.load(f)
        except (OSError, ValueError) as e:
            sys.exit(f"--compare cannot read {compare_path}: {e}")
        if not isinstance(compare_old.get("configs"), dict):
            sys.exit(f"--compare: {compare_path} is not a bench JSON "
                     "(no 'configs' object)")
        if compare_old.get("backend") != backend:
            # fail before the run, not after minutes of benching
            sys.exit(f"--compare refused: current backend {backend!r} vs "
                     f"{compare_old.get('backend')!r} in {compare_path}")
    args = argv
    only = args[0] if args else None
    if only and only not in BENCHES:
        sys.exit(f"unknown benchmark {only!r}; choose from: {', '.join(BENCHES)}")
    names = [only] if only else list(BENCHES)
    if check and only:
        sys.exit("--check-telemetry needs the full run (every documented "
                 "family must get a chance to appear); drop the config name")

    results = {}
    for name in names:
        # same-window calibration BEFORE each config (VERDICT r3 weak #3):
        # lets the next round tell code deltas from tunnel-window deltas.
        # TPU-only: the probe exists to characterize the tunnel window, and
        # ~0.8 TFLOP of matmuls would dominate the CPU smoke path
        cal = calibration_probe() if backend == "tpu" else None
        results[name] = BENCHES[name](params[name])
        if cal is not None:
            results[name]["calibration"] = cal
        memory_wd.sample()  # high-water gauge tracks the max across configs

    from deeplearning4j_tpu.common.precision import compute_dtype

    # ISSUE 10: one SLO-alert pass over everything the run just emitted —
    # evaluated BEFORE the registry snapshot so tdl_alert_firing rides the
    # telemetry block (a bench run with a firing alert is visibly abnormal).
    # after_warmup rules have no warmup mark in a one-shot bench run and
    # stay pending — reported as such, never silently "clean"
    from deeplearning4j_tpu.monitoring import AlertEngine

    alert_rows = AlertEngine().evaluate()

    effective_precision = compute_dtype().__name__  # resolves 'auto' per backend
    head = results.get("resnet50") or results[names[0]]
    head_cfg = {"batch": head.get("batch"), "image_size": head.get("image_size"),
                "matmul_precision": effective_precision}
    out = {
        "metric": head["metric"],
        "value": head["value"],
        "unit": head["unit"],
        "vs_baseline": round(_baseline_ratio(backend, head["value"], head_cfg), 3)
        if head["metric"] == "resnet50_train_images_per_sec" else 1.0,
        "backend": backend,
        "matmul_precision": effective_precision,
        "configs": results,
        # full registry snapshot: compile counters, memory watermarks, and
        # whatever metrics the exercised code paths emitted — BENCH files
        # carry telemetry from here on
        "telemetry": {"compiles": recompile_wd.stats(),
                      "metrics": get_registry().snapshot()},
        "alerts": {"firing": [a["rule"] for a in alert_rows if a["firing"]],
                   "pending_warmup": [a["rule"] for a in alert_rows
                                      if a["state"] == "pending_warmup"],
                   "evaluated": len(alert_rows)},
    }
    # step-time attribution headline (ISSUE 7): the ResNet-50 pipeline's
    # phase-percentage table, mirrored into the telemetry block
    pipeline = (results.get("resnet50") or {}).get("pipeline") or {}
    if "phases" in pipeline:
        out["telemetry"]["step_phases"] = pipeline["phases"]
    recompile_wd.close()
    print(json.dumps(out))
    if check:
        missing = check_telemetry(out, documented_bench_families())
        if missing:
            sys.exit("documented metric families missing/observation-free in "
                     f"the telemetry block (silently dead?): {missing}")
        print("check-telemetry: all documented bench families present",
              file=sys.stderr)
    if compare_path:
        # perf-regression gate (ISSUE 10 satellite): non-zero exit on >10%
        # per-config throughput drops vs the prior BENCH_r*.json
        try:
            regs = compare_benchmarks(out, compare_old)
        except ValueError as e:
            sys.exit(f"--compare refused: {e}")
        if regs:
            for r in regs:
                print(f"REGRESSION {r['config']}: {r['old']} -> {r['new']} "
                      f"{r['unit']} ({r['ratio']:.3f}x)", file=sys.stderr)
            sys.exit(f"{len(regs)} config(s) regressed >10% vs {compare_path}")
        print(f"compare: no >10% throughput regressions vs {compare_path}",
              file=sys.stderr)


if __name__ == "__main__":
    main()
