"""Benchmark runner — prints ONE JSON line.

Headline metric (BASELINE.json): ResNet-50 images/sec/chip. The whole
train step (forward+backward+updater) is one compiled XLA executable; the
loop below keeps dispatch async and only syncs at the end.

No reference numbers exist to compare against (BASELINE.json "published" is
empty; see BASELINE.md provenance note), so vs_baseline is reported as the
ratio against the value recorded in BENCH_BASELINE.json once a previous
round has produced one (self-relative trend), else 1.0.
"""

from __future__ import annotations

import json
import os
import pathlib
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.models import ResNet50

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    # full ImageNet-shape config on TPU; reduced config for CPU smoke runs
    if on_tpu:
        batch, hw, classes, steps, warmup = 128, 224, 1000, 20, 3
    else:
        batch, hw, classes, steps, warmup = 8, 64, 10, 5, 2

    net = ResNet50(num_classes=classes, input_shape=(3, hw, hw)).init()
    step = net._train_step_fn()

    rs = np.random.RandomState(0)
    x = {"input": jnp.asarray(rs.rand(batch, 3, hw, hw).astype(np.float32))}
    y = {"output": jnp.asarray(np.eye(classes, dtype=np.float32)[rs.randint(0, classes, batch)])}
    rng = jax.random.key(0)
    it = jnp.asarray(0, jnp.int32)
    ep = jnp.asarray(0, jnp.int32)

    params, opt, bn = net.params_, net.updater_state, net.bn_state
    for i in range(warmup):
        params, opt, bn, loss = step(params, opt, bn, it, ep, x, y, None, rng)
    float(loss)  # device fetch = true sync (block_until_ready alone does not
    # drain the axon tunnel's async dispatch queue)

    t0 = time.perf_counter()
    for i in range(steps):
        params, opt, bn, loss = step(params, opt, bn, it, ep, x, y, None, rng)
    float(loss)
    dt = time.perf_counter() - t0

    images_per_sec = batch * steps / dt

    baseline_file = pathlib.Path(__file__).parent / "BENCH_BASELINE.json"
    vs = 1.0
    prev = None
    if baseline_file.exists():
        try:
            d = json.loads(baseline_file.read_text())
            if d.get("backend") == backend:
                prev = d.get("value")
        except Exception:
            pass
    if prev:
        vs = images_per_sec / prev
    else:
        baseline_file.write_text(json.dumps(
            {"metric": "resnet50_train_images_per_sec", "value": images_per_sec,
             "backend": backend, "batch": batch, "image": hw}))

    print(json.dumps({
        "metric": "resnet50_train_images_per_sec",
        "value": round(images_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs, 3),
        "backend": backend,
        "batch": batch,
        "image_size": hw,
        "num_classes": classes,
    }))


if __name__ == "__main__":
    main()
