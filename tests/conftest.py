"""Test bootstrap: force an 8-device virtual CPU mesh BEFORE jax import.

SURVEY.md §4.6 #5: `XLA_FLAGS=--xla_force_host_platform_device_count=8` +
`JAX_PLATFORMS=cpu` is the TPU-world analog of DL4J's `local[N]` Spark tests —
multi-device semantics with zero real chips. Must run before anything imports
jax, which pytest guarantees for conftest at collection start.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("TDL_DEFAULT_FLOAT", "float32")
# numerics tests (grad checks, parity-to-1e-6 assertions) run the fp32 policy;
# the bf16 AMP path has its own dedicated tests (tests/test_precision.py)
os.environ.setdefault("TDL_MATMUL_PRECISION", "float32")

# The axon sitecustomize has ALREADY imported jax and registered the real-TPU
# tunnel plugin at interpreter startup, with JAX_PLATFORMS=axon captured into
# jax.config. Env mutation alone is too late — force the config post-import so
# backends() never initializes the tunnel client during tests.
import jax

jax.config.update("jax_platforms", "cpu")

import time

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seeded_rng():
    """Deterministic global RNG per test (BaseNd4jTest seeds Nd4j RNG)."""
    from deeplearning4j_tpu.rng import set_seed

    set_seed(12345)
    np.random.seed(12345)
    yield


_SHM_DIR = "/dev/shm"


def _tdl_shm_segments():
    try:
        return {n for n in os.listdir(_SHM_DIR) if n.startswith("tdl_")}
    except OSError:  # non-Linux: no visible shm namespace to audit
        return set()


@pytest.fixture(autouse=True)
def _no_leaked_children_or_shm():
    """ISSUE 6 satellite: fail any test that leaves live child processes
    (multiprocessing workers — e.g. an ETL service that wasn't closed) or
    shared-memory segments behind. Leaks are cleaned up after the failure is
    recorded so one offender can't cascade into the rest of the suite."""
    import multiprocessing as mp

    before = _tdl_shm_segments()
    yield
    leaked_procs = []
    children = mp.active_children()  # also reaps finished children
    if children:
        deadline = time.monotonic() + 3.0  # grace: normal teardown in flight
        for p in children:
            p.join(timeout=max(0.0, deadline - time.monotonic()))
        leaked_procs = [p.name for p in children if p.is_alive()]
        for p in children:
            if p.is_alive():
                p.terminate()
                p.join(timeout=2.0)
            if p.is_alive():
                p.kill()
                p.join(timeout=2.0)
    leaked_shm = _tdl_shm_segments() - before
    for name in leaked_shm:  # unlink so later tests start clean
        try:
            os.unlink(os.path.join(_SHM_DIR, name))
        except OSError:  # already gone: the owner raced our cleanup
            pass
    assert not leaked_procs and not leaked_shm, (
        f"test leaked live child processes {leaked_procs} and/or "
        f"shared-memory segments {sorted(leaked_shm)} — close() the ETL "
        "service / iterator (fit loops do it in their finally)")


# -- observability-artifact leak audit (ISSUE 7 satellite) --------------------

# Filenames/dirnames the observability plane writes. A test that points
# TDL_METRICS_SPOOL_DIR / TDL_FLIGHT_DIR (or a GangSupervisor workdir) at
# cwd or the shared tempdir instead of tmp_path leaves these behind for
# every later test (and CI run) to trip over.
_OBS_ARTIFACT_PREFIXES = ("tdl_metrics_", "tdl_flight_", "tdl_history_",
                          "tdl_gang_")
_OBS_ARTIFACT_NAMES = ("postmortem.json",)


def _obs_artifacts():
    import tempfile

    found = set()
    for base in (os.getcwd(), tempfile.gettempdir()):
        try:
            names = os.listdir(base)
        except OSError:
            continue
        for n in names:
            if n.startswith(_OBS_ARTIFACT_PREFIXES) or n in _OBS_ARTIFACT_NAMES:
                found.add(os.path.join(base, n))
    return found


@pytest.fixture(autouse=True)
def _no_spool_or_postmortem_outside_tmp_path():
    """Fail any test that leaves metrics-spool / flight-recorder / postmortem
    files (or a default-workdir gang dir) outside its tmp_path. Leaks are
    cleaned after the failure is recorded so one offender can't cascade."""
    import shutil

    before = _obs_artifacts()
    yield
    leaked = _obs_artifacts() - before
    for path in leaked:  # clean so later tests start from a known state
        try:
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
            else:
                os.unlink(path)
        except OSError:
            pass
    assert not leaked, (
        f"test leaked observability artifacts outside tmp_path: "
        f"{sorted(leaked)} — point TDL_METRICS_SPOOL_DIR/TDL_FLIGHT_DIR and "
        "GangSupervisor(workdir=...) at tmp_path")
