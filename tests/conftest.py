"""Test bootstrap: force an 8-device virtual CPU mesh BEFORE jax import.

SURVEY.md §4.6 #5: `XLA_FLAGS=--xla_force_host_platform_device_count=8` +
`JAX_PLATFORMS=cpu` is the TPU-world analog of DL4J's `local[N]` Spark tests —
multi-device semantics with zero real chips. Must run before anything imports
jax, which pytest guarantees for conftest at collection start.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("TDL_DEFAULT_FLOAT", "float32")
# numerics tests (grad checks, parity-to-1e-6 assertions) run the fp32 policy;
# the bf16 AMP path has its own dedicated tests (tests/test_precision.py)
os.environ.setdefault("TDL_MATMUL_PRECISION", "float32")

# The axon sitecustomize has ALREADY imported jax and registered the real-TPU
# tunnel plugin at interpreter startup, with JAX_PLATFORMS=axon captured into
# jax.config. Env mutation alone is too late — force the config post-import so
# backends() never initializes the tunnel client during tests.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seeded_rng():
    """Deterministic global RNG per test (BaseNd4jTest seeds Nd4j RNG)."""
    from deeplearning4j_tpu.rng import set_seed

    set_seed(12345)
    np.random.seed(12345)
    yield
