"""Autoregressive KV-cache decode (ISSUE 13 tentpole piece 1).

The correctness contract: incremental decode through the preallocated
slot-pool KV cache is TOKEN-IDENTICAL to naive generation by repeated full
forwards, and membership churn in the slot pool (continuous batching's
admit/retire at step boundaries) never changes results OR mints a new
decode-step XLA signature.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.models import transformer as tfm


def _cfg(**kw):
    kw.setdefault("causal", True)
    kw.setdefault("dropout", 0.0)
    kw.setdefault("param_dtype", jnp.float32)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("vocab_size", 97)
    kw.setdefault("max_len", 64)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_layers", 2)
    kw.setdefault("d_ff", 64)
    return tfm.TransformerConfig(**kw)


def _params(cfg, seed=0):
    import jax

    return tfm.init_params(jax.random.key(seed), cfg)


def _naive_generate(params, cfg, prompt, max_new, eos_id=None):
    """Reference: greedy decoding by re-running the FULL forward each step."""
    toks = list(int(t) for t in prompt)
    out = []
    for _ in range(max_new):
        logits = tfm.forward(params, jnp.asarray([toks], jnp.int32), cfg)
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        toks.append(nxt)
        if eos_id is not None and nxt == eos_id:
            break
    return out


def test_prefill_forward_matches_encode():
    cfg = _cfg()
    params = _params(cfg)
    toks = jnp.asarray(np.random.RandomState(0).randint(1, 97, (2, 11)),
                       jnp.int32)
    ref = tfm.encode(params, toks, cfg)
    h, ks, vs = tfm.prefill_forward(params, toks, cfg)
    np.testing.assert_allclose(np.asarray(h), np.asarray(ref), atol=1e-5)
    assert ks.shape == (cfg.n_layers, 2, cfg.n_heads, 11, cfg.head_dim)
    assert vs.shape == ks.shape


def test_incremental_decode_matches_naive_full_forward():
    """The tentpole parity pin: pool-based KV decode == repeated full
    forwards, token for token, across prompts of different lengths."""
    cfg = _cfg()
    params = _params(cfg)
    rs = np.random.RandomState(1)
    prompts = [rs.randint(1, 97, n).tolist() for n in (3, 9, 17, 5)]
    expected = [_naive_generate(params, cfg, p, 8) for p in prompts]
    got = tfm.generate(params, prompts, 8, cfg, slots=2)
    assert got == expected


def test_decode_requires_causal_config():
    cfg = _cfg(causal=False)
    with pytest.raises(ValueError, match="causal"):
        tfm.DecodeSlotPool(_params(cfg), cfg, slots=2)


def test_slot_pool_bounds_and_validation():
    cfg = _cfg()
    pool = tfm.DecodeSlotPool(_params(cfg), cfg, slots=1, max_len=16)
    with pytest.raises(ValueError, match="exceeds"):
        pool.admit(list(range(1, 15)), max_new_tokens=8)
    with pytest.raises(ValueError, match="at least one token"):
        pool.admit([], max_new_tokens=1)
    slot, _ = pool.admit([1, 2, 3], max_new_tokens=4)
    with pytest.raises(RuntimeError, match="no free decode slot"):
        pool.admit([4], max_new_tokens=1)
    pool.release(slot)
    with pytest.raises(ValueError, match="not active"):
        pool.release(slot)
    pool.admit([4], max_new_tokens=1)  # slot is reusable after release


def test_membership_churn_single_decode_signature_and_parity():
    """Continuous batching's enabling property: slots admit/retire while
    OTHER sequences are mid-decode, results still match naive generation,
    and the decode step never retraces (ONE XLA signature for the pool
    whatever its membership)."""
    cfg = _cfg()
    params = _params(cfg)
    rs = np.random.RandomState(2)
    long_p = rs.randint(1, 97, 4).tolist()
    short_a = rs.randint(1, 97, 6).tolist()
    short_b = rs.randint(1, 97, 2).tolist()

    pool = tfm.DecodeSlotPool(params, cfg, slots=2)
    slot_l, first_l = pool.admit(long_p, max_new_tokens=10)
    toks_l = [first_l]
    # run the long sequence alone for 3 steps
    for _ in range(3):
        toks_l.append(pool.step()[slot_l])
    traces_mid = pool.decode_traces
    # admit a short rider mid-flight (membership 1 -> 2)
    slot_a, first_a = pool.admit(short_a, max_new_tokens=3)
    toks_a = [first_a]
    while len(toks_a) < 3:
        out = pool.step()
        toks_l.append(out[slot_l])
        toks_a.append(out[slot_a])
    pool.release(slot_a)  # retire the rider (membership 2 -> 1)
    # refill the freed slot with a different sequence
    slot_b, first_b = pool.admit(short_b, max_new_tokens=2)
    toks_b = [first_b]
    while len(toks_l) < 10:
        out = pool.step()
        toks_l.append(out[slot_l])
        if slot_b in out and len(toks_b) < 2:
            toks_b.append(out[slot_b])
            if len(toks_b) == 2:
                pool.release(slot_b)
    pool.release(slot_l)

    assert toks_l == _naive_generate(params, cfg, long_p, 10)
    assert toks_a == _naive_generate(params, cfg, short_a, 3)
    assert toks_b == _naive_generate(params, cfg, short_b, 2)
    # the decode executable was traced exactly once, before AND after churn
    assert pool.decode_traces == 1
    assert traces_mid == 1


def test_prompt_bucketing_bounds_prefill_signatures():
    cfg = _cfg()
    params = _params(cfg)
    pool = tfm.DecodeSlotPool(params, cfg, slots=4, min_prompt_bucket=8)
    rs = np.random.RandomState(3)
    # lengths 2..8 share the 8-bucket; 9..16 the 16-bucket
    for n in (2, 5, 8, 3):
        slot, _ = pool.admit(rs.randint(1, 97, n).tolist(), 1)
        pool.release(slot)
    assert pool.prefill_traces == 1
    slot, _ = pool.admit(rs.randint(1, 97, 12).tolist(), 1)
    pool.release(slot)
    assert pool.prefill_traces == 2
    assert pool.prompt_bucket(2) == 8
    assert pool.prompt_bucket(12) == 16
    assert pool.prompt_bucket(63) == cfg.max_len  # clamped to the cache


def test_generate_eos_stops_early():
    cfg = _cfg()
    params = _params(cfg)
    prompt = [5, 9, 2]
    ref = _naive_generate(params, cfg, prompt, 8)
    eos = ref[2]  # force an early stop at the third generated token
    out = tfm.generate(params, [prompt], 8, cfg, eos_id=eos)
    assert out == [ref[:3]]


def test_generate_validates_args():
    cfg = _cfg()
    params = _params(cfg)
    assert tfm.generate(params, [], 4, cfg) == []
    with pytest.raises(ValueError, match="max_new_tokens"):
        tfm.generate(params, [[1, 2]], 0, cfg)


def test_failed_donated_call_resets_the_pool_not_poisons_it():
    """The jitted prefill/decode fns DONATE the KV buffers: a call that
    raises after dispatch leaves them consumed, so the pool must reset
    itself (fresh cache, all slots free, KvCacheLostError with the
    all_sequences_lost marker) — one transient fault must not turn every
    later admit/step into 'Array has been deleted'."""
    cfg = _cfg()
    params = _params(cfg)
    pool = tfm.DecodeSlotPool(params, cfg, slots=2)
    pool.admit([3, 1, 4], max_new_tokens=4)

    def boom(*a, **k):
        raise RuntimeError("injected device fault")

    real_decode = pool._decode_fn
    pool._decode_fn = boom
    with pytest.raises(tfm.KvCacheLostError) as ei:
        pool.step()
    assert ei.value.all_sequences_lost
    pool._decode_fn = real_decode
    # the pool healed: every slot free, and a fresh generation is correct
    assert pool.free_slots == pool.slots
    prompt = [5, 9, 2]
    out = tfm.generate(params, [prompt], 4, cfg, pool=pool)
    assert out == [_naive_generate(params, cfg, prompt, 4)]
