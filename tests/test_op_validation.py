"""The op-validation coverage GATE (SURVEY §4.2 OpValidation / §4.6 #1-2).

Every op in the registry has a TestCase: forward checked against an
independent numpy implementation, and (where differentiable) jax.grad
checked against central differences. The final test calls
``OpValidation.assert_coverage(all ops)`` — an op added to the registry
without a case here FAILS the suite, the reference's build-failing gate.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.autodiff.ops_registry import OPS
from deeplearning4j_tpu.autodiff.validation import (
    OpValidation,
    check_op_gradients,
    validate_op,
)

R = np.random.RandomState(7)
A = R.randn(3, 4).astype(np.float32)
B = R.randn(3, 4).astype(np.float32)
POS = (R.rand(3, 4).astype(np.float32) + 0.5)          # strictly positive
UNIT = (R.rand(3, 4).astype(np.float32) * 1.6 - 0.8)   # in (-0.8, 0.8)
OFF0 = A + np.sign(A) * 0.3                            # away from 0 kinks
IDX = np.array([2, 0, 1], np.int32)
SQ = R.randn(3, 3).astype(np.float32)
SPD = (SQ @ SQ.T + 3 * np.eye(3)).astype(np.float32)   # symmetric pos-def
IMG = R.randn(2, 3, 6, 6).astype(np.float32)           # NCHW
KER = (R.randn(4, 3, 3, 3) * 0.3).astype(np.float32)   # OIHW


def _np_softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def _np_conv2d(x, w, stride=(1, 1), padding="SAME"):
    N, C, H, W = x.shape
    O, _, kh, kw = w.shape
    if padding == "SAME":
        oh, ow = -(-H // stride[0]), -(-W // stride[1])
        ph = max((oh - 1) * stride[0] + kh - H, 0)
        pw = max((ow - 1) * stride[1] + kw - W, 0)
        x = np.pad(x, [(0, 0), (0, 0), (ph // 2, ph - ph // 2), (pw // 2, pw - pw // 2)])
    else:
        oh = (H - kh) // stride[0] + 1
        ow = (W - kw) // stride[1] + 1
    out = np.zeros((N, O, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = x[:, :, i * stride[0]:i * stride[0] + kh, j * stride[1]:j * stride[1] + kw]
            out[:, :, i, j] = np.einsum("nchw,ochw->no", patch, w)
    return out


def _np_lstm(x, h0, c0, wx, wh, b):
    sig = lambda v: 1 / (1 + np.exp(-v))  # noqa: E731
    h, c = h0.copy(), c0.copy()
    ys = []
    H = h0.shape[-1]
    for t in range(x.shape[0]):
        z = x[t] @ wx + h @ wh + b
        i, f, g, o = z[:, :H], z[:, H:2 * H], z[:, 2 * H:3 * H], z[:, 3 * H:]
        c = sig(f) * c + sig(i) * np.tanh(g)
        h = sig(o) * np.tanh(c)
        ys.append(h.copy())
    return np.stack(ys), h, c


def _np_gru(x, h0, wx, wh, b):
    sig = lambda v: 1 / (1 + np.exp(-v))  # noqa: E731
    h = h0.copy()
    H = h0.shape[-1]
    ys = []
    for t in range(x.shape[0]):
        xz = x[t] @ wx + b
        hz = h @ wh
        r = sig(xz[:, :H] + hz[:, :H])
        u = sig(xz[:, H:2 * H] + hz[:, H:2 * H])
        n = np.tanh(xz[:, 2 * H:] + r * hz[:, 2 * H:])
        h = (1 - u) * n + u * h
        ys.append(h.copy())
    return np.stack(ys), h


# Case = (args, kwargs, expected | checker(out, args) | None, grad_arg_indices)
# expected None → only a "runs + is finite/consistent" check; checker gets
# the raw op output for structural verification (qr reconstructs, etc).

_LSTM_ARGS = (R.randn(4, 2, 3).astype(np.float32), np.zeros((2, 5), np.float32),
              np.zeros((2, 5), np.float32), (R.randn(3, 20) * 0.4).astype(np.float32),
              (R.randn(5, 20) * 0.4).astype(np.float32), np.zeros(20, np.float32))
_GRU_ARGS = (R.randn(4, 2, 3).astype(np.float32), np.zeros((2, 5), np.float32),
             (R.randn(3, 15) * 0.4).astype(np.float32),
             (R.randn(5, 15) * 0.4).astype(np.float32), np.zeros(15, np.float32))
_ATTN = tuple((R.randn(2, 2, 4, 3) * 0.5).astype(np.float32) for _ in range(3))
_MH_X = (R.randn(2, 6, 5) * 0.5).astype(np.float32)
_MH_W = tuple((R.randn(4, 6) * 0.4).astype(np.float32) for _ in range(3))
_MH_WO = (R.randn(6, 4) * 0.4).astype(np.float32)

CASES = {
    # -------------------------------------------------------- broadcastable
    "add": ((A, B), {}, A + B, (0, 1)),
    "sub": ((A, B), {}, A - B, (0, 1)),
    "mul": ((A, B), {}, A * B, (0, 1)),
    "div": ((A, POS), {}, A / POS, (0, 1)),
    "rdiv": ((POS, A), {}, A / POS, (0, 1)),
    "rsub": ((A, B), {}, B - A, (0, 1)),
    "pow": ((POS, B), {}, POS ** B, (0, 1)),
    "floordiv": ((A, POS), {}, np.floor_divide(A, POS), ()),
    "mod": ((POS, POS.T.reshape(3, 4) + 1), {}, np.mod(POS, POS.T.reshape(3, 4) + 1), ()),
    "maximum": ((A, B), {}, np.maximum(A, B), (0, 1)),
    "minimum": ((A, B), {}, np.minimum(A, B), (0, 1)),
    "squared_difference": ((A, B), {}, (A - B) ** 2, (0, 1)),
    "atan2": ((POS, POS + 1), {}, np.arctan2(POS, POS + 1), (0, 1)),
    # ------------------------------------------------------------- compare
    "eq": ((IDX, IDX), {}, np.ones(3, bool), ()),
    "neq": ((IDX, IDX[::-1].copy()), {}, IDX != IDX[::-1], ()),
    "gt": ((A, B), {}, A > B, ()),
    "gte": ((A, B), {}, A >= B, ()),
    "lt": ((A, B), {}, A < B, ()),
    "lte": ((A, B), {}, A <= B, ()),
    "and": ((A > 0, B > 0), {}, (A > 0) & (B > 0), ()),
    "or": ((A > 0, B > 0), {}, (A > 0) | (B > 0), ()),
    "xor": ((A > 0, B > 0), {}, (A > 0) ^ (B > 0), ()),
    "not": ((A > 0,), {}, ~(A > 0), ()),
    # ---------------------------------------------------------- elementwise
    "abs": ((OFF0,), {}, np.abs(OFF0), (0,)),
    "neg": ((A,), {}, -A, (0,)),
    "sign": ((OFF0,), {}, np.sign(OFF0), ()),
    "ceil": ((A,), {}, np.ceil(A), ()),
    "floor": ((A,), {}, np.floor(A), ()),
    "round": ((A,), {}, np.round(A), ()),
    "exp": ((UNIT,), {}, np.exp(UNIT), (0,)),
    "expm1": ((UNIT,), {}, np.expm1(UNIT), (0,)),
    "log": ((POS,), {}, np.log(POS), (0,)),
    "log1p": ((POS,), {}, np.log1p(POS), (0,)),
    "log2": ((POS,), {}, np.log2(POS), (0,)),
    "sqrt": ((POS,), {}, np.sqrt(POS), (0,)),
    "rsqrt": ((POS,), {}, 1 / np.sqrt(POS), (0,)),
    "square": ((A,), {}, A ** 2, (0,)),
    "cube": ((A,), {}, A ** 3, (0,)),
    "reciprocal": ((POS,), {}, 1 / POS, (0,)),
    "sin": ((A,), {}, np.sin(A), (0,)),
    "cos": ((A,), {}, np.cos(A), (0,)),
    "tan": ((UNIT,), {}, np.tan(UNIT), (0,)),
    "asin": ((UNIT,), {}, np.arcsin(UNIT), (0,)),
    "acos": ((UNIT,), {}, np.arccos(UNIT), (0,)),
    "atan": ((A,), {}, np.arctan(A), (0,)),
    "sinh": ((UNIT,), {}, np.sinh(UNIT), (0,)),
    "cosh": ((UNIT,), {}, np.cosh(UNIT), (0,)),
    "tanh": ((A,), {}, np.tanh(A), (0,)),
    "asinh": ((A,), {}, np.arcsinh(A), (0,)),
    "acosh": ((POS + 1,), {}, np.arccosh(POS + 1), (0,)),
    "atanh": ((UNIT,), {}, np.arctanh(UNIT), (0,)),
    "erf": ((A,), {}, None, (0,)),
    "erfc": ((A,), {}, None, (0,)),
    "isnan": ((A,), {}, np.isnan(A), ()),
    "isinf": ((A,), {}, np.isinf(A), ()),
    "isfinite": ((A,), {}, np.isfinite(A), ()),
    "clip_by_value": ((A, -0.5, 0.5), {}, np.clip(A, -0.5, 0.5), ()),
    # ---------------------------------------------------------- activations
    "relu": ((OFF0,), {}, np.maximum(OFF0, 0), (0,)),
    "relu6": ((OFF0,), {}, np.clip(OFF0, 0, 6), (0,)),
    "leaky_relu": ((OFF0,), {}, np.where(OFF0 > 0, OFF0, 0.01 * OFF0), (0,)),
    "elu": ((OFF0,), {}, np.where(OFF0 > 0, OFF0, np.expm1(OFF0)), (0,)),
    "selu": ((OFF0,), {}, None, (0,)),
    "gelu": ((A,), {}, None, (0,)),
    "sigmoid": ((A,), {}, 1 / (1 + np.exp(-A)), (0,)),
    "hard_sigmoid": ((OFF0,), {}, None, ()),
    "hard_tanh": ((OFF0 * 2,), {}, np.clip(OFF0 * 2, -1, 1), ()),
    "softplus": ((A,), {}, np.log1p(np.exp(A)), (0,)),
    "softsign": ((A,), {}, A / (1 + np.abs(A)), (0,)),
    "swish": ((A,), {}, A / (1 + np.exp(-A)), (0,)),
    "mish": ((A,), {}, A * np.tanh(np.log1p(np.exp(A))), (0,)),
    "softmax": ((A,), {}, _np_softmax(A), (0,)),
    "log_softmax": ((A,), {}, np.log(_np_softmax(A)), (0,)),
    # ----------------------------------------------------------- reductions
    "reduce_sum": ((A,), dict(dims=1), A.sum(1), (0,)),
    "reduce_mean": ((A,), dict(dims=0), A.mean(0), (0,)),
    "reduce_max": ((A,), dict(dims=1), A.max(1), (0,)),
    "reduce_min": ((A,), dict(dims=1), A.min(1), (0,)),
    "reduce_prod": ((POS,), dict(dims=1), POS.prod(1), (0,)),
    "reduce_std": ((A,), dict(dims=1), A.std(1), (0,)),
    "reduce_var": ((A,), dict(dims=1), A.var(1), (0,)),
    "reduce_all": ((A > -10,), dict(dims=1), np.all(A > -10, 1), ()),
    "reduce_any": ((A > 0,), dict(dims=1), np.any(A > 0, 1), ()),
    "norm1": ((A,), dict(dims=1), np.abs(A).sum(1), ()),
    "norm2": ((A,), dict(dims=1), np.sqrt((A ** 2).sum(1)), (0,)),
    "normmax": ((A,), dict(dims=1), np.abs(A).max(1), ()),
    "argmax": ((A,), dict(dims=1), A.argmax(1), ()),
    "argmin": ((A,), dict(dims=1), A.argmin(1), ()),
    "cumsum": ((A,), dict(axis=1), A.cumsum(1), (0,)),
    "cumprod": ((POS,), dict(axis=1), POS.cumprod(1), (0,)),
    "trace": ((SQ,), {}, np.trace(SQ), (0,)),
    # ---------------------------------------------------------------- shape
    "reshape": ((A, (4, 3)), {}, A.reshape(4, 3), (0,)),
    "permute": ((IMG, (0, 2, 3, 1)), {}, IMG.transpose(0, 2, 3, 1), (0,)),
    "transpose": ((A,), {}, A.T, (0,)),
    "expand_dims": ((A, 1), {}, A[:, None, :], (0,)),
    "squeeze": ((A[:, None, :], 1), {}, A, (0,)),
    "slice": ((A, (1, 0), (2, 3)), {}, A[1:3, 0:3], (0,)),
    "strided_slice": ((A, (0, 1), (3, 4), (2, 2)), {}, A[0:3:2, 1:4:2], (0,)),
    "split": ((A, 2), dict(axis=1), None, (0,)),
    "stack": ((A, B), dict(axis=0), np.stack([A, B]), (0, 1)),
    "unstack": ((A,), dict(axis=0), None, (0,)),
    "concat": ((A, B), dict(axis=1), np.concatenate([A, B], 1), (0, 1)),
    "tile": ((A, (2, 1)), {}, np.tile(A, (2, 1)), (0,)),
    "reverse": ((A, 1), {}, A[:, ::-1], (0,)),
    "flip": ((A, 0), {}, A[::-1], (0,)),
    "pad": ((A, ((1, 0), (0, 2))), {}, np.pad(A, ((1, 0), (0, 2))), (0,)),
    "gather": ((A, IDX), dict(axis=0), A[IDX], (0,)),
    "gather_nd": ((A, np.array([[0, 1], [2, 3]], np.int32)), {}, A[[0, 2], [1, 3]], (0,)),
    "one_hot": ((IDX, 4), {}, np.eye(4, dtype=np.float32)[IDX], ()),
    "ones_like": ((A,), {}, np.ones_like(A), ()),
    "zeros_like": ((A,), {}, np.zeros_like(A), ()),
    "eye": ((3,), {}, np.eye(3), ()),
    "linspace": ((0.0, 1.0, 5), {}, np.linspace(0, 1, 5), ()),
    "range": ((0, 6, 2), {}, np.arange(0, 6, 2), ()),
    "shape_of": ((IMG,), {}, np.array(IMG.shape), ()),
    "size": ((A,), {}, A.size, ()),
    "rank": ((IMG,), {}, 4, ()),
    "where": ((A > 0, A, B), {}, np.where(A > 0, A, B), ()),
    "meshgrid": ((np.arange(3.0), np.arange(2.0)), {}, None, ()),
    "diag": ((np.arange(3.0),), {}, np.diag(np.arange(3.0)), ()),
    "space_to_depth": ((IMG, 2), {}, None, (0,)),
    "cast": ((A, jnp.int32), {}, A.astype(np.int32), ()),
    "dynamic_stitch": (
        ([np.array([0, 2], np.int32), np.array([1, 3], np.int32)],
         [np.array([[1.0, 1], [3, 3]], np.float32), np.array([[2.0, 2], [4, 4]], np.float32)]),
        {}, np.array([[1, 1], [2, 2], [3, 3], [4, 4]], np.float32), ()),
    # ------------------------------------------------------ scatter/segment
    "scatter_add": ((jnp.zeros((4, 2)), IDX, np.ones((3, 2), np.float32)),
                    {}, np.eye(4, 2, k=0) * 0 + np.array([[1., 1], [1, 1], [1, 1], [0, 0]]), ()),
    "scatter_update": ((jnp.zeros((4, 2)), IDX, np.ones((3, 2), np.float32)),
                       {}, np.array([[1., 1], [1, 1], [1, 1], [0, 0]]), ()),
    "scatter_max": ((jnp.full((4, 2), 0.5), IDX, np.ones((3, 2), np.float32)),
                    {}, np.array([[1., 1], [1, 1], [1, 1], [0.5, 0.5]]), ()),
    "segment_sum": ((np.arange(6.0, dtype=np.float32),
                     np.array([0, 0, 1, 1, 2, 2], np.int32)),
                    dict(num_segments=3), np.array([1.0, 5.0, 9.0]), ()),
    # --------------------------------------------------------------- linalg
    "matmul": ((A, B.T.copy()), {}, A @ B.T, (0, 1)),
    "batched_gemm": ((np.stack([A, A]), np.stack([B.T, B.T])), {},
                     np.stack([A @ B.T, A @ B.T]), (0, 1)),
    "tensormmul": ((A, B, (1,), (1,)), {}, np.tensordot(A, B, axes=((1,), (1,))), (0, 1)),
    "dot": ((A[0], B[0]), {}, A[0] @ B[0], (0, 1)),
    "outer": ((A[0], B[0]), {}, np.outer(A[0], B[0]), (0, 1)),
    "linear": ((A, B.T.copy(), np.ones(3, np.float32)), {}, A @ B.T + 1, (0, 1, 2)),
    "cholesky": ((SPD,), {},
                 lambda out, args: np.testing.assert_allclose(
                     np.asarray(out) @ np.asarray(out).T, SPD, atol=1e-4), ()),
    "matrix_inverse": ((SPD,), {}, np.linalg.inv(SPD), ()),
    "matrix_determinant": ((SPD,), {}, np.linalg.det(SPD), ()),
    "solve": ((SPD, A[:, :2].copy()), {}, np.linalg.solve(SPD, A[:, :2]), ()),
    "qr": ((SQ,), {},
           lambda out, args: np.testing.assert_allclose(
               np.asarray(out[0]) @ np.asarray(out[1]), SQ, atol=1e-4), ()),
    "svd": ((SQ,), {},
            lambda out, args: np.testing.assert_allclose(
                np.asarray(out[0]) @ np.diag(np.asarray(out[1])) @ np.asarray(out[2]),
                SQ, atol=1e-4), ()),
    # ------------------------------------------------------------------- nn
    "conv2d": ((IMG, KER), dict(padding="SAME"), _np_conv2d(IMG, KER), (0, 1)),
    "max_pool2d": ((IMG,), {}, IMG.reshape(2, 3, 3, 2, 3, 2).max((3, 5)), (0,)),
    "avg_pool2d": ((IMG,), {}, IMG.reshape(2, 3, 3, 2, 3, 2).mean((3, 5)), (0,)),
    "batch_norm": ((IMG, np.zeros(3, np.float32), np.ones(3, np.float32),
                    np.ones(3, np.float32), np.zeros(3, np.float32)),
                   dict(eps=0.0), IMG, (0,)),
    "layer_norm": ((A, np.ones(4, np.float32), np.zeros(4, np.float32)), {},
                   (A - A.mean(-1, keepdims=True))
                   / np.sqrt(A.var(-1, keepdims=True) + 1e-5), (0, 1)),
    "embedding_lookup": ((A, IDX), {}, A[IDX], (0,)),
    "dropout": ((np.ones((50, 50), np.float32), jax.random.key(0)),
                dict(keep_prob=0.8),
                lambda out, args: np.testing.assert_allclose(
                    float(np.mean(np.asarray(out))), 1.0, atol=0.05), ()),
    "lstm_layer": (_LSTM_ARGS, {},
                   lambda out, args: np.testing.assert_allclose(
                       np.asarray(out[0]), _np_lstm(*[np.asarray(a) for a in _LSTM_ARGS])[0],
                       rtol=1e-4, atol=1e-5), (3, 4)),
    "gru": (_GRU_ARGS, {},
            lambda out, args: np.testing.assert_allclose(
                np.asarray(out[0]), _np_gru(*[np.asarray(a) for a in _GRU_ARGS])[0],
                rtol=1e-4, atol=1e-5), (2, 3)),
    "dot_product_attention": (_ATTN, {},
                              lambda out, args: np.testing.assert_allclose(
                                  np.asarray(out),
                                  _np_softmax(np.einsum("bhqd,bhkd->bhqk", *_ATTN[:2])
                                              / np.sqrt(3)) @ _ATTN[2],
                                  rtol=1e-4, atol=1e-5), (0, 1, 2)),
    "multi_head_dot_product_attention": ((_MH_X, _MH_X, _MH_X) + _MH_W + (_MH_WO, 2),
                                         {}, None, (0,)),
    # --------------------------------------------------------------- losses
    "mean_squared_error": ((A, B), {}, ((A - B) ** 2).mean(), (1,)),
    "mean_absolute_error": ((A, B), {}, np.abs(A - B).mean(), (1,)),
    "huber_loss": ((A, B), {}, None, (1,)),
    "log_loss": ((POS / 2, POS / 2 + 0.1), {}, None, (1,)),
    "sigmoid_cross_entropy": (((A > 0).astype(np.float32), B), {},
                              np.mean(np.maximum(B, 0) - B * (A > 0)
                                      + np.log1p(np.exp(-np.abs(B)))), (1,)),
    "softmax_cross_entropy": ((np.eye(4, dtype=np.float32)[IDX], A), {},
                              np.mean(-(np.eye(4)[IDX] * np.log(_np_softmax(A))).sum(-1)),
                              (1,)),
    "sparse_softmax_cross_entropy": ((IDX, A), {},
                                     np.mean(-np.log(_np_softmax(A))[np.arange(3), IDX]),
                                     (1,)),
    "cosine_distance": ((A, B), {},
                        1 - (A * B).sum(-1) / (np.linalg.norm(A, axis=-1)
                                               * np.linalg.norm(B, axis=-1)), (0, 1)),
    # --------------------------------------------------------------- random
    "random_normal": ((jax.random.key(0), (500,)), {},
                      lambda out, args: abs(float(np.mean(np.asarray(out)))) < 0.2, ()),
    "random_uniform": ((jax.random.key(0), (500,)), {},
                       lambda out, args: 0.0 <= float(np.min(np.asarray(out))) <= 1.0, ()),
    "random_bernoulli": ((jax.random.key(0), (500,)), {},
                         lambda out, args: 0.3 < float(np.mean(np.asarray(out))) < 0.7, ()),
}

# ---------------------------------------------------------- corpus wave 2
INT_A = np.array([[5, 3], [12, 7]], np.int32)
INT_B = np.array([[3, 1], [6, 2]], np.int32)
SEG_X = np.arange(6.0, dtype=np.float32) + 1
SEG_ID = np.array([0, 0, 1, 1, 2, 2], np.int32)
PROB = (POS / POS.sum(-1, keepdims=True)).astype(np.float32)  # rows sum to 1
IMG5 = R.randn(1, 2, 4, 4, 4).astype(np.float32)              # NCDHW
K1 = (R.randn(3, 3, 3) * 0.3).astype(np.float32)              # OIW
K3 = (R.randn(2, 2, 2, 2, 2) * 0.3).astype(np.float32)        # OIDHW
KDW = (R.randn(3, 1, 3, 3) * 0.3).astype(np.float32)          # depthwise C=3
KTR = (R.randn(3, 2, 2, 2) * 0.3).astype(np.float32)          # IOHW deconv

def _np_lrn(x, dr=2, bias=1.0, alpha=1.0, beta=0.5):
    out = np.zeros_like(x)
    C = x.shape[1]
    for c in range(C):
        lo, hi = max(0, c - dr), min(C, c + dr + 1)
        s = (x[:, lo:hi] ** 2).sum(1)
        out[:, c] = x[:, c] / (bias + alpha * s) ** beta
    return out

CASES.update({
    "rint": ((A,), {}, np.rint(A), ()),
    "trunc": ((A,), {}, np.trunc(A), ()),
    "fmod": ((A, POS), {}, np.fmod(A, POS), ()),
    "log_sigmoid": ((A,), {}, -np.log1p(np.exp(-A)), (0,)),
    "prelu": ((OFF0, np.float32(0.2)), {}, np.where(OFF0 > 0, OFF0, 0.2 * OFF0), (0,)),
    "thresholded_relu": ((OFF0,), {}, np.where(OFF0 > 1.0, OFF0, 0.0), ()),
    "rectified_tanh": ((OFF0,), {}, np.maximum(np.tanh(OFF0), 0), ()),
    "hard_swish": ((OFF0,), {}, OFF0 * np.clip(OFF0 + 3, 0, 6) / 6, (0,)),
    "log10": ((POS,), {}, np.log10(POS), (0,)),
    "erfinv": ((UNIT,), {}, None, (0,)),
    "lgamma": ((POS + 1,), {}, None, (0,)),
    "digamma": ((POS + 1,), {}, None, (0,)),
    "polygamma": ((1, POS + 1), {}, None, ()),
    "igamma": ((POS + 1, POS), {}, None, ()),
    "igammac": ((POS + 1, POS), {}, None, ()),
    "betainc": ((POS + 1, POS + 1, PROB), {}, None, ()),
    "swapaxes": ((A, 0, 1), {}, A.T, (0,)),
    "l2_normalize": ((A,), {}, A / np.linalg.norm(A, axis=-1, keepdims=True), (0,)),
    "clip_by_norm": ((A, 1.0), {}, A / max(np.linalg.norm(A), 1.0), (0,)),
    "standardize": ((A,), {},
                    (A - A.mean(-1, keepdims=True)) / A.std(-1, keepdims=True), (0,)),
    "entropy": ((PROB,), dict(dims=1), -(PROB * np.log(PROB)).sum(1), (0,)),
    "log_entropy": ((PROB,), dict(dims=1),
                    np.log(-(PROB * np.log(PROB)).sum(1)), ()),
    "shannon_entropy": ((PROB,), dict(dims=1), -(PROB * np.log2(PROB)).sum(1), ()),
    "euclidean_distance": ((A, B), dict(dims=1),
                           np.sqrt(((A - B) ** 2).sum(1)), (0, 1)),
    "manhattan_distance": ((A, B), dict(dims=1), np.abs(A - B).sum(1), ()),
    "cosine_similarity": ((A, B), {},
                          (A * B).sum(-1) / (np.linalg.norm(A, axis=-1)
                                             * np.linalg.norm(B, axis=-1)), (0, 1)),
    "hamming_distance": ((INT_A, INT_B), {},
                         np.sum(INT_A != INT_B).astype(np.float32), ()),
    "jaccard_distance": ((POS, POS.T.reshape(3, 4)), {},
                         1 - np.minimum(POS, POS.T.reshape(3, 4)).sum()
                         / np.maximum(POS, POS.T.reshape(3, 4)).sum(), ()),
    "broadcast_to": ((A[0], (3, 4)), {}, np.broadcast_to(A[0], (3, 4)), ()),
    "repeat": ((A, 2), dict(axis=1), np.repeat(A, 2, axis=1), (0,)),
    "roll": ((A, 1), dict(axis=0), np.roll(A, 1, axis=0), (0,)),
    "sort": ((A,), {}, np.sort(A, axis=-1), (0,)),
    "argsort": ((A,), {}, np.argsort(A, axis=-1), ()),
    "triu": ((SQ,), {}, np.triu(SQ), (0,)),
    "tril": ((SQ,), {}, np.tril(SQ), (0,)),
    "fill": (((2, 3), 7.0), {}, np.full((2, 3), 7.0), ()),
    "zeros": (((2, 2),), {}, np.zeros((2, 2)), ()),
    "ones": (((2, 2),), {}, np.ones((2, 2)), ()),
    "full_like": ((A, 5.0), {}, np.full_like(A, 5.0), ()),
    "sequence_mask": ((np.array([1, 3], np.int32), 4), {},
                      np.array([[1, 0, 0, 0], [1, 1, 1, 0]], bool), ()),
    "reverse_sequence": ((A, np.array([2, 3, 1], np.int32)), {},
                         np.stack([np.concatenate([A[0][:2][::-1], A[0][2:]]),
                                   np.concatenate([A[1][:3][::-1], A[1][3:]]),
                                   A[2]]), ()),
    "depth_to_space": ((R.randn(1, 8, 2, 2).astype(np.float32), 2), {},
                       lambda out, args: np.testing.assert_allclose(
                           np.asarray(OPS["space_to_depth"](out, 2)), args[0],
                           rtol=1e-6), ()),
    "is_non_decreasing": ((np.array([1.0, 2.0, 2.0]),), {}, True, ()),
    "is_strictly_increasing": ((np.array([1.0, 2.0, 2.0]),), {}, False, ()),
    "bincount": ((np.array([0, 1, 1, 3], np.int32),), dict(minlength=5),
                 np.array([1, 2, 0, 1, 0]), ()),
    "confusion_matrix": ((np.array([0, 1, 1], np.int32),
                          np.array([0, 1, 0], np.int32), 2), {},
                         np.array([[1, 0], [1, 1]]), ()),
    "bitwise_and": ((INT_A, INT_B), {}, INT_A & INT_B, ()),
    "bitwise_or": ((INT_A, INT_B), {}, INT_A | INT_B, ()),
    "bitwise_xor": ((INT_A, INT_B), {}, INT_A ^ INT_B, ()),
    "left_shift": ((INT_A, np.int32(1)), {}, INT_A << 1, ()),
    "right_shift": ((INT_A, np.int32(1)), {}, INT_A >> 1, ()),
    "cyclic_shift_bits": ((INT_A.astype(np.uint32), np.uint32(4)), {},
                          (INT_A.astype(np.uint32) << np.uint32(4))
                          | (INT_A.astype(np.uint32) >> np.uint32(28)), ()),
    "matrix_diag": ((A,), {}, np.stack([np.diag(r) for r in A]), ()),
    "matrix_diag_part": ((np.stack([SQ, SQ]),), {},
                         np.stack([np.diag(SQ)] * 2), ()),
    "matrix_band_part": ((SQ, 0, -1), {}, np.triu(SQ), ()),
    "cross": ((A[:, :3], B[:, :3]), {}, np.cross(A[:, :3], B[:, :3]), (0, 1)),
    "slogdet": ((SPD,), {},
                lambda out, args: np.testing.assert_allclose(
                    float(out[0]) * np.exp(float(out[1])), np.linalg.det(SPD),
                    rtol=1e-4), ()),
    "triangular_solve": ((np.tril(SPD), A[:, :2].copy()), {},
                         np.linalg.solve(np.tril(SPD), A[:, :2]), ()),
    "eigh": ((SPD,), {},
             lambda out, args: np.testing.assert_allclose(
                 np.asarray(out[1]) @ np.diag(np.asarray(out[0]))
                 @ np.asarray(out[1]).T, SPD, atol=1e-3), ()),
    "lstsq": ((SPD, A[:, :2].copy()), {},
              np.linalg.lstsq(SPD, A[:, :2], rcond=None)[0], ()),
    "segment_max": ((SEG_X, SEG_ID), dict(num_segments=3),
                    np.array([2.0, 4.0, 6.0]), ()),
    "segment_min": ((SEG_X, SEG_ID), dict(num_segments=3),
                    np.array([1.0, 3.0, 5.0]), ()),
    "segment_prod": ((SEG_X, SEG_ID), dict(num_segments=3),
                     np.array([2.0, 12.0, 30.0]), ()),
    "segment_mean": ((SEG_X, SEG_ID), dict(num_segments=3),
                     np.array([1.5, 3.5, 5.5]), ()),
    "unsorted_segment_sum": ((SEG_X, SEG_ID), dict(num_segments=3),
                             np.array([3.0, 7.0, 11.0]), ()),
    "scatter_sub": ((jnp.full((4, 2), 5.0), IDX, np.ones((3, 2), np.float32)),
                    {}, np.array([[4.0, 4], [4, 4], [4, 4], [5, 5]]), ()),
    "scatter_mul": ((jnp.full((4, 2), 5.0), IDX, np.full((3, 2), 2.0, np.float32)),
                    {}, np.array([[10.0, 10], [10, 10], [10, 10], [5, 5]]), ()),
    "scatter_div": ((jnp.full((4, 2), 6.0), IDX, np.full((3, 2), 2.0, np.float32)),
                    {}, np.array([[3.0, 3], [3, 3], [3, 3], [6, 6]]), ()),
    "scatter_min": ((jnp.full((4, 2), 0.5), IDX, np.zeros((3, 2), np.float32)),
                    {}, np.array([[0.0, 0], [0, 0], [0, 0], [0.5, 0.5]]), ()),
    "moments": ((A,), dict(dims=1),
                lambda out, args: (np.testing.assert_allclose(
                    np.asarray(out[0]), A.mean(1), rtol=1e-5, atol=1e-6),
                    np.testing.assert_allclose(
                        np.asarray(out[1]), A.var(1), rtol=1e-5, atol=1e-6)), (0,)),
    "top_k": ((A, 2), {},
              lambda out, args: np.testing.assert_allclose(
                  np.asarray(out[0]), np.sort(A, -1)[:, ::-1][:, :2],
                  rtol=1e-6), ()),
    "in_top_k": ((IDX, A, 2), {},
                 lambda out, args: np.asarray(out).shape == (3,), ()),
    "conv1d": ((IMG[:, :, :, 0].copy(), K1), {}, None, (0, 1)),
    "conv3d": ((IMG5, K3), {}, None, (0, 1)),
    "depthwise_conv2d": ((IMG, KDW), {}, None, (0, 1)),
    "deconv2d": ((IMG[:, :3][:, :3].copy(), KTR), {}, None, (0,)),
    "upsampling2d": ((IMG, 2), {}, np.repeat(np.repeat(IMG, 2, 2), 2, 3), (0,)),
    "max_pool3d": ((IMG5,), {}, IMG5.reshape(1, 2, 2, 2, 2, 2, 2, 2).max((3, 5, 7)), (0,)),
    "avg_pool3d": ((IMG5,), {}, IMG5.reshape(1, 2, 2, 2, 2, 2, 2, 2).mean((3, 5, 7)), (0,)),
    "lrn": ((IMG,), dict(depth_radius=2), _np_lrn(IMG, 2), (0,)),
    "resize_bilinear": ((IMG, (12, 12)), {}, None, (0,)),
    "resize_nearest_neighbor": ((IMG, (12, 12)), {},
                                np.repeat(np.repeat(IMG, 2, 2), 2, 3), ()),
    "adjust_contrast": ((IMG, 2.0), {},
                        (IMG - IMG.mean((-2, -1), keepdims=True)) * 2
                        + IMG.mean((-2, -1), keepdims=True), (0,)),
    "hinge_loss": ((np.sign(A), B), {},
                   np.mean(np.maximum(0, 1 - np.sign(A) * B)), (1,)),
    "squared_hinge_loss": ((np.sign(A), B), {},
                           np.mean(np.maximum(0, 1 - np.sign(A) * B) ** 2), (1,)),
    "poisson_loss": ((POS, POS + 0.3), {},
                     np.mean((POS + 0.3) - POS * np.log(POS + 0.3 + 1e-12)), (1,)),
    "kl_divergence": ((PROB, np.roll(PROB, 1, 0)), {}, None, (1,)),
    "weighted_cross_entropy_with_logits": (((A > 0).astype(np.float32), B, 2.0),
                                           {}, None, (1,)),
    "absolute_difference": ((A, B), {}, np.abs(A - B).mean(), (1,)),
    "random_exponential": ((jax.random.key(0), (500,)), {},
                           lambda out, args: float(np.min(np.asarray(out))) >= 0, ()),
    "random_gamma": ((jax.random.key(0), (500,)), {},
                     lambda out, args: float(np.min(np.asarray(out))) >= 0, ()),
    "random_poisson": ((jax.random.key(0), (500,)), dict(lam=3.0),
                       lambda out, args: 2.0 < float(np.mean(np.asarray(out))) < 4.0, ()),
    "random_shuffle": ((jax.random.key(0), A), {},
                       lambda out, args: np.testing.assert_allclose(
                           np.sort(np.asarray(out), 0), np.sort(A, 0)), ()),
})


# ---------------------------------------------------------- corpus wave 3
# (VERDICT r3 missing #2: CTC, fused RNN, unsorted segments, TF-compat
# image/space-batch, linalg tail, skipgram/cbow registry ops)

RGB = R.rand(2, 3, 3, 3).astype(np.float32)            # [...,3] channels-last
BOXES = np.array([[0, 0, 1, 1], [0, 0, 1, 1.1], [2, 2, 3, 3]], np.float32)
NHWC = R.randn(2, 6, 6, 3).astype(np.float32)
SYN0 = (R.randn(8, 5) * 0.1).astype(np.float32)
SYN1 = (R.randn(8, 5) * 0.1).astype(np.float32)
CTC_LOGITS = R.randn(2, 4, 3).astype(np.float32)
CTC_LABELS = np.array([[1, 2], [2, 0]], np.int32)
CTC_LAB_LEN = np.array([2, 1], np.int32)
CTC_LOG_LEN = np.array([4, 3], np.int32)
_PEEP = tuple((R.rand(5).astype(np.float32) * 0.3) for _ in range(3))
_SRU_ARGS = (R.randn(4, 2, 3).astype(np.float32), np.zeros((2, 5), np.float32),
             (R.randn(3, 5) * 0.5).astype(np.float32),
             (R.randn(3, 5) * 0.5).astype(np.float32),
             (R.randn(3, 5) * 0.5).astype(np.float32),
             np.zeros(5, np.float32), np.zeros(5, np.float32))


def _np_ctc_loss(labels, logits, label_lens, logit_lens, blank=0):
    from itertools import product
    logp = np.log(_np_softmax(logits))
    B, T, C = logp.shape
    losses = []
    for b in range(B):
        lab = tuple(labels[b][:label_lens[b]])
        total = -np.inf
        for path in product(range(C), repeat=int(logit_lens[b])):
            col, prev = [], -1
            for s in path:
                if s != prev and s != blank:
                    col.append(s)
                prev = s
            if tuple(col) == lab:
                total = np.logaddexp(total, sum(logp[b, t, s] for t, s in enumerate(path)))
        losses.append(-total)
    return np.float32(np.mean(losses))


def _np_lstm_peep(x, h0, c0, wx, wh, b, wci, wcf, wco):
    sig = lambda v: 1 / (1 + np.exp(-v))  # noqa: E731
    h, c = h0.copy(), c0.copy()
    H = h0.shape[-1]
    ys = []
    for t in range(x.shape[0]):
        z = x[t] @ wx + h @ wh + b
        i, f, g, o = z[:, :H], z[:, H:2 * H], z[:, 2 * H:3 * H], z[:, 3 * H:]
        i = i + c * wci
        f = f + c * wcf
        c = sig(f) * c + sig(i) * np.tanh(g)
        o = o + c * wco
        h = sig(o) * np.tanh(c)
        ys.append(h.copy())
    return np.stack(ys), h, c


def _np_sru(x, c0, w, wf, wr, bf, br):
    sig = lambda v: 1 / (1 + np.exp(-v))  # noqa: E731
    c = c0.copy()
    hs = []
    for t in range(x.shape[0]):
        xt = x[t] @ w
        f = sig(x[t] @ wf + bf)
        r = sig(x[t] @ wr + br)
        c = f * c + (1 - f) * xt
        hs.append(r * np.tanh(c) + (1 - r) * xt)
    return np.stack(hs), c


def _np_skipgram(syn0, syn1, center, ctx, negs, lr):
    d0, d1 = np.zeros_like(syn0), np.zeros_like(syn1)
    for bi in range(len(center)):
        h = syn0[center[bi]]
        for t, lab in zip([ctx[bi]] + list(negs[bi]), [1.0] + [0.0] * negs.shape[1]):
            g = (1 / (1 + np.exp(-h @ syn1[t])) - lab) * lr
            d0[center[bi]] -= g * syn1[t]
            d1[t] -= g * h
    return syn0 + d0, syn1 + d1


def _np_cbow(syn0, syn1, ctxw, target, negs, lr):
    d0, d1 = np.zeros_like(syn0), np.zeros_like(syn1)
    W = ctxw.shape[1]
    for bi in range(len(target)):
        h = syn0[ctxw[bi]].mean(0)
        for t, lab in zip([target[bi]] + list(negs[bi]), [1.0] + [0.0] * negs.shape[1]):
            g = (1 / (1 + np.exp(-h @ syn1[t])) - lab) * lr
            for cw in ctxw[bi]:
                d0[cw] -= g * syn1[t]  # undivided neu1e, word2vec.c semantics
            d1[t] -= g * h
    return syn0 + d0, syn1 + d1


def _np_patches_nhwc(x, kh, kw, sh, sw):
    B, H, W, C = x.shape
    oh, ow = (H - kh) // sh + 1, (W - kw) // sw + 1
    out = np.zeros((B, oh, ow, kh * kw * C), x.dtype)
    for i in range(oh):
        for j in range(ow):
            out[:, i, j] = x[:, i * sh:i * sh + kh, j * sw:j * sw + kw, :].reshape(B, -1)
    return out


_S2B_X = np.arange(1.0, 17.0, dtype=np.float32).reshape(1, 4, 4, 1)
_SEG_2D = R.randn(6, 2).astype(np.float32)
_USEG_ID = np.array([0, 2, 0, 1, 2, 2], np.int32)
_GN_LIST = [A.copy(), B.copy()]
_PERM = np.array([2, 0, 3, 1], np.int32)
_SC_B = 1.0 + POS[0, :3]


def _ref_op(name):
    return OPS[name]


CASES.update({
    # ctc family
    "ctc_loss": ((CTC_LABELS, CTC_LOGITS, CTC_LAB_LEN, CTC_LOG_LEN), {},
                 _np_ctc_loss(CTC_LABELS, CTC_LOGITS, CTC_LAB_LEN, CTC_LOG_LEN), (1,)),
    "ctc_greedy_decoder": ((np.array([[[0.1, 5, 0.1], [0.1, 5, 0.1], [5, 0.1, 0.1],
                                       [0.1, 0.1, 5]]], np.float32),), {},
                           lambda out, args: (
                               np.testing.assert_array_equal(
                                   np.asarray(out[0])[0, :2], [1, 2]),
                               np.testing.assert_array_equal(np.asarray(out[1]), [2])), ()),
    "ctc_beam_search_decoder": ((np.array([[[0.1, 5, 0.1], [0.1, 5, 0.1], [5, 0.1, 0.1],
                                            [0.1, 0.1, 5]]], np.float32),), {},
                                lambda out, args: out[0][0][0] == (1, 2), ()),
    # fused recurrent
    "lstm_cell": ((_LSTM_ARGS[0][0],) + _LSTM_ARGS[1:], {},
                  lambda out, args: np.testing.assert_allclose(
                      np.asarray(out[0]),
                      _np_lstm(_LSTM_ARGS[0][:1], *_LSTM_ARGS[1:])[1],
                      rtol=1e-4, atol=1e-5), (3, 4)),
    "lstm_block": (_LSTM_ARGS + _PEEP, {},
                   lambda out, args: np.testing.assert_allclose(
                       np.asarray(out[0]),
                       _np_lstm_peep(*[np.asarray(a) for a in _LSTM_ARGS + _PEEP])[0],
                       rtol=1e-4, atol=1e-5), (3,)),
    "sru": (_SRU_ARGS, {},
            lambda out, args: np.testing.assert_allclose(
                np.asarray(out[0]), _np_sru(*_SRU_ARGS)[0], rtol=1e-4, atol=1e-5), (2,)),
    "sru_cell": ((_SRU_ARGS[0][0],) + _SRU_ARGS[1:], {},
                 lambda out, args: np.testing.assert_allclose(
                     np.asarray(out[0]), _np_sru(_SRU_ARGS[0][:1], *_SRU_ARGS[1:])[0][0],
                     rtol=1e-4, atol=1e-5), (2,)),
    "gru_cell": ((_GRU_ARGS[0][0],) + _GRU_ARGS[1:], {},
                 lambda out, args: np.testing.assert_allclose(
                     np.asarray(out), _np_gru(_GRU_ARGS[0][:1], *_GRU_ARGS[1:])[1],
                     rtol=1e-4, atol=1e-5), (2, 3)),
    # unsorted segment family
    "unsorted_segment_max": ((_SEG_2D, _USEG_ID, 3), {},
                             np.stack([_SEG_2D[[0, 2]].max(0), _SEG_2D[3],
                                       _SEG_2D[[1, 4, 5]].max(0)]), ()),
    "unsorted_segment_min": ((_SEG_2D, _USEG_ID, 3), {},
                             np.stack([_SEG_2D[[0, 2]].min(0), _SEG_2D[3],
                                       _SEG_2D[[1, 4, 5]].min(0)]), ()),
    "unsorted_segment_prod": ((_SEG_2D, _USEG_ID, 3), {},
                              np.stack([_SEG_2D[[0, 2]].prod(0), _SEG_2D[3],
                                        _SEG_2D[[1, 4, 5]].prod(0)]), ()),
    "unsorted_segment_mean": ((_SEG_2D, _USEG_ID, 3), {},
                              np.stack([_SEG_2D[[0, 2]].mean(0), _SEG_2D[3],
                                        _SEG_2D[[1, 4, 5]].mean(0)]), ()),
    "unsorted_segment_sqrt_n": ((_SEG_2D, _USEG_ID, 3), {},
                                np.stack([_SEG_2D[[0, 2]].sum(0) / np.sqrt(2),
                                          _SEG_2D[3],
                                          _SEG_2D[[1, 4, 5]].sum(0) / np.sqrt(3)]), ()),
    # image / space-batch
    "extract_image_patches": ((NHWC, (2, 2), (2, 2)), {},
                              _np_patches_nhwc(NHWC, 2, 2, 2, 2), (0,)),
    "im2col": ((IMG,), dict(kernel=(2, 2), strides=(2, 2), padding="VALID"), None, (0,)),
    "col2im": ((np.ones((2, 12, 3, 3), np.float32), (2, 3, 6, 6)),
               dict(kernel=(2, 2), strides=(2, 2), padding="VALID"),
               np.ones((2, 3, 6, 6), np.float32), (0,)),
    "space_to_batch": ((_S2B_X, 2), {},
                       lambda out, args: (
                           np.asarray(out).shape == (4, 2, 2, 1)
                           and np.testing.assert_allclose(
                               np.asarray(OPS["batch_to_space"](out, 2)), _S2B_X) is None), (0,)),
    "batch_to_space": ((_S2B_X.reshape(4, 2, 2, 1), 2), {},
                       lambda out, args: np.asarray(out).shape == (1, 4, 4, 1), (0,)),
    "space_to_batch_nd": ((_S2B_X, (2, 2), ((0, 0), (0, 0))), {},
                          lambda out, args: np.testing.assert_allclose(
                              np.asarray(OPS["batch_to_space_nd"](
                                  out, (2, 2), ((0, 0), (0, 0)))), _S2B_X), (0,)),
    "batch_to_space_nd": ((_S2B_X.reshape(4, 2, 2, 1), (2, 2), ((0, 0), (0, 0))), {},
                          lambda out, args: np.asarray(out).shape == (1, 4, 4, 1), (0,)),
    "resize_bicubic": ((IMG, (12, 12)), {}, None, (0,)),
    "resize_area": ((IMG, (3, 3)), {}, IMG.reshape(2, 3, 3, 2, 3, 2).mean((3, 5)), (0,)),
    "crop_and_resize": ((NHWC, np.array([[0, 0, 1, 1]], np.float32),
                         np.array([0], np.int32), (6, 6)), {},
                        lambda out, args: np.testing.assert_allclose(
                            np.asarray(out)[0], NHWC[0], rtol=1e-4, atol=1e-5), (0,)),
    "rgb_to_hsv": ((RGB,), {},
                   lambda out, args: np.testing.assert_allclose(
                       np.asarray(OPS["hsv_to_rgb"](out)), RGB, rtol=1e-4, atol=1e-5), ()),
    "hsv_to_rgb": ((np.array([[0.0, 1.0, 1.0], [1 / 3, 1.0, 0.5]], np.float32),), {},
                   np.array([[1, 0, 0], [0, 0.5, 0]], np.float32), ()),
    "rgb_to_grs": ((RGB,), {},
                   (RGB * np.array([0.299, 0.587, 0.114], np.float32)).sum(-1, keepdims=True),
                   (0,)),
    "adjust_hue": ((RGB, 0.0), {}, RGB, ()),
    "adjust_saturation": ((RGB, 1.0), {}, RGB, ()),
    "non_max_suppression": ((BOXES, np.array([0.9, 0.8, 0.7], np.float32), 3), {},
                            lambda out, args: (
                                np.testing.assert_array_equal(np.asarray(out[0]), [0, 2, -1]),
                                np.testing.assert_array_equal(np.asarray(out[1]), 2)), ()),
    "max_pool_with_argmax": ((IMG,), {},
                             lambda out, args: (
                                 np.testing.assert_allclose(
                                     np.asarray(out[0]),
                                     IMG.reshape(2, 3, 3, 2, 3, 2).max((3, 5)), rtol=1e-6),
                                 np.testing.assert_allclose(
                                     np.take_along_axis(
                                         IMG.reshape(2, 3, 36),
                                         np.asarray(out[1]).reshape(2, 3, 9), axis=2),
                                     np.asarray(out[0]).reshape(2, 3, 9), rtol=1e-6)), ()),
    "fused_batch_norm": ((NHWC, np.ones(3, np.float32), np.zeros(3, np.float32)), {},
                         lambda out, args: np.testing.assert_allclose(
                             np.asarray(out[0]),
                             (NHWC - NHWC.mean((0, 1, 2))) /
                             np.sqrt(NHWC.var((0, 1, 2)) + 1e-3),
                             rtol=1e-4, atol=1e-4), (0, 1)),
    "mirror_pad": ((A, ((1, 1), (0, 0))), {}, np.pad(A, ((1, 1), (0, 0)), mode="reflect"), (0,)),
    "upsampling3d": ((IMG5, 2), {},
                     np.repeat(np.repeat(np.repeat(IMG5, 2, 2), 2, 3), 2, 4), (0,)),
    # linalg tail
    "lu": ((SPD,), {},
           lambda out, args: np.testing.assert_allclose(
               np.asarray(out[0]) @ np.asarray(out[1]) @ np.asarray(out[2]), SPD,
               rtol=1e-4, atol=1e-4), ()),
    "matrix_exp": ((SQ * 0.3,), {},
                   lambda out, args: np.testing.assert_allclose(
                       np.asarray(out), __import__("scipy.linalg", fromlist=["expm"]).expm(
                           SQ * 0.3), rtol=1e-4, atol=1e-4), ()),
    "sqrtm": ((SPD,), {},
              lambda out, args: np.testing.assert_allclose(
                  np.real(np.asarray(out)) @ np.real(np.asarray(out)), SPD,
                  rtol=1e-3, atol=1e-3), ()),
    "pinv": ((A,), {},
             lambda out, args: np.testing.assert_allclose(
                 A @ np.asarray(out) @ A, A, rtol=1e-3, atol=1e-4), ()),
    "kron": ((SQ, np.eye(2, dtype=np.float32)), {},
             np.kron(SQ, np.eye(2, dtype=np.float32)), (0,)),
    "matrix_power": ((SPD, 3), {}, np.linalg.matrix_power(SPD, 3), ()),
    "tri": ((3, 4, 0), {}, np.tri(3, 4, 0), ()),
    "diag_part": ((np.stack([SQ, SQ]),), {}, np.stack([np.diag(SQ)] * 2), (0,)),
    # sg/cb training ops
    "skipgram": ((SYN0, SYN1, np.array([1, 3], np.int32), np.array([2, 4], np.int32),
                  np.array([[5, 6], [0, 7]], np.int32)), dict(lr=0.05),
                 lambda out, args: (
                     np.testing.assert_allclose(
                         np.asarray(out[0]),
                         _np_skipgram(SYN0, SYN1, [1, 3], [2, 4],
                                      np.array([[5, 6], [0, 7]]), 0.05)[0],
                         rtol=1e-4, atol=1e-6),
                     np.testing.assert_allclose(
                         np.asarray(out[1]),
                         _np_skipgram(SYN0, SYN1, [1, 3], [2, 4],
                                      np.array([[5, 6], [0, 7]]), 0.05)[1],
                         rtol=1e-4, atol=1e-6)), ()),
    "cbow": ((SYN0, SYN1, np.array([[0, 2], [3, 5]], np.int32),
              np.array([1, 4], np.int32), np.array([[6, 7], [2, 0]], np.int32)),
             dict(lr=0.05),
             lambda out, args: np.testing.assert_allclose(
                 np.asarray(out[0]),
                 _np_cbow(SYN0, SYN1, np.array([[0, 2], [3, 5]]), [1, 4],
                          np.array([[6, 7], [2, 0]]), 0.05)[0],
                 rtol=1e-4, atol=1e-6), ()),
    # reductions tail
    "reduce_logsumexp": ((A,), dict(dims=1),
                         np.log(np.exp(A).sum(1)), (0,)),
    "count_nonzero": ((np.array([[1.0, 0, 2], [0, 0, 3]]),), dict(dims=1),
                      np.array([2, 1]), ()),
    "count_zero": ((np.array([[1.0, 0, 2], [0, 0, 3]]),), dict(dims=1),
                   np.array([1, 2]), ()),
    "zero_fraction": ((np.array([[1.0, 0, 2], [0, 0, 3]]),), {}, 0.5, ()),
    "amax": ((OFF0,), dict(dims=1), np.abs(OFF0).max(1), (0,)),
    "amin": ((OFF0,), dict(dims=1), np.abs(OFF0).min(1), (0,)),
    "amean": ((OFF0,), dict(dims=1), np.abs(OFF0).mean(1), (0,)),
    "asum": ((OFF0,), dict(dims=1), np.abs(OFF0).sum(1), (0,)),
    "reduce_dot": ((A, B), dict(dims=1), (A * B).sum(1), (0, 1)),
    "sqnorm": ((A,), dict(dims=1), (A ** 2).sum(1), (0,)),
    "percentile": ((A, 50.0), dict(dims=1), np.percentile(A, 50, axis=1), ()),
    "median": ((A,), dict(dims=1), np.median(A, axis=1), ()),
    # broadcastable tail
    "truncatediv": ((A, POS), {}, np.trunc(A / POS), ()),
    "divide_no_nan": ((A, np.array([[1.0, 0, 2, 4]] * 3, np.float32)), {},
                      np.where(np.array([[1.0, 0, 2, 4]] * 3) == 0, 0,
                               A / np.where(np.array([[1.0, 0, 2, 4]] * 3) == 0, 1,
                                            np.array([[1.0, 0, 2, 4]] * 3))), (0,)),
    "realdiv": ((A, POS), {}, A / POS, (0, 1)),
    "floormod": ((A, POS), {}, A - np.floor(A / POS) * POS, ()),
    "logaddexp": ((A, B), {}, np.logaddexp(A, B), (0, 1)),
    "zeta": ((POS + 1.5, POS), {}, None, ()),
    # merge ops
    "mergeadd": ((A, B, A), {}, A + B + A, (0, 1)),
    "mergeavg": ((A, B), {}, (A + B) / 2, (0, 1)),
    "mergemax": ((A, B), {}, np.maximum(A, B), (0, 1)),
    "accumulate_n": (([A, B, A],), {}, A + B + A, ()),
    # shape/misc tail
    "invert_permutation": ((_PERM,), {}, np.argsort(_PERM), ()),
    "unique": ((np.array([3, 1, 3, 2], np.int32),), {}, np.array([1, 2, 3]), ()),
    "unique_with_counts": ((np.array([3, 1, 3, 2], np.int32),), {},
                           lambda out, args: (
                               np.testing.assert_array_equal(np.asarray(out[0]), [1, 2, 3]),
                               np.testing.assert_array_equal(np.asarray(out[1]), [1, 1, 2])), ()),
    "listdiff": ((np.array([1, 2, 3, 4], np.int32), np.array([2, 4], np.int32)), {},
                 lambda out, args: (
                     np.testing.assert_array_equal(out[0], [1, 3]),
                     np.testing.assert_array_equal(out[1], [0, 2])), ()),
    "nth_element": ((A, 1), {}, np.sort(A, -1)[:, 1], ()),
    "histogram": ((A,), dict(bins=4, range=(-2.0, 2.0)),
                  np.histogram(A, bins=4, range=(-2, 2))[0], ()),
    "histogram_fixed_width": ((A, (-2.0, 2.0)), dict(nbins=4),
                              np.histogram(np.clip(A, -2, 1.999), bins=4,
                                           range=(-2, 2))[0], ()),
    "nonzero": ((np.array([[1, 0], [0, 2]], np.int32),), {},
                np.array([[0, 0], [1, 1]]), ()),
    "searchsorted": ((np.array([1.0, 3, 5]), np.array([0.5, 3.0, 6.0])), {},
                     np.searchsorted([1.0, 3, 5], [0.5, 3.0, 6.0]), ()),
    "bucketize": ((np.array([0.5, 1.5, 7.0], np.float32), [1.0, 2.0, 5.0]), {},
                  np.array([0, 1, 3]), ()),
    "clip_by_avg_norm": ((A, 0.1), {},
                         A * min(1.0, 0.1 / np.sqrt((A ** 2).mean())), (0,)),
    "clip_by_global_norm": ((_GN_LIST, 1.0), {},
                            lambda out, args: np.testing.assert_allclose(
                                np.asarray(out[0]),
                                A * min(1.0, 1.0 / np.sqrt((A ** 2).sum() + (B ** 2).sum())),
                                rtol=1e-5), ()),
    "check_numerics": ((A,), {}, A, ()),
    "assign": ((A, B), {}, B, ()),
    "identity": ((A,), {}, A, (0,)),
    "stop_gradient": ((A,), {}, A, ()),
    "nan_to_num": ((np.array([1.0, np.nan, np.inf]),), dict(posinf=1e6, neginf=-1e6),
                   np.array([1.0, 0.0, 1e6]), ()),
    "dynamic_partition": ((np.arange(6.0, dtype=np.float32),
                           np.array([0, 1, 0, 1, 0, 1], np.int32), 2), {},
                          lambda out, args: (
                              np.testing.assert_allclose(out[0], [0, 2, 4]),
                              np.testing.assert_allclose(out[1], [1, 3, 5])), ()),
    "split_v": ((A, (1, 3)), dict(axis=1),
                lambda out, args: (
                    np.testing.assert_allclose(np.asarray(out[0]), A[:, :1]),
                    np.testing.assert_allclose(np.asarray(out[1]), A[:, 1:])), (0,)),
    "batch_gather": ((A, np.array([[1, 0], [2, 2], [0, 3]], np.int32)), {},
                     np.take_along_axis(A, np.array([[1, 0], [2, 2], [0, 3]]), 1), (0,)),
    "logspace": ((0.0, 2.0, 3), {}, np.logspace(0, 2, 3), ()),
    "step_fn": ((OFF0,), {}, (OFF0 > 0).astype(np.float32), ()),
    "rationaltanh": ((A,), {},
                     (1.7159 * A * 2 / 3) / (1 + np.abs(1.7159 * A * 2 / 3)), (0,)),
    "cyclic_rshift_bits": ((INT_A.astype(np.uint32), np.uint32(4)), {},
                           (INT_A.astype(np.uint32) >> np.uint32(4))
                           | (INT_A.astype(np.uint32) << np.uint32(28)), ()),
    # nn tail
    "bias_add": ((A, np.ones(4, np.float32)), {}, A + 1, (0, 1)),
    "xw_plus_b": ((A, B.T.copy(), np.ones(3, np.float32)), {}, A @ B.T + 1, (0, 1, 2)),
    "relu_layer": ((A, B.T.copy(), _SC_B), {},
                   np.maximum(A @ B.T + _SC_B, 0), ()),
    "l2_loss": ((A,), {}, 0.5 * (A ** 2).sum(), (0,)),
    "log_poisson_loss": ((POS, B), {}, np.mean(np.exp(B) - POS * B), (1,)),
    "separable_conv2d": ((IMG, KDW, (R.randn(4, 3, 1, 1) * 0.3).astype(np.float32)), {},
                         lambda out, args: np.testing.assert_allclose(
                             np.asarray(out),
                             np.asarray(OPS["conv2d"](
                                 OPS["depthwise_conv2d"](IMG, KDW, padding="SAME"),
                                 args[2], padding="VALID")),
                             rtol=1e-4, atol=1e-5), (0,)),
    # random tail
    "random_multinomial": ((jax.random.key(0), np.zeros((2, 3), np.float32), 100), {},
                           lambda out, args: (np.asarray(out).shape == (2, 100)
                                              and int(np.max(np.asarray(out))) <= 2), ()),
    "random_binomial": ((jax.random.key(0), (500,)), dict(n=20, p=0.5),
                        lambda out, args: 8.5 < float(np.mean(np.asarray(out))) < 11.5, ()),
    "random_truncated_normal": ((jax.random.key(0), (500,)), {},
                                lambda out, args: float(np.max(np.abs(np.asarray(out)))) <= 2.0,
                                ()),
    "isclose": ((A, A + 1e-7), dict(atol=1e-5), np.ones_like(A, bool), ()),
    "approx_equal": ((A, A + 1e-7), {}, np.ones_like(A, bool), ()),
})


# ---------------------------------------------------------- corpus wave 3b

_MORPH_X = R.rand(1, 6, 6, 2).astype(np.float32)
_MORPH_K = (R.rand(3, 3, 2) * 0.1).astype(np.float32)


def _np_dilation2d(x, k):
    B, H, W, C = x.shape
    kh, kw, _ = k.shape
    oh, ow = H - kh + 1, W - kw + 1
    out = np.zeros((B, oh, ow, C), np.float32)
    for i in range(oh):
        for j in range(ow):
            win = x[:, i:i + kh, j:j + kw, :] + k[None]
            out[:, i, j] = win.reshape(B, -1, C).max(1)
    return out


def _np_erosion2d(x, k):
    k = k[::-1, ::-1, :]  # TF: erosion uses the spatially-flipped kernel
    B, H, W, C = x.shape
    kh, kw, _ = k.shape
    oh, ow = H - kh + 1, W - kw + 1
    out = np.zeros((B, oh, ow, C), np.float32)
    for i in range(oh):
        for j in range(ow):
            win = x[:, i:i + kh, j:j + kw, :] - k[None]
            out[:, i, j] = win.reshape(B, -1, C).min(1)
    return out


def _np_pairwssqerr(labels, preds):
    # independent loop form: mean over samples and ALL ordered (i,j) pairs
    total, cnt = 0.0, 0
    for b in range(labels.shape[0]):
        d = preds[b] - labels[b]
        for i in range(len(d)):
            for j in range(len(d)):
                total += (d[i] - d[j]) ** 2
                cnt += 1
    return total / cnt


_SND_IDX = np.array([[0], [2]], np.int32)
_SND_UPD = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)

CASES.update({
    "dilation2d": ((_MORPH_X, _MORPH_K), {},
                   _np_dilation2d(_MORPH_X, _MORPH_K), (0,)),
    "erosion2d": ((_MORPH_X, _MORPH_K), {},
                  _np_erosion2d(_MORPH_X, _MORPH_K), ()),  # min-kink: no fd grad
    # TF semantics: zero point nudged to the integer grid first. The zero
    # point is derived the same way the op does (0 - min/scale sits on a
    # float tie at exactly 127.5; fp64 rounding picks the grid) and the
    # x-quantization is checked independently in float32
    "fake_quant_with_min_max_vars": ((A, -1.0, 1.0), {},
                                     lambda out, args: np.testing.assert_allclose(
                                         np.asarray(out),
                                         (lambda z: (np.clip(np.round(
                                             A / np.float32(2 / 255) + np.float32(z)),
                                             0, 255) - np.float32(z))
                                          * np.float32(2 / 255))(
                                             np.clip(np.round(
                                                 -np.float32(-1.0)
                                                 / np.float32(2 / 255)), 0, 255)),
                                         rtol=1e-4, atol=1e-5), ()),
    "is_numeric_tensor": ((A,), {}, True, ()),
    "log_matrix_determinant": ((SPD,), {},
                               lambda out, args: np.testing.assert_allclose(
                                   float(out[0]) * np.exp(float(out[1])),
                                   np.linalg.det(SPD), rtol=1e-4), ()),
    "matrix_set_diag": ((SQ, np.array([9.0, 8, 7], np.float32)), {},
                        SQ - np.diag(np.diag(SQ)) + np.diag([9.0, 8, 7]), (0,)),
    "mergemax_index": ((A, B, A + 10), {}, np.full_like(A, 2, dtype=np.int64), ()),
    "norm": ((A,), dict(ord=1, dims=1), np.abs(A).sum(1), ()),
    "normalize_moments": ((3.0, A.sum(0), (A * A).sum(0)), {},
                          lambda out, args: (
                              np.testing.assert_allclose(np.asarray(out[0]), A.mean(0),
                                                         rtol=1e-5, atol=1e-6),
                              np.testing.assert_allclose(np.asarray(out[1]), A.var(0),
                                                         rtol=1e-4, atol=1e-5)), ()),
    "sufficient_statistics": ((A, 0), {},
                              lambda out, args: (
                                  np.testing.assert_allclose(out[0], 3.0),
                                  np.testing.assert_allclose(np.asarray(out[1]),
                                                             A.sum(0), rtol=1e-5),
                                  np.testing.assert_allclose(np.asarray(out[2]),
                                                             (A * A).sum(0),
                                                             rtol=1e-5)), ()),
    "random_crop": ((jax.random.key(0), IMG, (2, 3, 4, 4)), {},
                    lambda out, args: np.asarray(out).shape == (2, 3, 4, 4), ()),
    "scatter_nd": ((_SND_IDX, _SND_UPD, (4, 2)), {},
                   np.array([[1.0, 2], [0, 0], [3, 4], [0, 0]]), ()),
    "scatter_nd_add": ((np.ones((4, 2), np.float32), _SND_IDX, _SND_UPD), {},
                       np.array([[2.0, 3], [1, 1], [4, 5], [1, 1]]), ()),
    "scatter_nd_update": ((np.ones((4, 2), np.float32), _SND_IDX, _SND_UPD), {},
                          np.array([[1.0, 2], [1, 1], [3, 4], [1, 1]]), ()),
    "size_at": ((IMG, 2), {}, 6, ()),
    "compare_and_bitpack": ((np.array([[1, -1, 1, 1, -1, -1, -1, 1]], np.float32),
                             0.0), {}, np.array([[0b10110001]], np.uint8), ()),
    "bitcast": ((np.array([1.0], np.float32), jnp.int32), {},
                np.array([1.0], np.float32).view(np.int32), ()),
    "broadcast_dynamic_shape": ((np.array([3, 1], np.int64),
                                 np.array([1, 4], np.int64)), {},
                                np.array([3, 4]), ()),
    "mean_pairwssqerr_loss": ((A, B), {},
                              lambda out, args: np.testing.assert_allclose(
                                  float(out), _np_pairwssqerr(A, B),
                                  rtol=1e-5), (1,)),
})


# ------------------------------------------------------------------ wave 4
# (deeplearning4j_tpu/autodiff/ops_wave4.py — VERDICT r4 missing #1 tail)

import math as _math

from deeplearning4j_tpu.autodiff.ops_wave4 import NDArrayList

X1D = IMG[:, :, :, 0].copy()                       # [2,3,6] NCW
NHWC4 = np.transpose(IMG, (0, 2, 3, 1)).copy()      # [2,6,6,3]
PW1 = (R.randn(4, 3, 1, 1) * 0.3).astype(np.float32)


def _np_rnn(x, h0, wx, wh, b):
    h = h0.copy()
    ys = []
    for t in range(x.shape[0]):
        h = np.tanh(x[t] @ wx + h @ wh + b)
        ys.append(h.copy())
    return np.stack(ys), h


_RNN_ARGS = (R.randn(4, 2, 3).astype(np.float32), np.zeros((2, 5), np.float32),
             (R.randn(3, 5) * 0.4).astype(np.float32),
             (R.randn(5, 5) * 0.4).astype(np.float32), np.zeros(5, np.float32))
_RNN_B = tuple(np.asarray(a).copy() for a in
               ((R.randn(3, 5) * 0.4), (R.randn(5, 5) * 0.4), np.zeros(5)))
_RNN_B = tuple(a.astype(np.float32) for a in _RNN_B)
_SRU_W2 = tuple((R.randn(*np.asarray(w).shape) * 0.4).astype(np.float32)
                if np.asarray(w).ndim > 1 else np.zeros_like(np.asarray(w))
                for w in _SRU_ARGS[2:])


def _np_bi_rnn(x, h0f, h0b, wxf, whf, bf, wxb, whb, bb):
    yf, hf = _np_rnn(x, h0f, wxf, whf, bf)
    yb, hb = _np_rnn(x[::-1], h0b, wxb, whb, bb)
    return np.concatenate([yf, yb[::-1]], -1), hf, hb


def _np_adamlike(g, u, m, lr, b1, b2, eps, t):
    m2 = b1 * m + (1 - b1) * g
    u2 = b2 * u + (1 - b2) * g * g
    a = lr * np.sqrt(1 - b2 ** (t + 1)) / (1 - b1 ** (t + 1))
    return a * m2 / (np.sqrt(u2) + eps), u2, m2


_Z = np.zeros_like(A)
_BOXES = np.array([[[0.0, 0.0, 0.5, 0.5]]], np.float32)  # one box, B=1

CASES.update({
    # ------------------------------------------------------ conv/pool tail
    "deconv3d": ((IMG5, K3), {},
                 lambda out, args: np.asarray(out).shape == (1, 2, 8, 8, 8), (0, 1)),
    "sconv2d": ((IMG, KDW, PW1), {},
                lambda out, args: np.testing.assert_allclose(
                    np.asarray(out),
                    np.asarray(OPS["separable_conv2d"](IMG, KDW, PW1)),
                    rtol=1e-4, atol=1e-5), (0, 1)),
    "pointwise_conv2d": ((IMG, PW1), {},
                         np.einsum("nchw,ocij->nohw", IMG, PW1), (0, 1)),
    "deconv2d_tf": (((2, 2, 12, 12), KTR, IMG), {},
                    lambda out, args: np.testing.assert_allclose(
                        np.asarray(out), np.asarray(OPS["deconv2d"](IMG, KTR)),
                        rtol=1e-4, atol=1e-5), ()),
    "max_pool1d": ((X1D,), {}, X1D.reshape(2, 3, 3, 2).max(-1), (0,)),
    "maxpool1d": ((X1D,), {}, X1D.reshape(2, 3, 3, 2).max(-1), (0,)),
    "avg_pool1d": ((X1D,), {}, X1D.reshape(2, 3, 3, 2).mean(-1), (0,)),
    "avgpool1d": ((X1D,), {}, X1D.reshape(2, 3, 3, 2).mean(-1), (0,)),
    "upsampling1d": ((X1D, 2), {}, np.repeat(X1D, 2, 2), (0,)),
    "pnormpool2d": ((IMG,), {},
                    ((IMG ** 2).reshape(2, 3, 3, 2, 3, 2).sum((3, 5))) ** 0.5, (0,)),
    "ismax": ((A,), dict(axis=1),
              (A == A.max(1, keepdims=True)).astype(np.float32), ()),
    # ------------------------------------------------------------ rnn tail
    "static_rnn": (_RNN_ARGS, {},
                   lambda out, args: np.testing.assert_allclose(
                       np.asarray(out[0]), _np_rnn(*_RNN_ARGS)[0],
                       rtol=1e-4, atol=1e-5), (2, 3)),
    "dynamic_rnn": ((np.swapaxes(_RNN_ARGS[0], 0, 1).copy(),) + _RNN_ARGS[1:], {},
                    lambda out, args: np.testing.assert_allclose(
                        np.asarray(out[0]),
                        np.swapaxes(_np_rnn(*_RNN_ARGS)[0], 0, 1),
                        rtol=1e-4, atol=1e-5), ()),
    "static_bidirectional_rnn": (
        (_RNN_ARGS[0], _RNN_ARGS[1], _RNN_ARGS[1].copy()) + _RNN_ARGS[2:] + _RNN_B, {},
        lambda out, args: np.testing.assert_allclose(
            np.asarray(out[0]),
            _np_bi_rnn(_RNN_ARGS[0], _RNN_ARGS[1], _RNN_ARGS[1],
                       *(_RNN_ARGS[2:] + _RNN_B))[0],
            rtol=1e-4, atol=1e-5), (3,)),
    "dynamic_bidirectional_rnn": (
        (np.swapaxes(_RNN_ARGS[0], 0, 1).copy(), _RNN_ARGS[1], _RNN_ARGS[1].copy())
        + _RNN_ARGS[2:] + _RNN_B, {},
        lambda out, args: np.testing.assert_allclose(
            np.asarray(out[0]),
            np.swapaxes(_np_bi_rnn(_RNN_ARGS[0], _RNN_ARGS[1], _RNN_ARGS[1],
                                   *(_RNN_ARGS[2:] + _RNN_B))[0], 0, 1),
            rtol=1e-4, atol=1e-5), ()),
    "lstm_block_cell": ((_LSTM_ARGS[0][0],) + _LSTM_ARGS[1:] + _PEEP, {},
                        lambda out, args: np.testing.assert_allclose(
                            np.asarray(out[0]),
                            _np_lstm_peep(*[np.asarray(a) for a in
                                            (_LSTM_ARGS[0][:1],) + _LSTM_ARGS[1:]
                                            + _PEEP])[0][0],
                            rtol=1e-4, atol=1e-5), (3,)),
    "sru_bi": ((_SRU_ARGS[0], _SRU_ARGS[1], _SRU_ARGS[1].copy())
               + _SRU_ARGS[2:] + _SRU_W2, {},
               lambda out, args: np.testing.assert_allclose(
                   np.asarray(out[0]),
                   np.concatenate([_np_sru(*_SRU_ARGS)[0],
                                   _np_sru(_SRU_ARGS[0][::-1], _SRU_ARGS[1],
                                           *_SRU_W2)[0][::-1]], -1),
                   rtol=1e-4, atol=1e-5), (3,)),
    # --------------------------------------------------------- random tail
    "multinomial": ((jax.random.key(0), np.zeros((2, 3), np.float32), 50), {},
                    lambda out, args: (np.asarray(out).shape == (2, 50)
                                       and int(np.max(np.asarray(out))) <= 2), ()),
    "alpha_dropout": ((jax.random.key(0), A), dict(rate=0.0), A, ()),
    "dropout_inverted": ((jax.random.key(0), A), dict(rate=0.0), A, ()),
    "get_seed": ((), {}, lambda out, args: int(out) >= 0, ()),
    "set_seed": ((123,), {}, lambda out, args: int(out) == 123, ()),
    # ---------------------------------------------------------- image tail
    "image_resize": ((NHWC4, (12, 12)), dict(method="nearest"),
                     np.repeat(np.repeat(NHWC4, 2, 1), 2, 2), ()),
    "draw_bounding_boxes": ((np.zeros((1, 4, 4, 1), np.float32), _BOXES), {},
                            lambda out, args: (
                                np.asarray(out)[0, 0, 0, 0] == 1.0     # corner
                                and np.asarray(out)[0, 1, 1, 0] == 0.0  # interior
                                and np.asarray(out)[0, 3, 3, 0] == 0.0), ()),
    "rgb_to_yiq": ((NHWC4,), {},
                   NHWC4 @ np.array([[0.299, 0.587, 0.114],
                                    [0.5959, -0.2746, -0.3213],
                                    [0.2115, -0.5227, 0.3112]], np.float32).T, (0,)),
    "yiq_to_rgb": ((NHWC4,), {},
                   lambda out, args: np.testing.assert_allclose(
                       np.asarray(OPS["rgb_to_yiq"](out)), NHWC4,
                       rtol=1e-3, atol=1e-4), (0,)),
    "rgb_to_yuv": ((NHWC4,), {},
                   lambda out, args: np.testing.assert_allclose(
                       np.asarray(OPS["yuv_to_rgb"](out)), NHWC4,
                       rtol=1e-3, atol=1e-4), (0,)),
    "yuv_to_rgb": ((NHWC4,), {},
                   lambda out, args: np.testing.assert_allclose(
                       np.asarray(OPS["rgb_to_yuv"](out)), NHWC4,
                       rtol=1e-3, atol=1e-4), (0,)),
    "adjust_contrast_v2": ((NHWC4, 2.0), {},
                           (NHWC4 - NHWC4.mean((1, 2), keepdims=True)) * 2.0
                           + NHWC4.mean((1, 2), keepdims=True), (0,)),
    "non_max_suppression_overlaps": (
        (np.array([[1.0, 0.9, 0.0], [0.9, 1.0, 0.0], [0.0, 0.0, 1.0]], np.float32),
         np.array([0.9, 0.8, 0.7], np.float32), 3), {},
        lambda out, args: (np.asarray(out[0])[:2].tolist() == [0, 2]
                           and int(out[1]) == 2), ()),
    # ------------------------------------------------------------- bit ops
    "toggle_bits": ((np.array([0, 1, -1], np.int32),), {},
                    np.invert(np.array([0, 1, -1], np.int32)), ()),
    "shift_bits": ((np.array([1, 2, 4], np.int32), 2), {},
                   np.array([4, 8, 16], np.int32), ()),
    "rshift_bits": ((np.array([4, 8, 16], np.int32), 2), {},
                    np.array([1, 2, 4], np.int32), ()),
    "bits_hamming_distance": ((np.array([0b1010], np.int32),
                               np.array([0b0110], np.int32)), {}, 2, ()),
    "hashcode": ((np.array([1, 2, 3], np.int32),), {},
                 lambda out, args: (int(out) == int(OPS["hashcode"](
                     np.array([1, 2, 3], np.int32)))
                     and int(out) != int(OPS["hashcode"](
                         np.array([3, 2, 1], np.int32)))), ()),
    # --------------------------------------------------------- compat tail
    "compat_sparse_to_dense": ((np.array([[0, 1], [1, 0]], np.int64), (2, 2),
                                np.array([5.0, 6.0], np.float32)), {},
                               np.array([[0, 5], [6, 0]], np.float32), ()),
    "compat_string_split": ((np.array(["a b", "c"]),), {},
                            lambda out, args: (out[0].shape == (3, 2)
                                               and out[1] == ["a", "b", "c"]
                                               and out[2].tolist() == [2, 2]), ()),
    "select": ((A > 0, A, B), {}, np.where(A > 0, A, B), ()),
    "where_np": ((np.array([[1, 0], [0, 1]], np.float32),), {},
                 lambda out, args: (int(out[1]) == 2
                                    and np.asarray(out[0])[:2].tolist()
                                    == [[0, 0], [1, 1]]), ()),
    "choose": ((A, 0.0), dict(mode=2),
               lambda out, args: int(out[1]) == int((A > 0).sum()), ()),
    "identity_n": ((A, B), {},
                   lambda out, args: (np.array_equal(np.asarray(out[0]), A)
                                      and np.array_equal(np.asarray(out[1]), B)), ()),
    "crelu": ((OFF0,), {},
              np.concatenate([np.maximum(OFF0, 0), np.maximum(-OFF0, 0)], -1), (0,)),
    "precise_gelu": ((A,), {},
                     0.5 * A * (1 + np.vectorize(_math.erf)(A / np.sqrt(2))), (0,)),
    "argamax": ((OFF0,), dict(axis=1), np.argmax(np.abs(OFF0), 1), ()),
    "argamin": ((OFF0,), dict(axis=1), np.argmin(np.abs(OFF0), 1), ()),
    "ones_as": ((A,), {}, np.ones_like(A), ()),
    "zeros_as": ((A,), {}, np.zeros_like(A), ()),
    "assert": ((np.array([True, True]),), {},
               lambda out, args: bool(np.all(np.asarray(out))), ()),
    "fake_quant_with_min_max_vars_per_channel": (
        (A, np.full(4, -1.0, np.float32), np.full(4, 1.0, np.float32)), {},
        lambda out, args: np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(OPS["fake_quant_with_min_max_vars"](A, -1.0, 1.0)),
            rtol=1e-5, atol=1e-6), ()),
    "match_condition": ((A, 0.0), dict(mode=2), int((A > 0).sum()), ()),
    "evaluate_reduction_shape": (((2, 3, 4), (1,)), {}, np.array([2, 4]), ()),
    "create": (((2, 3),), {}, np.zeros((2, 3), np.float32), ()),
    "broadcastgradientargs": (((3, 1, 4), (2, 1, 1, 4)), {},
                              lambda out, args: (out[0].tolist() == [0]
                                                 and out[1].tolist() == [1]), ()),
    "tear": ((A,), dict(axis=0),
             lambda out, args: (len(out) == 3
                                and np.array_equal(np.asarray(out[1]), A[1])), ()),
    "truncatemod": ((A, POS), {}, np.fmod(A, POS), ()),
    "axpy": ((A, B), dict(alpha=2.0), 2 * A + B, (0, 1)),
    "stabilize": ((np.array([0.0, 1e-6, -1e-6, 0.5], np.float32),), {},
                  np.array([1e-5, 1e-5, -1e-5, 0.5], np.float32), ()),
    "log_x": ((POS,), dict(base=10.0), np.log10(POS), (0,)),
    "pow_derivative": ((POS,), dict(p=3.0), 3 * POS ** 2, (0,)),
    # --------------------------------------------------------- linalg tail
    "eig": ((SQ,), {},
            lambda out, args: np.testing.assert_allclose(
                np.asarray(SQ, np.complex64) @ np.asarray(out[1]),
                np.asarray(out[1]) * np.asarray(out[0])[None, :],
                rtol=1e-3, atol=1e-4), ()),
    "logdet": ((SPD[None],), {},
               np.array([np.log(np.linalg.det(SPD.astype(np.float64)))],
                        np.float32), (0,)),
    "solve_ls": ((SPD, A[:3, :2].copy()), {},
                 lambda out, args: np.testing.assert_allclose(
                     np.asarray(out), np.linalg.lstsq(SPD, A[:3, :2], rcond=None)[0],
                     rtol=1e-3, atol=1e-4), ()),
    # ------------------------------------------------------ updater family
    "apply_sgd": ((A, B), dict(lr=0.1), A - 0.1 * B, (0, 1)),
    "sgd_updater": ((A,), dict(lr=0.1), 0.1 * A, (0,)),
    "nesterovs_updater": ((A, B), dict(lr=0.1, momentum=0.9),
                          lambda out, args: np.testing.assert_allclose(
                              np.asarray(out[0]),
                              0.9 * B - 1.9 * (0.9 * B - 0.1 * A),
                              rtol=1e-5, atol=1e-6), ()),
    "adam_updater": ((A, _Z, _Z), dict(iteration=0),
                     lambda out, args: np.testing.assert_allclose(
                         np.asarray(out[0]),
                         _np_adamlike(A, _Z, _Z, 1e-3, 0.9, 0.999, 1e-8, 0)[0],
                         rtol=1e-4, atol=1e-7), ()),
    "ada_grad_updater": ((A, _Z), dict(lr=0.01),
                         lambda out, args: np.testing.assert_allclose(
                             np.asarray(out[0]),
                             0.01 * A / (np.abs(A) + 1e-6), rtol=1e-4), ()),
    "ada_delta_updater": ((A, _Z, _Z), dict(rho=0.95),
                          lambda out, args: np.testing.assert_allclose(
                              np.asarray(out[0]),
                              np.sqrt(1e-6) / np.sqrt(0.05 * A * A + 1e-6) * A,
                              rtol=1e-4), ()),
    "rms_prop_updater": ((A, _Z), dict(lr=0.01, decay=0.95),
                         lambda out, args: np.testing.assert_allclose(
                             np.asarray(out[0]),
                             0.01 * A / (np.sqrt(0.05 * A * A) + 1e-8),
                             rtol=1e-4), ()),
    "ada_max_updater": ((A, _Z, _Z), dict(iteration=0),
                        lambda out, args: np.testing.assert_allclose(
                            np.asarray(out[0]),
                            2e-3 / 0.1 * (0.1 * A) / (np.abs(A) + 1e-8),
                            rtol=1e-4), ()),
    "nadam_updater": ((A, _Z, _Z), dict(iteration=0),
                      lambda out, args: np.all(np.isfinite(np.asarray(out[0]))), ()),
    "ams_grad_updater": ((A, _Z, _Z, _Z), dict(iteration=0),
                         lambda out, args: np.testing.assert_allclose(
                             np.asarray(out[0]),
                             _np_adamlike(A, _Z, _Z, 1e-3, 0.9, 0.999, 1e-8, 0)[0],
                             rtol=1e-4, atol=1e-7), ()),
    "adabelief_updater": ((A, _Z, _Z), dict(iteration=0),
                          lambda out, args: np.all(np.isfinite(np.asarray(out[0]))), ()),
    # --------------------------------------------------- NDArrayList family
    "create_list": ((), {}, lambda out, args: isinstance(out, NDArrayList), ()),
    "write_list": ((NDArrayList(), 0, A), {},
                   lambda out, args: np.array_equal(np.asarray(out.arrays[0]), A), ()),
    "read_list": ((NDArrayList({0: A}), 0), {}, A, ()),
    "size_list": ((NDArrayList({0: A, 1: B}),), {}, 2, ()),
    "stack_list": ((NDArrayList({0: A[0], 1: A[1]}),), {}, np.stack([A[0], A[1]]), ()),
    "unstack_list": ((A,), {},
                     lambda out, args: np.array_equal(np.asarray(out.arrays[1]), A[1]), ()),
    "scatter_list": ((NDArrayList(), np.array([1, 0]), np.stack([A[0], A[1]])), {},
                     lambda out, args: np.array_equal(np.asarray(out.arrays[1]), A[0]), ()),
    "gather_list": ((NDArrayList({0: A[0], 1: A[1], 2: A[2]}), np.array([2, 0])), {},
                    np.stack([A[2], A[0]]), ()),
    "split_list": ((NDArrayList(), A, np.array([1, 2])), {},
                   lambda out, args: (np.array_equal(np.asarray(out.arrays[0]), A[:1])
                                      and np.array_equal(np.asarray(out.arrays[1]),
                                                         A[1:3])), ()),
    "pick_list": ((NDArrayList({0: A[0], 1: A[1]}), np.array([1, 0, 0])), {},
                  np.concatenate([A[1], A[0], A[0]]), ()),
    "clone_list": ((NDArrayList({0: A}),), {},
                   lambda out, args: (isinstance(out, NDArrayList)
                                      and out is not args[0]
                                      and np.array_equal(np.asarray(out.arrays[0]), A)), ()),
    "delete_list": ((NDArrayList({0: A}), 0), {},
                    lambda out, args: len(out.arrays) == 0, ()),
    # --------------------------------------------- Barnes-Hut tSNE helpers
    "barnes_gains": ((np.ones(3, np.float32), np.array([1.0, -1.0, 1.0], np.float32),
                      np.array([1.0, 1.0, -1.0], np.float32)), {},
                     np.array([0.8, 1.2, 1.2], np.float32), ()),
    "barnes_edge_forces": ((np.array([0, 1, 2], np.int64), np.array([1, 0], np.int64),
                            np.array([1.0, 1.0], np.float32), 2,
                            np.array([[0.0, 0.0], [1.0, 1.0]], np.float32)), {},
                           np.array([[-1 / 3, -1 / 3], [1 / 3, 1 / 3]], np.float32), ()),
    "barnes_symmetrized": ((np.array([0, 1, 2], np.int64), np.array([1, 0], np.int64),
                            np.array([1.0, 1.0], np.float32), 2), {},
                           lambda out, args: (out[0].tolist() == [0, 1, 2]
                                              and out[1].tolist() == [1, 0]
                                              and np.allclose(out[2], [1.0, 1.0])), ()),
    "cell_contains": ((np.zeros(2, np.float32), np.array([2.0, 2.0], np.float32),
                       np.array([0.5, 0.5], np.float32)), {}, True, ()),
    "knn_mindistance": ((np.array([0.0, 0.0], np.float32),
                         np.array([1.0, 1.0], np.float32),
                         np.array([2.0, 2.0], np.float32)), {},
                        np.sqrt(np.float32(2.0)), ()),
    # ---------------------------------------------- compression codec ops
    "encode_threshold": ((np.array([0.002, -0.0005, -0.003, 0.0001], np.float32),),
                         dict(threshold=1e-3),
                         lambda out, args: np.testing.assert_allclose(
                             np.asarray(OPS["decode_threshold"](
                                 out[0], out[1], (4,), threshold=1e-3))
                             + np.asarray(out[2]),
                             np.array([0.002, -0.0005, -0.003, 0.0001]),
                             rtol=1e-5, atol=1e-7), ()),
    "decode_threshold": ((np.array([2, 0, -1, -1], np.int32),
                          np.array([1.0, -1.0, 0.0, 0.0], np.float32), (4,)),
                         dict(threshold=0.5),
                         np.array([-0.5, 0.0, 0.5, 0.0], np.float32), ()),
    "encode_bitmap": ((np.array([0.002, -0.0005, -0.003, 0.0001], np.float32),),
                      dict(threshold=1e-3),
                      lambda out, args: np.testing.assert_allclose(
                          np.asarray(OPS["decode_bitmap"](out[0], 4, threshold=1e-3))
                          + np.asarray(out[1]),
                          np.array([0.002, -0.0005, -0.003, 0.0001]),
                          rtol=1e-5, atol=1e-7), ()),
    "decode_bitmap": ((np.array([0b1001], np.int32), 4), dict(threshold=0.5),
                      np.array([0.5, -0.5, 0.0, 0.0], np.float32), ()),
    # ----------------------------------------------------- reduce_* family
    "reduce_norm1": ((OFF0,), dict(dims=1), np.abs(OFF0).sum(1), (0,)),
    "reduce_norm2": ((OFF0,), dict(dims=1), np.sqrt((OFF0 ** 2).sum(1)), (0,)),
    "reduce_norm_max": ((OFF0,), dict(dims=1), np.abs(OFF0).max(1), ()),
    "reduce_sqnorm": ((A,), dict(dims=1), (A ** 2).sum(1), (0,)),
    "reduce_variance": ((A,), dict(dims=1), A.var(1), (0,)),
    "reduce_stdev": ((A,), dict(dims=1, bias_corrected=True), A.std(1, ddof=1), (0,)),
    # ----------------------------------------------------------- shape tail
    "order": ((A,), dict(order="f"), A, ()),
    "tile_to_shape": ((A, (6, 8)), {}, np.tile(A, (2, 2)), (0,)),
    "reshape_as": ((A, np.zeros((4, 3))), {}, A.reshape(4, 3), (0,)),
    "flatten": ((A, B), {}, np.concatenate([A.ravel(), B.ravel()]), (0, 1)),
    "shapes_of": ((A, IMG), {},
                  lambda out, args: (out[0].tolist() == [3, 4]
                                     and out[1].tolist() == [2, 3, 6, 6]), ()),
    # ------------------------------------------------------------ nlp tail
    "skipgram_inference": ((SYN0, SYN1, 1, np.array([2, 3], np.int32)), {},
                           1 / (1 + np.exp(-(SYN1[[2, 3]] @ SYN0[1]))), ()),
    "cbow_inference": ((SYN0, SYN1, np.array([0, 2], np.int32),
                        np.array([2, 3], np.int32)), {},
                       1 / (1 + np.exp(-(SYN1[[2, 3]] @ SYN0[[0, 2]].mean(0)))), ()),
    # ------------------------------------------------------- attention tail
    "dot_product_attention_v2": (_ATTN, {},
                                 lambda out, args: np.testing.assert_allclose(
                                     np.asarray(out),
                                     np.asarray(OPS["dot_product_attention"](*_ATTN)),
                                     rtol=1e-4, atol=1e-5), (0, 1, 2)),
    # -------------------------------------------------------------- util ops
    "print_variable": ((A,), {}, A, ()),
    "print_affinity": ((A,), {}, A, ()),
})

# reference-canonical spellings share the impl AND the validation case
from deeplearning4j_tpu.autodiff.ops_wave4 import CANONICAL_ALIASES

for _canon, _alias in CANONICAL_ALIASES.items():
    CASES[_canon] = CASES[_alias]


def test_dynamic_rnn_zeroes_past_seq_len():
    """TF dynamic_rnn contract: outputs past each row's sequence_length are
    ZERO (not the frozen state); final state freezes at the last real step
    (r5 review finding)."""
    x, h0, wx, wh, b = _RNN_ARGS
    seq_len = np.array([4, 2], np.int32)
    ys, hT = OPS["static_rnn"](x, h0, wx, wh, b, seq_len=seq_len)
    ys_full, _ = _np_rnn(x, h0, wx, wh, b)
    np.testing.assert_allclose(np.asarray(ys[:, 0]), ys_full[:, 0],
                               rtol=1e-4, atol=1e-5)          # full-length row
    np.testing.assert_allclose(np.asarray(ys[:2, 1]), ys_full[:2, 1],
                               rtol=1e-4, atol=1e-5)          # real steps
    np.testing.assert_array_equal(np.asarray(ys[2:, 1]), 0.0)  # zero padding
    np.testing.assert_allclose(np.asarray(hT[1]), ys_full[1, 1],
                               rtol=1e-4, atol=1e-5)          # frozen state


def test_bidirectional_rnn_reverses_by_seq_len():
    """Backward direction must consume each row's REAL data first
    (reverse_sequence semantics), not the padding (r5 review finding)."""
    x, h0, wx, wh, b = _RNN_ARGS
    seq_len = np.array([4, 2], np.int32)
    out, _, _ = OPS["static_bidirectional_rnn"](
        x, h0, h0.copy(), wx, wh, b, *_RNN_B, seq_len=seq_len)
    H = h0.shape[-1]
    # row 1 has length 2: backward half over its real frames x[1], x[0]
    yb_row1 = _np_rnn(x[:2, 1:2][::-1], h0[1:2], *_RNN_B)[0]
    np.testing.assert_allclose(np.asarray(out)[1, 1, H:], yb_row1[0, 0],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out)[0, 1, H:], yb_row1[1, 0],
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("name", sorted(OPS))
def test_op_forward(name):
    assert name in CASES, (
        f"op '{name}' registered without a validation TestCase — add one to "
        f"tests/test_op_validation.py (SURVEY §4.2 coverage gate)")
    args, kwargs, expected, _ = CASES[name]
    fn = OPS[name]
    out = fn(*args, **kwargs)
    if callable(expected):
        res = expected(out, args)
        assert res is not False
    elif expected is not None:
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   rtol=2e-4, atol=2e-5)
    else:
        for leaf in jax.tree.leaves(out):
            assert np.all(np.isfinite(np.asarray(leaf, np.float32)))
    OpValidation.record(name)


_GRAD_OPS = sorted(n for n, c in CASES.items() if c[3])


@pytest.mark.parametrize("name", _GRAD_OPS)
def test_op_gradients(name):
    args, kwargs, _, diff_args = CASES[name]
    check_op_gradients(name, args, kwargs, diff_args=diff_args)


def test_zz_coverage_gate():
    """FAILS when any registered op lacks a validated TestCase (runs last:
    pytest executes this file in definition order)."""
    for name in CASES:
        OpValidation.record(name)
    OpValidation.assert_coverage(OPS)
