"""ComputationGraph scan-fused fit (r4: fit_scan)."""

import numpy as np

from deeplearning4j_tpu.nn import NeuralNetConfiguration



def test_fit_scan_matches_sequential():
    """K scan-fused steps must reproduce K sequential fit() calls exactly
    (same per-iteration rng fold, same updater/bn evolution)."""
    import jax

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import (
        BatchNormalization, ConvolutionLayer, DenseLayer, InputType, OutputLayer,
    )
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.updaters import Adam

    def build():
        g = (NeuralNetConfiguration.Builder().seed(11).updater(Adam(1e-2))
             .graph_builder().add_inputs("input")
             .set_input_types(InputType.convolutional(6, 6, 1)))
        g.add_layer("c", ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                          convolution_mode="same",
                                          activation="identity", has_bias=False),
                    "input")
        g.add_layer("bn", BatchNormalization(activation="relu"), "c")
        g.add_layer("d", DenseLayer(n_out=8, activation="tanh"), "bn")
        g.add_layer("output", OutputLayer(n_out=3, activation="softmax",
                                          loss="negativeloglikelihood"), "d")
        g.set_outputs("output")
        net = ComputationGraph(g.build())
        net.init()
        return net

    rs = np.random.RandomState(0)
    batches = [DataSet(rs.rand(4, 1, 6, 6).astype(np.float32),
                       np.eye(3, dtype=np.float32)[rs.randint(0, 3, 4)])
               for _ in range(4)]

    seq = build()
    for ds in batches:
        seq._fit_one(ds)
    fused = build()
    losses = fused.fit_scan(batches)
    assert losses.shape == (4,)

    for name in seq.params_:
        for p in seq.params_[name]:
            np.testing.assert_allclose(
                np.asarray(seq.params_[name][p]), np.asarray(fused.params_[name][p]),
                rtol=2e-5, atol=2e-6, err_msg=f"{name}/{p}")
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6),
        seq.bn_state, fused.bn_state)
    assert fused.iteration == 4


def test_mln_fit_scan_matches_sequential():
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    def build():
        conf = (NeuralNetConfiguration.Builder().seed(9).updater(Adam(1e-2)).list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.feed_forward(5)).build())
        return MultiLayerNetwork(conf).init()

    rs = np.random.RandomState(1)
    batches = [DataSet(rs.rand(6, 5).astype(np.float32),
                       np.eye(2, dtype=np.float32)[rs.randint(0, 2, 6)])
               for _ in range(5)]
    seq = build()
    for ds in batches:
        seq._fit_batch(ds)
    fused = build()
    losses = fused.fit_scan(batches)
    assert losses.shape == (5,)
    np.testing.assert_allclose(np.asarray(seq.params().numpy()),
                               np.asarray(fused.params().numpy()),
                               rtol=2e-5, atol=2e-6)
    assert fused.iteration == 5
