"""Trace-replay load generator (ISSUE 11 tentpole, layer 4).

Fast tier: deterministic arrival schedules, diurnal/burst rate shaping,
deadline mix, spec JSON round-trip, and a short live replay report.

Slow tier: the ISSUE 11 acceptance — a seeded diurnal + 10x-burst replay
against a live JsonModelServer (32-client harness) with a history ring, SLO
tracker and alert engine evaluating DURING the replay: the windowed p99 and
burn-rate rules fire under the burst and clear after recovery (matching
alert/alert_clear intervals), a sampled 200 and a shed 504 each reconstruct
their span timeline by request id, and the steady phase fires nothing.
"""

import json
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.monitoring import MetricsRegistry
from deeplearning4j_tpu.serving import (Burst, JsonModelServer, LoadGenerator,
                                        TraceSpec)


class EchoModel:
    """2x the input, optionally with a per-ROW cost so overload builds real
    queues: capacity is ~1/row_cost rows/sec, which a burst can exceed."""

    def __init__(self, row_cost_s: float = 0.0):
        self.row_cost_s = row_cost_s

    def output(self, x):
        x = np.asarray(x, np.float32)
        if self.row_cost_s:
            time.sleep(self.row_cost_s * x.shape[0])
        return x * 2.0


# ------------------------------------------------------------------- spec


def test_spec_validation():
    with pytest.raises(ValueError, match="must be > 0"):
        TraceSpec(duration_s=0)
    with pytest.raises(ValueError, match="amplitude"):
        TraceSpec(diurnal_amplitude=1.0)
    with pytest.raises(ValueError, match="positive weights"):
        TraceSpec(deadline_mix=((0.0, None),))


def test_arrivals_deterministic_and_json_roundtrip():
    spec = TraceSpec(duration_s=5.0, base_rate=80, seed=42,
                     diurnal_amplitude=0.5, bursts=(Burst(2.0, 1.0, 8.0),),
                     deadline_mix=((0.8, None), (0.2, 100.0)))
    a, b = spec.arrivals(), spec.arrivals()
    assert a == b  # same seed → byte-identical schedule
    assert TraceSpec(duration_s=5.0, base_rate=80, seed=43,
                     diurnal_amplitude=0.5, bursts=(Burst(2.0, 1.0, 8.0),),
                     deadline_mix=((0.8, None), (0.2, 100.0))).arrivals() != a
    rt = TraceSpec.from_dict(json.loads(json.dumps(spec.to_dict())))
    assert rt == spec and rt.arrivals() == a
    # every arrival inside the trace, deadline drawn from the mix
    assert all(0 <= t < 5.0 for t, _ in a)
    assert {d for _, d in a} <= {None, 100.0}
    with_deadline = sum(1 for _, d in a if d is not None)
    assert 0.1 < with_deadline / len(a) < 0.35  # ~20% by weight


def test_rate_curve_diurnal_and_burst_shape():
    spec = TraceSpec(duration_s=10.0, base_rate=100, seed=1,
                     diurnal_amplitude=0.5, bursts=(Burst(6.0, 2.0, 10.0),))
    # diurnal: starts at the trough (phase -pi/2) → rate_at(0) = base*(1-amp)
    assert spec.rate_at(0.0) == pytest.approx(50.0)
    assert spec.rate_at(5.0) == pytest.approx(150.0)  # peak mid-trace
    assert spec.rate_at(6.5) / spec.rate_at(5.9) > 8  # 10x burst edge
    assert spec.peak_rate == pytest.approx(1500.0)
    arrivals = spec.arrivals()
    in_burst = sum(1 for t, _ in arrivals if 6.0 <= t < 8.0)
    pre_burst = sum(1 for t, _ in arrivals if 3.0 <= t < 5.0)
    assert in_burst / max(1, pre_burst) > 4  # the spike is in the schedule


def test_live_replay_report_shape():
    server = JsonModelServer(EchoModel(),
                             warmup_input=np.zeros((1, 2), np.float32)).start()
    try:
        assert server.wait_ready(30.0)
        spec = TraceSpec(duration_s=1.5, base_rate=40, seed=3)
        rep = LoadGenerator(spec, server.port, n_clients=4,
                            payload=[[1.0, 2.0]], slo_threshold_ms=500,
                            slo_target=0.99, record_requests=True).run()
        assert rep["offered"] == len(spec.arrivals())
        assert rep["outcomes"].get("200", 0) == rep["offered"]
        assert rep["slo"]["attainment"] == 1.0
        assert rep["slo"]["error_budget_remaining"] == 1.0
        assert rep["slo"]["burn_rate_overall"] == 0.0
        assert rep["latency_ms"]["p99"] is not None
        assert len(rep["requests"]) == rep["offered"]
        # request ids are deterministic → joinable across runs/spans
        assert rep["requests"][0]["request_id"].startswith("replay-3-")
        # open-loop fidelity: the generator kept to its schedule
        assert rep["lateness_ms"]["p99"] < 500
    finally:
        server.stop()


# ------------------------------------------------------------- slow tier


@pytest.mark.slow
def test_replay_acceptance_burst_fires_and_clears_windowed_alerts():
    """ISSUE 11 acceptance: seeded diurnal+burst replay against a live
    server (32-client chaos-harness scale) → SLO report with attainment /
    budget / burn; p99+burn rules fire during the 10x burst and record
    matching alert/alert_clear intervals; a sampled 200 and a shed 504
    reconstruct full span timelines by request id; nothing fires in the
    steady pre-burst phase."""
    from deeplearning4j_tpu.monitoring import (AlertEngine, HistoryRing,
                                               SloTracker, default_objectives,
                                               default_rules, flight,
                                               get_registry)
    from deeplearning4j_tpu.monitoring.flight import FlightRecorder
    from deeplearning4j_tpu.parallel.supervisor import _alert_intervals

    rec = FlightRecorder(proc="replay-test", capacity=16384)
    flight.set_flight_recorder(rec)
    reg = MetricsRegistry()
    # per-row cost 10ms → capacity ~100 rows/s; steady ~40-50/s is
    # comfortable (measured steady p99 ~35ms), the 10x burst (~500/s
    # offered) is not — queues build, latency climbs past the deadline
    # slice: exactly the regime the windowed rules must catch
    server = JsonModelServer(EchoModel(row_cost_s=0.01), max_queue=256,
                             registry=reg,
                             warmup_input=np.zeros((1, 2), np.float32)).start()
    try:
        assert server.wait_ready(60.0)
        dur = 12.0
        burst = Burst(5.0, 3.0, 10.0)
        spec = TraceSpec(duration_s=dur, base_rate=40.0, seed=11,
                         diurnal_amplitude=0.3, bursts=(burst,),
                         deadline_mix=((0.8, None), (0.2, 150.0)))
        threshold_s = 0.1
        ring = HistoryRing(registry=reg, interval=0.0, capacity=1024)
        tracker = SloTracker(
            default_objectives(latency_threshold_s=threshold_s,
                               target=0.95, window_s=2.0),
            history_view=ring, registry=reg,
            burn_windows=(("fast", 2.0), ("slow", 8.0)))
        rules = default_rules(p99_latency_s=threshold_s,
                              latency_window_s=2.0,
                              burn_fast=3.0, burn_slow=1.5,
                              shed_window_s=2.0)
        engine = AlertEngine(rules, registry=reg, history_view=ring)
        t0 = time.monotonic()
        edges = []  # (monotonic t, rule, kind) from live evaluation
        stop_eval = threading.Event()

        def evaluate_loop():
            while not stop_eval.is_set():
                ring.sample(force=True)
                tracker.evaluate()
                engine.evaluate()
                stop_eval.wait(0.2)

        evaluator = threading.Thread(target=evaluate_loop, daemon=True)
        evaluator.start()
        report = LoadGenerator(
            spec, server.port, n_clients=32, payload=[[1.0, 2.0]],
            slo_threshold_ms=threshold_s * 1e3, slo_target=0.95,
            record_requests=True).run()
        # keep evaluating through recovery so firing rules can CLEAR
        # (windowed values fall back under threshold once the burst drains)
        recovery_deadline = time.monotonic() + 20.0
        while time.monotonic() < recovery_deadline:
            if not any(a["firing"] for a in engine.evaluate()):
                break
            time.sleep(0.2)
        stop_eval.set()
        evaluator.join(10.0)
        server.stop(drain=True)

        # -- the SLO report is machine-readable and shows the damage ------
        slo = report["slo"]
        assert slo["attainment"] is not None and slo["attainment"] < 1.0
        assert slo["error_budget_remaining"] < 1.0
        assert slo["burn_rate_worst_window"] > 1.0  # the burst burned hot
        outcomes = report["outcomes"]
        assert outcomes.get("200", 0) > 0
        assert set(outcomes) <= {"200", "429", "504"}  # only clean sheds

        # -- the windowed rules fired during the burst, then cleared ------
        alert_events = [e for e in rec.events()
                        if e["kind"] in ("alert", "alert_clear")]
        fired_rules = {e["rule"] for e in alert_events if e["kind"] == "alert"}
        assert "p99_latency_rising" in fired_rules
        assert ("error_budget_burn_fast" in fired_rules
                or "error_budget_burn_slow" in fired_rules)
        # steady phase clean: every rise happened at/after the burst began
        rise_offsets = [e["t"] - t0 for e in alert_events
                        if e["kind"] == "alert"]
        assert min(rise_offsets) >= burst.start_s - 0.5
        # intervals pair up: the p99 rule rose and CLEARED (postmortem form)
        intervals = _alert_intervals(sorted(alert_events,
                                            key=lambda e: e["t"]))
        p99_rows = [r for r in intervals if r["rule"] == "p99_latency_rising"]
        assert p99_rows and any(not r["still_firing"] for r in p99_rows)
        closed = [r for r in p99_rows if not r["still_firing"]][0]
        assert closed["duration"] > 0

        # -- span timelines reconstruct by request id ---------------------
        spans = {e["request_id"]: e for e in rec.events()
                 if e["kind"] == "request_span"}
        ok_rows = [r for r in report["requests"] if r["outcome"] == "200"
                   and r["request_id"] in spans]
        assert ok_rows, "no sampled 200 with a span event"
        ok_span = spans[ok_rows[0]["request_id"]]
        assert ok_span["outcome"] == "ok"
        assert set(ok_span["phases"]) == {"queue", "batch_form", "infer",
                                          "serialize"}
        shed_rows = [r for r in report["requests"] if r["outcome"] == "504"
                     and r["request_id"] in spans]
        assert shed_rows, "no shed 504 with a span event"
        shed_span = spans[shed_rows[0]["request_id"]]
        assert shed_span["outcome"] == "shed_deadline"
        assert shed_span["phases"]["queue"] > 0  # its life was the queue
    finally:
        server.stop()
        flight.set_flight_recorder(None)
