"""INDArray wave-2 acceptance suite — DL4J-exact semantics.

Each test mirrors a named upstream case from
``org.nd4j.linalg.Nd4jTestsC`` / ``NDArrayIndexingTests`` /
``BooleanIndexingTest`` (SURVEY §4.2: the reference's INDArray behavior
suite is the acceptance oracle for the J1 surface; VERDICT r5 task #3).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.ndarray import (
    BooleanIndexing,
    Conditions,
    NDArray,
    NDArrayIndex,
    array,
)

ALL, point, interval, indices = (NDArrayIndex.all, NDArrayIndex.point,
                                 NDArrayIndex.interval, NDArrayIndex.indices)


def _m34():
    return array(np.arange(12, dtype=np.float32).reshape(3, 4))


# ---------------------------------------------------- get(NDArrayIndex...)


class TestNDArrayIndexGet:
    def test_get_point_all_is_row_view(self):
        """Nd4jTestsC.testGetRow + INDArrayIndex view semantics: writes to
        the returned slice are visible in the parent."""
        a = _m34()
        row = a.get(point(1), ALL())
        assert row.shape == (4,)
        np.testing.assert_array_equal(row.numpy(), [4, 5, 6, 7])
        row.addi(10)  # write-through
        np.testing.assert_array_equal(a.numpy()[1], [14, 15, 16, 17])

    def test_get_interval(self):
        """Nd4jTestsC.testIntervalEdgeCase / testGetIntervalRangeEdgeCase2."""
        a = _m34()
        sub = a.get(ALL(), interval(1, 3))
        assert sub.shape == (3, 2)
        np.testing.assert_array_equal(sub.numpy(), a.numpy()[:, 1:3])

    def test_get_interval_inclusive_and_stride(self):
        """3-arg interval is the JAVA overload order (from, stride, to) —
        NDArrayIndexingTests parity, r5 review finding."""
        a = array(np.arange(10, dtype=np.float32))
        np.testing.assert_array_equal(
            a.get(interval(0, 2, 8)).numpy(), [0, 2, 4, 6])
        np.testing.assert_array_equal(
            a.get(interval(0, 2, 8, inclusive=True)).numpy(), [0, 2, 4, 6, 8])
        np.testing.assert_array_equal(
            a.get(interval(3, 7)).numpy(), [3, 4, 5, 6])

    def test_get_indices_is_copy(self):
        """indices() takes the copy path (the reference's specified-index
        case) — parent unaffected by writes."""
        a = _m34()
        picked = a.get(indices(2, 0), ALL())
        np.testing.assert_array_equal(picked.numpy(), a.numpy()[[2, 0]])
        picked.addi(100)
        assert a.get_double(0, 0) == 0.0

    def test_get_point_point_scalar(self):
        a = _m34()
        s = a.get(point(2), point(3))
        assert float(s) == 11.0

    def test_get_new_axis(self):
        a = _m34()
        b = a.get(NDArrayIndex.new_axis(), ALL(), ALL())
        assert b.shape == (1, 3, 4)

    def test_nested_interval_view_composition(self):
        """View-of-view composes against the root (TAD §2.1 N2 rule)."""
        a = _m34()
        v1 = a.get(ALL(), interval(1, 4))     # [3,3] view
        v2 = v1.get(interval(1, 3), point(1))  # rows 1-2 of col 2 of a
        v2.assign(-1.0)
        np.testing.assert_array_equal(a.numpy()[1:3, 2], [-1, -1])


class TestNDArrayIndexPut:
    def test_put_interval(self):
        """Nd4jTestsC.testPut / NDArrayIndexingTests put(interval)."""
        a = _m34()
        a.put((ALL(), interval(0, 2)), array(np.ones((3, 2), np.float32)))
        np.testing.assert_array_equal(a.numpy()[:, :2], np.ones((3, 2)))
        np.testing.assert_array_equal(a.numpy()[:, 2:],
                                      np.arange(12).reshape(3, 4)[:, 2:])

    def test_put_point_row(self):
        a = _m34()
        a.put((point(0), ALL()), array(np.full(4, 9, np.float32)))
        np.testing.assert_array_equal(a.numpy()[0], [9, 9, 9, 9])

    def test_put_indices(self):
        a = _m34()
        a.put((indices(0, 2), ALL()), array(np.zeros((2, 4), np.float32)))
        np.testing.assert_array_equal(a.numpy()[[0, 2]], np.zeros((2, 4)))
        np.testing.assert_array_equal(a.numpy()[1], [4, 5, 6, 7])

    def test_put_slice(self):
        a = _m34()
        a.put_slice(2, array(np.full(4, 5, np.float32)))
        np.testing.assert_array_equal(a.numpy()[2], [5, 5, 5, 5])


# -------------------------------------------------- BooleanIndexing family


class TestBooleanIndexing:
    def test_apply_where_scalar(self):
        """BooleanIndexingTest.testApplyWhere: in-place scalar replace."""
        a = array(np.array([-2.0, -1.0, 1.0, 2.0], np.float32))
        BooleanIndexing.apply_where(a, Conditions.less_than(0), 0.0)
        np.testing.assert_array_equal(a.numpy(), [0, 0, 1, 2])

    def test_replace_where_array(self):
        """BooleanIndexingTest.testReplaceWhereArray."""
        a = array(np.array([1.0, -1.0, 2.0, -2.0], np.float32))
        put = array(np.array([10.0, 20.0, 30.0, 40.0], np.float32))
        BooleanIndexing.replace_where(a, put, Conditions.less_than(0))
        np.testing.assert_array_equal(a.numpy(), [1, 20, 2, 40])

    def test_and_or(self):
        """BooleanIndexingTest.testAnd1 / testOr1."""
        a = array(np.array([1.0, 2.0, 3.0], np.float32))
        assert BooleanIndexing.and_(a, Conditions.greater_than(0))
        assert not BooleanIndexing.and_(a, Conditions.greater_than(2))
        assert BooleanIndexing.or_(a, Conditions.greater_than(2))
        assert not BooleanIndexing.or_(a, Conditions.greater_than(5))

    def test_first_last_index(self):
        """BooleanIndexingTest.testFirstIndex1 / testLastIndex1."""
        a = array(np.array([0.0, 5.0, 0.0, 7.0, 0.0], np.float32))
        assert BooleanIndexing.first_index(a, Conditions.greater_than(1)) == 1
        assert BooleanIndexing.last_index(a, Conditions.greater_than(1)) == 3
        assert BooleanIndexing.first_index(a, Conditions.greater_than(99)) == -1

    def test_cond_mask(self):
        """INDArray.cond(Condition) → BOOL array (Nd4jTestsC.testWhere-ish)."""
        a = _m34()
        m = a.cond(Conditions.greater_than(5))
        np.testing.assert_array_equal(m.numpy(), np.arange(12).reshape(3, 4) > 5)

    def test_assign_if(self):
        a = array(np.array([1.0, -3.0, 2.0], np.float32))
        a.assign_if(array(np.zeros(3, np.float32)), Conditions.less_than(0))
        np.testing.assert_array_equal(a.numpy(), [1, 0, 2])

    def test_put_where_with_mask(self):
        a = array(np.array([1.0, 2.0, 3.0], np.float32))
        out = a.put_where_with_mask(array(np.array([1.0, 0.0, 1.0])),
                                    array(np.array([9.0, 9.0, 9.0])))
        np.testing.assert_array_equal(out.numpy(), [9, 2, 9])
        np.testing.assert_array_equal(a.numpy(), [1, 2, 3])  # copy, not in place

    def test_conditions_nan_inf(self):
        a = array(np.array([1.0, np.nan, np.inf], np.float32))
        np.testing.assert_array_equal(a.cond(Conditions.is_nan()).numpy(),
                                      [False, True, False])
        np.testing.assert_array_equal(a.cond(Conditions.is_infinite()).numpy(),
                                      [False, False, True])
        np.testing.assert_array_equal(a.cond(Conditions.is_finite()).numpy(),
                                      [True, False, False])


# ------------------------------------------------------- broadcast_* family


class TestBroadcastFamily:
    def test_broadcast_add_dim0(self):
        """Nd4jTestsC.testBroadcastingGenerated-style: column broadcast."""
        a = _m34()
        v = array(np.array([10.0, 20.0, 30.0], np.float32))
        out = a.broadcast_add(v, 0)
        np.testing.assert_array_equal(
            out.numpy(), a.numpy() + np.array([[10], [20], [30]]))

    def test_broadcast_mul_dim1(self):
        """Nd4jTestsC.testBroadcastMult row broadcast along dim 1."""
        a = _m34()
        v = array(np.array([1.0, 2.0, 3.0, 4.0], np.float32))
        out = a.broadcast_mul(v, 1)
        np.testing.assert_array_equal(out.numpy(), a.numpy() * v.numpy())

    def test_broadcast_div_sub_rsub_rdiv(self):
        a = array(np.full((2, 3), 12.0, np.float32))
        v = array(np.array([2.0, 3.0, 4.0], np.float32))
        np.testing.assert_array_equal(a.broadcast_div(v, 1).numpy(),
                                      [[6, 4, 3]] * 2)
        np.testing.assert_array_equal(a.broadcast_sub(v, 1).numpy(),
                                      [[10, 9, 8]] * 2)
        np.testing.assert_array_equal(a.broadcast_rsub(v, 1).numpy(),
                                      [[-10, -9, -8]] * 2)
        np.testing.assert_allclose(a.broadcast_rdiv(v, 1).numpy(),
                                   [[2 / 12, 3 / 12, 4 / 12]] * 2, rtol=1e-6)

    def test_broadcast_copy_and_compare(self):
        a = _m34()
        v = array(np.array([0.0, 5.0, 9.0, 11.0], np.float32))
        np.testing.assert_array_equal(a.broadcast_copy(v, 1).numpy(),
                                      np.tile(v.numpy(), (3, 1)))
        np.testing.assert_array_equal(a.broadcast_equal(v, 1).numpy(),
                                      a.numpy() == v.numpy())
        np.testing.assert_array_equal(a.broadcast_gt(v, 1).numpy(),
                                      a.numpy() > v.numpy())
        np.testing.assert_array_equal(a.broadcast_lte(v, 1).numpy(),
                                      a.numpy() <= v.numpy())


# ----------------------------------------------------------- accessor tail


class TestAccessorTail:
    def test_linear_get_double(self):
        """BaseNDArray.getDouble(long): linear offset in the array's order
        (Nd4jTestsC.testGetDouble)."""
        a = _m34()
        assert a.get_double(5) == 5.0
        f = a.dup("f")
        assert f.get_double(1) == 4.0  # F-order walks columns first

    def test_rsub_rdiv_vectors(self):
        """Nd4jTestsC.testRSubi / rdiv row-vector family."""
        a = array(np.full((2, 3), 2.0, np.float32))
        v = array(np.array([10.0, 20.0, 30.0], np.float32))
        np.testing.assert_array_equal(a.rsub_row_vector(v).numpy(),
                                      [[8, 18, 28]] * 2)
        np.testing.assert_array_equal(a.rdiv_row_vector(v).numpy(),
                                      [[5, 10, 15]] * 2)
        c = array(np.array([1.0, 2.0], np.float32))
        np.testing.assert_array_equal(a.rsub_column_vector(c).numpy(),
                                      [[-1, -1, -1], [0, 0, 0]])
        a.rsubi_row_vector(v)
        np.testing.assert_array_equal(a.numpy(), [[8, 18, 28]] * 2)

    def test_eps(self):
        """Nd4jTestsC.testEps."""
        a = array(np.array([1.0, 2.0, 3.0], np.float32))
        np.testing.assert_array_equal(
            a.eps(array(np.array([1.0, 2.5, 3.0], np.float32))).numpy(),
            [True, False, True])

    def test_number_reductions(self):
        a = _m34().addi(1)
        assert a.prod_number() == float(np.prod(np.arange(1, 13)))
        assert a.amax_number() == 12.0
        assert a.amin_number() == 1.0
        np.testing.assert_allclose(a.amean_number(), 6.5)
        p = array(np.array([0.25, 0.25, 0.25, 0.25], np.float32))
        np.testing.assert_allclose(p.shannon_entropy_number(), 2.0, rtol=1e-6)

    def test_entropy_median_percentile_dims(self):
        p = array(np.full((2, 4), 0.25, np.float32))
        np.testing.assert_allclose(p.shannon_entropy(1).numpy(), [2.0, 2.0])
        a = _m34()
        np.testing.assert_array_equal(a.median(1).numpy(), [1.5, 5.5, 9.5])
        np.testing.assert_allclose(a.percentile(50, 1).numpy(), [1.5, 5.5, 9.5])

    def test_dtype_class_predicates(self):
        assert array(np.zeros(2, np.float32)).is_r()
        assert array(np.zeros(2, np.int32)).is_z()
        assert array(np.zeros(2, bool)).is_b()
        assert not array(np.zeros(2, np.float32)).is_s()

    def test_vector_along_dimension(self):
        """Nd4jTestsC.testVectorAlongDimension."""
        a = _m34()
        v = a.vector_along_dimension(1, 1)  # second row-vector along dim 1
        np.testing.assert_array_equal(v.numpy(), [4, 5, 6, 7])
        assert a.vectors_along_dimension(1) == 3
        v.muli(0)
        np.testing.assert_array_equal(a.numpy()[1], [0, 0, 0, 0])

    def test_leading_trailing_ones_and_shapeinfo(self):
        a = array(np.zeros((1, 1, 3, 1), np.float32))
        assert a.get_leading_ones() == 2
        assert a.get_trailing_ones() == 1
        assert "4,1,1,3,1" in a.shape_info_to_string()

    def test_lifecycle_tail(self):
        a = array(np.zeros(3, np.float32))
        assert not a.is_attached() and not a.is_compressed() and not a.is_sparse()
        assert a.closeable() and not a.was_closed()
        assert a.migrate() is a and a.leverage() is a
        u = a.ulike()
        assert u.shape == a.shape and u.data_type == a.data_type
        a.close()
        assert a.was_closed()

    def test_conversions(self):
        a = _m34()
        m = a.to_long_matrix()
        assert m.dtype == np.int64 and m.shape == (3, 4)
        v = array(np.array([1.5, 2.5], np.float32)).to_long_vector()
        assert v.dtype == np.int64
        with pytest.raises(ValueError):
            a.to_long_vector()  # rank-2 is not a vector: IllegalState parity

    def test_transposei_and_slices(self):
        a = _m34()
        assert a.slices() == 3
        a.transposei()
        assert a.shape == (4, 3)

    def test_repmat(self):
        """Nd4jTestsC.testRepmat."""
        a = array(np.array([[1.0, 2.0]], np.float32))
        np.testing.assert_array_equal(a.repmat(2, 2).numpy(),
                                      [[1, 2, 1, 2]] * 2)

    def test_cumsumi_mutates(self):
        a = array(np.ones((2, 3), np.float32))
        a.cumsumi(1)
        np.testing.assert_array_equal(a.numpy(), [[1, 2, 3]] * 2)
