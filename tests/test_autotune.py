"""Persistent Pallas block autotuner (ISSUE 12 tentpole layer 3).

Acceptance pins:
- the interpret-mode search is DETERMINISTIC and lands exactly on the
  hand-measured static table at every BASELINE.md long-context grid point
  (exact-match acceptable; regression forbidden — on hardware the
  regression guard keeps a noisy winner from displacing the static entry);
- ``flash_attention`` consults a persisted measured entry before the
  static defaults, and the result stays numerically correct;
- the table round-trips to disk (atomic write, corruption-tolerant read,
  backend-keyed);
- CI lint: Pallas kernel call sites take block sizes from the registry or
  an explicit argument — never fresh numeric literals (``# block-ok:``
  escapes the static fallback table and the candidate grid).
"""

import ast
import json
import os
import pathlib

import numpy as np
import pytest

from deeplearning4j_tpu.kernels import autotune
from deeplearning4j_tpu.kernels.autotune import (AutotuneTable,
                                                 autotune_flash_attention,
                                                 resolve_blocks, shape_key,
                                                 static_flash_blocks)

ROOT = pathlib.Path(__file__).resolve().parent.parent / "deeplearning4j_tpu"


# -------------------------------------------------------------- static table


def test_static_table_matches_baseline_grid():
    """BASELINE.md r5: 128² below T=4096, (512, 1024) at and beyond."""
    assert static_flash_blocks(128, 128) == (128, 128)
    assert static_flash_blocks(2048, 2048) == (128, 128)
    assert static_flash_blocks(4096, 4096) == (512, 1024)
    assert static_flash_blocks(8192, 8192) == (512, 1024)
    assert static_flash_blocks(16384, 16384) == (512, 1024)
    # mixed: the SHORTER side decides (decode-with-prefix shapes)
    assert static_flash_blocks(128, 8192) == (128, 128)


def test_shape_key_buckets_nearby_shapes_together():
    k1 = shape_key("flash_attention", B=1, H=12, Tq=8000, Tk=8000, D=64,
                   dtype="bfloat16")
    k2 = shape_key("flash_attention", B=1, H=12, Tq=8192, Tk=8192, D=64,
                   dtype="bfloat16")
    assert k1 == k2  # both bucket to tq8192/tk8192
    assert shape_key("flash_attention", B=1, H=12, Tq=8192, Tk=8192, D=64,
                     dtype="float32") != k1  # dtype is part of the key
    assert "d64" in k1 and "bh16" in k1


# ---------------------------------------------------- deterministic search


def test_interpret_search_is_deterministic_static_fallback(tmp_path):
    """ISSUE 12 acceptance (CPU leg): at every BASELINE.md long-context
    grid point the interpret-mode search returns EXACTLY the hand-picked
    table (timing the Pallas interpreter would persist noise), twice in a
    row, and persists the entry."""
    table = AutotuneTable(str(tmp_path / "autotune_cpu.json"))
    for T in (2048, 4096, 8192, 16384):
        e1 = autotune_flash_attention(1, 12, T, 64, np.float32, table=table,
                                      interpret=True)
        e2 = autotune_flash_attention(1, 12, T, 64, np.float32, table=table,
                                      interpret=True)
        assert e1 == e2
        assert (e1["block_q"], e1["block_k"]) == static_flash_blocks(T, T)
        assert e1["measured"] is False
    # resolve_blocks now answers from the table at every grid point —
    # tuned >= hand-picked holds by exact match
    for T in (2048, 4096, 8192, 16384):
        assert resolve_blocks(
            "flash_attention", B=1, H=12, Tq=T, Tk=T, D=64, dtype="float32",
            table=table) == static_flash_blocks(T, T)


def test_regression_guard_keeps_static_winner(monkeypatch):
    """A 'winner' measured slower than the static choice must not displace
    it — tuned >= hand-picked at every point, by construction. Driven by a
    fake timer keyed on the deterministic candidate order ([(128, 256),
    (256, 256)] then the appended static (128, 128))."""
    import deeplearning4j_tpu.kernels.autotune as mod

    def timer_from(times):
        seq = iter(times)

        def fake(fn, *args, trials, warmup=1):
            return next(seq)

        return fake

    table = AutotuneTable(None)
    # static (last) measures FASTEST → static stays the winner
    monkeypatch.setattr(mod, "_time_best_of", timer_from([0.5, 0.5, 0.1]))
    e = autotune_flash_attention(
        1, 2, 256, 64, np.float32, table=table, interpret=False,
        candidates=[(128, 256), (256, 256)], trials=1,
        include_backward=False, persist=False)
    assert (e["block_q"], e["block_k"]) == (128, 128)
    assert e["measured"] is True

    # a candidate beats static → it displaces the static entry
    monkeypatch.setattr(mod, "_time_best_of", timer_from([0.5, 0.1, 0.5]))
    e = autotune_flash_attention(
        1, 2, 256, 64, np.float32, table=table, interpret=False,
        candidates=[(128, 256), (256, 256)], trials=1,
        include_backward=False, persist=False)
    assert (e["block_q"], e["block_k"]) == (256, 256)


def test_all_failed_candidates_record_unmeasured_fallback(tmp_path,
                                                          monkeypatch):
    """When every timed candidate fails (transient OOM, missing backend)
    the static fallback is recorded with measured:false — never as a
    'measured' table winner carrying best_us 0.0 that future lookups
    would report as a real measurement."""
    import deeplearning4j_tpu.kernels.autotune as mod
    from deeplearning4j_tpu.kernels.autotune import static_flash_blocks

    def boom(fn, *args, trials, warmup=1):
        raise RuntimeError("RESOURCE_EXHAUSTED: transient OOM")

    monkeypatch.setattr(mod, "_time_best_of", boom)
    table = AutotuneTable(str(tmp_path / "t.json"))
    e = autotune_flash_attention(
        1, 2, 256, 64, np.float32, table=table, interpret=False,
        candidates=[(128, 256)], trials=1, include_backward=False)
    assert e["measured"] is False
    assert e["source"] == "all-candidates-failed"
    assert (e["block_q"], e["block_k"]) == static_flash_blocks(256, 256)
    assert "best_us" not in e
    # persisted form keeps the honesty flag
    reloaded = AutotuneTable(str(tmp_path / "t.json"))
    assert len(reloaded) == 1
    key = mod.shape_key("flash_attention", B=1, H=2, Tq=256, Tk=256, D=64,
                        dtype="float32")
    assert reloaded.lookup(key)["measured"] is False


def test_candidate_validity_filters():
    assert autotune.candidate_valid(128, 128, 256, 256, 64)
    assert not autotune.candidate_valid(1024, 1024, 256, 256, 64)  # > T
    # VMEM blowout: giant probs block
    assert not autotune.candidate_valid(2048, 2048, 4096, 4096, 256)


# --------------------------------------------------------- flash consults


def test_flash_attention_consults_table_and_stays_correct(tmp_path,
                                                          monkeypatch):
    import jax.numpy as jnp

    from deeplearning4j_tpu.kernels import flash_attention, mha_reference
    from deeplearning4j_tpu.monitoring import get_registry

    d = tmp_path / "at"
    monkeypatch.setenv(autotune.ENV_DIR, str(d))
    autotune.reset_table()
    try:
        table = autotune.get_table()
        assert table.path and str(d) in table.path
        # persist a DISTINCTIVE measured winner for this shape bucket
        key = shape_key("flash_attention", B=2, H=2, Tq=64, Tk=64, D=16,
                        dtype="float32")
        table.record(key, {"block_q": 32, "block_k": 32, "measured": True})

        before = _lookup_count("table")
        rs = np.random.RandomState(0)
        q = jnp.asarray(rs.randn(2, 2, 64, 16), jnp.float32)
        k = jnp.asarray(rs.randn(2, 2, 64, 16), jnp.float32)
        v = jnp.asarray(rs.randn(2, 2, 64, 16), jnp.float32)
        out = flash_attention(q, k, v)
        assert _lookup_count("table") == before + 1
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(mha_reference(q, k, v)),
                                   atol=2e-5)
        # an explicit argument bypasses the table (no new lookup)
        flash_attention(q, k, v, block_q=16, block_k=16)
        assert _lookup_count("table") == before + 1
    finally:
        autotune.reset_table()


def _lookup_count(source):
    from deeplearning4j_tpu.monitoring import get_registry

    m = get_registry().get("tdl_autotune_lookups_total")
    if m is None:
        return 0
    for s in m.snapshot()["series"]:
        if s["labels"] == {"op": "flash_attention", "source": source}:
            return s["value"]
    return 0


# ------------------------------------------------------------- persistence


def test_table_roundtrip_and_corruption_tolerance(tmp_path):
    p = str(tmp_path / "autotune_cpu.json")
    t = AutotuneTable(p, backend="cpu")
    t.record("k1", {"block_q": 512, "block_k": 1024, "measured": True})
    t2 = AutotuneTable(p, backend="cpu")
    assert t2.lookup("k1")["block_q"] == 512
    # wrong backend: measured TPU tiles must never leak onto another backend
    assert AutotuneTable(p, backend="tpu").lookup("k1") is None
    # corruption degrades to empty, never raises
    with open(p, "w") as f:
        f.write("{torn json")
    assert AutotuneTable(p, backend="cpu").lookup("k1") is None
    # missing file is fine
    assert AutotuneTable(str(tmp_path / "nope.json"),
                         backend="cpu").lookup("k1") is None


def test_default_table_lives_next_to_compile_cache(tmp_path, monkeypatch):
    from deeplearning4j_tpu.common import compile_cache

    monkeypatch.delenv(autotune.ENV_DIR, raising=False)
    autotune.reset_table()
    try:
        compile_cache.enable(str(tmp_path / "cc"))
        path = autotune.default_table_path()
        assert path is not None
        assert os.path.join(str(tmp_path), "cc", "autotune") in path
    finally:
        compile_cache.disable()
        autotune.reset_table()


# ------------------------------------------------------------------- lint


_BLOCK_KEYWORDS = {"block_q", "block_k"}


def _int_literals(node):
    return [n for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, int)
            and not isinstance(n.value, bool)]


def test_no_hardcoded_pallas_block_sizes():
    """ISSUE 12 satellite (repo lint): Pallas kernel call sites in kernels/
    must take block sizes from the autotune registry or an explicit caller
    argument — never fresh numeric literals. The measured static fallback
    table and the candidate grid carry a ``# block-ok: <reason>`` escape.
    Scope: keyword arguments named block_q/block_k and assignments to those
    names whose value embeds an int literal."""
    offenders = []
    for path in sorted((ROOT / "kernels").rglob("*.py")):
        rel = path.relative_to(ROOT.parent).as_posix()
        src = path.read_text()
        lines = src.splitlines()
        tree = ast.parse(src, filename=rel)
        for node in ast.walk(tree):
            hits = []
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg in _BLOCK_KEYWORDS and _int_literals(kw.value):
                        hits.append(kw.value)
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                named = any(isinstance(t, ast.Name) and t.id in _BLOCK_KEYWORDS
                            for t in targets)
                if named and node.value is not None and \
                        _int_literals(node.value):
                    hits.append(node.value)
            for h in hits:
                line = lines[h.lineno - 1]
                if "block-ok" not in line and \
                        "block-ok" not in lines[node.lineno - 1]:
                    offenders.append(f"{rel}:{h.lineno}")
    assert not offenders, (
        "hardcoded Pallas block sizes (take them from kernels.autotune, an "
        "explicit argument, or justify with `# block-ok: <reason>`): "
        f"{offenders}")


def test_lint_catches_a_planted_literal(tmp_path):
    """The lint must actually bite: a planted call-site literal without the
    escape is flagged; with the escape it passes."""
    planted = "flash_attention(q, k, v, block_q=256, block_k=512)\n"
    tree = ast.parse(planted)
    call = tree.body[0].value
    flagged = [kw for kw in call.keywords
               if kw.arg in _BLOCK_KEYWORDS and _int_literals(kw.value)]
    assert len(flagged) == 2
