"""NLP tests (SURVEY §2.5): tokenizers, vocab/Huffman, Word2Vec SGNS on the
batched-TPU path, WordPiece + BertIterator."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    BertIterator,
    BertMaskedLMMasker,
    BertWordPieceTokenizer,
    CommonPreprocessor,
    DefaultTokenizerFactory,
    Huffman,
    VocabConstructor,
    Word2Vec,
    WordVectorSerializer,
)


def test_default_tokenizer_and_preprocessor():
    tf = DefaultTokenizerFactory(CommonPreprocessor())
    toks = tf.create("Hello, World! 123 foo.bar").get_tokens()
    assert toks == ["hello", "world", "foobar"]


def test_vocab_constructor_and_huffman():
    sents = ["a a a a b b c", "a b c d"]
    vocab = VocabConstructor(min_word_frequency=2).build_vocab(sents)
    assert vocab.num_words() == 3  # d dropped (freq 1)
    assert vocab.word_at_index(0) == "a"  # most frequent first
    words = vocab.vocab_words()
    Huffman(words).build()
    # Huffman: most frequent word gets the shortest code
    lens = {w.word: len(w.codes) for w in words}
    assert lens["a"] <= lens["b"] <= lens["c"]
    assert all(len(w.codes) == len(w.points) for w in words)


def _cluster_corpus(n=300, seed=1):
    """Two co-occurrence clusters: {cat,dog,pet} and {car,bus,road}."""
    rs = np.random.RandomState(seed)
    a, b = ["cat", "dog", "pet"], ["car", "bus", "road"]
    sents = []
    for _ in range(n):
        grp = a if rs.rand() < 0.5 else b
        sents.append(" ".join(rs.choice(grp, size=6)))
    return sents


def test_word2vec_sgns_clusters():
    w2v = (Word2Vec.Builder()
           .layer_size(24).window_size(3).min_word_frequency(1)
           .negative_sample(4).learning_rate(0.1).epochs(10)
           .batch_size(256).seed(7).sampling(0.0)  # 6-word vocab: every word
           # is "frequent"; default subsampling would discard ~90% of tokens
           .iterate(_cluster_corpus())
           .build())
    w2v.fit()
    # in-cluster similarity must beat cross-cluster
    assert w2v.similarity("cat", "dog") > w2v.similarity("cat", "car")
    assert w2v.similarity("bus", "road") > w2v.similarity("bus", "pet")
    near = w2v.words_nearest("cat", 2)
    assert set(near) <= {"dog", "pet"}


def test_word2vec_cbow_clusters():
    """CBOW path (was a silent no-op in r1 — VERDICT Weak #5)."""
    w2v = (Word2Vec.Builder()
           .layer_size(24).window_size(3).min_word_frequency(1)
           .negative_sample(4).learning_rate(0.1).epochs(10)
           .batch_size(256).seed(7).sampling(0.0)
           .cbow()
           .iterate(_cluster_corpus())
           .build())
    w2v.fit()
    assert w2v.cbow
    assert w2v.similarity("cat", "dog") > w2v.similarity("cat", "car")
    assert w2v.similarity("bus", "road") > w2v.similarity("bus", "pet")


def test_word2vec_hierarchical_softmax_clusters():
    """HS-only training (negative=0): the Huffman path actually trains
    (r1 built the tree and discarded it)."""
    w2v = (Word2Vec.Builder()
           .layer_size(24).window_size(3).min_word_frequency(1)
           .negative_sample(0).use_hierarchic_softmax()
           .learning_rate(0.15).epochs(10)
           .batch_size(256).seed(7).sampling(0.0)
           .iterate(_cluster_corpus())
           .build())
    w2v.fit()
    assert w2v.syn1 is not None and np.abs(w2v.syn1).sum() > 0
    assert w2v.syn1neg is None  # no NS table when negative=0
    assert w2v.similarity("cat", "dog") > w2v.similarity("cat", "car")
    assert w2v.similarity("bus", "road") > w2v.similarity("bus", "pet")


def test_word2vec_cbow_hs_combo():
    w2v = Word2Vec(layer_size=16, window=3, negative=3, hs=True, cbow=True,
                   subsampling=0.0, learning_rate=0.1, epochs=4,
                   batch_size=128, seed=11)
    w2v.fit(_cluster_corpus(120))
    assert w2v.syn1 is not None and w2v.syn1neg is not None
    assert np.isfinite(w2v.syn0).all()


def test_word2vec_no_objective_raises():
    import pytest
    with pytest.raises(ValueError):
        Word2Vec(negative=0, hs=False)


def test_word_vector_serializer_roundtrip(tmp_path):
    w2v = Word2Vec(layer_size=8, epochs=1, batch_size=64, seed=3)
    w2v.fit(_cluster_corpus(50))
    p = str(tmp_path / "vecs.txt")
    WordVectorSerializer.write_word_vectors(w2v, p)
    w2 = WordVectorSerializer.read_word_vectors(p)
    v1, v2 = w2v.get_word_vector("cat"), w2.get_word_vector("cat")
    np.testing.assert_allclose(v1, v2, atol=1e-5)


def _wp_vocab():
    words = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
             "the", "quick", "brown", "fox", "jump", "##ed", "##s", "over", "dog", "."]
    return {w: i for i, w in enumerate(words)}


def test_wordpiece_tokenizer():
    tok = BertWordPieceTokenizer(_wp_vocab())
    assert tok.tokenize("The quick fox jumped") == ["the", "quick", "fox", "jump", "##ed"]
    assert tok.tokenize("zebra") == ["[UNK]"]
    ids = tok.convert_tokens_to_ids(["the", "##s"])
    assert ids == [_wp_vocab()["the"], _wp_vocab()["##s"]]


def test_bert_iterator_masked_lm():
    tok = BertWordPieceTokenizer(_wp_vocab())
    sents = ["the quick brown fox", "the dog jumps over the fox ."] * 4
    it = BertIterator(tokenizer=tok, sentences=sents, max_length=16, batch_size=4,
                      task="UNSUPERVISED",
                      masker=BertMaskedLMMasker(mask_token_id=_wp_vocab()["[MASK]"],
                                                vocab_size=len(_wp_vocab())))
    mds = next(iter(it))
    ids, segs = mds.features
    assert ids.shape == (4, 16) and segs.shape == (4, 16)
    labels = mds.labels[0]
    lm_mask = mds.labels_masks[0]
    assert lm_mask.sum() >= 4  # ≥1 masked position per sentence
    # where lm_mask is set, labels hold the ORIGINAL token (ids may differ)
    masked_pos = np.nonzero(lm_mask)
    assert labels.shape == ids.shape
    # all batches drain
    count = sum(1 for _ in it)
    assert count == 2


def test_bert_iterator_classification():
    tok = BertWordPieceTokenizer(_wp_vocab())
    sents = ["the fox", "the dog", "quick fox", "dog ."]
    it = BertIterator(tokenizer=tok, sentences=sents, labels=[0, 1, 0, 1],
                      max_length=8, batch_size=2, task="SEQ_CLASSIFICATION", n_classes=2)
    mds = next(iter(it))
    assert mds.labels[0].shape == (2, 2)
    np.testing.assert_allclose(mds.labels[0], [[1, 0], [0, 1]])


def test_w2v_sharded_embedding_tables_match_single_device():
    """J17 distributed embedding: tables sharded over a mesh axis train to
    the same vectors as the single-device run (GSPMD collectives replace the
    reference's parameter-server protocol)."""
    import jax
    from jax.sharding import Mesh

    rs = np.random.RandomState(0)
    vocab = [f"w{i}" for i in range(64)]
    sentences = [" ".join(rs.choice(vocab, size=rs.randint(6, 12)))
                 for _ in range(200)]

    ref = Word2Vec(layer_size=16, window=3, negative=5, epochs=2,
                   batch_size=512, seed=9)
    ref.fit(sentences)

    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    sharded = Word2Vec(layer_size=16, window=3, negative=5, epochs=2,
                       batch_size=512, seed=9, mesh=mesh)
    sharded.fit(sentences)
    np.testing.assert_allclose(sharded.syn0, ref.syn0, rtol=1e-4, atol=1e-5)
