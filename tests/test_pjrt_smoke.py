"""PJRT C-API smoke surface (native/tnd_pjrt.cpp; SURVEY §2.9 N1/N13).

The C++ runtime drives a real PJRT plugin with no Python in the loop:
dlopen + GetPjrtApi + version negotiation run everywhere; client creation,
H2D/D2H and compile+execute require attached hardware, so those run when a
plugin can actually initialize and skip (with the plugin's own error) when
not — e.g. on this build host libtpu reports "No jellyfish device found"
because the TPU is only reachable through the axon tunnel.

Runs in a subprocess: libtpu does not tolerate re-initialization in a
process that may later (or already did) init JAX.
"""

import json
import subprocess
import sys

import pytest

from deeplearning4j_tpu.native import pjrt

_CHILD = r"""
import json

import numpy as np

from deeplearning4j_tpu.native.pjrt import PjrtSmoke, PjrtSmokeError

out = {}
s = PjrtSmoke().open()
out["api_version"] = s.api_version()
try:
    s.create_client()
    out["platform"] = s.platform_name()
    out["devices"] = s.device_count()
    x = np.arange(16, dtype=np.float32)
    out["roundtrip_ok"] = bool(np.allclose(s.roundtrip(x), x))
    out["add_ok"] = bool(np.allclose(s.execute_add(x, 2 * x), 3 * x))
    s.close()
except PjrtSmokeError as e:
    out["client_error"] = str(e)[:200]
print("RESULT " + json.dumps(out))
"""


@pytest.mark.skipif(not pjrt.buildable(), reason="g++ or pjrt_c_api.h unavailable")
@pytest.mark.skipif(pjrt.default_plugin_path() is None, reason="no PJRT plugin .so")
def test_pjrt_c_abi_smoke():
    import os

    env = dict(os.environ)
    # the child must see the real environment (libtpu init consults TPU_*/
    # metadata vars; a stripped env makes it probe the network and hang) but
    # must NOT inherit a forced-CPU JAX setting from the test session
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run([sys.executable, "-c", _CHILD], capture_output=True,
                          text=True, timeout=180, env=env)
    lines = [l for l in proc.stdout.splitlines() if l.startswith("RESULT ")]
    assert lines, f"child failed:\n{proc.stdout}\n{proc.stderr[-2000:]}"
    res = json.loads(lines[0][len("RESULT "):])
    # the ABI surface itself must always work: load + version negotiation
    major, minor = res["api_version"]
    assert major >= 0 and minor > 0
    if "client_error" in res:
        # no locally-attached accelerator: the plugin must have failed with
        # its own initialization error, not an ABI-level crash
        assert "client_create" in res["client_error"]
        pytest.skip(f"no local PJRT device: {res['client_error']}")
    # hardware present: the full C-only path must produce correct numerics
    assert res["devices"] >= 1
    assert res["roundtrip_ok"] and res["add_ok"]
