"""Device-resident input pipeline (ISSUE 4): narrow uint8 wire format +
on-device normalization parity, DevicePrefetchIterator overlap/placement,
async-iterator error propagation and cheap reset, sharded gang prefetch."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data import (
    ArrayDataSetIterator,
    AsyncDataSetIterator,
    DataSet,
    DevicePrefetchIterator,
    ImagePreProcessingScaler,
    ListDataSetIterator,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
    make_device_ingest,
)
from deeplearning4j_tpu.data.iterators import DataSetIterator
from deeplearning4j_tpu.monitoring import MetricsRegistry
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf import (
    ConvolutionLayer,
    DenseLayer,
    InputType,
    OutputLayer,
)
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.parallel import ParallelTrainer, build_mesh


# ----------------------------------------------------------------- fake bases


class CountingIterator(DataSetIterator):
    """n batches of (batch, 4) floats; counts next() calls across resets."""

    def __init__(self, n=500, batch=8, delay_s=0.0, fail_at=None):
        self.n, self._batch, self.delay_s = n, batch, delay_s
        self.fail_at = fail_at
        self.next_calls = 0
        self._pos = 0

    def has_next(self):
        return self._pos < self.n

    def next(self):
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.fail_at is not None and self._pos == self.fail_at:
            raise RuntimeError(f"ETL blew up at batch {self._pos}")
        self.next_calls += 1
        x = np.full((self._batch, 4), self._pos, np.float32)
        y = np.eye(2, dtype=np.float32)[np.arange(self._batch) % 2]
        self._pos += 1
        return DataSet(x, y)

    def reset(self):
        self._pos = 0

    def batch(self):
        return self._batch


# --------------------------------------------- satellite: error propagation


class TestAsyncErrorPropagation:
    def test_etl_error_reraised_not_truncated(self):
        it = AsyncDataSetIterator(CountingIterator(n=10, fail_at=3), queue_size=2)
        seen = 0
        with pytest.raises(RuntimeError, match="blew up at batch 3"):
            while it.has_next():
                it.next()
                seen += 1
        # every batch produced before the failure is delivered, then the
        # error surfaces — the epoch is not silently cut short
        assert seen == 3

    def test_error_sticks_until_reset(self):
        it = AsyncDataSetIterator(CountingIterator(n=10, fail_at=0), queue_size=2)
        with pytest.raises(RuntimeError):
            it.has_next()
        with pytest.raises(RuntimeError):  # sticky: can't mistake for clean end
            it.next()
        base = CountingIterator(n=4)
        it._base = base  # swap in a healthy base; reset must clear the error
        it.reset()
        assert sum(1 for _ in it) == 4

    def test_device_prefetch_propagates_base_exception(self):
        it = DevicePrefetchIterator(CountingIterator(n=10, fail_at=2),
                                    buffer_size=2, registry=MetricsRegistry())
        with pytest.raises(RuntimeError, match="blew up"):
            while it.has_next():
                it.next()


# --------------------------------------------------- satellite: cheap reset


class TestAsyncReset:
    def test_reset_does_not_drain_epoch(self):
        base = CountingIterator(n=500)
        it = AsyncDataSetIterator(base, queue_size=2)
        for _ in range(3):
            it.next()
        it.reset()
        # worker produced at most consumed + queue + in-flight, not the epoch
        assert base.next_calls <= 3 + 2 + 2, base.next_calls

    def test_reset_then_full_epoch(self):
        base = CountingIterator(n=20)
        it = AsyncDataSetIterator(base, queue_size=3)
        it.next()
        it.reset()
        assert sum(1 for _ in it) == 20

    def test_reset_before_consumption_costs_nothing(self):
        base = CountingIterator(n=500)
        it = AsyncDataSetIterator(base, queue_size=2)
        it.reset()
        assert base.next_calls == 0

    def test_next_after_exhaustion_raises_not_hangs(self):
        it = AsyncDataSetIterator(CountingIterator(n=2), queue_size=2)
        while it.has_next():
            it.next()
        with pytest.raises(StopIteration, match="reset"):
            it.next()


# ------------------------------------------------- device prefetch iterator


class TestDevicePrefetch:
    def test_batches_arrive_device_resident(self):
        reg = MetricsRegistry()
        it = DevicePrefetchIterator(CountingIterator(n=4), buffer_size=2,
                                    registry=reg)
        batches = list(it)
        assert len(batches) == 4
        for ds in batches:
            assert isinstance(ds.features, jax.Array)
            assert isinstance(ds.labels, jax.Array)
        stats = it.stats()
        # 4 batches × (8×4 f32 features + 8×2 f32 labels)
        assert stats["h2d_bytes"] == 4 * (8 * 4 * 4 + 8 * 2 * 4)
        assert stats["epoch_steps"] == 5  # 4 batches + the END sentinel pop
        assert reg.get("tdl_h2d_bytes_total").value == stats["h2d_bytes"]

    def test_fit_with_device_resident_batches_matches_host_path(self):
        """The fit loop detects already-placed batches (_put passthrough):
        training through DevicePrefetchIterator is numerically identical to
        the synchronous host path."""
        x = np.random.default_rng(0).normal(size=(32, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[np.arange(32) % 3]
        dss = [DataSet(x[i:i + 8], y[i:i + 8]) for i in range(0, 32, 8)]

        def _net():
            conf = (NeuralNetConfiguration.Builder().seed(7).updater(Sgd(0.1))
                    .list()
                    .layer(DenseLayer(n_in=6, n_out=8, activation="tanh"))
                    .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                                       loss="mcxent"))
                    .build())
            return MultiLayerNetwork(conf).init()

        a, b = _net(), _net()
        a.fit(ListDataSetIterator(dss))
        b.fit(DevicePrefetchIterator(ListDataSetIterator(dss), buffer_size=2,
                                     registry=MetricsRegistry()))
        np.testing.assert_allclose(a.params().numpy(), b.params().numpy(),
                                   atol=1e-6)

    def test_overlap_hides_slow_etl(self):
        """Slow fake iterator (20 ms/batch) + a consumer 'step' slower than
        ETL → per-step input wait ≈ 0 after warmup: the prefetcher keeps the
        queue ahead of the consumer."""
        it = DevicePrefetchIterator(
            CountingIterator(n=10, delay_s=0.02), buffer_size=3,
            registry=MetricsRegistry())
        while it.has_next():
            it.next()
            time.sleep(0.04)  # simulated device step, slower than ETL
        steady = it.wait_seconds[2:]
        assert steady and float(np.median(steady)) < 0.01, it.wait_seconds
        assert it.stats()["input_wait_ms_per_step"] < 10.0

    def test_sharded_placement_on_mesh(self):
        mesh = build_mesh(data=8)
        from deeplearning4j_tpu.parallel.sharding import batch_sharding

        sh = batch_sharding(mesh)
        it = DevicePrefetchIterator(CountingIterator(n=3, batch=16),
                                    buffer_size=2, sharding=sh,
                                    registry=MetricsRegistry())
        ds = it.next()
        assert ds.features.sharding.is_equivalent_to(sh, ds.features.ndim)

    def test_remainder_batch_falls_back_to_default_placement(self):
        mesh = build_mesh(data=8)
        from deeplearning4j_tpu.parallel.sharding import batch_sharding

        it = DevicePrefetchIterator(CountingIterator(n=2, batch=12),
                                    buffer_size=2,
                                    sharding=batch_sharding(mesh),
                                    registry=MetricsRegistry())
        ds = it.next()  # 12 % 8 != 0 → staged unsharded, trainer slices it
        assert isinstance(ds.features, jax.Array)


# ------------------------------------------------------ gang (mesh) prefetch


def test_parallel_trainer_prefetch_matches_synchronous():
    x = np.random.default_rng(1).normal(size=(64, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.argmax(x[:, :3], axis=1)]
    dss = [DataSet(x[i:i + 16], y[i:i + 16]) for i in range(0, 64, 16)]

    def _net():
        conf = (NeuralNetConfiguration.Builder().seed(3).updater(Sgd(0.05))
                .list()
                .layer(DenseLayer(n_in=6, n_out=16, activation="tanh"))
                .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                                   loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    a, b = _net(), _net()
    ParallelTrainer(a, mesh=build_mesh(data=8)).fit(ListDataSetIterator(dss))
    ParallelTrainer(b, mesh=build_mesh(data=8)).fit(ListDataSetIterator(dss),
                                                    prefetch=2)
    np.testing.assert_allclose(a.params().numpy(), b.params().numpy(),
                               atol=1e-6)


# ------------------------------------------- narrow wire format: parity tests


class TestWireFormatParity:
    def test_standardize_device_transform_matches_host(self):
        rs = np.random.RandomState(0)
        x = rs.randn(64, 5).astype(np.float32) * 3 + 1
        norm = NormalizerStandardize()
        norm.fit(ListDataSetIterator([DataSet(x, np.zeros((64, 1), np.float32))]))
        ds = DataSet(x.copy(), None)
        norm.transform(ds)
        dev = np.asarray(norm.device_transform(jnp.asarray(x)))
        np.testing.assert_allclose(dev, ds.features, atol=1e-6)

    def test_standardize_device_transform_matches_host_4d(self):
        rs = np.random.RandomState(1)
        x = rs.randn(8, 3, 6, 6).astype(np.float32) * 2 - 1
        norm = NormalizerStandardize()
        norm.fit(ListDataSetIterator([DataSet(x, np.zeros((8, 1), np.float32))]))
        ds = DataSet(x.copy(), None)
        norm.transform(ds)
        dev = np.asarray(norm.device_transform(jnp.asarray(x)))
        np.testing.assert_allclose(dev, ds.features, atol=1e-6)

    def test_minmax_device_transform_matches_host(self):
        rs = np.random.RandomState(2)
        x = rs.rand(32, 4).astype(np.float32) * 10
        norm = NormalizerMinMaxScaler()
        norm.fit(ListDataSetIterator([DataSet(x, np.zeros((32, 1), np.float32))]))
        ds = DataSet(x.copy(), None)
        norm.transform(ds)
        dev = np.asarray(norm.device_transform(jnp.asarray(x)))
        np.testing.assert_allclose(dev, ds.features, atol=1e-6)

    def test_scaler_device_transform_matches_host(self):
        rs = np.random.RandomState(3)
        x = rs.randint(0, 256, (16, 3, 5, 5)).astype(np.float32)
        scaler = ImagePreProcessingScaler()
        ds = DataSet(x.copy(), None)
        scaler.transform(ds)
        dev = np.asarray(scaler.device_transform(jnp.asarray(x, jnp.uint8)))
        np.testing.assert_allclose(dev, ds.features, atol=1e-6)

    def test_make_device_ingest_nhwc_uint8(self):
        rs = np.random.RandomState(4)
        u8 = rs.randint(0, 256, (6, 8, 8, 3), np.uint8)
        ingest = make_device_ingest(ImagePreProcessingScaler(),
                                    source_layout="NHWC")
        got = np.asarray(ingest(jnp.asarray(u8)))
        want = u8.transpose(0, 3, 1, 2).astype(np.float32) / 255.0
        assert got.dtype == np.float32
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_make_device_ingest_rejects_bad_layout(self):
        with pytest.raises(ValueError, match="NCHW or NHWC"):
            make_device_ingest(source_layout="HWCN")

    def test_network_output_parity_uint8_wire_vs_host_normalize(self):
        """End-to-end acceptance parity: uint8 NHWC wire + on-device ingest
        ≡ float32 NCHW host-normalized input, within 1e-6."""
        conf = (NeuralNetConfiguration.Builder().seed(5).updater(Sgd(0.01))
                .list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                        activation="relu"))
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional(8, 8, 3))
                .build())
        net = MultiLayerNetwork(conf).init()
        rs = np.random.RandomState(6)
        u8 = rs.randint(0, 256, (5, 8, 8, 3), np.uint8)
        host_f32 = u8.transpose(0, 3, 1, 2).astype(np.float32) / 255.0

        out_host = net.output(host_f32).numpy()
        net.set_device_ingest(make_device_ingest(ImagePreProcessingScaler(),
                                                 source_layout="NHWC"))
        out_wire = net.output(u8).numpy()
        np.testing.assert_allclose(out_wire, out_host, atol=1e-6)

        net.set_device_ingest(None)  # removable: host path restored
        np.testing.assert_allclose(net.output(host_f32).numpy(), out_host,
                                   atol=1e-6)

    def test_train_step_parity_uint8_wire_vs_host_normalize(self):
        """One fit step through the compiled-in ingest matches the host-
        normalized f32 path (the normalization really is inside the step)."""
        def _net():
            conf = (NeuralNetConfiguration.Builder().seed(9).updater(Sgd(0.1))
                    .list()
                    .layer(ConvolutionLayer(n_out=2, kernel_size=(3, 3),
                                            activation="relu"))
                    .layer(OutputLayer(n_out=2, activation="softmax",
                                       loss="mcxent"))
                    .set_input_type(InputType.convolutional(6, 6, 1))
                    .build())
            return MultiLayerNetwork(conf).init()

        rs = np.random.RandomState(7)
        u8 = rs.randint(0, 256, (8, 6, 6, 1), np.uint8)
        y = np.eye(2, dtype=np.float32)[np.arange(8) % 2]
        host_f32 = u8.transpose(0, 3, 1, 2).astype(np.float32) / 255.0

        a, b = _net(), _net()
        a.fit(DataSet(host_f32, y))
        b.set_device_ingest(make_device_ingest(ImagePreProcessingScaler(),
                                               source_layout="NHWC"))
        b.fit(DataSet(u8, y))
        np.testing.assert_allclose(a.params().numpy(), b.params().numpy(),
                                   atol=1e-6)

    def test_uint8_wire_is_4x_narrower(self):
        """The staged bytes really shrink 4x: uint8 wire vs float32 wire for
        the same images (labels excluded from the comparison)."""
        rs = np.random.RandomState(8)
        u8 = rs.randint(0, 256, (16, 8, 8, 3), np.uint8)
        f32 = u8.astype(np.float32)

        def staged_bytes(feat):
            reg = MetricsRegistry()
            it = DevicePrefetchIterator(
                ListDataSetIterator([DataSet(feat, None)]), buffer_size=1,
                registry=reg)
            list(it)
            return reg.get("tdl_h2d_bytes_total").value

        assert staged_bytes(f32) == 4 * staged_bytes(u8)


# ----------------------------------- per-input ingest on ComputationGraph


class TestGraphPerInputIngest:
    """set_device_ingest({input_name: fn}) scopes the ingest to one named
    input of a multi-input graph — the image input rides the uint8 wire
    while the dense side input stages at model dtype, untouched."""

    @staticmethod
    def _build():
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.graph_conf import MergeVertex

        g = (NeuralNetConfiguration.Builder().seed(13).updater(Sgd(0.05))
             .graph_builder().add_inputs("img", "side")
             .set_input_types(InputType.convolutional(6, 6, 1),
                              InputType.feed_forward(4)))
        g.add_layer("c", ConvolutionLayer(n_out=2, kernel_size=(3, 3),
                                          activation="relu"), "img")
        g.add_layer("dimg", DenseLayer(n_out=4, activation="tanh"), "c")
        g.add_vertex("m", MergeVertex(), "dimg", "side")
        g.add_layer("output", OutputLayer(n_out=3, activation="softmax",
                                          loss="mcxent"), "m")
        g.set_outputs("output")
        return ComputationGraph(g.build()).init()

    def test_output_parity_dict_ingest(self):
        rs = np.random.RandomState(11)
        u8 = rs.randint(0, 256, (5, 6, 6, 1), np.uint8)
        side = rs.rand(5, 4).astype(np.float32)
        host_img = u8.transpose(0, 3, 1, 2).astype(np.float32) / 255.0

        net = self._build()
        out_host = net.output(host_img, side)[0].numpy()
        net.set_device_ingest({"img": make_device_ingest(
            ImagePreProcessingScaler(), source_layout="NHWC")})
        out_wire = net.output(u8, side)[0].numpy()
        np.testing.assert_allclose(out_wire, out_host, atol=1e-6)

    def test_dict_ingest_rejected_on_multilayer(self):
        """A dict of ingests needs named inputs — MultiLayerNetwork rejects
        it at set time instead of failing opaquely mid-jit-trace."""
        conf = (NeuralNetConfiguration.Builder().seed(1).updater(Sgd(0.1))
                .list()
                .layer(DenseLayer(n_in=4, n_out=2, activation="tanh"))
                .layer(OutputLayer(n_in=2, n_out=2, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        with pytest.raises(TypeError, match="ComputationGraph"):
            net.set_device_ingest({"input": lambda x: x})

    def test_fit_parity_dict_ingest(self):
        rs = np.random.RandomState(12)
        u8 = rs.randint(0, 256, (5, 6, 6, 1), np.uint8)
        side = rs.rand(5, 4).astype(np.float32)
        host_img = u8.transpose(0, 3, 1, 2).astype(np.float32) / 255.0
        y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 5)]

        a, b = self._build(), self._build()
        a.fit([host_img, side], y)
        b.set_device_ingest({"img": make_device_ingest(
            ImagePreProcessingScaler(), source_layout="NHWC")})
        b.fit([u8, side], y)
        for name in a.params_:
            for p in a.params_[name]:
                np.testing.assert_allclose(
                    np.asarray(a.params_[name][p]),
                    np.asarray(b.params_[name][p]), atol=1e-6,
                    err_msg=f"{name}/{p}")


# --------------------------------------- tbptt with device-resident batches


def test_tbptt_device_resident_batch_matches_host():
    """_fit_tbptt pads/segments device arrays with jnp ops (a prefetched
    batch must not round-trip d2h→h2d) and matches the numpy host path —
    including the tail-pad branch (T=10, fwd=4) and a device-side mask."""
    from deeplearning4j_tpu.nn.conf import GravesLSTM, RnnOutputLayer

    B, C, T = 4, 2, 10
    rng = np.random.default_rng(5)
    x = rng.normal(size=(B, C, T)).astype(np.float32)
    y = np.moveaxis(np.eye(C, dtype=np.float32)[x.argmax(1)], 2, 1)
    lmask = np.ones((B, T), np.float32)
    lmask[:, -3:] = 0.0

    def _rnn():
        conf = (NeuralNetConfiguration.Builder().seed(1).updater(Sgd(0.05))
                .list()
                .layer(GravesLSTM(n_in=2, n_out=8))
                .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(2))
                .t_bptt_length(4)
                .build())
        return MultiLayerNetwork(conf).init()

    a, b = _rnn(), _rnn()
    a.fit(DataSet(x, y, labels_mask=lmask))
    b.fit(DataSet(jnp.asarray(x), jnp.asarray(y),
                  labels_mask=jnp.asarray(lmask)))
    np.testing.assert_allclose(a.params().numpy(), b.params().numpy(),
                               atol=1e-6)
    np.testing.assert_allclose(float(a.score()), float(b.score()), atol=1e-6)
