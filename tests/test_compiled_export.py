"""Compiled-artifact export/reload (SURVEY §2.9 N11/N12): StableHLO module +
weights zip executes WITHOUT the Python model object."""

import numpy as np

import jax.numpy as jnp

from deeplearning4j_tpu.serde.compiled import (
    CompiledModel,
    _flatten,
    _unflatten,
    load_compiled,
)


def _mlp():
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import (
        BatchNormalization,
        DenseLayer,
        InputType,
        OutputLayer,
    )
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (
        NeuralNetConfiguration.Builder()
        .seed(3)
        .updater(Adam(1e-2))
        .list()
        .layer(DenseLayer(n_in=6, n_out=16, activation="relu"))
        .layer(BatchNormalization())
        .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(6))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def test_flatten_roundtrip():
    tree = {"a": {"b": np.ones((2,)), "c": np.zeros((3,))},
            "lst": [np.full((1,), 2.0), {"d": np.full((2, 2), 3.0)}]}
    back = _unflatten(_flatten(tree))
    assert set(back) == {"a", "lst"}
    np.testing.assert_array_equal(back["lst"][1]["d"], tree["lst"][1]["d"])


def test_mln_export_reload_matches(tmp_path):
    net = _mlp()
    # train a little so bn stats + params are non-trivial
    rs = np.random.RandomState(0)
    x = rs.randn(16, 6).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 16)]
    from deeplearning4j_tpu.data.dataset import DataSet

    for _ in range(3):
        net._fit_batch(DataSet(x, y))

    want = np.asarray(net.output(x).numpy())
    p = str(tmp_path / "model.zip")
    net.export(p, x)
    loaded = load_compiled(p)
    assert isinstance(loaded, CompiledModel)
    got = np.asarray(loaded(x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert loaded.metadata["model_type"] == "MultiLayerNetwork"


def test_export_without_batchnorm(tmp_path):
    """bn_state == {} must survive the flatten/unflatten round trip (empty
    containers are part of the export calling convention)."""
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (
        NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-2)).list()
        .layer(DenseLayer(n_in=5, n_out=8, activation="relu"))
        .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(5))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = np.random.RandomState(4).randn(3, 5).astype(np.float32)
    p = str(tmp_path / "nobn.zip")
    net.export(p, x)
    got = np.asarray(load_compiled(p)(x))
    np.testing.assert_allclose(got, np.asarray(net.output(x).numpy()),
                               rtol=1e-5, atol=1e-6)


def test_samediff_export_reload_matches(tmp_path):
    from deeplearning4j_tpu.autodiff.samediff import SameDiff

    sd = SameDiff.create()
    x = sd.placeholder("x", (4, 3))
    w = sd.var("w", np.random.RandomState(1).randn(3, 5).astype(np.float32))
    b = sd.var("b", np.zeros(5, np.float32))
    h = sd.op("relu", sd.nn().linear(x, w, b))
    out = sd.op("softmax", h)

    ph = {"x": np.random.RandomState(2).randn(4, 3).astype(np.float32)}
    want = np.asarray(sd.output(ph, out.name)[out.name])

    p = str(tmp_path / "sd.zip")
    sd.save_compiled(p, ph, out.name)
    loaded = load_compiled(p)
    got = loaded({"x": jnp.asarray(ph["x"])})[out.name]
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)


def test_artifact_is_self_contained(tmp_path):
    """The zip holds everything: module bytes, weights, metadata."""
    import zipfile

    net = _mlp()
    x = np.random.RandomState(0).randn(4, 6).astype(np.float32)
    p = str(tmp_path / "m.zip")
    net.export(p, x)
    with zipfile.ZipFile(p) as z:
        names = set(z.namelist())
    assert names == {"model.stablehlo", "weights.npz", "metadata.json"}
