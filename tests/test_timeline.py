"""Fleet timeline (ISSUE 16): cross-process trace propagation + the
wall-clock-aligned Perfetto/chrome-trace export.

Fast tier: clock-skew correction over synthetic spools (anchors, NTP-step
median, event-pair fallback, unplaceable-spool dropping), flow joining by
trace id, supervisor-verdict mirroring onto worker lanes, torn-spool
counting under the shared reader-labeled error counter, the EVENT_KINDS
AST lint (with a planted-offender self-test), trace-id propagation through
JsonModelServer, run-id inheritance via TDL_RUN_ID, `/debug/timeline` on
UIServer, OpProfiler spool round-trip, the concurrent-span-nesting and
StepPhaseRecorder.discard() telemetry-purity satellites, and memory-gauge
sampling.

Slow tier: the acceptance chaos run — a 2-rank gang with an injected crash
and a 2-replica serving pool with traced requests, merged into ONE
chrome-trace JSON with the cross-process handshake aligned within 50 ms.
"""

import ast
import json
import os
import pathlib
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.monitoring import flight, timeline
from deeplearning4j_tpu.monitoring.flight import (EVENT_KINDS, FlightRecorder,
                                                  clock_anchor)
from deeplearning4j_tpu.monitoring.registry import MetricsRegistry
from deeplearning4j_tpu.monitoring.trace import StepPhaseRecorder, span

ROOT = pathlib.Path(__file__).resolve().parent.parent
WORKERS = os.path.join(os.path.dirname(__file__), "mp_workers.py")
POOL_WORKERS = os.path.join(os.path.dirname(__file__), "pool_workers.py")


# ------------------------------------------------------- synthetic spools


def _write_spool(directory, proc, events, offset=0.0, anchors=True,
                 run_id=None):
    """A flight spool whose private clock runs ``offset`` seconds behind
    the wall (anchor wall = mono + 1000 + offset)."""
    payload = {"proc": proc, "pid": 1, "events": events}
    if anchors:
        payload["anchors"] = [{"mono": 100.0, "wall": 1100.0 + offset}]
    if run_id:
        payload["run_id"] = run_id
    os.makedirs(directory, exist_ok=True)
    flight.atomic_json_write(
        os.path.join(directory, f"{flight.SPOOL_PREFIX}{proc}.json"), payload)


def _by_name(doc):
    out = {}
    for ev in doc["traceEvents"]:
        out.setdefault(ev.get("name"), []).append(ev)
    return out


def test_skew_correction_aligns_lanes_and_joins_flows(tmp_path):
    """Two spools, 5 s of synthetic clock skew between them: after the
    anchor correction the replica's request_span lands INSIDE the router's
    route slice, and one flow (s → f) joins them by trace id."""
    d = str(tmp_path)
    _write_spool(d, "router", [
        {"t": 100.5, "kind": "route", "request_id": "r1", "trace_id": "tr1",
         "replica": 1, "seconds": 0.2}], offset=0.0, run_id="runA")
    _write_spool(d, "replica1", [
        {"t": 95.45, "kind": "request_span", "request_id": "r1",
         "trace_id": "tr1", "outcome": "ok",
         "phases": {"queue": 0.01, "infer": 0.05}}], offset=5.0,
        run_id="runA")
    doc = timeline.build_timeline(flight_dirs=[d], registry=MetricsRegistry())
    assert doc["otherData"]["flows"] == 1
    assert doc["otherData"]["spools_dropped"] == 0
    assert doc["otherData"]["run_ids"] == ["runA"]
    by = _by_name(doc)
    route = by["route"][0]
    spn = by["request:ok"][0]
    assert route["ph"] == "X" and spn["ph"] == "X"
    assert route["pid"] != spn["pid"]  # distinct lanes
    # post-correction the span nests inside the route slice (µs axis)
    assert route["ts"] <= spn["ts"] + 1.0
    assert spn["ts"] + spn["dur"] <= route["ts"] + route["dur"] + 1.0
    flows = [e for e in doc["traceEvents"] if e.get("cat") == "trace"]
    assert {e["ph"] for e in flows} == {"s", "f"}
    assert all(e["id"] == "tr1" for e in flows)
    assert [e for e in flows if e["ph"] == "f"][0]["bp"] == "e"


def test_median_offset_shrugs_off_one_ntp_step():
    """One NTP-stepped anchor among several must not move the lane: the
    median of wall − mono ignores the outlier."""
    anchors = [{"mono": 10.0, "wall": 1010.0},
               {"mono": 20.0, "wall": 1020.0},
               {"mono": 30.0, "wall": 4030.0},  # 3000 s step, then corrected
               {"mono": 40.0, "wall": 1040.0},
               {"mono": 50.0, "wall": 1050.0}]
    assert timeline._median_offset(anchors) == 1000.0


def test_anchorless_spool_falls_back_to_event_wall_pairs(tmp_path):
    """A pre-anchor spool still places: the events' own (t, wall) pairs
    derive the offset. A spool with neither is dropped AND counted."""
    d = str(tmp_path)
    _write_spool(d, "old", [
        {"t": 5.0, "wall": 2005.0, "kind": "alert", "rule": "x"}],
        anchors=False)
    _write_spool(d, "unplaceable", [{"t": 7.0, "kind": "alert"}],
                 anchors=False)
    doc = timeline.build_timeline(flight_dirs=[d], registry=MetricsRegistry())
    assert doc["otherData"]["spools_dropped"] == 1
    assert "old" in doc["otherData"]["procs"]
    assert "unplaceable" not in doc["otherData"]["procs"]


def test_supervisor_verdicts_mirror_onto_worker_lanes(tmp_path):
    """gang_failure names ranks=[1]: the instant appears on the supervisor
    lane AND is mirrored onto the rank1 lane, so the lane that died shows
    where in its own stream it died."""
    d = str(tmp_path)
    _write_spool(d, "supervisor", [
        {"t": 50.0, "kind": "gang_failure", "reason": "crash", "ranks": [1],
         "iteration": 7}])
    _write_spool(d, "rank1", [
        {"t": 49.9, "kind": "step_begin", "iteration": 7}])
    doc = timeline.build_timeline(flight_dirs=[d], registry=MetricsRegistry())
    procs = doc["otherData"]["procs"]
    failures = _by_name(doc)["gang_failure"]
    assert {e["pid"] for e in failures} == {procs["supervisor"],
                                            procs["rank1"]}
    assert all(e["ph"] == "i" and e["s"] == "p" for e in failures)
    # the unpaired step_begin renders as the crash signature
    assert any(n.startswith("step_begin 7") for n in _by_name(doc))


def test_step_pairs_fold_into_slices(tmp_path):
    d = str(tmp_path)
    _write_spool(d, "rank0", [
        {"t": 10.0, "kind": "step_begin", "iteration": 3},
        {"t": 10.5, "kind": "step_end", "iteration": 3, "loss": 0.5}])
    doc = timeline.build_timeline(flight_dirs=[d], registry=MetricsRegistry())
    steps = _by_name(doc)["step 3"]
    assert steps[0]["ph"] == "X"
    assert steps[0]["dur"] == pytest.approx(0.5e6, rel=1e-3)


def test_torn_spool_counts_under_timeline_reader_label(tmp_path):
    d = str(tmp_path)
    _write_spool(d, "good", [{"t": 1.0, "kind": "alert", "rule": "r"}])
    with open(os.path.join(d, f"{flight.SPOOL_PREFIX}bad.json"), "w") as f:
        f.write('{"torn')
    reg = MetricsRegistry()
    doc = timeline.build_timeline(flight_dirs=[d], registry=reg)
    assert "good" in doc["otherData"]["procs"]
    series = reg.get("tdl_spool_read_errors_total").snapshot()["series"]
    labels = {tuple(s["labels"].items()): s["value"] for s in series}
    assert labels[(("reader", "timeline"), ("proc", "bad"))] == 1.0


def test_history_rings_count_under_history_reader_label(tmp_path):
    """Satellite: EVERY scan_spool_json call site feeds the shared counter
    with its own reader label — history.read_rings included."""
    from deeplearning4j_tpu.monitoring import history
    from deeplearning4j_tpu.monitoring.aggregate import spool_read_errors
    from deeplearning4j_tpu.monitoring.registry import get_registry

    d = str(tmp_path)
    with open(os.path.join(d, f"{history.SPOOL_PREFIX}rank0.1.json"),
              "w") as f:
        f.write("not json")
    errors = spool_read_errors(get_registry())
    before = errors.labels("history", "rank0").value
    assert history.read_rings(d) == []
    assert errors.labels("history", "rank0").value == before + 1


def test_trace_json_is_perfetto_shaped(tmp_path):
    """Structural contract of the export: serializable, µs timestamps from
    a zero origin, known phase letters, metadata lanes for every proc."""
    d = str(tmp_path)
    _write_spool(d, "router", [
        {"t": 10.0, "kind": "route", "request_id": "a", "trace_id": "t1",
         "replica": 0, "seconds": 0.1},
        {"t": 11.0, "kind": "pool_scale", "direction": "up"}])
    _write_spool(d, "replica0", [
        {"t": 9.95, "kind": "request_span", "request_id": "a",
         "trace_id": "t1", "outcome": "ok", "phases": {"infer": 0.02}}],
        offset=2.5)
    out = tmp_path / "trace.json"
    timeline.write_timeline(str(out), flight_dirs=[d],
                            registry=MetricsRegistry())
    with open(out) as f:
        doc = json.load(f)  # artifact round-trips as strict JSON
    assert doc["displayTimeUnit"] == "ms"
    pids = set(doc["otherData"]["procs"].values())
    seen_meta = set()
    for ev in doc["traceEvents"]:
        assert ev["ph"] in ("X", "i", "M", "s", "t", "f")
        assert ev["pid"] in pids
        assert ev["ts"] >= 0
        if ev["ph"] == "X":
            assert ev["dur"] > 0
        if ev["ph"] == "i":
            assert ev["s"] in ("t", "p")
        if ev["ph"] == "M":
            seen_meta.add((ev["pid"], ev["name"]))
    for pid in pids:
        assert (pid, "process_name") in seen_meta
        assert (pid, "thread_name") in seen_meta


def test_optrace_spools_merge_onto_the_same_axis(tmp_path):
    """OpProfiler spools (private perf_counter origin) land on the shared
    wall axis next to the flight lanes, under the proc's own lane."""
    from deeplearning4j_tpu.ops.profiler import OpProfiler, ProfilerConfig

    fdir, odir = str(tmp_path / "fl"), str(tmp_path / "op")
    prof = OpProfiler(ProfilerConfig(trace_events=True), proc="rank0",
                      directory=odir)
    with prof.timed("matmul"):
        time.sleep(0.002)
    assert prof.flush() is not None
    _write_spool(fdir, "rank0", [{"t": 1.0, "kind": "step_begin",
                                  "iteration": 0}])
    doc = timeline.build_timeline(flight_dirs=[fdir], optrace_dirs=[odir],
                                  registry=MetricsRegistry())
    by = _by_name(doc)
    assert "matmul" in by
    assert by["matmul"][0]["pid"] == doc["otherData"]["procs"]["rank0"]


def test_optrace_prefix_stays_in_sync_with_profiler():
    from deeplearning4j_tpu.ops import profiler

    assert timeline.OPTRACE_PREFIX == profiler.SPOOL_PREFIX


# ------------------------------------------------ EVENT_KINDS AST lint


def _record_kind_literals(tree):
    """Every ``<anything>.record("<literal>", ...)`` call's kind literal."""
    out = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "record"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            out.append((node.args[0].value, node.lineno))
    return out


def test_every_flight_record_kind_is_registered():
    """Repo lint (satellite): a ``flight.record("new_kind", ...)`` call
    whose kind is not in ``flight.EVENT_KINDS`` fails here — the schema
    table in OBSERVABILITY.md and the registry can't silently drift."""
    root = ROOT / "deeplearning4j_tpu"
    offenders = []
    for path in sorted(root.rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        tree = ast.parse(path.read_text(), filename=rel)
        for kind, lineno in _record_kind_literals(tree):
            if kind not in EVENT_KINDS:
                offenders.append(f"{rel}:{lineno} kind={kind!r}")
    assert not offenders, (
        "flight.record() with a kind missing from flight.EVENT_KINDS "
        "(add it there AND to the OBSERVABILITY.md event table): "
        f"{offenders}")


def test_kind_lint_catches_a_planted_offender():
    """The lint must actually bite: a planted record() with an unregistered
    kind is flagged; a registered kind passes."""
    planted = ast.parse(
        'flight.record("definitely_not_a_kind", x=1)\n'
        'self._flight.record(\n    "step_begin", iteration=3)\n')
    kinds = [k for k, _ in _record_kind_literals(planted)]
    assert kinds == ["definitely_not_a_kind", "step_begin"]
    assert "definitely_not_a_kind" not in EVENT_KINDS
    assert "step_begin" in EVENT_KINDS


# ------------------------------------------- recorder anchors + run id


def test_recorder_spools_anchors_and_run_identity(tmp_path, monkeypatch):
    monkeypatch.setenv(flight.ENV_RUN_ID, "run42")
    monkeypatch.setenv(flight.ENV_RANK, "1")
    rec = FlightRecorder(proc="rank1", directory=str(tmp_path), interval=0.0)
    rec.record("step_begin", iteration=0)
    rec.flush()
    with open(rec.path) as f:
        payload = json.load(f)
    assert payload["run_id"] == "run42"
    assert payload["rank"] == 1
    # one anchor from open + one per flush, each a usable (mono, wall) pair
    assert len(payload["anchors"]) >= 2
    for a in payload["anchors"]:
        assert a["wall"] > a["mono"]
    ev = payload["events"][0]
    assert ev["run_id"] == "run42" and ev["rank"] == 1


def test_clock_anchor_pairs_the_two_clocks():
    a = clock_anchor()
    b = clock_anchor()
    assert b["mono"] >= a["mono"] and b["wall"] >= a["wall"]
    # the offset the merge computes is stable between back-to-back anchors
    assert abs((a["wall"] - a["mono"]) - (b["wall"] - b["mono"])) < 0.1


# ------------------------------------------------- trace-id propagation


def test_trace_id_adopts_sane_headers_and_inherits_rid():
    from deeplearning4j_tpu.serving.json_server import _trace_id

    assert _trace_id("client-trace-1", "rid") == "client-trace-1"
    assert _trace_id(None, "rid") == "rid"
    assert _trace_id("", "rid") == "rid"
    assert _trace_id("\x00\x01evil", "rid") == "rid"
    assert _trace_id("x" * 500, "rid") == "rid"


class _Double:
    def output(self, x):
        return np.asarray(x, np.float32) * 2.0


def test_server_echoes_trace_id_and_stamps_spans(tmp_path):
    """End to end through one JsonModelServer: the client's X-Trace-Id
    comes back on the response AND lands in the request_span flight
    event; an insane header degrades to the request id."""
    from deeplearning4j_tpu.serving import JsonModelServer

    rec = FlightRecorder(proc="server", directory=None)
    flight.set_flight_recorder(rec)
    server = JsonModelServer(_Double(), port=0,
                             warmup_input=np.zeros((1, 4), np.float32))
    try:
        server.start()
        assert server.wait_ready(60.0)

        def post(headers):
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/predict",
                data=json.dumps([[1.0, 2.0, 3.0, 4.0]]).encode(),
                headers={"Content-Type": "application/json", **headers})
            with urllib.request.urlopen(req, timeout=15) as resp:
                return json.loads(resp.read()), dict(resp.headers)

        _, h = post({"X-Trace-Id": "trace-abc", "X-Request-Id": "req-1"})
        assert h["X-Trace-Id"] == "trace-abc"
        _, h2 = post({"X-Trace-Id": "\x00bad", "X-Request-Id": "req-2"})
        assert h2["X-Trace-Id"] == "req-2"
    finally:
        server.stop()
        flight.set_flight_recorder(None)
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        spans = {e.get("request_id"): e for e in rec.events()
                 if e["kind"] == "request_span"}
        if {"req-1", "req-2"} <= set(spans):
            break
        time.sleep(0.05)
    assert spans["req-1"]["trace_id"] == "trace-abc"
    assert spans["req-1"]["outcome"] == "ok"
    assert spans["req-2"]["trace_id"] == "req-2"


def test_ui_serves_debug_timeline(tmp_path):
    from deeplearning4j_tpu.ui.server import UIServer

    d = str(tmp_path)
    _write_spool(d, "rank0", [{"t": 1.0, "kind": "step_begin",
                               "iteration": 0}])
    ui = UIServer(port=0)
    try:
        ui.attach_registry(MetricsRegistry())
        url = f"http://127.0.0.1:{ui.port}/debug/timeline"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url, timeout=10)
        assert ei.value.code == 404  # nothing attached yet
        ui.attach_timeline(flight_dirs=d)
        with urllib.request.urlopen(url, timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["otherData"]["procs"] == {"rank0": 1}
        assert any(e["name"] != "process_name" for e in doc["traceEvents"])
    finally:
        ui.stop()


# ------------------------------------- span nesting / phase purity (sat d)


def test_span_nesting_is_per_thread_under_concurrency():
    """Two threads nesting spans concurrently never see each other's stack:
    qualified names stay thread-local and unwind cleanly."""
    from deeplearning4j_tpu.monitoring.trace import current_span_path

    barrier = threading.Barrier(2, timeout=30)
    results = {}
    errors = []

    def worker(name):
        try:
            for _ in range(20):
                with span(name):
                    barrier.wait()  # both threads inside their outer span
                    with span("inner"):
                        results[name] = current_span_path()
                    barrier.wait()
                assert current_span_path() == ""
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(n,))
               for n in ("alpha", "beta")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errors
    assert results == {"alpha": "alpha/inner", "beta": "beta/inner"}


def _phase_counts(reg):
    m = reg.get("tdl_step_phase_seconds")
    if m is None:
        return {}
    return {s["labels"]["phase"]: s["count"]
            for s in m.snapshot()["series"]}


def test_step_phase_discard_leaves_no_partial_rows():
    """discard() must drop accumulated phase time WITHOUT observing it: the
    registry histogram sees only completed steps, never the StopIteration
    stub slice a loop boundary records."""
    reg = MetricsRegistry()
    rec = StepPhaseRecorder(registry=reg)
    with rec.phase("input"):
        pass
    rec.discard()
    assert _phase_counts(reg) == {}  # nothing observed, no empty series
    with rec.phase("input"):
        pass
    with rec.phase("compute"):
        pass
    rec.step_done()
    assert _phase_counts(reg) == {"input": 1, "compute": 1}
    # and the discarded slice didn't leak into the completed step's totals
    assert rec.summary()["steps"] == 1


# -------------------------------------------------- memory gauges (sat b)


def test_sample_memory_sets_host_rss_gauge():
    from deeplearning4j_tpu.monitoring import heartbeat

    reg = MetricsRegistry()
    out = heartbeat.sample_memory(reg)
    assert out["host_rss"] > 0
    assert reg.get("tdl_mem_host_rss_bytes").value == out["host_rss"]
    # jax IS imported in the test process; device stats are best-effort on
    # CPU backends (may expose no memory_stats), but sampling never raises
    # and anything it did sample is a positive byte count
    for label, v in out.items():
        assert v >= 0


# ------------------------------------------------------------- slow tier


@pytest.mark.slow
def test_fleet_timeline_chaos_acceptance(tmp_path, monkeypatch):
    """Acceptance: a crash-injected 2-rank gang AND a 2-replica serving
    pool with traced requests merge into ONE chrome-trace JSON — request
    spans under the router and replica lanes joined by trace id, the
    crash on the correct rank lane, and the cross-process handshake pair
    (router route ↔ replica request_span) aligned within 50 ms after skew
    correction."""
    from deeplearning4j_tpu.parallel import GangSupervisor
    from deeplearning4j_tpu.serving import ServingPool

    # -- half 1: supervised gang with an injected crash on rank 1 ---------
    env = {"TDL_MP_OUT": str(tmp_path / "out.json"),
           "TDL_MP_CKPT": str(tmp_path / "ckpt"),
           "TDL_MP_STEPS": "10",
           "TDL_MP_CKPT_EVERY": "2",
           "TDL_MATMUL_PRECISION": "float32",
           "TDL_FAULT_SPEC": "crash@iter=7,rank=1",
           "TDL_FLIGHT_INTERVAL": "0",
           "TDL_METRICS_SPOOL_INTERVAL": "0"}
    os.makedirs(env["TDL_MP_CKPT"], exist_ok=True)
    sup = GangSupervisor(f"{WORKERS}:supervised_train", n_processes=2,
                         n_local_devices=2, extra_env=env,
                         workdir=str(tmp_path / "gang"),
                         heartbeat_interval=0.0, startup_grace=300.0,
                         backoff_base=0.1, kill_grace=1.0, max_restarts=3,
                         registry=MetricsRegistry())
    results = sup.run(timeout=540.0)
    for r in results:
        assert r.returncode == 0, f"rank {r.rank} failed:\n{r.stderr[-3000:]}"
    assert sup.restarts >= 1
    # the postmortem embedded its timeline artifact
    with open(sup.postmortem_path) as f:
        pm = json.load(f)
    assert pm["timeline"] and os.path.exists(pm["timeline"])
    with open(pm["timeline"]) as f:
        gang_doc = json.load(f)
    assert {"rank0", "rank1", "supervisor"} <= set(
        gang_doc["otherData"]["procs"])

    # -- half 2: serving pool with one traced request ---------------------
    pool = ServingPool(f"{POOL_WORKERS}:stub_server",
                       workdir=str(tmp_path / "pool"), replicas=2,
                       min_replicas=1, registry=MetricsRegistry(),
                       extra_env={"TDL_FLIGHT_INTERVAL": "0"})
    # the ROUTER half of the handshake records into the pool's flight dir
    monkeypatch.setenv("TDL_PROC_NAME", "router")
    monkeypatch.setenv(flight.ENV_DIR, pool.flight_dir)
    monkeypatch.setenv(flight.ENV_INTERVAL, "0")
    monkeypatch.setenv(flight.ENV_RUN_ID, pool.run_id)
    trace_id = "chaos-trace-1"
    try:
        pool.start()
        assert pool.wait_ready(60.0)
        req = urllib.request.Request(
            f"http://127.0.0.1:{pool.port}/predict",
            data=json.dumps([[1.0, 2.0, 3.0, 4.0]]).encode(),
            headers={"Content-Type": "application/json",
                     "X-Trace-Id": trace_id})
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert resp.headers["X-Trace-Id"] == trace_id
            replica_lane = f"replica{resp.headers['X-Replica']}"
            json.loads(resp.read())
        flight.flush()  # router-side route event (interval 0 → already spooled)
    finally:
        pool.stop()

    # -- ONE merged artifact over both fleets -----------------------------
    gang_flight_dirs = sorted(
        os.path.join(sup.workdir, d) for d in os.listdir(sup.workdir)
        if d.startswith("flight_"))
    merged_path = str(tmp_path / "fleet_timeline.json")
    reg = MetricsRegistry()
    timeline.write_timeline(merged_path,
                            flight_dirs=gang_flight_dirs + [pool.flight_dir],
                            extra_events=sup._flight.events(), registry=reg)
    with open(merged_path) as f:
        doc = json.load(f)
    procs = doc["otherData"]["procs"]
    assert {"rank0", "rank1", "supervisor", "router",
            replica_lane} <= set(procs)
    assert {sup.run_id, pool.run_id} <= set(doc["otherData"]["run_ids"])

    events = doc["traceEvents"]
    # Perfetto structural contract on the merged artifact
    for ev in events:
        assert ev["ph"] in ("X", "i", "M", "s", "t", "f")
        assert ev["ts"] >= 0

    # crash + respawn instants on the CORRECT rank lane
    rank1 = procs["rank1"]
    assert any(ev["name"] == "fault_injected" and ev["pid"] == rank1
               for ev in events)
    assert any(ev["name"] == "gang_failure" and ev["pid"] == rank1
               for ev in events)  # mirrored supervisor verdict
    assert any(ev["name"] == "restart_decision"
               and ev["pid"] == procs["supervisor"] for ev in events)

    # the traced request: router route slice + replica request_span joined
    route = next(ev for ev in events if ev["name"] == "route"
                 and ev.get("args", {}).get("trace_id") == trace_id)
    spn = next(ev for ev in events if ev["name"].startswith("request:")
               and ev.get("args", {}).get("trace_id") == trace_id)
    assert route["pid"] == procs["router"]
    assert spn["pid"] == procs[replica_lane]
    flows = [ev for ev in events if ev.get("cat") == "trace"
             and ev.get("id") == trace_id]
    assert {ev["ph"] for ev in flows} >= {"s", "f"}
    # the handshake pair aligns within 50 ms post-skew-correction: the
    # replica span starts inside (or within 50 ms of) the route slice
    tol_us = 50_000.0
    assert route["ts"] - tol_us <= spn["ts"] <= route["ts"] + route["dur"] + tol_us
    assert spn["ts"] + spn["dur"] <= route["ts"] + route["dur"] + tol_us
