"""GloVe / ParagraphVectors / tSNE (SURVEY §2.5 P5)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.nlp.tsne import BarnesHutTsne


def _two_topic_corpus(n=300, seed=0):
    """Sentences drawn from two disjoint topic vocabularies: embeddings must
    put same-topic words closer than cross-topic words."""
    rs = np.random.RandomState(seed)
    animals = ["cat", "dog", "fox", "wolf", "bear", "lion"]
    tools = ["hammer", "wrench", "drill", "saw", "pliers", "chisel"]
    out = []
    for _ in range(n):
        vocab = animals if rs.rand() < 0.5 else tools
        out.append(" ".join(rs.choice(vocab, size=rs.randint(5, 10))))
    return out, animals, tools


class TestGlove:
    def test_learns_topic_structure(self):
        sentences, animals, tools = _two_topic_corpus()
        g = (Glove.Builder().layer_size(24).window_size(4).epochs(40)
             .learning_rate(0.1).seed(7).iterate(sentences).build())
        g.fit()
        same = g.similarity("cat", "dog")
        cross = g.similarity("cat", "hammer")
        assert same > cross, (same, cross)
        assert g.loss_curve[-1] < g.loss_curve[0]

    def test_word_vector_and_nearest(self):
        sentences, animals, tools = _two_topic_corpus()
        g = Glove(layer_size=16, window=4, epochs=25, learning_rate=0.1, seed=3)
        g.fit(sentences)
        assert g.get_word_vector("cat").shape == (16,)
        near = g.words_nearest("cat", 3)
        assert len(near) == 3


class TestParagraphVectors:
    def _docs(self, n=120, seed=1):
        rs = np.random.RandomState(seed)
        animals = ["cat", "dog", "fox", "wolf", "bear", "lion"]
        tools = ["hammer", "wrench", "drill", "saw", "pliers", "chisel"]
        docs = []
        for i in range(n):
            topic = "animal" if i % 2 == 0 else "tool"
            vocab = animals if topic == "animal" else tools
            docs.append((f"{topic}_{i}", " ".join(rs.choice(vocab, size=rs.randint(8, 14)))))
        return docs

    def test_doc_vectors_cluster_by_topic(self):
        docs = self._docs()
        pv = ParagraphVectors(layer_size=24, window=3, epochs=80,
                              learning_rate=0.05, batch_size=128, seed=5)
        pv.fit(docs)
        a = np.stack([pv.get_vector(l) for l, _ in docs if l.startswith("animal")])
        t = np.stack([pv.get_vector(l) for l, _ in docs if l.startswith("tool")])

        def cos(u, v):
            return (u @ v) / (np.linalg.norm(u) * np.linalg.norm(v) + 1e-12)

        within = cos(a.mean(0), a[0]) + cos(t.mean(0), t[0])
        across = cos(a.mean(0), t[0]) + cos(t.mean(0), a[0])
        assert within > across, (within, across)

    def test_infer_vector_lands_near_topic(self):
        docs = self._docs()
        pv = ParagraphVectors(layer_size=24, window=3, epochs=80,
                              learning_rate=0.05, batch_size=128, seed=5)
        pv.fit(docs)
        v = pv.infer_vector("cat dog wolf bear cat lion dog", steps=100,
                            learning_rate=0.1)
        near = pv.nearest_labels(v, 10)
        animal_frac = sum(1 for l in near if l.startswith("animal")) / len(near)
        assert animal_frac >= 0.7, near

    def test_dbow_mode_trains(self):
        docs = self._docs(40)
        pv = ParagraphVectors(layer_size=12, window=3, epochs=5, dm=False,
                              train_words=False, seed=2)
        pv.fit(docs)
        assert pv.doc_vectors.shape == (40, 12)

        assert np.all(np.isfinite(pv.doc_vectors))

    def test_dbow_with_train_words_raises(self):
        pv = ParagraphVectors(dm=False, train_words=True)
        with pytest.raises(ValueError, match="PV-DBOW"):
            pv.fit(self._docs(4))


class TestTsne:
    def test_clusters_stay_separated(self):
        rs = np.random.RandomState(0)
        centers = np.array([[8.0] * 10, [-8.0] * 10, [8.0] * 5 + [-8.0] * 5])
        x = np.concatenate([c + rs.randn(25, 10) for c in centers]).astype(np.float32)
        labels = np.repeat([0, 1, 2], 25)
        ts = BarnesHutTsne(perplexity=10, n_iter=300, learning_rate=100.0, seed=1)
        y = ts.fit_transform(x)
        assert y.shape == (75, 2)
        # 1-NN purity in the embedding: same-cluster neighbors dominate
        d = ((y[:, None] - y[None]) ** 2).sum(-1)
        np.fill_diagonal(d, np.inf)
        nn = d.argmin(1)
        purity = float(np.mean(labels[nn] == labels))
        assert purity > 0.9, purity
        assert ts.kl_curve_[-1] < ts.kl_curve_[0]

    def test_builder_surface(self):
        ts = (BarnesHutTsne.Builder().set_max_iter(100).perplexity(5.0)
              .learning_rate(50.0).theta(0.5).seed(4).build())
        x = np.random.RandomState(2).randn(30, 6).astype(np.float32)
        y = ts.fit_transform(x)
        assert y.shape == (30, 2)
