"""Zoo breadth wave (SURVEY §2.4 C15): init + forward + one train step on
small input shapes."""

import numpy as np
import pytest

from deeplearning4j_tpu.models import AlexNet, Darknet19, SqueezeNet, UNet, Xception


@pytest.mark.parametrize("zoo,shape,classes", [
    (lambda: AlexNet(num_classes=7, input_shape=(3, 67, 67)), (3, 67, 67), 7),
    (lambda: Darknet19(num_classes=7, input_shape=(3, 64, 64)), (3, 64, 64), 7),
    (lambda: SqueezeNet(num_classes=7, input_shape=(3, 64, 64)), (3, 64, 64), 7),
    (lambda: Xception(num_classes=7, input_shape=(3, 32, 32), middle_blocks=1),
     (3, 32, 32), 7),
])
def test_classifier_zoo_forward(zoo, shape, classes):
    net = zoo().init()
    x = np.random.RandomState(0).randn(2, *shape).astype(np.float32)
    out = net.output(x)
    arr = np.asarray(out[0].numpy() if isinstance(out, list) else out.numpy())
    assert arr.shape == (2, classes)
    np.testing.assert_allclose(arr.sum(-1), 1.0, rtol=1e-4)  # softmax head


def test_unet_segmentation_shape():
    net = UNet(n_channels_out=1, input_shape=(3, 32, 32), base_filters=4,
               depth=2).init()
    x = np.random.RandomState(1).randn(2, 3, 32, 32).astype(np.float32)
    out = net.output(x)
    arr = np.asarray(out[0].numpy() if isinstance(out, list) else out.numpy())
    assert arr.shape == (2, 1, 32, 32)
    assert 0.0 <= arr.min() and arr.max() <= 1.0  # sigmoid map
