"""NN framework tests — modeled on deeplearning4j's MultiLayerTest /
gradient-check / semantics tiers (SURVEY §4.3): small nets, real training on
tiny data, convergence + shape + serialization assertions."""

import numpy as np
import pytest

import deeplearning4j_tpu.ndarray as nd
from deeplearning4j_tpu.data import ArrayDataSetIterator, DataSet, ListDataSetIterator
from deeplearning4j_tpu.data.iterators import AsyncDataSetIterator
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf import (
    ActivationLayer,
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    DropoutLayer,
    EmbeddingSequenceLayer,
    GlobalPoolingLayer,
    GravesLSTM,
    InputType,
    LSTM,
    LastTimeStep,
    MultiLayerConfiguration,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.updaters import Adam, Nesterovs, Sgd


def _xor_data(n=256, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 2)).astype(np.float32)
    y_idx = ((x[:, 0] * x[:, 1]) > 0).astype(int)
    return x, np.eye(2, dtype=np.float32)[y_idx]


def _mlp_conf(updater=None):
    return (
        NeuralNetConfiguration.Builder()
        .seed(42)
        .updater(updater or Adam(1e-2))
        .list()
        .layer(DenseLayer(n_in=2, n_out=24, activation="relu"))
        .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(2))
        .build()
    )


class TestMlpTraining:
    def test_loss_decreases_and_learns(self):
        x, y = _xor_data()
        net = MultiLayerNetwork(_mlp_conf()).init()
        it = ArrayDataSetIterator(x, y, batch_size=64, shuffle=True)
        net.fit(it, epochs=1)
        first = net.score()
        net.fit(it, epochs=25)
        assert net.score() < first * 0.6
        acc = net.evaluate(ArrayDataSetIterator(x, y, batch_size=128)).accuracy()
        assert acc > 0.9

    def test_output_shape_and_softmax(self):
        x, y = _xor_data(32)
        net = MultiLayerNetwork(_mlp_conf()).init()
        out = net.output(x).numpy()
        assert out.shape == (32, 2)
        assert np.allclose(out.sum(-1), 1.0, atol=1e-5)

    def test_sgd_and_nesterovs_train(self):
        x, y = _xor_data(128)
        for upd in (Sgd(0.5), Nesterovs(0.1, 0.9)):
            net = MultiLayerNetwork(_mlp_conf(upd)).init()
            ds = DataSet(x, y)
            s0 = None
            for _ in range(40):
                net.fit(ds)
                s0 = s0 or net.score()
            assert net.score() < s0

    def test_params_flat_roundtrip(self):
        net = MultiLayerNetwork(_mlp_conf()).init()
        flat = net.params()
        assert flat.length == net.num_params()
        net2 = MultiLayerNetwork(_mlp_conf()).init()
        net2.set_params(flat)
        assert np.allclose(net2.params().numpy(), flat.numpy())

    def test_set_params_wrong_size_message(self):
        net = MultiLayerNetwork(_mlp_conf()).init()
        with pytest.raises(ValueError, match="numParams"):
            net.set_params(nd.zeros(7))

    def test_json_roundtrip_preserves_model(self):
        x, _ = _xor_data(16)
        conf = _mlp_conf()
        net = MultiLayerNetwork(conf).init()
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        net2 = MultiLayerNetwork(conf2).init()
        net2.set_params(net.params())
        assert np.allclose(net.output(x).numpy(), net2.output(x).numpy(), atol=1e-6)

    def test_async_iterator_trains_same(self):
        x, y = _xor_data(128)
        base = ArrayDataSetIterator(x, y, batch_size=32)
        wrapped = AsyncDataSetIterator(ArrayDataSetIterator(x, y, batch_size=32))
        seen_base = sum(1 for _ in base)
        seen_async = sum(1 for _ in wrapped)
        assert seen_base == seen_async == 4
        # reset + re-iterate works
        assert sum(1 for _ in wrapped) == 4


class TestCnn:
    def _lenet_ish(self):
        return (
            NeuralNetConfiguration.Builder()
            .seed(1)
            .updater(Adam(1e-3))
            .list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3), stride=(1, 1), activation="relu"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 1))
            .build()
        )

    def test_shape_inference_and_forward(self):
        conf = self._lenet_ish()
        net = MultiLayerNetwork(conf).init()
        x = np.random.default_rng(0).normal(size=(5, 1, 8, 8)).astype(np.float32)
        out = net.output(x).numpy()
        assert out.shape == (5, 3)
        # auto-inserted CnnToFeedForward before the dense layer
        assert any(type(p).__name__ == "CnnToFeedForwardPreProcessor" for p in conf.preprocessors.values())

    def test_cnn_trains(self):
        rng = np.random.default_rng(3)
        x = rng.normal(size=(60, 1, 8, 8)).astype(np.float32)
        y_idx = (x.mean((1, 2, 3)) > 0).astype(int)
        y = np.eye(3, dtype=np.float32)[y_idx]
        net = MultiLayerNetwork(self._lenet_ish()).init()
        ds = DataSet(x, y)
        net.fit(ds)
        s0 = net.score()
        for _ in range(30):
            net.fit(ds)
        assert net.score() < s0

    def test_batchnorm_cnn(self):
        conf = (
            NeuralNetConfiguration.Builder()
            .updater(Sgd(0.1))
            .list()
            .layer(ConvolutionLayer(n_out=2, kernel_size=(3, 3), activation="relu"))
            .layer(BatchNormalization())
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(6, 6, 1))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        x = np.random.default_rng(0).normal(size=(8, 1, 6, 6)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[np.arange(8) % 2]
        m0 = net.bn_state["1"]["mean"].copy()
        net.fit(DataSet(x, y))
        assert not np.allclose(net.bn_state["1"]["mean"], m0)  # running stats moved
        assert net.output(x).shape == (8, 2)


class TestRnn:
    def _seq_data(self, B=16, T=10, C=3, seed=0):
        """Predict class by which channel has the largest mean over time."""
        rng = np.random.default_rng(seed)
        x = rng.normal(size=(B, C, T)).astype(np.float32)
        y_idx = x.mean(-1).argmax(-1)
        y = np.eye(C, dtype=np.float32)[y_idx]  # [B,C]
        return x, y

    def test_lstm_last_timestep_classifier(self):
        x, y = self._seq_data()
        conf = (
            NeuralNetConfiguration.Builder()
            .seed(7)
            .updater(Adam(5e-3))
            .list()
            .layer(LSTM(n_in=3, n_out=16))
            .layer(LastTimeStep())
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(3))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        ds = DataSet(x, y)
        net.fit(ds)
        s0 = net.score()
        for _ in range(60):
            net.fit(ds)
        assert net.score() < s0 * 0.7

    def test_rnn_output_layer_time_distributed(self):
        B, C, T = 8, 3, 6
        rng = np.random.default_rng(0)
        x = rng.normal(size=(B, C, T)).astype(np.float32)
        y_idx = x.argmax(1)  # [B,T]
        y = np.moveaxis(np.eye(C, dtype=np.float32)[y_idx], 2, 1)  # [B,C,T]
        conf = (
            NeuralNetConfiguration.Builder()
            .seed(1)
            .updater(Adam(1e-2))
            .list()
            .layer(LSTM(n_in=3, n_out=12))
            .layer(RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(3))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        out = net.output(x).numpy()
        assert out.shape == (B, C, T)
        assert np.allclose(out.sum(1), 1.0, atol=1e-5)
        ds = DataSet(x, y)
        net.fit(ds)
        s0 = net.score()
        for _ in range(40):
            net.fit(ds)
        assert net.score() < s0

    def test_tbptt_with_mask(self):
        """tBPTT over T=10 with fwd=4 (tail pad) + a labels mask."""
        B, C, T = 4, 2, 10
        rng = np.random.default_rng(0)
        x = rng.normal(size=(B, C, T)).astype(np.float32)
        y = np.moveaxis(np.eye(C, dtype=np.float32)[x.argmax(1)], 2, 1)
        lmask = np.ones((B, T), np.float32)
        lmask[:, -3:] = 0.0
        conf = (
            NeuralNetConfiguration.Builder()
            .seed(1)
            .updater(Adam(1e-2))
            .list()
            .layer(GravesLSTM(n_in=2, n_out=8))
            .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(2))
            .t_bptt_length(4)
            .build()
        )
        assert conf.backprop_type == "TruncatedBPTT"
        net = MultiLayerNetwork(conf).init()
        ds = DataSet(x, y, labels_mask=lmask)
        net.fit(ds)
        s0 = net.score()
        for _ in range(15):
            net.fit(ds)
        assert np.isfinite(net.score())
        assert net.score() < s0

    def test_rnn_time_step_streaming_matches_full(self):
        """rnnTimeStep over chunks == full-sequence output (MultiLayerNetwork
        rnnTimeStep contract)."""
        x, y = self._seq_data(B=4, T=8)
        conf = (
            NeuralNetConfiguration.Builder()
            .seed(3)
            .updater(Adam(1e-2))
            .list()
            .layer(LSTM(n_in=3, n_out=8))
            .layer(RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(3))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        full = net.output(x).numpy()
        net.rnn_clear_previous_state()
        o1 = net.rnn_time_step(x[..., :5]).numpy()
        o2 = net.rnn_time_step(x[..., 5:]).numpy()
        stream = np.concatenate([o1, o2], axis=-1)
        assert np.allclose(stream, full, atol=1e-5)

    def test_dense_between_rnn_layers(self):
        """ff<->rnn preprocessor auto-insertion (regression: review finding
        that FeedForwardToRnnPreProcessor was a no-op)."""
        x, y = self._seq_data(B=4, T=6)
        conf = (
            NeuralNetConfiguration.Builder()
            .seed(3)
            .updater(Adam(1e-2))
            .list()
            .layer(LSTM(n_in=3, n_out=8))
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(LSTM(n_out=8))
            .layer(RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(3))
            .build()
        )
        # fix n_in of second LSTM from shape inference path
        conf.layers[2].n_in = 8
        net = MultiLayerNetwork(conf).init()
        out = net.output(x).numpy()
        assert out.shape == (4, 3, 6)

    def test_embedding_sequence_layer(self):
        B, T, V, E = 4, 5, 11, 6
        rng = np.random.default_rng(0)
        ix = rng.integers(0, V, size=(B, T))
        conf = (
            NeuralNetConfiguration.Builder()
            .updater(Adam(1e-2))
            .list()
            .layer(EmbeddingSequenceLayer(n_in=V, n_out=E))
            .layer(GlobalPoolingLayer(pooling_type="avg"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(V))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        out = net.output(ix.astype(np.float32)).numpy()
        assert out.shape == (B, 2)


class TestMaskedLoss:
    def test_mse_with_timestep_mask(self):
        """Regression for review finding: [B,T] mask over [B,T,C] preds via
        the generic loss registry."""
        from deeplearning4j_tpu.nn import losses

        import jax.numpy as jnp

        B, T, C = 3, 4, 2
        labels = jnp.zeros((B, T, C))
        preds = jnp.ones((B, T, C))
        mask = jnp.asarray(np.array([[1, 1, 0, 0], [1, 0, 0, 0], [1, 1, 1, 1]], np.float32))
        val = losses.get("mse")(labels, preds, mask=mask)
        # per-unit error = C * 1.0 = 2.0; mean over 7 unmasked units
        assert abs(float(val) - 2.0) < 1e-6

    def test_example_mask(self):
        from deeplearning4j_tpu.nn import losses
        import jax.numpy as jnp

        labels = jnp.zeros((4, 2))
        preds = jnp.ones((4, 2)) * jnp.asarray([[1.0], [1.0], [100.0], [100.0]])
        mask = jnp.asarray([1.0, 1.0, 0.0, 0.0])
        assert abs(float(losses.get("mse")(labels, preds, mask=mask)) - 2.0) < 1e-6


class TestEvalGrowth:
    def test_confusion_grows_across_batches(self):
        from deeplearning4j_tpu.eval import Evaluation

        ev = Evaluation()
        ev.eval(np.array([0, 1, 2]), np.array([0, 1, 2]))
        ev.eval(np.array([5]), np.array([5]))  # class unseen in batch 1
        assert ev.num_classes == 6
        assert ev.accuracy() == 1.0
