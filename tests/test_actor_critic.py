"""A3C/A2C actor-critic (SURVEY §2.7 R1 async family)."""

import numpy as np
import pytest

from deeplearning4j_tpu.rl.actor_critic import (
    A2CVectorized,
    A3CConfiguration,
    A3CDiscrete,
)
from deeplearning4j_tpu.rl.mdp import SimpleToyMDP


def test_a2c_vectorized_learns_chain():
    cfg = A3CConfiguration(seed=3, t_max=8, learning_rate=3e-3, gamma=0.95,
                           ent_coef=0.01)
    a2c = A2CVectorized(lambda: SimpleToyMDP(n=5), cfg, n_in=5, n_actions=2,
                        n_envs=8).train(updates=150)
    score = a2c.policy().play(SimpleToyMDP(n=5))
    # optimal = 3 * -0.01 + 10; random policy rarely reaches the goal
    assert score > 9.0, score


def test_a3c_async_workers_learn_chain():
    cfg = A3CConfiguration(seed=1, t_max=8, num_threads=2, learning_rate=3e-3,
                           gamma=0.95)
    a3c = A3CDiscrete(lambda: SimpleToyMDP(n=4), cfg, n_in=4, n_actions=2)
    a3c.train(total_steps=4000)
    score = a3c.policy().play(SimpleToyMDP(n=4))
    assert score > 9.0, score
    assert len(a3c.episode_rewards) > 10  # workers actually completed episodes
