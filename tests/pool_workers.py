"""Replica targets for ServingPool tests (ISSUE 13).

Loaded BY PATH inside replica subprocesses
(``python -m deeplearning4j_tpu.serving.pool /path/pool_workers.py:fn``).
Deliberately jax-free: the pool mechanics under test (spawn, heartbeat,
respawn, routing, readiness, autoscaling) are model-agnostic, and a
numpy-only replica spawns in well under a second — which is what keeps the
replica-kill chaos test in the fast tier.

Knobs ride the pool's ``extra_env``:

- ``TDL_STUB_START_DELAY``  seconds to sleep before serving (warmup window)
- ``TDL_STUB_STEP_DELAY``   fake decode-step seconds (generative stub)
- ``TDL_STUB_MAX_NEW``      default max_new_tokens (generative stub)
- ``TDL_STUB_QUEUE``        admission queue size
"""

import os
import time

import numpy as np


class DoubleModel:
    """output(x) = 2x — deterministic, numpy-only."""

    def output(self, x):
        return np.asarray(x, np.float32) * 2.0


class StubSession:
    """FakeSession twin (see tests/test_serving_generative.py): emits
    ``prompt[-1]+1, +2, ...`` with a configurable per-step delay."""

    def __init__(self, slots=4, max_len=100_000, step_delay=0.0):
        self.slots = slots
        self.max_len = max_len
        self.step_delay = step_delay
        self.eos_id = None
        self._next = {}

    @property
    def free_slots(self):
        return self.slots - len(self._next)

    def admit(self, prompt, max_new_tokens):
        prompt = np.asarray(prompt)
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError("prompt too long for the cache")
        if len(self._next) >= self.slots:
            raise RuntimeError("no free decode slot")
        slot = min(set(range(self.slots)) - set(self._next))
        first = int(prompt[-1]) + 1
        self._next[slot] = first + 1
        return slot, first

    def step(self):
        if self.step_delay:
            time.sleep(self.step_delay)
        out = dict(self._next)
        self._next = {s: t + 1 for s, t in self._next.items()}
        return out

    def release(self, slot):
        del self._next[slot]


def _maybe_start_delay():
    delay = float(os.environ.get("TDL_STUB_START_DELAY", "0"))
    if delay:
        time.sleep(delay)


def stub_server():
    """Plain inference replica: POST [[...]] -> 2x."""
    from deeplearning4j_tpu.serving import JsonModelServer

    _maybe_start_delay()
    return JsonModelServer(
        DoubleModel(), port=0,
        max_queue=int(os.environ.get("TDL_STUB_QUEUE", "64")),
        warmup_input=np.zeros((1, 4), np.float32))


class ScaledModel:
    """output(x) = scale·x — the 'model version' is the scale factor."""

    def __init__(self, scale):
        self.scale = float(scale)

    def output(self, x):
        return np.asarray(x, np.float32) * self.scale


def swappable_server():
    """Versioned inference replica (ISSUE 14 swap tests): the model version
    rides ``TDL_MODEL_CKPT`` — a json file ``{"scale": k}`` (``{"fail":
    true}`` simulates a checkpoint the new build cannot load, the swap
    validation-failure path). No env = the historical 2x model."""
    import json as _json

    from deeplearning4j_tpu.serving import JsonModelServer

    _maybe_start_delay()
    ckpt = os.environ.get("TDL_MODEL_CKPT")
    scale = 2.0
    if ckpt:
        with open(ckpt) as f:
            doc = _json.load(f)
        if doc.get("fail"):
            raise RuntimeError(f"injected model-load failure from {ckpt}")
        scale = float(doc["scale"])
    return JsonModelServer(
        ScaledModel(scale), port=0,
        max_queue=int(os.environ.get("TDL_STUB_QUEUE", "64")),
        warmup_input=np.zeros((1, 4), np.float32))


def generative_stub_server():
    """Continuous-batching generative replica over the stub session."""
    from deeplearning4j_tpu.serving import JsonModelServer

    _maybe_start_delay()
    session = StubSession(
        slots=4, step_delay=float(os.environ.get("TDL_STUB_STEP_DELAY", "0")))
    return JsonModelServer(
        None, port=0, generative_session=session,
        default_max_new_tokens=int(os.environ.get("TDL_STUB_MAX_NEW", "8")),
        max_queue=int(os.environ.get("TDL_STUB_QUEUE", "64")),
        warmup_input=[1])
