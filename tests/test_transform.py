"""Transform-pipeline depth (VERDICT r3 missing #5): reductions, sequence
ops, dual-column math, conditional copy, quality analysis."""

import numpy as np

from deeplearning4j_tpu.data.transform import Schema, TransformProcess



# ------------------------------------------------------- D2 depth (wave 3)


def _sales_schema():
    return (Schema.Builder()
            .add_column_string("store")
            .add_column_double("amount")
            .add_column_double("qty")
            .add_column_integer("t")
            .build())


_SALES = [
    ["a", 10.0, 1.0, 0], ["a", 20.0, 2.0, 1], ["a", 30.0, 3.0, 2],
    ["b", 5.0, 1.0, 0], ["b", 7.0, 1.0, 1],
]


class TestReductions:
    def test_reduce_group_by(self):
        from deeplearning4j_tpu.data import Reducer

        red = (Reducer.Builder("store")
               .sum_columns("amount")
               .mean_columns("qty")
               .count_columns("t")
               .build())
        tp = (TransformProcess.Builder(_sales_schema())
              .reduce(red)
              .build())
        out = tp.execute([list(r) for r in _SALES])
        assert out == [["a", 60.0, 2.0, 3], ["b", 12.0, 1.0, 2]]
        names = tp.final_schema().names()
        assert names == ["store", "sum(amount)", "mean(qty)", "count(t)"]

    def test_reduce_stdev_range_first_last(self):
        from deeplearning4j_tpu.data import Reducer

        red = (Reducer.Builder("store")
               .stdev_columns("amount")
               .range_columns("qty")
               .take_first_columns("t")
               .build())
        out = (TransformProcess.Builder(_sales_schema())
               .reduce(red).build()).execute([list(r) for r in _SALES])
        np.testing.assert_allclose(out[0][1], np.std([10, 20, 30], ddof=1))
        assert out[0][2] == 2.0 and out[0][3] == 0

    def test_reduce_json_roundtrip(self):
        from deeplearning4j_tpu.data import Reducer

        tp = (TransformProcess.Builder(_sales_schema())
              .reduce(Reducer.Builder("store").sum_columns("amount").build())
              .build())
        tp2 = TransformProcess.from_json(tp.to_json())
        assert tp2.execute([list(r) for r in _SALES])[0][:2] == ["a", 60.0]


class TestDualColumnAndConditional:
    def test_columns_math_op(self):
        tp = (TransformProcess.Builder(_sales_schema())
              .columns_math_op("total", "multiply", "amount", "qty")
              .build())
        out = tp.execute([list(r) for r in _SALES])
        assert out[0][-1] == 10.0 and out[2][-1] == 90.0
        assert tp.final_schema().names()[-1] == "total"

    def test_conditional_copy(self):
        tp = (TransformProcess.Builder(_sales_schema())
              .conditional_copy("amount", "qty", "store", "eq", "b")
              .build())
        out = tp.execute([list(r) for r in _SALES])
        assert out[3][1] == 1.0 and out[0][1] == 10.0


class TestSequenceOps:
    def test_convert_split_offset_window(self):
        from deeplearning4j_tpu.data import (
            Reducer,
            SplitMaxLengthSequence,
            convert_to_sequence,
            offset_sequence,
            reduce_sequence_by_window,
            split_sequences,
        )

        schema = _sales_schema()
        seqs = convert_to_sequence(schema, [list(r) for r in _SALES],
                                   "store", sort_column="t")
        assert [len(s) for s in seqs] == [3, 2]
        assert seqs[0][0][3] == 0 and seqs[0][2][3] == 2

        chunks = split_sequences(seqs, SplitMaxLengthSequence(2))
        assert [len(s) for s in chunks] == [2, 1, 2]

        # lag feature: amount shifted by +1 step, first step trimmed
        lagged = offset_sequence(schema, seqs, ["amount"], 1)
        assert len(lagged[0]) == 2
        assert lagged[0][0][1] == 10.0 and lagged[0][0][3] == 1  # t=1 row, t=0 amount

        red = Reducer.Builder("store").mean_columns("amount").build()
        win = reduce_sequence_by_window(schema, seqs, 2, red)
        assert win[0] == [["a", 15.0], ["a", 30.0]]


class TestQualityAnalysis:
    def test_quality_counts(self):
        from deeplearning4j_tpu.data import DataQualityAnalysis

        schema = (Schema.Builder()
                  .add_column_double("x")
                  .add_column_categorical("c", "u", "v")
                  .build())
        rows = [[1.0, "u"], ["oops", "v"], [None, "w"], [float("inf"), "u"],
                [2.5, ""]]
        q = DataQualityAnalysis.analyze(schema, rows)
        x = q.column_quality["x"]
        assert (x.valid, x.invalid, x.missing, x.total) == (2, 2, 1, 5)
        c = q.column_quality["c"]
        assert (c.valid, c.invalid, c.missing, c.total) == (3, 1, 1, 5)
        assert "\"valid\": 2" in q.to_json()


class TestWave3ReviewFixes:
    def test_reduce_schema_matches_rows_when_key_not_first(self):
        from deeplearning4j_tpu.data import Reducer

        schema = (Schema.Builder().add_column_double("amount")
                  .add_column_string("user").build())
        tp = (TransformProcess.Builder(schema)
              .reduce(Reducer.Builder("user").sum_columns("amount").build())
              .build())
        out = tp.execute([[1.0, "u1"], [2.0, "u1"], [5.0, "u2"]])
        fs = tp.final_schema()
        assert fs.names() == ["user", "sum(amount)"]
        # schema index_of must agree with the data positions
        assert out[0][fs.index_of("user")] == "u1"
        assert out[0][fs.index_of("sum(amount)")] == 3.0

    def test_columns_math_divide_by_zero_is_inf(self):
        schema = (Schema.Builder().add_column_double("a")
                  .add_column_double("b").build())
        tp = (TransformProcess.Builder(schema)
              .columns_math_op("r", "divide", "a", "b").build())
        out = tp.execute([[1.0, 0.0], [4.0, 2.0]])
        assert out[0][-1] == float("inf") and out[1][-1] == 2.0

    def test_offset_sequence_new_column_and_bad_mode(self):
        import pytest as _pytest

        from deeplearning4j_tpu.data import convert_to_sequence, offset_sequence

        schema = _sales_schema()
        seqs = convert_to_sequence(schema, [list(r) for r in _SALES], "store", "t")
        nc = offset_sequence(schema, seqs, ["amount"], 1, mode="new_column")
        assert len(nc[0][0]) == 5                       # original row + lag col
        assert nc[0][0][1] == 20.0 and nc[0][0][4] == 10.0
        with _pytest.raises(ValueError, match="mode"):
            offset_sequence(schema, seqs, ["amount"], 1, mode="bogus")
