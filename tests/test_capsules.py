"""CapsNet layers (C4/C16): squash, dynamic routing, end-to-end learning."""

import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.nn.capsules import (
    CapsNetOutputLayer,
    CapsuleLayer,
    CapsuleStrengthLayer,
    PrimaryCapsules,
    margin_loss,
    squash,
)


def test_squash_norm_below_one():
    rs = np.random.RandomState(0)
    v = np.asarray(squash(jnp.asarray(rs.randn(4, 8).astype(np.float32) * 5)))
    norms = np.linalg.norm(v, axis=-1)
    assert np.all(norms < 1.0)
    # big inputs keep direction
    big = np.array([[10.0, 0.0]], np.float32)
    out = np.asarray(squash(jnp.asarray(big)))
    assert out[0, 0] > 0.98 and abs(out[0, 1]) < 1e-6


def test_margin_loss_prefers_correct_lengths():
    y = np.eye(3, dtype=np.float32)[[0]]
    good = np.array([[0.95, 0.05, 0.05]], np.float32)
    bad = np.array([[0.05, 0.95, 0.95]], np.float32)
    assert float(margin_loss(y, jnp.asarray(good))) < float(margin_loss(y, jnp.asarray(bad)))


def test_capsnet_learns_synthetic_shapes():
    """PrimaryCapsules → routing → strengths classifies two synthetic
    patterns on 12x12 images."""
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import ConvolutionLayer, InputType
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.Builder().seed(2).updater(Adam(3e-3)).list()
            .layer(ConvolutionLayer(n_out=16, kernel_size=(5, 5), activation="relu"))
            .layer(PrimaryCapsules(capsules=4, capsule_dim=4, kernel_size=3, stride=2))
            .layer(CapsuleLayer(capsules=2, capsule_dim=8, routings=3))
            .layer(CapsuleStrengthLayer())
            .layer(CapsNetOutputLayer())
            .set_input_type(InputType.convolutional(12, 12, 1))
            .build())
    net = MultiLayerNetwork(conf).init()

    rs = np.random.RandomState(1)
    n = 64
    y = rs.randint(0, 2, n)
    x = rs.randn(n, 1, 12, 12).astype(np.float32) * 0.1
    for i, c in enumerate(y):
        if c == 0:
            x[i, 0, 3, :] += 2.0        # horizontal bar
        else:
            x[i, 0, :, 3] += 2.0        # vertical bar
    labels = np.eye(2, dtype=np.float32)[y]

    out = net.output(x[:4]).numpy()
    assert out.shape == (4, 2)
    s0 = None
    for _ in range(40):
        net._fit_batch(DataSet(x, labels))
        if s0 is None:
            s0 = net.score_
    assert net.score_ < s0 * 0.5
    preds = net.output(x).numpy().argmax(-1)
    assert (preds == y).mean() > 0.9
