"""SameDiff-parity graph tests (SURVEY §2.2 J11-J15, §4.2)."""

import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.autodiff.ops_registry import OPS
from deeplearning4j_tpu.autodiff.validation import OpValidation, check_gradients, validate_op
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.updaters import Adam


def _mlp_graph():
    """BASELINE-style tiny MLP as a SameDiff graph."""
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 4))
    y = sd.placeholder("y", shape=(None, 3))
    w0 = sd.var("w0", (4, 16))
    b0 = sd.var("b0", (16,), weight_init="zeros")
    w1 = sd.var("w1", (16, 3))
    b1 = sd.var("b1", (3,), weight_init="zeros")
    a = sd.op("tanh", sd.nn().linear(x, w0, b0))
    logits = sd.nn().linear(a, w1, b1).rename("logits")
    loss = sd.loss().softmax_cross_entropy(y, logits).rename("loss")
    sd.set_loss_variables("loss")
    return sd


def _toy_data(n=128, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 4).astype(np.float32)
    yi = np.argmax(X[:, :3] + 0.1 * rs.randn(n, 3), axis=1)
    return X, np.eye(3, dtype=np.float32)[yi]


def test_output_whole_graph():
    sd = _mlp_graph()
    X, Y = _toy_data(8)
    out = sd.output({"x": X, "y": Y}, ["logits", "loss"])
    assert out["logits"].shape == (8, 3)
    assert np.isfinite(float(out["loss"]))


def test_eval_and_operator_sugar():
    sd = SameDiff.create()
    a = sd.constant("a", np.array([1.0, 2.0, 3.0], np.float32))
    b = sd.constant("b", np.array([4.0, 5.0, 6.0], np.float32))
    c = (a * 2.0 + b).rename("c")
    np.testing.assert_allclose(np.asarray(c.eval()), [6.0, 9.0, 12.0])
    s = a.sum().rename("s")
    assert float(s.eval()) == 6.0


def test_fit_decreases_loss():
    sd = _mlp_graph()
    X, Y = _toy_data(128)
    cfg = TrainingConfig(updater=Adam(0.01),
                         data_set_feature_mapping=["x"],
                         data_set_label_mapping=["y"])
    sd.set_training_config(cfg)
    it = ListDataSetIterator([DataSet(X[i:i + 32], Y[i:i + 32]) for i in range(0, 128, 32)])
    hist = sd.fit(it, epochs=15)
    assert hist.loss_curve[-1] < hist.loss_curve[0] * 0.7


def test_calculate_gradients_and_gradcheck():
    sd = _mlp_graph()
    X, Y = _toy_data(4)
    grads = sd.calculate_gradients({"x": X, "y": Y}, ["w1", "b1"])
    assert grads["w1"].shape == (16, 3)
    # central-difference check on the small head params
    check_gradients(sd, {"x": X, "y": Y}, ["b1"], eps=1e-3, max_rel_error=5e-2,
                    abs_error=1e-4)


def test_save_load_roundtrip(tmp_path):
    sd = _mlp_graph()
    X, Y = _toy_data(8)
    ref = sd.output({"x": X, "y": Y}, "logits")["logits"]
    p = str(tmp_path / "model.sdz")
    sd.save(p)
    sd2 = SameDiff.load(p)
    out = sd2.output({"x": X, "y": Y}, "logits")["logits"]
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=1e-6)
    assert sd2.loss_names == ["loss"]


def test_save_load_resume_training(tmp_path):
    sd = _mlp_graph()
    X, Y = _toy_data(64)
    cfg = TrainingConfig(updater=Adam(0.01), data_set_feature_mapping=["x"],
                         data_set_label_mapping=["y"])
    sd.set_training_config(cfg)
    it = ListDataSetIterator([DataSet(X, Y)])
    sd.fit(it, epochs=3)
    p = str(tmp_path / "ckpt.sdz")
    sd.save(p, save_updater_state=True)
    sd2 = SameDiff.load(p)
    assert sd2.updater_state  # updater state survived
    h = sd2.fit(it, epochs=2)
    assert np.isfinite(h.final_loss())


def test_lstm_layer_op():
    rs = np.random.RandomState(0)
    T, B, I, H = 5, 2, 3, 4
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(T, B, I))
    h0 = sd.constant("h0", np.zeros((B, H), np.float32))
    c0 = sd.constant("c0", np.zeros((B, H), np.float32))
    wx = sd.var("wx", np.asarray(rs.randn(I, 4 * H), np.float32))
    wh = sd.var("wh", np.asarray(rs.randn(H, 4 * H), np.float32))
    b = sd.var("b", np.zeros((4 * H,), np.float32))
    ys, hT, cT = sd.rnn().lstm_layer(x, h0, c0, wx, wh, b)
    ys.rename("ys")
    out = sd.output({"x": rs.randn(T, B, I).astype(np.float32)}, ["ys"])
    assert out["ys"].shape == (T, B, H)


def test_op_registry_size_and_validation_gate():
    # broad corpus exists (reference has ~500 declarable ops; the eager+graph
    # corpus here targets the subset the baseline workloads exercise)
    assert len(OPS) > 140
    validate_op("add", [np.ones(3), np.ones(3)], expected=2 * np.ones(3))
    validate_op("matmul", [np.eye(2), np.eye(2)], expected=np.eye(2))
    validate_op("softmax", [np.zeros((1, 4))], expected=0.25 * np.ones((1, 4)))
    OpValidation.assert_coverage(["add", "matmul", "softmax"])
    with pytest.raises(AssertionError):
        OpValidation.assert_coverage(["some_untested_op_name"])
