"""SameDiff-parity graph tests (SURVEY §2.2 J11-J15, §4.2)."""

import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.autodiff.ops_registry import OPS
from deeplearning4j_tpu.autodiff.validation import OpValidation, check_gradients, validate_op
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.updaters import Adam


def _mlp_graph():
    """BASELINE-style tiny MLP as a SameDiff graph."""
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 4))
    y = sd.placeholder("y", shape=(None, 3))
    w0 = sd.var("w0", (4, 16))
    b0 = sd.var("b0", (16,), weight_init="zeros")
    w1 = sd.var("w1", (16, 3))
    b1 = sd.var("b1", (3,), weight_init="zeros")
    a = sd.op("tanh", sd.nn().linear(x, w0, b0))
    logits = sd.nn().linear(a, w1, b1).rename("logits")
    loss = sd.loss().softmax_cross_entropy(y, logits).rename("loss")
    sd.set_loss_variables("loss")
    return sd


def _toy_data(n=128, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 4).astype(np.float32)
    yi = np.argmax(X[:, :3] + 0.1 * rs.randn(n, 3), axis=1)
    return X, np.eye(3, dtype=np.float32)[yi]


def test_output_whole_graph():
    sd = _mlp_graph()
    X, Y = _toy_data(8)
    out = sd.output({"x": X, "y": Y}, ["logits", "loss"])
    assert out["logits"].shape == (8, 3)
    assert np.isfinite(float(out["loss"]))


def test_eval_and_operator_sugar():
    sd = SameDiff.create()
    a = sd.constant("a", np.array([1.0, 2.0, 3.0], np.float32))
    b = sd.constant("b", np.array([4.0, 5.0, 6.0], np.float32))
    c = (a * 2.0 + b).rename("c")
    np.testing.assert_allclose(np.asarray(c.eval()), [6.0, 9.0, 12.0])
    s = a.sum().rename("s")
    assert float(s.eval()) == 6.0


def test_fit_decreases_loss():
    sd = _mlp_graph()
    X, Y = _toy_data(128)
    cfg = TrainingConfig(updater=Adam(0.01),
                         data_set_feature_mapping=["x"],
                         data_set_label_mapping=["y"])
    sd.set_training_config(cfg)
    it = ListDataSetIterator([DataSet(X[i:i + 32], Y[i:i + 32]) for i in range(0, 128, 32)])
    hist = sd.fit(it, epochs=15)
    assert hist.loss_curve[-1] < hist.loss_curve[0] * 0.7


def test_calculate_gradients_and_gradcheck():
    sd = _mlp_graph()
    X, Y = _toy_data(4)
    grads = sd.calculate_gradients({"x": X, "y": Y}, ["w1", "b1"])
    assert grads["w1"].shape == (16, 3)
    # central-difference check on the small head params
    check_gradients(sd, {"x": X, "y": Y}, ["b1"], eps=1e-3, max_rel_error=5e-2,
                    abs_error=1e-4)


def test_save_load_roundtrip(tmp_path):
    sd = _mlp_graph()
    X, Y = _toy_data(8)
    ref = sd.output({"x": X, "y": Y}, "logits")["logits"]
    p = str(tmp_path / "model.sdz")
    sd.save(p)
    sd2 = SameDiff.load(p)
    out = sd2.output({"x": X, "y": Y}, "logits")["logits"]
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=1e-6)
    assert sd2.loss_names == ["loss"]


def test_save_load_resume_training(tmp_path):
    sd = _mlp_graph()
    X, Y = _toy_data(64)
    cfg = TrainingConfig(updater=Adam(0.01), data_set_feature_mapping=["x"],
                         data_set_label_mapping=["y"])
    sd.set_training_config(cfg)
    it = ListDataSetIterator([DataSet(X, Y)])
    sd.fit(it, epochs=3)
    p = str(tmp_path / "ckpt.sdz")
    sd.save(p, save_updater_state=True)
    sd2 = SameDiff.load(p)
    assert sd2.updater_state  # updater state survived
    h = sd2.fit(it, epochs=2)
    assert np.isfinite(h.final_loss())


def test_lstm_layer_op():
    rs = np.random.RandomState(0)
    T, B, I, H = 5, 2, 3, 4
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(T, B, I))
    h0 = sd.constant("h0", np.zeros((B, H), np.float32))
    c0 = sd.constant("c0", np.zeros((B, H), np.float32))
    wx = sd.var("wx", np.asarray(rs.randn(I, 4 * H), np.float32))
    wh = sd.var("wh", np.asarray(rs.randn(H, 4 * H), np.float32))
    b = sd.var("b", np.zeros((4 * H,), np.float32))
    ys, hT, cT = sd.rnn().lstm_layer(x, h0, c0, wx, wh, b)
    ys.rename("ys")
    out = sd.output({"x": rs.randn(T, B, I).astype(np.float32)}, ["ys"])
    assert out["ys"].shape == (T, B, H)


def test_op_registry_size_and_validation_gate():
    # broad corpus exists (reference has ~500 declarable ops; the eager+graph
    # corpus here targets the subset the baseline workloads exercise)
    assert len(OPS) > 140
    validate_op("add", [np.ones(3), np.ones(3)], expected=2 * np.ones(3))
    validate_op("matmul", [np.eye(2), np.eye(2)], expected=np.eye(2))
    validate_op("softmax", [np.zeros((1, 4))], expected=0.25 * np.ones((1, 4)))
    OpValidation.assert_coverage(["add", "matmul", "softmax"])
    with pytest.raises(AssertionError):
        OpValidation.assert_coverage(["some_untested_op_name"])


class TestControlFlow:
    """SameDiff if/while (SURVEY §2.2 J11 control flow → lax.cond/while_loop)."""

    def test_if_cond_branches(self):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff

        sd = SameDiff.create()
        x = sd.placeholder("x", (3,))
        pred = sd.placeholder("p", ())
        out = sd.if_cond(
            pred,
            lambda sub, a: sub.op("mul", a, sub.constant("two", 2.0)),
            lambda sub, a: sub.op("neg", a),
            inputs=[x], name="branched")
        xs = np.array([1.0, -2.0, 3.0], np.float32)
        hi = sd.output({"x": xs, "p": np.asarray(1.0)}, "branched")["branched"]
        lo = sd.output({"x": xs, "p": np.asarray(0.0)}, "branched")["branched"]
        np.testing.assert_allclose(np.asarray(hi), xs * 2)
        np.testing.assert_allclose(np.asarray(lo), -xs)

    def test_while_loop_accumulates(self):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff

        sd = SameDiff.create()
        i0 = sd.constant("i0", np.asarray(0.0, np.float32))
        acc0 = sd.placeholder("acc0", ())
        outs = sd.while_loop(
            [i0, acc0],
            lambda sub, i, acc: sub.op("lt", i, sub.constant("n", 5.0)),
            lambda sub, i, acc: (sub.op("add", i, sub.constant("one", 1.0)),
                                 sub.op("add", acc, i)),
            name="loop")
        res = sd.output({"acc0": np.asarray(0.0, np.float32)},
                        [o.name for o in outs])
        # sum of 0..4 = 10, i ends at 5
        np.testing.assert_allclose(float(np.asarray(res[outs[0].name])), 5.0)
        np.testing.assert_allclose(float(np.asarray(res[outs[1].name])), 10.0)

    def test_while_arity_mismatch_raises(self):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff

        sd = SameDiff.create()
        a = sd.constant("a", np.asarray(0.0, np.float32))
        with pytest.raises(ValueError, match="loop vars"):
            sd.while_loop([a],
                          lambda sub, i: sub.op("lt", i, sub.constant("n", 3.0)),
                          lambda sub, i: (i, i))

    def test_control_flow_serialization_roundtrip(self, tmp_path):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff

        sd = SameDiff.create()
        x = sd.placeholder("x", (3,))
        p = sd.placeholder("p", ())
        sd.if_cond(p,
                   lambda sub, a: sub.op("mul", a, sub.constant("three", 3.0)),
                   lambda sub, a: sub.op("abs", a),
                   inputs=[x], name="cf")
        path = str(tmp_path / "cf.zip")
        sd.save(path)
        sd2 = SameDiff.load(path)
        xs = np.array([-1.0, 2.0, -3.0], np.float32)
        got = sd2.output({"x": xs, "p": np.asarray(1.0)}, "cf")["cf"]
        np.testing.assert_allclose(np.asarray(got), xs * 3)
        got0 = sd2.output({"x": xs, "p": np.asarray(0.0)}, "cf")["cf"]
        np.testing.assert_allclose(np.asarray(got0), np.abs(xs))

    def test_grad_through_cond(self):
        """Training graphs can contain conditionals (grad flows through the
        taken branch)."""
        from deeplearning4j_tpu.autodiff.samediff import SameDiff

        sd = SameDiff.create()
        w = sd.var("w", np.asarray([2.0, 3.0], np.float32))
        p = sd.placeholder("p", ())
        y = sd.if_cond(p,
                       lambda sub, a: sub.op("mul", a, a),
                       lambda sub, a: a,
                       inputs=[w], name="y")
        loss = sd.op("reduce_sum", y, name="loss")
        sd.set_loss_variables("loss")
        grads = sd.calculate_gradients({"p": np.asarray(1.0)}, ["w"])
        np.testing.assert_allclose(np.asarray(grads["w"]), [4.0, 6.0])


class TestShapeFnContract:
    """N5 shape-function contract: output shapes known at GRAPH BUILD time
    (the reference's DECLARE_SHAPE_FN, here via jax.eval_shape for every op)."""

    def test_shapes_inferred_through_graph(self):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff

        sd = SameDiff.create()
        x = sd.placeholder("x", (4, 3))
        w = sd.var("w", np.zeros((3, 8), np.float32))
        h = sd.op("matmul", x, w)
        assert h.shape == (4, 8)
        r = sd.op("reduce_sum", h, dims=1)
        assert r.shape == (4,)
        s = sd.op("softmax", h)
        assert s.shape == (4, 8) and str(s.dtype) == "float32"

    def test_unknown_placeholder_shape_degrades_gracefully(self):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff

        sd = SameDiff.create()
        x = sd.placeholder("x")  # no shape
        y = sd.op("tanh", x)
        assert y.shape is None  # unknown, not wrong


class TestPlatformOverrides:
    """N10 platform-helper hook: fast-path impls consulted before generic
    (the cuDNN/oneDNN PlatformHelper pattern, generalized to any op)."""

    def test_override_dispatch_and_clear(self):
        from deeplearning4j_tpu.autodiff.ops_registry import (
            clear_platform_overrides,
            get_op,
            register_platform_override,
        )
        from deeplearning4j_tpu.autodiff.samediff import SameDiff

        calls = []

        def fast_tanh(x):
            calls.append(1)
            return np.tanh(np.asarray(x)) * 1.0

        try:
            register_platform_override("tanh", lambda: True, fast_tanh)
            out = get_op("tanh")(np.float32(0.5))
            assert calls, "override not consulted"
            np.testing.assert_allclose(np.asarray(out), np.tanh(0.5), rtol=1e-6)

            # predicate False → generic path
            clear_platform_overrides("tanh")
            register_platform_override("tanh", lambda: False, fast_tanh)
            calls.clear()
            get_op("tanh")(np.float32(0.5))
            assert not calls
        finally:
            clear_platform_overrides("tanh")

    def test_override_flows_through_samediff(self):
        from deeplearning4j_tpu.autodiff.ops_registry import (
            clear_platform_overrides,
            register_platform_override,
        )
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        import jax.numpy as jnp

        try:
            register_platform_override("relu", lambda: True,
                                       lambda x: jnp.maximum(x, 0.0) + 1.0)
            sd = SameDiff.create()
            x = sd.placeholder("x", (3,))
            y = sd.op("relu", x, name="y")
            got = sd.output({"x": np.array([-1.0, 0.5, 2.0], np.float32)}, "y")["y"]
            np.testing.assert_allclose(np.asarray(got), [1.0, 1.5, 3.0])
        finally:
            clear_platform_overrides("relu")

    def test_override_registered_after_trace_invalidates_cache(self):
        from deeplearning4j_tpu.autodiff.ops_registry import (
            clear_platform_overrides,
            register_platform_override,
        )
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        import jax.numpy as jnp

        sd = SameDiff.create()
        x = sd.placeholder("x", (2,))
        sd.op("relu", x, name="y")
        feed = {"x": np.array([-1.0, 2.0], np.float32)}
        base = sd.output(feed, "y")["y"]  # trace + cache with generic impl
        np.testing.assert_allclose(np.asarray(base), [0.0, 2.0])
        try:
            register_platform_override("relu", lambda: True,
                                       lambda v: jnp.maximum(v, 0.0) + 5.0)
            got = sd.output(feed, "y")["y"]
            np.testing.assert_allclose(np.asarray(got), [5.0, 7.0])
        finally:
            clear_platform_overrides("relu")
