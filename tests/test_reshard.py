"""Cross-topology checkpoint resharding (ISSUE 14 tentpole piece 1).

The restore-parity matrix, single-process over the 8-device CPU mesh: a
checkpoint written under one SpecLayout restores under a DIFFERENT layout —
fsdp↔tp changes, fewer devices, more devices, and down to replicated — with
exact (bitwise) param + optimizer-state parity, via the source→target chunk
intersection of arXiv:2112.01075 (each rank fills only its addressable
shards from the overlapping saved chunk slices; no process materializes a
full array — the AST lint at the bottom keeps that claim from rotting).

Genuinely incompatible checkpoints stay loud: param-shape drift, missing
chunks, and non-tiling coverage all raise naming the problem. The
multi-process tier (real 4-rank → 2-rank gangs) rides
tests/test_multiprocess.py::test_cross_topology_gang_restore_parity.
"""

import ast
import os
import pathlib
import re

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.monitoring import get_registry
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.parallel import (ParallelTrainer, Partitioner,
                                         SpecLayout, largest_layout)
from deeplearning4j_tpu.parallel.mesh import mesh_from_shape
from deeplearning4j_tpu.serde.checkpoint import (TrainingCheckpointer,
                                                 _fill_from_chunks)

ROOT = pathlib.Path(__file__).resolve().parent.parent / "deeplearning4j_tpu"


def _mlp(seed=7, hidden=16):
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_in=8, n_out=hidden, activation="tanh"))
            .layer(DenseLayer(n_in=hidden, n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _batches(steps=4, n=16):
    out = []
    for s in range(steps):
        rs = np.random.RandomState(100 + s)
        x = rs.rand(n, 8).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, n)]
        out.append(DataSet(x, y))
    return out


def _sub_partitioner(layout, n_devices):
    """Partitioner over the FIRST n devices — how the matrix emulates a
    smaller/larger target topology inside one process."""
    return Partitioner(layout, mesh=mesh_from_shape(
        layout.shape(), devices=jax.devices()[:n_devices]))


def _trained_ckpt(tmp_path, layout=None, partitioner=None):
    a = _mlp()
    ta = ParallelTrainer(a, mesh_layout=partitioner
                         if partitioner is not None else layout)
    for ds in _batches():
        ta._fit_batch(ds)
    ck = ta.checkpointer(str(tmp_path), async_write=False)
    ck.save(a)
    return a


def _assert_state_parity(a, b):
    """Bitwise equality of params AND optimizer state — the structural-
    mirror rule resharded the Adam m/v exactly like their params."""
    for wa, wb in zip(jax.tree.leaves(a.params_), jax.tree.leaves(b.params_)):
        np.testing.assert_array_equal(np.asarray(wa), np.asarray(wb))
    for ua, ub in zip(jax.tree.leaves(a.updater_state),
                      jax.tree.leaves(b.updater_state)):
        np.testing.assert_array_equal(np.asarray(ua), np.asarray(ub))
    assert b.iteration == a.iteration


# ------------------------------------------------------- the parity matrix


@pytest.mark.parametrize("target_layout,target_devices", [
    (SpecLayout(data=1, fsdp=4, tp=2), 8),   # fsdp↔tp change, same devices
    (SpecLayout(data=1, fsdp=2, tp=2), 4),   # "restore on fewer ranks"
    (SpecLayout(data=1, fsdp=2, tp=1), 2),   # even fewer
    (SpecLayout(data=1, fsdp=8, tp=1), 8),   # "restore on more ranks"
])
def test_reshard_restore_matrix(tmp_path, target_layout, target_devices):
    """A (2,2,2)-trained checkpoint restores under every target layout with
    exact param+opt parity, lands SHARDED per the target layout, and
    training continues bit-compatibly from the redistributed shards."""
    a = _trained_ckpt(tmp_path, layout=SpecLayout(data=2, fsdp=2, tp=2))

    b = _mlp(seed=99)  # different init — every leaf must be overwritten
    part = _sub_partitioner(target_layout, target_devices)
    tb = ParallelTrainer(b, mesh_layout=part)
    assert tb.checkpointer(str(tmp_path), async_write=False).restore(
        b, reshard=True)
    tb._place_net()
    _assert_state_parity(a, b)
    spec = b.params_["0"]["W"].sharding.spec
    want = part.spec_tree(b.params_)["0"]["W"]
    assert spec == want, (spec, want)
    # the redistributed state is a live training state, not just bytes
    tb._fit_batch(_batches(steps=5)[-1])
    assert np.isfinite(float(b.score_))


def test_reshard_restore_to_replicated(tmp_path):
    """Sharded → replicated with reshard=True: the one direction where a
    full array per process is the CONTRACT (a replicated net holds them by
    definition), assembled host-side."""
    a = _trained_ckpt(tmp_path, layout=SpecLayout(data=1, fsdp=4, tp=2))
    r = _mlp(seed=3)
    assert TrainingCheckpointer(str(tmp_path), async_write=False,
                                reshard=True).restore(r)
    _assert_state_parity(a, r)


def test_reshard_records_cost_metrics_and_flight(tmp_path):
    before = {}
    snap = get_registry().snapshot()
    if "tdl_reshard_seconds" in snap:
        before["n"] = snap["tdl_reshard_seconds"]["series"][0]["count"]
        before["b"] = snap["tdl_reshard_bytes_total"]["series"][0]["value"]
    a = _trained_ckpt(tmp_path, layout=SpecLayout(data=2, fsdp=2, tp=2))
    b = _mlp(seed=99)
    tb = ParallelTrainer(b, mesh_layout=SpecLayout(data=1, fsdp=4, tp=2))
    assert tb.checkpointer(str(tmp_path), async_write=False).restore(
        b, reshard=True)
    snap = get_registry().snapshot()
    assert snap["tdl_reshard_seconds"]["series"][0]["count"] == \
        before.get("n", 0) + 1
    moved = snap["tdl_reshard_bytes_total"]["series"][0]["value"] - \
        before.get("b", 0)
    # every param/opt/bn byte of the net moved through the intersection copy
    assert moved >= sum(np.asarray(w).nbytes
                        for w in jax.tree.leaves(a.params_))


def test_mismatch_still_fails_loudly_without_optin(tmp_path):
    """The PR 8 contract survives as the DEFAULT: reshard is opt-in, and the
    refusal now tells the caller about it."""
    _trained_ckpt(tmp_path, layout=SpecLayout(data=2, fsdp=2, tp=2))
    c = _mlp()
    tc = ParallelTrainer(c, mesh_layout=SpecLayout(data=1, fsdp=4, tp=2))
    ck = tc.checkpointer(str(tmp_path), async_write=False)
    with pytest.raises(ValueError) as ei:
        ck.restore(c)
    msg = str(ei.value)
    assert "data=2 x fsdp=2 x tp=2" in msg and "data=1 x fsdp=4 x tp=2" in msg
    assert "reshard=True" in msg
    # explicit False overrides a reshard-by-default checkpointer too
    ck2 = tc.checkpointer(str(tmp_path), async_write=False, reshard=True)
    with pytest.raises(ValueError, match="mesh layout mismatch"):
        ck2.restore(c, reshard=False)


# ------------------------------------------- incompatible-checkpoint fallbacks


def test_reshard_rejects_param_shape_drift(tmp_path):
    _trained_ckpt(tmp_path, layout=SpecLayout(data=2, fsdp=2, tp=2))
    wider = _mlp(hidden=24)
    tw = ParallelTrainer(wider, mesh_layout=SpecLayout(data=1, fsdp=4, tp=2))
    with pytest.raises(ValueError, match="shape"):
        tw.checkpointer(str(tmp_path), async_write=False).restore(
            wider, reshard=True)


def test_reshard_rejects_missing_chunks(tmp_path):
    """A net declaring state the checkpoint never saved (model drift) must
    refuse — resharding redistributes chunks, it cannot invent them."""
    _trained_ckpt(tmp_path, layout=SpecLayout(data=2, fsdp=2, tp=2))
    deeper_conf = (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-2))
                   .list()
                   .layer(DenseLayer(n_in=8, n_out=16, activation="tanh"))
                   .layer(DenseLayer(n_in=16, n_out=16, activation="relu"))
                   .layer(DenseLayer(n_in=16, n_out=16, activation="relu"))
                   .layer(OutputLayer(n_out=4, activation="softmax",
                                      loss="mcxent"))
                   .set_input_type(InputType.feed_forward(8))
                   .build())
    deeper = MultiLayerNetwork(deeper_conf).init()
    td = ParallelTrainer(deeper, mesh_layout=SpecLayout(data=1, fsdp=4, tp=2))
    with pytest.raises(ValueError, match="missing chunks"):
        td.checkpointer(str(tmp_path), async_write=False).restore(
            deeper, reshard=True)


def test_fill_from_chunks_verifies_tiling_coverage():
    """The intersection copy counts cells: chunks that do not tile the leaf
    (torn/foreign write) raise instead of silently restoring zeros."""
    class _Npz(dict):
        pass

    npz = _Npz(a=np.arange(8, dtype=np.float32))
    full = [(np.asarray([[0, 8]]), (8,), npz, "a")]
    out = _fill_from_chunks((slice(2, 6),), full, (8,), "p")
    np.testing.assert_array_equal(out, [2, 3, 4, 5])
    hole = [(np.asarray([[0, 4]]), (8,), npz, "a")]
    with pytest.raises(ValueError, match="4/8|cover"):
        _fill_from_chunks((slice(0, 8),), hole, (8,), "p")


def test_save_cleans_stale_shards_from_a_bigger_gang(tmp_path):
    """ISSUE 14 aftermath hygiene, lineage form (ISSUE 15): a smaller
    (post-resize) gang re-saving an iteration whose generation dir holds a
    bigger gang's TORN leftovers must not commit the dead ranks' stale
    shard/manifest files into the generation — the next verify would fail
    the save-id/manifest checks and a healthy checkpoint would read as
    corrupt (the post-resize gang could never recover)."""
    import shutil

    from deeplearning4j_tpu.serde.checkpoint import _gen_name

    a = _mlp()
    ta = ParallelTrainer(a, mesh_layout=SpecLayout(data=2, fsdp=2, tp=2))
    ta._fit_batch(_batches(1)[0])
    ck = ta.checkpointer(str(tmp_path), async_write=False)
    gen1 = ck.save(a)
    # plant the bigger gang's torn leftover AT the iteration the next save
    # will use: a rank-1 shard + manifest in the not-yet-written gen dir
    ta._fit_batch(_batches(2)[-1])
    next_gen = tmp_path / "latest" / _gen_name(int(a.iteration))
    next_gen.mkdir()
    shutil.copy(os.path.join(gen1, "shard_0.npz"), next_gen / "shard_1.npz")
    shutil.copy(os.path.join(gen1, "manifest_0.json"),
                next_gen / "manifest_1.json")
    gen2 = ck.save(a)
    assert gen2 == str(next_gen)
    assert not (next_gen / "shard_1.npz").exists()
    assert not (next_gen / "manifest_1.json").exists()
    b = _mlp(seed=99)
    tb = ParallelTrainer(b, mesh_layout=SpecLayout(data=2, fsdp=2, tp=2))
    assert tb.checkpointer(str(tmp_path), async_write=False).restore(b)
    _assert_state_parity(a, b)


def test_largest_layout_picks_valid_meshes():
    assert largest_layout(8) == SpecLayout(data=1, fsdp=8, tp=1)
    assert largest_layout(8, tp=2) == SpecLayout(data=1, fsdp=4, tp=2)
    assert largest_layout(6, tp=4) == SpecLayout(data=1, fsdp=2, tp=3)
    assert largest_layout(1) == SpecLayout(data=1, fsdp=1, tp=1)
    assert largest_layout(7, tp=2, data=2) == SpecLayout(data=1, fsdp=7, tp=1)
    # the helper's output always builds (the supervisor hands it to workers)
    assert largest_layout(8, tp=2).build_mesh().devices.size == 8


# ------------------------------------------------------------------ AST lint


_RESTORE_FN_RE = re.compile(r"restore|reshard|_fill_from_chunks|_place_leaf")
_LINT_FILES = ("serde/checkpoint.py", "parallel/partition.py")


def _full_array_offenders(src: str, rel: str):
    """``np.asarray`` / ``jax.device_get`` call sites inside restore-path
    functions without a ``# gather-ok:`` justification on the call line
    or the line above it."""
    lines = src.splitlines()
    offenders = []
    for node in ast.walk(ast.parse(src, filename=rel)):
        if not (isinstance(node, ast.FunctionDef)
                and _RESTORE_FN_RE.search(node.name)):
            continue
        for call in ast.walk(node):
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr in ("asarray", "device_get")
                    and isinstance(call.func.value, ast.Name)
                    and call.func.value.id in ("np", "numpy", "jax")):
                continue
            context = lines[max(0, call.lineno - 2):call.lineno]
            if any("gather-ok" in ln for ln in context):
                continue
            offenders.append(f"{rel}:{call.lineno} ({node.name})")
    return offenders


def test_no_full_array_in_restore_paths():
    """ISSUE 14 satellite (repo lint): the no-full-array-on-one-host
    constraint is a RESTORE-PATH invariant, and one convenient
    ``np.asarray(params)`` / ``jax.device_get`` would silently rot it into
    a gather. Ban both inside the restore/reshard/placement functions of
    serde/checkpoint.py + parallel/partition.py unless the call line (or
    the line above it) carries a ``# gather-ok: <reason>`` justification."""
    offenders = []
    for rel in _LINT_FILES:
        offenders += _full_array_offenders((ROOT / rel).read_text(), rel)
    assert not offenders, (
        "full-array materialization in a restore path (annotate a genuinely "
        "host-side/metadata site with `# gather-ok: <reason>`): "
        f"{offenders}")


def test_full_array_lint_catches_a_planted_offender():
    planted = (
        "import numpy as np\n"
        "def _restore_sharded(net):\n"
        "    ok = np.asarray(meta)  # gather-ok: metadata\n"
        "    other = 1\n"
        "    bad = np.asarray(net.params_)\n"
        "def unrelated(x):\n"
        "    return np.asarray(x)\n"
    )
    hits = _full_array_offenders(planted, "planted.py")
    assert hits == ["planted.py:5 (_restore_sharded)"]
