"""Framework-wide shape bucketing (ISSUE 12 tentpole layer 1).

Acceptance pins:
- the bucket policy is THE serving policy (extracted from
  ``ParallelInference._bucket``), byte-identical over its whole domain;
- pad-to-bucket training yields loss (and param) parity with unbucketed
  training to 1e-6 on LeNet — padded rows are invisible to loss/grads;
- a shape-churning workload (varying batch tail) shows compiles flat after
  warmup: every ragged tail lands in one bucket, one signature, one
  executable;
- fit loops that pad report the TRUE example count as ``last_batch_size``
  (satellite: samples/sec listeners must not count phantom rows).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.common.bucketing import (BucketSpec, bucket_ladder,
                                                 bucket_size, pad_dataset,
                                                 pad_multidataset)
from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.nn import (ComputationGraph, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf import (ConvolutionLayer, DenseLayer,
                                        InputType, LSTM, OutputLayer,
                                        RnnOutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.updaters import Adam


def _dense_net(seed=0, n_in=8, n_out=4):
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_in=n_in, n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=n_out, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _lenet(seed=0):
    """LeNet shape (conv-pool-conv-pool-dense) on 12x12 inputs — the
    acceptance model, scaled so CPU tier-1 stays fast."""
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                    activation="relu"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(12, 12, 1))
            .build())
    return MultiLayerNetwork(conf).init()


# ------------------------------------------------------------------ policy


def test_bucket_size_matches_serving_policy():
    """The extracted policy must be byte-identical to the historical
    ParallelInference._bucket over its whole domain."""
    def reference(n, batch_limit, ndata):
        b = ndata
        while b < batch_limit:
            b *= 2
        while b < n:
            b *= 2
        return b

    for n in list(range(0, 600, 7)) + [1, 2, 1023, 1024, 1025]:
        for bl in (1, 2, 8, 16, 32):
            for nd in (1, 2, 4, 8):
                assert bucket_size(n, min_bucket=bl, multiple=nd) == \
                    reference(n, bl, nd), (n, bl, nd)


def test_parallel_inference_bucket_delegates_to_common_policy():
    net = _dense_net()
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    pi = ParallelInference(net, batch_limit=16)
    for n in (1, 5, 16, 17, 100):
        assert pi._bucket(n) == bucket_size(n, min_bucket=16,
                                            multiple=pi._ndata)
    ladder = pi.bucket_sizes(100)
    assert ladder[-1] == pi._bucket(100)
    assert all(b == 2 * a for a, b in zip(ladder, ladder[1:]))


def test_bucket_ladder_covers_every_bucket():
    assert bucket_ladder(128, min_bucket=16) == [16, 32, 64, 128]
    assert bucket_ladder(17, min_bucket=4, multiple=2) == [4, 8, 16, 32]
    assert bucket_ladder(1, min_bucket=8) == [8]


# ------------------------------------------------------------- pad_dataset


def test_pad_dataset_pads_rows_with_zero_mask():
    ds = DataSet(np.ones((17, 3), np.float32), np.ones((17, 5), np.float32))
    padded, n = pad_dataset(ds, BucketSpec(min_batch=32))
    assert n == 17
    assert padded.features.shape == (32, 3)
    assert padded.labels.shape == (32, 5)
    assert padded.labels_mask.shape == (32,)
    assert padded.labels_mask[:17].all() and not padded.labels_mask[17:].any()
    # padded feature rows are zeros, real rows untouched
    assert (padded.features[17:] == 0).all()
    assert (padded.features[:17] == 1).all()


def test_pad_dataset_aligned_batch_still_carries_the_mask():
    """An aligned batch gets the all-ones mask padding would have created:
    the jit signature must not flicker between aligned (maskless) and
    padded (masked) batches — that flicker IS a second executable."""
    ds = DataSet(np.ones((32, 3), np.float32), np.ones((32, 5), np.float32))
    padded, n = pad_dataset(ds, BucketSpec(min_batch=32))
    assert n == 32
    assert padded.features.shape == (32, 3)
    assert padded.labels_mask.shape == (32,) and padded.labels_mask.all()


def test_pad_dataset_aligned_batch_with_mask_is_identity():
    ds = DataSet(np.ones((32, 3), np.float32), np.ones((32, 5), np.float32),
                 None, np.ones((32,), np.float32))
    padded, n = pad_dataset(ds, BucketSpec(min_batch=32))
    assert padded is ds and n == 32


def test_pad_dataset_sequence_bucketing_extends_masks():
    B, C, T = 4, 2, 37
    ds = DataSet(np.ones((B, C, T), np.float32),
                 np.ones((B, C, T), np.float32),
                 None, np.ones((B, T), np.float32))
    padded, n = pad_dataset(ds, BucketSpec(min_batch=4, sequence=True,
                                           min_seq=16))
    assert n == 4
    assert padded.features.shape == (4, 2, 64)
    assert padded.labels.shape == (4, 2, 64)
    # features mask materialized (ones on real steps), zero on padding
    assert padded.features_mask.shape == (4, 64)
    assert padded.features_mask[:, :T].all()
    assert not padded.features_mask[:, T:].any()
    assert not padded.labels_mask[:, T:].any()


def test_pad_dataset_sequence_requires_mask_for_time_labels():
    ds = DataSet(np.ones((4, 2, 37), np.float32),
                 np.ones((4, 2, 37), np.float32))
    with pytest.raises(ValueError, match="labels_mask"):
        pad_dataset(ds, BucketSpec(sequence=True, min_seq=16))


def test_pad_multidataset_pads_every_stream():
    mds = MultiDataSet([np.ones((9, 3)), np.ones((9, 2))],
                       [np.ones((9, 4)), np.ones((9, 1))])
    padded, n = pad_multidataset(mds, BucketSpec(min_batch=16))
    assert n == 9
    assert all(f.shape[0] == 16 for f in padded.features)
    assert all(y.shape[0] == 16 for y in padded.labels)
    for m in padded.labels_masks:
        assert m[:9].all() and not m[9:].any()


def test_pad_multidataset_aligned_batch_materializes_masks():
    """Signature stability, MultiDataSet form: a bucket-aligned batch still
    gets the all-ones labels masks padding would have created — otherwise
    aligned batches (maskless) and padded tails (masked) mint TWO
    executables for one workload, the exact churn bucketing exists to
    kill (pad_dataset already pins this for the DataSet path)."""
    mds = MultiDataSet([np.ones((16, 3))], [np.ones((16, 4))])
    padded, n = pad_multidataset(mds, BucketSpec(min_batch=16))
    assert n == 16
    assert padded.features[0].shape[0] == 16
    assert len(padded.labels_masks) == 1
    assert padded.labels_masks[0].shape == (16,)
    assert padded.labels_masks[0].all()
    # existing masks pass through untouched — no double-materialize
    again, _ = pad_multidataset(padded, BucketSpec(min_batch=16))
    assert again is padded


# ------------------------------------------------------------- loss parity


def test_lenet_bucketed_loss_parity_1e6():
    """ISSUE 12 acceptance: pad-to-bucket training == unbucketed training
    to 1e-6 on LeNet — per-step losses AND final params."""
    rs = np.random.RandomState(0)
    X = rs.rand(45, 1, 12, 12).astype(np.float32)
    Y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, 45)]

    plain, bucketed = _lenet(), _lenet()
    bucketed.set_bucketing(BucketSpec(min_batch=16))
    losses_p, losses_b = [], []
    for lo in range(0, 45, 16):  # batches of 16, 16, 13 — ragged tail
        ds = DataSet(X[lo:lo + 16], Y[lo:lo + 16])
        plain._fit_batch(DataSet(X[lo:lo + 16], Y[lo:lo + 16]))
        bucketed._fit_batch(ds)
        losses_p.append(plain.score_)
        losses_b.append(bucketed.score_)
    np.testing.assert_allclose(losses_b, losses_p, atol=1e-6)
    np.testing.assert_allclose(np.asarray(bucketed.params().numpy()),
                               np.asarray(plain.params().numpy()), atol=1e-6)


def test_graph_bucketed_loss_parity():
    def build():
        g = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-2))
             .graph_builder().add_inputs("in")
             .set_input_types(InputType.feed_forward(6)))
        g.add_layer("d", DenseLayer(n_out=12, activation="relu"), "in")
        g.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                       loss="mcxent"), "d")
        g.set_outputs("out")
        return ComputationGraph(g.build()).init()

    rs = np.random.RandomState(1)
    X = rs.randn(21, 6).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 21)]
    plain, bucketed = build(), build()
    bucketed.set_bucketing(BucketSpec(min_batch=16))
    lp, lb = [], []
    for lo in range(0, 21, 16):
        plain.fit(DataSet(X[lo:lo + 16], Y[lo:lo + 16]))
        bucketed.fit(DataSet(X[lo:lo + 16], Y[lo:lo + 16]))
        lp.append(plain.score_)
        lb.append(bucketed.score_)
    np.testing.assert_allclose(lb, lp, atol=1e-6)
    assert bucketed.last_batch_size == 5  # true tail, not the padded 16


def test_parallel_trainer_bucketing_keeps_mesh_divisibility():
    """ParallelTrainer folds the mesh data-axis size into the bucket
    multiple: bucketed batches never take the remainder-fallback path, and
    loss still matches unbucketed single-device training."""
    from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

    rs = np.random.RandomState(2)
    X = rs.randn(19, 8).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 19)]

    plain = _dense_net(seed=3)
    plain._fit_batch(DataSet(X, Y))

    net = _dense_net(seed=3)
    trainer = ParallelTrainer(net, bucketing=BucketSpec(min_batch=8))
    trainer._fit_batch(DataSet(X, Y))
    assert net.last_batch_size == 19
    np.testing.assert_allclose(float(net.score_), float(plain.score_),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(net.params().numpy()),
                               np.asarray(plain.params().numpy()), atol=1e-6)


# ----------------------------------------------------- compile-churn pins


def test_varying_batch_tail_compiles_flat_after_warmup():
    """The churn workload the tentpole exists for: ragged tails mint ONE
    signature (one executable) with bucketing on — and would mint one per
    distinct tail without it."""
    from deeplearning4j_tpu.monitoring import RecompileWatchdog

    rs = np.random.RandomState(4)
    X = rs.randn(64, 8).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 64)]

    with RecompileWatchdog() as wd:
        net = _dense_net(seed=5).set_bucketing(BucketSpec(min_batch=32))
        for tail in (32, 17, 9, 23, 31, 32):
            net._fit_batch(DataSet(X[:tail], Y[:tail]))
        assert wd.stats()["signatures"]["MultiLayerNetwork.train_step"] == 1

    with RecompileWatchdog() as wd:
        churner = _dense_net(seed=5)  # no bucketing: one signature per tail
        for tail in (32, 17, 9, 23):
            churner._fit_batch(DataSet(X[:tail], Y[:tail]))
        assert wd.stats()["signatures"]["MultiLayerNetwork.train_step"] == 4


def test_sequence_bucketing_single_signature_for_ragged_time():
    """Variable-length text: T in {11, 13, 16} all pad to one seq bucket
    (and one batch bucket) — one signature."""
    from deeplearning4j_tpu.monitoring import RecompileWatchdog

    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-2))
            .list()
            .layer(LSTM(n_in=3, n_out=8))
            .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(3))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.set_bucketing(BucketSpec(min_batch=4, sequence=True, min_seq=16))
    rs = np.random.RandomState(6)
    with RecompileWatchdog() as wd:
        for T in (11, 13, 16):
            x = rs.randn(3, 3, T).astype(np.float32)
            y = np.zeros((3, 2, T), np.float32)
            y[:, 0] = 1.0
            net._fit_batch(DataSet(x, y, None, np.ones((3, T), np.float32)))
        assert wd.stats()["signatures"]["MultiLayerNetwork.train_step"] == 1
    assert net.last_batch_size == 3


def test_tbptt_accepts_per_example_bucket_mask():
    """Batch bucketing creates a [B] mask; the tbptt path broadcasts it to
    its per-timestep [B, T] form — padded rows contribute zero segments."""
    def build():
        conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-2))
                .list()
                .layer(LSTM(n_in=2, n_out=4))
                .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(2))
                .t_bptt_length(4)
                .build())
        return MultiLayerNetwork(conf).init()

    rs = np.random.RandomState(7)
    x = rs.randn(3, 2, 8).astype(np.float32)
    y = np.zeros((3, 2, 8), np.float32)
    y[:, 1] = 1.0
    plain = build()
    plain._fit_batch(DataSet(x, y))
    bucketed = build().set_bucketing(BucketSpec(min_batch=4))
    bucketed._fit_batch(DataSet(x, y))
    assert bucketed.last_batch_size == 3
    np.testing.assert_allclose(float(bucketed.score_), float(plain.score_),
                               atol=1e-6)


# ------------------------------------------------- correctness guard rails


def test_set_bucketing_refuses_batchnorm():
    """The labels mask keeps padded rows out of the LOSS, but BN batch
    statistics are computed over every row of the padded batch — phantom
    zero rows would silently change training vs unbucketed, so
    set_bucketing refuses instead of breaking the parity contract."""
    from deeplearning4j_tpu.nn.conf import BatchNormalization

    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
            .layer(BatchNormalization())
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf)
    with pytest.raises(ValueError, match="BatchNormalization"):
        net.set_bucketing(BucketSpec(min_batch=32))
    assert net._bucketing is None  # refusal leaves bucketing off
    net.set_bucketing(None)  # disabling is always allowed


def test_multiprocess_bucket_divergence_is_deterministic_error():
    """Per-rank ragged tails that straddle a power-of-2 boundary (17 vs 16)
    bucket to DIFFERENT sizes; MultiProcessTrainer's per-batch lockstep
    check must turn that into a ValueError naming the sizes instead of a
    hang in the first collective."""
    import jax
    from jax.experimental import multihost_utils

    from deeplearning4j_tpu.parallel.trainer import _check_lockstep_buckets

    class _Patch:
        def __init__(self, sizes):
            self.sizes = sizes

        def __enter__(self):
            self._pc, self._ag = jax.process_count, multihost_utils.process_allgather
            jax.process_count = lambda: 2
            multihost_utils.process_allgather = \
                lambda x: np.asarray(self.sizes, np.int32)
            return self

        def __exit__(self, *exc):
            jax.process_count = self._pc
            multihost_utils.process_allgather = self._ag

    with _Patch([32, 16]):
        with pytest.raises(ValueError, match=r"diverged.*\[32, 16\]"):
            _check_lockstep_buckets(32)
    with _Patch([32, 32]):
        _check_lockstep_buckets(32)  # agreement passes
    _check_lockstep_buckets(7)  # single-process: no collective, no-op


def test_multiprocess_bucket_multiple_is_process_local():
    """Each rank buckets its LOCAL shard: with 8 global devices over 2
    processes the multiple is 4, so a 3-row local tail pads to 4 — folding
    the GLOBAL axis size in would over-pad it to 8 (2x the phantom rows,
    every ragged step, on every rank)."""
    import jax

    from deeplearning4j_tpu.parallel import build_mesh
    from deeplearning4j_tpu.parallel.trainer import MultiProcessTrainer

    net = _dense_net().set_bucketing(BucketSpec())
    trainer = MultiProcessTrainer(net, mesh=build_mesh(data=8))
    orig = jax.process_count
    jax.process_count = lambda: 2
    try:
        assert trainer._bucket_multiple() == 4
        ds, n = trainer._bucket_for_mesh(
            DataSet(np.ones((3, 8), np.float32),
                    np.ones((3, 4), np.float32)))
        assert n == 3
        assert ds.features.shape[0] == 4
    finally:
        jax.process_count = orig
    # single-process: the whole data axis, exactly as before
    assert trainer._bucket_multiple() == 8
