"""MoE / expert parallelism (SURVEY §2.10 EP row)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.models.moe import (
    MoEConfig,
    init_moe_params,
    moe_ffn,
    moe_partition_specs,
)


def _setup(E=4, k=2, cap=4.0, D=8, F=16):
    cfg = MoEConfig(d_model=D, d_ff=F, n_experts=E, top_k=k, capacity_factor=cap)
    params = init_moe_params(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (2, 6, D), jnp.float32)
    return cfg, params, x


def test_moe_forward_shapes_and_finite():
    cfg, params, x = _setup()
    y, aux = moe_ffn(params, x, cfg)
    assert y.shape == x.shape
    assert np.isfinite(float(aux)) and float(aux) > 0


def test_top1_uncapped_equals_dense_expert_choice():
    """With top_k=1 and capacity >= all tokens, every token goes through
    exactly its argmax expert's FFN — verifiable densely."""
    cfg, params, x = _setup(E=3, k=1, cap=100.0)
    y, _ = moe_ffn(params, x, cfg)

    xt = np.asarray(x).reshape(-1, cfg.d_model)
    gates = np.asarray(jax.nn.softmax(jnp.asarray(xt) @ params["wg"], axis=-1))
    choice = gates.argmax(-1)
    want = np.zeros_like(xt)
    for n in range(xt.shape[0]):
        e = choice[n]
        h = np.asarray(jax.nn.gelu(jnp.asarray(xt[n]) @ params["w1"][e] + params["b1"][e]))
        want[n] = (h @ np.asarray(params["w2"][e]) + np.asarray(params["b2"][e]))
    np.testing.assert_allclose(np.asarray(y).reshape(-1, cfg.d_model), want,
                               rtol=1e-4, atol=1e-5)


def test_capacity_drops_tokens():
    """Tiny capacity: some tokens lose their expert slot and contribute 0."""
    cfg, params, x = _setup(E=2, k=1, cap=0.26)  # capacity ~ 2 tokens/expert
    y, _ = moe_ffn(params, x, cfg)
    yt = np.asarray(y).reshape(-1, cfg.d_model)
    dropped = np.sum(np.all(yt == 0.0, axis=-1))
    assert dropped > 0  # capacity ceiling really dropped someone


def test_expert_parallel_sharding_matches_replicated():
    """Experts sharded over an 'expert' mesh axis == unsharded numerics
    (GSPMD inserts the dispatch all-to-alls)."""
    cfg, params, x = _setup(E=8, k=2)
    y_ref, aux_ref = moe_ffn(params, x, cfg)

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("data", "expert"))
    specs = moe_partition_specs(cfg)
    sharded = jax.device_put(
        params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda v: isinstance(v, P)))
    xs = jax.device_put(x, NamedSharding(mesh, P("data", None, None)))
    y, aux = jax.jit(lambda p, a: moe_ffn(p, a, cfg))(sharded, xs)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)


def test_moe_is_differentiable():
    cfg, params, x = _setup()

    def loss(p):
        y, aux = moe_ffn(p, x, cfg)
        return jnp.sum(y ** 2) + 0.01 * aux

    grads = jax.grad(loss)(params)
    for leaf in jax.tree.leaves(grads):
        assert np.all(np.isfinite(np.asarray(leaf)))
    # router receives gradient (both from combine weights and aux loss)
    assert float(jnp.sum(jnp.abs(grads["wg"]))) > 0
