"""SLO objectives + tracker (ISSUE 11 tentpole, layer 3).

Objective validation, attainment math (latency histograms with bucket
interpolation, success ratios with label-prefix goodness), error-budget and
burn-rate arithmetic, gauge export, the /slo endpoint, and the AST lint
pinning every library SloObjective to a registry-declared family.
"""

import ast
import json
import pathlib
import re
import time
import urllib.request

import pytest

from deeplearning4j_tpu.monitoring import (HistoryRing, MetricsRegistry,
                                           SloObjective, SloTracker,
                                           default_objectives)
from deeplearning4j_tpu.monitoring import aggregate

ROOT = pathlib.Path(__file__).resolve().parent.parent


# -------------------------------------------------------------- validation


def test_objective_validation():
    with pytest.raises(ValueError, match="exactly one of"):
        SloObjective("x")
    with pytest.raises(ValueError, match="exactly one of"):
        SloObjective("x", histogram_family="tdl_inference_latency_seconds",
                     success_ratio_of="tdl_inference_requests_total")
    with pytest.raises(ValueError, match="threshold_seconds"):
        SloObjective("x", histogram_family="tdl_inference_latency_seconds")
    with pytest.raises(ValueError, match="target must be in"):
        SloObjective("x", success_ratio_of="tdl_inference_requests_total",
                     target=1.0)
    with pytest.raises(ValueError, match="window must be"):
        SloObjective("x", success_ratio_of="tdl_inference_requests_total",
                     window=0)
    with pytest.raises(ValueError, match="duplicate"):
        SloTracker(objectives=(
            SloObjective("d", success_ratio_of="tdl_inference_requests_total"),
            SloObjective("d", success_ratio_of="tdl_inference_requests_total")))
    # a success-ratio objective defaults goodness to HTTP 2xx
    obj = SloObjective("a", success_ratio_of="tdl_inference_requests_total")
    assert obj.good_labels_dict == {"code": "2"}
    assert obj.family == "tdl_inference_requests_total"


# ------------------------------------------------------------- attainment


def _fed_ring(reg):
    ring = HistoryRing(registry=reg, interval=0.0)
    ring.sample(force=True)
    return ring


def test_latency_objective_attainment_budget_and_burn():
    reg = MetricsRegistry()
    h = reg.histogram("tdl_inference_latency_seconds", buckets=(0.1, 0.5, 1.0))
    ring = _fed_ring(reg)
    for _ in range(95):
        h.observe(0.05)   # good
    for _ in range(5):
        h.observe(0.9)    # bad (over the 0.1s threshold)
    time.sleep(0.01)
    ring.sample(force=True)
    tracker = SloTracker(objectives=(
        SloObjective("lat", histogram_family="tdl_inference_latency_seconds",
                     threshold_seconds=0.1, target=0.9, window=60),),
        history_view=ring, registry=reg,
        burn_windows=(("fast", 60.0),))
    row = tracker.evaluate()[0]
    assert row["attainment"] == pytest.approx(0.95)
    # allowed error 0.1, observed 0.05 → half the budget consumed
    assert row["error_budget_remaining"] == pytest.approx(0.5)
    assert row["burn_rate"]["fast"] == pytest.approx(0.5)
    assert row["state"] == "ok"

    gauges = {s["labels"]["slo"]: s["value"]
              for s in reg.get("tdl_slo_attainment").snapshot()["series"]}
    assert gauges["lat"] == pytest.approx(0.95)
    burn = {(s["labels"]["slo"], s["labels"]["window"]): s["value"]
            for s in reg.get("tdl_slo_burn_rate").snapshot()["series"]}
    assert burn[("lat", "fast")] == pytest.approx(0.5)


def test_latency_objective_interpolates_inside_threshold_bucket():
    """A threshold between bucket edges counts the containing bucket's
    observations proportionally — same interpolation as the p99 rules."""
    reg = MetricsRegistry()
    h = reg.histogram("tdl_inference_latency_seconds", buckets=(0.1, 0.5))
    ring = _fed_ring(reg)
    for _ in range(100):
        h.observe(0.3)  # all in the (0.1, 0.5] bucket
    time.sleep(0.01)
    ring.sample(force=True)
    tracker = SloTracker(objectives=(
        SloObjective("lat", histogram_family="tdl_inference_latency_seconds",
                     threshold_seconds=0.3, target=0.9, window=60),),
        history_view=ring, registry=reg, burn_windows=())
    row = tracker.evaluate()[0]
    # (0.3 - 0.1) / (0.5 - 0.1) = half the bucket counts as good
    assert row["attainment"] == pytest.approx(0.5)
    assert row["state"] == "violating"


def test_success_ratio_objective_prefix_goodness():
    reg = MetricsRegistry()
    c = reg.counter("tdl_inference_requests_total", labels=("code",))
    ring = _fed_ring(reg)
    c.labels("200").inc(90)
    c.labels("201").inc(5)   # also 2xx-good
    c.labels("429").inc(4)
    c.labels("504").inc(1)
    time.sleep(0.01)
    ring.sample(force=True)
    tracker = SloTracker(objectives=(
        SloObjective("avail", success_ratio_of="tdl_inference_requests_total",
                     target=0.9, window=60),),
        history_view=ring, registry=reg, burn_windows=())
    row = tracker.evaluate()[0]
    assert row["attainment"] == pytest.approx(0.95)
    assert row["error_budget_remaining"] == pytest.approx(0.5)


def test_no_traffic_reports_full_budget_not_outage():
    reg = MetricsRegistry()
    reg.histogram("tdl_inference_latency_seconds", buckets=(0.1,))
    ring = _fed_ring(reg)
    ring.sample(force=True)
    tracker = SloTracker(objectives=(
        SloObjective("lat", histogram_family="tdl_inference_latency_seconds",
                     threshold_seconds=0.1, target=0.99, window=60),),
        history_view=ring, registry=reg)
    row = tracker.evaluate()[0]
    assert row["state"] == "no_traffic"
    assert row["attainment"] is None
    assert row["error_budget_remaining"] == 1.0
    assert all(b == 0.0 for b in row["burn_rate"].values())
    # the gauge encodes no-traffic as -1, never 0.0 (0 reads as an outage)
    assert reg.get("tdl_slo_attainment").labels("lat").value == -1.0


def test_tracker_self_feeds_without_history_view():
    reg = MetricsRegistry()
    c = reg.counter("tdl_inference_requests_total", labels=("code",))
    tracker = SloTracker(objectives=(
        SloObjective("avail", success_ratio_of="tdl_inference_requests_total",
                     target=0.9, window=60),),
        registry=reg, burn_windows=())
    c.labels("200").inc(1)
    assert tracker.evaluate()[0]["state"] == "no_traffic"  # one sample: no delta
    c.labels("200").inc(9)
    c.labels("500").inc(10)
    time.sleep(0.01)
    row = tracker.evaluate()[0]
    assert row["attainment"] == pytest.approx(9 / 19)
    assert row["state"] == "violating"


# ------------------------------------------------------------ /slo endpoint


def test_slo_endpoint_serves_tracker():
    from deeplearning4j_tpu.ui import UIServer

    reg = MetricsRegistry()
    h = reg.histogram("tdl_inference_latency_seconds", buckets=(0.1, 0.5))
    ring = HistoryRing(registry=reg, interval=0.0)
    ring.sample(force=True)
    for _ in range(10):
        h.observe(0.05)
    for _ in range(10):
        h.observe(0.4)
    time.sleep(0.01)
    ring.sample(force=True)
    tracker = SloTracker(objectives=(
        SloObjective("lat", histogram_family="tdl_inference_latency_seconds",
                     threshold_seconds=0.1, target=0.99, window=60),),
        history_view=ring, registry=reg)
    server = UIServer(port=0)
    try:
        server.attach_registry(reg)
        server.attach_slo(tracker)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/slo", timeout=10) as r:
            payload = json.loads(r.read())
        assert payload["violating"] == ["lat"]
        row = payload["slos"][0]
        assert row["slo"] == "lat"
        assert row["attainment"] == pytest.approx(0.5)
        assert "burn_rate" in row and "error_budget_remaining" in row
    finally:
        server.stop()


# --------------------------------------------- spool robustness (satellite)


def test_read_spools_skips_corrupt_files_and_counts_errors(tmp_path):
    from deeplearning4j_tpu.monitoring import get_registry
    from deeplearning4j_tpu.monitoring.aggregate import (MetricsSpooler,
                                                         read_spools)

    reg = MetricsRegistry()
    reg.gauge("tdl_test_gauge").set(3)
    MetricsSpooler(str(tmp_path), proc="rank0", registry=reg,
                   interval=0.0, rank=0).spool(force=True)
    # a torn write, a non-object payload, and an object with a bogus
    # snapshot — each must degrade that file only, never the scrape
    (tmp_path / "tdl_metrics_rank1.123.json").write_text('{"proc": "rank1", ')
    (tmp_path / "tdl_metrics_rank2.124.json").write_text("[1, 2, 3]")
    (tmp_path / "tdl_metrics_rank3.125.json").write_text(
        json.dumps({"proc": "rank3", "wall": 1, "snapshot": "not-a-dict"}))

    before = {s["labels"]["proc"]: s["value"] for s in
              (get_registry().get("tdl_spool_read_errors_total") or
               aggregate.spool_read_errors()).snapshot()["series"]}
    spools = read_spools(str(tmp_path))
    assert [s["proc"] for s in spools] == ["rank0"]  # the good spool survives
    after = {s["labels"]["proc"]: s["value"] for s in
             get_registry().get("tdl_spool_read_errors_total")
             .snapshot()["series"]}
    assert after.get("rank1", 0) - before.get("rank1", 0) == 1
    assert after.get("rank3", 0) - before.get("rank3", 0) == 1
    # the non-object file has no proc field; its filename gives rank2
    assert (after.get("rank2", 0) + after.get("unknown", 0)) \
        - (before.get("rank2", 0) + before.get("unknown", 0)) == 1

    # and the merged exposition still renders (the original bug class:
    # one corrupt file poisoning the whole merged /metrics view)
    text = aggregate.merged_prometheus(str(tmp_path))
    assert 'tdl_test_gauge{proc="rank0",rank="0"} 3' in text


# --------------------------------------------------------------- AST lint


def _declared_families() -> set:
    decl = re.compile(
        r'\.(?:counter|gauge|histogram)\(\s*["\'](tdl_[a-z0-9_]+)["\']')
    declared = set(aggregate.DERIVED_FAMILIES)
    for path in sorted((ROOT / "deeplearning4j_tpu").rglob("*.py")):
        declared.update(decl.findall(path.read_text()))
    return declared


def test_slo_objectives_reference_declared_histograms():
    """Repo lint (ISSUE 11 satellite, mirror of the alert-rule lint): every
    SloObjective(...) in library code must name its family
    (histogram_family / success_ratio_of) as a LITERAL declared by some
    registry — renaming a metric fails the build instead of silently
    rotting the SLO that watches it."""
    declared = _declared_families()
    assert len(declared) > 30
    offenders, found = [], 0
    for path in sorted((ROOT / "deeplearning4j_tpu").rglob("*.py")):
        rel = path.relative_to(ROOT).as_posix()
        tree = ast.parse(path.read_text(), filename=rel)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and ((isinstance(node.func, ast.Name)
                          and node.func.id == "SloObjective")
                         or (isinstance(node.func, ast.Attribute)
                             and node.func.attr == "SloObjective"))):
                continue
            found += 1
            refs = {}
            for kw in node.keywords:
                if kw.arg in ("histogram_family", "success_ratio_of"):
                    refs[kw.arg] = kw.value
            if not refs:
                offenders.append(f"{rel}:{node.lineno} (no family argument)")
                continue
            for role, val in refs.items():
                if not (isinstance(val, ast.Constant)
                        and isinstance(val.value, str)):
                    if isinstance(val, ast.Constant) and val.value is None:
                        continue
                    offenders.append(
                        f"{rel}:{node.lineno} ({role} is not a string literal)")
                elif val.value not in declared:
                    offenders.append(
                        f"{rel}:{node.lineno} ({role}={val.value!r} is not a "
                        "registry-declared family)")
    assert found >= 3  # the scan saw default_objectives()
    assert not offenders, (
        "SLO objectives referencing unknown metric families (declare the "
        f"family in a registry, or fix the objective): {offenders}")


def test_default_objectives_compile_against_default_rules():
    """The stock burn alert rules watch the families the stock tracker
    exports — the pairing must construct without wiring errors."""
    from deeplearning4j_tpu.monitoring import AlertEngine, default_rules

    reg = MetricsRegistry()
    tracker = SloTracker(default_objectives(), registry=reg)
    engine = AlertEngine(default_rules(), registry=reg)
    tracker.evaluate()
    rows = {a["rule"]: a for a in engine.evaluate()}
    # burn gauges exist (tracker exported them) → the rules see numbers,
    # zero on a clean registry → not firing
    assert rows["error_budget_burn_fast"]["value"] == 0.0
    assert not rows["error_budget_burn_fast"]["firing"]
    assert rows["error_budget_burn_slow"]["value"] == 0.0
