"""Sharded-parameter training tests (ISSUE 9): SpecLayout role→spec policy,
fsdp×tp loss parity with the replicated gang, per-device shard accounting,
layout-aware checkpoints, the bundled-model coverage gate, and the donation
lint for fused-step compilations.

The multi-process acceptance tier (per-rank byte shrink over a real gang,
sharded-checkpoint round trip across gangs) rides tests/mp_workers.py in
test_multiprocess.py (slow-marked)."""

import ast
import pathlib

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn import (ComputationGraph, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf import (BatchNormalization, DenseLayer,
                                        EmbeddingSequenceLayer, GravesLSTM,
                                        InputType, LSTM, OutputLayer,
                                        RnnOutputLayer)
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.parallel import (ParallelTrainer, Partitioner,
                                         SpecLayout, build_mesh,
                                         param_role_tree)
from deeplearning4j_tpu.parallel.partition import uncovered_params
from deeplearning4j_tpu.parallel.sharding import batch_sharding

ROOT = pathlib.Path(__file__).resolve().parent.parent / "deeplearning4j_tpu"


def _mlp(seed=7, classes=4, hidden=16):
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_in=8, n_out=hidden, activation="tanh"))
            .layer(BatchNormalization())
            .layer(DenseLayer(n_in=hidden, n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=classes, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _batches(steps=10, n=16, classes=4):
    out = []
    for s in range(steps):
        rs = np.random.RandomState(100 + s)
        x = rs.rand(n, 8).astype(np.float32)
        y = np.eye(classes, dtype=np.float32)[rs.randint(0, classes, n)]
        out.append(DataSet(x, y))
    return out


# ------------------------------------------------------------ role → spec map


def test_spec_layout_assigns_specs_by_role():
    net = _mlp()
    layout = SpecLayout(data=2, fsdp=2, tp=2)
    part = Partitioner(layout)
    specs = part.spec_tree(net.params_, param_role_tree(net))
    assert specs["0"]["W"] == P("fsdp", "tp")     # dense kernel
    assert specs["0"]["b"] == P("fsdp")           # bias over fsdp
    assert specs["1"]["gamma"] == P("fsdp")       # norm over fsdp
    assert specs["3"]["W"] == P("fsdp", "tp")


def test_spec_layout_embedding_table_shards_vocab_over_fsdp_x_tp():
    conf = (NeuralNetConfiguration.Builder().seed(3).updater(Adam(1e-2)).list()
            .layer(EmbeddingSequenceLayer(n_in=64, n_out=8))
            .layer(RnnOutputLayer(n_in=8, n_out=4, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(64, 5))
            .build())
    net = MultiLayerNetwork(conf).init()
    part = Partitioner(SpecLayout(data=2, fsdp=2, tp=2))
    specs = part.spec_tree(net.params_, param_role_tree(net))
    # the [vocab, dim] table: vocab dim over fsdp AND tp combined
    assert specs["0"]["W"] == P(("fsdp", "tp"))


def test_divisibility_fallback_is_per_axis_and_reported():
    net = _mlp(classes=3)  # 3-class head: 3 divides neither fsdp=2 nor tp=2
    part = Partitioner(SpecLayout(data=2, fsdp=2, tp=2))
    rep: dict = {}
    specs = part.spec_tree(net.params_, param_role_tree(net), report=rep)
    # kernel [16, 3]: dim0 keeps fsdp, dim1 drops tp
    assert specs["3"]["W"] == P("fsdp")
    # bias [3]: nothing divides → replicated AND reported, never silent
    assert specs["3"]["b"] == P()
    assert "3/b" in rep["replicated_fallback"]
    assert rep["uncovered"] == []


# ------------------------------------------------- acceptance: loss parity


def test_fsdp_tp_matches_replicated_loss_curve():
    """ISSUE 9 acceptance: an fsdp×tp run matches the replicated run's loss
    curve to 1e-6 over ≥10 steps on the same seeded data."""
    a, b = _mlp(), _mlp()
    ta = ParallelTrainer(a, mesh=build_mesh(data=8))
    tb = ParallelTrainer(b, mesh_layout=SpecLayout(data=2, fsdp=2, tp=2))
    la, lb = [], []
    for ds in _batches(steps=10):
        ta._fit_batch(ds)
        tb._fit_batch(ds)
        la.append(a.score_)
        lb.append(b.score_)
    np.testing.assert_allclose(la, lb, atol=1e-6)
    # and the final params agree too (the updates really applied on shards)
    for wa, wb in zip(jax.tree.leaves(a.params_), jax.tree.leaves(b.params_)):
        np.testing.assert_allclose(np.asarray(wa), np.asarray(wb), atol=1e-5)


def test_graph_fsdp_training_matches_replicated():
    def graph():
        g = (NeuralNetConfiguration.Builder().seed(11).updater(Adam(1e-2))
             .graph_builder().add_inputs("in")
             .set_input_types(InputType.feed_forward(8)))
        g.add_layer("d1", DenseLayer(n_in=8, n_out=16, activation="tanh"), "in")
        g.add_layer("out", OutputLayer(n_in=16, n_out=4, activation="softmax",
                                       loss="mcxent"), "d1")
        g.set_outputs("out")
        return ComputationGraph(g.build()).init()

    a, b = graph(), graph()
    ta = ParallelTrainer(a, mesh=build_mesh(data=8))
    tb = ParallelTrainer(b, mesh_layout=SpecLayout(data=2, fsdp=2, tp=2))
    for ds in _batches(steps=5):
        ta._fit_batch(ds)
        tb._fit_batch(ds)
    np.testing.assert_allclose(float(a.score_), float(b.score_), atol=1e-6)


# --------------------------------------------------- shard byte accounting


def test_partition_shards_params_and_opt_state():
    net = _mlp()  # every dim divides 4 → fully sharded over fsdp×tp
    trainer = ParallelTrainer(net, mesh_layout=SpecLayout(data=2, fsdp=2, tp=2))
    trainer._place_net()
    rep = trainer.partition_report
    assert rep.uncovered == [] and rep.replicated_fallback == []
    # each device holds exactly nbytes/prod(sharded axes) of every leaf:
    # kernels split fsdp×tp (4-way), 1-D norms/biases split fsdp (2-way)
    mesh = trainer.mesh

    def shard_frac(spec):
        axes = [a for dim in spec if dim is not None
                for a in (dim if isinstance(dim, tuple) else (dim,))]
        return int(np.prod([mesh.shape[a] for a in axes])) if axes else 1

    expected_dev = sum(w.nbytes // shard_frac(s)
                       for w, s in zip(jax.tree.leaves(net.params_),
                                       jax.tree.leaves(
                                           rep.specs,
                                           is_leaf=lambda x: isinstance(x, P))))
    assert rep.per_device_params_bytes == expected_dev
    # the 2-D kernels dominate → per-device bytes land well under total/2
    assert rep.per_device_params_bytes < rep.params_bytes_total // 2
    # Adam m/v shard identically to the params
    assert rep.opt_bytes_per_rank == 2 * rep.params_bytes_per_rank
    # donation sanity: a fit step updates in place on the shards and keeps
    # the sharding (no silent gather-to-replicated)
    trainer._fit_batch(_batches(steps=1)[0])
    w = net.params_["0"]["W"]
    assert w.sharding.spec == P("fsdp", "tp")

    from deeplearning4j_tpu.monitoring import get_registry

    snap = get_registry().snapshot()
    kinds = {s["labels"]["kind"]: s["value"]
             for s in snap["tdl_param_bytes_per_rank"]["series"]}
    assert kinds["params"] == rep.params_bytes_per_rank
    assert kinds["opt_state"] == rep.opt_bytes_per_rank
    infos = snap["tdl_mesh_layout_info"]["series"]
    assert [s["labels"] for s in infos] == [{"data": "2", "fsdp": "2", "tp": "2"}]


def test_strict_partitioner_refuses_uncovered_params():
    part = Partitioner(SpecLayout(data=2, fsdp=2, tp=2))
    with pytest.raises(ValueError, match="does not cover.*mystery"):
        part.spec_tree({"0": {"mystery_param": np.zeros((4, 4), np.float32)}})


# ------------------------------------------------------------ batch sharding


def test_batch_sharding_generalizes_to_layout_meshes():
    # ISSUE 9 satellite: multi-axis mesh → batch over data, REPLICATED over
    # fsdp/tp; 1-axis mesh under any name keeps the historical behavior
    layout_mesh = SpecLayout(data=2, fsdp=2, tp=2).build_mesh()
    assert batch_sharding(layout_mesh).spec == P("data")
    one_axis = build_mesh(model=8)
    assert batch_sharding(one_axis).spec == P("model")
    no_data = SpecLayout(data=1, fsdp=4, tp=2).build_mesh()
    # degenerate data axis still present → still P("data") (size-1 split)
    assert batch_sharding(no_data).spec == P("data")
    import jax.numpy as jnp
    from jax.sharding import Mesh

    pure_model = Mesh(np.array(jax.devices()).reshape(4, 2), ("x", "y"))
    assert batch_sharding(pure_model).spec == P()  # no data axis: replicate
    # and a placement through it actually works
    out = jax.device_put(jnp.ones((8, 3)), batch_sharding(layout_mesh))
    assert out.sharding.spec == P("data")


# ------------------------------------------------- layout-aware checkpoints


def test_sharded_checkpoint_roundtrip_and_layout_mismatch(tmp_path):
    from deeplearning4j_tpu.serde.checkpoint import TrainingCheckpointer

    a = _mlp()
    ta = ParallelTrainer(a, mesh_layout=SpecLayout(data=2, fsdp=2, tp=2))
    for ds in _batches(steps=4):
        ta._fit_batch(ds)
    ck = ta.checkpointer(str(tmp_path), async_write=False)
    ck.save(a)

    # same layout: restore places shards directly (no host assembly)
    b = _mlp(seed=99)  # different init — must be fully overwritten
    tb = ParallelTrainer(b, mesh_layout=SpecLayout(data=2, fsdp=2, tp=2))
    assert tb.checkpointer(str(tmp_path), async_write=False).restore(b)
    tb._place_net()
    for wa, wb in zip(jax.tree.leaves(a.params_), jax.tree.leaves(b.params_)):
        np.testing.assert_array_equal(np.asarray(wa), np.asarray(wb))
        assert wb.sharding.spec == wa.sharding.spec
    for ua, ub in zip(jax.tree.leaves(a.updater_state),
                      jax.tree.leaves(b.updater_state)):
        np.testing.assert_array_equal(np.asarray(ua), np.asarray(ub))
    assert b.iteration == a.iteration

    # training continues bit-for-bit from the restored shards
    ds = _batches(steps=5)[-1]
    ta._fit_batch(ds)
    tb._fit_batch(ds)
    np.testing.assert_allclose(float(a.score_), float(b.score_), atol=1e-7)

    # mismatched layout: clear error NAMING BOTH layouts
    c = _mlp()
    tc = ParallelTrainer(c, mesh_layout=SpecLayout(data=1, fsdp=4, tp=2))
    with pytest.raises(ValueError) as ei:
        tc.checkpointer(str(tmp_path), async_write=False).restore(c)
    msg = str(ei.value)
    assert "data=2 x fsdp=2 x tp=2" in msg and "data=1 x fsdp=4 x tp=2" in msg

    # replicated restore of a sharded checkpoint is also a (named) mismatch
    with pytest.raises(ValueError, match="replicated"):
        TrainingCheckpointer(str(tmp_path), async_write=False).restore(_mlp())


def test_replicated_checkpoint_still_restores_under_a_partitioner(tmp_path):
    """A layout-less (replicated) checkpoint loads into a sharded trainer:
    assemble host-side, then _place_net shards it — the upgrade path from a
    replicated gang to a sharded one."""
    from deeplearning4j_tpu.serde.checkpoint import TrainingCheckpointer

    a = _mlp()
    ParallelTrainer(a, mesh=build_mesh(data=8))._fit_batch(_batches(1)[0])
    TrainingCheckpointer(str(tmp_path), async_write=False).save(a)

    b = _mlp(seed=99)
    tb = ParallelTrainer(b, mesh_layout=SpecLayout(data=2, fsdp=2, tp=2))
    # place (and fit) BEFORE restoring: the one-shot _place_net is already
    # spent, so the restore itself must re-shard the assembled arrays
    tb._fit_batch(_batches(1)[0])
    assert tb.checkpointer(str(tmp_path), async_write=False).restore(b)
    assert b.params_["0"]["W"].sharding.spec == P("fsdp", "tp")
    for ua in jax.tree.leaves(b.updater_state):
        assert hasattr(ua.sharding, "spec")  # opt state re-placed too
    for wa, wb in zip(jax.tree.leaves(a.params_), jax.tree.leaves(b.params_)):
        np.testing.assert_allclose(np.asarray(wa), np.asarray(wb), atol=0)


# ------------------------------------------------------------- coverage gate


def _bundled_nets():
    """Representative bundled models exercising every param-producing layer
    family: zoo CNNs, recurrent stacks, embeddings, attention, the extended
    layers, and a ComputationGraph."""
    from deeplearning4j_tpu.models.zoo import LeNet, SimpleCNN
    from deeplearning4j_tpu.nn.attention_layers import (
        LearnedSelfAttentionLayer, SelfAttentionLayer)
    from deeplearning4j_tpu.nn.conf import (Bidirectional, EmbeddingLayer,
                                            GlobalPoolingLayer,
                                            SeparableConvolution2D, SimpleRnn)
    from deeplearning4j_tpu.nn.layers_ext import (CenterLossOutputLayer,
                                                  GRULayer, PReLULayer)
    from deeplearning4j_tpu.nn.layers_tail import GravesBidirectionalLSTM

    yield LeNet(input_shape=(1, 12, 12)).init()
    yield SimpleCNN(input_shape=(3, 16, 16)).init()

    rnn = (NeuralNetConfiguration.Builder().seed(5).updater(Adam(1e-3)).list()
           .layer(EmbeddingSequenceLayer(n_in=32, n_out=8))
           .layer(LSTM(n_in=8, n_out=8))
           .layer(GravesLSTM(n_in=8, n_out=8, peephole=True))
           .layer(GRULayer(n_in=8, n_out=8))
           .layer(GravesBidirectionalLSTM(n_in=8, n_out=8))
           .layer(Bidirectional(fwd=SimpleRnn(n_in=8, n_out=8)))
           .layer(RnnOutputLayer(n_in=16, n_out=4, activation="softmax",
                                 loss="mcxent"))
           .set_input_type(InputType.recurrent(32, 6))
           .build())
    yield MultiLayerNetwork(rnn).init()

    attn = (NeuralNetConfiguration.Builder().seed(5).updater(Adam(1e-3)).list()
            .layer(SelfAttentionLayer(n_heads=2, n_out=8, project_input=True))
            .layer(LearnedSelfAttentionLayer(n_heads=2, n_out=8, n_queries=4,
                                             project_input=True))
            .layer(GlobalPoolingLayer(pooling_type="avg"))
            .layer(PReLULayer())
            .layer(CenterLossOutputLayer(n_in=8, n_out=4))
            .set_input_type(InputType.recurrent(8, 6))
            .build())
    yield MultiLayerNetwork(attn).init()

    cnn_ext = (NeuralNetConfiguration.Builder().seed(5).updater(Adam(1e-3)).list()
               .layer(SeparableConvolution2D(n_out=8, kernel_size=(3, 3),
                                             convolution_mode="same"))
               .layer(BatchNormalization())
               .layer(DenseLayer(n_out=16, activation="relu"))
               .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
               .set_input_type(InputType.convolutional(8, 8, 2))
               .build())
    yield MultiLayerNetwork(cnn_ext).init()


def test_spec_layout_covers_bundled_model_params():
    """ISSUE 9 satellite (the coverage gate): SpecLayout must assign a role
    to EVERY param name the bundled models produce — an unmatched name would
    silently replicate, eating the memory the partitioner exists to save.
    New layers must extend nn.conf param-role tagging to pass this."""
    for net in _bundled_nets():
        missing = uncovered_params(net.params_, param_role_tree(net))
        assert not missing, (
            f"{type(net).__name__} params with no partition role "
            f"(tag them in nn.conf / Layer.param_roles): {missing}")


def test_spec_layout_covers_functional_transformer_params():
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       init_params,
                                                       init_qa_head)

    cfg = TransformerConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    missing = uncovered_params(params, param_role_tree(params))
    assert not missing, missing
    qa = init_qa_head(jax.random.key(1), cfg)
    assert not uncovered_params(qa, param_role_tree(qa))


# ------------------------------------------------------------- donation lint


_DONATE_SCAN = ("parallel",)
_DONATE_FILES = ("nn/multilayer.py", "nn/graph.py", "models/transformer.py")


def test_fused_step_compilations_donate_buffers():
    """ISSUE 9 satellite (repo lint): every ``jax.jit`` in the parallel/
    package and the fused-step modules must pass ``donate_argnums`` —
    an un-donated (params, opt-state) compilation doubles peak memory and
    silently defeats in-place sharded updates. Non-donating sites that are
    genuinely read-only (inference executables) carry a ``# donate-ok:``
    justification."""
    files = [p for d in _DONATE_SCAN for p in sorted((ROOT / d).rglob("*.py"))]
    files += [ROOT / f for f in _DONATE_FILES]
    offenders = []
    for path in files:
        rel = path.relative_to(ROOT).as_posix()
        src = path.read_text()
        lines = src.splitlines()
        tree = ast.parse(src, filename=rel)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "jit"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "jax"):
                continue
            if any(kw.arg == "donate_argnums" for kw in node.keywords):
                continue
            if "donate-ok" in lines[node.lineno - 1]:
                continue
            offenders.append(f"{rel}:{node.lineno}")
    assert not offenders, (
        "jax.jit without donate_argnums in a fused-step module (donate the "
        "params/opt-state, or justify a read-only executable with "
        f"`# donate-ok: <reason>`): {offenders}")
