"""Persistent compiled-executable cache (ISSUE 12 tentpole layer 2).

Acceptance pins:
- the restart contract: two PROCESSES sharing one TDL_COMPILE_CACHE_DIR —
  the second pays ZERO per-fn compiles after warmup and shows cache-hit
  counters as evidence;
- per-fn hit/miss attribution through the note_signature thread
  announcements;
- executables restored from disk are NOT counted as compiles (the
  backend_compile duration event wraps jax's cache retrieval too — pinned
  here so a jax upgrade changing that ordering fails loudly);
- env contract plumbing: GangSupervisor hands every incarnation a STABLE
  ``workdir/compile_cache``; the serving builder takes an explicit dir;
- warmup completeness satellite: with the cache present the executor warms
  EVERY ParallelInference bucket, not just the smallest.
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from deeplearning4j_tpu.common import compile_cache
from deeplearning4j_tpu.common.bucketing import bucket_ladder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def enabled_cache(tmp_path):
    """Enable the persistent cache at a tmp dir for one test, restoring the
    disabled state after (the cache is process-wide jax config — leaking it
    would slow and dirty every later test)."""
    d = str(tmp_path / "compile_cache")
    compile_cache.enable(d)
    try:
        yield d
    finally:
        compile_cache.disable()


def _tiny_net():
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _fit_some(net, steps=3):
    from deeplearning4j_tpu.data.dataset import DataSet

    rs = np.random.RandomState(0)
    X = rs.randn(32, 8).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 32)]
    for _ in range(steps):
        net._fit_batch(DataSet(X, Y))


# ----------------------------------------------------------- in-process


def test_miss_then_hit_attributed_per_fn(enabled_cache):
    """First compile = miss (written to disk); after dropping jax's
    in-memory caches the same dispatch = hit, both attributed to the
    announcing fit loop — and a restored executable never increments the
    compile counters."""
    import jax

    from deeplearning4j_tpu.monitoring import RecompileWatchdog, compilecache

    net = _tiny_net()
    _fit_some(net)
    s1 = compilecache.stats()
    assert s1["misses"].get("MultiLayerNetwork.train_step") == 1
    assert s1["bytes"] > 0
    assert os.listdir(enabled_cache)  # executables actually on disk

    with RecompileWatchdog() as wd:
        jax.clear_caches()
        net2 = _tiny_net()
        _fit_some(net2)
        s2 = compilecache.stats()
        # every announced executable restored, none compiled. (A couple of
        # anonymous helper jits — threefry seeding etc. — can legitimately
        # get fresh cache keys after an in-process clear_caches; the REAL
        # restart contract, zero misses of any kind in a fresh process, is
        # pinned by test_compiles_flat_across_process_restart below.)
        assert sum(s2["hits"].values()) > sum(s1["hits"].values())
        assert s2["hits"].get("MultiLayerNetwork.train_step", 0) >= 1
        named = {k: v for k, v in wd.stats()["per_fn_compiles"].items()
                 if k != "_unattributed"}
        assert named == {}, (
            f"cache restores must not count as compiles: {named}")


def test_hit_restore_spends_the_watchdog_announcement(enabled_cache):
    """A cache-hit restore must CLEAR the per-watchdog announcement, not
    just skip the compile counters: the restored fn's announcement is spent
    by the restore, so the thread's next UNANNOUNCED compile (an anonymous
    helper jit within the 120s attribution window) stays _unattributed
    instead of minting a phantom tdl_xla_compiles_total{fn=train_step} —
    the exact counter the flat-across-restart acceptance reads."""
    import jax

    from deeplearning4j_tpu.monitoring import RecompileWatchdog

    net = _tiny_net()
    _fit_some(net)  # misses written to disk

    with RecompileWatchdog() as wd:
        jax.clear_caches()
        net2 = _tiny_net()
        _fit_some(net2)  # hit-restores; last announcement = train_step
        # fresh anonymous jit on the SAME thread: a real compile nothing
        # announced
        jax.jit(lambda x: x * 2.0 + 1.0)(np.ones(3, np.float32))
        stats = wd.stats()["per_fn_compiles"]
        assert stats.get("MultiLayerNetwork.train_step", 0) == 0, stats
        assert stats.get("_unattributed", 0) >= 1, stats


def test_cache_bytes_gauge_tracks_directory(enabled_cache):
    from deeplearning4j_tpu.monitoring import get_registry

    from deeplearning4j_tpu.monitoring import compilecache

    net = _tiny_net()
    _fit_some(net, steps=1)
    # the miss event fires just before jax writes the entry, so the gauge
    # trails the disk by one entry until refreshed
    n = compilecache.refresh_bytes()
    g = get_registry().get("tdl_compile_cache_bytes")
    assert g is not None
    assert g.snapshot()["series"][0]["value"] == n
    assert n == compile_cache.cache_size_bytes(enabled_cache) > 0


def test_enable_is_idempotent_and_disable_resets(tmp_path):
    d = str(tmp_path / "cc")
    assert compile_cache.enable(d) == compile_cache.enable(d)
    assert compile_cache.enabled() and compile_cache.cache_dir() == \
        os.path.abspath(d)
    compile_cache.disable()
    assert not compile_cache.enabled()
    import jax

    assert jax.config.jax_compilation_cache_dir is None


# ------------------------------------------------- the restart acceptance


_RESTART_WORKER = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["TDL_COMPILE_CACHE_DIR"] = sys.argv[1]
    import numpy as np
    from deeplearning4j_tpu.monitoring import RecompileWatchdog, compilecache
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.data.dataset import DataSet

    wd = RecompileWatchdog().install()
    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_in=8, n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rs = np.random.RandomState(0)
    X = rs.randn(32, 8).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 32)]
    for _ in range(4):
        net._fit_batch(DataSet(X, Y))
    stats = compilecache.stats()
    print(json.dumps({
        "per_fn_compiles": wd.stats()["per_fn_compiles"],
        "hits": stats["hits"], "misses": stats["misses"],
        "bytes": stats["bytes"],
    }))
""")


def _run_restart_worker(cache_dir):
    env = dict(os.environ, PYTHONPATH=REPO)
    out = subprocess.run(
        [sys.executable, "-c", _RESTART_WORKER, cache_dir],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_compiles_flat_across_process_restart(tmp_path):
    """ISSUE 12 acceptance: same TDL_COMPILE_CACHE_DIR across two processes
    ⇒ the second process records ZERO compiles per fn (every executable —
    the announced train step AND the helper jits — restores from disk),
    with cache-hit counters as the evidence."""
    cache_dir = str(tmp_path / "compile_cache")
    run1 = _run_restart_worker(cache_dir)
    assert run1["per_fn_compiles"].get("MultiLayerNetwork.train_step") == 1
    assert run1["misses"].get("MultiLayerNetwork.train_step") == 1
    assert run1["bytes"] > 0

    run2 = _run_restart_worker(cache_dir)
    assert run2["per_fn_compiles"] == {}, (
        f"process restart recompiled: {run2['per_fn_compiles']}")
    assert sum(run2["hits"].values()) > 0
    assert run2["hits"].get("MultiLayerNetwork.train_step", 0) >= 1
    assert run2["misses"] == {}


# ------------------------------------------------------- env contract


def test_supervisor_child_env_carries_stable_compile_cache_dir(tmp_path):
    from deeplearning4j_tpu.parallel.supervisor import GangSupervisor

    sup = GangSupervisor("tests.mp_workers:dp_train", n_processes=1,
                         workdir=str(tmp_path))
    env1 = sup._child_env(0, str(tmp_path / "hb_0"))
    env2 = sup._child_env(1, str(tmp_path / "hb_1"))
    expected = os.path.join(str(tmp_path), "compile_cache")
    # STABLE across attempts: incarnation N+1 must find incarnation N's
    # executables (flight dirs, by contrast, are per-attempt)
    assert env1[compile_cache.ENV_DIR] == expected
    assert env2[compile_cache.ENV_DIR] == expected
    assert sup.compile_cache_dir == expected
    # an operator override through extra_env wins
    sup2 = GangSupervisor("tests.mp_workers:dp_train", n_processes=1,
                          workdir=str(tmp_path / "w2"),
                          extra_env={compile_cache.ENV_DIR: "/elsewhere"})
    assert sup2._child_env(0, str(tmp_path / "hb"))[
        compile_cache.ENV_DIR] == "/elsewhere"
    assert sup2.compile_cache_dir == "/elsewhere"


def test_multiprocess_cpu_gang_skips_cache(tmp_path, monkeypatch):
    """Reloaded XLA:CPU executables carrying gloo collectives segfault
    (observed: respawned CPU gangs died -11/-6 on their first restored
    step), so the env contract is deliberately ignored on multi-process
    CPU — TPU gangs and single-process runs use the cache normally."""
    from jax._src import distributed

    monkeypatch.setenv(compile_cache.ENV_DIR, str(tmp_path / "cc"))
    monkeypatch.setattr(distributed.global_state, "client", object(),
                        raising=False)
    assert compile_cache.maybe_enable_from_env() is None
    assert not compile_cache.enabled()
    # same process, distributed torn down (single-process again): enabled
    monkeypatch.setattr(distributed.global_state, "client", None,
                        raising=False)
    try:
        assert compile_cache.maybe_enable_from_env() is not None
        assert compile_cache.enabled()
    finally:
        compile_cache.disable()


def test_env_enable_revoked_when_gang_turns_multiprocess(tmp_path,
                                                         monkeypatch):
    """The first net/executor can be built BEFORE jax.distributed
    initializes — the safety probe still answers 'safe' and the env var
    enables the cache. The next entry point after distributed init must
    REVOKE it: a respawned gang restoring XLA:CPU collective executables
    from that early enable segfaults (-11/-6 at the first restored step)."""
    from jax._src import distributed

    monkeypatch.setenv(compile_cache.ENV_DIR, str(tmp_path / "cc"))
    monkeypatch.setattr(distributed.global_state, "client", None,
                        raising=False)
    try:
        assert compile_cache.maybe_enable_from_env() is not None  # pre-init
        assert compile_cache.enabled()
        monkeypatch.setattr(distributed.global_state, "client", object(),
                            raising=False)
        assert compile_cache.maybe_enable_from_env() is None
        assert not compile_cache.enabled()
    finally:
        compile_cache.disable()


def test_explicit_enable_wins_over_env(tmp_path, monkeypatch):
    """An entry point's maybe_enable_from_env must NOT re-point a cache the
    serving builder (or operator) explicitly enabled — executables would be
    silently stranded in a directory a restarted replica never reads."""
    explicit = str(tmp_path / "explicit")
    monkeypatch.setenv(compile_cache.ENV_DIR, str(tmp_path / "env"))
    try:
        compile_cache.enable(explicit)
        assert compile_cache.maybe_enable_from_env() == \
            os.path.abspath(explicit)
        assert compile_cache.cache_dir() == os.path.abspath(explicit)
    finally:
        compile_cache.disable()


def test_server_builder_enables_explicit_cache_dir(tmp_path):
    from deeplearning4j_tpu.serving import JsonModelServer

    d = str(tmp_path / "serving_cache")
    try:
        server = (JsonModelServer.Builder(_tiny_net())
                  .compile_cache_dir(d).build())
        assert compile_cache.cache_dir() == os.path.abspath(d)
        assert os.path.isdir(d)
        assert server.warmup_all_buckets is None  # auto: cache on → ladder
    finally:
        compile_cache.disable()


# ------------------------------------------- warmup completeness satellite


def test_executor_warms_every_bucket_with_cache_present(enabled_cache):
    """Satellite: pre-ISSUE-12 only the smallest bucket was warmed and the
    first large coalesced batch ate a compile mid-traffic; with the cache
    enabled the whole ladder is warmed (cheap on cache hit)."""
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    from deeplearning4j_tpu.serving.executor import BatchingInferenceExecutor

    net = _tiny_net()
    pi = ParallelInference(net, batch_limit=16)
    warmed = []
    orig = pi.output_batched
    pi.output_batched = lambda xs: (warmed.append(
        sum(x.shape[0] for x in xs)), orig(xs))[1]
    ex = BatchingInferenceExecutor(
        parallel_inference=pi, max_batch_rows=64,
        warmup_input=np.zeros((1, 8), np.float32)).start()
    try:
        assert ex.wait_warm(120)
        assert warmed == bucket_ladder(64, min_bucket=16,
                                       multiple=pi._ndata)
    finally:
        ex.stop()


def test_executor_warms_smallest_bucket_without_cache():
    """Historical default preserved: no cache, no opt-in ⇒ one warmup
    forward (compiling the whole ladder up front would tax every cold
    start for buckets that may never arrive)."""
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    from deeplearning4j_tpu.serving.executor import BatchingInferenceExecutor

    net = _tiny_net()
    pi = ParallelInference(net, batch_limit=16)
    warmed = []
    orig = pi.output_batched
    pi.output_batched = lambda xs: (warmed.append(
        sum(x.shape[0] for x in xs)), orig(xs))[1]
    ex = BatchingInferenceExecutor(
        parallel_inference=pi, max_batch_rows=64,
        warmup_input=np.zeros((1, 8), np.float32)).start()
    try:
        assert ex.wait_warm(120)
        assert len(warmed) == 1
    finally:
        ex.stop()
