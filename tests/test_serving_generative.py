"""Continuous-batching generative serving (ISSUE 13 tentpole piece 2).

The executor contract under test: requests admit into free KV slots AT STEP
BOUNDARIES and retire the moment they finish — a short request riding next
to a long one never waits for the long one (the p99 lever), deadlines evict
mid-decode through the existing 504 path, and the decode loop's truth lands
in the ``tdl_decode_*`` families. A pure-python FakeSession keeps the
semantics tests fast; one end-to-end test runs the REAL transformer slot
pool through the HTTP server.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.monitoring import MetricsRegistry
from deeplearning4j_tpu.serving import (DeadlineExceededError,
                                        ExecutorClosedError,
                                        GenerativeInferenceExecutor,
                                        JsonModelClient, JsonModelServer,
                                        QueueFullError)


class FakeSession:
    """Deterministic slot-pool stand-in: every sequence emits
    ``prompt[-1] + 1, +2, ...``; ``step_delay`` simulates decode-step cost."""

    def __init__(self, slots=4, max_len=64, step_delay=0.0, eos_id=None):
        self.slots = slots
        self.max_len = max_len
        self.step_delay = step_delay
        self.eos_id = eos_id
        self._next = {}
        self.admit_log = []
        self.steps_run = 0

    @property
    def free_slots(self):
        return self.slots - len(self._next)

    def admit(self, prompt, max_new_tokens):
        prompt = np.asarray(prompt)
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError("prompt too long for the cache")
        if len(self._next) >= self.slots:
            raise RuntimeError("no free decode slot")
        slot = min(set(range(self.slots)) - set(self._next))
        first = int(prompt[-1]) + 1
        self._next[slot] = first + 1
        self.admit_log.append((slot, int(prompt[-1]), max_new_tokens))
        return slot, first

    def step(self):
        if self.step_delay:
            time.sleep(self.step_delay)
        self.steps_run += 1
        out = {s: t for s, t in self._next.items()}
        self._next = {s: t + 1 for s, t in self._next.items()}
        return out

    def release(self, slot):
        del self._next[slot]


def _counter_values(reg, name):
    m = reg.get(name)
    if m is None:
        return {}
    return {tuple(s["labels"].values()): s["value"]
            for s in m.snapshot()["series"]}


# ---------------------------------------------------------------- executor


def test_generation_completes_and_tokens_are_sequential():
    reg = MetricsRegistry()
    ex = GenerativeInferenceExecutor(FakeSession(), registry=reg).start()
    try:
        fut = ex.submit([3, 7], max_new_tokens=5)
        assert fut.wait(10.0) and fut.error is None
        np.testing.assert_array_equal(fut.result, [8, 9, 10, 11, 12])
        assert _counter_values(reg, "tdl_decode_admitted_total")[()] == 1
        assert _counter_values(reg, "tdl_decode_steps_total")[()] >= 4
        assert _counter_values(reg, "tdl_decode_tokens_total")[()] >= 5
    finally:
        ex.stop(drain=True)


def test_continuous_batching_short_request_overtakes_long():
    """The p99 claim itself: a short request admitted while a long decode is
    mid-flight finishes FIRST — nobody waits for the slowest batch member."""
    session = FakeSession(slots=2, step_delay=0.01)
    ex = GenerativeInferenceExecutor(session, continuous=True).start()
    try:
        long_fut = ex.submit([1], max_new_tokens=60)
        time.sleep(0.08)  # the long decode is well underway
        short_fut = ex.submit([1], max_new_tokens=3)
        assert short_fut.wait(10.0) and short_fut.error is None
        assert not long_fut.done  # the long request is STILL decoding
        assert long_fut.wait(10.0) and long_fut.error is None
        assert len(long_fut.result) == 60 and len(short_fut.result) == 3
        stats = ex.stats()
        assert stats["mean_slot_occupancy"] > 1.0  # they genuinely shared steps
    finally:
        ex.stop(drain=True)


def test_static_batching_mode_waits_for_slowest_member():
    """continuous=False is the measured strawman: admission only into an
    EMPTY pool, so a late short request waits for the running batch."""
    session = FakeSession(slots=2, step_delay=0.01)
    ex = GenerativeInferenceExecutor(session, continuous=False).start()
    try:
        long_fut = ex.submit([1], max_new_tokens=40)
        time.sleep(0.05)
        short_fut = ex.submit([1], max_new_tokens=2)
        assert long_fut.wait(10.0) and long_fut.error is None
        # the short request could not share the pool: it was admitted only
        # after the long batch drained
        assert short_fut.wait(10.0) and short_fut.error is None
        long_admit = session.admit_log[0]
        short_admit = session.admit_log[1]
        assert long_admit[2] == 40 and short_admit[2] == 2
        assert ex.stats()["mean_slot_occupancy"] <= 1.0
    finally:
        ex.stop(drain=True)


def test_deadline_evicts_mid_decode_and_frees_the_slot():
    reg = MetricsRegistry()
    session = FakeSession(slots=1, max_len=100_000, step_delay=0.02)
    ex = GenerativeInferenceExecutor(session, registry=reg).start()
    try:
        doomed = ex.submit([1], max_new_tokens=10_000, deadline_ms=120)
        assert doomed.wait(10.0)
        assert isinstance(doomed.error, DeadlineExceededError)
        assert "mid-decode" in str(doomed.error)
        # the slot freed at the eviction boundary: a new request completes
        nxt = ex.submit([5], max_new_tokens=2)
        assert nxt.wait(10.0) and nxt.error is None
        np.testing.assert_array_equal(nxt.result, [6, 7])
        evicted = _counter_values(reg, "tdl_decode_evicted_total")
        assert evicted[("deadline",)] == 1
        shed = _counter_values(reg, "tdl_inference_shed_total")
        assert shed[("decode_deadline",)] == 1
    finally:
        ex.stop(drain=True)


def test_eos_retires_immediately():
    session = FakeSession(slots=2, eos_id=10)
    ex = GenerativeInferenceExecutor(session).start()
    try:
        fut = ex.submit([7], max_new_tokens=50)  # emits 8, 9, 10=eos
        assert fut.wait(10.0) and fut.error is None
        np.testing.assert_array_equal(fut.result, [8, 9, 10])
    finally:
        ex.stop(drain=True)


def test_queue_full_and_submit_validation():
    session = FakeSession(slots=1, step_delay=0.05)
    ex = GenerativeInferenceExecutor(session, max_queue=1).start()
    try:
        running = ex.submit([1], max_new_tokens=50)
        time.sleep(0.05)  # it is decoding; the queue slot is free
        queued = ex.submit([2], max_new_tokens=2)
        with pytest.raises(QueueFullError):
            ex.submit([3], max_new_tokens=2)
        with pytest.raises(ValueError, match="token ids"):
            ex.submit([1.5], max_new_tokens=2)
        with pytest.raises(ValueError, match="1-D"):
            ex.submit(np.zeros((2, 3), np.int32), max_new_tokens=2)
        with pytest.raises(ValueError, match="max_new_tokens"):
            ex.submit([1], max_new_tokens=0)
        with pytest.raises(ValueError, match="KV cache"):
            ex.submit(list(range(60)), max_new_tokens=10)
        ex.stop(drain=True)  # drain completes both accepted requests
        assert running.done and running.error is None
        assert queued.done and queued.error is None
    finally:
        ex.stop(drain=True)


def test_submit_rejects_out_of_range_token_ids():
    """An id past the session's vocab (or negative / past int32) must be a
    400-class ValueError at admission — the embedding gather would clamp
    or wrap it into a plausible-looking 200 from the wrong row."""
    session = FakeSession(slots=1)
    session.vocab_size = 100
    ex = GenerativeInferenceExecutor(session).start()
    try:
        with pytest.raises(ValueError, match=r"token ids must be in \[0, 99\]"):
            ex.submit([150], max_new_tokens=1)
        with pytest.raises(ValueError, match="token ids must be in"):
            ex.submit([-5], max_new_tokens=1)
        fut = ex.submit([42], max_new_tokens=2)  # in range: serves fine
        assert fut.wait(10.0) and fut.error is None
    finally:
        ex.stop(drain=True)


def test_decode_step_failure_counts_evictions_and_serves_on():
    """A step() failure kills every rider: each one counts under
    tdl_decode_evicted_total (cache_lost when the session lost its KV
    cache, step_error otherwise) so stats()['evicted'] agrees with the
    number of killed generations whichever call faulted."""
    class FailingStep(FakeSession):
        fail_next = False

        def step(self):
            if self.fail_next:
                self.fail_next = False
                self._next = {}  # the pool's reset frees every slot
                err = RuntimeError("device fault mid-step; cache reset")
                err.all_sequences_lost = True
                raise err
            return super().step()

    reg = MetricsRegistry()
    session = FailingStep(slots=2, max_len=100_000, step_delay=0.01)
    ex = GenerativeInferenceExecutor(session, registry=reg).start()
    try:
        fut = ex.submit([1], max_new_tokens=10_000)
        time.sleep(0.05)  # decoding
        session.fail_next = True
        assert fut.wait(10.0)
        assert getattr(fut.error, "all_sequences_lost", False)
        evicted = _counter_values(reg, "tdl_decode_evicted_total")
        assert evicted[("cache_lost",)] == 1
        assert ex.stats()["evicted"] == 1
        nxt = ex.submit([7], max_new_tokens=2)  # not poisoned
        assert nxt.wait(10.0) and nxt.error is None
    finally:
        ex.stop(drain=True)


def test_warmup_step_failure_does_not_leak_the_slot():
    """A warmup whose decode step raises must still release its slot: _loop
    swallows the warmup error and serves on, and at slots=1 a leaked
    warmup slot would be a permanent no-admissions outage."""
    class FailFirstStep(FakeSession):
        def step(self):
            if self.steps_run == 0:
                self.steps_run += 1
                raise RuntimeError("injected warmup step failure")
            return super().step()

    session = FailFirstStep(slots=1)
    ex = GenerativeInferenceExecutor(session, registry=MetricsRegistry(),
                                     warmup_prompt=[1]).start()
    try:
        assert ex.wait_warm(10.0)
        assert session.free_slots == 1  # released despite the failed step
        fut = ex.submit([4], max_new_tokens=3)
        assert fut.wait(10.0) and fut.error is None
        assert fut.tokens == [5, 6, 7]
    finally:
        ex.stop(drain=True)


def test_cache_lost_fails_riders_and_executor_serves_on():
    """A session admit that fails with the ``all_sequences_lost`` marker
    (transformer.KvCacheLostError's duck-typed contract: the KV cache was
    reset, every in-flight sequence died with it) must fail the ACTIVE
    riders too — not leave them waiting for tokens from a zeroed cache —
    and the executor keeps serving afterwards."""
    class CacheLossy(FakeSession):
        lose_on_admit = None

        def admit(self, prompt, max_new_tokens):
            if self.lose_on_admit and len(self.admit_log) + 1 == self.lose_on_admit:
                self._next = {}  # the pool's reset frees every slot
                err = RuntimeError("device fault mid-prefill; cache reset")
                err.all_sequences_lost = True
                raise err
            return super().admit(prompt, max_new_tokens)

    reg = MetricsRegistry()
    session = CacheLossy(slots=2, max_len=100_000, step_delay=0.01)
    ex = GenerativeInferenceExecutor(session, registry=reg).start()
    try:
        rider = ex.submit([1], max_new_tokens=10_000)  # long-lived
        time.sleep(0.05)  # it is decoding in a slot
        session.lose_on_admit = 2
        victim = ex.submit([2], max_new_tokens=5)
        assert victim.wait(10.0) and victim.error is not None
        assert rider.wait(10.0) and rider.error is not None
        assert getattr(rider.error, "all_sequences_lost", False)
        evicted = _counter_values(reg, "tdl_decode_evicted_total")
        assert evicted[("cache_lost",)] == 1
        # the executor is not poisoned: the next request completes
        session.lose_on_admit = None
        fut = ex.submit([7], max_new_tokens=2)
        assert fut.wait(10.0) and fut.error is None
        assert fut.tokens == [8, 9]
    finally:
        ex.stop(drain=True)


def test_stop_without_drain_cancels_active_and_queued():
    session = FakeSession(slots=1, max_len=100_000, step_delay=0.02)
    ex = GenerativeInferenceExecutor(session, max_queue=4).start()
    active = ex.submit([1], max_new_tokens=10_000)
    time.sleep(0.05)
    queued = ex.submit([2], max_new_tokens=5)
    ex.stop(drain=False, timeout=10.0)
    assert active.wait(5.0) and isinstance(active.error, ExecutorClosedError)
    assert queued.wait(5.0) and isinstance(queued.error, ExecutorClosedError)


# ------------------------------------------------------------------- server


def _post_tokens(port, tokens, headers=None, timeout=15):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps(tokens).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def test_server_generative_mode_end_to_end():
    reg = MetricsRegistry()
    server = JsonModelServer(None, generative_session=FakeSession(),
                             default_max_new_tokens=4, registry=reg,
                             warmup_input=[1]).start()
    try:
        assert server.wait_ready(30.0)
        status, out = _post_tokens(server.port, [4, 9])
        assert status == 200
        assert out["output"] == [10, 11, 12, 13]
        # per-request budget via header
        status, out = _post_tokens(server.port, [4, 9],
                                   headers={"X-Max-New-Tokens": "2"})
        assert out["output"] == [10, 11]
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_tokens(server.port, [4, 9],
                         headers={"X-Max-New-Tokens": "zero"})
        assert ei.value.code == 400
        # non-integer payload is the caller's fault
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_tokens(server.port, [["no"]])
        assert ei.value.code == 400
        # float token ids 400 too — the wire deserializer must not silently
        # truncate them to int32 before the executor's validation
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_tokens(server.port, [4.5, 9.2])
        assert ei.value.code == 400
        codes = _counter_values(reg, "tdl_inference_requests_total")
        assert codes[("200",)] == 2
    finally:
        server.stop()


def test_server_generative_deadline_504():
    server = JsonModelServer(
        None, generative_session=FakeSession(max_len=100_000, step_delay=0.02),
        default_max_new_tokens=10_000, registry=MetricsRegistry()).start()
    try:
        assert server.wait_ready(30.0)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_tokens(server.port, [1],
                         headers={"X-Deadline-Ms": "150"})
        assert ei.value.code == 504
    finally:
        server.stop()


def test_server_generative_with_real_transformer_pool():
    """End to end against the REAL KV-cache slot pool: HTTP tokens in,
    greedy continuation out, identical to the offline generate() API."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig.tiny(
        causal=True, dropout=0.0, param_dtype=jnp.float32,
        compute_dtype=jnp.float32, attn_impl="xla", vocab_size=64,
        max_len=32, d_model=32, n_heads=2, n_layers=2, d_ff=64)
    params = tfm.init_params(jax.random.key(0), cfg)
    pool = tfm.DecodeSlotPool(params, cfg, slots=2)
    prompt = [3, 11, 7]
    expected = tfm.generate(params, [prompt], 5, cfg)[0]

    server = JsonModelServer(None, generative_session=pool,
                             default_max_new_tokens=5,
                             warmup_input=[1],
                             registry=MetricsRegistry()).start()
    try:
        assert server.wait_ready(60.0)
        client = JsonModelClient(port=server.port)
        out = client.predict(prompt)
        assert out == expected
    finally:
        server.stop()


def test_generative_request_span_carries_decode_timeline():
    """ISSUE 13: a sampled generative 200's span reconstructs queue →
    prefill → decode with the per-step timeline and step count."""
    from deeplearning4j_tpu.monitoring import flight
    from deeplearning4j_tpu.monitoring.flight import FlightRecorder

    rec = FlightRecorder(proc="gen-span-test", capacity=1024)
    flight.set_flight_recorder(rec)
    server = JsonModelServer(None, generative_session=FakeSession(),
                             default_max_new_tokens=4,
                             registry=MetricsRegistry()).start()
    try:
        assert server.wait_ready(30.0)
        _post_tokens(server.port, [2],
                     headers={"X-Request-Id": "gen-span-1"})
        spans = [e for e in rec.events() if e["kind"] == "request_span"
                 and e.get("request_id") == "gen-span-1"]
        assert len(spans) == 1
        ev = spans[0]
        assert ev["outcome"] == "ok" and ev["code"] == 200
        assert set(ev["phases"]) == {"queue", "prefill", "decode",
                                     "serialize"}
        assert ev["steps"] == 3  # 4 tokens = 1 prefill + 3 decode steps
        assert len(ev["step_ms"]) == 3
    finally:
        server.stop()
        flight.set_flight_recorder(None)
