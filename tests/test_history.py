"""Metrics history ring (ISSUE 11 tentpole, layer 1).

Ring semantics (eviction order, throttling), spool + newest-per-proc merge,
window queries (baselines, born-mid-window zeroing), the shared quantile /
delta math every windowed consumer uses, and the `/history` endpoint with
family/label/window filters.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from deeplearning4j_tpu.monitoring import HistoryRing, HistoryView, MetricsRegistry
from deeplearning4j_tpu.monitoring import history


# ------------------------------------------------------------------- ring


def test_ring_appends_and_evicts_oldest_first():
    reg = MetricsRegistry()
    g = reg.gauge("tdl_test_gauge")
    ring = HistoryRing(registry=reg, capacity=4, interval=0.0, proc="p0")
    for i in range(7):
        g.set(i)
        assert ring.sample(force=True) is not None
    assert len(ring) == 4
    vals = [s["snapshot"]["tdl_test_gauge"]["series"][0]["value"]
            for s in ring.samples()]
    # oldest evicted first: the ring holds the LAST four samples, in order
    assert vals == [3.0, 4.0, 5.0, 6.0]
    ts = [s["t"] for s in ring.samples()]
    assert ts == sorted(ts)


def test_ring_interval_throttles_and_force_overrides():
    ring = HistoryRing(registry=MetricsRegistry(), interval=60.0)
    assert ring.sample() is not None
    assert ring.sample() is None          # throttled
    assert ring.sample(force=True) is not None
    assert len(ring) == 2


def test_ring_spool_throttled_separately_from_sampling(tmp_path):
    """Disk spooling rewrites the whole ring, so it must NOT happen on
    every in-memory sample — the hot-path hook samples every couple of
    seconds, the spool rewrites an order of magnitude less often.
    force=True bypasses both throttles (fault injectors, tests)."""
    ring = HistoryRing(registry=MetricsRegistry(), interval=0.0,
                       proc="p0", directory=str(tmp_path),
                       spool_interval=3600.0)
    ring.sample()  # first sample: spools (no previous flush)
    first = history.read_rings(str(tmp_path))[0]
    assert len(first["samples"]) == 1
    ring.sample()  # in-memory only: spool throttled
    assert len(ring) == 2
    assert len(history.read_rings(str(tmp_path))[0]["samples"]) == 1
    ring.sample(force=True)  # force bypasses the spool throttle
    assert len(history.read_rings(str(tmp_path))[0]["samples"]) == 3


def test_ring_window_filter():
    ring = HistoryRing(registry=MetricsRegistry(), interval=0.0)
    ring.sample(force=True)
    time.sleep(0.05)
    ring.sample(force=True)
    now = time.monotonic()
    assert len(ring.samples()) == 2
    assert len(ring.samples(window=0.03, now=now)) == 1
    assert len(ring.samples(window=10.0, now=now)) == 2


# ------------------------------------------------------- spools and merge


def test_spool_roundtrip_and_newest_per_proc_dedup(tmp_path):
    reg = MetricsRegistry()
    reg.gauge("tdl_test_gauge").set(1)
    old = HistoryRing(registry=reg, interval=0.0, proc="rank0",
                      directory=str(tmp_path))
    old.sample(force=True)
    time.sleep(0.02)
    # a "respawned incarnation" under the same proc name, different pid is
    # simulated by pointing a second ring at the same dir with a tweaked
    # path via proc — same proc → newest wins
    newer = HistoryRing(registry=reg, interval=0.0, proc="rank0",
                        directory=str(tmp_path))
    # give the two rings distinct files the way distinct pids would
    newer_path = str(tmp_path / "tdl_history_rank0.999999.json")
    payload = {"proc": "rank0", "rank": 0, "pid": 999999,
               "wall": time.time() + 10, "samples": newer.samples()}
    with open(newer_path, "w") as f:
        json.dump(payload, f)
    rings = history.read_rings(str(tmp_path))
    assert len(rings) == 1  # newest per proc
    assert rings[0]["pid"] == 999999

    # torn/corrupt/non-dict files are skipped, not raised
    (tmp_path / "tdl_history_bad.1.json").write_text("{torn")
    (tmp_path / "tdl_history_list.2.json").write_text("[1, 2]")
    assert len(history.read_rings(str(tmp_path))) == 1


def test_merged_samples_local_ring_wins_over_its_own_spool(tmp_path):
    reg = MetricsRegistry()
    ring = HistoryRing(registry=reg, interval=0.0, proc="serve0",
                       directory=str(tmp_path))
    ring.sample(force=True)
    ring.sample(force=True)  # ring spooled itself: same proc on disk
    other = HistoryRing(registry=MetricsRegistry(), interval=0.0,
                        proc="rank1", directory=str(tmp_path))
    other.sample(force=True)
    merged = history.merged_samples(str(tmp_path), ring)
    procs = [s["proc"] for s in merged]
    # serve0 appears exactly twice (from the live ring, NOT double-counted
    # with its spool), rank1 once from its spool
    assert procs.count("serve0") == 2 and procs.count("rank1") == 1
    ts = [s["t"] for s in merged]
    assert ts == sorted(ts)
    view = HistoryView(ring=ring, directory=str(tmp_path))
    assert len(view.samples()) == 3


# ------------------------------------------------------------ window math


def test_window_points_baseline_and_born_mid_window():
    snapA = {"tdl_c": {"type": "counter", "series": [
        {"labels": {"r": "a"}, "value": 10.0}]}}
    snapB = {"tdl_c": {"type": "counter", "series": [
        {"labels": {"r": "a"}, "value": 25.0},
        {"labels": {"r": "b"}, "value": 7.0}]}}
    samples = [
        {"t": 0.0, "proc": "p", "snapshot": snapA},    # before the window
        {"t": 50.0, "proc": "p", "snapshot": snapA},   # window baseline edge
        {"t": 90.0, "proc": "p", "snapshot": snapB},
    ]
    pts = history.window_points(samples, "tdl_c", window=60, now=100.0,
                                baseline=True)
    a = pts[("p", (("r", "a"),))]
    # nearest pre-window point (t=0 is older than t=50? no — t=50 is IN
    # window [40, 100]; t=0 is the pre-window baseline)
    assert [t for t, _ in a] == [0.0, 50.0, 90.0]
    b = pts[("p", (("r", "b"),))]
    # series b born mid-window: synthetic zero at the earliest in-window
    # sample time, so its 7 events count
    assert b[0] == (50.0, {"value": 0.0, "count": 0, "sum": 0.0,
                           "buckets": {}, "inf": 0})
    assert history.counter_increase(b[0][1]["value"], b[-1][1]["value"]) == 7.0


def test_counter_increase_handles_reset():
    assert history.counter_increase(10, 25) == 15
    assert history.counter_increase(100, 30) == 30  # restart: count from 0


def test_histogram_delta_and_merge_and_quantile():
    first = {"count": 100, "sum": 5.0, "buckets": {"0.1": 100, "0.5": 0}, "inf": 0}
    last = {"count": 130, "sum": 23.0, "buckets": {"0.1": 110, "0.5": 20}, "inf": 0}
    d = history.histogram_delta(first, last)
    assert d == {"buckets": {"0.1": 10, "0.5": 20}, "inf": 0,
                 "sum": 18.0, "count": 30}
    # restart (count went down) → delta is the whole new histogram
    reset = history.histogram_delta(last, first)
    assert reset["count"] == 100 and reset["buckets"]["0.1"] == 100

    merged = history.merge_histograms([d, d])
    assert merged["count"] == 60 and merged["buckets"]["0.5"] == 40

    # quantile: 10 in (0, 0.1], 20 in (0.1, 0.5] → p50 rank 15 → 5/20 into
    # the second bucket → 0.1 + 0.4 * 0.25 = 0.2
    assert history.quantile_from_buckets(d["buckets"], d["inf"], 0.5) \
        == pytest.approx(0.2)
    # all mass in +Inf reports the highest finite edge
    assert history.quantile_from_buckets({"0.1": 0, "0.5": 0}, 5, 0.99) == 0.5
    assert history.quantile_from_buckets({}, 0, 0.99) is None


def test_count_at_or_below_interpolates():
    buckets = {"0.1": 10, "0.5": 20, "1.0": 0}
    assert history.count_at_or_below(buckets, 0.1) == 10
    assert history.count_at_or_below(buckets, 0.5) == 30
    # halfway through the (0.1, 0.5] bucket → 10 + 20 * 0.5
    assert history.count_at_or_below(buckets, 0.3) == pytest.approx(20.0)
    assert history.count_at_or_below(buckets, 2.0) == 30


# -------------------------------------------------------- env-driven hook


def test_maybe_sample_env_contract(tmp_path, monkeypatch):
    import importlib

    monkeypatch.setenv(history.ENV_DIR, str(tmp_path))
    monkeypatch.setenv(history.ENV_INTERVAL, "0")
    # reset the cached module ring so the new env contract is picked up
    history._ring = None
    history._ring_key = None
    try:
        history.maybe_sample(force=True)
        history.maybe_sample(force=True)
        rings = history.read_rings(str(tmp_path))
        assert len(rings) == 1
        assert len(rings[0]["samples"]) == 2
    finally:
        history._ring = None
        history._ring_key = None


def test_maybe_spool_drives_history_hook(tmp_path, monkeypatch):
    """aggregate.maybe_spool is the one hook site every process kind
    already calls — TDL_HISTORY_DIR alone (no metrics spool dir) must be
    enough to accrue history."""
    from deeplearning4j_tpu.monitoring import aggregate

    monkeypatch.delenv(aggregate.ENV_DIR, raising=False)
    monkeypatch.setenv(history.ENV_DIR, str(tmp_path))
    monkeypatch.setenv(history.ENV_INTERVAL, "0")
    history._ring = None
    history._ring_key = None
    try:
        aggregate.maybe_spool(force=True)
        assert len(history.read_rings(str(tmp_path))) == 1
    finally:
        history._ring = None
        history._ring_key = None


# ------------------------------------------------------- /history endpoint


def test_history_endpoint_filters(tmp_path):
    from deeplearning4j_tpu.ui import UIServer

    reg = MetricsRegistry()
    g = reg.gauge("tdl_test_gauge", labels=("shard",))
    h = reg.histogram("tdl_test_hist", buckets=(0.1, 1.0))
    # long interval: the endpoint's per-request sample() is throttled, so
    # the point series below stays exactly the two forced samples
    ring = HistoryRing(registry=reg, interval=3600.0, proc="serve0")
    g.labels("a").set(1)
    g.labels("b").set(9)
    h.observe(0.05)
    ring.sample(force=True)
    g.labels("a").set(2)
    ring.sample(force=True)

    server = UIServer(port=0)
    try:
        server.attach_registry(reg)
        server.attach_history(ring)

        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}{path}", timeout=10) as r:
                return json.loads(r.read())

        summary = get("/history")
        assert summary["procs"] == ["serve0"]
        assert "tdl_test_gauge" in summary["families"]
        assert summary["samples"] >= 2

        fam = get("/history?family=tdl_test_gauge&label.shard=a")
        assert fam["type"] == "gauge"
        vals = [p["value"] for p in fam["points"]]
        assert vals == [1.0, 2.0]
        assert all(p["labels"] == {"shard": "a"} for p in fam["points"])
        assert all(p["proc"] == "serve0" for p in fam["points"])

        hist = get("/history?family=tdl_test_hist")
        assert hist["type"] == "histogram"
        assert all("buckets" in p for p in hist["points"])

        # a tiny window excludes old samples (endpoint samples the ring per
        # request, so at least the fresh sample is inside)
        recent = get("/history?family=tdl_test_gauge&window=0.0001")
        assert len(recent["points"]) <= len(fam["points"])

        none = get("/history?family=tdl_nope")
        assert none["points"] == [] and none["type"] is None
    finally:
        server.stop()


def test_history_endpoint_404_without_attachment():
    from deeplearning4j_tpu.ui import UIServer

    server = UIServer(port=0)
    try:
        server.attach_registry(MetricsRegistry())
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/history", timeout=10)
        assert ei.value.code == 404
    finally:
        server.stop()
