"""UI stats pipeline + JSON serving tests (SURVEY §2.4 C14, §2.6 S7, §5.1)."""

import json
import time
import urllib.request

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.serving import JsonModelClient, JsonModelServer
from deeplearning4j_tpu.ui import (
    FileStatsStorage,
    InMemoryStatsStorage,
    ProfilingListener,
    StatsListener,
    UIServer,
)
from deeplearning4j_tpu.ui.profiling import ProfileAnalyzer


def _net():
    conf = (NeuralNetConfiguration.Builder().seed(5).updater(Adam(0.01)).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _fit(net, listeners, steps=12):
    net.add_listeners(*listeners)
    rs = np.random.RandomState(0)
    X = rs.randn(16, 4).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 16)]
    for _ in range(steps):
        net._fit_batch(DataSet(X, Y))


def test_stats_listener_records():
    storage = InMemoryStatsStorage()
    net = _net()
    _fit(net, [StatsListener(storage, frequency=2)])
    recs = storage.records()
    assert len(recs) == 6
    r = recs[-1]
    assert "score" in r and "params" in r and "update_ratios" in r
    assert "0/W" in r["params"] and r["params"]["0/W"]["std"] > 0
    assert r["update_ratios"]["1/W"] > 0  # params actually moving


def test_file_stats_storage_roundtrip(tmp_path):
    p = str(tmp_path / "stats.jsonl")
    storage = FileStatsStorage(p)
    storage.put_record({"session": "s1", "iteration": 1, "score": 0.5})
    storage.put_record({"session": "s2", "iteration": 2, "score": 0.4})
    assert len(storage.records()) == 2
    assert storage.records("s1")[0]["score"] == 0.5
    assert storage.session_ids() == ["s1", "s2"]


def test_ui_server_endpoints():
    storage = InMemoryStatsStorage()
    net = _net()
    _fit(net, [StatsListener(storage, frequency=1)])
    server = UIServer(port=0)
    server.attach(storage)
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/data", timeout=10) as r:
            d = json.loads(r.read())
        assert d["records"] == 12 and len(d["score"]) == 12
        with urllib.request.urlopen(base + "/", timeout=10) as r:
            assert b"Training overview" in r.read()
    finally:
        server.stop()


def test_profiling_listener_and_analyzer(tmp_path):
    p = str(tmp_path / "trace.json")
    net = _net()
    lst = ProfilingListener(p)
    _fit(net, [lst], steps=6)
    lst.flush()
    trace = ProfileAnalyzer.load(p)
    assert len(trace["traceEvents"]) == 5  # N steps -> N-1 complete events
    s = ProfileAnalyzer.summarize(trace)
    assert s["events"] == 5 and s["mean_us"] > 0
    cmp = ProfileAnalyzer.compare(trace, trace)
    assert abs(cmp["mean_speedup"] - 1.0) < 1e-9


def test_json_server_status_codes_and_client_error_surface():
    """ISSUE 3 satellite: 400 is reserved for malformed payloads, 500 for
    internal model failures, and the client surfaces the server's structured
    JSON error instead of urllib's bare HTTPError."""
    import urllib.error

    class BoomModel:
        def output(self, x):
            raise RuntimeError("updater state poisoned")

    server = JsonModelServer(BoomModel()).start()
    try:
        url = f"http://127.0.0.1:{server.port}/predict"

        # malformed JSON body → 400 with a structured error
        req = urllib.request.Request(
            url, data=b"{not json", headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=10)
            raise AssertionError("expected HTTPError")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "error" in json.loads(e.read())

        # valid payload, model raises → 500 (internal), not 400
        req = urllib.request.Request(
            url, data=b"[[1.0, 2.0]]", headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=10)
            raise AssertionError("expected HTTPError")
        except urllib.error.HTTPError as e:
            assert e.code == 500
            assert "updater state poisoned" in json.loads(e.read())["error"]

        # the client turns the HTTPError into the server's message
        client = JsonModelClient(port=server.port)
        try:
            client.predict([[1.0, 2.0]])
            raise AssertionError("expected RuntimeError")
        except RuntimeError as e:
            assert "500" in str(e) and "updater state poisoned" in str(e)

        # undecodable payload stays a client error (400) end to end
        try:
            client.predict(["not", "numbers"])
            raise AssertionError("expected RuntimeError")
        except RuntimeError as e:
            assert "400" in str(e)
    finally:
        server.stop()


def test_json_model_server_roundtrip():
    net = _net()
    server = JsonModelServer.Builder(net).port(0).build().start()
    try:
        client = JsonModelClient(port=server.port)
        x = np.random.RandomState(1).randn(3, 4).astype(np.float32)
        out = np.asarray(client.predict(x))
        ref = net.output(x).numpy()
        np.testing.assert_allclose(out, ref, atol=1e-5)
        # malformed input -> HTTP error, server stays alive
        try:
            client.predict(["not", "numbers"])
            raised = False
        except Exception:
            raised = True
        assert raised
        out2 = np.asarray(client.predict(x))
        np.testing.assert_allclose(out2, ref, atol=1e-5)
    finally:
        server.stop()


def test_device_profiler_captures_xplane(tmp_path):
    """SURVEY §5.1: device-level XPlane capture (the chrome-trace listener is
    host-side only; this is the on-device tier)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ui.profiling import DeviceProfiler

    prof = DeviceProfiler(str(tmp_path / "prof"))
    with prof:
        with DeviceProfiler.annotate("matmul_region"):
            a = jnp.ones((64, 64))
            jax.block_until_ready(jax.jit(lambda x: x @ x)(a))
    files = prof.trace_files()
    assert files, "no .xplane.pb produced"


def test_ui_model_graph_tab():
    """C14 model-graph tier: /model/graph serves the attached net topology."""
    import json
    import urllib.request

    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.ui.server import UIServer, model_graph_json

    conf = (NeuralNetConfiguration.Builder().seed(0).updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()

    g = model_graph_json(net)
    assert [n["type"] for n in g["nodes"]] == ["Input", "DenseLayer", "OutputLayer"]
    assert g["nodes"][1]["params"] == 4 * 8 + 8
    assert len(g["edges"]) == 2

    srv = UIServer(port=0)
    try:
        srv.attach_model(net)
        port = srv.port
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/model/graph") as r:
            got = json.loads(r.read())
        assert got == g
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/train/model") as r:
            html = r.read().decode()
        assert "DenseLayer" in html and "Model graph" in html
    finally:
        srv.stop()


def test_remote_stats_routing():
    """RemoteUIStatsStorageRouter → UIServer /remoteReceive → same storage
    the dashboard reads (VERDICT r3 weak #7: remote stats routing)."""
    from deeplearning4j_tpu.ui import RemoteUIStatsStorageRouter

    storage = InMemoryStatsStorage()
    server = UIServer(port=0)
    server.attach(storage)
    try:
        router = RemoteUIStatsStorageRouter(f"http://127.0.0.1:{server.port}")
        router.put_record({"session": "remote", "iteration": 1, "score": 0.9})
        router.put_record({"session": "remote", "iteration": 2, "score": 0.7})
        assert router.flush(timeout=10)  # posting is async (daemon thread)
        recs = storage.records("remote")
        assert [r["score"] for r in recs] == [0.9, 0.7]
        # the dashboard data endpoint sees the remotely-routed records
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/data", timeout=10) as r:
            d = json.loads(r.read())
        assert d["records"] == 2
        # router is write-only by design
        import pytest as _pytest
        with _pytest.raises(NotImplementedError):
            router.records()
        assert router.dropped == 0
    finally:
        server.stop()


def test_remote_router_drops_when_unreachable():
    from deeplearning4j_tpu.ui import RemoteUIStatsStorageRouter

    router = RemoteUIStatsStorageRouter("http://127.0.0.1:1", retry_count=2,
                                        retry_backoff_ms=1)
    t0 = time.perf_counter()
    router.put_record({"score": 1.0})  # must not raise / stall
    assert time.perf_counter() - t0 < 0.5  # backoff happens OFF-thread
    assert router.flush(timeout=10)
    assert router.dropped == 1
    router.close()


def test_arbiter_tab():
    """A2 tail: the arbiter UI tab renders an OptimizationResult."""
    from deeplearning4j_tpu.arbiter.optimize import OptimizationResult

    res = OptimizationResult(
        best_candidate={"lr": 0.01}, best_score=0.12, best_index=1,
        all_results=[({"lr": 0.1, "__id__": 0}, 0.5),
                     ({"lr": 0.01, "__id__": 1}, 0.12)])
    server = UIServer(port=0)
    server.attach_arbiter(res)
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/arbiter/data", timeout=10) as r:
            d = json.loads(r.read())
        assert d["best_score"] == 0.12 and len(d["trials"]) == 2
        assert "__id__" not in d["trials"][0]["candidate"]
        with urllib.request.urlopen(base + "/arbiter", timeout=10) as r:
            page = r.read().decode()
        assert "2 trials" in page and "0.12" in page
    finally:
        server.stop()


def test_layer_drilldown_endpoints():
    """Per-layer histogram time-series drilldown (r5: VERDICT r4 weak #8):
    /layers lists parameters, /layer/data serves mean/std/min/max + ratio +
    histogram series, /train/layer renders the page."""
    storage = InMemoryStatsStorage()
    net = _net()
    _fit(net, [StatsListener(storage, frequency=2,
                             collect_histograms=True, histogram_bins=8)])
    server = UIServer(port=0)
    server.attach(storage)
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/layers", timeout=10) as r:
            keys = json.loads(r.read())
        assert "0/W" in keys and "1/b" in keys
        with urllib.request.urlopen(
                base + "/layer/data?name=0/W", timeout=10) as r:
            d = json.loads(r.read())
        assert d["name"] == "0/W"
        assert len(d["iters"]) >= 5
        assert len(d["mean"]) == len(d["iters"]) == len(d["std"])
        assert all(lo <= m <= hi for lo, m, hi
                   in zip(d["min"], d["mean"], d["max"]))
        # ratio present from the second record on (log10, finite)
        assert any(v is not None for v in d["ratio"])
        h = d["hist"]
        assert len(h["counts"]) == len(h["iters"]) >= 5
        assert len(h["counts"][0]) == 8 and h["lo"] < h["hi"]
        assert sum(h["counts"][0]) == 4 * 8  # every weight binned
        # per-record ranges travel with the counts (columns realign on the
        # global axis client-side; r5 review finding)
        assert len(h["los"]) == len(h["his"]) == len(h["iters"])
        assert all(h["lo"] <= lo < hi <= h["hi"]
                   for lo, hi in zip(h["los"], h["his"]))
        with urllib.request.urlopen(
                base + "/train/layer?name=0/W", timeout=10) as r:
            page = r.read().decode()
        assert "histogram over time" in page
    finally:
        server.stop()


def test_layer_data_tolerates_pre_r5_histogram_lists():
    """Old FileStatsStorage JSONL rows stored bare counts lists; the
    drilldown endpoint must serve them, not 500 (r5 review finding)."""
    storage = InMemoryStatsStorage()
    storage.put_record({
        "session": "s", "iteration": 0, "epoch": 0, "time": 0.0,
        "score": 1.0,
        "params": {"0/W": {"mean": 0.0, "std": 1.0, "min": -2.0, "max": 2.0}},
        "histograms": {"0/W": [1, 2, 3, 2]},
    })
    server = UIServer(port=0)
    server.attach(storage)
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/layer/data?name=0/W", timeout=10) as r:
            d = json.loads(r.read())
        assert d["hist"]["counts"] == [[1, 2, 3, 2]]
        assert d["hist"]["los"] == [-2.0] and d["hist"]["his"] == [2.0]
    finally:
        server.stop()


def test_layer_data_sanitizes_nonfinite_and_unions_layers():
    """Divergence writes NaN stats; /layer/data must emit strict JSON
    (null, not the NaN token) and /layers must union across records
    (r5 review findings)."""
    storage = InMemoryStatsStorage()
    storage.put_record({
        "session": "s", "iteration": 0, "epoch": 0, "time": 0.0, "score": 1.0,
        "params": {"0/W": {"mean": float("nan"), "std": float("inf"),
                           "min": -1.0, "max": 1.0}},
        "update_ratios": {"0/W": float("nan")},
    })
    storage.put_record({"session": "s", "iteration": 1, "epoch": 0,
                        "time": 1.0, "score": 2.0})  # no params at all
    server = UIServer(port=0)
    server.attach(storage)
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/layers", timeout=10) as r:
            assert json.loads(r.read()) == ["0/W"]  # union, not last record
        with urllib.request.urlopen(base + "/layer/data?name=0/W", timeout=10) as r:
            raw = r.read().decode()
        assert "NaN" not in raw and "Infinity" not in raw
        d = json.loads(raw)
        assert d["mean"] == [None] and d["std"] == [None]
        assert d["ratio"] == [None] and d["min"] == [-1.0]
    finally:
        server.stop()
