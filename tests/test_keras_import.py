"""Keras import golden conformance: imported nets must reproduce the real
Keras model's forward activations (SURVEY §4.2 golden-file pattern — "the
single most valuable testing idea"; here the goldens are generated live by
Keras itself rather than stored, which is strictly stronger).
"""

import numpy as np
import pytest

keras = pytest.importorskip("keras")
from keras import layers  # noqa: E402

from deeplearning4j_tpu.modelimport import KerasModelImport  # noqa: E402
from deeplearning4j_tpu.modelimport.keras_import import KerasImportError  # noqa: E402


def _save(model, tmp_path, name="m.h5"):
    p = str(tmp_path / name)
    model.save(p)
    return p


def _assert_matches(net, x_keras, y_keras, to_ours):
    got = np.asarray(net.output(to_ours(x_keras)).numpy())
    np.testing.assert_allclose(got, y_keras, rtol=1e-4, atol=1e-5)


class TestSequentialImport:
    def test_dense_mlp_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((12,)),
            layers.Dense(32, activation="relu"),
            layers.Dense(16, activation="tanh"),
            layers.Dropout(0.5),
            layers.Dense(5, activation="softmax"),
        ])
        x = np.random.RandomState(0).randn(6, 12).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_sequential(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a)

    def test_cnn_golden(self, tmp_path):
        """Conv/pool/flatten/dense with the NHWC→NCHW and flatten-order
        kernel permutation — the layout-sensitive path."""
        m = keras.Sequential([
            keras.Input((10, 8, 3)),
            layers.Conv2D(6, 3, activation="relu", padding="valid"),
            layers.MaxPooling2D(2),
            layers.Conv2D(4, 3, activation="relu", padding="same"),
            layers.Flatten(),
            layers.Dense(7, activation="softmax"),
        ])
        x = np.random.RandomState(1).randn(4, 10, 8, 3).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a.transpose(0, 3, 1, 2))  # NHWC→NCHW

    def test_batchnorm_inference_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((8, 8, 2)),
            layers.Conv2D(4, 3),
            layers.BatchNormalization(),
            layers.Activation("relu"),
            layers.GlobalAveragePooling2D(),
            layers.Dense(3),
        ])
        # push the BN moving stats away from init so the test is meaningful
        m.layers[1].set_weights([
            np.random.RandomState(2).rand(4).astype(np.float32) + 0.5,
            np.random.RandomState(3).randn(4).astype(np.float32),
            np.random.RandomState(4).randn(4).astype(np.float32),
            np.random.RandomState(5).rand(4).astype(np.float32) + 0.5,
        ])
        x = np.random.RandomState(6).randn(5, 8, 8, 2).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a.transpose(0, 3, 1, 2))

    def test_lstm_golden(self, tmp_path):
        """LSTM gate-order remap + [B,T,F]→[B,F,T] layout + return_sequences
        False → LastTimeStep expansion."""
        m = keras.Sequential([
            keras.Input((9, 5)),
            layers.LSTM(8, return_sequences=True),
            layers.LSTM(6),
            layers.Dense(4, activation="softmax"),
        ])
        x = np.random.RandomState(7).randn(3, 9, 5).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a.transpose(0, 2, 1))  # [B,T,F]→[B,F,T]

    def test_dense_then_activation_folds_into_output(self, tmp_path):
        """Dense -> Activation('softmax') tail: activation folds into the
        OutputLayer so the imported net both predicts AND fits."""
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.nn.conf import OutputLayer

        m = keras.Sequential([
            keras.Input((6,)),
            layers.Dense(8, activation="relu"),
            layers.Dense(3),
            layers.Activation("softmax"),
        ])
        x = np.random.RandomState(11).randn(4, 6).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_sequential(_save(m, tmp_path))
        out = net.conf.layers[-1]
        assert isinstance(out, OutputLayer) and out.activation == "softmax"
        assert out.loss == "mcxent"
        _assert_matches(net, x, y, lambda a: a)
        yl = np.eye(3, dtype=np.float32)[np.random.RandomState(0).randint(0, 3, 4)]
        net._fit_batch(DataSet(x, yl))  # fit works (compute_loss exists)

    def test_imported_net_is_trainable(self, tmp_path):
        from deeplearning4j_tpu.data.dataset import DataSet

        m = keras.Sequential([
            keras.Input((6,)),
            layers.Dense(16, activation="relu"),
            layers.Dense(2, activation="softmax"),
        ])
        net = KerasModelImport.import_sequential(_save(m, tmp_path))
        rs = np.random.RandomState(0)
        x = rs.randn(32, 6).astype(np.float32)
        yl = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 32)]
        s0 = None
        for _ in range(30):
            net._fit_batch(DataSet(x, yl))
            if s0 is None:
                s0 = net.score_
        assert net.score_ < s0, (s0, net.score_)


class TestFunctionalImport:
    def test_functional_branch_merge_golden(self, tmp_path):
        inp = keras.Input((10,))
        a = layers.Dense(8, activation="relu", name="a")(inp)
        b = layers.Dense(8, activation="tanh", name="b")(inp)
        add = layers.Add(name="add")([a, b])
        cat = layers.Concatenate(name="cat")([a, add])
        out = layers.Dense(3, activation="softmax", name="out")(cat)
        m = keras.Model(inp, out)
        x = np.random.RandomState(8).randn(5, 10).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        got = np.asarray(net.output(x)[0].numpy())
        np.testing.assert_allclose(got, y, rtol=1e-4, atol=1e-5)

    def test_functional_lstm_expansion_wiring(self, tmp_path):
        inp = keras.Input((7, 4))
        h = layers.LSTM(6, name="l")(inp)  # expands to LSTM + LastTimeStep
        out = layers.Dense(2, name="o")(h)
        m = keras.Model(inp, out)
        x = np.random.RandomState(9).randn(4, 7, 4).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        got = np.asarray(net.output(x.transpose(0, 2, 1))[0].numpy())
        np.testing.assert_allclose(got, y, rtol=1e-4, atol=1e-5)


class TestImportErrors:
    def test_unsupported_layer_raises(self, tmp_path):
        m = keras.Sequential([keras.Input((4, 4, 1)), layers.ConvLSTM1D(2, 3)])
        with pytest.raises(KerasImportError, match="ConvLSTM1D"):
            KerasModelImport.import_model(_save(m, tmp_path))

    def test_keras_zip_rejected_with_hint(self, tmp_path):
        m = keras.Sequential([keras.Input((4,)), layers.Dense(2)])
        p = str(tmp_path / "m.keras")
        m.save(p)
        with pytest.raises((KerasImportError, OSError)):
            KerasModelImport.import_model(p)


class TestWave2Mappers:
    """r4 mapper breadth (VERDICT r3 missing #4): Embedding, GRU, SimpleRNN,
    Bidirectional, Separable/DepthwiseConv2D, UpSampling/ZeroPadding/Cropping,
    Reshape/Permute/RepeatVector, Conv1D/Pooling1D, custom-layer registry."""

    def _seq_matches(self, net, x_ours, y_keras, rtol=1e-4):
        got = np.asarray(net.output(x_ours).numpy())
        np.testing.assert_allclose(got, y_keras.transpose(0, 2, 1),
                                   rtol=rtol, atol=1e-5)

    def test_embedding_gru_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((7,)),
            layers.Embedding(20, 8),
            layers.GRU(6, return_sequences=True),
        ])
        x = np.random.RandomState(0).randint(0, 20, (4, 7))
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        self._seq_matches(net, x.astype(np.float32), y)

    def test_gru_no_reset_after_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((5, 4)),
            layers.GRU(6, reset_after=False),
            layers.Dense(3, activation="softmax"),
        ])
        x = np.random.RandomState(1).randn(3, 5, 4).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a.transpose(0, 2, 1))

    def test_simplernn_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((6, 3)),
            layers.SimpleRNN(5, return_sequences=True),
        ])
        x = np.random.RandomState(2).randn(2, 6, 3).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        self._seq_matches(net, x.transpose(0, 2, 1), y)

    def test_bidirectional_lstm_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((6, 4)),
            layers.Bidirectional(layers.LSTM(3, return_sequences=True)),
        ])
        x = np.random.RandomState(3).randn(2, 6, 4).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        self._seq_matches(net, x.transpose(0, 2, 1), y)

    def test_bidirectional_no_sequences_raises(self, tmp_path):
        m = keras.Sequential([
            keras.Input((6, 4)),
            layers.Bidirectional(layers.LSTM(3)),
        ])
        with pytest.raises(KerasImportError, match="return_sequences"):
            KerasModelImport.import_model(_save(m, tmp_path))

    def test_separable_depthwise_conv_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((8, 8, 3)),
            layers.SeparableConv2D(5, 3, padding="same", activation="relu"),
            layers.DepthwiseConv2D(3, depth_multiplier=2, padding="valid"),
            layers.Flatten(),
            layers.Dense(4),
        ])
        x = np.random.RandomState(4).randn(3, 8, 8, 3).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a.transpose(0, 3, 1, 2))

    def test_upsample_pad_crop_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((5, 5, 2)),
            layers.UpSampling2D(2),
            layers.ZeroPadding2D(((1, 2), (0, 1))),
            layers.Cropping2D(((0, 1), (2, 0))),
            layers.Conv2D(3, 3),
            layers.Flatten(),
            layers.Dense(4),
        ])
        x = np.random.RandomState(5).randn(2, 5, 5, 2).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a.transpose(0, 3, 1, 2))

    def test_permute_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((4, 6)),
            layers.Permute((2, 1)),
        ])
        x = np.random.RandomState(6).randn(3, 4, 6).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        self._seq_matches(net, x.transpose(0, 2, 1), y)

    def test_reshape_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((4, 6)),
            layers.Reshape((2, 12)),
        ])
        x = np.random.RandomState(7).randn(3, 4, 6).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        self._seq_matches(net, x.transpose(0, 2, 1), y)

    def test_repeat_vector_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((5,)),
            layers.RepeatVector(4),
        ])
        x = np.random.RandomState(8).randn(3, 5).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        self._seq_matches(net, x, y)

    def test_conv1d_pool1d_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((10, 3)),
            layers.Conv1D(4, 3, padding="same", activation="relu"),
            layers.MaxPooling1D(2),
        ])
        x = np.random.RandomState(9).randn(2, 10, 3).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        self._seq_matches(net, x.transpose(0, 2, 1), y)

    def test_custom_layer_registry(self, tmp_path):
        from deeplearning4j_tpu.modelimport.keras_import import (
            CUSTOM_LAYER_MAPPERS,
            register_custom_layer,
        )
        from deeplearning4j_tpu.nn.conf import ActivationLayer

        @keras.saving.register_keras_serializable()
        class PassThrough(keras.layers.Layer):
            def call(self, x):
                return x

        m = keras.Sequential([
            keras.Input((6,)),
            layers.Dense(4, activation="relu"),
            PassThrough(),
        ])
        path = _save(m, tmp_path)
        with pytest.raises(KerasImportError, match="PassThrough"):
            KerasModelImport.import_model(path)
        register_custom_layer(
            "PassThrough",
            lambda cfg, w, ctx, it, is_output: (
                [ActivationLayer(activation="identity")], [None], None))
        try:
            x = np.random.RandomState(10).randn(3, 6).astype(np.float32)
            y = m.predict(x, verbose=0)
            net = KerasModelImport.import_model(path)
            _assert_matches(net, x, y, lambda a: a)
        finally:
            CUSTOM_LAYER_MAPPERS.pop("PassThrough", None)
