"""Keras import golden conformance: imported nets must reproduce the real
Keras model's forward activations (SURVEY §4.2 golden-file pattern — "the
single most valuable testing idea"; here the goldens are generated live by
Keras itself rather than stored, which is strictly stronger).
"""

import numpy as np
import pytest

keras = pytest.importorskip("keras")
from keras import layers  # noqa: E402

from deeplearning4j_tpu.modelimport import KerasModelImport  # noqa: E402
from deeplearning4j_tpu.modelimport.keras_import import KerasImportError  # noqa: E402


def _save(model, tmp_path, name="m.h5"):
    p = str(tmp_path / name)
    model.save(p)
    return p


def _assert_matches(net, x_keras, y_keras, to_ours):
    got = np.asarray(net.output(to_ours(x_keras)).numpy())
    np.testing.assert_allclose(got, y_keras, rtol=1e-4, atol=1e-5)


class TestSequentialImport:
    def test_dense_mlp_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((12,)),
            layers.Dense(32, activation="relu"),
            layers.Dense(16, activation="tanh"),
            layers.Dropout(0.5),
            layers.Dense(5, activation="softmax"),
        ])
        x = np.random.RandomState(0).randn(6, 12).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_sequential(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a)

    def test_cnn_golden(self, tmp_path):
        """Conv/pool/flatten/dense with the NHWC→NCHW and flatten-order
        kernel permutation — the layout-sensitive path."""
        m = keras.Sequential([
            keras.Input((10, 8, 3)),
            layers.Conv2D(6, 3, activation="relu", padding="valid"),
            layers.MaxPooling2D(2),
            layers.Conv2D(4, 3, activation="relu", padding="same"),
            layers.Flatten(),
            layers.Dense(7, activation="softmax"),
        ])
        x = np.random.RandomState(1).randn(4, 10, 8, 3).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a.transpose(0, 3, 1, 2))  # NHWC→NCHW

    def test_batchnorm_inference_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((8, 8, 2)),
            layers.Conv2D(4, 3),
            layers.BatchNormalization(),
            layers.Activation("relu"),
            layers.GlobalAveragePooling2D(),
            layers.Dense(3),
        ])
        # push the BN moving stats away from init so the test is meaningful
        m.layers[1].set_weights([
            np.random.RandomState(2).rand(4).astype(np.float32) + 0.5,
            np.random.RandomState(3).randn(4).astype(np.float32),
            np.random.RandomState(4).randn(4).astype(np.float32),
            np.random.RandomState(5).rand(4).astype(np.float32) + 0.5,
        ])
        x = np.random.RandomState(6).randn(5, 8, 8, 2).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a.transpose(0, 3, 1, 2))

    def test_lstm_golden(self, tmp_path):
        """LSTM gate-order remap + [B,T,F]→[B,F,T] layout + return_sequences
        False → LastTimeStep expansion."""
        m = keras.Sequential([
            keras.Input((9, 5)),
            layers.LSTM(8, return_sequences=True),
            layers.LSTM(6),
            layers.Dense(4, activation="softmax"),
        ])
        x = np.random.RandomState(7).randn(3, 9, 5).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a.transpose(0, 2, 1))  # [B,T,F]→[B,F,T]

    def test_dense_then_activation_folds_into_output(self, tmp_path):
        """Dense -> Activation('softmax') tail: activation folds into the
        OutputLayer so the imported net both predicts AND fits."""
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.nn.conf import OutputLayer

        m = keras.Sequential([
            keras.Input((6,)),
            layers.Dense(8, activation="relu"),
            layers.Dense(3),
            layers.Activation("softmax"),
        ])
        x = np.random.RandomState(11).randn(4, 6).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_sequential(_save(m, tmp_path))
        out = net.conf.layers[-1]
        assert isinstance(out, OutputLayer) and out.activation == "softmax"
        assert out.loss == "mcxent"
        _assert_matches(net, x, y, lambda a: a)
        yl = np.eye(3, dtype=np.float32)[np.random.RandomState(0).randint(0, 3, 4)]
        net._fit_batch(DataSet(x, yl))  # fit works (compute_loss exists)

    def test_imported_net_is_trainable(self, tmp_path):
        from deeplearning4j_tpu.data.dataset import DataSet

        m = keras.Sequential([
            keras.Input((6,)),
            layers.Dense(16, activation="relu"),
            layers.Dense(2, activation="softmax"),
        ])
        net = KerasModelImport.import_sequential(_save(m, tmp_path))
        rs = np.random.RandomState(0)
        x = rs.randn(32, 6).astype(np.float32)
        yl = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 32)]
        s0 = None
        for _ in range(30):
            net._fit_batch(DataSet(x, yl))
            if s0 is None:
                s0 = net.score_
        assert net.score_ < s0, (s0, net.score_)


class TestFunctionalImport:
    def test_functional_branch_merge_golden(self, tmp_path):
        inp = keras.Input((10,))
        a = layers.Dense(8, activation="relu", name="a")(inp)
        b = layers.Dense(8, activation="tanh", name="b")(inp)
        add = layers.Add(name="add")([a, b])
        cat = layers.Concatenate(name="cat")([a, add])
        out = layers.Dense(3, activation="softmax", name="out")(cat)
        m = keras.Model(inp, out)
        x = np.random.RandomState(8).randn(5, 10).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        got = np.asarray(net.output(x)[0].numpy())
        np.testing.assert_allclose(got, y, rtol=1e-4, atol=1e-5)

    def test_functional_lstm_expansion_wiring(self, tmp_path):
        inp = keras.Input((7, 4))
        h = layers.LSTM(6, name="l")(inp)  # expands to LSTM + LastTimeStep
        out = layers.Dense(2, name="o")(h)
        m = keras.Model(inp, out)
        x = np.random.RandomState(9).randn(4, 7, 4).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        got = np.asarray(net.output(x.transpose(0, 2, 1))[0].numpy())
        np.testing.assert_allclose(got, y, rtol=1e-4, atol=1e-5)


class TestImportErrors:
    def test_unsupported_layer_raises(self, tmp_path):
        m = keras.Sequential([keras.Input((4, 4, 1)), layers.ConvLSTM1D(2, 3)])
        with pytest.raises(KerasImportError, match="ConvLSTM1D"):
            KerasModelImport.import_model(_save(m, tmp_path))

    def test_keras_zip_rejected_with_hint(self, tmp_path):
        m = keras.Sequential([keras.Input((4,)), layers.Dense(2)])
        p = str(tmp_path / "m.keras")
        m.save(p)
        with pytest.raises((KerasImportError, OSError)):
            KerasModelImport.import_model(p)


class TestWave2Mappers:
    """r4 mapper breadth (VERDICT r3 missing #4): Embedding, GRU, SimpleRNN,
    Bidirectional, Separable/DepthwiseConv2D, UpSampling/ZeroPadding/Cropping,
    Reshape/Permute/RepeatVector, Conv1D/Pooling1D, custom-layer registry."""

    def _seq_matches(self, net, x_ours, y_keras, rtol=1e-4):
        got = np.asarray(net.output(x_ours).numpy())
        np.testing.assert_allclose(got, y_keras.transpose(0, 2, 1),
                                   rtol=rtol, atol=1e-5)

    def test_embedding_gru_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((7,)),
            layers.Embedding(20, 8),
            layers.GRU(6, return_sequences=True),
        ])
        x = np.random.RandomState(0).randint(0, 20, (4, 7))
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        self._seq_matches(net, x.astype(np.float32), y)

    def test_gru_no_reset_after_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((5, 4)),
            layers.GRU(6, reset_after=False),
            layers.Dense(3, activation="softmax"),
        ])
        x = np.random.RandomState(1).randn(3, 5, 4).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a.transpose(0, 2, 1))

    def test_simplernn_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((6, 3)),
            layers.SimpleRNN(5, return_sequences=True),
        ])
        x = np.random.RandomState(2).randn(2, 6, 3).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        self._seq_matches(net, x.transpose(0, 2, 1), y)

    def test_bidirectional_lstm_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((6, 4)),
            layers.Bidirectional(layers.LSTM(3, return_sequences=True)),
        ])
        x = np.random.RandomState(3).randn(2, 6, 4).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        self._seq_matches(net, x.transpose(0, 2, 1), y)

    def test_bidirectional_no_sequences_raises(self, tmp_path):
        m = keras.Sequential([
            keras.Input((6, 4)),
            layers.Bidirectional(layers.LSTM(3)),
        ])
        with pytest.raises(KerasImportError, match="return_sequences"):
            KerasModelImport.import_model(_save(m, tmp_path))

    def test_separable_depthwise_conv_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((8, 8, 3)),
            layers.SeparableConv2D(5, 3, padding="same", activation="relu"),
            layers.DepthwiseConv2D(3, depth_multiplier=2, padding="valid"),
            layers.Flatten(),
            layers.Dense(4),
        ])
        x = np.random.RandomState(4).randn(3, 8, 8, 3).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a.transpose(0, 3, 1, 2))

    def test_upsample_pad_crop_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((5, 5, 2)),
            layers.UpSampling2D(2),
            layers.ZeroPadding2D(((1, 2), (0, 1))),
            layers.Cropping2D(((0, 1), (2, 0))),
            layers.Conv2D(3, 3),
            layers.Flatten(),
            layers.Dense(4),
        ])
        x = np.random.RandomState(5).randn(2, 5, 5, 2).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a.transpose(0, 3, 1, 2))

    def test_permute_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((4, 6)),
            layers.Permute((2, 1)),
        ])
        x = np.random.RandomState(6).randn(3, 4, 6).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        self._seq_matches(net, x.transpose(0, 2, 1), y)

    def test_reshape_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((4, 6)),
            layers.Reshape((2, 12)),
        ])
        x = np.random.RandomState(7).randn(3, 4, 6).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        self._seq_matches(net, x.transpose(0, 2, 1), y)

    def test_repeat_vector_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((5,)),
            layers.RepeatVector(4),
        ])
        x = np.random.RandomState(8).randn(3, 5).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        self._seq_matches(net, x, y)

    def test_conv1d_pool1d_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((10, 3)),
            layers.Conv1D(4, 3, padding="same", activation="relu"),
            layers.MaxPooling1D(2),
        ])
        x = np.random.RandomState(9).randn(2, 10, 3).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        self._seq_matches(net, x.transpose(0, 2, 1), y)

    def test_custom_layer_registry(self, tmp_path):
        from deeplearning4j_tpu.modelimport.keras_import import (
            CUSTOM_LAYER_MAPPERS,
            register_custom_layer,
        )
        from deeplearning4j_tpu.nn.conf import ActivationLayer

        @keras.saving.register_keras_serializable()
        class PassThrough(keras.layers.Layer):
            def call(self, x):
                return x

        m = keras.Sequential([
            keras.Input((6,)),
            layers.Dense(4, activation="relu"),
            PassThrough(),
        ])
        path = _save(m, tmp_path)
        with pytest.raises(KerasImportError, match="PassThrough"):
            KerasModelImport.import_model(path)
        register_custom_layer(
            "PassThrough",
            lambda cfg, w, ctx, it, is_output: (
                [ActivationLayer(activation="identity")], [None], None))
        try:
            x = np.random.RandomState(10).randn(3, 6).astype(np.float32)
            y = m.predict(x, verbose=0)
            net = KerasModelImport.import_model(path)
            _assert_matches(net, x, y, lambda a: a)
        finally:
            CUSTOM_LAYER_MAPPERS.pop("PassThrough", None)


class TestR5MapperWave:
    """r5 mapper wave (VERDICT r4 missing #4): advanced activations, masking,
    TimeDistributed, the Conv3D/ConvLSTM2D family, 1-D/3-D shape layers,
    noise/dropout schemes, LocallyConnected, Lambda hook."""

    def test_advanced_activation_layers_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((6,)),
            layers.Dense(8),
            layers.ReLU(),
            layers.Dense(8),
            layers.LeakyReLU(negative_slope=0.25),
            layers.Dense(8),
            layers.ELU(),
            layers.Dense(4),
            layers.Softmax(),
        ])
        x = np.random.RandomState(0).randn(5, 6).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a)

    def test_prelu_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((7,)),
            layers.Dense(5),
            layers.PReLU(),
            layers.Dense(3),
        ])
        m.layers[1].set_weights([np.random.RandomState(1).rand(5).astype(np.float32)])
        x = np.random.RandomState(2).randn(4, 7).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a)

    def test_masking_imports_as_mask_zero_layer(self, tmp_path):
        from deeplearning4j_tpu.nn.layers_tail import MaskZeroLayer

        m = keras.Sequential([
            keras.Input((5, 3)),
            layers.Masking(mask_value=9.0),
            layers.LSTM(4, return_sequences=False),
        ])
        x = np.random.RandomState(3).randn(2, 5, 3).astype(np.float32)
        y = m.predict(x, verbose=0)  # no sentinel steps → exact keras parity
        net = KerasModelImport.import_sequential(_save(m, tmp_path))
        assert any(isinstance(l, MaskZeroLayer) for l in net.conf.layers)
        _assert_matches(net, x, y, lambda a: a.transpose(0, 2, 1))

    def test_time_distributed_dense_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((6, 4)),
            layers.TimeDistributed(layers.Dense(5, activation="relu")),
            layers.GlobalAveragePooling1D(),
            layers.Dense(2),
        ])
        x = np.random.RandomState(4).randn(3, 6, 4).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a.transpose(0, 2, 1))

    def test_conv3d_pool3d_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((6, 6, 6, 2)),
            layers.Conv3D(4, 3, activation="relu", padding="same"),
            layers.MaxPooling3D(2),
            layers.GlobalAveragePooling3D(),
            layers.Dense(3),
        ])
        x = np.random.RandomState(5).randn(2, 6, 6, 6, 2).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a.transpose(0, 4, 1, 2, 3))

    def test_conv3d_transpose_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((3, 3, 3, 2)),
            layers.Conv3DTranspose(3, 2, strides=2, padding="same"),
            layers.GlobalAveragePooling3D(),
        ])
        x = np.random.RandomState(6).randn(2, 3, 3, 3, 2).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a.transpose(0, 4, 1, 2, 3))

    def test_convlstm2d_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((4, 5, 5, 2)),   # [T, H, W, C]
            layers.ConvLSTM2D(3, 3, padding="same", return_sequences=False),
            layers.GlobalAveragePooling2D(),
        ])
        x = np.random.RandomState(7).randn(2, 4, 5, 5, 2).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a.transpose(0, 4, 1, 2, 3))

    def test_shape_layers_1d_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((6, 3)),
            layers.ZeroPadding1D(2),
            layers.Cropping1D((1, 1)),
            layers.UpSampling1D(2),
            layers.GlobalAveragePooling1D(),
            layers.Dense(2),
        ])
        x = np.random.RandomState(8).randn(3, 6, 3).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a.transpose(0, 2, 1))

    def test_shape_layers_3d_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((4, 4, 4, 2)),
            layers.ZeroPadding3D(1),
            layers.Cropping3D(((1, 1), (0, 1), (1, 0))),
            layers.UpSampling3D(2),
            layers.GlobalMaxPooling3D(),
        ])
        x = np.random.RandomState(9).randn(2, 4, 4, 4, 2).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a.transpose(0, 4, 1, 2, 3))

    def test_noise_layers_are_inference_identity(self, tmp_path):
        m = keras.Sequential([
            keras.Input((5,)),
            layers.GaussianNoise(0.5),
            layers.Dense(6, activation="relu"),
            layers.GaussianDropout(0.3),
            layers.AlphaDropout(0.2),
            layers.Dense(3),
        ])
        x = np.random.RandomState(10).randn(4, 5).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a)

    def test_spatial_dropout_golden(self, tmp_path):
        m = keras.Sequential([
            keras.Input((6, 6, 2)),
            layers.Conv2D(3, 3, padding="same"),
            layers.SpatialDropout2D(0.4),
            layers.GlobalAveragePooling2D(),
        ])
        x = np.random.RandomState(11).randn(2, 6, 6, 2).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a.transpose(0, 3, 1, 2))

    def test_lambda_requires_registered_mapper(self, tmp_path):
        m = keras.Sequential([
            keras.Input((4,)),
            layers.Lambda(lambda t: t * 2.0, name="double_it"),
            layers.Dense(2),
        ])
        path = _save(m, tmp_path)
        with pytest.raises(KerasImportError, match="Lambda:double_it"):
            KerasModelImport.import_model(path)
        # register the mapper → import succeeds and matches
        from deeplearning4j_tpu.modelimport.keras_import import (
            CUSTOM_LAYER_MAPPERS, register_custom_layer)
        from deeplearning4j_tpu.nn.conf import ActivationLayer

        register_custom_layer(
            "Lambda:double_it",
            lambda cfg, w, ctx, it, is_out: ([ActivationLayer(
                activation=lambda t: t * 2.0)], [None], None))
        try:
            x = np.random.RandomState(12).randn(3, 4).astype(np.float32)
            y = m.predict(x, verbose=0)
            net = KerasModelImport.import_model(path)
            _assert_matches(net, x, y, lambda a: a)
        finally:
            CUSTOM_LAYER_MAPPERS.pop("Lambda:double_it", None)

    # keras 3 removed ThresholdedReLU / LocallyConnected — the mappers are
    # exercised directly against hand-built configs + numpy oracles
    def test_thresholded_relu_mapper_direct(self):
        from deeplearning4j_tpu.modelimport.keras_import import _Ctx, _map_layer
        from deeplearning4j_tpu.nn.conf import InputType

        layers_, params, _ = _map_layer(
            "ThresholdedReLU", {"theta": 0.7}, None, _Ctx(),
            InputType.feed_forward(4), False)
        x = np.array([[-1.0, 0.5, 0.8, 2.0]], np.float32)
        got = np.asarray(layers_[0].forward({}, x, InputType.feed_forward(4),
                                            training=False))
        np.testing.assert_allclose(got, [[0.0, 0.0, 0.8, 2.0]])

    def test_locally_connected_mappers_direct(self):
        from deeplearning4j_tpu.modelimport.keras_import import _Ctx, _map_layer
        from deeplearning4j_tpu.nn.conf import InputType

        rs = np.random.RandomState(13)
        # 1D: T=5, C=2, k=2 → OT=4; keras kernel [OT, k*C, F] in (k, c) order
        kern = rs.randn(4, 4, 3).astype(np.float32)
        bias = rs.randn(4, 3).astype(np.float32)
        layers_, params, _ = _map_layer(
            "LocallyConnected1D",
            {"filters": 3, "kernel_size": [2], "strides": [1],
             "padding": "valid", "activation": "linear"},
            {"kernel": kern, "bias": bias.reshape(-1)}, _Ctx(),
            InputType.recurrent(2, 5), False)
        x = rs.randn(1, 2, 5).astype(np.float32)   # framework [B,C,T]
        got = np.asarray(layers_[0].forward(params[0], x,
                                            InputType.recurrent(2, 5),
                                            training=False))
        expected = np.zeros((1, 3, 4), np.float32)
        for t in range(4):
            patch = np.stack([x[0, :, t], x[0, :, t + 1]])  # [k, C] keras order
            expected[0, :, t] = patch.reshape(-1) @ kern[t] + bias[t]
        np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)

        # 2D: H=W=3, C=2, k=2 → 2x2 positions; keras kernel [P, kh*kw*C, F]
        kern2 = rs.randn(4, 8, 3).astype(np.float32)
        layers_, params, _ = _map_layer(
            "LocallyConnected2D",
            {"filters": 3, "kernel_size": [2, 2], "strides": [1, 1],
             "padding": "valid", "activation": "linear", "use_bias": False},
            {"kernel": kern2}, _Ctx(), InputType.convolutional(3, 3, 2), False)
        xi = rs.randn(1, 2, 3, 3).astype(np.float32)
        got = np.asarray(layers_[0].forward(params[0], xi,
                                            InputType.convolutional(3, 3, 2),
                                            training=False))
        expected = np.zeros((1, 3, 2, 2), np.float32)
        for p, (i, j) in enumerate([(0, 0), (0, 1), (1, 0), (1, 1)]):
            patch = xi[0, :, i:i + 2, j:j + 2]          # [C, kh, kw]
            feat = patch.transpose(1, 2, 0).reshape(-1)  # keras (h, w, c)
            expected[0, :, i, j] = feat @ kern2[p]
        np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


class TestMaskingPlacement:
    def test_masking_wraps_bidirectional(self, tmp_path):
        from deeplearning4j_tpu.nn.layers_tail import MaskZeroLayer

        m = keras.Sequential([
            keras.Input((5, 3)),
            layers.Masking(mask_value=9.0),
            layers.Bidirectional(layers.LSTM(4, return_sequences=True)),
            layers.GlobalAveragePooling1D(),
        ])
        net = KerasModelImport.import_sequential(_save(m, tmp_path))
        wrapped = [l for l in net.conf.layers if isinstance(l, MaskZeroLayer)]
        assert len(wrapped) == 1
        from deeplearning4j_tpu.nn.conf import Bidirectional
        assert isinstance(wrapped[0].underlying, Bidirectional)

    def test_unconsumed_masking_raises(self, tmp_path):
        m = keras.Sequential([
            keras.Input((5, 3)),
            layers.Masking(mask_value=9.0),
            layers.GlobalAveragePooling1D(),
            layers.Dense(2),
        ])
        with pytest.raises(KerasImportError, match="Masking"):
            KerasModelImport.import_sequential(_save(m, tmp_path))

    def test_leaky_relu_alpha_zero_preserved(self, tmp_path):
        m = keras.Sequential([
            keras.Input((4,)),
            layers.Dense(4),
            layers.LeakyReLU(negative_slope=0.0),
        ])
        x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        y = m.predict(x, verbose=0)
        net = KerasModelImport.import_model(_save(m, tmp_path))
        _assert_matches(net, x, y, lambda a: a)
