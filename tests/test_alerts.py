"""SLO alert engine (ISSUE 10 tentpole, layer 3) + compile attribution.

Fast tier: rule evaluation semantics (agg/ratio/after_warmup/no_data),
edge-triggered flight events + counters, the /alerts endpoint, the
compile-attribution acceptance ("flat after warmup per fn; churn fires the
recompile alert in /alerts"), and the alert-rule AST lint (rules may only
reference registry-declared or derived metric families).

Slow tier: the full gang acceptance — a shape-churning, crash-injected gang
under GangSupervisor leaves a postmortem whose event stream carries the
fired alert and the compile events, aggregated into `compile_churn`.
"""

import ast
import json
import os
import pathlib
import re
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.monitoring import (AlertEngine, AlertRule,
                                           MetricsRegistry, RecompileWatchdog,
                                           default_rules)
from deeplearning4j_tpu.monitoring import aggregate, flight
from deeplearning4j_tpu.monitoring.aggregate import MetricsSpooler
from deeplearning4j_tpu.monitoring.flight import FlightRecorder

WORKERS = os.path.join(os.path.dirname(__file__), "mp_workers.py")
ROOT = pathlib.Path(__file__).resolve().parent.parent


def _net():
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.Builder().seed(5).updater(Adam(0.01)).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _batch(n=16):
    from deeplearning4j_tpu.data.dataset import DataSet

    rs = np.random.RandomState(0)
    return DataSet(rs.randn(n, 4).astype(np.float32),
                   np.eye(2, dtype=np.float32)[rs.randint(0, 2, n)])


# ----------------------------------------------------------- rule semantics


def test_rule_validation():
    with pytest.raises(ValueError, match="unknown op"):
        AlertRule("x", "tdl_score", op="!=")
    with pytest.raises(ValueError, match="unknown agg"):
        AlertRule("x", "tdl_score", agg="median")
    with pytest.raises(ValueError, match="unknown agg"):
        AlertRule("x", "tdl_score", agg="p0")  # quantile must be in (0, 100)
    with pytest.raises(ValueError, match="duplicate"):
        AlertEngine(rules=(AlertRule("dup", "tdl_score"),
                           AlertRule("dup", "tdl_score")))
    # v2 (ISSUE 11) field validation
    assert AlertRule("q", "tdl_score", agg="p99.9").agg == "p99.9"
    with pytest.raises(ValueError, match="rate=True needs window"):
        AlertRule("x", "tdl_score", rate=True)
    with pytest.raises(ValueError, match="mutually exclusive"):
        AlertRule("x", "tdl_score", window=10, after_warmup=True)
    with pytest.raises(ValueError, match="mutually exclusive"):
        AlertRule("x", "tdl_score", window=10, ratio_of="tdl_score")
    with pytest.raises(ValueError, match="window must be > 0"):
        AlertRule("x", "tdl_score", window=0)
    with pytest.raises(ValueError, match="for_duration"):
        AlertRule("x", "tdl_score", for_duration=-1)
    # label_filter normalizes to a hashable sorted tuple on the frozen rule
    r = AlertRule("x", "tdl_score", label_filter={"b": 2, "a": "1"})
    assert r.label_filter == (("a", "1"), ("b", "2"))
    assert r.label_filter_dict == {"a": "1", "b": "2"}


def test_threshold_and_agg_over_series():
    reg = MetricsRegistry()
    g = reg.gauge("tdl_inference_queue_depth", labels=("replica",))
    g.labels("a").set(10)
    g.labels("b").set(55)
    eng = AlertEngine(rules=(
        AlertRule("hwm_max", "tdl_inference_queue_depth", ">=", 48, agg="max"),
        AlertRule("hwm_min", "tdl_inference_queue_depth", ">=", 48, agg="min"),
        AlertRule("hwm_sum", "tdl_inference_queue_depth", ">", 60, agg="sum"),
    ), registry=reg)
    by = {a["rule"]: a for a in eng.evaluate()}
    assert by["hwm_max"]["firing"] and by["hwm_max"]["value"] == 55
    assert not by["hwm_min"]["firing"]
    assert by["hwm_sum"]["firing"] and by["hwm_sum"]["value"] == 65


def test_histogram_agg_mean_and_count():
    reg = MetricsRegistry()
    h = reg.histogram("tdl_input_wait_seconds", buckets=(0.1, 1.0))
    for v in (0.2, 0.4, 0.6):
        h.observe(v)
    eng = AlertEngine(rules=(
        AlertRule("mean_wait", "tdl_input_wait_seconds", ">", 0.3, agg="mean"),
        AlertRule("n_waits", "tdl_input_wait_seconds", ">", 2, agg="sum"),
    ), registry=reg)
    by = {a["rule"]: a for a in eng.evaluate()}
    assert by["mean_wait"]["value"] == pytest.approx(0.4)
    assert by["mean_wait"]["firing"]
    assert by["n_waits"]["value"] == 3  # histograms under sum read the count


def test_ratio_rule_and_no_data():
    reg = MetricsRegistry()
    eng = AlertEngine(rules=(
        AlertRule("hbm", "tdl_device_memory_bytes_in_use", ">", 0.9,
                  ratio_of="tdl_device_memory_limit_bytes"),
    ), registry=reg)
    assert eng.evaluate()[0]["state"] == "no_data"  # neither family exists
    reg.gauge("tdl_device_memory_bytes_in_use", labels=("device",)) \
       .labels("d0").set(95)
    assert eng.evaluate()[0]["state"] == "no_data"  # no limit → no ratio
    reg.gauge("tdl_device_memory_limit_bytes", labels=("device",)) \
       .labels("d0").set(100)
    row = eng.evaluate()[0]
    assert row["firing"] and row["value"] == pytest.approx(0.95)


def test_ratio_rule_pairs_series_by_labels_not_global_aggregates():
    """A huge denominator on ONE proc/device must not hide another device
    sitting at 97% of ITS OWN limit — ratios are per-series, agg folds the
    ratios."""
    tpu = MetricsRegistry()
    tpu.gauge("tdl_device_memory_bytes_in_use", labels=("device",)) \
       .labels("tpu:0").set(15.5e9)
    tpu.gauge("tdl_device_memory_limit_bytes", labels=("device",)) \
       .labels("tpu:0").set(16e9)
    host = MetricsRegistry()
    host.gauge("tdl_device_memory_bytes_in_use", labels=("device",)) \
        .labels("host").set(2e9)
    host.gauge("tdl_device_memory_limit_bytes", labels=("device",)) \
        .labels("host").set(64e9)
    rule = AlertRule("hbm", "tdl_device_memory_bytes_in_use", ">", 0.9,
                     ratio_of="tdl_device_memory_limit_bytes")
    eng = AlertEngine(rules=(rule,), registry=tpu)
    # hand the engine both snapshots the way a spool merge would
    snaps = [tpu.snapshot(), host.snapshot()]
    value, state = eng._rule_value(snaps, rule)
    assert state == "ok" and value == pytest.approx(15.5 / 16)
    # a series with no same-labels denominator is skipped, not mis-paired
    lone = MetricsRegistry()
    lone.gauge("tdl_device_memory_bytes_in_use", labels=("device",)) \
        .labels("tpu:1").set(1e9)
    assert eng._rule_value([lone.snapshot()], rule) == (None, "no_data")


def test_after_warmup_rule_measures_increase_only():
    reg = MetricsRegistry()
    c = reg.counter("tdl_input_starved_steps_total")
    c.inc(7)  # starvation during warmup is expected
    eng = AlertEngine(rules=(
        AlertRule("starved", "tdl_input_starved_steps_total", ">", 0,
                  agg="sum", after_warmup=True),), registry=reg)
    assert eng.evaluate()[0]["state"] == "pending_warmup"
    eng.mark_warmup_done()
    row = eng.evaluate()[0]
    assert row["value"] == 0.0 and not row["firing"]
    c.inc(2)
    row = eng.evaluate()[0]
    assert row["firing"] and row["value"] == 2.0


def test_rising_edge_records_flight_event_and_counter_once():
    rec = FlightRecorder(proc="alert-test")
    flight.set_flight_recorder(rec)
    try:
        reg = MetricsRegistry()
        g = reg.gauge("tdl_inference_queue_depth")
        eng = AlertEngine(rules=(
            AlertRule("hwm", "tdl_inference_queue_depth", ">=", 48),),
            registry=reg)
        g.set(60)
        eng.evaluate()
        eng.evaluate()  # still firing: level stays, NO second edge
        g.set(0)
        eng.evaluate()  # clears
        g.set(70)
        eng.evaluate()  # second rising edge
        fired = reg.get("tdl_alerts_fired_total").labels("hwm").value
        assert fired == 2
        events = [e for e in rec.events() if e["kind"] == "alert"]
        assert len(events) == 2
        assert events[0]["rule"] == "hwm" and events[0]["value"] == 60
        assert reg.get("tdl_alert_firing").labels("hwm").value == 1
    finally:
        flight.set_flight_recorder(None)


def test_engine_over_spool_dir_sees_derived_straggler_gauges(tmp_path):
    def rank_registry(step_seconds):
        reg = MetricsRegistry()
        h = reg.histogram("tdl_step_wall_seconds", labels=("trainer",))
        for _ in range(5):
            h.labels("T").observe(step_seconds)
        return reg

    MetricsSpooler(str(tmp_path), proc="rank0", registry=rank_registry(0.01),
                   interval=0.0, rank=0).spool(force=True)
    MetricsSpooler(str(tmp_path), proc="rank1", registry=rank_registry(0.05),
                   interval=0.0, rank=1).spool(force=True)
    eng = AlertEngine(registry=MetricsRegistry(), spool_dir=str(tmp_path))
    by = {a["rule"]: a for a in eng.evaluate()}
    skew = by["straggler_skew"]
    assert skew["firing"] and skew["value"] == pytest.approx(5.0)


# ----------------------------- compile attribution acceptance (fast tier)


def test_compiles_flat_after_warmup_and_churn_fires_alert_in_alerts_endpoint():
    """ISSUE 10 acceptance (in-process half): per-fn compile counters stay
    FLAT over a steady-shape fit loop after warmup, while a shape-churning
    loop grows them, fires `recompiles_after_warmup`, and the firing alert
    is served at UIServer /alerts."""
    from deeplearning4j_tpu.ui import UIServer

    reg = MetricsRegistry()
    net = _net()
    rec = FlightRecorder(proc="churn-test")
    flight.set_flight_recorder(rec)
    try:
        with RecompileWatchdog(registry=reg):
            engine = AlertEngine(registry=reg)
            ds = _batch()
            for _ in range(3):  # warmup: one signature, one compile
                net._fit_batch(ds)
            engine.mark_warmup_done()

            def per_fn():
                return {s["labels"]["fn"]: s["value"] for s in
                        reg.get("tdl_xla_compiles_total").snapshot()["series"]}

            at_warmup = per_fn()
            assert at_warmup.get("MultiLayerNetwork.train_step", 0) >= 1
            for _ in range(5):  # steady shapes: NO fn may compile again
                net._fit_batch(ds)
            assert per_fn() == at_warmup
            assert not [a for a in engine.evaluate() if a["firing"]]

            for n in (6, 7, 9):  # deliberate batch-size churn
                net._fit_batch(_batch(n))
            after_churn = per_fn()
            assert after_churn["MultiLayerNetwork.train_step"] == \
                at_warmup["MultiLayerNetwork.train_step"] + 3

            server = UIServer(port=0)
            try:
                server.attach_registry(reg)
                server.attach_alerts(engine)
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{server.port}/alerts",
                        timeout=10) as r:
                    payload = json.loads(r.read())
            finally:
                server.stop()
            assert "recompiles_after_warmup" in payload["firing"]
            row = {a["rule"]: a for a in payload["alerts"]}[
                "recompiles_after_warmup"]
            assert row["value"] >= 3 and row["severity"] == "critical"
        # the watchdog left per-compile flight events carrying the fn
        compiles = [e for e in rec.events() if e["kind"] == "compile"]
        assert any(e["fn"] == "MultiLayerNetwork.train_step"
                   for e in compiles)
        assert all("seconds" in e for e in compiles)
        # ...and the fired alert is on the same timeline
        assert any(e["kind"] == "alert"
                   and e["rule"] == "recompiles_after_warmup"
                   for e in rec.events())
    finally:
        flight.set_flight_recorder(None)


def test_compile_churn_postmortem_section_aggregates_events():
    from deeplearning4j_tpu.parallel.supervisor import _compile_churn

    events = [
        {"kind": "compile", "proc": "rank0", "fn": "f", "seconds": 0.5},
        {"kind": "compile", "proc": "rank0", "fn": "f", "seconds": 0.25},
        {"kind": "compile", "proc": "rank1", "fn": "g", "seconds": 1.0},
        {"kind": "step_begin", "proc": "rank0", "iteration": 3},
    ]
    rows = _compile_churn(events)
    assert rows[0] == {"proc": "rank0", "fn": "f", "compiles": 2,
                       "seconds": 0.75}
    assert rows[1]["fn"] == "g" and rows[1]["compiles"] == 1
    assert _compile_churn([{"kind": "step_begin"}]) == []


def test_signature_lru_bounds_table_and_counts_evictions():
    """ISSUE 10 satellite: the per-fn signature table is an LRU bounded at
    max_signatures_per_fn; sustained churn evicts instead of leaking."""
    from deeplearning4j_tpu.monitoring import watchdogs as wd_mod

    reg = MetricsRegistry()
    wd = RecompileWatchdog(registry=reg, max_signatures_per_fn=4,
                           window_steps=1000, churn_threshold=1000)
    wd.install()
    try:
        for i in range(10):
            wd.note_signature("f", ("sig", i))
        stats = wd.stats()
        assert stats["signatures"]["f"] == 4  # bounded, not 10
        assert reg.get("tdl_jit_signature_evictions_total") \
                  .labels("f").value == 6
        # LRU: touching an old-but-kept signature keeps it resident
        wd.note_signature("f", ("sig", 7))  # hit → move to end
        wd.note_signature("f", ("sig", 99))  # evicts sig 6, not sig 7
        assert ("sig", 7) in wd._seen["f"]
        assert ("sig", 6) not in wd._seen["f"]
    finally:
        wd.close()
    assert wd_mod.UNATTRIBUTED == "_unattributed"


# ------------------------------------------- alert rules v2 (ISSUE 11)


class FakeHistory:
    """History view with hand-authored samples (controlled timestamps)."""

    def __init__(self, samples):
        self._samples = samples

    def samples(self, window=None, now=None):
        return self._samples


def _hist_sample(t, reg, proc="local"):
    return {"t": t, "proc": proc, "snapshot": reg.snapshot()}


def test_windowed_p99_rule_reads_history_window_delta():
    """agg="p99" + window: the quantile comes from the WINDOW's bucket
    deltas, not the all-time cumulative histogram — old fast traffic
    outside the window cannot mask a slow last minute."""
    import time as _time

    now = _time.monotonic()
    reg = MetricsRegistry()
    h = reg.histogram("tdl_inference_latency_seconds",
                      buckets=(0.1, 0.5, 1.0))
    samples = []
    for _ in range(1000):  # ancient fast traffic (outside the window)
        h.observe(0.05)
    samples.append(_hist_sample(now - 120, reg))
    samples.append(_hist_sample(now - 50, reg))  # window baseline
    for _ in range(90):
        h.observe(0.05)
    for _ in range(10):
        h.observe(0.9)  # slow tail INSIDE the window
    samples.append(_hist_sample(now - 1, reg))
    rule = AlertRule("p99", "tdl_inference_latency_seconds", ">", 0.2,
                     agg="p99", window=60)
    eng = AlertEngine(rules=(rule,), registry=reg,
                      history_view=FakeHistory(samples))
    row = eng.evaluate()[0]
    # window delta: 90@0.05 + 10@0.9 → rank 99 lands in the (0.5, 1.0]
    # bucket, interpolated 0.5 + 0.5*0.9 = 0.95
    assert row["value"] == pytest.approx(0.95)
    assert row["firing"]
    # all-time p99 over the same registry stays fast (1090 fast vs 10 slow)
    eng2 = AlertEngine(rules=(
        AlertRule("p99_all", "tdl_inference_latency_seconds", ">", 0.2,
                  agg="p99"),), registry=reg)
    assert eng2.evaluate()[0]["value"] < 0.2


def test_windowed_rate_rule_counter_per_second():
    import time as _time

    now = _time.monotonic()
    reg = MetricsRegistry()
    c = reg.counter("tdl_inference_shed_total", labels=("reason",))
    c.labels("queue_full").inc(100)
    s0 = _hist_sample(now - 10, reg)
    c.labels("queue_full").inc(50)  # +50 over 10 seconds → 5/s
    s1 = _hist_sample(now, reg)
    eng = AlertEngine(rules=(
        AlertRule("shed", "tdl_inference_shed_total", ">", 3, agg="sum",
                  window=60, rate=True),), registry=reg,
        history_view=FakeHistory([s0, s1]))
    row = eng.evaluate()[0]
    assert row["value"] == pytest.approx(5.0, rel=1e-6)
    assert row["firing"]


def test_windowed_rule_series_born_mid_window_counts_from_zero():
    """A family whose first observation happened inside the window must
    still produce a windowed value (synthetic zero baseline), not no_data —
    otherwise the first minute of traffic is invisible to every rule."""
    import time as _time

    now = _time.monotonic()
    reg = MetricsRegistry()
    reg.counter("tdl_inference_shed_total", labels=("reason",))  # no series
    s0 = _hist_sample(now - 10, reg)
    reg.get("tdl_inference_shed_total").labels("queue_full").inc(30)
    s1 = _hist_sample(now, reg)
    eng = AlertEngine(rules=(
        AlertRule("shed", "tdl_inference_shed_total", ">", 1, agg="sum",
                  window=60, rate=True),), registry=reg,
        history_view=FakeHistory([s0, s1]))
    row = eng.evaluate()[0]
    assert row["value"] == pytest.approx(3.0, rel=1e-6)  # 30 over 10s


def test_windowed_percentile_over_gauge_is_no_data_not_mean():
    """A pNN agg needs bucket data; over a gauge family it must report
    no_data (matching the snapshot path), never silently fold the point
    samples into a mean that under-reports the tail."""
    import time as _time

    now = _time.monotonic()
    reg = MetricsRegistry()
    g = reg.gauge("tdl_inference_queue_depth")
    samples = []
    for t_off, v in ((-30, 0), (-20, 0), (-10, 0), (-1, 100)):
        g.set(v)
        samples.append(_hist_sample(now + t_off, reg))
    eng = AlertEngine(rules=(
        AlertRule("p99_depth", "tdl_inference_queue_depth", ">", 50,
                  agg="p99", window=60),), registry=reg,
        history_view=FakeHistory(samples))
    row = eng.evaluate()[0]
    assert row["state"] == "no_data" and row["value"] is None


def test_label_filter_restricts_series():
    reg = MetricsRegistry()
    g = reg.gauge("tdl_slo_burn_rate", labels=("slo", "window"))
    g.labels("latency", "fast").set(20.0)
    g.labels("latency", "slow").set(1.0)
    eng = AlertEngine(rules=(
        AlertRule("burn_fast", "tdl_slo_burn_rate", ">", 10, agg="max",
                  label_filter={"window": "fast"}),
        AlertRule("burn_slow", "tdl_slo_burn_rate", ">", 10, agg="max",
                  label_filter={"window": "slow"}),
    ), registry=reg)
    by = {a["rule"]: a for a in eng.evaluate()}
    assert by["burn_fast"]["firing"] and by["burn_fast"]["value"] == 20.0
    assert not by["burn_slow"]["firing"] and by["burn_slow"]["value"] == 1.0


def test_for_duration_requires_consecutive_holds_before_firing():
    """ISSUE 11 satellite: no fire before for_duration evaluations; a dip
    resets the count — exactly the anti-flap contract a scaler needs."""
    reg = MetricsRegistry()
    g = reg.gauge("tdl_inference_queue_depth")
    eng = AlertEngine(rules=(
        AlertRule("hwm", "tdl_inference_queue_depth", ">=", 48,
                  for_duration=3),), registry=reg)
    g.set(60)
    assert eng.evaluate()[0]["state"] == "pending"  # hold 1
    assert eng.evaluate()[0]["state"] == "pending"  # hold 2
    g.set(0)
    assert eng.evaluate()[0]["state"] == "ok"       # dip resets the count
    g.set(60)
    states = [eng.evaluate()[0]["state"] for _ in range(3)]
    assert states == ["pending", "pending", "firing"]
    fired = reg.get("tdl_alerts_fired_total").labels("hwm").value
    assert fired == 1  # the two pending runs never fired


def test_hysteresis_keeps_one_interval_and_clear_recorded_once():
    """ISSUE 11 satellite (edge semantics): rising → firing → value dips
    INSIDE the hysteresis band (stays firing, no second edge) → below the
    band (alert_clear exactly once, with duration) → back inside the band
    (does NOT re-fire: clearing direction crossed, rising needs the full
    threshold again)."""
    rec = FlightRecorder(proc="hyst-test")
    flight.set_flight_recorder(rec)
    try:
        reg = MetricsRegistry()
        g = reg.gauge("tdl_inference_queue_depth")
        eng = AlertEngine(rules=(
            AlertRule("hwm", "tdl_inference_queue_depth", ">", 50,
                      clear_hysteresis=10),), registry=reg)
        g.set(60)
        assert eng.evaluate()[0]["firing"]       # rising edge
        g.set(45)                                # inside (40, 50] band
        assert eng.evaluate()[0]["firing"]       # still ONE interval
        g.set(35)                                # below threshold - band
        row = eng.evaluate()[0]
        assert not row["firing"] and row["state"] == "ok"
        g.set(45)                                # back inside the band
        assert not eng.evaluate()[0]["firing"]   # no re-fire inside band
        g.set(60)
        assert eng.evaluate()[0]["firing"]       # full threshold re-fires

        fired = reg.get("tdl_alerts_fired_total").labels("hwm").value
        cleared = reg.get("tdl_alerts_cleared_total").labels("hwm").value
        assert (fired, cleared) == (2, 1)
        clears = [e for e in rec.events() if e["kind"] == "alert_clear"]
        assert len(clears) == 1
        assert clears[0]["rule"] == "hwm" and clears[0]["duration"] >= 0
        rises = [e for e in rec.events() if e["kind"] == "alert"]
        assert len(rises) == 2
    finally:
        flight.set_flight_recorder(None)


def test_engine_internal_history_feed_gives_windowed_values():
    """Without an explicit history view, the engine's own evaluations feed
    the buffer — two scrapes are enough for a windowed rate."""
    import time as _time

    reg = MetricsRegistry()
    c = reg.counter("tdl_inference_shed_total", labels=("reason",))
    c.labels("queue_full").inc(5)
    eng = AlertEngine(rules=(
        AlertRule("shed", "tdl_inference_shed_total", ">", 0.0001,
                  agg="sum", window=60, rate=True),), registry=reg)
    eng.evaluate()  # sample 1 (dt=0 → no rate yet)
    c.labels("queue_full").inc(5)
    _time.sleep(0.05)
    row = eng.evaluate()[0]  # sample 2: +5 over ~0.05s
    assert row["value"] is not None and row["value"] > 1
    assert row["firing"]


def test_alert_intervals_pairs_rising_and_falling_edges():
    from deeplearning4j_tpu.parallel.supervisor import _alert_intervals

    events = [
        {"kind": "alert", "proc": "rank0", "rule": "p99", "t": 10.0,
         "severity": "warning"},
        {"kind": "step_begin", "proc": "rank0", "t": 11.0},
        {"kind": "alert_clear", "proc": "rank0", "rule": "p99", "t": 14.0,
         "duration": 4.0, "severity": "warning"},
        {"kind": "alert", "proc": "rank1", "rule": "burn", "t": 12.0,
         "severity": "critical"},
    ]
    rows = _alert_intervals(events)
    assert len(rows) == 2
    still = [r for r in rows if r["still_firing"]][0]
    assert still["rule"] == "burn" and still["end_t"] is None
    closed = [r for r in rows if not r["still_firing"]][0]
    assert closed["rule"] == "p99"
    assert closed["start_t"] == 10.0 and closed["end_t"] == 14.0
    assert closed["duration"] == 4.0
    assert _alert_intervals([{"kind": "step_begin"}]) == []


# ---------------------------------------------------- alert-rule AST lint


def _declared_families() -> set:
    decl = re.compile(
        r'\.(?:counter|gauge|histogram)\(\s*["\'](tdl_[a-z0-9_]+)["\']')
    declared = set(aggregate.DERIVED_FAMILIES)
    for path in sorted((ROOT / "deeplearning4j_tpu").rglob("*.py")):
        declared.update(decl.findall(path.read_text()))
    return declared


def test_alert_rules_reference_declared_families():
    """Repo lint (ISSUE 10 satellite): every AlertRule(...) in library code
    must name a metric family some registry declares (or a derived family
    from aggregate.DERIVED_FAMILIES) as a LITERAL — renaming a metric
    therefore fails the build instead of silently rotting its alert."""
    declared = _declared_families()
    assert len(declared) > 30
    offenders, found = [], 0
    for path in sorted((ROOT / "deeplearning4j_tpu").rglob("*.py")):
        rel = path.relative_to(ROOT).as_posix()
        tree = ast.parse(path.read_text(), filename=rel)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and ((isinstance(node.func, ast.Name)
                          and node.func.id == "AlertRule")
                         or (isinstance(node.func, ast.Attribute)
                             and node.func.attr == "AlertRule"))):
                continue
            found += 1
            refs = {}
            if len(node.args) >= 2:
                refs["family"] = node.args[1]
            for kw in node.keywords:
                if kw.arg in ("family", "ratio_of"):
                    refs[kw.arg] = kw.value
            if "family" not in refs:
                offenders.append(f"{rel}:{node.lineno} (no family argument)")
                continue
            for role, val in refs.items():
                if not (isinstance(val, ast.Constant)
                        and isinstance(val.value, str)):
                    if role == "ratio_of" and isinstance(val, ast.Constant) \
                            and val.value is None:
                        continue
                    offenders.append(
                        f"{rel}:{node.lineno} ({role} is not a string literal)")
                elif val.value not in declared:
                    offenders.append(
                        f"{rel}:{node.lineno} ({role}={val.value!r} is not a "
                        "registry-declared or derived family)")
    assert found >= 5  # the scan saw default_rules()
    assert not offenders, (
        "alert rules referencing unknown metric families (declare the "
        f"family in a registry, or fix the rule): {offenders}")


# ------------------------------------------------------------- slow tier


@pytest.mark.slow
def test_churning_crashed_gang_postmortem_carries_alert_and_compile_churn(tmp_path):
    """ISSUE 10 acceptance (gang half, reusing the PR 2 fault injector): a
    shape-churning gang member fires the recompile alert, then a crash is
    injected — the postmortem's merged event stream contains the alert AND
    the attributed compile events, and the compile_churn section names the
    churning fn. The respawned incarnation trains steady-shape and reports
    per-fn compiles FLAT after warmup."""
    from deeplearning4j_tpu.parallel import GangSupervisor

    env = {"TDL_MP_OUT": str(tmp_path / "out.json"),
           "TDL_MATMUL_PRECISION": "float32",
           "TDL_FAULT_SPEC": "crash@iter=10,rank=1",
           "TDL_FLIGHT_INTERVAL": "0",
           "TDL_METRICS_SPOOL_INTERVAL": "0"}
    sup = GangSupervisor(f"{WORKERS}:churn_train", n_processes=2,
                         n_local_devices=2, extra_env=env,
                         workdir=str(tmp_path / "gang"),
                         heartbeat_interval=0.0, startup_grace=300.0,
                         backoff_base=0.1, kill_grace=1.0, max_restarts=3,
                         registry=MetricsRegistry())
    results = sup.run(timeout=540.0)
    for r in results:
        assert r.returncode == 0, f"rank {r.rank} failed:\n{r.stderr[-3000:]}"
    assert sup.restarts >= 1

    with open(sup.postmortem_path) as f:
        pm = json.load(f)
    assert pm["classification"] == "crash"
    # the fired recompile alert is ON the postmortem timeline
    alerts = [e for e in pm["events"] if e["kind"] == "alert"]
    assert any(e["rule"] == "recompiles_after_warmup" for e in alerts)
    # attributed compile events made it too, and the churn section names
    # the churning fit fn as the top offender for some rank
    compiles = [e for e in pm["events"] if e["kind"] == "compile"]
    assert any(e["fn"] == "MultiLayerNetwork.train_step" for e in compiles)
    churn_fns = {row["fn"] for row in pm["compile_churn"]}
    assert "MultiLayerNetwork.train_step" in churn_fns

    # final (steady, respawned) incarnation: flat after warmup per fn, and
    # the steady evaluation before churn never fired
    for rank in (0, 1):
        with open(env["TDL_MP_OUT"] + f".rank{rank}") as f:
            out = json.load(f)
        assert out["incarnation"] >= 1
        assert not out["steady_firing"]
        assert not out["churn_firing"]  # no churn in the steady incarnation
        assert out["per_fn_compiles_final"] == out["per_fn_compiles_warmup"]
