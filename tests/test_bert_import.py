"""BERT checkpoint import (VERDICT r1 Missing #1, SURVEY §2.2 J14):
HF weights → transformer params, golden-output verified, fine-tunable
under dp sharding. Uses a randomly-initialized local HF model — zero
network, same code path as a downloaded checkpoint."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from deeplearning4j_tpu.common import jax_compat
from deeplearning4j_tpu.models.bert_import import (
    config_from_hf,
    import_hf_bert,
    params_from_state_dict,
)
from deeplearning4j_tpu.models.transformer import forward, loss_fn, make_train_step


def _small_hf_bert(seed=0):
    torch.manual_seed(seed)
    cfg = transformers.BertConfig(
        vocab_size=120, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=48, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        hidden_act="gelu",
    )
    return transformers.BertForMaskedLM(cfg).eval()


def test_import_forward_matches_hf_golden():
    model = _small_hf_bert()
    params, cfg = import_hf_bert(model)
    assert cfg.norm_position == "post" and not cfg.gelu_approximate

    rs = np.random.RandomState(0)
    tokens = rs.randint(0, 120, (3, 16))
    segments = np.zeros((3, 16), np.int64)

    with torch.no_grad():
        golden = model(input_ids=torch.tensor(tokens),
                       token_type_ids=torch.tensor(segments)).logits.numpy()

    ours = np.asarray(forward(params, jnp.asarray(tokens, jnp.int32), cfg,
                              segments=jnp.asarray(segments, jnp.int32),
                              train=False))
    assert ours.shape == golden.shape
    np.testing.assert_allclose(ours, golden, atol=1e-3, rtol=1e-3)


def test_import_respects_attention_mask():
    model = _small_hf_bert(1)
    params, cfg = import_hf_bert(model)
    rs = np.random.RandomState(1)
    tokens = rs.randint(0, 120, (2, 12))
    mask = np.ones((2, 12), np.int64)
    mask[:, 8:] = 0

    with torch.no_grad():
        golden = model(input_ids=torch.tensor(tokens),
                       attention_mask=torch.tensor(mask)).logits.numpy()
    ours = np.asarray(forward(params, jnp.asarray(tokens, jnp.int32), cfg,
                              pad_mask=jnp.asarray(mask, jnp.float32),
                              train=False))
    # only compare unmasked positions (HF computes garbage at padded ones too,
    # but identical garbage is not contractual)
    np.testing.assert_allclose(ours[:, :8], golden[:, :8], atol=1e-3, rtol=1e-3)


def test_imported_model_fine_tunes_under_dp():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.models.transformer import batch_specs
    from deeplearning4j_tpu.nn.updaters import Adam

    model = _small_hf_bert(2)
    params, cfg = import_hf_bert(model)
    devices = np.array(jax.devices()[:4]).reshape(4, 1, 1)
    mesh = Mesh(devices, ("dp", "tp", "sp"))

    updater = Adam(1e-4)
    opt = updater.init(params)
    step = jax.jit(make_train_step(cfg, updater), donate_argnums=(0, 1))
    rs = np.random.RandomState(3)
    B, T = 8, 16
    bspec = batch_specs(cfg)
    batch = {
        "tokens": jnp.asarray(rs.randint(0, 120, (B, T)), jnp.int32),
        "labels": jnp.asarray(rs.randint(0, 120, (B, T)), jnp.int32),
        "weights": jnp.asarray((rs.rand(B, T) < 0.15).astype(np.float32)),
    }
    batch = {k: jax.device_put(v, NamedSharding(mesh, bspec[k])) for k, v in batch.items()}
    with jax_compat.set_mesh(mesh):
        losses = []
        for i in range(4):
            params, opt, loss = step(params, opt, batch,
                                     jnp.asarray(i, jnp.int32), jax.random.key(i))
            losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # fine-tuning moves the loss


def test_plain_bertmodel_without_head_imports():
    torch.manual_seed(4)
    hf_cfg = transformers.BertConfig(
        vocab_size=80, hidden_size=16, num_hidden_layers=1,
        num_attention_heads=2, intermediate_size=32,
        max_position_embeddings=32, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)
    base = transformers.BertModel(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg)
    params = params_from_state_dict(base.state_dict(), cfg)
    out = forward(params, jnp.zeros((1, 8), jnp.int32), cfg, train=False)
    assert out.shape == (1, 8, 80)
    assert np.isfinite(np.asarray(out)).all()
