"""VariationalAutoencoder (C16): ELBO training, reconstruction, sampling."""

import numpy as np
import pytest

from deeplearning4j_tpu.models.vae import VariationalAutoencoder


def _binary_pattern_data(n=512, seed=0):
    """Two prototype binary patterns + bit noise — easy VAE fodder."""
    rs = np.random.RandomState(seed)
    protos = (rs.rand(2, 16) > 0.5).astype(np.float32)
    which = rs.randint(0, 2, n)
    x = protos[which].copy()
    flip = rs.rand(n, 16) < 0.05
    x[flip] = 1.0 - x[flip]
    return x, which


def test_vae_elbo_decreases_and_reconstructs():
    x, which = _binary_pattern_data()
    vae = VariationalAutoencoder(n_in=16, latent=4, encoder_layers=(32,),
                                 decoder_layers=(32,), learning_rate=3e-3, seed=1)
    vae.fit(x, epochs=30, batch_size=128)
    assert vae.loss_curve[-1] < vae.loss_curve[0]
    rec = vae.reconstruct(x[:64])
    acc = float(np.mean((rec > 0.5) == (x[:64] > 0.5)))
    assert acc > 0.9, acc


def test_vae_latent_separates_prototypes():
    x, which = _binary_pattern_data()
    vae = VariationalAutoencoder(n_in=16, latent=2, seed=2)
    vae.fit(x, epochs=25, batch_size=128)
    z = vae.activate(x)
    c0, c1 = z[which == 0].mean(0), z[which == 1].mean(0)
    spread = (z[which == 0].std(0).mean() + z[which == 1].std(0).mean()) / 2
    assert np.linalg.norm(c0 - c1) > spread, (c0, c1, spread)


def test_reconstruction_probability_ranks_inliers():
    x, _ = _binary_pattern_data()
    vae = VariationalAutoencoder(n_in=16, latent=4, seed=3)
    vae.fit(x, epochs=25, batch_size=128)
    inlier = vae.reconstruction_probability(x[:32], num_samples=24)
    rs = np.random.RandomState(9)
    outlier = vae.reconstruction_probability(
        (rs.rand(32, 16) > 0.5).astype(np.float32), num_samples=24)
    assert inlier.mean() > outlier.mean() + 1.0


def test_generate_from_latent():
    vae = VariationalAutoencoder(n_in=16, latent=4, seed=4)
    out = vae.generate(np.zeros((5, 4), np.float32))
    assert out.shape == (5, 16)
    assert np.all((out >= 0) & (out <= 1))  # bernoulli means


def test_gaussian_reconstruction_mode():
    rs = np.random.RandomState(5)
    x = rs.randn(256, 8).astype(np.float32) * 0.5
    vae = VariationalAutoencoder(n_in=8, latent=3, reconstruction="gaussian", seed=5)
    vae.fit(x, epochs=10, batch_size=64)
    assert np.isfinite(vae.loss_curve[-1])
    assert vae.reconstruct(x[:4]).shape == (4, 8)
