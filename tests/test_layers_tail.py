"""Layer-config tail: forward semantics + gradient flow + serde round-trip.

Reference parity: org.deeplearning4j.nn.conf.layers.* (SURVEY §2.4 C1;
VERDICT r4 missing #6). Forward outputs are checked against independent
numpy math; every parameterized layer gets a grad-flow check through
jax.grad; JSON round-trip covers the nested-wrapper configs.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import InputType, Layer
from deeplearning4j_tpu.nn.layers_tail import (
    Cnn3DLossLayer,
    CnnLossLayer,
    Convolution2D,
    Cropping1D,
    Cropping3D,
    Deconvolution3D,
    ElementWiseMultiplicationLayer,
    FrozenLayerWithBackprop,
    GravesBidirectionalLSTM,
    MaskLayer,
    MaskZeroLayer,
    Pooling1D,
    Pooling2D,
    RnnLossLayer,
    SpaceToBatch,
    SpaceToDepth,
    TimeDistributed,
    Upsampling1D,
    Upsampling3D,
    ZeroPadding1DLayer,
    ZeroPadding3DLayer,
)

R = np.random.RandomState(3)
RNN_X = jnp.asarray(R.randn(2, 3, 5), jnp.float32)     # [B,C,T]
RNN_IT = InputType.recurrent(3, 5)
CNN_X = jnp.asarray(R.randn(2, 4, 6, 6), jnp.float32)  # [B,C,H,W]
CNN_IT = InputType.convolutional(6, 6, 4)
C3D_X = jnp.asarray(R.randn(1, 2, 4, 4, 4), jnp.float32)
C3D_IT = InputType.convolutional3d(4, 4, 4, 2)


def _grad_flows(layer, params, x, it):
    g = jax.grad(lambda p, xx: jnp.sum(
        layer.forward(p, xx, it, training=False) ** 2), argnums=(0, 1))(params, x)
    leaves = jax.tree.leaves(g)
    assert leaves and all(np.all(np.isfinite(np.asarray(l))) for l in leaves)
    return g


class TestRecurrentTail:
    def test_graves_bidirectional_sums_directions(self):
        """The reference's GravesBidirectionalLSTMLayer adds fwd+bwd passes."""
        layer = GravesBidirectionalLSTM(n_in=3, n_out=4)
        p = layer.init_params(jax.random.key(0), RNN_IT)
        out = layer.forward(p, RNN_X, RNN_IT, training=False)
        assert out.shape == (2, 4, 5)
        # manual: run the inner cell both ways and add
        cell = layer._cell()
        f = cell.forward(p["fwd"], RNN_X, RNN_IT, training=False)
        b = jnp.flip(cell.forward(p["bwd"], jnp.flip(RNN_X, 2), RNN_IT,
                                  training=False), 2)
        np.testing.assert_allclose(np.asarray(out), np.asarray(f + b), atol=1e-6)
        assert layer.output_type(RNN_IT).size == 4
        _grad_flows(layer, p, RNN_X, RNN_IT)

    def test_time_distributed_matches_per_step(self):
        from deeplearning4j_tpu.nn.conf import DenseLayer

        layer = TimeDistributed(underlying=DenseLayer(n_in=3, n_out=6,
                                                      activation="relu"))
        p = layer.init_params(jax.random.key(1), RNN_IT)
        out = layer.forward(p, RNN_X, RNN_IT, training=False)
        assert out.shape == (2, 6, 5)
        step2 = layer.underlying.forward(p, RNN_X[:, :, 2],
                                         InputType.feed_forward(3), training=False)
        np.testing.assert_allclose(np.asarray(out[:, :, 2]), np.asarray(step2),
                                   atol=1e-6)
        assert layer.output_type(RNN_IT).size == 6
        _grad_flows(layer, p, RNN_X, RNN_IT)


class TestMaskLayers:
    def test_mask_layer(self):
        layer = MaskLayer()
        mask = jnp.asarray([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], jnp.float32)
        out = layer.forward({}, RNN_X, RNN_IT, training=False, mask=mask)
        np.testing.assert_array_equal(np.asarray(out[0, :, 3:]), 0.0)
        np.testing.assert_allclose(np.asarray(out[1]), np.asarray(RNN_X[1]))
        assert np.allclose(np.asarray(layer.forward({}, RNN_X, RNN_IT,
                                                    training=False)), RNN_X)

    def test_mask_zero_layer(self):
        from deeplearning4j_tpu.nn.conf import SimpleRnn

        layer = MaskZeroLayer(underlying=SimpleRnn(n_in=3, n_out=4),
                              mask_value=9.0)
        x = RNN_X.at[:, :, -1].set(9.0)  # last step = sentinel on every feature
        p = layer.init_params(jax.random.key(2), RNN_IT)
        out = layer.forward(p, x, RNN_IT, training=False)
        # the underlying layer must see zeros at the sentinel step
        ref = layer.underlying.forward(p, x.at[:, :, -1].set(0.0), RNN_IT,
                                       training=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


class TestLossLayers:
    def test_rnn_loss_layer_masked(self):
        layer = RnnLossLayer(loss="mse")
        labels = jnp.zeros_like(RNN_X)
        mask = jnp.asarray([[1, 1, 0, 0, 0], [1, 1, 1, 1, 1]], jnp.float32)
        loss = layer.compute_loss({}, RNN_X, labels, RNN_IT, training=False,
                                  mask=mask)
        x = np.asarray(RNN_X)
        m = np.asarray(mask)
        # nd4j LossMSE contract: SUM over outputs per step, mean over
        # unmasked example-steps
        expected = (((x ** 2).sum(1) * m).sum()) / m.sum()
        np.testing.assert_allclose(float(loss), expected, rtol=1e-5)

    def test_cnn_loss_layer(self):
        layer = CnnLossLayer(loss="mse")
        labels = jnp.zeros_like(CNN_X)
        loss = layer.compute_loss({}, CNN_X, labels, CNN_IT, training=False)
        np.testing.assert_allclose(float(loss),
                                   (np.asarray(CNN_X) ** 2).sum(1).mean(),
                                   rtol=1e-5)

    def test_cnn3d_loss_layer(self):
        layer = Cnn3DLossLayer(loss="mse")
        loss = layer.compute_loss({}, C3D_X, jnp.zeros_like(C3D_X), C3D_IT,
                                  training=False)
        np.testing.assert_allclose(float(loss),
                                   (np.asarray(C3D_X) ** 2).sum(1).mean(),
                                   rtol=1e-5)


class TestMiscTail:
    def test_elementwise_multiplication(self):
        layer = ElementWiseMultiplicationLayer(n_in=4, n_out=4)
        it = InputType.feed_forward(4)
        p = layer.init_params(jax.random.key(3), it)
        p = {"W": jnp.asarray([1.0, 2.0, 3.0, 4.0]), "b": jnp.ones(4)}
        x = jnp.ones((2, 4))
        out = layer.forward(p, x, it, training=False)
        np.testing.assert_array_equal(np.asarray(out), [[2, 3, 4, 5]] * 2)
        _grad_flows(layer, p, x, it)

    def test_frozen_with_backprop_delegates_and_freezes(self):
        from deeplearning4j_tpu.nn.conf import DenseLayer

        layer = FrozenLayerWithBackprop(underlying=DenseLayer(n_in=3, n_out=2))
        assert layer.frozen is True
        it = InputType.feed_forward(3)
        p = layer.init_params(jax.random.key(4), it)
        out = layer.forward(p, jnp.ones((2, 3)), it, training=False)
        ref = layer.underlying.forward(p, jnp.ones((2, 3)), it, training=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref))
        # gradients flow THROUGH (wrt input) even though params are frozen
        g = jax.grad(lambda xx: jnp.sum(layer.forward(p, xx, it, training=False)))(
            jnp.ones((2, 3)))
        assert np.any(np.asarray(g) != 0)


class TestSpaceReshapes:
    def test_space_to_depth_roundtrip_values(self):
        layer = SpaceToDepth(block_size=2)
        out = layer.forward({}, CNN_X, CNN_IT, training=False)
        assert out.shape == (2, 16, 3, 3)
        # block (0,0) of image 0, channel 0 lands in the first depth group
        np.testing.assert_allclose(float(out[0, 0, 0, 0]), float(CNN_X[0, 0, 0, 0]))
        ot = layer.output_type(CNN_IT)
        assert (ot.height, ot.width, ot.channels) == (3, 3, 16)

    def test_space_to_batch(self):
        layer = SpaceToBatch(block_size=(2, 2))
        out = layer.forward({}, CNN_X, CNN_IT, training=False)
        assert out.shape == (8, 4, 3, 3)
        np.testing.assert_allclose(np.asarray(out[0, :, 0, 0]),
                                   np.asarray(CNN_X[0, :, 0, 0]))


class TestCropPadUpsample:
    def test_cropping1d(self):
        out = Cropping1D(cropping=(1, 2)).forward({}, RNN_X, RNN_IT, training=False)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(RNN_X[:, :, 1:3]))

    def test_cropping3d(self):
        out = Cropping3D(cropping=(1, 1, 0, 1, 2, 0)).forward(
            {}, C3D_X, C3D_IT, training=False)
        np.testing.assert_array_equal(np.asarray(out),
                                      np.asarray(C3D_X[:, :, 1:3, 0:3, 2:4]))

    def test_zero_padding_1d_3d(self):
        out = ZeroPadding1DLayer(padding=(2, 1)).forward({}, RNN_X, RNN_IT,
                                                         training=False)
        assert out.shape == (2, 3, 8)
        np.testing.assert_array_equal(np.asarray(out[:, :, :2]), 0.0)
        out3 = ZeroPadding3DLayer(padding=(1, 0, 0, 1, 2, 2)).forward(
            {}, C3D_X, C3D_IT, training=False)
        assert out3.shape == (1, 2, 5, 5, 8)

    def test_upsampling_1d_3d(self):
        out = Upsampling1D(size=3).forward({}, RNN_X, RNN_IT, training=False)
        np.testing.assert_array_equal(np.asarray(out),
                                      np.repeat(np.asarray(RNN_X), 3, 2))
        out3 = Upsampling3D(size=(2, 1, 2)).forward({}, C3D_X, C3D_IT,
                                                    training=False)
        assert out3.shape == (1, 2, 8, 4, 8)

    def test_deconvolution3d(self):
        layer = Deconvolution3D(n_in=2, n_out=3, kernel_size=(2, 2, 2),
                                stride=(2, 2, 2))
        p = layer.init_params(jax.random.key(5), C3D_IT)
        out = layer.forward(p, C3D_X, C3D_IT, training=False)
        assert out.shape == (1, 3, 8, 8, 8)
        ot = layer.output_type(C3D_IT)
        assert (ot.depth, ot.height, ot.width, ot.channels) == (8, 8, 8, 3)
        _grad_flows(layer, p, C3D_X, C3D_IT)


class TestAliasesAndSerde:
    def test_dl4j_alias_classes(self):
        assert issubclass(Convolution2D, Layer)
        assert Pooling2D().pooling_type == "max"
        assert Pooling1D().has_params() is False

    @pytest.mark.parametrize("layer", [
        GravesBidirectionalLSTM(n_in=3, n_out=4),
        MaskLayer(),
        RnnLossLayer(loss="mse"),
        CnnLossLayer(loss="mse"),
        ElementWiseMultiplicationLayer(n_in=4, n_out=4),
        SpaceToDepth(block_size=2),
        Cropping1D(cropping=(1, 1)),
        ZeroPadding3DLayer(padding=(1, 1, 1, 1, 1, 1)),
        Upsampling1D(size=2),
        Deconvolution3D(n_in=2, n_out=3),
    ])
    def test_json_roundtrip(self, layer):
        d = layer.to_json()
        back = Layer.from_json(d)
        assert type(back) is type(layer)
        assert back.to_json() == d

    def test_nested_wrapper_roundtrip(self):
        """Layer.from_json recurses nested layer configs (r5 fix — also
        covers Bidirectional.fwd upstream)."""
        from deeplearning4j_tpu.nn.conf import DenseLayer

        for wrapper in (TimeDistributed(underlying=DenseLayer(n_in=3, n_out=6)),
                        FrozenLayerWithBackprop(underlying=DenseLayer(n_in=3, n_out=2)),
                        MaskZeroLayer(underlying=DenseLayer(n_in=3, n_out=2),
                                      mask_value=9.0)):
            back = Layer.from_json(wrapper.to_json())
            assert type(back) is type(wrapper)
            assert isinstance(back.underlying, DenseLayer)
            assert back.underlying.n_out == wrapper.underlying.n_out
