"""Block-paged KV cache, CoW prefix sharing, speculative decoding (ISSUE 17).

The contracts under test: paged decode is TOKEN-IDENTICAL to the dense-era
reference (and to naive full-forward generation) behind the same
one-signature decode step; residency is priced in BLOCKS at admission (the
429/400 paths fire at the door, never mid-decode); copy-on-write prefix
sharing deduplicates physical blocks without changing any sequence's
output; and speculative decoding changes wall clock, never text.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.models import transformer as tfm
from deeplearning4j_tpu.monitoring import MetricsRegistry
from deeplearning4j_tpu.serving import (GenerativeInferenceExecutor,
                                        JsonModelServer, TraceSpec)


def _cfg(**kw):
    kw.setdefault("causal", True)
    kw.setdefault("dropout", 0.0)
    kw.setdefault("param_dtype", jnp.float32)
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("vocab_size", 97)
    kw.setdefault("max_len", 64)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_layers", 2)
    kw.setdefault("d_ff", 64)
    return tfm.TransformerConfig(**kw)


def _params(cfg, seed=0):
    import jax

    return tfm.init_params(jax.random.key(seed), cfg)


CFG = _cfg()
PARAMS = _params(CFG)
_SHARED_DENSE = []


def _dense_generate(params, cfg, prompts, max_new):
    """The PR 12 dense-era reference path, pinned explicitly.  References
    against the shared default model reuse ONE compiled dense pool so the
    tier-1 suite does not pay a fresh XLA compile per test."""
    if params is PARAMS:
        if not _SHARED_DENSE:
            _SHARED_DENSE.append(tfm.DecodeSlotPool(PARAMS, CFG, slots=6))
        return tfm.generate(params, prompts, max_new, cfg,
                            pool=_SHARED_DENSE[0])
    pool = tfm.DecodeSlotPool(params, cfg, slots=max(2, len(prompts)))
    return tfm.generate(params, prompts, max_new, cfg, pool=pool)


# ------------------------------------------------------------------ tentpole


def test_paged_decode_matches_dense_and_naive_under_churn():
    """The parity pin: paged generation == dense-era generation, token for
    token, over ragged prompts — and the paged decode step is traced
    exactly ONCE whatever the admission/retirement churn."""
    cfg, params = CFG, PARAMS
    rs = np.random.RandomState(1)
    prompts = [rs.randint(1, 97, n).tolist() for n in (3, 9, 17, 5, 12, 2)]
    expected = _dense_generate(params, cfg, prompts, 8)

    pool = tfm.PagedDecodeSlotPool(params, cfg, slots=3, block_T=8)
    got = tfm.generate(params, prompts, 8, cfg, pool=pool)
    assert got == expected
    # 6 sequences through 3 slots forced churn; still one XLA signature
    assert pool.decode_traces == 1
    assert pool.free_slots == pool.slots
    assert pool.block_stats()["blocks_free"] == pool.total_blocks


def test_generate_routes_through_paged_pool_by_default(monkeypatch):
    """Offline generate() without an explicit pool builds a paged pool (the
    satellite routing pin) — and the output still matches the dense era."""
    cfg, params = CFG, PARAMS
    built = {}
    real = tfm.PagedDecodeSlotPool

    class Spy(real):
        def __init__(self, *a, **kw):
            built["kw"] = kw
            super().__init__(*a, **kw)

    monkeypatch.setattr(tfm, "PagedDecodeSlotPool", Spy)
    prompts = [[5, 9, 2], [7, 3]]
    out = tfm.generate(params, prompts, 6, cfg)
    assert built, "default generate() did not build a PagedDecodeSlotPool"
    assert out == _dense_generate(params, cfg, prompts, 6)


def test_block_accounting_and_admission_priced_in_blocks():
    cfg = _cfg(max_len=32)
    params = _params(cfg)
    # 9 usable blocks of 8 positions
    pool = tfm.PagedDecodeSlotPool(params, cfg, slots=8, block_T=8,
                                   n_blocks=10)
    assert pool.total_blocks == 9
    assert pool.request_blocks(5, 4) == 2  # span 9 -> 2 blocks
    # never-fits is a ValueError at the door, not a retryable 429
    with pytest.raises(ValueError, match="exceeds"):
        pool.admit(list(range(1, 30)), max_new_tokens=8)
    s0, _ = pool.admit([1, 2, 3, 4, 5], max_new_tokens=18)  # span 23 -> 3
    s1, _ = pool.admit([6, 7, 8, 9, 10], max_new_tokens=18)
    assert pool.block_stats()["blocks_free"] == 3
    # 4 blocks wanted, 3 free: retryable refusal, pool state untouched
    assert not pool.can_admit([11, 12], max_new_tokens=28)
    with pytest.raises(tfm.NoFreeBlocksError) as ei:
        pool.admit([11, 12], max_new_tokens=28)
    assert ei.value.retry_admission
    assert pool.free_slots == 6
    pool.release(s0)
    assert pool.can_admit([11, 12], max_new_tokens=28)
    pool.release(s1)
    assert pool.block_stats()["blocks_free"] == 9


def test_cow_prefix_sharing_dedups_blocks_without_changing_tokens():
    """Admissions sharing a prompt prefix map the same physical blocks
    (refcount > 1 in cow_shared_blocks) and still generate exactly what
    they would alone."""
    cfg, params = CFG, PARAMS
    rs = np.random.RandomState(3)
    prefix = rs.randint(1, 97, 16).tolist()  # two full 8-blocks
    solo_a, solo_b = _dense_generate(params, cfg,
                                     [prefix + [11, 12],
                                      prefix + [13, 14, 15]], 6)
    a, b = prefix + [11, 12], prefix + [13, 14, 15]

    pool = tfm.PagedDecodeSlotPool(params, cfg, slots=4, block_T=8)
    free0 = pool.block_stats()["blocks_free"]
    sa, fa = pool.admit(a, max_new_tokens=6)
    used_a = free0 - pool.block_stats()["blocks_free"]
    sb, fb = pool.admit(b, max_new_tokens=6)
    used_b = (free0 - used_a) - pool.block_stats()["blocks_free"]
    stats = pool.block_stats()
    assert stats["cow_shared_blocks"] == 2  # the two full prefix blocks
    assert stats["cow_saved_blocks"] >= 2
    assert used_b < used_a  # the sharer did not pay for the prefix again

    toks = {sa: [fa], sb: [fb]}
    while len(toks[sa]) < 6 or len(toks[sb]) < 6:
        for slot, new in pool.step().items():
            toks[slot].extend(new)
    pool.release(sa), pool.release(sb)
    assert toks[sa] == solo_a
    assert toks[sb] == solo_b
    assert pool.block_stats()["blocks_free"] == free0
    assert pool.block_stats()["cow_shared_blocks"] == 0


def _identity_tail_draft(params, cfg, draft_layers):
    """Zero the tail layers' residual-writing mats: pre-LN makes them exact
    no-ops, so the truncated draft predicts the target argmax exactly.
    Returns (target_params, draft_params, draft_cfg) without mutating the
    caller's tree."""
    import dataclasses

    blocks = [dict(b) for b in params["blocks"]]
    for blk in blocks[draft_layers:]:
        blk["out_w"] = jnp.zeros_like(blk["out_w"])
        blk["ffn_w2"] = jnp.zeros_like(blk["ffn_w2"])
    target_params = {"embed": params["embed"], "mlm": params["mlm"],
                     "blocks": blocks}
    draft_cfg = dataclasses.replace(cfg, n_layers=draft_layers)
    draft_params = {"embed": params["embed"], "mlm": params["mlm"],
                    "blocks": blocks[:draft_layers]}
    return target_params, draft_params, draft_cfg


@pytest.mark.parametrize("draft_kind", ["random", "identity_tail"])
def test_speculative_decode_is_token_identical(draft_kind):
    """Speculation may only change wall clock: with a draft that agrees
    with the target (acceptance ~1.0) AND one that never does (acceptance
    ~0), the emitted tokens equal plain greedy decode exactly, budgets
    clamp mid-window, and the step stays one XLA signature.  The
    identity-tail branch also pins eos-inside-an-accepted-window on the
    same compiled pool."""
    cfg = CFG
    rs = np.random.RandomState(4)
    prompts = [rs.randint(1, 97, n).tolist() for n in (3, 10, 6)]
    max_new = 7  # NOT a multiple of spec_tokens+1: pins the budget clamp
    eos_prompt = [5, 9, 2]
    if draft_kind == "identity_tail":
        params, draft_params, draft_cfg = _identity_tail_draft(PARAMS, cfg, 1)
        # one off-default dense pool serves both the parity and eos refs:
        # greedy decode is prefix-stable, so max_new=8 covers max_new=7
        refs = _dense_generate(params, cfg, prompts + [eos_prompt], 8)
        expected, eos_ref = [r[:max_new] for r in refs[:3]], refs[3]
    else:
        params = PARAMS
        draft_cfg = _cfg(n_layers=1)
        draft_params = _params(draft_cfg, seed=9)  # unrelated weights
        expected = _dense_generate(params, cfg, prompts, max_new)

    pool = tfm.PagedDecodeSlotPool(
        params, cfg, slots=3, block_T=8,
        draft_params=draft_params, draft_cfg=draft_cfg, spec_tokens=3)
    got = tfm.generate(params, prompts, max_new, cfg, pool=pool)
    assert got == expected
    assert pool.decode_traces == 1
    stats = pool.block_stats()
    assert stats["spec_proposed"] > 0
    rate = stats["spec_accepted"] / stats["spec_proposed"]
    if draft_kind == "identity_tail":
        assert rate == pytest.approx(1.0)
        # EOS inside an accepted window retires the sequence AT the eos,
        # not at the window edge — same truncation the dense pool applies
        eos = eos_ref[2]
        cut = eos_ref.index(eos) + 1
        out = tfm.generate(params, [eos_prompt], 8, cfg, pool=pool,
                           eos_id=eos)
        assert out == [eos_ref[:cut]]
        assert pool.decode_traces == 1  # eos handling is host-side
    else:
        assert rate < 0.5  # an unrelated draft earns ~nothing


def test_failed_donated_step_resets_arena_and_executor_evicts_riders():
    """A failed donated decode call must surface KvCacheLostError with
    every rider marked lost, and leave the pool healed (fresh arena, all
    blocks free) — not poisoned with deleted buffers.  Then the same pool
    behind the EXECUTOR: a failed step evicts the riders (counted under
    reason="cache_lost"), the arena resets, and the next request
    succeeds."""
    cfg, params = CFG, PARAMS
    pool = tfm.PagedDecodeSlotPool(params, cfg, slots=2, block_T=8)
    pool.admit([3, 1, 4], max_new_tokens=4)
    pool.admit([2, 7], max_new_tokens=4)

    def boom(*a, **k):
        raise RuntimeError("injected device fault")

    real = pool._decode_fn
    pool._decode_fn = boom
    with pytest.raises(tfm.KvCacheLostError) as ei:
        pool.step()
    assert ei.value.all_sequences_lost
    pool._decode_fn = real
    assert pool.free_slots == pool.slots
    assert pool.block_stats()["blocks_free"] == pool.total_blocks
    prompt = [5, 9, 2]
    out = tfm.generate(params, [prompt], 4, cfg, pool=pool)
    assert out == _dense_generate(params, cfg, [prompt], 4)

    reg = MetricsRegistry()
    ex = GenerativeInferenceExecutor(pool, max_queue=8, registry=reg).start()
    try:
        def boom_once(*a, **k):
            pool._decode_fn = real  # fail exactly one step
            raise RuntimeError("injected device fault")

        pool._decode_fn = boom_once
        fut = ex.submit([3, 1, 4], max_new_tokens=8)
        assert fut.wait(30.0)
        assert getattr(fut.error, "all_sequences_lost", False)
        ok = ex.submit([5, 9, 2], max_new_tokens=3)
        assert ok.wait(30.0) and ok.error is None
        assert len(ok.tokens) == 3
        snap = reg.get("tdl_decode_evicted_total").snapshot()["series"]
        reasons = {tuple(s["labels"].values()): s["value"] for s in snap}
        assert reasons.get(("cache_lost",)) == 1
    finally:
        ex.stop(drain=False)


# ------------------------------------------------- admission at the door


def test_server_rejects_block_overrun_at_the_door():
    """Satellite bugfix pin: an X-Max-New-Tokens (or prompt) the block
    budget can never satisfy is a 400 AT ADMISSION — the request must not
    enter decode and get evicted mid-flight later."""
    cfg = _cfg(max_len=32)
    params = _params(cfg)
    # tiny arena: 2 usable blocks of 8, inside a 32-position max_len — the
    # BLOCK budget, not max_len, must be what refuses
    pool = tfm.PagedDecodeSlotPool(params, cfg, slots=4, block_T=8,
                                   n_blocks=3)
    server = JsonModelServer(None, generative_session=pool,
                             default_max_new_tokens=4, warmup_input=[1],
                             registry=MetricsRegistry()).start()
    try:
        assert server.wait_ready(60.0)

        def post(tokens, **headers):
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/predict",
                data=json.dumps(tokens).encode(),
                headers={"Content-Type": "application/json", **headers})
            with urllib.request.urlopen(req, timeout=15) as resp:
                return resp.status, json.loads(resp.read())

        # span 23 fits max_len but wants 3 blocks of an arena with 2: 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            post([1, 2, 3], **{"X-Max-New-Tokens": "20"})
        assert ei.value.code == 400
        assert b"KV blocks" in ei.value.read()
        assert pool.occupancy == 0  # it never touched a slot
        # a span past max_len itself still 400s with the cache message
        with pytest.raises(urllib.error.HTTPError) as ei:
            post([1, 2, 3], **{"X-Max-New-Tokens": "64"})
        assert ei.value.code == 400
        # the same budget that fits sails through
        status, out = post([1, 2, 3], **{"X-Max-New-Tokens": "4"})
        assert status == 200 and len(out["output"]) == 4

        # GET /stats exposes the block truth for capacity dashboards
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/stats", timeout=15) as r:
            stats = json.loads(r.read())["stats"]
        assert stats["blocks"]["blocks_total"] == 2
        assert stats["blocks"]["blocks_free"] == 2
    finally:
        server.stop()


def test_executor_queues_retryable_block_exhaustion():
    """Transient block exhaustion (fits the arena, just not NOW) must queue
    behind the live sequences and complete once blocks free up — not 400
    and not busy-loop."""
    cfg = _cfg(max_len=32)
    params = _params(cfg)
    pool = tfm.PagedDecodeSlotPool(params, cfg, slots=4, block_T=8,
                                   n_blocks=7)  # 6 usable blocks
    ex = GenerativeInferenceExecutor(pool, max_queue=8,
                                     registry=MetricsRegistry()).start()
    try:
        # 3 blocks each: two in flight exhaust the arena
        futs = [ex.submit([i + 1, i + 2], max_new_tokens=20)
                for i in range(3)]
        for f in futs:
            assert f.wait(60.0) and f.error is None
            assert len(f.tokens) == 20
    finally:
        ex.stop(drain=True)
    assert pool.block_stats()["blocks_free"] == 6


# ------------------------------------------------------- shared-prefix trace


def test_trace_spec_shared_prefix_mix_round_trips():
    spec = TraceSpec(duration_s=1.0, base_rate=10.0, seed=5,
                     prefix_tenants=3, prefix_len=12, suffix_len=4,
                     prompt_vocab=50)
    fn = spec.prompt_fn()
    a0, b0 = fn(0), fn(1)
    assert len(a0) == 16 and len(b0) == 16
    assert fn(0) == a0  # deterministic per index
    assert fn(3)[:12] == a0[:12]  # same tenant -> same prefix
    assert fn(3)[12:] != a0[12:]  # ...different suffix
    assert fn(1)[:12] != a0[:12]  # different tenant -> different prefix
    assert all(1 <= t < 50 for t in a0 + b0)

    clone = TraceSpec.from_dict(spec.to_dict())
    assert clone.prompt_fn()(7) == fn(7)
    # without the mix, prompt_fn is refused rather than guessing shapes
    with pytest.raises(ValueError, match="prefix_tenants"):
        TraceSpec(duration_s=1.0, base_rate=10.0).prompt_fn()


def test_trace_spec_shared_prefix_validation():
    with pytest.raises(ValueError, match="prefix_len"):
        TraceSpec(duration_s=1.0, base_rate=1.0, prefix_tenants=2,
                  prefix_len=0)
    with pytest.raises(ValueError, match="prompt_vocab"):
        TraceSpec(duration_s=1.0, base_rate=1.0, prefix_tenants=2,
                  prompt_vocab=1)
