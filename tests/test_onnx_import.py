"""ONNX import goldens (VERDICT r4 missing #3, SURVEY §0.5 J14).

The golden .onnx bytes are BUILT through the importer's own wire-format
writer (`wire_field`) — genuine ONNX protobuf wire encoding end to end —
because this image ships neither ``onnx`` nor ``onnxscript`` (torch cannot
export). Expected outputs come from independent numpy implementations.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.onnx_import import (
    OnnxGraphMapper,
    OnnxImportError,
    wire_field,
)

R = np.random.RandomState(11)


# ------------------------------------------------------- wire-format builders


def t_proto(name, arr):
    arr = np.asarray(arr)
    dt = {np.dtype(np.float32): 1, np.dtype(np.int64): 7,
          np.dtype(np.int32): 6}[arr.dtype]
    out = b"".join(wire_field(1, d, 0) for d in arr.shape)
    out += wire_field(2, dt, 0)
    out += wire_field(8, name)
    out += wire_field(9, arr.tobytes())
    return out


def a_int(name, v):
    return wire_field(1, name) + wire_field(3, v, 0) + wire_field(20, 2, 0)


def a_ints(name, vs):
    return (wire_field(1, name) + b"".join(wire_field(8, v, 0) for v in vs)
            + wire_field(20, 7, 0))


def a_float(name, v):
    return wire_field(1, name) + wire_field(2, v, 5) + wire_field(20, 1, 0)


def a_tensor(name, arr):
    return wire_field(1, name) + wire_field(5, t_proto("", arr)) + wire_field(20, 4, 0)


def node(op_type, inputs, outputs, *attrs, name=""):
    out = b"".join(wire_field(1, i) for i in inputs)
    out += b"".join(wire_field(2, o) for o in outputs)
    out += wire_field(3, name or outputs[0])
    out += wire_field(4, op_type)
    out += b"".join(wire_field(5, a) for a in attrs)
    return out


def value_info(name, shape):
    dims = b"".join(wire_field(1, wire_field(1, d, 0)) for d in shape)
    ttype = wire_field(1, 1, 0) + wire_field(2, dims)
    return wire_field(1, name) + wire_field(2, wire_field(1, ttype))


def model(nodes, initializers, inputs, outputs):
    g = b"".join(wire_field(1, n) for n in nodes)
    g += wire_field(2, "g")
    g += b"".join(wire_field(5, t) for t in initializers)
    g += b"".join(wire_field(11, vi) for vi in inputs)
    g += b"".join(wire_field(12, wire_field(1, o)) for o in outputs)
    return wire_field(1, 8, 0) + wire_field(8, wire_field(2, 17, 0)) + wire_field(7, g)


# ----------------------------------------------------------- numpy reference


def np_conv(x, w, b, pad=1):
    N, C, H, W = x.shape
    O, _, kh, kw = w.shape
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    out = np.zeros((N, O, H, W), np.float32)
    for i in range(H):
        for j in range(W):
            patch = xp[:, :, i:i + kh, j:j + kw]
            out[:, :, i, j] = np.einsum("nchw,ochw->no", patch, w)
    return out + b[None, :, None, None]


def np_softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


# -------------------------------------------------------------------- tests


class TestOnnxCnnGolden:
    def _build(self):
        w = (R.randn(4, 3, 3, 3) * 0.3).astype(np.float32)
        b = R.randn(4).astype(np.float32)
        scale = (R.rand(4) + 0.5).astype(np.float32)
        bias = R.randn(4).astype(np.float32)
        mean = R.randn(4).astype(np.float32)
        var = (R.rand(4) + 0.5).astype(np.float32)
        fc_w = (R.randn(4, 5) * 0.4).astype(np.float32)
        fc_b = R.randn(5).astype(np.float32)
        nodes = [
            node("Conv", ["x", "w", "b"], ["c1"],
                 a_ints("pads", [1, 1, 1, 1]), a_ints("strides", [1, 1]),
                 a_ints("kernel_shape", [3, 3])),
            node("BatchNormalization", ["c1", "scale", "bias", "mean", "var"],
                 ["bn"], a_float("epsilon", 1e-5)),
            node("Relu", ["bn"], ["r1"]),
            node("MaxPool", ["r1"], ["p1"], a_ints("kernel_shape", [2, 2]),
                 a_ints("strides", [2, 2])),
            node("GlobalAveragePool", ["p1"], ["gap"]),
            node("Flatten", ["gap"], ["flat"], a_int("axis", 1)),
            node("Gemm", ["flat", "fc_w", "fc_b"], ["fc"],
                 a_float("alpha", 1.0), a_float("beta", 1.0)),
            node("Softmax", ["fc"], ["probs"], a_int("axis", -1)),
        ]
        inits = [t_proto("w", w), t_proto("b", b), t_proto("scale", scale),
                 t_proto("bias", bias), t_proto("mean", mean),
                 t_proto("var", var), t_proto("fc_w", fc_w), t_proto("fc_b", fc_b)]
        mb = model(nodes, inits, [value_info("x", (2, 3, 8, 8))], ["probs"])
        return mb, (w, b, scale, bias, mean, var, fc_w, fc_b)

    def test_cnn_forward_matches_numpy(self):
        mb, (w, b, scale, bias, mean, var, fc_w, fc_b) = self._build()
        g = OnnxGraphMapper.import_model(mb)
        x = R.randn(2, 3, 8, 8).astype(np.float32)
        got = g.output({"x": x})["probs"]

        h = np_conv(x, w, b, pad=1)
        h = ((h - mean[None, :, None, None])
             / np.sqrt(var[None, :, None, None] + 1e-5)
             * scale[None, :, None, None] + bias[None, :, None, None])
        h = np.maximum(h, 0)
        h = h.reshape(2, 4, 4, 2, 4, 2).max((3, 5))        # 2x2 maxpool
        h = h.mean((2, 3))                                  # GAP + flatten
        logits = h @ fc_w + fc_b
        expected = np_softmax(logits)
        np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)

    def test_placeholder_roundtrip_and_allowlist(self):
        mb, _ = self._build()
        g = OnnxGraphMapper.import_model(mb)
        assert g.placeholders == ["x"]
        assert "Conv" in OnnxGraphMapper.supported_ops()


class TestOnnxTransformerGolden:
    def test_attention_block_matches_numpy(self):
        D, T = 8, 4
        wq, wk, wv, wo = [(R.randn(D, D) * 0.3).astype(np.float32) for _ in range(4)]
        ln_g = (R.rand(D) + 0.5).astype(np.float32)
        ln_b = R.randn(D).astype(np.float32)
        w1 = (R.randn(D, 16) * 0.3).astype(np.float32)
        w2 = (R.randn(16, D) * 0.3).astype(np.float32)
        scale = np.float32(np.sqrt(D))

        nodes = [
            node("MatMul", ["x", "wq"], ["q"]),
            node("MatMul", ["x", "wk"], ["k"]),
            node("MatMul", ["x", "wv"], ["v"]),
            node("Transpose", ["k"], ["kT"], a_ints("perm", [0, 2, 1])),
            node("MatMul", ["q", "kT"], ["scores"]),
            node("Div", ["scores", "sqrt_d"], ["scaled"]),
            node("Softmax", ["scaled"], ["probs"], a_int("axis", -1)),
            node("MatMul", ["probs", "v"], ["ctx"]),
            node("MatMul", ["ctx", "wo"], ["proj"]),
            node("Add", ["x", "proj"], ["res"]),
            node("LayerNormalization", ["res", "ln_g", "ln_b"], ["ln"],
                 a_float("epsilon", 1e-5), a_int("axis", -1)),
            node("MatMul", ["ln", "w1"], ["m1"]),
            node("Gelu", ["m1"], ["gelu"]),
            node("MatMul", ["gelu", "w2"], ["m2"]),
            node("Add", ["ln", "m2"], ["out"]),
        ]
        inits = [t_proto("wq", wq), t_proto("wk", wk), t_proto("wv", wv),
                 t_proto("wo", wo), t_proto("ln_g", ln_g), t_proto("ln_b", ln_b),
                 t_proto("w1", w1), t_proto("w2", w2),
                 t_proto("sqrt_d", scale.reshape(()))]
        mb = model(nodes, inits, [value_info("x", (1, T, D))], ["out"])
        g = OnnxGraphMapper.import_model(mb)

        x = (R.randn(1, T, D) * 0.5).astype(np.float32)
        got = g.output({"x": x})["out"]

        q, k, v = x @ wq, x @ wk, x @ wv
        probs = np_softmax(q @ k.transpose(0, 2, 1) / scale)
        res = x + probs @ v @ wo
        mu = res.mean(-1, keepdims=True)
        ln = (res - mu) / np.sqrt(res.var(-1, keepdims=True) + 1e-5) * ln_g + ln_b
        import math
        m1 = ln @ w1
        gelu = 0.5 * m1 * (1 + np.vectorize(math.erf)(m1 / np.sqrt(2)))
        expected = ln + gelu @ w2
        np.testing.assert_allclose(got, expected, rtol=1e-3, atol=1e-4)


class TestOnnxFoldingAndErrors:
    def test_shape_arithmetic_folds_statically(self):
        """Shape → Slice → Concat → Reshape collapses at import (the
        tf_import constant-folding contract, same walker design)."""
        nodes = [
            node("Shape", ["x"], ["sh"]),
            node("Slice", ["sh", "starts", "ends"], ["lead"]),
            node("Concat", ["lead", "minus1"], ["tgt"], a_int("axis", 0)),
            node("Reshape", ["x", "tgt"], ["out"]),
        ]
        inits = [t_proto("starts", np.array([0], np.int64)),
                 t_proto("ends", np.array([1], np.int64)),
                 t_proto("minus1", np.array([-1], np.int64))]
        mb = model(nodes, inits, [value_info("x", (2, 3, 4))], ["out"])
        g = OnnxGraphMapper.import_model(mb)
        x = R.randn(2, 3, 4).astype(np.float32)
        np.testing.assert_allclose(g.output({"x": x})["out"], x.reshape(2, 12))

    def test_gather_split_cast_unsqueeze(self):
        emb = R.randn(10, 4).astype(np.float32)
        nodes = [
            node("Cast", ["ids_f"], ["ids"], a_int("to", 7)),
            node("Gather", ["emb", "ids"], ["rows"], a_int("axis", 0)),
            node("Split", ["rows"], ["a", "b"], a_int("axis", 1),
                 a_ints("split", [2, 2])),
            node("Unsqueeze", ["a", "axes0"], ["a3"]),
            node("Squeeze", ["a3", "axes0"], ["a2"]),
            node("Sub", ["a2", "b"], ["out"]),
        ]
        inits = [t_proto("emb", emb), t_proto("axes0", np.array([0], np.int64))]
        mb = model(nodes, inits, [value_info("ids_f", (3,))], ["out"])
        g = OnnxGraphMapper.import_model(mb)
        ids = np.array([1.0, 5.0, 9.0], np.float32)
        rows = emb[[1, 5, 9]]
        np.testing.assert_allclose(g.output({"ids_f": ids})["out"],
                                   rows[:, :2] - rows[:, 2:], rtol=1e-5)

    def test_constant_node_and_clip(self):
        nodes = [
            node("Constant", [], ["c"], a_tensor("value", np.array([2.0], np.float32))),
            node("Mul", ["x", "c"], ["m"]),
            node("Clip", ["m"], ["out"], a_float("min", -1.0), a_float("max", 1.0)),
        ]
        mb = model(nodes, [], [value_info("x", (3,))], ["out"])
        g = OnnxGraphMapper.import_model(mb)
        x = np.array([-3.0, 0.25, 3.0], np.float32)
        np.testing.assert_allclose(g.output({"x": x})["out"], [-1.0, 0.5, 1.0])

    def test_unsupported_op_lists_allowlist(self):
        mb = model([node("LSTM", ["x"], ["y"])], [],
                   [value_info("x", (1, 2))], ["y"])
        with pytest.raises(OnnxImportError, match="unsupported ONNX ops: LSTM"):
            OnnxGraphMapper.import_model(mb)

    def test_depthwise_conv_group(self):
        w = (R.randn(3, 1, 3, 3) * 0.3).astype(np.float32)
        nodes = [node("Conv", ["x", "w"], ["out"], a_int("group", 3),
                      a_ints("pads", [1, 1, 1, 1]), a_ints("strides", [1, 1]),
                      a_ints("kernel_shape", [3, 3]))]
        mb = model(nodes, [t_proto("w", w)], [value_info("x", (1, 3, 6, 6))], ["out"])
        g = OnnxGraphMapper.import_model(mb)
        x = R.randn(1, 3, 6, 6).astype(np.float32)
        got = g.output({"x": x})["out"]
        expected = np.stack([
            np_conv(x[:, c:c + 1], w[c:c + 1], np.zeros(1, np.float32))[0, 0]
            for c in range(3)])[None]
        np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


class TestR5ReviewFixes:
    def test_negative_step_slice_reverses(self):
        """starts=-1, ends=INT64_MIN, steps=-1 — the tf2onnx tensor-reverse
        idiom (r5 review: positive-only clamping dropped index 0)."""
        nodes = [node("Slice", ["x", "st", "en", "ax", "sp"], ["out"])]
        inits = [t_proto("st", np.array([-1], np.int64)),
                 t_proto("en", np.array([-(2 ** 63)], np.int64)),
                 t_proto("ax", np.array([1], np.int64)),
                 t_proto("sp", np.array([-1], np.int64))]
        mb = model(nodes, inits, [value_info("x", (2, 4))], ["out"])
        g = OnnxGraphMapper.import_model(mb)
        x = np.arange(8, dtype=np.float32).reshape(2, 4)
        np.testing.assert_array_equal(g.output({"x": x})["out"], x[:, ::-1])

    def test_negative_step_slice_end_clamp(self):
        """ADVICE r5: starts=-1, ends=2, steps=-1 on a length-5 axis must
        yield [4, 3] — the old clamp wrapped NON-negative ends by +n and
        produced an empty slice."""
        nodes = [node("Slice", ["x", "st", "en", "ax", "sp"], ["out"])]
        inits = [t_proto("st", np.array([-1], np.int64)),
                 t_proto("en", np.array([2], np.int64)),
                 t_proto("ax", np.array([0], np.int64)),
                 t_proto("sp", np.array([-1], np.int64))]
        mb = model(nodes, inits, [value_info("x", (5,))], ["out"])
        g = OnnxGraphMapper.import_model(mb)
        x = np.arange(5, dtype=np.float32)
        np.testing.assert_array_equal(g.output({"x": x})["out"], x[-1:2:-1])

    def test_negative_step_slice_torch_export_shape(self):
        """The torch ``x[4:1:-1]`` export (positive start AND end with a
        negative step) keeps its length-3 result."""
        nodes = [node("Slice", ["x", "st", "en", "ax", "sp"], ["out"])]
        inits = [t_proto("st", np.array([4], np.int64)),
                 t_proto("en", np.array([1], np.int64)),
                 t_proto("ax", np.array([0], np.int64)),
                 t_proto("sp", np.array([-1], np.int64))]
        mb = model(nodes, inits, [value_info("x", (5, 2))], ["out"])
        g = OnnxGraphMapper.import_model(mb)
        x = np.arange(10, dtype=np.float32).reshape(5, 2)
        got = g.output({"x": x})["out"]
        assert got.shape == (3, 2)
        np.testing.assert_array_equal(got, x[4:1:-1])

    def test_colon_in_tensor_names(self):
        """tf2onnx keeps 'scope/op:0' names; lookups must be exact."""
        nodes = [node("Relu", ["model/dense/BiasAdd:0"], ["model/out:0"])]
        mb = model(nodes, [], [value_info("model/dense/BiasAdd:0", (3,))],
                   ["model/out:0"])
        g = OnnxGraphMapper.import_model(mb)
        x = np.array([-1.0, 0.0, 2.0], np.float32)
        np.testing.assert_array_equal(
            g.output({"model/dense/BiasAdd:0": x})["model/out:0"], [0, 0, 2])
