"""Cost & memory attribution observatory (ISSUE 10 tentpole, layer 1).

The acceptance gate: the per-layer estimator accounts ≥90% of XLA's own
cost_analysis total for LeNet (MultiLayerNetwork over conf layers) and the
functional transformer — plus unit coverage of the per-layer formulas, the
HBM breakdown and the exported gauge families.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.monitoring import MetricsRegistry, costmodel
from deeplearning4j_tpu.nn.conf import (BatchNormalization, ConvolutionLayer,
                                        DenseLayer, EmbeddingLayer, InputType,
                                        LSTM, SubsamplingLayer)


# ------------------------------------------------------- per-layer formulas


def test_dense_flops_formula():
    l = DenseLayer(n_in=64, n_out=32)
    # 2·MACs + bias adds
    assert l.flops_per_example(InputType.feed_forward(64)) == 2 * 64 * 32 + 32


def test_dense_time_distributed_multiplies_by_T():
    l = DenseLayer(n_in=8, n_out=4)
    ff = l.flops_per_example(InputType.feed_forward(8))
    rnn = l.flops_per_example(InputType.recurrent(8, 10))
    assert rnn == 10 * ff


def test_conv_flops_counts_valid_taps_only():
    it = InputType.convolutional(8, 8, 3)
    full = ConvolutionLayer(n_out=16, kernel_size=(3, 3), padding=(0, 0))
    # VALID 3x3 over 8x8 → 6x6 outputs, every tap valid
    assert full.flops_per_example(it) == 2 * 6 * 6 * 9 * 3 * 16
    same = ConvolutionLayer(n_out=16, kernel_size=(3, 3),
                            convolution_mode="same")
    # SAME pads the border; XLA counts only in-bounds taps, so the SAME
    # flops are strictly below the naive out_h*out_w*k*k product
    naive = 2 * 8 * 8 * 9 * 3 * 16
    got = same.flops_per_example(it)
    assert got < naive
    # per-dim valid taps for size 8, k=3, s=1, SAME: 2 + 3*6 + 2... = 22
    assert got == 2 * (22 * 22) * 3 * 16


def test_lstm_and_misc_layer_flops_positive():
    it = InputType.recurrent(16, 20)
    assert LSTM(n_in=16, n_out=8).flops_per_example(it) > \
        20 * (2 * 16 * 32 + 2 * 8 * 32)  # projections at least
    assert SubsamplingLayer().flops_per_example(
        InputType.convolutional(8, 8, 4)) > 0
    assert BatchNormalization().flops_per_example(
        InputType.convolutional(8, 8, 4)) == 8 * 8 * 8 * 4
    # embedding is a gather: ~no flops beyond the output write
    assert EmbeddingLayer(n_in=1000, n_out=16).flops_per_example(
        InputType.feed_forward(1000)) == 16


# -------------------------------------------------- acceptance: coverage ≥ 90%


def _lenet(batch):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models import LeNet

    net = LeNet(num_classes=10).init()
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(batch, 1, 28, 28).astype(np.float32))
    y = jnp.asarray(np.eye(10, dtype=np.float32)[rs.randint(0, 10, batch)])
    args = (net.params_, net.updater_state, net.bn_state,
            jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32), x, y,
            None, None, jax.random.key(0))
    return net, net._train_step_fn(), args


def test_lenet_layer_costs_cover_xla_total():
    """Acceptance (ISSUE 10): per-layer table accounts ≥90% of the XLA
    cost-analysis total for the compiled LeNet train step."""
    net, step, args = _lenet(batch=16)
    xla = costmodel.xla_step_cost(step, *args)
    assert xla["flops"] > 0
    table = costmodel.cost_table(costmodel.layer_costs(net, 16), xla)
    assert 0.9 <= table["coverage"] <= 1.25, table["coverage"]
    # conv2 is LeNet's dominant layer; the table must say so
    top = max(table["layers"], key=lambda r: r["pct"])
    assert top["kind"] == "ConvolutionLayer"
    assert sum(r["pct"] for r in table["layers"]) == pytest.approx(100, abs=1)
    # memory analysis rode along
    assert xla["peak_bytes"] > 0
    assert xla["argument_bytes"] > 0


def test_transformer_layer_costs_cover_xla_total():
    """Acceptance (ISSUE 10): same gate for the functional transformer's
    compiled MLM train step (tiny config, gathered mlm_positions)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models import transformer as tr
    from deeplearning4j_tpu.nn.updaters import Adam

    cfg = tr.TransformerConfig.tiny(dropout=0.0)
    B, T = 2, 64
    params = tr.init_params(jax.random.key(0), cfg)
    upd = Adam(1e-4)
    opt = upd.init(params)
    step = jax.jit(tr.make_train_step(cfg, upd), donate_argnums=(0, 1))
    P = max(1, int(T * 0.15))
    rs = np.random.RandomState(0)
    pos = np.stack([np.sort(rs.choice(T, P, replace=False)) for _ in range(B)])
    batch = {
        "tokens": jnp.asarray(rs.randint(0, cfg.vocab_size, (B, T)), jnp.int32),
        "mlm_positions": jnp.asarray(pos, jnp.int32),
        "labels": jnp.asarray(rs.randint(0, cfg.vocab_size, (B, P)), jnp.int32),
        "weights": jnp.ones((B, P), jnp.float32),
    }
    xla = costmodel.xla_step_cost(step, params, opt, batch,
                                  jnp.asarray(0, jnp.int32), jax.random.key(1))
    rows = tr.layer_costs(cfg, B, T, mlm_positions=P)
    table = costmodel.cost_table(rows, xla)
    assert 0.9 <= table["coverage"] <= 1.25, table["coverage"]
    names = [r["layer"] for r in rows]
    assert names == ["embed"] + [f"block{i}" for i in range(cfg.n_layers)] + \
        ["mlm_head"]
    # blocks dominate a transformer step
    assert sum(r["pct"] for r in table["layers"]
               if r["kind"] == "TransformerBlock") > 80


# ------------------------------------------------------------ graph support


def test_layer_costs_walks_computation_graph_nodes():
    from deeplearning4j_tpu.nn import ComputationGraph, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import OutputLayer

    conf = (NeuralNetConfiguration.Builder().seed(0).graph_builder()
            .add_inputs("in")
            .add_layer("d1", DenseLayer(n_in=6, n_out=12, activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_in=12, n_out=3, activation="softmax",
                                          loss="mcxent"), "d1")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(6))
            .build())
    net = ComputationGraph(conf).init()
    rows = costmodel.layer_costs(net, batch=8)
    by_name = {r["layer"]: r for r in rows}
    assert by_name["d1"]["flops"] == (2 * 6 * 12 + 12) * 8 * 3.0
    assert by_name["d1"]["param_bytes"] == (6 * 12 + 12) * 4
    assert by_name["out"]["kind"] == "OutputLayer"


# ------------------------------------------------------------ HBM breakdown


def test_live_hbm_breakdown_buckets_by_owner():
    net, _, _ = _lenet(batch=4)
    reg = MetricsRegistry()
    out = costmodel.net_hbm_breakdown(net, model="lenet", registry=reg)
    param_bytes = sum(r["param_bytes"]
                      for r in costmodel.layer_costs(net, 1))
    assert out["params"] == param_bytes
    assert out["opt_state"] > 0        # Adam m/v live on device
    assert out["bn_state"] == 0        # LeNet has no BN
    series = reg.get("tdl_hbm_bytes").snapshot()["series"]
    kinds = {s["labels"]["kind"]: s["value"] for s in series
             if s["labels"]["model"] == "lenet"}
    assert kinds["params"] == param_bytes
    assert "other" in kinds


def test_publish_exports_gauges_and_table():
    net, step, args = _lenet(batch=4)
    reg = MetricsRegistry()
    xla = costmodel.xla_step_cost(step, *args)
    table = costmodel.publish("lenet", costmodel.layer_costs(net, 4), xla,
                              registry=reg)
    assert table["coverage"] > 0
    assert reg.get("tdl_model_flops_per_step").labels("lenet").value == \
        xla["flops"]
    assert reg.get("tdl_hbm_peak_bytes").labels("lenet").value == \
        xla["peak_bytes"]
    layer_series = reg.get("tdl_layer_cost_info").snapshot()["series"]
    assert len([s for s in layer_series
                if s["labels"]["model"] == "lenet"]) == len(table["layers"])


def test_xla_step_cost_accepts_plain_callable():
    import jax.numpy as jnp

    c = costmodel.xla_step_cost(lambda a, b: a @ b,
                                jnp.zeros((8, 16)), jnp.zeros((16, 4)))
    assert c["flops"] >= 2 * 8 * 16 * 4


# ------------------------------------------------- bench --compare satellite


def test_compare_benchmarks_gates_throughput_regressions():
    import bench

    old = {"backend": "cpu", "configs": {
        "resnet50": {"value": 100.0, "unit": "images/sec/chip"},
        "bert": {"value": 1000.0, "unit": "tokens/sec/chip"},
        "lenet": {"value": 30.0, "unit": "sec_to_95%_acc"},
    }}
    cur = {"backend": "cpu", "configs": {
        "resnet50": {"value": 85.0, "unit": "images/sec/chip"},   # -15%: gate
        "bert": {"value": 950.0, "unit": "tokens/sec/chip"},      # -5%: noise
        "lenet": {"value": 60.0, "unit": "sec_to_95%_acc"},       # not a rate
    }}
    regs = bench.compare_benchmarks(cur, old)
    assert [r["config"] for r in regs] == ["resnet50"]
    assert regs[0]["ratio"] == pytest.approx(0.85)
    # identical runs never regress
    assert bench.compare_benchmarks(old, old) == []
    # new/missing configs are not regressions (trajectories add configs)
    assert bench.compare_benchmarks(
        {"backend": "cpu", "configs": {"new": {"value": 1, "unit": "x/s"}}},
        old) == []
    # cross-backend comparisons are refused, not silently wrong
    with pytest.raises(ValueError, match="cannot compare backends"):
        bench.compare_benchmarks({"backend": "tpu", "configs": {}}, old)
    # a config whose UNIT changed between runs is incomparable — skipped
    # rather than ratioed into a fabricated regression
    assert bench.compare_benchmarks(
        {"backend": "cpu", "configs": {
            "resnet50": {"value": 3.2, "unit": "batches/sec"}}}, old) == []
    # a current value of 0 against a real baseline IS a (total) regression
    zeroed = bench.compare_benchmarks(
        {"backend": "cpu", "configs": {
            "resnet50": {"value": 0.0, "unit": "images/sec/chip"}}}, old)
    assert [r["config"] for r in zeroed] == ["resnet50"]
