"""INDArray-semantics tests, modeled on nd4j's Nd4jTestsC corpus
(SURVEY.md §4.2): views aliasing storage, in-place ops, 'c'/'f' order,
broadcasting, reductions — golden-checked against numpy."""

import numpy as np
import pytest

import deeplearning4j_tpu.ndarray as nd
from deeplearning4j_tpu.common.dtypes import DataType, promote_types


class TestCreation:
    def test_zeros_ones(self):
        z = nd.zeros(2, 3)
        assert z.shape == (2, 3)
        assert np.allclose(z.numpy(), 0)
        o = nd.ones((4,))
        assert np.allclose(o.numpy(), 1)

    def test_default_float_is_f32(self):
        a = nd.array([[1.0, 2.0]])
        assert a.data_type == DataType.FLOAT

    def test_arange_linspace_eye(self):
        assert np.array_equal(nd.arange(5).numpy(), np.arange(5))
        assert np.allclose(nd.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5))
        assert np.allclose(nd.eye(3).numpy(), np.eye(3))

    def test_value_array_of(self):
        v = nd.value_array_of((2, 2), 3.5)
        assert np.allclose(v.numpy(), 3.5)

    def test_empty(self):
        e = nd.empty()
        assert e.is_empty()

    def test_one_hot(self):
        oh = nd.factory.one_hot(nd.array([0, 2]), 3)
        assert np.allclose(oh.numpy(), [[1, 0, 0], [0, 0, 1]])


class TestDtypes:
    def test_promotion_float_beats_int(self):
        assert promote_types(DataType.FLOAT, DataType.LONG) == DataType.FLOAT

    def test_promotion_half_bf16(self):
        assert promote_types(DataType.HALF, DataType.BFLOAT16) == DataType.FLOAT

    def test_promotion_wider_wins(self):
        assert promote_types(DataType.INT, DataType.LONG) == DataType.LONG
        assert promote_types(DataType.FLOAT, DataType.DOUBLE) == DataType.DOUBLE

    def test_cast(self):
        a = nd.array([1.7, 2.3])
        b = a.cast_to(DataType.INT)
        assert b.data_type == DataType.INT
        assert np.array_equal(b.numpy(), [1, 2])


class TestArithmetic:
    def test_add_sub_mul_div(self):
        a, b = nd.array([1.0, 2.0, 3.0]), nd.array([4.0, 5.0, 6.0])
        assert np.allclose((a + b).numpy(), [5, 7, 9])
        assert np.allclose((a - b).numpy(), [-3, -3, -3])
        assert np.allclose((a * b).numpy(), [4, 10, 18])
        assert np.allclose((b / a).numpy(), [4, 2.5, 2])

    def test_rsub_rdiv(self):
        a = nd.array([1.0, 2.0, 4.0])
        assert np.allclose(a.rsub(10).numpy(), [9, 8, 6])
        assert np.allclose(a.rdiv(8).numpy(), [8, 4, 2])

    def test_inplace_returns_self_and_mutates(self):
        a = nd.array([1.0, 2.0])
        r = a.addi(1)
        assert r is a
        assert np.allclose(a.numpy(), [2, 3])

    def test_scalar_broadcast(self):
        a = nd.ones(2, 2)
        assert np.allclose((a + 5).numpy(), 6)
        assert np.allclose((2 * a).numpy(), 2)

    def test_row_column_vector_ops(self):
        m = nd.zeros(2, 3)
        m.addi_row_vector(nd.array([1.0, 2.0, 3.0]))
        assert np.allclose(m.numpy(), [[1, 2, 3], [1, 2, 3]])
        m.addi_column_vector(nd.array([[10.0], [20.0]]))
        assert np.allclose(m.numpy(), [[11, 12, 13], [21, 22, 23]])

    def test_comparison_ops_bool(self):
        a = nd.array([1.0, 5.0])
        g = a.gt(2)
        assert g.data_type == DataType.BOOL
        assert np.array_equal(g.numpy(), [False, True])

    def test_neg(self):
        a = nd.array([1.0, -2.0])
        assert np.allclose((-a).numpy(), [-1, 2])


class TestViewsAliasing:
    """The DL4J contract: views alias storage, writes via views visible in base
    (BaseNDArray.get(NDArrayIndex...) semantics)."""

    def test_row_view_write_visible_in_base(self):
        m = nd.zeros(3, 3)
        row = m.get_row(1)
        row.assign(nd.array([1.0, 2.0, 3.0]))
        assert np.allclose(m.numpy(), [[0, 0, 0], [1, 2, 3], [0, 0, 0]])

    def test_view_addi_mutates_base(self):
        m = nd.ones(2, 4)
        col = m.get_column(2)
        col.addi(10)
        expected = np.ones((2, 4))
        expected[:, 2] += 10
        assert np.allclose(m.numpy(), expected)

    def test_base_write_visible_in_view(self):
        m = nd.zeros(2, 2)
        v = m[0]
        m.assign(7)
        assert np.allclose(v.numpy(), [7, 7])

    def test_interval_view(self):
        a = nd.arange(10, dtype="float32")
        v = a[2:7]
        v.muli(0)
        out = a.numpy()
        assert np.allclose(out[2:7], 0)
        assert np.allclose(out[:2], [0, 1])
        assert np.allclose(out[7:], [7, 8, 9])

    def test_view_of_view_composition(self):
        a = nd.arange(20, dtype="float32").reshape(4, 5)
        v1 = a[1:3]         # rows 1..2
        v2 = v1[1]          # row 2 of a
        v2.addi(100)
        out = a.numpy()
        assert np.allclose(out[2], np.arange(10, 15) + 100)
        assert np.allclose(out[1], np.arange(5, 10))

    def test_strided_view(self):
        a = nd.arange(10, dtype="float32")
        v = a[::2]
        v.addi(1)
        assert np.allclose(a.numpy(), [1, 1, 3, 3, 5, 5, 7, 7, 9, 9])

    def test_negative_step_view(self):
        """Regression: reversed-slice views composed to length 0."""
        a = nd.arange(6, dtype="float32")
        v = a[::-1]
        assert np.allclose(v.numpy(), [5, 4, 3, 2, 1, 0])
        assert v.get_double(0) == 5.0
        v2 = a[0:6][::-1]
        assert np.allclose(v2.numpy(), [5, 4, 3, 2, 1, 0])
        v2.put_scalar(0, 99.0)
        assert a.get_double(5) == 99.0

    def test_newaxis_copies(self):
        a = nd.arange(6, dtype="float32").reshape(2, 3)
        w = a[None]
        assert w.shape == (1, 2, 3)
        w.addi(1)  # copy — must NOT mutate a
        assert np.allclose(a.numpy(), np.arange(6).reshape(2, 3))

    def test_dup_detaches(self):
        m = nd.zeros(2, 2)
        d = m.get_row(0).dup()
        d.addi(5)
        assert np.allclose(m.numpy(), 0)

    def test_put_scalar_and_get(self):
        m = nd.zeros(2, 2)
        m.put_scalar((0, 1), 42.0)
        assert m.get_double(0, 1) == 42.0

    def test_setitem(self):
        m = nd.zeros(3, 3)
        m[1, :] = nd.array([1.0, 2.0, 3.0])
        assert np.allclose(m.numpy()[1], [1, 2, 3])


class TestShapeOps:
    def test_reshape_c(self):
        a = nd.arange(6).reshape(2, 3)
        assert np.array_equal(a.numpy(), np.arange(6).reshape(2, 3))

    def test_reshape_f(self):
        a = nd.arange(6, dtype="float32")
        f = a.reshape(2, 3, order="f")
        assert np.array_equal(f.numpy(), np.arange(6).reshape(2, 3, order="F"))

    def test_ravel_f(self):
        a = nd.array([[1.0, 2.0], [3.0, 4.0]])
        assert np.allclose(a.ravel("f").numpy(), [1, 3, 2, 4])
        assert np.allclose(a.ravel("c").numpy(), [1, 2, 3, 4])

    def test_transpose_permute(self):
        a = nd.arange(24).reshape(2, 3, 4)
        assert a.T.shape == (4, 3, 2)
        assert a.permute(2, 0, 1).shape == (4, 2, 3)

    def test_tad(self):
        a = nd.arange(24, dtype="float32").reshape(2, 3, 4)
        assert a.tensors_along_dimension(2) == 6
        t = a.tensor_along_dimension(1, 2)  # second row-of-4: [0,1,:]
        assert np.allclose(t.numpy(), [4, 5, 6, 7])
        t.addi(1000)
        assert np.allclose(a.numpy()[0, 1], [1004, 1005, 1006, 1007])

    def test_squeeze_expand(self):
        a = nd.zeros(1, 3, 1)
        assert a.squeeze().shape == (3,)
        assert a.expand_dims(0).shape == (1, 1, 3, 1)


class TestReductions:
    def test_sum_mean_dims(self):
        a = nd.array([[1.0, 2.0], [3.0, 4.0]])
        assert np.allclose(a.sum(0).numpy(), [4, 6])
        assert np.allclose(a.sum(1).numpy(), [3, 7])
        assert a.sum_number() == 10.0
        assert a.mean_number() == 2.5

    def test_std_var_bias_corrected(self):
        a = nd.array([1.0, 2.0, 3.0, 4.0])
        assert abs(a.std_number() - np.std([1, 2, 3, 4], ddof=1)) < 1e-6
        assert abs(a.var_number(False) - np.var([1, 2, 3, 4])) < 1e-6

    def test_norms(self):
        a = nd.array([3.0, -4.0])
        assert a.norm1_number() == 7.0
        assert a.norm2_number() == 5.0

    def test_argmax_argmin(self):
        a = nd.array([[1.0, 9.0, 2.0], [8.0, 0.0, 3.0]])
        assert np.array_equal(a.argmax(1).numpy(), [1, 0])
        assert np.array_equal(a.argmin(0).numpy(), [0, 1, 0])

    def test_cumsum(self):
        a = nd.array([1.0, 2.0, 3.0])
        assert np.allclose(a.cumsum(0).numpy(), [1, 3, 6])


class TestLinalg:
    def test_mmul(self):
        a = nd.array([[1.0, 2.0], [3.0, 4.0]])
        b = nd.array([[5.0, 6.0], [7.0, 8.0]])
        assert np.allclose(a.mmul(b).numpy(), np.array([[19, 22], [43, 50]]))

    def test_mmul_transpose_flags(self):
        a = nd.rand(3, 2)
        b = nd.rand(3, 4)
        out = a.mmul(b, transpose_a=True)
        assert np.allclose(out.numpy(), a.numpy().T @ b.numpy(), atol=1e-5)

    def test_batched_mmul(self):
        a, b = nd.rand(5, 2, 3), nd.rand(5, 3, 4)
        out = a.mmul(b)
        assert out.shape == (5, 2, 4)
        assert np.allclose(out.numpy(), a.numpy() @ b.numpy(), atol=1e-5)

    def test_dot(self):
        assert nd.array([1.0, 2.0]).dot(nd.array([3.0, 4.0])) == 11.0


class TestFactoryOps:
    def test_concat_stack(self):
        a, b = nd.ones(2, 2), nd.zeros(2, 2)
        assert nd.concat(0, a, b).shape == (4, 2)
        assert nd.concat(1, a, b).shape == (2, 4)
        assert nd.stack(0, a, b).shape == (2, 2, 2)

    def test_where(self):
        out = nd.where(nd.array([True, False]), nd.array([1.0, 1.0]), nd.array([2.0, 2.0]))
        assert np.allclose(out.numpy(), [1, 2])

    def test_sort(self):
        assert np.allclose(nd.factory.sort(nd.array([3.0, 1.0, 2.0])).numpy(), [1, 2, 3])
        assert np.allclose(nd.factory.sort(nd.array([3.0, 1.0, 2.0]), descending=True).numpy(), [3, 2, 1])


class TestEquality:
    def test_equals_to(self):
        a = nd.array([1.0, 2.0])
        assert a.equals_to(nd.array([1.0, 2.0]))
        assert a.equals_to(nd.array([1.0, 2.0 + 1e-7]))
        assert not a.equals_to(nd.array([1.0, 2.1]))
        assert not a.equals_to(nd.array([1.0, 2.0, 3.0]))


class TestRng:
    def test_seeded_reproducibility(self):
        from deeplearning4j_tpu.rng import get_random, set_seed

        set_seed(42)
        a = nd.rand(3, 3).numpy()
        set_seed(42)
        b = nd.rand(3, 3).numpy()
        assert np.array_equal(a, b)

    def test_stream_advances(self):
        a = nd.rand(3).numpy()
        b = nd.rand(3).numpy()
        assert not np.array_equal(a, b)

    def test_distributions_moments(self):
        from deeplearning4j_tpu.rng import get_random

        r = get_random()
        n = r.normal((20000,), mean=2.0, std=3.0).numpy()
        assert abs(n.mean() - 2.0) < 0.1
        assert abs(n.std() - 3.0) < 0.1
        u = r.uniform((20000,), minval=-1, maxval=1).numpy()
        assert abs(u.mean()) < 0.05
        bern = r.bernoulli((20000,), p=0.3).numpy()
        assert abs(bern.mean() - 0.3) < 0.02

    def test_dropout_mask_inverted(self):
        from deeplearning4j_tpu.rng import get_random

        m = get_random().dropout_mask((10000,), keep_prob=0.5).numpy()
        assert set(np.unique(m)).issubset({0.0, 2.0})
        assert abs(m.mean() - 1.0) < 0.1


def test_memory_workspace_facade():
    """§2.9 N4: the workspace API exists as a documented no-op/HBM-hint
    facade — scopes nest, the manager caches per-thread, and detach/leverage
    are identity (XLA owns HBM)."""
    from deeplearning4j_tpu.ndarray import (
        WorkspaceConfiguration, current_workspace, workspace_manager)

    mgr = workspace_manager()
    assert current_workspace() is None
    cfg = WorkspaceConfiguration(initial_size=1 << 20, policy_learning="FIRST_LOOP")
    with mgr.get_and_activate_workspace(cfg, "WS_TEST") as ws:
        assert ws.is_scope_active()
        assert current_workspace() is ws
        with mgr.get_and_activate_workspace(cfg, "WS_INNER") as inner:
            assert current_workspace() is inner
            with mgr.scope_out_of_workspaces():
                assert current_workspace() is None  # detached scope
            assert current_workspace() is inner
        assert current_workspace() is ws
    assert not ws.is_scope_active()
    assert ws.generation == 1
    # same id on the same thread returns the cached workspace
    assert mgr.get_workspace_for_current_thread("WS_TEST") is ws
    # arrays are always "detached" in the reference's sense — detach() is a
    # plain dup, never tied to a workspace lifetime
    import deeplearning4j_tpu.ndarray as nd
    import numpy as np
    a = nd.ones(3)
    np.testing.assert_array_equal(a.detach().numpy(), a.numpy())


from deeplearning4j_tpu.ndarray.ndarray import NDArray


class TestJ1Wave3:
    """J1 breadth wave 3: distances, order stats, layout accessors,
    BooleanIndexing-style conditionals, and the Transforms static API."""

    def test_distances(self):
        a = NDArray(np.array([1.0, 2.0, 3.0], np.float32))
        b = NDArray(np.array([1.0, 0.0, 5.0], np.float32))
        assert a.distance1(b) == 4.0
        np.testing.assert_allclose(a.distance2(b), np.sqrt(8.0), rtol=1e-6)
        assert a.squared_distance(b) == 8.0

    def test_order_stats(self):
        a = NDArray(np.array([5.0, 1.0, 3.0, 2.0, 4.0], np.float32))
        assert a.median_number() == 3.0
        assert a.percentile_number(100) == 5.0

    def test_stride_offset_slice_element(self):
        a = NDArray(np.arange(12.0, dtype=np.float32).reshape(3, 4))
        assert a.stride() == (4, 1)
        assert NDArray(np.zeros((3, 4), np.float32), order="f").stride() == (1, 3)
        assert a.offset() == 0
        row = a.slice(1)
        np.testing.assert_allclose(row.numpy(), [4, 5, 6, 7])
        col = a.slice(2, dim=1)
        np.testing.assert_allclose(col.numpy(), [2, 6, 10])
        assert NDArray(np.array([[7.0]], np.float32)).element() == 7.0

    def test_boolean_indexing(self):
        a = NDArray(np.array([-2.0, 3.0, -1.0, 4.0], np.float32))
        mask = a.match_condition(lambda x: x < 0)
        np.testing.assert_array_equal(mask.numpy(), [True, False, True, False])
        a.replace_where(0.0, lambda x: x < 0)
        np.testing.assert_allclose(a.numpy(), [0, 3, 0, 4])
        got = a.get_where(np.array([1.0, -1.0, 1.0, -1.0]), lambda x: x > 0)
        np.testing.assert_allclose(got.numpy(), [0, 0])

    def test_transforms_api(self):
        from deeplearning4j_tpu.ndarray import transforms as T

        a = NDArray(np.array([1.0, 4.0, 9.0], np.float32))
        np.testing.assert_allclose(T.sqrt(a).numpy(), [1, 2, 3], rtol=1e-6)
        np.testing.assert_allclose(T.sigmoid(NDArray(np.zeros(2, np.float32))).numpy(), 0.5)
        # dup=False writes through
        b = NDArray(np.array([1.0, 2.0], np.float32))
        out = T.exp(b, dup=False)
        assert out is b
        np.testing.assert_allclose(b.numpy(), np.exp([1.0, 2.0]), rtol=1e-6)
        u = T.unit_vec(np.array([3.0, 4.0]))
        np.testing.assert_allclose(u.numpy(), [0.6, 0.8], rtol=1e-6)
        assert abs(T.cosine_sim([1.0, 0.0], [0.0, 1.0])) < 1e-6
        assert T.euclidean_distance([0.0, 0.0], [3.0, 4.0]) == 5.0
        m = T.is_max(np.array([[1.0, 9.0], [3.0, 2.0]]))
        np.testing.assert_allclose(m.numpy(), [[0, 1], [0, 0]])
        s = T.softmax(np.array([[0.0, 0.0]]))
        np.testing.assert_allclose(s.numpy(), [[0.5, 0.5]])
        np.testing.assert_allclose(
            T.sort(np.array([3.0, 1.0, 2.0]), descending=True).numpy(), [3, 2, 1])


class TestJ1Wave4:
    def test_inplace_rowcol_tail(self):
        a = NDArray(np.ones((2, 3), np.float32) * 6)
        a.subi_row_vector(np.array([1.0, 2, 3]))
        np.testing.assert_allclose(a.numpy(), [[5, 4, 3], [5, 4, 3]])
        a.divi_column_vector(np.array([1.0, 2.0]))
        np.testing.assert_allclose(a.numpy(), [[5, 4, 3], [2.5, 2, 1.5]])

    def test_shape_accessors_and_conversions(self):
        a = NDArray(np.arange(6.0, dtype=np.float32).reshape(2, 3))
        assert a.rows() == 2 and a.columns() == 3 and not a.is_square()
        assert NDArray(np.eye(3, dtype=np.float32)).is_square()
        # rank-1 = row vector (DL4J): rows()=1, columns()=length
        v1 = NDArray(np.ones(5, np.float32))
        assert v1.rows() == 1 and v1.columns() == 5
        row = NDArray(np.arange(6.0, dtype=np.float32).reshape(1, 6))
        v = row.to_double_vector()
        assert v.dtype == np.float64 and v.shape == (6,)
        assert row.to_int_vector().tolist() == [0, 1, 2, 3, 4, 5]
        m = a.to_float_matrix()
        assert m.dtype == np.float32 and m.shape == (2, 3)
        np.testing.assert_allclose(a.to_double_matrix(), a.numpy())
        import pytest as _pytest
        with _pytest.raises(ValueError, match="Vector"):
            a.to_double_vector()
        with _pytest.raises(ValueError, match="Matrix"):
            v1.to_float_matrix()

    def test_inplace_keeps_dtype_owner_and_view(self):
        a = NDArray(np.array([[4, 5]], np.int32))
        a.divi_row_vector(np.array([2, 2]))
        assert a.numpy().dtype == np.int32
        np.testing.assert_array_equal(a.numpy(), [[2, 2]])  # truncating divi
        big = NDArray(np.full((2, 2), 9, np.int32))
        view = big.get_row(0)
        view.divi(2)
        assert big.numpy().dtype == np.int32
        np.testing.assert_array_equal(big.numpy(), [[4, 4], [9, 9]])
