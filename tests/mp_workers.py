"""Worker targets for the multi-process distributed tests.

Each function runs inside a freshly-spawned worker process AFTER
``launcher.initialize()`` (so jax already sees the global device set).
Results are written to the file named by TDL_MP_OUT (one file per rank) for
the parent pytest process to assert on — mirrors how the reference's
local-Spark tests collect per-executor results (SURVEY §4.4).
"""

import json
import os

import numpy as np


def _out_path(rank):
    return os.environ["TDL_MP_OUT"] + f".rank{rank}"


def _write(rank, payload):
    with open(_out_path(rank), "w") as f:
        json.dump(payload, f)


def _toy_net(seed=7):
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (
        NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2)).list()
        .layer(DenseLayer(n_in=6, n_out=16, activation="tanh"))
        .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(6))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _global_batch(step, n=16):
    """Deterministic batch keyed by step — identical on every process."""
    rs = np.random.RandomState(1000 + step)
    x = rs.rand(n, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, n)]
    return x, y


def allgather_blobs():
    """SPI smoke: pickled blob allgather over the real process boundary."""
    import jax

    from deeplearning4j_tpu.parallel.launcher import ProcessCollectives

    col = ProcessCollectives()
    rank = col.rank
    blobs = col.allgather("smoke", {"rank": rank, "payload": "x" * (10 + rank * 100)})
    col.barrier("done")
    _write(rank, {
        "world": col.world,
        "global_devices": jax.device_count(),
        "local_devices": jax.local_device_count(),
        "gathered_ranks": [b["rank"] for b in blobs],
        "lens": [len(b["payload"]) for b in blobs],
    })


def dp_train():
    """2-process data-parallel fit via MultiProcessTrainer; every process
    writes its final params hash + losses; parent asserts cross-process
    equality AND equality with a single-process reference run."""
    import jax

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.parallel.launcher import ProcessCollectives
    from deeplearning4j_tpu.parallel.mesh import build_mesh
    from deeplearning4j_tpu.parallel.trainer import MultiProcessTrainer

    col = ProcessCollectives()
    rank, world = col.rank, col.world
    net = _toy_net()
    trainer = MultiProcessTrainer(net, build_mesh(data=-1))

    steps = 6
    losses = []
    for step in range(steps):
        x, y = _global_batch(step)
        # each process feeds its local shard (standard SPMD input pipeline)
        lo = rank * (len(x) // world)
        hi = lo + len(x) // world
        trainer.fit([DataSet(x[lo:hi], y[lo:hi])])
        losses.append(net.score_)

    flat = np.asarray(net.params().numpy(), np.float64)
    _write(rank, {
        "losses": [float(l) for l in losses],
        "param_sum": float(flat.sum()),
        "param_norm": float(np.linalg.norm(flat)),
        "global_devices": jax.device_count(),
    })


def grad_exchange():
    """EncodedGradientsAccumulator across a genuine process boundary."""
    from deeplearning4j_tpu.parallel.compression import EncodedGradientsAccumulator
    from deeplearning4j_tpu.parallel.launcher import ProcessCollectives

    col = ProcessCollectives()
    rank = col.rank
    acc = EncodedGradientsAccumulator(col, threshold=0.1)
    rs = np.random.RandomState(42)  # same stream every rank
    g_all = rs.randn(2, 257).astype(np.float32) * 0.3
    mine = g_all[rank]
    upd1 = acc.exchange(mine)
    upd2 = acc.exchange(mine)
    _write(rank, {
        "upd1_sum": float(upd1.sum()),
        "upd2_sum": float(upd2.sum()),
        "residual_norm": float(np.linalg.norm(acc.residual)),
    })


def ckpt_train():
    """Training loop with rotating checkpoints; rank 1 optionally crashes at
    TDL_MP_DIE_AT (simulated preemption). On TDL_MP_RESTORE=1 the run resumes
    from the newest checkpoint instead of a fresh init."""
    import jax

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.parallel.launcher import ProcessCollectives
    from deeplearning4j_tpu.parallel.mesh import build_mesh
    from deeplearning4j_tpu.parallel.trainer import MultiProcessTrainer
    from deeplearning4j_tpu.serde.model_serializer import ModelSerializer

    col = ProcessCollectives()
    rank, world = col.rank, col.world
    ckpt_dir = os.environ["TDL_MP_CKPT"]
    die_at = int(os.environ.get("TDL_MP_DIE_AT", "-1"))
    total_steps = int(os.environ.get("TDL_MP_STEPS", "8"))
    every = int(os.environ.get("TDL_MP_CKPT_EVERY", "2"))

    net = _toy_net()
    start = 0
    marker = os.path.join(ckpt_dir, "latest.json")
    if os.environ.get("TDL_MP_RESTORE") == "1" and os.path.exists(marker):
        with open(marker) as f:
            meta = json.load(f)
        restored = ModelSerializer.restore_multi_layer_network(meta["path"], load_updater=True)
        net = restored
        net.iteration = meta["iteration"]
        start = meta["step"]

    trainer = MultiProcessTrainer(net, build_mesh(data=-1))
    losses = []
    for step in range(start, total_steps):
        x, y = _global_batch(step)
        lo = rank * (len(x) // world)
        hi = lo + len(x) // world
        trainer.fit([DataSet(x[lo:hi], y[lo:hi])])
        losses.append(net.score_)
        if (step + 1) % every == 0:
            col.barrier(f"ckpt-{step}")
            if rank == 0:  # process-0 writes (params replicated = identical)
                path = os.path.join(ckpt_dir, f"ckpt-{step}.zip")
                ModelSerializer.write_model(net, path, save_updater=True)
                with open(marker, "w") as f:
                    json.dump({"path": path, "step": step + 1,
                               "iteration": net.iteration}, f)
            col.barrier(f"ckpt-done-{step}")
        if rank == 1 and die_at == step:
            os._exit(17)  # simulated preemption: hard kill, no cleanup

    flat = np.asarray(net.params().numpy(), np.float64)
    _write(rank, {"losses": [float(l) for l in losses],
                  "param_sum": float(flat.sum()),
                  "param_norm": float(np.linalg.norm(flat)),
                  "start": start})


def supervised_train():
    """GangSupervisor worker target: data-parallel training with SHARDED
    checkpoints (``TrainingCheckpointer``) every TDL_MP_CKPT_EVERY steps and
    an unconditional restore-from-latest on start — the supervisor restart
    contract. Heartbeats and fault injection ride the real
    ``ParallelTrainer._fit_core`` hooks (TDL_HEARTBEAT_DIR / TDL_FAULT_SPEC
    env, set by the supervisor / the chaos test)."""
    import jax

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.parallel.launcher import ProcessCollectives
    from deeplearning4j_tpu.parallel.mesh import build_mesh
    from deeplearning4j_tpu.parallel.trainer import MultiProcessTrainer
    from deeplearning4j_tpu.serde.checkpoint import TrainingCheckpointer

    col = ProcessCollectives()
    rank, world = col.rank, col.world
    total_steps = int(os.environ.get("TDL_MP_STEPS", "10"))
    every = int(os.environ.get("TDL_MP_CKPT_EVERY", "2"))
    incarnation = int(os.environ.get("TDL_GANG_RESTART_COUNT", "0"))

    net = _toy_net()
    ck = TrainingCheckpointer(os.environ["TDL_MP_CKPT"], async_write=False)
    start = 0
    if ck.restore(net):  # empty dir on incarnation 0 → False
        start = int(net.iteration)
    trainer = MultiProcessTrainer(net, build_mesh(data=-1))
    losses = []
    for step in range(start, total_steps):
        x, y = _global_batch(step)
        lo = rank * (len(x) // world)
        hi = lo + len(x) // world
        trainer.fit([DataSet(x[lo:hi], y[lo:hi])])
        losses.append(net.score_)
        if (step + 1) % every == 0:
            # all ranks at the same iteration before anyone writes a shard
            col.barrier(f"ck-{step}")
            ck.save(net)
            col.barrier(f"ck-done-{step}")

    flat = np.asarray(net.params().numpy(), np.float64)
    _write(rank, {"losses": [float(l) for l in losses],
                  "param_sum": float(flat.sum()),
                  "param_norm": float(np.linalg.norm(flat)),
                  "start": start, "incarnation": incarnation,
                  "global_devices": jax.device_count()})


def observability_train():
    """ISSUE 7 acceptance target: a 2-rank gang whose members train
    INDEPENDENTLY (single-rank local mesh, no cross-rank collectives) with a
    per-rank checkpoint every step — so a ``slow_ckpt_io@value=...,rank=1``
    fault makes rank 1 a genuine straggler instead of being hidden by
    lockstep barriers. Each rank's ``ParallelTrainer._fit_core`` drives the
    whole observability plane via the env contracts the supervisor sets:
    heartbeats, flight step events, ``tdl_step_wall_seconds`` (which
    INCLUDES the checkpoint time between fit calls — the skew signal), and
    the metrics spool the parent scrapes as one aggregated /metrics."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.monitoring import aggregate, flight
    from deeplearning4j_tpu.parallel.launcher import ProcessCollectives
    from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
    from deeplearning4j_tpu.serde.checkpoint import TrainingCheckpointer

    col = ProcessCollectives()
    rank = col.rank
    total_steps = int(os.environ.get("TDL_MP_STEPS", "8"))

    net = _toy_net(seed=7 + rank)
    mesh = Mesh(np.array(jax.local_devices()[:1]).reshape(1), ("data",))
    trainer = ParallelTrainer(net, mesh)
    ck = TrainingCheckpointer(os.path.join(os.environ["TDL_MP_CKPT"],
                                           f"rank{rank}"), async_write=False)
    for step in range(total_steps):
        x, y = _global_batch(step)
        trainer.fit([DataSet(x, y)])
        ck.save(net)  # every step: the slow_ckpt_io rank straggles HERE
    aggregate.maybe_spool(force=True)  # final counters for the parent's scrape
    flight.flush()
    col.barrier("obs-done")  # neither rank exits before both spooled
    _write(rank, {"iterations": int(net.iteration), "rank": rank})


def churn_train():
    """ISSUE 10 acceptance target: a 2-rank gang whose FIRST incarnation
    deliberately churns minibatch shapes after marking warmup done — the
    RecompileWatchdog attributes the recompiles per fn, the AlertEngine's
    ``recompiles_after_warmup`` rule fires (alert + compile events land in
    the flight ring), and a crash injected later (TDL_FAULT_SPEC) makes the
    supervisor write a postmortem carrying both. The respawned incarnation
    trains steady-shape to completion, proving compiles stay FLAT after
    warmup when shapes don't churn."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.monitoring import (AlertEngine, RecompileWatchdog,
                                               aggregate, flight)
    from deeplearning4j_tpu.parallel.launcher import ProcessCollectives
    from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

    col = ProcessCollectives()
    rank = col.rank
    incarnation = int(os.environ.get("TDL_GANG_RESTART_COUNT", "0"))
    net = _toy_net(seed=7 + rank)
    mesh = Mesh(np.array(jax.local_devices()[:1]).reshape(1), ("data",))
    trainer = ParallelTrainer(net, mesh)
    wd = RecompileWatchdog().install()
    engine = AlertEngine()

    def fit(step, n=16):
        x, y = _global_batch(step, n=n)
        trainer.fit([DataSet(x, y)])

    for step in range(4):  # steady warmup: one signature, one compile
        fit(step)
    engine.mark_warmup_done()
    compiles_at_warmup = dict(wd.stats()["per_fn_compiles"])
    steady_eval = [a for a in engine.evaluate()
                   if a["rule"] == "recompiles_after_warmup"][0]
    churn_firing = False
    if incarnation == 0:
        for step, n in enumerate((6, 7, 9, 11), start=4):  # shape churn
            fit(step, n=n)
        churn_firing = [a for a in engine.evaluate()
                        if a["rule"] == "recompiles_after_warmup"][0]["firing"]
        for step in range(8, 14):  # crash@iter=10,rank=1 fires in here
            fit(step)
    else:
        for step in range(4, 14):  # steady to completion
            fit(step)
    final_compiles = dict(wd.stats()["per_fn_compiles"])
    wd.close()
    aggregate.maybe_spool(force=True)
    flight.flush()
    col.barrier("churn-done")
    _write(rank, {"rank": rank, "incarnation": incarnation,
                  "steady_firing": steady_eval["firing"],
                  "churn_firing": churn_firing,
                  "per_fn_compiles_warmup": compiles_at_warmup,
                  "per_fn_compiles_final": final_compiles})


def etl_train():
    """ISSUE 6 acceptance target: per-rank SHARDED multi-process ETL feeding
    a 2-rank data-parallel gang under GangSupervisor. Each rank's ETL
    service decodes only its ``rank/world`` slice of the batch stream;
    checkpoints carry the iterator position (``TrainingCheckpointer.save(
    net, iterator)``), so a restarted gang replays the exact surviving
    stream — the parent asserts exact param parity with an unfaulted gang
    plus per-step batch-hash equality."""
    import hashlib

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.etl_service import (EtlDataSetIterator,
                                                     ImageEtlSpec)
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Sgd
    from deeplearning4j_tpu.parallel.launcher import ProcessCollectives
    from deeplearning4j_tpu.parallel.mesh import build_mesh
    from deeplearning4j_tpu.parallel.trainer import MultiProcessTrainer
    from deeplearning4j_tpu.serde.checkpoint import TrainingCheckpointer

    col = ProcessCollectives()
    rank, world = col.rank, col.world
    total_steps = int(os.environ.get("TDL_MP_STEPS", "8"))
    every = int(os.environ.get("TDL_MP_CKPT_EVERY", "2"))
    incarnation = int(os.environ.get("TDL_GANG_RESTART_COUNT", "0"))

    conf = (NeuralNetConfiguration.Builder().seed(7).updater(Sgd(0.05)).list()
            .layer(DenseLayer(n_in=24 * 24 * 3, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(24 * 24 * 3))
            .build())
    net = MultiLayerNetwork(conf).init()

    spec = ImageEtlSpec.from_directory(
        os.environ["TDL_ETL_DIR"], 24, 24, batch_size=8, store_pad=8,
        cache_dir=os.environ.get("TDL_ETL_CACHE")).for_rank(rank, world)
    it = EtlDataSetIterator(spec, num_workers=2, zero_copy=False)
    ck = TrainingCheckpointer(os.environ["TDL_MP_CKPT"], async_write=False)
    start = 0
    if ck.restore(net, it):  # also restores the iterator position
        start = int(net.iteration)
    trainer = MultiProcessTrainer(net, build_mesh(data=-1))
    step_hashes = {}
    try:
        for step in range(start, total_steps):
            if not it.has_next():
                it.reset()  # epoch boundary: stream continues seamlessly
            ds = it.next()
            step_hashes[str(step)] = hashlib.sha256(
                ds.features.tobytes() + ds.labels.tobytes()).hexdigest()
            x = (ds.features.reshape(ds.features.shape[0], -1)
                 .astype(np.float32) / 255.0)
            trainer.fit([DataSet(x, ds.labels)])
            if (step + 1) % every == 0:
                col.barrier(f"ck-{step}")
                ck.save(net, it)
                col.barrier(f"ck-done-{step}")
    finally:
        it.close()

    flat = np.asarray(net.params().numpy(), np.float64)
    _write(rank, {"param_sum": float(flat.sum()),
                  "param_tail": [float(v) for v in flat[-8:]],
                  "step_hashes": step_hashes, "start": start,
                  "incarnation": incarnation})


def w2v_shard_train():
    """Cross-process embedding-shard training (SURVEY §2.2 J17 / §2.6 S6):
    syn0/syn1 rows shard over a GLOBAL mesh spanning both processes; the
    epoch executable's gathers/updates compile to cross-process collectives.
    Each rank writes table hashes (cross-process row sync) + a semantic
    check (co-occurring words more similar than non-co-occurring)."""
    import hashlib

    import jax
    from jax.sharding import Mesh

    from deeplearning4j_tpu.nlp.word2vec import Word2Vec
    from deeplearning4j_tpu.parallel.launcher import ProcessCollectives

    col = ProcessCollectives()
    rank = col.rank
    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("model",))

    # two word clusters that never co-occur: 64 words → V=64 divides the
    # 8-device axis, so the tables genuinely shard 8 ways across processes
    rs = np.random.RandomState(0)
    a_words = [f"a{i}" for i in range(32)]
    b_words = [f"b{i}" for i in range(32)]
    sents = []
    for _ in range(400):
        sents.append(" ".join(rs.choice(a_words, 6)))
        sents.append(" ".join(rs.choice(b_words, 6)))

    w2v = Word2Vec(layer_size=16, window=3, negative=4, epochs=20,
                   learning_rate=0.05, batch_size=256, min_word_frequency=1,
                   seed=3, subsampling=0.0, mesh=mesh)
    w2v.fit(sents)

    def sim(u, v):
        u, v = w2v.get_word_vector(u), w2v.get_word_vector(v)
        return float(u @ v / (np.linalg.norm(u) * np.linalg.norm(v) + 1e-9))

    within = np.mean([sim(f"a{i}", f"a{i+1}") for i in range(0, 30, 2)]
                     + [sim(f"b{i}", f"b{i+1}") for i in range(0, 30, 2)])
    across = np.mean([sim(f"a{i}", f"b{i}") for i in range(0, 32, 2)])
    col.barrier("w2v-done")
    _write(rank, {
        "syn0_hash": hashlib.sha256(np.ascontiguousarray(w2v.syn0)).hexdigest(),
        "syn1_hash": hashlib.sha256(np.ascontiguousarray(w2v.syn1neg)).hexdigest(),
        "within": float(within), "across": float(across),
        "vocab": w2v.vocab.num_words(),
        "global_devices": jax.device_count(),
    })


def fsdp_train():
    """ISSUE 9 acceptance target: a gang training with SHARDED parameters —
    ``MultiProcessTrainer(mesh_layout=SpecLayout(data=1, fsdp=F, tp=T))``
    places params AND optimizer state over the fsdp/tp axes spanning the
    process boundary. Modes (TDL_MP_MODE):

    - ``train``: N steps on deterministic global batches (data axis is 1, so
      every rank feeds the full batch and GSPMD shards the math); layout-
      stamped sharded checkpoints via ``trainer.checkpointer`` when
      TDL_MP_CKPT is set.
    - ``restore``: a FRESH gang restores the sharded checkpoint (each rank
      reads only its shards) and writes the param fingerprint — the parent
      asserts exact parity with the trained gang, and that a mismatched
      TDL_MP_FSDP/TDL_MP_TP gang dies with the layout-mismatch error.
      ``TDL_MP_RESHARD=1`` opts the restore into the ISSUE 14 cross-topology
      path: a DIFFERENT gang shape/layout redistributes the saved chunks
      instead of refusing (each rank still reads only the chunk slices
      overlapping its addressable shards).

    Every rank reports ``tdl_param_bytes_per_rank`` so the parent can assert
    per-rank bytes shrink ~linearly with the fsdp axis size."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.monitoring.partition import partition_metrics
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.parallel.launcher import ProcessCollectives
    from deeplearning4j_tpu.parallel.partition import Partitioner, SpecLayout
    from deeplearning4j_tpu.parallel.trainer import MultiProcessTrainer

    col = ProcessCollectives()
    rank, world = col.rank, col.world
    data = int(os.environ.get("TDL_MP_DATA", "1"))
    fsdp = int(os.environ.get("TDL_MP_FSDP", "-1"))
    tp = int(os.environ.get("TDL_MP_TP", "1"))
    mode = os.environ.get("TDL_MP_MODE", "train")
    steps = int(os.environ.get("TDL_MP_STEPS", "4"))
    every = int(os.environ.get("TDL_MP_CKPT_EVERY", "2"))

    # every dim divisible by 4 so a 4-way fsdp axis shards EVERY leaf —
    # per-rank bytes then shrink exactly linearly (no replicated remainder)
    conf = (NeuralNetConfiguration.Builder().seed(7).updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_in=8, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8))
            .build())
    net = MultiLayerNetwork(conf).init()
    partitioner = Partitioner(SpecLayout(data=data, fsdp=fsdp, tp=tp))
    trainer = MultiProcessTrainer(net, mesh_layout=partitioner)
    ck = (trainer.checkpointer(os.environ["TDL_MP_CKPT"], async_write=False)
          if "TDL_MP_CKPT" in os.environ else None)

    def batch(step, n=8):
        rs = np.random.RandomState(2000 + step)
        x = rs.rand(n, 8).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, n)]
        return x, y

    losses = []
    if mode == "restore":
        reshard = os.environ.get("TDL_MP_RESHARD") == "1"
        if not ck or not ck.restore(net, reshard=reshard):
            raise RuntimeError("restore mode found no checkpoint")
        trainer._place_net()  # pass-through: shards already placed
    else:
        for step in range(steps):
            x, y = batch(step)
            trainer.fit([DataSet(x, y)])  # data axis =1: full global batch
            losses.append(float(net.score_))
            if ck is not None and (step + 1) % every == 0:
                col.barrier(f"fsdp-ck-{step}")
                ck.save(net)
                col.barrier(f"fsdp-ck-done-{step}")

    # device-side fingerprint (replicated scalars): the flat host view would
    # gather non-addressable shards — exactly what sharded state forbids
    psum = float(sum(jnp.sum(w) for w in jax.tree.leaves(net.params_)))
    pnorm = float(jnp.sqrt(sum(jnp.sum(jnp.square(w))
                               for w in jax.tree.leaves(net.params_))))
    m = partition_metrics()
    rep = trainer.partition_report
    col.barrier("fsdp-done")
    _write(rank, {
        "losses": losses, "param_sum": psum, "param_norm": pnorm,
        "iteration": int(net.iteration),
        "bytes_params": m.param_bytes.labels("params").value,
        "bytes_opt": m.param_bytes.labels("opt_state").value,
        "params_bytes_total": rep.params_bytes_total,
        "local_devices": jax.local_device_count(),
        "mesh": {a: int(s) for a, s in trainer.mesh.shape.items()},
        "global_devices": jax.device_count(),
    })


def elastic_train():
    """ISSUE 14 elastic-resize target: a sharded gang that adapts to
    WHATEVER world size the supervisor spawned.

    - layout = ``largest_layout(total devices)`` (fsdp absorbs them all), so
      a resized gang builds a valid smaller mesh without reconfiguration;
    - restore is unconditional with ``reshard=True``: after an elastic
      resize the survivors inherit the bigger gang's checkpoint through the
      cross-topology chunk redistribution;
    - the permanently-dead host is simulated by TDL_MP_DEAD_RANK: that rank
      ``os._exit``s at BOOT (before jax / any heartbeat) in every respawn
      (incarnation >= 1) while the world is still larger than
      TDL_MP_SURVIVORS — exactly a host that never comes back. Once the
      supervisor degrades the gang to the survivor count, the env rank ids
      renumber below the dead one and training continues unattended.
    """
    incarnation = int(os.environ.get("TDL_GANG_RESTART_COUNT", "0"))
    env_rank = int(os.environ.get("TDL_PROCESS_ID", "0"))
    env_world = int(os.environ.get("TDL_NUM_PROCESSES", "1"))
    dead = os.environ.get("TDL_MP_DEAD_RANK")
    survivors = int(os.environ.get("TDL_MP_SURVIVORS", "1"))
    if (dead is not None and env_rank == int(dead) and incarnation >= 1
            and env_world > survivors):
        os._exit(43)  # the "host" is gone: no boot, no heartbeat, ever

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.parallel.launcher import ProcessCollectives
    from deeplearning4j_tpu.parallel.partition import (Partitioner,
                                                       largest_layout)
    from deeplearning4j_tpu.parallel.trainer import MultiProcessTrainer

    col = ProcessCollectives()
    rank, world = col.rank, col.world
    steps = int(os.environ.get("TDL_MP_STEPS", "8"))
    every = int(os.environ.get("TDL_MP_CKPT_EVERY", "2"))

    conf = (NeuralNetConfiguration.Builder().seed(7).updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_in=8, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8))
            .build())
    net = MultiLayerNetwork(conf).init()
    partitioner = Partitioner(largest_layout(jax.device_count()))
    trainer = MultiProcessTrainer(net, mesh_layout=partitioner)
    ck = trainer.checkpointer(os.environ["TDL_MP_CKPT"], async_write=False,
                              reshard=True)
    start = 0
    if ck.restore(net):  # cross-topology after a resize; False on a cold dir
        start = int(net.iteration)
        trainer._place_net()

    def batch(step, n=8):
        rs = np.random.RandomState(2000 + step)
        x = rs.rand(n, 8).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, n)]
        return x, y

    for step in range(start, steps):
        x, y = batch(step)
        trainer.fit([DataSet(x, y)])  # data axis = 1: full global batch
        if (step + 1) % every == 0:
            col.barrier(f"el-ck-{step}")
            ck.save(net)
            col.barrier(f"el-ck-done-{step}")

    psum = float(sum(jnp.sum(w) for w in jax.tree.leaves(net.params_)))
    pnorm = float(jnp.sqrt(sum(jnp.sum(jnp.square(w))
                               for w in jax.tree.leaves(net.params_))))
    col.barrier("el-done")
    _write(rank, {
        "param_sum": psum, "param_norm": pnorm,
        "iteration": int(net.iteration), "start": start,
        "world": world, "incarnation": incarnation,
        "mesh": {a: int(s) for a, s in trainer.mesh.shape.items()},
        "global_devices": jax.device_count(),
    })


def tp_train():
    """Cross-process TENSOR-parallel numerics (r5 hygiene, VERDICT r4 weak
    #7): a dp×tp transformer step over a global 2-process mesh — the tp
    axis spans the process boundary, so Megatron column/row collectives
    cross it. Each rank writes the loss sequence; the parent asserts
    rank-identical losses AND parity with a single-process dp×tp run."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig,
        batch_specs,
        init_params,
        make_train_step,
        partition_specs,
    )
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.parallel.launcher import ProcessCollectives

    col = ProcessCollectives()
    rank = col.rank
    losses = tp_step_losses(Mesh(np.array(jax.devices()).reshape(2, 2),
                                 ("dp", "tp")))
    col.barrier("tp-done")
    _write(rank, {"losses": losses, "global_devices": jax.device_count()})


def tp_step_losses(mesh, steps=3):
    """Shared by the worker and the parent's single-process reference:
    deterministic dp×tp transformer training losses on the given mesh."""
    import jax

    from deeplearning4j_tpu.common import jax_compat
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.tree_util import tree_map

    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig,
        batch_specs,
        init_params,
        make_train_step,
        partition_specs,
    )
    from deeplearning4j_tpu.nn.updaters import Adam

    cfg = TransformerConfig.tiny(dropout=0.0)
    params = init_params(jax.random.key(0), cfg)
    pspecs = partition_specs(cfg)
    def _place(a, spec):
        arr = np.asarray(a)
        sh = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(arr.shape, sh, lambda idx: arr[idx])

    params = tree_map(_place, params, pspecs, is_leaf=lambda x: x is None)
    updater = Adam(1e-3)
    opt = updater.init(params)
    step = jax.jit(make_train_step(cfg, updater), donate_argnums=(0, 1))

    rs = np.random.RandomState(5)
    B, T = 8, 64
    bspec = batch_specs(cfg)
    batch = {
        "tokens": rs.randint(0, cfg.vocab_size, (B, T)).astype(np.int32),
        "labels": rs.randint(0, cfg.vocab_size, (B, T)).astype(np.int32),
        "weights": (rs.rand(B, T) < 0.15).astype(np.float32),
    }
    batch = {k: _place(v, bspec[k]) for k, v in batch.items()}
    rep = NamedSharding(mesh, jax.sharding.PartitionSpec())

    def _rep_arr(a):
        arr = np.asarray(a)
        return jax.make_array_from_callback(arr.shape, rep, lambda idx: arr[idx])

    rng = jax.random.wrap_key_data(_rep_arr(jax.random.key_data(jax.random.key(9))))
    losses = []
    with jax_compat.set_mesh(mesh):
        for i in range(steps):
            it = _rep_arr(np.asarray(i, np.int32))
            params, opt, loss = step(params, opt, batch, it, rng)
            losses.append(float(loss))
    return losses
