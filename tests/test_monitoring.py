"""Monitoring subsystem tests: registry/exposition, /metrics endpoints,
watchdogs, trace spans, OpProfiler chrome-trace round-trip, print lint
(ISSUE 1 acceptance criteria)."""

import ast
import json
import logging
import pathlib
import re
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.monitoring import (
    DeviceMemoryWatchdog,
    MetricsListener,
    MetricsRegistry,
    RecompileWatchdog,
    get_registry,
    set_trace_profiler,
    signature_of,
    span,
)
from deeplearning4j_tpu.monitoring import trace as trace_mod

_LABEL_RE = r'[a-zA-Z0-9_]+="(?:[^"\\]|\\.)*"'
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{%s(,%s)*\})?"
    r" (-?[0-9.eE+-]+|\+Inf|-Inf|NaN)$" % (_LABEL_RE, _LABEL_RE))


def _assert_valid_prometheus(text: str) -> None:
    assert text.endswith("\n")
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _SAMPLE_RE.match(line), f"invalid exposition line: {line!r}"


def _net():
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.Builder().seed(5).updater(Adam(0.01)).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _batch(n=16):
    from deeplearning4j_tpu.data.dataset import DataSet

    rs = np.random.RandomState(0)
    X = rs.randn(n, 4).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[rs.randint(0, 2, n)]
    return DataSet(X, Y)


# ------------------------------------------------------------------ registry


def test_counter_gauge_histogram_basics():
    r = MetricsRegistry()
    c = r.counter("requests_total", "reqs", labels=("op",))
    c.labels("matmul").inc()
    c.labels("matmul").inc(2)
    c.labels(op="add").inc()
    assert c.labels("matmul").value == 3
    with pytest.raises(ValueError):
        c.labels("matmul").inc(-1)  # counters only go up

    g = r.gauge("temp")
    g.set(4.5)
    g.set_to_max(2.0)  # lower value must NOT lower the watermark via max
    assert g.value == 4.5
    g.inc(0.5)
    assert g.value == 5.0

    h = r.histogram("lat_seconds", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    snap = r.snapshot()["lat_seconds"]["series"][0]
    assert snap["count"] == 4 and snap["inf"] == 1
    assert snap["buckets"] == {"0.1": 1, "1": 1, "10": 1}


def test_registry_get_or_create_and_mismatch():
    r = MetricsRegistry()
    a = r.counter("x_total", "first")
    assert r.counter("x_total") is a  # same object, no coordination needed
    with pytest.raises(ValueError):
        r.gauge("x_total")  # kind mismatch
    with pytest.raises(ValueError):
        r.counter("x_total", labels=("op",))  # labels mismatch


def test_prometheus_exposition_format():
    r = MetricsRegistry()
    r.counter("c_total", "a counter", labels=("k",)).labels('we"ird\n').inc()
    r.gauge("g").set(1.25)
    h = r.histogram("h_seconds", "hist", buckets=(0.5, 2.0))
    h.observe(0.1)
    h.observe(1.0)
    h.observe(100.0)
    text = r.to_prometheus()
    _assert_valid_prometheus(text)
    assert "# TYPE h_seconds histogram" in text
    # cumulative buckets ending at +Inf == count
    assert 'h_seconds_bucket{le="0.5"} 1' in text
    assert 'h_seconds_bucket{le="2"} 2' in text
    assert 'h_seconds_bucket{le="+Inf"} 3' in text
    assert "h_seconds_count 3" in text
    # label escaping survives round-trip
    assert 'c_total{k="we\\"ird\\n"} 1' in text


# ---------------------------------------------------- /metrics on the UIServer


def test_metrics_endpoint_after_fit():
    """Acceptance: GET /metrics returns valid Prometheus text incl. step
    duration histogram, samples/sec gauge, compile counter, device-memory
    high-water gauge after a short fit on the CPU backend."""
    from deeplearning4j_tpu.ui import UIServer

    reg = MetricsRegistry()
    net = _net()
    with RecompileWatchdog(registry=reg):
        net.add_listeners(MetricsListener(registry=reg, score_every=2,
                                          memory_every=4))
        ds = _batch()
        for _ in range(10):
            net._fit_batch(ds)
    assert net.last_batch_size == 16  # fit loops now record throughput basis

    server = UIServer(port=0)
    server.attach_registry(reg)
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
            ctype = resp.headers.get("Content-Type", "")
            text = resp.read().decode()
        assert "text/plain" in ctype and "version=0.0.4" in ctype
        _assert_valid_prometheus(text)
        for family in ("tdl_step_duration_seconds_bucket",
                       "tdl_samples_per_sec",
                       "tdl_xla_compiles_total",
                       "tdl_device_memory_high_water_bytes",
                       "tdl_score",
                       "tdl_iterations_total"):
            assert family in text, f"missing metric family {family}"

        with urllib.request.urlopen(base + "/metrics.json", timeout=10) as resp:
            snap = json.loads(resp.read())
        assert snap["tdl_iterations_total"]["series"][0]["value"] == 10
        assert snap["tdl_step_duration_seconds"]["series"][0]["count"] == 9
        assert snap["tdl_samples_per_sec"]["series"][0]["value"] > 0
    finally:
        server.stop()


def test_metrics_endpoint_defaults_to_process_registry():
    from deeplearning4j_tpu.ui import UIServer

    get_registry().counter("tdl_default_probe_total").inc()
    server = UIServer(port=0)
    server.attach_registry(None)  # explicit: serve the process default
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
            text = resp.read().decode()
        assert "tdl_default_probe_total" in text
    finally:
        server.stop()
        get_registry().unregister("tdl_default_probe_total")


# ------------------------------------------------------------------ watchdogs


def test_recompile_watchdog_shape_churn_warns_and_counts(caplog):
    """Acceptance: provoke shape-churn through the real fit path and assert
    the warning + counter increment."""
    reg = MetricsRegistry()
    net = _net()
    with RecompileWatchdog(registry=reg, window_steps=20, churn_threshold=3):
        with caplog.at_level(logging.WARNING,
                             logger="deeplearning4j_tpu.monitoring"):
            for n in (6, 7, 8, 9):  # batch-size churn: new jit signature each
                net._fit_batch(_batch(n))
    assert any("shape churn" in r.message for r in caplog.records)
    churn = reg.get("tdl_shape_churn_warnings_total")
    assert churn is not None and churn.value >= 1
    sigs = reg.get("tdl_jit_new_signatures_total")
    assert sigs.labels("MultiLayerNetwork.train_step").value == 4
    # real XLA compiles were observed and timed
    # real XLA compiles were observed, timed, and fn-attributed (ISSUE 10)
    compiles = {s["labels"]["fn"]: s["value"]
                for s in reg.get("tdl_xla_compiles_total").snapshot()["series"]}
    assert sum(compiles.values()) > 0
    assert compiles.get("MultiLayerNetwork.train_step", 0) > 0
    seconds = reg.get("tdl_xla_compile_seconds_total").snapshot()["series"]
    assert sum(s["value"] for s in seconds) > 0


def test_recompile_watchdog_stable_shapes_quiet(caplog):
    reg = MetricsRegistry()
    net = _net()
    ds = _batch()
    with RecompileWatchdog(registry=reg, window_steps=20, churn_threshold=3):
        with caplog.at_level(logging.WARNING,
                             logger="deeplearning4j_tpu.monitoring"):
            for _ in range(8):
                net._fit_batch(ds)
    assert not any("shape churn" in r.message for r in caplog.records)
    assert reg.get("tdl_shape_churn_warnings_total").value == 0
    assert reg.get("tdl_jit_new_signatures_total").labels(
        "MultiLayerNetwork.train_step").value == 1


def test_signature_of_distinguishes_shape_and_dtype():
    import jax.numpy as jnp

    a = jnp.ones((2, 3))
    assert signature_of(a) == signature_of(jnp.zeros((2, 3)))
    assert signature_of(a) != signature_of(jnp.ones((3, 2)))
    assert signature_of(a) != signature_of(jnp.ones((2, 3), jnp.int32))
    assert signature_of({"x": a, "m": None}) == signature_of({"x": a, "m": None})


def test_device_memory_watchdog_cpu_fallback_high_water():
    reg = MetricsRegistry()
    wd = DeviceMemoryWatchdog(registry=reg)
    sampled = wd.sample()
    assert sampled  # something was sampled even on the stats-less CPU backend
    hw = reg.get("tdl_device_memory_high_water_bytes")
    label = next(iter(sampled))
    first = hw.labels(label).value
    assert first > 0
    wd.sample()
    assert hw.labels(label).value >= first  # watermark never decreases


def test_device_memory_watchdog_threshold_dump(caplog):
    import jax.numpy as jnp

    keep = jnp.ones((128, 128))  # a live buffer for the dump to find
    reg = MetricsRegistry()
    wd = DeviceMemoryWatchdog(registry=reg, threshold_bytes=1,
                              dump_live_buffers=True, dump_top=3)
    with caplog.at_level(logging.WARNING,
                         logger="deeplearning4j_tpu.monitoring"):
        wd.sample()
    assert reg.get("tdl_device_memory_threshold_exceeded_total").value == 1
    msgs = [r.message for r in caplog.records]
    assert any("exceeds threshold" in m for m in msgs)
    assert any("MB" in m for m in msgs[1:]), "live-buffer dump missing"
    del keep


# ------------------------------------------------------------------- tracing


def test_spans_nest_and_feed_op_profiler():
    from deeplearning4j_tpu.ops.profiler import OpProfiler, ProfilerConfig

    prof = OpProfiler(ProfilerConfig(trace_events=True))
    with span("fit", profiler=prof):
        assert trace_mod.current_span_path() == "fit"
        with span("step", profiler=prof):
            assert trace_mod.current_span_path() == "fit/step"
    assert trace_mod.current_span_path() == ""
    stats = prof.stats()
    assert set(stats) == {"fit", "fit/step"}
    # enclosing span covers the nested one
    assert stats["fit"]["total_ns"] >= stats["fit/step"]["total_ns"]


def test_fit_step_spans_land_in_chrome_trace(tmp_path):
    """One chrome-trace file shows fit-step spans + op events together."""
    from deeplearning4j_tpu.ops.profiler import (OpProfiler, ProfileAnalyzer,
                                                 ProfilerConfig)

    prof = OpProfiler(ProfilerConfig(trace_events=True))
    set_trace_profiler(prof)
    try:
        net = _net()
        ds = _batch()
        for _ in range(3):
            net._fit_batch(ds)
        prof.record("custom_op", 1000)  # op event alongside the spans
    finally:
        set_trace_profiler(None)
    path = str(tmp_path / "trace.json")
    prof.to_chrome_trace(path)
    stats = ProfileAnalyzer.load(path)
    assert stats["train"].count == 3
    assert stats["custom_op"].count == 1


def test_op_profiler_chrome_trace_roundtrip(tmp_path):
    """Satellite: OpProfiler.to_chrome_trace → ProfileAnalyzer.load/compare
    round-trips counts and durations."""
    from deeplearning4j_tpu.ops.profiler import (OpProfiler, ProfileAnalyzer,
                                                 ProfilerConfig)

    prof = OpProfiler(ProfilerConfig(trace_events=True))
    with prof.timed("matmul"):
        np.dot(np.ones((64, 64)), np.ones((64, 64)))
    prof.record("add", 2_000)
    prof.record("add", 3_000)
    a = str(tmp_path / "a.json")
    prof.to_chrome_trace(a)

    loaded = ProfileAnalyzer.load(a)
    assert loaded["add"].count == 2
    assert loaded["matmul"].count == 1
    # ns → us → ns round-trip keeps microsecond resolution
    assert abs(loaded["add"].total_ns - 5_000) < 2_000
    assert loaded["matmul"].total_ns > 0

    rows = ProfileAnalyzer.compare(a, a)
    assert {r["op"] for r in rows} == {"matmul", "add"}
    assert all(r["delta_ns"] == 0 for r in rows)
    assert all(r["a_count"] == r["b_count"] for r in rows)


# ---------------------------------------------------------- listener satellites


class _StubModel:
    """Counts score() reads; exposes the listener-facing surface."""

    def __init__(self):
        self.score_calls = 0
        self.last_batch_size = 32
        self.epoch = 0

    def score(self):
        self.score_calls += 1
        return 0.25

    @property
    def score_(self):
        return 0.25


def test_score_iteration_listener_single_score_read(caplog):
    from deeplearning4j_tpu.listeners import ScoreIterationListener

    m = _StubModel()
    lst = ScoreIterationListener(print_iterations=1)
    with caplog.at_level(logging.INFO, logger="deeplearning4j_tpu"):
        lst.iteration_done(m, 1, 0)
    assert m.score_calls == 1  # was 2: score() evaluated twice per report
    assert any("Score at iteration 1" in r.message for r in caplog.records)


def test_time_iteration_listener_lazy_clock_and_clamp():
    import time as _time

    from deeplearning4j_tpu.listeners import TimeIterationListener

    lst = TimeIterationListener(total_iterations=100, frequency=0)  # no ZeroDivisionError
    assert lst.frequency == 1
    m = _StubModel()
    built_at = _time.perf_counter()
    _time.sleep(0.05)  # construction-to-fit gap must not skew the ETA clock
    lst.iteration_done(m, 1, 0)
    assert lst._start >= built_at + 0.04  # clock started at first iteration
    lst.iteration_done(m, 2, 0)  # frequency=1 path exercises the ETA math


def test_performance_listener_reports_rss(caplog):
    from deeplearning4j_tpu.listeners import PerformanceListener

    reg = MetricsRegistry()
    lst = PerformanceListener(frequency=1, registry=reg)
    m = _StubModel()
    with caplog.at_level(logging.INFO, logger="deeplearning4j_tpu"):
        lst.iteration_done(m, 1, 0)
        lst.iteration_done(m, 2, 0)
    assert lst.last_rss_bytes > 0
    assert reg.get("tdl_host_rss_bytes").value == lst.last_rss_bytes
    assert reg.get("tdl_listener_samples_per_sec").value > 0
    assert any("host RSS" in r.message for r in caplog.records)


def test_fit_scan_reports_per_step_batch():
    """last_batch_size is per STEP (rate listeners scale by iteration
    delta); fit_scan must not report the whole dispatch's sample count."""
    reg = MetricsRegistry()
    net = _net()
    net.add_listeners(MetricsListener(registry=reg))
    net._fit_batch(_batch(8))  # seed the listener's (time, iteration) mark
    net.fit_scan([_batch(8) for _ in range(4)])
    assert net.last_batch_size == 8
    assert net.iteration == 5
    sps = reg.get("tdl_samples_per_sec")
    assert sps.labels("MultiLayerNetwork").value > 0


def test_metrics_listener_epochs_and_fit_wiring():
    reg = MetricsRegistry()
    net = _net()
    net.add_listeners(MetricsListener(registry=reg, score_every=1))
    net.fit(_batch(), epochs=2)
    snap = reg.snapshot()
    assert snap["tdl_epochs_total"]["series"][0]["value"] == 2
    assert snap["tdl_iterations_total"]["series"][0]["value"] == 2
    assert snap["tdl_score"]["series"][0]["value"] > 0


# ------------------------------------------------------------------ print lint


_LINT_ALLOWED = (
    # UI/CLI surfaces: rendering to a terminal/browser is their job
    "ui/",
)


def test_no_bare_print_in_library_code():
    """Repo lint (ISSUE 1 satellite): library code reports through logging
    or the metrics registry, never bare print()."""
    root = pathlib.Path(__file__).resolve().parent.parent / "deeplearning4j_tpu"
    offenders = []
    for path in sorted(root.rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        if rel.startswith(_LINT_ALLOWED):
            continue
        tree = ast.parse(path.read_text(), filename=rel)
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                offenders.append(f"{rel}:{node.lineno}")
    assert not offenders, (
        "bare print() in library code (use logging or the metrics "
        f"registry): {offenders}")


# Fit/ETL hot-path modules: code here may legitimately receive DEVICE-resident
# arrays (DevicePrefetchIterator batches), where np.asarray is a blocking d2h
# copy the fit loop immediately re-uploads — the silent round trip the device
# pipeline exists to remove. Every np.asarray in these files must either be
# guarded (device arrays pass through first) or carry a `# host-ok:` comment
# justifying why the buffer is host-side by construction.
_HOT_PATH_FILES = (
    "nn/multilayer.py",
    "nn/graph.py",
    "parallel/trainer.py",
    "data/dataset.py",
    "data/iterators.py",
)


def test_no_unannotated_np_asarray_in_hot_paths():
    """Repo lint (ISSUE 4 satellite): blocking ``np.asarray(...)`` on a
    device array inside the fit/ETL hot paths is a silent d2h→h2d round-trip
    footgun. Static analysis can't prove an argument is host-side, so the
    rule is: in hot-path modules, every np.asarray call line must carry a
    ``# host-ok:`` justification (and the guard in data.dataset._to_np keeps
    device arrays away from the annotated ones)."""
    root = pathlib.Path(__file__).resolve().parent.parent / "deeplearning4j_tpu"
    offenders = []
    for rel in _HOT_PATH_FILES:
        src = (root / rel).read_text()
        lines = src.splitlines()
        tree = ast.parse(src, filename=rel)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "asarray"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in ("np", "numpy")):
                continue
            if "host-ok" not in lines[node.lineno - 1]:
                offenders.append(f"{rel}:{node.lineno}")
    assert not offenders, (
        "np.asarray in a fit/ETL hot path without a `# host-ok:` "
        "justification (on a device array this is a blocking d2h→h2d round "
        f"trip — use jnp.asarray / pass device arrays through): {offenders}")


def _dotted_name(node) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted_name(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def test_no_untimeouted_network_io():
    """Repo lint (ISSUE 5 satellite): a urllib/socket/http.client call
    without an explicit timeout hangs the caller forever when the peer
    wedges — the exact footgun the serving deadline work exists to remove.
    Library code must pass ``timeout=`` (urlopen / create_connection /
    HTTPConnection) or justify the site with a ``# timeout-ok:`` comment
    (raw ``socket.socket`` has no constructor timeout, so it always needs
    the annotation or a visible ``settimeout``)."""
    root = pathlib.Path(__file__).resolve().parent.parent / "deeplearning4j_tpu"
    offenders = []
    for path in sorted(root.rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        src = path.read_text()
        lines = src.splitlines()
        tree = ast.parse(src, filename=rel)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted_name(node.func)
            needs_timeout_kw = (
                name.endswith("urlopen")
                or name.endswith("create_connection")
                or name.endswith("HTTPConnection")
                or name.endswith("HTTPSConnection"))
            bare_socket = name == "socket.socket" or name.endswith(".socket.socket")
            if not (needs_timeout_kw or bare_socket):
                continue
            if "timeout-ok" in lines[node.lineno - 1]:
                continue
            if needs_timeout_kw and any(kw.arg == "timeout"
                                        for kw in node.keywords):
                continue
            offenders.append(f"{rel}:{node.lineno} ({name})")
    assert not offenders, (
        "network I/O without an explicit timeout in library code (pass "
        "timeout=..., or annotate a justified site with `# timeout-ok: "
        f"<reason>`): {offenders}")


_SHM_CLEANUP_FUNCS = ("close", "shutdown", "_teardown", "_cleanup",
                      "__del__", "__exit__")


def _exit_path_calls(tree: ast.AST, attr: str) -> bool:
    """True when a ``<x>.<attr>()`` call exists on an EXIT PATH: inside a
    ``finally`` block, or inside a function whose name marks it a teardown
    surface (close/shutdown/_teardown/_cleanup/__del__/__exit__)."""

    def walk(node, on_exit):
        if (on_exit and isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == attr):
            return True
        for name, value in ast.iter_fields(node):
            children = value if isinstance(value, list) else [value]
            child_exit = on_exit
            if isinstance(node, ast.Try) and name == "finalbody":
                child_exit = True
            for c in children:
                if isinstance(c, ast.FunctionDef):
                    if walk(c, on_exit or c.name in _SHM_CLEANUP_FUNCS):
                        return True
                elif isinstance(c, ast.AST):
                    if walk(c, child_exit):
                        return True
        return False

    return walk(tree, False)


def test_shared_memory_paired_with_cleanup():
    """Repo lint (ISSUE 6 satellite): a ``multiprocessing.shared_memory.
    SharedMemory`` creation that is never ``unlink()``ed leaks a named
    segment past process death (``/dev/shm`` fills; the pytest leak fixture
    only catches it in tests). Every creation site in library code must
    live in a module that calls BOTH ``.unlink()`` and ``.close()`` on an
    exit path (a ``finally`` block or a teardown-named function), or carry
    a ``# shm-ok: <reason>`` justification (e.g. attach-only sites where
    the creator owns the unlink)."""
    root = pathlib.Path(__file__).resolve().parent.parent / "deeplearning4j_tpu"
    offenders = []
    for path in sorted(root.rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        src = path.read_text()
        lines = src.splitlines()
        tree = ast.parse(src, filename=rel)
        creations = []
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and _dotted_name(node.func).endswith("SharedMemory")
                    and "shm-ok" not in lines[node.lineno - 1]):
                creations.append(node.lineno)
        if not creations:
            continue
        missing = [a for a in ("unlink", "close")
                   if not _exit_path_calls(tree, a)]
        if missing:
            offenders.extend(f"{rel}:{ln} (no {'/'.join(missing)} on an "
                             "exit path)" for ln in creations)
    assert not offenders, (
        "SharedMemory created without paired unlink()/close() on an exit "
        "path (finally block or close/_teardown/__del__/__exit__; annotate "
        f"justified sites with `# shm-ok: <reason>`): {offenders}")


def _broad_handler(handler: ast.ExceptHandler) -> bool:
    """Bare ``except:`` or ``except (Base)Exception`` — the handlers that can
    swallow genuine bugs. Narrow handlers (``except (TypeError, ValueError)``)
    may legitimately pass: dropping unparseable rows IS their semantics."""
    t = handler.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in ("Exception", "BaseException"):
            return True
        if isinstance(n, ast.Attribute) and n.attr in ("Exception", "BaseException"):
            return True
    return False


def test_no_silent_exception_swallowing():
    """Repo lint (ISSUE 3 satellite): a broad except whose entire body is
    ``pass``/``...`` silently swallows bugs — library code must log (even at
    debug level), narrow the exception, or actually handle it."""
    root = pathlib.Path(__file__).resolve().parent.parent / "deeplearning4j_tpu"
    offenders = []
    for path in sorted(root.rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        tree = ast.parse(path.read_text(), filename=rel)
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            body = node.body
            only_pass = len(body) == 1 and (
                isinstance(body[0], ast.Pass)
                or (isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)
                    and body[0].value.value is Ellipsis))
            if only_pass and _broad_handler(node):
                offenders.append(f"{rel}:{node.lineno}")
    assert not offenders, (
        "silent broad exception swallowing in library code (log it, narrow "
        f"it, or handle it): {offenders}")


# --------------------------------------------------------- etl ring/cache stats


def test_device_prefetch_stats_exports_etl_ring_and_cache_counters(
        tmp_path, tmp_path_factory):
    """ISSUE 6 satellite: DevicePrefetchIterator.stats() surfaces the ETL
    service's ring/cache counters, and the same numbers flow through the
    tdl_etl_* metric families of the registry."""
    from PIL import Image

    from deeplearning4j_tpu.data.etl_service import (EtlDataSetIterator,
                                                     ImageEtlSpec)
    from deeplearning4j_tpu.data.iterators import DevicePrefetchIterator

    root = tmp_path_factory.mktemp("etl_mon_imgs")
    rs = np.random.RandomState(3)
    for i in range(8):
        d = root / f"c{i % 2}"
        d.mkdir(exist_ok=True)
        Image.fromarray(rs.randint(0, 255, (32, 32, 3), dtype=np.uint8)).save(
            str(d / f"i{i}.jpg"), quality=85)

    reg = MetricsRegistry()
    spec = ImageEtlSpec.from_directory(str(root), 24, 24, batch_size=4,
                                       store_pad=8,
                                       cache_dir=str(tmp_path / "cache"))
    it = DevicePrefetchIterator(
        EtlDataSetIterator(spec, num_workers=1, registry=reg), buffer_size=2,
        registry=reg)
    try:
        for _ in range(2):  # epoch 2 serves from the decoded-batch cache
            it.reset()
            n = 0
            while it.has_next():
                ds = it.next()
                assert hasattr(ds.features, "devices")  # device-resident
                n += 1
            assert n == 2
        s = it.stats()
    finally:
        it.close()
    # ring/cache counters merged into the ONE pipeline stats() surface
    for key in ("ring_occupancy", "etl_worker_busy_frac", "cache_hits",
                "cache_misses", "etl_workers", "worker_respawns"):
        assert key in s, key
    assert s["etl_workers"] == 1
    assert s["cache_misses"] <= 2
    assert s["cache_hits"] >= 2          # epoch ≥2 skipped decode
    assert 0.0 <= s["etl_worker_busy_frac"] <= 1.0
    # ...and exported through the tdl_* families on the registry
    snap = reg.snapshot()
    for fam in ("tdl_etl_ring_occupancy", "tdl_etl_worker_busy_frac",
                "tdl_etl_cache_hits_total", "tdl_etl_cache_misses_total",
                "tdl_etl_workers", "tdl_etl_batches_total",
                "tdl_etl_worker_respawns_total"):
        assert fam in snap, fam
    # registry counters are cumulative PRODUCTION (close() syncs the final
    # worker counters, which may have run ahead of the consumed stats)
    assert snap["tdl_etl_cache_hits_total"]["series"][0]["value"] >= s["cache_hits"]
    assert snap["tdl_etl_batches_total"]["series"][0]["value"] >= 4
    # the h2d families from the device-prefetch layer ride along as before
    assert reg.get("tdl_h2d_bytes_total").value > 0
