"""Attention config layers (SURVEY §2.4 C1, VERDICT r1 Missing #7): the
DL4J builder surface can now express attention models, gradient-checked and
trainable end-to-end."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn import (
    AttentionVertex,
    LearnedSelfAttentionLayer,
    RecurrentAttentionLayer,
    SelfAttentionLayer,
)
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    GlobalPoolingLayer,
    InputType,
    Layer,
    NeuralNetConfiguration,
    OutputLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam


def _seq_data(rs, B=8, C=6, T=10, classes=3):
    x = rs.rand(B, C, T).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rs.randint(0, classes, B)]
    return x, y


def test_self_attention_layer_trains():
    rs = np.random.RandomState(0)
    x, y = _seq_data(rs)
    conf = (
        NeuralNetConfiguration.Builder().seed(1).updater(Adam(5e-3)).list()
        .layer(SelfAttentionLayer(n_out=8, n_heads=2, project_input=True))
        .layer(GlobalPoolingLayer(pooling_type="avg"))
        .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.recurrent(6))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    net.fit(DataSet(x, y))
    l0 = net.score_
    for _ in range(30):
        net.fit(DataSet(x, y))
    assert net.score_ < l0
    out = net.output(x).numpy()
    assert out.shape == (8, 3)
    np.testing.assert_allclose(out.sum(-1), 1.0, atol=1e-5)


def test_self_attention_unprojected_single_head():
    rs = np.random.RandomState(1)
    layer = SelfAttentionLayer(n_in=6, n_heads=1, project_input=False)
    x = jnp.asarray(rs.rand(4, 6, 10), jnp.float32)
    out = layer.forward({}, x, InputType.recurrent(6), training=False)
    assert out.shape == (4, 6, 10)
    import pytest
    with pytest.raises(ValueError):
        SelfAttentionLayer(n_heads=2, project_input=False)


def test_self_attention_respects_mask():
    """Masked (padded) timesteps must not change unmasked outputs... they DO
    change outputs when the mask is absent — assert the mask makes the padded
    and truncated sequences agree."""
    rs = np.random.RandomState(2)
    layer = SelfAttentionLayer(n_in=6, n_out=6, n_heads=2, head_size=3)
    params = layer.init_params(jax.random.key(0), InputType.recurrent(6))
    x_short = jnp.asarray(rs.rand(2, 6, 5), jnp.float32)
    x_pad = jnp.concatenate([x_short, jnp.ones((2, 6, 3))], axis=2)
    mask = jnp.concatenate([jnp.ones((2, 5)), jnp.zeros((2, 3))], axis=1)
    o_short = layer.forward(params, x_short, InputType.recurrent(6), training=False)
    o_pad = layer.forward(params, x_pad, InputType.recurrent(6), training=False, mask=mask)
    np.testing.assert_allclose(o_short, o_pad[..., :5], atol=1e-5)


def test_learned_self_attention_fixed_output_length():
    rs = np.random.RandomState(3)
    conf = (
        NeuralNetConfiguration.Builder().seed(1).updater(Adam(5e-3)).list()
        .layer(LearnedSelfAttentionLayer(n_out=8, n_heads=2, n_queries=4))
        .layer(GlobalPoolingLayer(pooling_type="avg"))
        .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.recurrent(6))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x, y = _seq_data(rs, T=12)
    net.fit(DataSet(x, y))
    l0 = net.score_
    for _ in range(20):
        net.fit(DataSet(x, y))
    assert net.score_ < l0
    # pooling is over the FIXED n_queries axis regardless of input T
    x2, _ = _seq_data(rs, T=12)
    assert net.output(x2).numpy().shape == (8, 3)


def test_recurrent_attention_layer_trains():
    rs = np.random.RandomState(4)
    x, y = _seq_data(rs, C=5, T=8)
    conf = (
        NeuralNetConfiguration.Builder().seed(2).updater(Adam(5e-3)).list()
        .layer(RecurrentAttentionLayer(n_in=5, n_out=8, n_heads=2, head_size=4))
        .layer(RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.recurrent(5))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    yt = np.eye(3, dtype=np.float32)[rs.randint(0, 3, (8, 8))].transpose(0, 2, 1)
    net.fit(DataSet(x, yt))
    l0 = net.score_
    for _ in range(20):
        net.fit(DataSet(x, yt))
    assert net.score_ < l0


def test_attention_vertex_in_graph():
    rs = np.random.RandomState(5)
    x, y = _seq_data(rs, C=6, T=10)
    conf = (
        NeuralNetConfiguration.Builder().seed(3).updater(Adam(5e-3))
        .graph_builder()
        .add_inputs("in")
        .set_input_types(InputType.recurrent(6))
        .add_vertex("attn", AttentionVertex(n_in=6, n_out=8, n_heads=2, head_size=4), "in")
        .add_layer("pool", GlobalPoolingLayer(pooling_type="avg"), "attn")
        .add_layer("out", OutputLayer(n_out=3, activation="softmax", loss="mcxent"), "pool")
        .set_outputs("out")
        .build()
    )
    g = ComputationGraph(conf).init()
    assert "attn" in g.params_  # parameterized vertex got params
    g.fit(DataSet(x, y))
    l0 = g.score_
    for _ in range(25):
        g.fit(DataSet(x, y))
    assert g.score_ < l0


def test_attention_gradcheck():
    """Finite-difference gradient check on SelfAttentionLayer params."""
    rs = np.random.RandomState(6)
    layer = SelfAttentionLayer(n_in=4, n_out=4, n_heads=2, head_size=2)
    params = layer.init_params(jax.random.key(0), InputType.recurrent(4))
    x = jnp.asarray(rs.rand(2, 4, 6), jnp.float32)

    def loss(p):
        return jnp.sum(layer.forward(p, x, InputType.recurrent(4), training=False) ** 2)

    g = jax.grad(loss)(params)
    eps = 1e-3  # fp32 central differences
    for name in ("Wq", "Wo"):
        w = params[name]
        idx = (0, 1)
        pp = {**params, name: w.at[idx].add(eps)}
        pm = {**params, name: w.at[idx].add(-eps)}
        fd = (loss(pp) - loss(pm)) / (2 * eps)
        np.testing.assert_allclose(float(g[name][idx]), float(fd), rtol=2e-2, atol=1e-4)


def test_attention_layer_serde_roundtrip():
    conf = (
        NeuralNetConfiguration.Builder().seed(1).list()
        .layer(SelfAttentionLayer(n_out=8, n_heads=2))
        .layer(GlobalPoolingLayer(pooling_type="avg"))
        .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.recurrent(6))
        .build()
    )
    import json
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration

    j = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(j)
    assert isinstance(conf2.layers[0], SelfAttentionLayer)
    assert conf2.layers[0].n_heads == 2
