"""Checkpoint depth (SURVEY §5.4): sharded save/restore, async write,
iterator-position capture, preemption hook, resume-equals-uninterrupted."""

import os
import signal

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.serde.checkpoint import PreemptionHandler, TrainingCheckpointer


def _net(seed=5):
    conf = (
        NeuralNetConfiguration.Builder()
        .seed(seed)
        .updater(Adam(1e-2))
        .list()
        .layer(DenseLayer(n_in=4, n_out=12, activation="relu"))
        .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(4))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _data(n=64, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, n)]
    return x, y


class TestCheckpointer:
    def test_save_restore_roundtrip(self, tmp_path):
        net = _net()
        x, y = _data()
        for i in range(3):
            net._fit_batch(DataSet(x[i * 8:(i + 1) * 8], y[i * 8:(i + 1) * 8]))
        ck = TrainingCheckpointer(str(tmp_path), async_write=False)
        ck.save(net)

        net2 = _net(seed=99)  # different init
        assert ck.restore(net2)
        assert net2.iteration == net.iteration
        for k in net.params_:
            for p in net.params_[k]:
                np.testing.assert_array_equal(
                    np.asarray(net2.params_[k][p]), np.asarray(net.params_[k][p]))
        import jax

        for a, b in zip(jax.tree.leaves(net.updater_state),
                        jax.tree.leaves(net2.updater_state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_restore_missing_returns_false(self, tmp_path):
        assert not TrainingCheckpointer(str(tmp_path)).restore(_net())

    def test_async_write_is_durable_after_wait(self, tmp_path):
        net = _net()
        ck = TrainingCheckpointer(str(tmp_path), async_write=True)
        ck.save(net)
        ck.wait()
        assert os.path.exists(tmp_path / "latest" / "train_state.json")
        assert os.path.exists(tmp_path / "latest" / "shard_0.npz")

    def test_kill_at_step_k_resume_reproduces_loss_curve(self, tmp_path):
        """The §5.4 'done' bar: checkpoint at step k, restore into a FRESH
        net + iterator, continue — losses match the uninterrupted run."""
        x, y = _data(64)

        # uninterrupted reference run: 8 batches
        ref = _net()
        it_ref = ArrayDataSetIterator(x, y, 8, shuffle=True, seed=3)
        ref_losses = []
        while it_ref.has_next():
            ref._fit_batch(it_ref.next())
            ref_losses.append(ref.score_)

        # interrupted run: 4 batches, checkpoint (incl. iterator pos), "die"
        a = _net()
        it_a = ArrayDataSetIterator(x, y, 8, shuffle=True, seed=3)
        for _ in range(4):
            a._fit_batch(it_a.next())
        ck = TrainingCheckpointer(str(tmp_path), async_write=False)
        ck.save(a, iterator=it_a)
        del a, it_a

        # resume in a fresh net + fresh iterator
        b = _net(seed=123)
        it_b = ArrayDataSetIterator(x, y, 8, shuffle=True, seed=3)
        assert ck.restore(b, iterator=it_b)
        resumed = []
        while it_b.has_next():
            b._fit_batch(it_b.next())
            resumed.append(b.score_)
        np.testing.assert_allclose(resumed, ref_losses[4:], rtol=1e-5, atol=1e-6)

    def test_async_write_failure_surfaces(self, tmp_path, monkeypatch):
        """ISSUE 3 satellite: a failed background write must not vanish —
        it re-raises from wait() (or the next save()) and counts
        tdl_checkpoint_failures_total."""
        import numpy as _np

        from deeplearning4j_tpu.monitoring.registry import get_registry

        failures = get_registry().counter("tdl_checkpoint_failures_total")
        before = failures.value
        net = _net()
        ck = TrainingCheckpointer(str(tmp_path), async_write=True)

        real_savez = _np.savez

        def boom(*a, **k):
            raise OSError("disk full")

        monkeypatch.setattr(_np, "savez", boom)
        ck.save(net)  # background thread hits the failing write
        with pytest.raises(OSError, match="disk full"):
            ck.wait()
        assert failures.value == before + 1

        # the error is consumed once surfaced; a healthy save works again
        monkeypatch.setattr(_np, "savez", real_savez)
        ck.save(net)
        ck.wait()
        assert os.path.exists(tmp_path / "latest" / "shard_0.npz")

    def test_async_write_failure_reraised_by_next_save(self, tmp_path, monkeypatch):
        import numpy as _np

        net = _net()
        ck = TrainingCheckpointer(str(tmp_path), async_write=True)
        monkeypatch.setattr(_np, "savez",
                            lambda *a, **k: (_ for _ in ()).throw(OSError("enospc")))
        ck.save(net)
        with pytest.raises(OSError, match="enospc"):
            ck.save(net)

    def test_sharded_arrays_roundtrip_over_mesh(self, tmp_path):
        """Params sharded over the 8-device mesh save shard-wise and
        reassemble to the same global values."""
        import jax
        from deeplearning4j_tpu.parallel.mesh import build_mesh
        from deeplearning4j_tpu.parallel.sharding import alternating_dense_rules
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
        from deeplearning4j_tpu.data.iterators import ListDataSetIterator

        x, y = _data(32)
        net = _net()
        before = {k: {p: np.asarray(v) for p, v in d.items()}
                  for k, d in net.params_.items()}
        mesh = build_mesh(data=2, model=4)
        tr = ParallelTrainer(net, mesh, sharding_rules=alternating_dense_rules())
        tr._place_net()  # shard without training: values must be preserved
        ck = TrainingCheckpointer(str(tmp_path), async_write=False)
        ck.save(net)
        net2 = _net(seed=77)
        assert ck.restore(net2)
        for k in before:
            for p in before[k]:
                np.testing.assert_allclose(
                    np.asarray(net2.params_[k][p]), before[k][p], rtol=1e-6)


class TestPreemption:
    def test_sigterm_saves_before_death(self, tmp_path):
        net = _net()
        x, y = _data(16)
        net._fit_batch(DataSet(x, y))
        ck = TrainingCheckpointer(str(tmp_path), async_write=True)
        h = PreemptionHandler(ck, net, signals=(signal.SIGTERM,), swallow=True).install()
        try:
            os.kill(os.getpid(), signal.SIGTERM)
        finally:
            h.uninstall()
        assert h.fired
        assert os.path.exists(tmp_path / "preempt" / "train_state.json")
        net2 = _net(seed=42)
        assert ck.restore(net2, tag="preempt")
        np.testing.assert_array_equal(
            np.asarray(net2.params_["0"]["W"]), np.asarray(net.params_["0"]["W"]))


def test_model_guesser(tmp_path):
    """ModelGuesser: format sniffing across the three container types."""
    import pytest as _pytest

    from deeplearning4j_tpu.models import LeNet
    from deeplearning4j_tpu.serde import ModelGuesser, ModelSerializer

    net = LeNet(num_classes=3, input_shape=(1, 8, 8)).init()
    p = str(tmp_path / "m.zip")
    ModelSerializer.write_model(net, p)
    loaded = ModelGuesser.load_model_guess(p)
    x = np.random.RandomState(0).rand(2, 1, 8, 8).astype(np.float32)
    np.testing.assert_allclose(loaded.output(x).numpy(), net.output(x).numpy(),
                               rtol=1e-5, atol=1e-6)

    keras = _pytest.importorskip("keras")
    m = keras.Sequential([keras.Input((6,)), keras.layers.Dense(4)])
    kp = str(tmp_path / "k.h5")
    m.save(kp)
    knet = ModelGuesser.load_model_guess(kp)
    xk = np.random.RandomState(1).randn(3, 6).astype(np.float32)
    np.testing.assert_allclose(knet.output(xk).numpy(), m.predict(xk, verbose=0),
                               rtol=1e-4, atol=1e-5)

    bad = str(tmp_path / "junk.bin")
    open(bad, "wb").write(b"\x00\x01\x02garbage")
    with _pytest.raises(ValueError, match="cannot guess"):
        ModelGuesser.load_model_guess(bad)
