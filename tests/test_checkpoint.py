"""Checkpoint depth (SURVEY §5.4) + durable lineage (ISSUE 15): sharded
save/restore, async write, iterator-position capture, preemption hook,
resume-equals-uninterrupted — and the crash-consistent generational story:
two-phase commit, verify-then-fallback restore with quarantine,
transactional restore, keep-last-K GC, and the fsync AST lint."""

import ast
import json
import os
import pathlib
import signal
import zlib

import numpy as np
import pytest

from deeplearning4j_tpu.common import faults
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.monitoring.registry import get_registry
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.serde.checkpoint import (CheckpointVerifyError,
                                                 PreemptionHandler,
                                                 TrainingCheckpointer,
                                                 _gen_name, _self_checksummed,
                                                 lineage_state,
                                                 verify_checkpoint)

ROOT = pathlib.Path(__file__).resolve().parent.parent / "deeplearning4j_tpu"


def _net(seed=5):
    conf = (
        NeuralNetConfiguration.Builder()
        .seed(seed)
        .updater(Adam(1e-2))
        .list()
        .layer(DenseLayer(n_in=4, n_out=12, activation="relu"))
        .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(4))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _data(n=64, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, n)]
    return x, y


def _fit_steps(net, steps, x, y, batch=8):
    for i in range(steps):
        lo = (i * batch) % (len(x) - batch)
        net._fit_batch(DataSet(x[lo:lo + batch], y[lo:lo + batch]))


def _flip_byte(path):
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))


def _state_bytes(net):
    """Bit-exact snapshot of every param/updater/bn leaf + counters."""
    import jax

    leaves = (jax.tree.leaves(net.params_) + jax.tree.leaves(net.updater_state)
              + jax.tree.leaves(net.bn_state))
    return ([np.asarray(a).tobytes() for a in leaves],
            int(net.iteration), int(net.epoch))


def _counter_value(name, *label_vals):
    snap = get_registry().snapshot().get(name)
    if not snap:
        return 0.0
    total = 0.0
    for s in snap["series"]:
        if not label_vals or list(s["labels"].values()) == list(label_vals):
            total += s["value"]
    return total


class TestCheckpointer:
    def test_save_restore_roundtrip(self, tmp_path):
        net = _net()
        x, y = _data()
        for i in range(3):
            net._fit_batch(DataSet(x[i * 8:(i + 1) * 8], y[i * 8:(i + 1) * 8]))
        ck = TrainingCheckpointer(str(tmp_path), async_write=False)
        ck.save(net)

        net2 = _net(seed=99)  # different init
        assert ck.restore(net2)
        assert net2.iteration == net.iteration
        for k in net.params_:
            for p in net.params_[k]:
                np.testing.assert_array_equal(
                    np.asarray(net2.params_[k][p]), np.asarray(net.params_[k][p]))
        import jax

        for a, b in zip(jax.tree.leaves(net.updater_state),
                        jax.tree.leaves(net2.updater_state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_restore_missing_returns_false(self, tmp_path):
        assert not TrainingCheckpointer(str(tmp_path)).restore(_net())

    def test_async_write_is_durable_after_wait(self, tmp_path):
        net = _net()
        ck = TrainingCheckpointer(str(tmp_path), async_write=True)
        gendir = ck.save(net)
        ck.wait()
        assert os.path.exists(os.path.join(gendir, "train_state.json"))
        assert os.path.exists(os.path.join(gendir, "shard_0.npz"))
        assert os.path.exists(os.path.join(gendir, "manifest_0.json"))
        assert os.path.exists(os.path.join(gendir, "COMMIT"))
        assert ck.committed_generation() == gendir

    def test_kill_at_step_k_resume_reproduces_loss_curve(self, tmp_path):
        """The §5.4 'done' bar: checkpoint at step k, restore into a FRESH
        net + iterator, continue — losses match the uninterrupted run."""
        x, y = _data(64)

        # uninterrupted reference run: 8 batches
        ref = _net()
        it_ref = ArrayDataSetIterator(x, y, 8, shuffle=True, seed=3)
        ref_losses = []
        while it_ref.has_next():
            ref._fit_batch(it_ref.next())
            ref_losses.append(ref.score_)

        # interrupted run: 4 batches, checkpoint (incl. iterator pos), "die"
        a = _net()
        it_a = ArrayDataSetIterator(x, y, 8, shuffle=True, seed=3)
        for _ in range(4):
            a._fit_batch(it_a.next())
        ck = TrainingCheckpointer(str(tmp_path), async_write=False)
        ck.save(a, iterator=it_a)
        del a, it_a

        # resume in a fresh net + fresh iterator
        b = _net(seed=123)
        it_b = ArrayDataSetIterator(x, y, 8, shuffle=True, seed=3)
        assert ck.restore(b, iterator=it_b)
        resumed = []
        while it_b.has_next():
            b._fit_batch(it_b.next())
            resumed.append(b.score_)
        np.testing.assert_allclose(resumed, ref_losses[4:], rtol=1e-5, atol=1e-6)

    def test_async_write_failure_surfaces(self, tmp_path, monkeypatch):
        """ISSUE 3 satellite: a failed background write must not vanish —
        it re-raises from wait() (or the next save()) and counts
        tdl_checkpoint_failures_total."""
        import numpy as _np

        failures = get_registry().counter("tdl_checkpoint_failures_total")
        before = failures.value
        net = _net()
        ck = TrainingCheckpointer(str(tmp_path), async_write=True)

        real_savez = _np.savez

        def boom(*a, **k):
            raise OSError("disk full")

        monkeypatch.setattr(_np, "savez", boom)
        ck.save(net)  # background thread hits the failing write
        with pytest.raises(OSError, match="disk full"):
            ck.wait()
        assert failures.value == before + 1

        # the error is consumed once surfaced; a healthy save works again
        monkeypatch.setattr(_np, "savez", real_savez)
        gendir = ck.save(net)
        ck.wait()
        assert os.path.exists(os.path.join(gendir, "shard_0.npz"))
        assert ck.committed_generation() == gendir

    def test_async_write_failure_reraised_by_next_save(self, tmp_path, monkeypatch):
        import numpy as _np

        net = _net()
        ck = TrainingCheckpointer(str(tmp_path), async_write=True)
        monkeypatch.setattr(_np, "savez",
                            lambda *a, **k: (_ for _ in ()).throw(OSError("enospc")))
        ck.save(net)
        with pytest.raises(OSError, match="enospc"):
            ck.save(net)

    def test_sharded_arrays_roundtrip_over_mesh(self, tmp_path):
        """Params sharded over the 8-device mesh save shard-wise and
        reassemble to the same global values."""
        from deeplearning4j_tpu.parallel.mesh import build_mesh
        from deeplearning4j_tpu.parallel.sharding import alternating_dense_rules
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

        net = _net()
        before = {k: {p: np.asarray(v) for p, v in d.items()}
                  for k, d in net.params_.items()}
        mesh = build_mesh(data=2, model=4)
        tr = ParallelTrainer(net, mesh, sharding_rules=alternating_dense_rules())
        tr._place_net()  # shard without training: values must be preserved
        ck = TrainingCheckpointer(str(tmp_path), async_write=False)
        ck.save(net)
        net2 = _net(seed=77)
        assert ck.restore(net2)
        for k in before:
            for p in before[k]:
                np.testing.assert_allclose(
                    np.asarray(net2.params_[k][p]), before[k][p], rtol=1e-6)


# ------------------------------------------------ durable lineage (ISSUE 15)


class TestLineage:
    def test_generational_saves_never_mutate_and_pointer_tracks_newest(
            self, tmp_path):
        net = _net()
        x, y = _data()
        ck = TrainingCheckpointer(str(tmp_path), async_write=False)
        _fit_steps(net, 2, x, y)
        gen_a = ck.save(net)
        a_bytes = open(os.path.join(gen_a, "shard_0.npz"), "rb").read()
        _fit_steps(net, 2, x, y)
        gen_b = ck.save(net)
        assert gen_a != gen_b
        # the older generation was not touched by the newer save
        assert open(os.path.join(gen_a, "shard_0.npz"), "rb").read() == a_bytes
        with open(tmp_path / "latest" / "LATEST") as f:
            assert f.read().strip() == os.path.basename(gen_b)
        assert ck.committed_generation() == gen_b
        st = lineage_state(str(tmp_path))
        assert [g["generation"] for g in st["committed"]] == \
            [os.path.basename(gen_a), os.path.basename(gen_b)]
        assert st["pointer"] == os.path.basename(gen_b)
        assert st["quarantined"] == [] and st["uncommitted"] == []

    def test_gc_keeps_last_k_and_never_the_newest_committed(self, tmp_path):
        net = _net()
        x, y = _data()
        ck = TrainingCheckpointer(str(tmp_path), async_write=False,
                                  keep_last=2)
        gens = []
        for _ in range(5):
            _fit_steps(net, 1, x, y)
            gens.append(os.path.basename(ck.save(net)))
        live = sorted(n for n in os.listdir(tmp_path / "latest")
                      if n.startswith("gen-"))
        assert live == gens[-2:]
        assert ck.committed_generation().endswith(gens[-1])
        # keep_last=1 (clamped floor): even then the newest survives every GC
        ck1 = TrainingCheckpointer(str(tmp_path), async_write=False,
                                   keep_last=1)
        for _ in range(3):
            _fit_steps(net, 1, x, y)
            newest = ck1.save(net)
            assert os.path.isdir(newest)
            fresh = _net(seed=31)
            assert ck1.restore(fresh)
            assert fresh.iteration == net.iteration

    def test_concurrent_async_save_gc_never_breaks_restore(self, tmp_path):
        """keep_last=1 with ASYNC saves: GC runs on the writer thread while
        the train loop keeps fitting — after every wait() the lineage must
        hold a restorable newest generation (GC never eats the generation
        being written or the one just committed)."""
        net = _net()
        x, y = _data()
        ck = TrainingCheckpointer(str(tmp_path), async_write=True,
                                  keep_last=1)
        for _ in range(4):
            _fit_steps(net, 1, x, y)
            ck.save(net)
        ck.wait()
        fresh = _net(seed=8)
        assert ck.restore(fresh)
        assert fresh.iteration == net.iteration

    def test_resave_at_same_iteration_never_mutates_committed(self, tmp_path):
        """Review fix pin: a re-save at an UNCHANGED iteration counter (the
        PBT clone/re-save shape) lands in a suffixed sibling generation —
        the committed bytes are never rewritten in place, and the suffixed
        sibling is the newer one by ordering."""
        net = _net()
        x, y = _data()
        _fit_steps(net, 2, x, y)
        ck = TrainingCheckpointer(str(tmp_path), async_write=False)
        g1 = ck.save(net)
        bytes1 = open(os.path.join(g1, "shard_0.npz"), "rb").read()

        clone = _net(seed=99)            # different weights...
        clone.iteration = net.iteration  # ...same iteration counter
        clone.epoch = net.epoch
        g2 = ck.save(clone)
        assert g2 == g1 + "a", (g1, g2)
        assert open(os.path.join(g1, "shard_0.npz"), "rb").read() == bytes1
        assert ck.committed_generation() == g2  # suffix orders newest-last
        fresh = _net(seed=3)
        assert ck.restore(fresh)         # the clone's weights win
        np.testing.assert_array_equal(
            np.asarray(fresh.params_["0"]["W"]),
            np.asarray(clone.params_["0"]["W"]))
        assert verify_checkpoint(str(tmp_path))["generation"] == \
            os.path.basename(g2)

        # async form of the pin: the name probe runs AFTER the in-flight
        # background write commits (save waits first), so back-to-back
        # async saves at one iteration land in DISTINCT suffixed siblings
        # instead of the second mutating the first's just-committed dir
        ck_async = TrainingCheckpointer(str(tmp_path), async_write=True)
        g3 = g4 = None
        for seed in (55, 56):
            c = _net(seed=seed)
            c.iteration, c.epoch = net.iteration, net.epoch
            g3, g4 = g4, ck_async.save(c)
        ck_async.wait()
        assert g3 == g1 + "b" and g4 == g1 + "c", (g3, g4)
        assert os.path.exists(os.path.join(g3, "COMMIT"))
        assert os.path.exists(os.path.join(g4, "COMMIT"))

    def test_restore_is_transactional_on_verify_failure(self, tmp_path):
        """ISSUE 15 pin: when NOTHING verifies, restore raises and leaves
        params, updater state, net.iteration and the ITERATOR position
        bit-identical to the pre-call state."""
        x, y = _data(64)
        net = _net()
        it = ArrayDataSetIterator(x, y, 8, shuffle=True, seed=3)
        for _ in range(2):
            net._fit_batch(it.next())
        ck = TrainingCheckpointer(str(tmp_path), async_write=False)
        ck.save(net, iterator=it)
        # corrupt EVERY committed generation (there is exactly one)
        for name in os.listdir(tmp_path / "latest"):
            if name.startswith("gen-"):
                _flip_byte(tmp_path / "latest" / name / "shard_0.npz")

        victim = _net(seed=77)
        it2 = ArrayDataSetIterator(x, y, 8, shuffle=True, seed=9)
        for _ in range(3):
            victim._fit_batch(it2.next())
        leaves0, iter0, epoch0 = _state_bytes(victim)
        it_state0 = json.dumps(it2.state())
        with pytest.raises(CheckpointVerifyError, match="nothing restorable"):
            ck.restore(victim, iterator=it2)
        leaves1, iter1, epoch1 = _state_bytes(victim)
        assert leaves0 == leaves1          # bit-identical state trees
        assert (iter0, epoch0) == (iter1, epoch1)
        assert json.dumps(it2.state()) == it_state0
        # the failing generation is quarantined, not left as poison
        assert any(".corrupt" in n for n in os.listdir(tmp_path / "latest"))
        # ...and the all-corrupt verdict is STICKY: the next restore (the
        # respawned incarnation) must raise again off the pointer/COMMIT
        # evidence, never silently fresh-init over lost progress
        with pytest.raises(CheckpointVerifyError, match="demonstrably"):
            ck.restore(_net(seed=78))

    def test_fallback_restores_newest_verifiable_generation(self, tmp_path):
        net = _net()
        x, y = _data()
        ck = TrainingCheckpointer(str(tmp_path), async_write=False)
        _fit_steps(net, 2, x, y)
        gen_a = ck.save(net)
        import jax

        params_a = [np.asarray(w) for w in jax.tree.leaves(net.params_)]
        iter_a = int(net.iteration)
        _fit_steps(net, 2, x, y)
        gen_b = ck.save(net)
        _flip_byte(os.path.join(gen_b, "shard_0.npz"))

        fails0 = _counter_value("tdl_ckpt_verify_failures_total")
        quar0 = _counter_value("tdl_ckpt_quarantined_total")
        fb0 = _counter_value("tdl_ckpt_fallback_restores_total")
        fresh = _net(seed=42)
        assert ck.restore(fresh)
        assert int(fresh.iteration) == iter_a
        for got, want in zip(jax.tree.leaves(fresh.params_), params_a):
            np.testing.assert_array_equal(np.asarray(got), want)
        assert _counter_value("tdl_ckpt_verify_failures_total") == fails0 + 1
        assert _counter_value("tdl_ckpt_quarantined_total") == quar0 + 1
        assert _counter_value("tdl_ckpt_fallback_restores_total") == fb0 + 1
        # quarantined under a *.corrupt name; gen_a still the committed tip
        assert not os.path.exists(gen_b)
        assert os.path.isdir(gen_b + ".corrupt")
        assert ck.committed_generation() == gen_a
        # a quarantined dir handed back to the pre-flight is NEVER blessed
        # (its basename no longer parses as a generation — without the
        # explicit check it would sniff as a "legacy" flat checkpoint)
        rep = verify_checkpoint(gen_b + ".corrupt")
        assert not rep["ok"] and rep["reason"] == "quarantined", rep
        # the freed name is reusable: training on and re-saving works
        _fit_steps(fresh, 4, x, y)
        ck.save(fresh)
        assert ck.restore(_net(seed=43))

    def test_kill_matrix_boundaries_single_process(self, tmp_path):
        """Fast tier mirror of the chaos kill-matrix: hand-build the exact
        on-disk states a SIGKILL leaves at each commit boundary and pin
        which generation restores. (The real-process version rides
        tests/test_supervisor.py's slow tier.)"""
        net = _net()
        x, y = _data()
        ck = TrainingCheckpointer(str(tmp_path), async_write=False)
        _fit_steps(net, 2, x, y)
        gen_a = ck.save(net)
        iter_a = int(net.iteration)
        _fit_steps(net, 2, x, y)
        gen_b = ck.save(net)
        iter_b = int(net.iteration)
        lineage = tmp_path / "latest"
        import shutil

        pristine = tmp_path / "pristine_gen_b"
        shutil.copytree(gen_b, pristine)

        def reset_gen_b(tamper):
            """Fresh copy of the committed gen_b, then one boundary tamper
            (the state a SIGKILL at that boundary leaves behind)."""
            if os.path.isdir(gen_b):
                shutil.rmtree(gen_b)
            shutil.copytree(pristine, gen_b)
            with open(lineage / "LATEST", "w") as f:
                f.write(os.path.basename(gen_b) + "\n")
            tamper()

        def restore_iteration():
            fresh = _net(seed=9)
            assert ck.restore(fresh)
            return int(fresh.iteration)

        # pre-pointer-swap: COMMIT exists, pointer still names gen_a —
        # iteration order wins and the NEW generation restores
        reset_gen_b(lambda: open(lineage / "LATEST", "w").write(
            os.path.basename(gen_a) + "\n"))
        assert restore_iteration() == iter_b

        # pre-COMMIT: marker missing → uncommitted → quarantine + fallback
        reset_gen_b(lambda: os.unlink(os.path.join(gen_b, "COMMIT")))
        assert restore_iteration() == iter_a
        assert os.path.isdir(gen_b + ".corrupt")

        # post-shard / pre-manifest: COMMIT present but no rank manifest
        reset_gen_b(lambda: os.unlink(os.path.join(gen_b, "manifest_0.json")))
        assert restore_iteration() == iter_a

        # mid-shard: a torn (truncated) shard fails its manifest CRCs
        def truncate_shard():
            shard = os.path.join(gen_b, "shard_0.npz")
            with open(shard, "r+b") as f:
                f.truncate(os.path.getsize(shard) // 2)

        reset_gen_b(truncate_shard)
        assert restore_iteration() == iter_a

    def test_uncommitted_only_lineage_is_no_checkpoint_not_silent(
            self, tmp_path):
        """Nothing was ever committed (first save torn): restore answers
        False — truthfully, no save() ever completed — but LOUDLY: the torn
        generation is quarantined and counted, never restored from."""
        lineage = tmp_path / "latest"
        gen = lineage / _gen_name(2)
        gen.mkdir(parents=True)
        (gen / "shard_0.npz").write_bytes(b"torn")
        quar0 = _counter_value("tdl_ckpt_quarantined_total")
        ck = TrainingCheckpointer(str(tmp_path), async_write=False)
        assert not ck.restore(_net())
        assert _counter_value("tdl_ckpt_quarantined_total") == quar0 + 1
        assert any(n.endswith(".corrupt") for n in os.listdir(lineage))
        # never-committed evidence stays "no checkpoint" on every later
        # call too (no pointer, no COMMIT marker = no commit was ever lost)
        assert not ck.restore(_net())
        # ...but once a commit EXISTED, an unverifiable lineage must raise
        net = _net()
        x, y = _data()
        _fit_steps(net, 1, x, y)
        gendir = ck.save(net)
        _flip_byte(os.path.join(gendir, "shard_0.npz"))
        with pytest.raises(CheckpointVerifyError):
            ck.restore(_net(seed=2))

    def test_legacy_torn_dir_raises_instead_of_fresh_init(self, tmp_path):
        """ISSUE 15 satellite bugfix: a PRE-LINEAGE dir holding shard files
        but no train_state.json (rank-0 killed between shard and meta
        writes) used to return False — the next incarnation silently
        trained from scratch. Now it raises."""
        legacy = tmp_path / "latest"
        legacy.mkdir()
        (legacy / "shard_0.npz").write_bytes(b"not-a-real-npz")
        with pytest.raises(CheckpointVerifyError, match="torn"):
            TrainingCheckpointer(str(tmp_path)).restore(_net())

    def test_manifest_save_id_and_checksum_tampering_detected(self, tmp_path):
        net = _net()
        x, y = _data()
        _fit_steps(net, 2, x, y)
        ck = TrainingCheckpointer(str(tmp_path), async_write=False)
        gendir = ck.save(net)
        man_path = os.path.join(gendir, "manifest_0.json")
        with open(man_path) as f:
            man = json.load(f)

        # (a) flipped save_id with a RE-STAMPED checksum → reason save_id
        bad = dict(man)
        bad["save_id"] = man["save_id"] + 1
        with open(man_path, "w") as f:
            json.dump(_self_checksummed(bad), f)
        rep = verify_checkpoint(str(tmp_path))
        assert not rep["ok"] and rep["reason"] == "save_id"

        # (b) edited body WITHOUT re-stamping → self-checksum catches it
        bad2 = dict(man)
        bad2["entries"] = dict(man["entries"], **{"__save_id__": 1})
        with open(man_path, "w") as f:
            json.dump(bad2, f)
        rep = verify_checkpoint(str(tmp_path))
        assert not rep["ok"] and rep["reason"] == "manifest_crc"

        # restore agrees with the pre-flight verdict: quarantine + raise
        with pytest.raises(CheckpointVerifyError):
            ck.restore(_net(seed=3))

    def test_legacy_flat_never_shadows_generations(self, tmp_path):
        """Review fix pin: after the lineage upgrade, a leftover pre-lineage
        flat checkpoint in the same dir must NOT shadow newer committed
        generations — generations outrank it, and it survives only as the
        deepest fallback."""
        import shutil

        net = _net()
        x, y = _data()
        ck = TrainingCheckpointer(str(tmp_path), async_write=False)
        _fit_steps(net, 2, x, y)
        gen_old = ck.save(net)
        iter_legacy = int(net.iteration)
        # fabricate the pre-lineage flat layout from that save's artifacts
        lineage = tmp_path / "latest"
        shutil.copy(os.path.join(gen_old, "shard_0.npz"),
                    lineage / "shard_0.npz")
        shutil.copy(os.path.join(gen_old, "train_state.json"),
                    lineage / "train_state.json")
        shutil.rmtree(gen_old)
        # progress continues post-upgrade: two committed generations on top
        _fit_steps(net, 2, x, y)
        ck.save(net)
        _fit_steps(net, 2, x, y)
        gen_new = ck.save(net)
        iter_new = int(net.iteration)

        fresh = _net(seed=21)
        assert ck.restore(fresh)
        assert int(fresh.iteration) == iter_new  # generation won, not legacy
        rep = verify_checkpoint(str(tmp_path))
        assert rep["ok"] and rep["generation"] == os.path.basename(gen_new)
        st = lineage_state(str(tmp_path))
        assert st["legacy_flat"] and st["format"] == "lineage"

        # every generation corrupted → the flat checkpoint is the LAST
        # fallback instead of a raise (it is still a committed artifact)
        for name in list(os.listdir(lineage)):
            if name.startswith("gen-") and not name.endswith(".corrupt"):
                _flip_byte(lineage / name / "shard_0.npz")
        fb0 = _counter_value("tdl_ckpt_fallback_restores_total")
        fresh2 = _net(seed=22)
        assert ck.restore(fresh2)
        assert int(fresh2.iteration) == iter_legacy
        assert _counter_value("tdl_ckpt_fallback_restores_total") == fb0 + 1

    def test_verify_checkpoint_accepts_all_path_shapes(self, tmp_path):
        """Review fix pin: verify_checkpoint must judge the SAME generation
        whether handed the checkpointer root, the lineage dir, or the
        generation dir save() returned — a silent 'no_checkpoint' pass on
        any of those shapes would let swap_model skip verification."""
        net = _net()
        x, y = _data()
        _fit_steps(net, 2, x, y)
        ck = TrainingCheckpointer(str(tmp_path), async_write=False)
        gendir = ck.save(net)
        for path, fmt in ((str(tmp_path), "lineage"),
                          (str(tmp_path / "latest"), "lineage"),
                          (gendir, "generation")):
            rep = verify_checkpoint(path)
            assert rep["ok"] and rep["format"] == fmt, (path, rep)
            assert rep["generation"] == os.path.basename(gendir)
        _flip_byte(os.path.join(gendir, "shard_0.npz"))
        for path in (str(tmp_path), str(tmp_path / "latest"), gendir):
            rep = verify_checkpoint(path)
            assert not rep["ok"] and rep["reason"] == "shard_crc", (path, rep)

    def test_commit_scope_mismatch_is_a_verify_failure(self, tmp_path):
        """Review fix pin: a manifest with the right save id but a DIFFERENT
        gang shape (a torn same-iteration leftover from before a resize)
        must fail verification — committing or restoring it would mix two
        topologies in one generation."""
        net = _net()
        x, y = _data()
        _fit_steps(net, 2, x, y)
        ck = TrainingCheckpointer(str(tmp_path), async_write=False)
        gendir = ck.save(net)
        man_path = os.path.join(gendir, "manifest_0.json")
        with open(man_path) as f:
            man = json.load(f)
        man["process_count"] = 4  # the old, bigger gang's scope
        with open(man_path, "w") as f:
            json.dump(_self_checksummed(man), f)
        rep = verify_checkpoint(str(tmp_path))
        assert not rep["ok"] and rep["reason"] == "scope", rep

    def test_verify_checkpoint_api(self, tmp_path):
        rep = verify_checkpoint(str(tmp_path))
        assert not rep["ok"] and rep["reason"] == "no_checkpoint"
        net = _net()
        x, y = _data()
        _fit_steps(net, 2, x, y)
        ck = TrainingCheckpointer(str(tmp_path), async_write=False)
        gendir = ck.save(net)
        rep = verify_checkpoint(str(tmp_path))
        assert rep["ok"] and rep["format"] == "lineage"
        assert rep["generation"] == os.path.basename(gendir)
        assert rep["iteration"] == int(net.iteration)
        assert rep["bytes"] > 0
        # a corrupt NEWEST generation fails pre-flight even though restore
        # could fall back — swap_model must not silently ship an older model
        _fit_steps(net, 1, x, y)
        gen_b = ck.save(net)
        _flip_byte(os.path.join(gen_b, "shard_0.npz"))
        rep = verify_checkpoint(str(tmp_path))
        assert not rep["ok"] and rep["reason"] == "shard_crc"
        # pre-flight never quarantines: restore still sees both generations
        assert os.path.isdir(gen_b)


# ------------------------------------------ checkpoint chaos faults (ISSUE 15)


class TestCheckpointFaults:
    def test_torn_ckpt_spec_parsing_and_stage_validation(self):
        f = faults.parse_fault_spec("torn_ckpt@iter=4,stage=shard,rank=0")[0]
        assert f.kind == "torn_ckpt" and f.iteration == 4 and f.rank == 0
        assert f.params["stage"] == "shard"
        assert faults.parse_fault_spec("corrupt_ckpt@iter=3")[0].kind == \
            "corrupt_ckpt"
        assert faults.parse_fault_spec("enospc@iter=2,rank=1")[0].kind == \
            "enospc"
        with pytest.raises(ValueError, match="torn_ckpt stage"):
            faults.parse_fault_spec("torn_ckpt@iter=4,stage=nope")
        # default stage is the pre-COMMIT boundary
        f = faults.parse_fault_spec("torn_ckpt@iter=4")[0]
        inj = faults.FaultInjector([f], rank=0, incarnation=1)
        inj.fire("ckpt_commit", iteration=4)  # wrong incarnation: no exit

    def test_enospc_fault_fails_save_and_generation_stays_uncommitted(
            self, tmp_path, monkeypatch):
        net = _net()
        x, y = _data()
        _fit_steps(net, 2, x, y)
        ck = TrainingCheckpointer(str(tmp_path), async_write=False)
        monkeypatch.setenv(faults.ENV_SPEC,
                           f"enospc@iter={int(net.iteration)}")
        with pytest.raises(OSError, match="No space left"):
            ck.save(net)
        assert ck.committed_generation() is None
        # the failed attempt left no restorable state; next save (fault is
        # one-shot at that iteration in incarnation 0 only... here the env
        # clause stays, so clear it) commits into the SAME generation name
        monkeypatch.delenv(faults.ENV_SPEC)
        gendir = ck.save(net)
        assert os.path.exists(os.path.join(gendir, "COMMIT"))
        assert ck.restore(_net(seed=3))

    def test_corrupt_ckpt_fault_bitflips_committed_shard(self, tmp_path,
                                                         monkeypatch):
        net = _net()
        x, y = _data()
        _fit_steps(net, 2, x, y)
        ck = TrainingCheckpointer(str(tmp_path), async_write=False)
        gen_a = ck.save(net)
        iter_a = int(net.iteration)
        _fit_steps(net, 2, x, y)
        monkeypatch.setenv(faults.ENV_SPEC,
                           f"corrupt_ckpt@iter={int(net.iteration)}")
        gen_b = ck.save(net)  # commits, THEN the injector flips a bit
        monkeypatch.delenv(faults.ENV_SPEC)
        assert os.path.exists(os.path.join(gen_b, "COMMIT"))
        rep = verify_checkpoint(str(tmp_path))
        assert not rep["ok"] and rep["reason"] == "shard_crc"
        fresh = _net(seed=11)
        assert ck.restore(fresh)  # quarantine + fallback
        assert int(fresh.iteration) == iter_a
        assert os.path.isdir(gen_b + ".corrupt")


# ------------------------------------------------------------------ AST lint


_DURABILITY_LINT_FILES = ("serde/checkpoint.py", "common/durability.py")
_SYNC_CALLS = {"fsync", "fsync_dir", "durable_replace", "durable_write_json",
               "durable_write_bytes"}


def _durability_offenders(src: str, rel: str):
    """``os.replace`` rename-commits without an fsync call earlier in the
    SAME function (nested functions are their own scope) and without a
    ``# durability-ok:`` justification on the call line or the line above."""
    lines = src.splitlines()
    tree = ast.parse(src, filename=rel)
    fns = [n for n in ast.walk(tree)
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    offenders = []
    for fn in fns:
        calls = []

        def collect(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue  # nested scope: audited as its own function
                if isinstance(child, ast.Call):
                    calls.append(child)
                collect(child)

        collect(fn)
        sync_lines = []
        replaces = []
        for c in calls:
            name = None
            if isinstance(c.func, ast.Attribute):
                name = c.func.attr
            elif isinstance(c.func, ast.Name):
                name = c.func.id
            if name in _SYNC_CALLS:
                sync_lines.append(c.lineno)
            elif name == "replace" and isinstance(c.func, ast.Attribute) \
                    and isinstance(c.func.value, ast.Name) \
                    and c.func.value.id == "os":
                replaces.append(c.lineno)
        for lineno in replaces:
            context = lines[max(0, lineno - 2):lineno]
            if any("durability-ok" in ln for ln in context):
                continue
            if not any(s < lineno for s in sync_lines):
                offenders.append(f"{rel}:{lineno} ({fn.name})")
    return offenders


def test_checkpoint_writes_are_durable():
    """ISSUE 15 satellite (repo lint): every open-for-write + ``os.replace``
    commit in the checkpoint writers must fsync in between — a host power
    loss after an unfsynced rename leaves a zero-length "committed" file.
    Escape hatch: ``# durability-ok: <reason>`` on the call line or the
    line above it."""
    offenders = []
    for rel in _DURABILITY_LINT_FILES:
        offenders += _durability_offenders((ROOT / rel).read_text(), rel)
    assert not offenders, (
        "rename-commit without an fsync before it (power loss can leave a "
        "zero-length committed file; annotate genuinely-advisory writes "
        f"with `# durability-ok: <reason>`): {offenders}")


def test_durability_lint_catches_a_planted_offender():
    planted = (
        "import os\n"
        "def bad(path):\n"
        "    tmp = path + '.tmp'\n"
        "    with open(tmp, 'w') as f:\n"
        "        f.write('x')\n"
        "    os.replace(tmp, path)\n"
        "def good(path):\n"
        "    tmp = path + '.tmp'\n"
        "    with open(tmp, 'w') as f:\n"
        "        f.write('x')\n"
        "        os.fsync(f.fileno())\n"
        "    os.replace(tmp, path)\n"
        "def escaped(path):\n"
        "    os.replace(path + '.t', path)  # durability-ok: advisory spool\n"
        "def nested(path):\n"
        "    os.fsync(0)\n"
        "    def inner():\n"
        "        os.replace(path + '.t', path)\n"  # no fsync in ITS scope
        "    inner()\n"
    )
    hits = _durability_offenders(planted, "planted.py")
    assert hits == ["planted.py:6 (bad)", "planted.py:18 (inner)"], hits


class TestPreemption:
    def test_sigterm_saves_before_death(self, tmp_path):
        net = _net()
        x, y = _data(16)
        net._fit_batch(DataSet(x, y))
        ck = TrainingCheckpointer(str(tmp_path), async_write=True)
        h = PreemptionHandler(ck, net, signals=(signal.SIGTERM,), swallow=True).install()
        try:
            os.kill(os.getpid(), signal.SIGTERM)
        finally:
            h.uninstall()
        assert h.fired
        gendir = ck.committed_generation(tag="preempt")
        assert gendir and os.path.exists(os.path.join(gendir,
                                                      "train_state.json"))
        assert verify_checkpoint(str(tmp_path), tag="preempt")["ok"]
        net2 = _net(seed=42)
        assert ck.restore(net2, tag="preempt")
        np.testing.assert_array_equal(
            np.asarray(net2.params_["0"]["W"]), np.asarray(net.params_["0"]["W"]))


def test_model_guesser(tmp_path):
    """ModelGuesser: format sniffing across the three container types."""
    import pytest as _pytest

    from deeplearning4j_tpu.models import LeNet
    from deeplearning4j_tpu.serde import ModelGuesser, ModelSerializer

    net = LeNet(num_classes=3, input_shape=(1, 8, 8)).init()
    p = str(tmp_path / "m.zip")
    ModelSerializer.write_model(net, p)
    loaded = ModelGuesser.load_model_guess(p)
    x = np.random.RandomState(0).rand(2, 1, 8, 8).astype(np.float32)
    np.testing.assert_allclose(loaded.output(x).numpy(), net.output(x).numpy(),
                               rtol=1e-5, atol=1e-6)

    keras = _pytest.importorskip("keras")
    m = keras.Sequential([keras.Input((6,)), keras.layers.Dense(4)])
    kp = str(tmp_path / "k.h5")
    m.save(kp)
    knet = ModelGuesser.load_model_guess(kp)
    xk = np.random.RandomState(1).randn(3, 6).astype(np.float32)
    np.testing.assert_allclose(knet.output(xk).numpy(), m.predict(xk, verbose=0),
                               rtol=1e-4, atol=1e-5)

    bad = str(tmp_path / "junk.bin")
    open(bad, "wb").write(b"\x00\x01\x02garbage")
    with _pytest.raises(ValueError, match="cannot guess"):
        ModelGuesser.load_model_guess(bad)
