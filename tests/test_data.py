"""Data pipeline tests (SURVEY §2.3 datavec, §2.4 C12 datasets/iterators)."""

import numpy as np
import pytest

from deeplearning4j_tpu.data import (
    CollectionRecordReader,
    CSVRecordReader,
    DataSet,
    FileSplit,
    ImagePreProcessingScaler,
    IrisDataSetIterator,
    LineRecordReader,
    MnistDataSetIterator,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
    RecordReaderDataSetIterator,
    Schema,
    TransformProcess,
)


def test_csv_record_reader(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("h1,h2,h3\n1,2,0\n4,5,1\n7,8,2\n")
    rr = CSVRecordReader(skip_num_lines=1).initialize(FileSplit(str(p)))
    rows = list(rr)
    assert rows == [["1", "2", "0"], ["4", "5", "1"], ["7", "8", "2"]]
    rr.reset()
    assert rr.has_next()


def test_record_reader_dataset_iterator(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("".join(f"{i},{i*2},{i%3}\n" for i in range(10)))
    rr = CSVRecordReader().initialize(FileSplit(str(p)))
    it = RecordReaderDataSetIterator(rr, batch_size=4, label_index=-1, num_classes=3)
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].features.shape == (4, 2)
    assert batches[0].labels.shape == (4, 3)
    assert batches[-1].features.shape == (2, 2)  # remainder
    np.testing.assert_allclose(batches[0].labels[1], [0, 1, 0])  # i=1 -> class 1


def test_transform_process_roundtrip():
    schema = (Schema.Builder()
              .add_column_string("name")
              .add_column_categorical("color", "red", "green", "blue")
              .add_column_double("size")
              .build())
    tp = (TransformProcess.Builder(schema)
          .string_map_transform("name", "lower")
          .categorical_to_one_hot("color")
          .double_math_op("size", "Multiply", 2.0)
          .remove_columns("name")
          .build())
    rows = [["Alice", "red", 1.5], ["BOB", "blue", 3.0]]
    out = tp.execute(rows)
    assert out == [[1, 0, 0, 3.0], [0, 0, 1, 6.0]]
    assert tp.final_schema().names() == ["color[red]", "color[green]", "color[blue]", "size"]
    # JSON round-trip executes identically (serialization invariant)
    tp2 = TransformProcess.from_json(tp.to_json())
    assert tp2.execute(rows) == out


def test_normalizer_standardize_roundtrip():
    rs = np.random.RandomState(0)
    x = rs.randn(100, 5).astype(np.float32) * 3 + 7
    ds = DataSet(x.copy(), None)
    n = NormalizerStandardize().fit(ds)
    n.transform(ds)
    assert abs(float(ds.features.mean())) < 1e-4
    assert abs(float(ds.features.std()) - 1.0) < 1e-2
    back = n.revert_features(ds.features)
    np.testing.assert_allclose(back, x, atol=1e-4)


def test_normalizer_serialization(tmp_path):
    x = np.arange(20, dtype=np.float32).reshape(10, 2)
    n = NormalizerMinMaxScaler().fit(DataSet(x, None))
    p = str(tmp_path / "norm.json")
    n.save(p)
    n2 = NormalizerMinMaxScaler.restore(p)
    ds = DataSet(x.copy(), None)
    n2.transform(ds)
    assert float(ds.features.min()) == 0.0 and float(ds.features.max()) == 1.0


def test_image_scaler():
    ds = DataSet(np.full((2, 1, 4, 4), 255.0, np.float32), None)
    ImagePreProcessingScaler().transform(ds)
    np.testing.assert_allclose(ds.features, 1.0)


def test_iris_iterator():
    it = IrisDataSetIterator(batch_size=50)
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].features.shape == (50, 4)
    assert batches[0].labels.shape == (50, 3)
    # shuffled split contains all three classes in first batch
    assert batches[0].labels.sum(axis=0).min() > 0


def test_mnist_iterator_and_lenet_slice():
    """BASELINE config #1 minimum end-to-end slice (SURVEY §7.1 M3): LeNet +
    MNIST iterator + Adam + Evaluation. Synthetic fallback in zero-egress
    envs; accuracy must beat chance decisively after one epoch."""
    from deeplearning4j_tpu.models import LeNet

    train = MnistDataSetIterator(batch_size=64, train=True, num_examples=1024)
    test = MnistDataSetIterator(batch_size=256, train=False, num_examples=512)
    net = LeNet().init()
    net.fit(train, epochs=2)
    ev = net.evaluate(test)
    assert ev.accuracy() > 0.8, ev.accuracy()


def test_cifar_emnist_tinyimagenet_iterators():
    """C12 breadth: synthetic-fallback dataset iterators batch one-hot NCHW."""
    from deeplearning4j_tpu.data import (
        Cifar10DataSetIterator,
        EmnistDataSetIterator,
        TinyImageNetDataSetIterator,
    )

    for it, shape, classes in [
        (Cifar10DataSetIterator(32, num_examples=64), (32, 3, 32, 32), 10),
        (EmnistDataSetIterator(16, num_examples=32), (16, 1, 28, 28), 26),
        (TinyImageNetDataSetIterator(8, num_examples=16), (8, 3, 64, 64), 200),
    ]:
        ds = it.next()
        assert ds.features.shape == shape
        assert ds.labels.shape == (shape[0], classes)
        assert it.has_next()
        it.next()
        assert not it.has_next()
        it.reset()
        assert it.has_next()
        # train/test disjoint determinism
        assert it.synthetic


def test_wav_record_reader_and_spectrogram(tmp_path):
    """D6 audio: WAV decode (stdlib wave) → waveform/spectrogram rows with
    dir labels."""
    import wave as wavmod

    from deeplearning4j_tpu.data.audio import WavFileRecordReader, read_wav, spectrogram
    from deeplearning4j_tpu.data.image import ParentPathLabelGenerator
    from deeplearning4j_tpu.data.records import FileSplit

    rs = np.random.RandomState(0)
    for ci, cls in enumerate(["sine", "noise"]):
        d = tmp_path / cls
        d.mkdir()
        for i in range(2):
            t = np.arange(2000) / 8000.0
            x = (np.sin(2 * np.pi * 440 * t) if cls == "sine"
                 else rs.randn(2000) * 0.3)
            pcm = (np.clip(x, -1, 1) * 32767).astype(np.int16)
            with wavmod.open(str(d / f"a{i}.wav"), "wb") as w:
                w.setnchannels(1); w.setsampwidth(2); w.setframerate(8000)
                w.writeframes(pcm.tobytes())

    x, rate = read_wav(str(tmp_path / "sine" / "a0.wav"))
    assert rate == 8000 and abs(float(np.max(x)) - 1.0) < 0.01

    rr = WavFileRecordReader(features="spectrogram", n_fft=128, hop=64,
                             max_samples=2000,
                             label_generator=ParentPathLabelGenerator())
    rr.initialize(FileSplit(str(tmp_path)))
    assert rr.labels() == ["noise", "sine"]
    rows = []
    while rr.has_next():
        rows.append(rr.next())
    assert len(rows) == 4
    feat, label = rows[0]
    assert feat.shape[1] == 128 // 2 + 1
    # a pure sine concentrates energy in one bin; noise doesn't
    sine_rows = [r for r in rows if r[1] == rr.labels().index("sine")]
    spec = sine_rows[0][0].mean(0)
    assert spec.argmax() == round(440 * 128 / 8000)


def test_tfidf_vectorizer():
    """D6 NLP: TfidfVectorizer fit/transform parity behaviors."""
    from deeplearning4j_tpu.nlp.tfidf import TfidfVectorizer

    corpus = ["the cat sat", "the dog sat", "the cat ran fast"]
    v = TfidfVectorizer(normalize=True)
    m = v.fit_transform(corpus)
    assert m.shape == (3, len(v.vocab_))
    np.testing.assert_allclose(np.linalg.norm(m, axis=1), 1.0, rtol=1e-5)
    # 'the' appears everywhere → lowest idf; 'fast' in one doc → highest
    assert v.idf_[v.vocab_["the"]] < v.idf_[v.vocab_["fast"]]
    # unseen words ignored at transform
    m2 = v.transform(["zebra cat"])
    assert m2[0, v.vocab_["cat"]] > 0


def test_transform_wave2_time_condition_join_analysis():
    """D2 breadth: time parse/derive, conditional replace/filter, join,
    DataAnalysis — all JSON round-trippable where step-based."""
    from deeplearning4j_tpu.data.transform import (
        DataAnalysis,
        Schema,
        TransformProcess,
        join,
    )

    schema = (Schema.Builder()
              .add_column_string("ts")
              .add_column_double("amount")
              .add_column_string("user")
              .build())
    tp = (TransformProcess.Builder(schema)
          .string_to_time("ts")
          .derive_time_fields("ts", "hourOfDay", "dayOfWeek")
          .conditional_replace("amount", "lt", 0.0, 0.0)
          .filter_by_condition("amount", "gt", 100.0)
          .build())
    rows = [
        ["2024-03-04 13:30:00", -5.0, "a"],   # negative → clamped to 0
        ["2024-03-05 07:00:00", 50.0, "b"],
        ["2024-03-06 09:00:00", 500.0, "c"],  # filtered out (>100)
    ]
    out_schema = tp.final_schema()
    assert [c["name"] for c in out_schema.columns][-2:] == ["ts_hourOfDay", "ts_dayOfWeek"]
    out = tp.execute(rows)
    assert len(out) == 2
    assert out[0][1] == 0.0
    assert out[0][-2] == 13 and out[0][-1] == 0  # 2024-03-04 = Monday
    # JSON round trip executes identically
    tp2 = TransformProcess.from_json(tp.to_json())
    assert tp2.execute(rows) == out

    # join
    right = (Schema.Builder().add_column_string("user")
             .add_column_integer("age").build())
    js, jrows = join(out_schema, out, right, [["a", 30], ["x", 99]], "user",
                     join_type="LeftOuter")
    assert [c["name"] for c in js.columns][-1] == "age"
    assert jrows[0][-1] == 30 and jrows[1][-1] is None
    _, inner = join(out_schema, out, right, [["a", 30]], "user")
    assert len(inner) == 1

    # analysis
    an = DataAnalysis.analyze(out_schema, out)
    assert an.column_stats["amount"]["max"] == 50.0
    assert an.column_stats["user"]["countUnique"] == 2
    assert "mean" in an.column_stats["ts_hourOfDay"]


class TestD6ReaderTail:
    """D6 breadth (SVMLight / regex / JSON-lines readers)."""

    def test_svmlight(self, tmp_path):
        from deeplearning4j_tpu.data import SVMLightRecordReader
        from deeplearning4j_tpu.data.records import FileSplit

        p = tmp_path / "d.svm"
        p.write_text("1 1:0.5 3:2.0 # note\n0 2:-1.5\n")
        rr = SVMLightRecordReader(num_features=4).initialize(FileSplit(str(tmp_path)))
        assert rr.next() == [0.5, 0.0, 2.0, 0.0, 1.0]
        assert rr.next() == [0.0, -1.5, 0.0, 0.0, 0.0]
        assert not rr.has_next()

    def test_regex_reader(self, tmp_path):
        from deeplearning4j_tpu.data import RegexLineRecordReader
        from deeplearning4j_tpu.data.records import FileSplit

        p = tmp_path / "log.txt"
        p.write_text("header\n2026-01-01 WARN disk full\n2026-01-02 INFO ok\n")
        rr = RegexLineRecordReader(r"(\d{4}-\d{2}-\d{2}) (\w+) (.*)",
                                   skip_num_lines=1).initialize(FileSplit(str(tmp_path)))
        assert rr.next() == ["2026-01-01", "WARN", "disk full"]
        assert rr.next() == ["2026-01-02", "INFO", "ok"]
        import pytest as _pytest

        rr2 = RegexLineRecordReader(r"(\d+)").initialize(FileSplit(str(tmp_path)))
        with _pytest.raises(ValueError, match="does not match"):
            rr2.next()

    def test_jackson_lines(self, tmp_path):
        from deeplearning4j_tpu.data import JacksonLineRecordReader
        from deeplearning4j_tpu.data.records import FileSplit

        p = tmp_path / "rows.jsonl"
        p.write_text('{"a": 1, "b": "x"}\n{"b": "y", "c": 3}\n')
        rr = JacksonLineRecordReader(["a", "b"]).initialize(FileSplit(str(tmp_path)))
        assert rr.next() == [1, "x"]
        assert rr.next() == [None, "y"]


def test_svmlight_qid_and_bad_index(tmp_path):
    import pytest as _pytest

    from deeplearning4j_tpu.data import SVMLightRecordReader
    from deeplearning4j_tpu.data.records import FileSplit

    (tmp_path / "r.svm").write_text("2 qid:7 1:0.5\n")
    rr = SVMLightRecordReader(num_features=2).initialize(FileSplit(str(tmp_path)))
    assert rr.next() == [0.5, 0.0, 2.0]

    (tmp_path / "bad").mkdir()
    (tmp_path / "bad" / "b.svm").write_text("1 9:1.0\n")
    rr2 = SVMLightRecordReader(num_features=2).initialize(
        FileSplit(str(tmp_path / "bad")))
    with _pytest.raises(ValueError, match="outside"):
        rr2.next()


class TestExcelRecordReader:
    """datavec-excel ExcelRecordReader parity (r5: VERDICT r4 missing #7) —
    the golden .xlsx is written as the zip-of-XML the format actually is."""

    @staticmethod
    def _write_xlsx(path, rows, shared):
        import zipfile

        ns = 'xmlns="http://schemas.openxmlformats.org/spreadsheetml/2006/main"'
        si = "".join(f"<si><t>{s}</t></si>" for s in shared)
        cells_xml = []
        for ri, row in enumerate(rows, start=1):
            cs = []
            for ci, cell in enumerate(row):
                ref = chr(ord("A") + ci) + str(ri)
                if cell is None:
                    continue  # gap → blank on read
                if isinstance(cell, str):
                    cs.append(f'<c r="{ref}" t="s"><v>{shared.index(cell)}</v></c>')
                else:
                    cs.append(f'<c r="{ref}"><v>{cell}</v></c>')
            cells_xml.append(f'<row r="{ri}">{"".join(cs)}</row>')
        sheet = (f'<?xml version="1.0"?><worksheet {ns}><sheetData>'
                 f'{"".join(cells_xml)}</sheetData></worksheet>')
        sstr = f'<?xml version="1.0"?><sst {ns}>{si}</sst>'
        wb = f'<?xml version="1.0"?><workbook {ns}><sheets/></workbook>'
        with zipfile.ZipFile(path, "w") as z:
            z.writestr("xl/workbook.xml", wb)
            z.writestr("xl/sharedStrings.xml", sstr)
            z.writestr("xl/worksheets/sheet1.xml", sheet)

    def test_reads_numbers_strings_and_gaps(self, tmp_path):
        from deeplearning4j_tpu.data import ExcelRecordReader
        from deeplearning4j_tpu.data.records import FileSplit

        p = str(tmp_path / "book.xlsx")
        self._write_xlsx(p, [["name", "x", "y"],
                             ["a", 1.5, 2.0],
                             ["b", None, 3.0]], shared=["name", "x", "y", "a", "b"])
        rr = ExcelRecordReader(skip_num_rows=1).initialize(FileSplit(p))
        recs = list(rr)
        assert recs == [["a", 1.5, 2.0], ["b", "", 3.0]]
        rr.reset()
        assert rr.has_next() and rr.next()[0] == "a"

    def test_sheet_out_of_range(self, tmp_path):
        from deeplearning4j_tpu.data import ExcelRecordReader
        from deeplearning4j_tpu.data.records import FileSplit

        p = str(tmp_path / "b2.xlsx")
        self._write_xlsx(p, [[1.0]], shared=[])
        import pytest as _pytest

        with _pytest.raises(ValueError, match="out of range"):
            ExcelRecordReader(sheet_index=3).initialize(FileSplit(p))
