"""Extended layers, dropout schemes, constraints, weight noise
(SURVEY §2.4 C1 breadth — VERDICT r1 item #8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.constraints import (
    DropConnect,
    MaxNormConstraint,
    NonNegativeConstraint,
    UnitNormConstraint,
    WeightNoise,
)
from deeplearning4j_tpu.nn.dropout import (
    AlphaDropout,
    GaussianDropout,
    GaussianNoise,
    SpatialDropout,
)
from deeplearning4j_tpu.nn.layers_ext import (
    CenterLossOutputLayer,
    Convolution3D,
    Cropping2D,
    LocallyConnected2D,
    PReLULayer,
    Subsampling3DLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam


def test_conv3d_stack_trains():
    rs = np.random.RandomState(0)
    x = rs.rand(4, 1, 6, 6, 6).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 4)]
    conf = (
        NeuralNetConfiguration.Builder().seed(1).updater(Adam(3e-3)).list()
        .layer(Convolution3D(n_out=4, kernel_size=(2, 2, 2), activation="relu"))
        .layer(Subsampling3DLayer(kernel_size=(2, 2, 2), stride=(2, 2, 2)))
        .layer(DenseLayer(n_out=8, activation="relu"))
        .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.convolutional3d(6, 6, 6, 1))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    net.fit(DataSet(x, y))
    l0 = net.score_
    for _ in range(20):
        net.fit(DataSet(x, y))
    assert net.score_ < l0


def test_locally_connected_vs_shared_conv_shapes():
    rs = np.random.RandomState(1)
    layer = LocallyConnected2D(n_in=2, n_out=3, kernel_size=(2, 2), stride=(1, 1))
    it = InputType.convolutional(5, 5, 2)
    params = layer.init_params(jax.random.key(0), it)
    assert params["W"].shape == (16, 8, 3)  # 4x4 positions, 2*2*2 patch, 3 out
    x = jnp.asarray(rs.rand(3, 2, 5, 5), jnp.float32)
    out = layer.forward(params, x, it, training=False)
    assert out.shape == (3, 3, 4, 4)
    # unshared: permuting position weights changes outputs at those positions only
    w2 = params["W"].at[0].multiply(2.0)
    out2 = layer.forward({**params, "W": w2}, x, it, training=False)
    diff = np.abs(np.asarray(out2 - out)).reshape(3, 3, 16).sum(axis=(0, 1))
    assert diff[0] > 0 and np.allclose(diff[1:], 0)


def test_prelu_layer():
    layer = PReLULayer()
    it = InputType.feed_forward(4)
    params = layer.init_params(jax.random.key(0), it)
    assert params["alpha"].shape == (4,)
    x = jnp.asarray([[-2.0, -1.0, 1.0, 2.0]])
    # alpha starts at 0 → ReLU behavior
    np.testing.assert_allclose(layer.forward(params, x, it, training=False),
                               [[0, 0, 1, 2]])
    p2 = {"alpha": jnp.full((4,), 0.5)}
    np.testing.assert_allclose(layer.forward(p2, x, it, training=False),
                               [[-1, -0.5, 1, 2]])


def test_cropping2d():
    layer = Cropping2D(cropping=(1, 1, 2, 0))
    x = jnp.arange(2 * 1 * 6 * 6, dtype=jnp.float32).reshape(2, 1, 6, 6)
    out = layer.forward({}, x, None, training=False)
    assert out.shape == (2, 1, 4, 4)
    np.testing.assert_allclose(out, x[:, :, 1:5, 2:6])


def test_center_loss_output_layer_trains():
    rs = np.random.RandomState(2)
    x = rs.rand(32, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 32)]
    conf = (
        NeuralNetConfiguration.Builder().seed(3).updater(Adam(1e-2)).list()
        .layer(DenseLayer(n_in=6, n_out=8, activation="relu"))
        .layer(CenterLossOutputLayer(n_out=3, lambda_=1e-2))
        .set_input_type(InputType.feed_forward(6))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    net.fit(DataSet(x, y))
    l0 = net.score_
    for _ in range(30):
        net.fit(DataSet(x, y))
    assert net.score_ < l0
    # centers moved off their zero init toward the class features
    assert np.abs(np.asarray(net.params_["1"]["centers"])).sum() > 0


@pytest.mark.parametrize("scheme", [
    GaussianDropout(0.3), GaussianNoise(0.2), AlphaDropout(0.8), SpatialDropout(0.5)])
def test_dropout_schemes(scheme):
    rng = jax.random.key(0)
    x = jnp.ones((8, 4, 10))
    out_train = scheme.apply(x, rng, True)
    out_eval = scheme.apply(x, rng, False)
    np.testing.assert_array_equal(out_eval, x)  # inference: identity
    assert not np.allclose(out_train, x)        # training: perturbs
    if isinstance(scheme, SpatialDropout):
        # whole channels dropped: each [b, c] row is all-zero or all-scaled
        arr = np.asarray(out_train)
        per_chan = arr.reshape(8, 4, 10)
        for b in range(8):
            for c in range(4):
                vals = np.unique(per_chan[b, c])
                assert len(vals) == 1


def test_dropout_scheme_in_layer_and_serde():
    conf = (
        NeuralNetConfiguration.Builder().seed(1).list()
        .layer(DenseLayer(n_in=4, n_out=8, activation="relu",
                          dropout=GaussianDropout(0.2)))
        .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(4))
        .build()
    )
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration

    conf2 = MultiLayerConfiguration.from_json(conf.to_json())
    assert isinstance(conf2.layers[0].dropout, GaussianDropout)
    assert conf2.layers[0].dropout.rate == 0.2
    rs = np.random.RandomState(0)
    net = MultiLayerNetwork(conf2).init()
    net.fit(rs.rand(16, 4).astype(np.float32),
            np.eye(2, dtype=np.float32)[rs.randint(0, 2, 16)], epochs=2)
    assert np.isfinite(net.score_)


def test_constraints_applied_after_update():
    rs = np.random.RandomState(3)
    conf = (
        NeuralNetConfiguration.Builder().seed(1).updater(Adam(5e-2)).list()
        .layer(DenseLayer(n_in=4, n_out=8, activation="relu",
                          constraints=(MaxNormConstraint(0.5, axes=(0,)),)))
        .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent",
                           constraints=(NonNegativeConstraint(),)))
        .set_input_type(InputType.feed_forward(4))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = rs.rand(32, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 32)]
    for _ in range(5):
        net.fit(DataSet(x, y))
    w0 = np.asarray(net.params_["0"]["W"])
    norms = np.sqrt((w0 ** 2).sum(axis=0))
    assert (norms <= 0.5 + 1e-5).all()
    assert (np.asarray(net.params_["1"]["W"]) >= 0).all()


def test_unit_norm_constraint():
    w = jnp.asarray(np.random.RandomState(0).rand(5, 3) * 4)
    out = UnitNormConstraint(axes=(0,)).apply(w)
    np.testing.assert_allclose(np.sqrt((np.asarray(out) ** 2).sum(0)), 1.0, atol=1e-5)


def test_weight_noise_and_dropconnect():
    rs = np.random.RandomState(4)
    conf = (
        NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-2)).list()
        .layer(DenseLayer(n_in=4, n_out=8, activation="relu",
                          weight_noise=WeightNoise(stddev=0.05)))
        .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent",
                           weight_noise=DropConnect(0.8)))
        .set_input_type(InputType.feed_forward(4))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = rs.rand(16, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 16)]
    net.fit(DataSet(x, y))
    l0 = net.score_
    for _ in range(20):
        net.fit(DataSet(x, y))
    assert np.isfinite(net.score_)
    # inference is deterministic (no noise outside training)
    o1, o2 = net.output(x).numpy(), net.output(x).numpy()
    np.testing.assert_array_equal(o1, o2)


def test_cg_constraints_and_weight_noise():
    """ADVICE r2: ComputationGraph must honor constraints + weight noise."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    rs = np.random.RandomState(5)
    conf = (
        NeuralNetConfiguration.Builder().seed(1).updater(Adam(5e-2))
        .graph_builder()
        .add_inputs("in")
        .set_input_types(InputType.feed_forward(4))
        .add_layer("d1", DenseLayer(n_out=8, activation="relu",
                                    constraints=(MaxNormConstraint(0.5, axes=(0,)),),
                                    weight_noise=WeightNoise(stddev=0.05)), "in")
        .add_layer("out", OutputLayer(n_out=2, activation="softmax", loss="mcxent",
                                      constraints=(NonNegativeConstraint(),)), "d1")
        .set_outputs("out")
        .build()
    )
    g = ComputationGraph(conf).init()
    x = rs.rand(32, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 32)]
    for _ in range(5):
        g.fit(DataSet(x, y))
    w = np.asarray(g.params_["d1"]["W"])
    assert (np.sqrt((w ** 2).sum(axis=0)) <= 0.5 + 1e-5).all()
    assert (np.asarray(g.params_["out"]["W"]) >= 0).all()
    # weight noise is train-only: inference deterministic
    o1 = g.output_single(x).numpy()
    o2 = g.output_single(x).numpy()
    np.testing.assert_array_equal(o1, o2)


class TestConv1DFamily:
    """C4 Conv1D family: NCW conv + pooling over sequences."""

    def test_shapes_and_learning(self):
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf import GlobalPoolingLayer, InputType, OutputLayer
        from deeplearning4j_tpu.nn.layers_ext import Convolution1DLayer, Subsampling1DLayer
        from deeplearning4j_tpu.nn.updaters import Adam

        conf = (NeuralNetConfiguration.Builder().seed(3).updater(Adam(5e-3)).list()
                .layer(Convolution1DLayer(n_in=4, n_out=8, kernel_size=3,
                                          activation="relu"))
                .layer(Subsampling1DLayer(kernel_size=2, stride=2))
                .layer(Convolution1DLayer(n_out=8, kernel_size=3, activation="relu"))
                .layer(GlobalPoolingLayer(pooling_type="max"))
                .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.recurrent(4, 16))
                .build())
        net = MultiLayerNetwork(conf).init()
        # class 0: a bump early in the sequence; class 1: late
        rs = np.random.RandomState(0)
        x = rs.randn(64, 4, 16).astype(np.float32) * 0.1
        y = rs.randint(0, 2, 64)
        for i, c in enumerate(y):
            x[i, :, 2 if c == 0 else 12] += 2.0
        labels = np.eye(2, dtype=np.float32)[y]
        out = net.output(x[:4]).numpy()
        assert out.shape == (4, 2)
        for _ in range(60):
            net._fit_batch(DataSet(x, labels))
        preds = net.output(x).numpy().argmax(-1)
        assert (preds == y).mean() > 0.9

    def test_conf_json_roundtrip(self):
        from deeplearning4j_tpu.nn import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf import InputType, MultiLayerConfiguration, OutputLayer
        from deeplearning4j_tpu.nn.layers_ext import Convolution1DLayer, Subsampling1DLayer
        from deeplearning4j_tpu.nn.updaters import Adam

        conf = (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-3)).list()
                .layer(Convolution1DLayer(n_in=3, n_out=5, kernel_size=3))
                .layer(Subsampling1DLayer())
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.recurrent(3, 8))
                .build())
        back = MultiLayerConfiguration.from_json(conf.to_json())
        assert type(back.layers[0]).__name__ == "Convolution1DLayer"
        assert back.layers[0].kernel_size == 3
