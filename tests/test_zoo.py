"""Zoo tail models (VERDICT r3 missing #8): VGG19 + InceptionResNetV1."""

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet



def test_vgg19_builds_and_steps():
    from deeplearning4j_tpu.models import VGG19

    net = VGG19(num_classes=5, input_shape=(3, 32, 32)).init()
    # 16 conv + 2 dense + output = 19 weight layers (the name)
    n_weighted = sum(1 for k, v in net.params_.items() if "W" in v)
    assert n_weighted == 19
    x = np.random.RandomState(0).rand(2, 3, 32, 32).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[[0, 3]]
    net.fit(DataSet(x, y))
    assert np.isfinite(float(net.score()))


def test_inception_resnet_v1_builds_and_steps():
    from deeplearning4j_tpu.models import InceptionResNetV1

    m = InceptionResNetV1(num_classes=7, input_shape=(3, 96, 96),
                          blocks=(1, 1, 1), embedding_size=32)
    net = m.init()
    x = np.random.RandomState(0).rand(2, 3, 96, 96).astype(np.float32)
    y = np.eye(7, dtype=np.float32)[[1, 4]]
    net.fit({"input": x}, {"output": y})
    assert np.isfinite(float(net.score_))
    out = net.output_single(x).numpy()
    assert out.shape == (2, 7)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-4)


def test_init_pretrained_checksum(tmp_path):
    import hashlib

    from deeplearning4j_tpu.models import LeNet
    from deeplearning4j_tpu.serde.model_serializer import ModelSerializer

    net = LeNet(num_classes=4, input_shape=(1, 8, 8)).init()
    p = str(tmp_path / "lenet.zip")
    ModelSerializer.write_model(net, p)
    digest = hashlib.sha256(open(p, "rb").read()).hexdigest()

    restored = LeNet(num_classes=4, input_shape=(1, 8, 8)).init_pretrained(
        p, checksum=digest)
    x = np.random.RandomState(0).rand(2, 1, 8, 8).astype(np.float32)
    np.testing.assert_allclose(restored.output(x).numpy(), net.output(x).numpy(),
                               rtol=1e-5, atol=1e-6)
    import pytest as _pytest
    with _pytest.raises(ValueError, match="checksum mismatch"):
        LeNet(num_classes=4, input_shape=(1, 8, 8)).init_pretrained(
            p, checksum="0" * 64)
    with _pytest.raises(ValueError, match="zero egress|downloaded"):
        LeNet().init_pretrained()


def test_ocnn_output_layer_learns_inlier_region():
    """OCNN (C4 tail): train on one cluster; inliers must score higher than
    far-away outliers."""
    from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import DenseLayer, InputType
    from deeplearning4j_tpu.nn.layers_ext import OCNNOutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    rs = np.random.RandomState(0)
    X = (rs.randn(256, 4) * 0.3 + 2.0).astype(np.float32)   # tight cluster at 2
    conf = (NeuralNetConfiguration.Builder().seed(3).updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OCNNOutputLayer(hidden_size=8, nu=0.1))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    dummy_y = np.zeros((256, 1), np.float32)
    for _ in range(120):
        net.fit(DataSet(X, dummy_y))
    inl = net.output(X[:32]).numpy().mean()
    outl = net.output((rs.randn(32, 4) * 0.3 - 6.0).astype(np.float32)).numpy().mean()
    assert inl > outl + 0.05, (inl, outl)


def test_nasnet_builds_and_steps():
    from deeplearning4j_tpu.models import NASNet

    m = NASNet(num_classes=6, input_shape=(3, 64, 64),
               penultimate_filters=96, num_cells=1, stem_filters=8)
    net = m.init()
    x = np.random.RandomState(0).rand(2, 3, 64, 64).astype(np.float32)
    y = np.eye(6, dtype=np.float32)[[0, 5]]
    net.fit({"input": x}, {"output": y})
    assert np.isfinite(float(net.score_))
    out = net.output_single(x).numpy()
    assert out.shape == (2, 6)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-4)
