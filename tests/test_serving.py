"""Production-hardened serving tests (ISSUE 5, SURVEY §2.6 S5/S7).

Covers the micro-batching executor (bounded admission, deadlines, coalescing
parity, graceful drain), the hardened JsonModelServer (429/504/413/503 +
Retry-After, /health vs /ready, restart robustness), the hardened
JsonModelClient (retry/backoff, circuit breaker, URLError normalization),
ParallelInference input validation, and the 32-client chaos stress test
driven by the ``slow_infer`` fault injector.
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.monitoring import MetricsRegistry
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.parallel import ParallelInference
from deeplearning4j_tpu.serving import (BatchingInferenceExecutor,
                                        DeadlineExceededError,
                                        JsonModelClient, JsonModelServer,
                                        QueueFullError)


def _net():
    conf = (NeuralNetConfiguration.Builder().seed(7).updater(Adam(0.01)).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


class SlowModel:
    """Deterministic stand-in: 2x the input after a fixed delay, counting
    calls and flagging when a forward has started."""

    def __init__(self, delay=0.0):
        self.delay = delay
        self.calls = 0
        self.started = threading.Event()

    def output(self, x):
        self.calls += 1
        self.started.set()
        if self.delay:
            time.sleep(self.delay)
        return np.asarray(x, np.float32) * 2.0


class FlakyModel(SlowModel):
    def __init__(self, fail_first=2):
        super().__init__()
        self.fail_first = fail_first

    def output(self, x):
        self.calls += 1
        self.started.set()
        if self.calls <= self.fail_first:
            raise RuntimeError("transient replica failure")
        return np.asarray(x, np.float32) * 2.0


def _counter_values(reg, name):
    m = reg.get(name)
    if m is None:
        return {}
    snap = m.snapshot()
    return {tuple(s["labels"].values()): s["value"] for s in snap["series"]}


def _post(port, body, headers=None, timeout=15):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict", data=body,
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(port, path, timeout=15):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as resp:
        return resp.status, json.loads(resp.read()), dict(resp.headers)


# ------------------------------------------------------- ParallelInference


def test_output_batched_empty_returns_empty():
    net = _net()
    pi = ParallelInference(net, batch_limit=8)
    assert pi.output_batched([]) == []


def test_output_batched_validates_mixed_requests():
    net = _net()
    pi = ParallelInference(net, batch_limit=8)
    ok = np.zeros((2, 4), np.float32)
    with pytest.raises(ValueError, match="request 1.*feature shape"):
        pi.output_batched([ok, np.zeros((2, 5), np.float32)])
    with pytest.raises(ValueError, match="request 2.*dtype"):
        pi.output_batched([ok, ok, np.zeros((2, 4), np.float64)])
    with pytest.raises(ValueError, match="request 0.*batch dimension"):
        pi.output_batched([np.float32(1.0)])


# --------------------------------------------------------------- executor


def test_executor_micro_batching_parity_and_coalescing(monkeypatch):
    """ISSUE 5 satellite: coalesced-batch outputs == per-request outputs to
    1e-6, and concurrent requests actually coalesce (fewer executor cycles
    than requests while a slow_infer fault holds the first cycle open)."""
    monkeypatch.setenv("TDL_FAULT_SPEC", "slow_infer@p=0.15")
    reg = MetricsRegistry()
    net = _net()
    pi = ParallelInference(net, batch_limit=8)
    ex = BatchingInferenceExecutor(parallel_inference=pi, max_queue=64,
                                   registry=reg).start()
    try:
        rs = np.random.RandomState(3)
        xs = [rs.randn(1 + i % 3, 4).astype(np.float32) for i in range(10)]
        expected = [net.output(x).numpy() for x in xs]
        futs = [ex.submit(x) for x in xs]
        for f in futs:
            assert f.wait(30.0)
            assert f.error is None
        for f, exp in zip(futs, expected):
            np.testing.assert_allclose(f.result, exp, atol=1e-6)
        cycles = reg.get("tdl_inference_batch_size").snapshot()["series"][0]
        assert 0 < cycles["count"] < 10  # coalesced, not one cycle per request
    finally:
        ex.stop(drain=True)


def test_executor_sheds_expired_requests_without_running_model():
    reg = MetricsRegistry()
    model = SlowModel(delay=0.3)
    ex = BatchingInferenceExecutor(model=model, max_queue=16,
                                   registry=reg).start()
    try:
        x = np.ones((1, 4), np.float32)
        f1 = ex.submit(x, deadline_ms=5000)
        assert model.started.wait(5.0)  # f1 is in the model now
        stale = [ex.submit(x, deadline_ms=50) for _ in range(4)]
        assert f1.wait(5.0) and f1.error is None
        for f in stale:
            assert f.wait(5.0)
            assert isinstance(f.error, DeadlineExceededError)
        # the expired requests never reached the model
        assert model.calls == 1
        shed = _counter_values(reg, "tdl_inference_shed_total")
        assert shed[("queue_expired",)] == 4
    finally:
        ex.stop(drain=True)


def test_executor_queue_full_and_graceful_drain():
    reg = MetricsRegistry()
    model = SlowModel(delay=0.3)
    ex = BatchingInferenceExecutor(model=model, max_queue=2,
                                   registry=reg).start()
    x = np.ones((1, 4), np.float32)
    f1 = ex.submit(x)
    assert model.started.wait(5.0)
    queued = [ex.submit(x), ex.submit(x)]
    with pytest.raises(QueueFullError):
        ex.submit(x)
    assert _counter_values(reg, "tdl_inference_shed_total")[("queue_full",)] == 1
    # graceful drain completes every accepted request
    ex.stop(drain=True)
    for f in [f1] + queued:
        assert f.done and f.error is None
        np.testing.assert_allclose(f.result, 2.0 * np.ones((1, 4)))


def test_executor_mixed_shape_requests_grouped_not_failed():
    """A mixed workload (different feature shapes in one cycle) is served by
    grouping, never a deep jax concatenate error."""
    ex = BatchingInferenceExecutor(model=SlowModel(), max_queue=16).start()
    try:
        fa = ex.submit(np.ones((2, 4), np.float32))
        fb = ex.submit(np.ones((1, 6), np.float32))
        assert fa.wait(5.0) and fb.wait(5.0)
        assert fa.error is None and fb.error is None
        assert fa.result.shape == (2, 4) and fb.result.shape == (1, 6)
    finally:
        ex.stop(drain=True)


# ----------------------------------------------------------------- server


def test_builder_parallel_inference_wiring_roundtrip():
    """ISSUE 5 satellite: DL4J builder parity — parallel_inference(pi) /
    batch_limit(n) route requests through the sharded bucketed forward."""
    net = _net()
    pi = ParallelInference(net, batch_limit=8)
    server = (JsonModelServer.Builder(net).port(0).parallel_inference(pi)
              .warmup_input(np.zeros((1, 4), np.float32)).build().start())
    try:
        assert server.wait_ready(30.0)
        client = JsonModelClient(port=server.port)
        x = np.random.RandomState(1).randn(3, 4).astype(np.float32)
        out = np.asarray(client.predict(x))
        np.testing.assert_allclose(out, net.output(x).numpy(), atol=1e-5)
    finally:
        server.stop()
    # batch_limit(n) without an explicit pi builds one internally
    server2 = JsonModelServer.Builder(net).port(0).batch_limit(8).build().start()
    try:
        assert server2.parallel_inference is not None
        out = np.asarray(JsonModelClient(port=server2.port).predict(x))
        np.testing.assert_allclose(out, net.output(x).numpy(), atol=1e-5)
    finally:
        server2.stop()


def test_server_deadline_header_yields_504_not_hang():
    reg = MetricsRegistry()
    server = JsonModelServer(SlowModel(delay=0.5), registry=reg).start()
    try:
        t0 = time.perf_counter()
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(server.port, b"[[1.0, 2.0, 3.0, 4.0]]",
                  headers={"X-Deadline-Ms": "100"})
        elapsed = time.perf_counter() - t0
        assert ei.value.code == 504
        assert elapsed < 0.45  # answered at the deadline, not after the model
        assert "deadline" in json.loads(ei.value.read())["error"]
        codes = _counter_values(reg, "tdl_inference_requests_total")
        assert codes[("504",)] == 1
    finally:
        server.stop()


def test_server_queue_full_429_with_retry_after():
    reg = MetricsRegistry()
    model = SlowModel(delay=0.5)
    server = JsonModelServer(model, max_queue=1, registry=reg).start()
    try:
        body = b"[[1.0, 2.0, 3.0, 4.0]]"
        results = []

        def fire():
            try:
                results.append(_post(server.port, body)[0])
            except urllib.error.HTTPError as e:
                results.append(e.code)

        t1 = threading.Thread(target=fire)
        t1.start()
        assert model.started.wait(5.0)  # first request is inside the model
        t2 = threading.Thread(target=fire)
        t2.start()
        time.sleep(0.1)  # second request now occupies the only queue slot
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(server.port, body)
        assert ei.value.code == 429
        assert ei.value.headers.get("Retry-After") is not None
        t1.join(10.0)
        t2.join(10.0)
        assert results == [200, 200]
        assert _counter_values(
            reg, "tdl_inference_shed_total")[("queue_full",)] == 1
    finally:
        server.stop()


def test_health_ready_split_and_graceful_drain():
    model = SlowModel(delay=0.4)
    server = JsonModelServer(
        model, warmup_input=np.zeros((1, 4), np.float32)).start()
    try:
        # /health is liveness: 200 even while the warmup forward runs
        assert _get(server.port, "/health")[0] == 200
        assert server.wait_ready(30.0)
        assert _get(server.port, "/ready")[0] == 200

        # an accepted slow request + concurrent shutdown: /ready flips 503
        # so balancers stop routing, and drain completes the request
        outcome = []

        def slow_request():
            outcome.append(_post(server.port, b"[[1.0, 2.0, 3.0, 4.0]]"))

        t = threading.Thread(target=slow_request)
        t.start()
        model.started.clear()
        assert model.started.wait(5.0)

        stopper = threading.Thread(target=lambda: server.stop(drain=True))
        stopper.start()
        saw_not_ready = False
        for _ in range(100):
            try:
                status, body, headers = _get(server.port, "/ready", timeout=2)
            except urllib.error.HTTPError as e:
                assert e.code == 503
                assert e.headers.get("Retry-After") is not None
                saw_not_ready = True
                break
            except (urllib.error.URLError, ConnectionError, OSError):
                break  # socket already closed — drain finished
            time.sleep(0.01)
        stopper.join(30.0)
        t.join(30.0)
        assert saw_not_ready
        assert outcome and outcome[0][0] == 200  # accepted request completed
        np.testing.assert_allclose(outcome[0][1]["output"],
                                   [[2.0, 4.0, 6.0, 8.0]])
    finally:
        server.stop()  # idempotent


def test_body_cap_413_and_missing_content_length():
    server = JsonModelServer(SlowModel(), max_body_bytes=1024).start()
    try:
        # ~7MB body: well past loopback socket buffers, so this also proves
        # the server DRAINS the oversized body before answering — otherwise
        # the close RSTs the upload and this surfaces as URLError, not 413
        big = json.dumps([[0.0] * 4] * 300_000).encode()
        assert len(big) > 4 << 20
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(server.port, big)
        assert ei.value.code == 413
        # a request with no Content-Length cannot be buffered safely → 413
        with socket.create_connection(("127.0.0.1", server.port),
                                      timeout=10) as s:
            s.sendall(b"POST /predict HTTP/1.1\r\nHost: localhost\r\n\r\n")
            chunks = []
            while True:
                chunk = s.recv(4096)
                if not chunk:
                    break
                chunks.append(chunk)
            reply = b"".join(chunks).decode()
        assert "413" in reply.split("\r\n")[0]
        assert "Content-Length header required" in reply
        # negative Content-Length must be rejected up front, never read(-1)
        with socket.create_connection(("127.0.0.1", server.port),
                                      timeout=10) as s:
            s.sendall(b"POST /predict HTTP/1.1\r\nHost: localhost\r\n"
                      b"Content-Length: -1\r\n\r\n")
            reply = s.recv(4096).decode()
        assert "400" in reply.split("\r\n")[0]
    finally:
        server.stop()


def test_server_restart_same_port_and_idempotent_stop():
    net = _net()
    server = JsonModelServer(net).start()
    port = server.port
    x = np.random.RandomState(2).randn(2, 4).astype(np.float32)
    ref = net.output(x).numpy()
    np.testing.assert_allclose(
        np.asarray(JsonModelClient(port=port).predict(x)), ref, atol=1e-5)
    server.stop()
    server.stop()  # idempotent: second stop is a no-op, not an error
    server.start()  # SO_REUSEADDR: rebinds the SAME port during TIME_WAIT
    try:
        assert server.port == port
        np.testing.assert_allclose(
            np.asarray(JsonModelClient(port=port).predict(x)), ref, atol=1e-5)
    finally:
        server.stop()


def test_fail_infer_fault_maps_to_500_then_recovers(monkeypatch):
    monkeypatch.setenv("TDL_FAULT_SPEC", "fail_infer@n=1")
    server = JsonModelServer(SlowModel()).start()
    try:
        client = JsonModelClient(port=server.port, retries=1,
                                 backoff_base=0.01, backoff_max=0.02)
        with pytest.raises(RuntimeError, match="500.*InjectedFault"):
            client.predict([[1.0, 2.0, 3.0, 4.0]])
        monkeypatch.setenv("TDL_FAULT_SPEC", "")  # fault cleared → recovery
        out = client.predict([[1.0, 2.0, 3.0, 4.0]])
        np.testing.assert_allclose(out, [[2.0, 4.0, 6.0, 8.0]])
    finally:
        server.stop()


# ----------------------------------------------------------------- client


def test_client_normalizes_connection_refused():
    with socket.socket() as s:  # grab a port that is certainly closed
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
    client = JsonModelClient(port=dead_port, retries=0)
    with pytest.raises(RuntimeError) as ei:
        client.predict([[1.0]])
    assert client.url in str(ei.value)  # not a raw URLError escaping


def test_client_retries_converge_on_success():
    model = FlakyModel(fail_first=2)
    server = JsonModelServer(model).start()
    try:
        client = JsonModelClient(port=server.port, retries=4,
                                 backoff_base=0.01, backoff_max=0.05)
        out = client.predict([[1.0, 2.0, 3.0, 4.0]])
        np.testing.assert_allclose(out, [[2.0, 4.0, 6.0, 8.0]])
        assert model.calls == 3  # two 500s retried, third attempt lands
    finally:
        server.stop()


def test_client_never_retries_400():
    server = JsonModelServer(SlowModel()).start()
    try:
        client = JsonModelClient(port=server.port, retries=5,
                                 backoff_base=0.01)
        t0 = time.perf_counter()
        with pytest.raises(RuntimeError, match="400"):
            client.predict(["not", "numbers"])
        assert time.perf_counter() - t0 < 1.0  # no backoff loop happened
    finally:
        server.stop()


def test_client_circuit_breaker_opens_and_half_opens():
    class Boom:
        def __init__(self):
            self.calls = 0

        def output(self, x):
            self.calls += 1
            raise RuntimeError("replica wedged")

    model = Boom()
    server = JsonModelServer(model).start()
    try:
        client = JsonModelClient(port=server.port, retries=0,
                                 backoff_base=0.01, breaker_threshold=2,
                                 breaker_cooldown=0.2)
        for _ in range(2):
            with pytest.raises(RuntimeError, match="500"):
                client.predict([[1.0, 2.0, 3.0, 4.0]])
        assert model.calls == 2
        # breaker open: fails fast without touching the server
        with pytest.raises(RuntimeError, match="circuit breaker open"):
            client.predict([[1.0, 2.0, 3.0, 4.0]])
        assert model.calls == 2
        time.sleep(0.25)  # cooldown elapses → half-open probe goes through
        with pytest.raises(RuntimeError, match="500"):
            client.predict([[1.0, 2.0, 3.0, 4.0]])
        assert model.calls == 3
    finally:
        server.stop()


# ------------------------------------------------------------ chaos stress


def test_serving_chaos_32_clients(monkeypatch):
    """ISSUE 5 acceptance: slow_infer fault + 32 concurrent clients against a
    bounded queue — the server only ever answers 200/429/504, queue depth
    stays bounded, no client hangs, client retries converge on eventual 200s,
    and it is all visible in the tdl_inference_* metrics."""
    monkeypatch.setenv("TDL_FAULT_SPEC", "slow_infer@p=0.02")
    reg = MetricsRegistry()
    net = _net()
    server = (JsonModelServer.Builder(net).port(0).batch_limit(8)
              .queue_size(8).registry(reg)
              .warmup_input(np.zeros((1, 4), np.float32)).build().start())
    try:
        assert server.wait_ready(60.0)
        clients, per_client = 32, 3
        successes = [0] * clients
        depth_gauge = reg.get("tdl_inference_queue_depth")
        depth_samples = []
        stop_sampling = threading.Event()

        def sample_depth():
            while not stop_sampling.is_set():
                depth_samples.append(depth_gauge.value)
                time.sleep(0.005)

        def worker(idx):
            client = JsonModelClient(
                port=server.port, timeout=15, retries=12,
                backoff_base=0.01, backoff_max=0.1,
                breaker_threshold=10 ** 6, deadline_ms=10_000)
            x = np.random.RandomState(idx).randn(1, 4).astype(np.float32)
            for _ in range(per_client):
                client.predict(x)  # raises if retries don't converge
                successes[idx] += 1

        sampler = threading.Thread(target=sample_depth, daemon=True)
        sampler.start()
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60.0)
        stop_sampling.set()
        sampler.join(5.0)

        assert not any(t.is_alive() for t in threads)  # zero hung clients
        assert sum(successes) == clients * per_client  # retries converged
        assert max(depth_samples) <= 8  # admission queue stayed bounded

        codes = _counter_values(reg, "tdl_inference_requests_total")
        assert set(codes) <= {("200",), ("429",), ("504",)}
        assert ("500",) not in codes
        assert codes[("200",)] == clients * per_client
        snap = reg.snapshot()
        assert snap["tdl_inference_queue_wait_seconds"]["series"][0]["count"] > 0
        assert snap["tdl_inference_latency_seconds"]["series"][0]["count"] > 0
        assert snap["tdl_inference_batch_size"]["series"][0]["count"] > 0
        server.stop(drain=True)  # nothing in flight; drain is a clean no-op
    finally:
        server.stop()


# -------------------------------------------------- request IDs (ISSUE 10)


def test_request_id_echoed_on_success_and_generated_when_absent():
    model = SlowModel()
    server = JsonModelServer(model, registry=MetricsRegistry()).start()
    try:
        body = json.dumps([[1.0, 2.0, 3.0, 4.0]]).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/predict", data=body,
            headers={"Content-Type": "application/json",
                     "X-Request-Id": "client-abc-123"})
        with urllib.request.urlopen(req, timeout=15) as resp:
            assert resp.headers["X-Request-Id"] == "client-abc-123"
            out = json.loads(resp.read())
        assert out["request_id"] == "client-abc-123"
        # no client id → the server mints one and still echoes it
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/predict", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=15) as resp:
            rid = resp.headers["X-Request-Id"]
            out = json.loads(resp.read())
        assert rid and out["request_id"] == rid
    finally:
        server.stop()


def test_request_id_rides_error_responses_and_logs(caplog):
    import logging

    # 413 (body too big) and 429 (queue full) both carry the id in header
    # AND error JSON; the queue-full shed also logs it executor-side
    model = SlowModel(delay=0.6)
    server = JsonModelServer(model, max_queue=1, max_body_bytes=256,
                             registry=MetricsRegistry()).start()
    try:
        big = json.dumps([[0.0] * 2000]).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/predict", data=big,
            headers={"Content-Type": "application/json",
                     "X-Request-Id": "too-big-1"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=15)
        assert ei.value.code == 413
        assert ei.value.headers["X-Request-Id"] == "too-big-1"
        assert json.loads(ei.value.read())["request_id"] == "too-big-1"

        ok = json.dumps([[1.0, 2.0, 3.0, 4.0]]).encode()
        results = []

        def fire(rid):
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/predict", data=ok,
                headers={"Content-Type": "application/json",
                         "X-Request-Id": rid})
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    results.append((200, json.loads(resp.read())))
            except urllib.error.HTTPError as e:
                results.append((e.code, json.loads(e.read()),
                                e.headers.get("X-Request-Id")))

        with caplog.at_level(logging.DEBUG,
                             logger="deeplearning4j_tpu.serving"):
            # fill the 1-slot queue while the slow forward runs, then one
            # more request must be shed with 429 + its id echoed
            threads = [threading.Thread(target=fire, args=(f"rid-{i}",))
                       for i in range(6)]
            for t in threads:
                t.start()
                time.sleep(0.02)
            for t in threads:
                t.join(30.0)
        shed = [r for r in results if r[0] == 429]
        assert shed, f"no 429 among {[r[0] for r in results]}"
        code, body429, hdr = shed[0]
        assert body429["request_id"].startswith("rid-")
        assert hdr == body429["request_id"]
        assert any("admission queue full" in r.message and "rid-" in r.message
                   for r in caplog.records)
    finally:
        server.stop()


def test_request_id_sanitizes_garbage_header():
    from deeplearning4j_tpu.serving.json_server import _request_id

    assert _request_id("ok-id") == "ok-id"
    generated = _request_id("bad\nid")
    assert "\n" not in generated and len(generated) == 16
    assert len(_request_id("x" * 500)) == 16  # over-long → replaced
    assert len(_request_id(None)) == 16


# ------------------------------------------- request spans (ISSUE 11)


def _install_recorder():
    from deeplearning4j_tpu.monitoring import flight
    from deeplearning4j_tpu.monitoring.flight import FlightRecorder

    rec = FlightRecorder(proc="span-test", capacity=4096)
    flight.set_flight_recorder(rec)
    return rec


def _clear_recorder():
    from deeplearning4j_tpu.monitoring import flight

    flight.set_flight_recorder(None)


def _spans(rec, rid=None):
    return [e for e in rec.events() if e["kind"] == "request_span"
            and (rid is None or e.get("request_id") == rid)]


def test_request_span_for_200_carries_full_phase_timeline():
    """ISSUE 11: a sampled 200's life — queue → batch_form → infer →
    serialize — reconstructs from ONE flight event joined by request id."""
    rec = _install_recorder()
    server = JsonModelServer(SlowModel(), registry=MetricsRegistry()).start()
    try:
        body = json.dumps([[1.0, 2.0, 3.0, 4.0]]).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/predict", data=body,
            headers={"Content-Type": "application/json",
                     "X-Request-Id": "span-ok-1"})
        with urllib.request.urlopen(req, timeout=15) as resp:
            assert resp.status == 200
        spans = _spans(rec, "span-ok-1")
        assert len(spans) == 1
        ev = spans[0]
        assert ev["outcome"] == "ok" and ev["code"] == 200
        assert set(ev["phases"]) == {"queue", "batch_form", "infer",
                                     "serialize"}
        assert all(v >= 0 for v in ev["phases"].values())
        assert ev["batch_rows"] >= 1
    finally:
        server.stop()
        _clear_recorder()


def test_request_span_for_shed_queue_full_and_expired_deadline():
    """ISSUE 11 satellite: a 429 and an expired-in-queue 504 leave spans
    too (outcome=shed_queue_full / shed_deadline) — an error's timeline is
    as reconstructable as a 200's."""
    rec = _install_recorder()
    model = SlowModel(delay=0.4)
    server = JsonModelServer(model, max_queue=1,
                             registry=MetricsRegistry()).start()
    try:
        ok = json.dumps([[1.0, 2.0, 3.0, 4.0]]).encode()

        def fire(rid, deadline_ms=None):
            headers = {"Content-Type": "application/json",
                       "X-Request-Id": rid}
            if deadline_ms:
                headers["X-Deadline-Ms"] = str(deadline_ms)
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/predict", data=ok,
                headers=headers)
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    return resp.status
            except urllib.error.HTTPError as e:
                e.read()
                return e.code

        t1 = threading.Thread(target=fire, args=("span-first",))
        t1.start()
        assert model.started.wait(5.0)  # first request inside the model
        # occupies the single queue slot, with a deadline shorter than the
        # in-flight forward → expires IN QUEUE
        t2 = threading.Thread(target=fire, args=("span-expired", 100))
        t2.start()
        time.sleep(0.1)
        # queue full now → shed at admission
        assert fire("span-full") == 429
        t1.join(30.0)
        t2.join(30.0)
        server.stop(drain=True)

        full = _spans(rec, "span-full")
        assert len(full) == 1 and full[0]["outcome"] == "shed_queue_full"
        expired = _spans(rec, "span-expired")
        assert len(expired) == 1
        assert expired[0]["outcome"] == "shed_deadline"
        assert expired[0]["phases"]["queue"] >= 0.1  # its life WAS the queue
        ok_span = _spans(rec, "span-first")
        assert len(ok_span) == 1 and ok_span[0]["outcome"] == "ok"
    finally:
        server.stop()
        _clear_recorder()


def test_span_sampling_is_deterministic_by_request_id():
    from deeplearning4j_tpu.monitoring import flight
    from deeplearning4j_tpu.monitoring.flight import FlightRecorder
    from deeplearning4j_tpu.serving.executor import span_sampled

    # inactive flight recording → never sampled (hot path pays one lookup)
    assert not span_sampled("abc", 1)
    rec = FlightRecorder(proc="sample-test")
    flight.set_flight_recorder(rec)
    try:
        assert span_sampled("abc", 1)
        assert span_sampled(None, 1)
        # deterministic: same id, same verdict, every call
        verdicts = {rid: span_sampled(rid, 4) for rid in
                    (f"r{i}" for i in range(64))}
        assert verdicts == {rid: span_sampled(rid, 4) for rid in verdicts}
        kept = sum(verdicts.values())
        assert 0 < kept < 64  # ~1/4 sampled
        assert not span_sampled(None, 4)  # no id → no joinable timeline
    finally:
        flight.set_flight_recorder(None)


# ------------------------------------------- client metrics (ISSUE 11)


def test_client_metrics_record_outcomes_and_retries():
    reg = MetricsRegistry()
    model = FlakyModel(fail_first=2)
    server = JsonModelServer(model).start()
    try:
        client = JsonModelClient(port=server.port, retries=4,
                                 backoff_base=0.01, backoff_max=0.05,
                                 registry=reg)
        client.predict([[1.0, 2.0, 3.0, 4.0]])  # two 500s then success
        hist = reg.get("tdl_client_request_seconds").snapshot()["series"]
        by_outcome = {s["labels"]["outcome"]: s["count"] for s in hist}
        assert by_outcome == {"ok": 1}  # ONE request from the caller's view
        retries = _counter_values(reg, "tdl_client_retries_total")
        assert retries[("http_500",)] == 2

        with pytest.raises(RuntimeError, match="400"):
            client.predict(["not", "numbers"])
        by_outcome = {s["labels"]["outcome"]: s["count"]
                      for s in reg.get("tdl_client_request_seconds")
                      .snapshot()["series"]}
        assert by_outcome == {"ok": 1, "bad_request": 1}
    finally:
        server.stop()


def test_client_metrics_connection_and_breaker_outcomes():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
    reg = MetricsRegistry()
    client = JsonModelClient(port=dead_port, retries=0, breaker_threshold=1,
                             breaker_cooldown=30.0, registry=reg)
    with pytest.raises(RuntimeError):
        client.predict([[1.0]])
    with pytest.raises(RuntimeError, match="circuit breaker open"):
        client.predict([[1.0]])
    by_outcome = {s["labels"]["outcome"]: s["count"]
                  for s in reg.get("tdl_client_request_seconds")
                  .snapshot()["series"]}
    assert by_outcome == {"connection": 1, "breaker_open": 1}
