"""Early stopping + transfer learning tests (SURVEY §2.4 C10/C11)."""

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn import (
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    FineTuneConfiguration,
    MultiLayerNetwork,
    NeuralNetConfiguration,
    TransferLearning,
    TransferLearningHelper,
)
from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.earlystopping import (
    DataSetLossCalculator,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
from deeplearning4j_tpu.nn.updaters import Adam, Sgd


def _net(lr=0.02, seed=11):
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(lr)).list()
            .layer(DenseLayer(n_in=5, n_out=16, activation="tanh"))
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _iters(seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(120, 5).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[np.argmax(X[:, :3], 1)]
    train = ListDataSetIterator([DataSet(X[i:i + 40], Y[i:i + 40]) for i in range(0, 80, 40)])
    val = ListDataSetIterator([DataSet(X[80:], Y[80:])])
    return train, val


def test_early_stopping_max_epochs():
    train, val = _iters()
    net = _net()
    cfg = (EarlyStoppingConfiguration.Builder()
           .epoch_termination_conditions(MaxEpochsTerminationCondition(5))
           .score_calculator(DataSetLossCalculator(val))
           .build())
    result = EarlyStoppingTrainer(cfg, net, train).fit()
    assert result.total_epochs <= 5
    assert len(result.score_vs_epoch) == result.total_epochs
    assert result.best_model_score <= result.score_vs_epoch[0]
    best = result.get_best_model()
    assert best is not None


def test_early_stopping_patience_stops_before_max():
    train, val = _iters()
    net = _net(lr=0.0)  # lr=0 -> no improvement -> patience fires immediately
    cfg = (EarlyStoppingConfiguration.Builder()
           .epoch_termination_conditions(
               ScoreImprovementEpochTerminationCondition(2),
               MaxEpochsTerminationCondition(50))
           .score_calculator(DataSetLossCalculator(val))
           .build())
    result = EarlyStoppingTrainer(cfg, net, train).fit()
    assert result.total_epochs < 50
    assert result.termination_details == "ScoreImprovementEpochTerminationCondition"


def test_early_stopping_divergence_abort():
    train, val = _iters()
    # absurd SGD lr + unbounded activations -> divergence (Adam would
    # normalize the step away; tanh would bound the logits)
    conf = (NeuralNetConfiguration.Builder().seed(11).updater(Sgd(500.0)).list()
            .layer(DenseLayer(n_in=5, n_out=16, activation="identity"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    cfg = (EarlyStoppingConfiguration.Builder()
           .epoch_termination_conditions(MaxEpochsTerminationCondition(50))
           .iteration_termination_conditions(MaxScoreIterationTerminationCondition(1e3))
           .score_calculator(DataSetLossCalculator(val))
           .build())
    result = EarlyStoppingTrainer(cfg, net, train).fit()
    assert result.termination_reason == "IterationTerminationCondition"


def test_transfer_learning_freeze_and_replace():
    rs = np.random.RandomState(0)
    X = rs.randn(32, 5).astype(np.float32)
    Y3 = np.eye(3, dtype=np.float32)[np.argmax(X[:, :3], 1)]
    base = _net()
    base.fit(DataSet(X, Y3))
    frozen_w_before = np.asarray(base.params_["0"]["W"])

    # new 4-class head; freeze layers 0-1
    Y4 = np.eye(4, dtype=np.float32)[np.argmax(X[:, :4], 1)]
    new = (TransferLearning.Builder(base)
           .fine_tune_configuration(FineTuneConfiguration.Builder().updater(Sgd(0.1)).build())
           .set_feature_extractor(1)
           .remove_output_layer()
           .add_layer(OutputLayer(n_in=8, n_out=4, activation="softmax", loss="mcxent"))
           .build())
    # retained weights copied
    np.testing.assert_allclose(np.asarray(new.params_["0"]["W"]), frozen_w_before)
    for _ in range(3):
        new.fit(DataSet(X, Y4))
    # frozen layers unchanged, head trained
    np.testing.assert_allclose(np.asarray(new.params_["0"]["W"]), frozen_w_before)
    assert new.output(X).numpy().shape == (32, 4)


def test_transfer_learning_helper_featurize():
    base = _net()
    rs = np.random.RandomState(0)
    X = rs.randn(16, 5).astype(np.float32)
    Y = np.eye(3, dtype=np.float32)[np.argmax(X[:, :3], 1)]
    helper = TransferLearningHelper(base, frozen_until=0)
    feat = helper.featurize(DataSet(X, Y))
    assert feat.features.shape == (16, 16)  # first dense layer output
    head = helper.unfrozen_mln()
    out_full = base.output(X).numpy()
    out_head = head.output(feat.features).numpy()
    np.testing.assert_allclose(out_full, out_head, atol=1e-5)


class TestROCMultiClassAndCalibration:
    """J10 tail: ROCMultiClass + EvaluationCalibration (mergeable)."""

    def _data(self, n=400, C=3, seed=0):
        rs = np.random.RandomState(seed)
        y = rs.randint(0, C, n)
        logits = rs.randn(n, C) * 0.5
        logits[np.arange(n), y] += 2.0  # informative predictions
        p = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
        return np.eye(C)[y].astype(np.float32), p.astype(np.float32)

    def test_roc_multiclass_auc(self):
        from deeplearning4j_tpu.eval import ROCMultiClass

        y, p = self._data()
        roc = ROCMultiClass()
        roc.eval(y[:200], p[:200])
        other = ROCMultiClass()
        other.eval(y[200:], p[200:])
        roc.merge(other)
        assert roc.num_classes() == 3
        for c in range(3):
            assert roc.calculate_auc(c) > 0.85
        assert roc.calculate_average_auc() > 0.85
        # random scores → AUC near 0.5
        rand = ROCMultiClass()
        rs = np.random.RandomState(1)
        pr = rs.rand(400, 3); pr /= pr.sum(-1, keepdims=True)
        rand.eval(y, pr.astype(np.float32))
        assert abs(rand.calculate_average_auc() - 0.5) < 0.1

    def test_calibration_ece_and_reliability(self):
        from deeplearning4j_tpu.eval import EvaluationCalibration

        y, p = self._data()
        cal = EvaluationCalibration(reliability_bins=10)
        cal.eval(y[:200], p[:200])
        other = EvaluationCalibration(reliability_bins=10)
        other.eval(y[200:], p[200:])
        cal.merge(other)
        rows = cal.reliability_diagram()
        assert len(rows) == 10
        assert sum(r[3] for r in rows) == 400
        ece = cal.expected_calibration_error()
        assert 0.0 <= ece <= 1.0
        # degenerate overconfident predictions → large ECE
        bad = EvaluationCalibration()
        yb = np.eye(2)[np.zeros(100, int)].astype(np.float32)
        pb = np.tile(np.array([[0.01, 0.99]], np.float32), (100, 1))  # always wrong
        bad.eval(yb, pb)
        assert bad.expected_calibration_error() > 0.9
