"""Worker + eval targets for the deployment-controller tests (ISSUE 18).

Loaded BY PATH in two roles:

- ``lifecycle_train`` is a ``GangSupervisor`` worker target (the e2e chaos
  training run). Unlike ``mp_workers.supervised_train`` its labels are a
  DETERMINISTIC function of the inputs, so a healthy checkpoint evaluates to
  genuinely high held-out accuracy while a ``loss_spike``-poisoned one
  craters — the separation the controller's offline eval gate judges.
- ``eval_candidate`` / ``eval_sleepy`` are controller ``eval_target``
  functions (``gen_dir -> metrics``), importable in-process and loadable by
  the ``python -m deeplearning4j_tpu.deploy.controller`` subprocess.
"""

import json
import os
import time

import numpy as np

#: fixed 6->3 linear map: labels = argmax(x @ W) — learnable, deterministic
_TASK_W = np.asarray(
    [[1.2, -0.7, 0.1], [-0.9, 1.1, 0.3], [0.4, 0.2, -1.3],
     [0.8, -1.0, 0.6], [-0.5, 0.9, -0.2], [0.3, -0.4, 1.0]], np.float32)


def _task_batch(step, n=32):
    rs = np.random.RandomState(500 + step)
    x = rs.rand(n, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ _TASK_W, axis=1)]
    return x, y


def _toy_net(seed=7):
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import DenseLayer, InputType, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (
        NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2)).list()
        .layer(DenseLayer(n_in=6, n_out=16, activation="tanh"))
        .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(6))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def lifecycle_train():
    """Gang worker: data-parallel training on the learnable task with
    lineage checkpoints every ``TDL_MP_CKPT_EVERY`` steps and
    restore-from-latest on start (the supervisor restart contract). Chaos
    rides ``TDL_FAULT_SPEC`` through the real ``_fit_core`` hooks — a
    ``crash`` kills a rank mid-run, a ``loss_spike`` ruins the weights while
    the checkpointer keeps committing structurally perfect generations."""
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.parallel.launcher import ProcessCollectives
    from deeplearning4j_tpu.parallel.mesh import build_mesh
    from deeplearning4j_tpu.parallel.trainer import MultiProcessTrainer
    from deeplearning4j_tpu.serde.checkpoint import TrainingCheckpointer

    col = ProcessCollectives()
    rank, world = col.rank, col.world
    total_steps = int(os.environ.get("TDL_MP_STEPS", "12"))
    every = int(os.environ.get("TDL_MP_CKPT_EVERY", "3"))

    net = _toy_net()
    ck = TrainingCheckpointer(os.environ["TDL_MP_CKPT"], async_write=False,
                              keep_last=8)  # the controller wants them all
    start = 0
    if ck.restore(net):
        start = int(net.iteration)
    trainer = MultiProcessTrainer(net, build_mesh(data=-1))
    for step in range(start, total_steps):
        x, y = _task_batch(step)
        lo = rank * (len(x) // world)
        hi = lo + len(x) // world
        trainer.fit([DataSet(x[lo:hi], y[lo:hi])])
        if (step + 1) % every == 0:
            col.barrier(f"ck-{step}")
            ck.save(net)
            col.barrier(f"ck-done-{step}")

    out = os.environ.get("TDL_MP_OUT")
    if out:
        with open(out + f".rank{rank}", "w") as f:
            json.dump({"start": start, "iteration": int(net.iteration)}, f)


def _restore_generation(gendir):
    """Load ONE specific generation into a fresh net. ``restore()`` loads
    the newest committed generation of a lineage, so build a throwaway
    lineage holding just this generation (symlink — zero copy) and restore
    through the normal verified path."""
    import tempfile

    from deeplearning4j_tpu.serde.checkpoint import TrainingCheckpointer

    gendir = os.path.normpath(gendir)
    name = os.path.basename(gendir)
    root = tempfile.mkdtemp(prefix="tdl-eval-")
    lineage = os.path.join(root, "latest")
    os.makedirs(lineage)
    os.symlink(gendir, os.path.join(lineage, name))
    with open(os.path.join(lineage, "LATEST"), "w") as f:
        f.write(name + "\n")
    net = _toy_net()
    if not TrainingCheckpointer(root, async_write=False).restore(net):
        raise RuntimeError(f"no committed checkpoint under {gendir}")
    return net


def eval_candidate(gendir):
    """Controller eval target: restore the candidate generation and judge it
    on held-out batches the training run never saw. The headline ``score``
    is log-loss based (``1/(1+xent)``) — argmax accuracy is nearly invariant
    to a multiplicative weight spike (saturated tanh keeps its sign
    pattern), but the spiked net's exploded CONFIDENCE on wrong samples
    makes its held-out cross-entropy, and therefore this score, crater."""
    net = _restore_generation(gendir)
    losses, accs = [], []
    for step in (901, 902, 903):
        x, y = _task_batch(step, n=64)
        p = np.clip(np.asarray(net.output(x).numpy()), 1e-12, 1.0)
        losses.append(float(-(y * np.log(p)).sum(axis=1).mean()))
        accs.append(float((p.argmax(1) == y.argmax(1)).mean()))
    return {"score": 1.0 / (1.0 + float(np.mean(losses))),
            "accuracy": float(np.mean(accs))}


def eval_sleepy(gendir):
    """Deterministic eval target for the SIGKILL-resume test: sleep
    ``TDL_EVAL_SLEEP`` seconds (long in the run that gets killed mid-gate,
    unset in the resumed run), then return a fixed verdict."""
    time.sleep(float(os.environ.get("TDL_EVAL_SLEEP", "0")))
    return {"accuracy": float(os.environ.get("TDL_EVAL_ACC", "0.9"))}
